"""Unified telemetry: metrics registry, span tracing, and perf reporting.

The reference QuEST has no observability surface at all beyond
``reportQuregParams`` (SURVEY.md §5.1); quest_tpu until this round had
three disconnected fragments — compile-cache counters in env.py, the
degradation registry in resilience.py, and thin ``jax.profiler`` wrappers
in utils/profiling.py.  Distributed simulators at production scale treat
communication-volume and per-phase timing accounting as first-class
(mpiQulacs, arXiv:2203.16044 §V; qHiPSTER, arXiv:1601.07195 §IV): you
cannot tune what you cannot count.  This module is that layer — one
process-wide registry every subsystem reports into:

* **Metrics** — counters / gauges / histograms with labeled series
  (``inc``/``set_gauge``/``observe``).  The instrumented hot layers:
  api dispatch (``dispatch_total{family}``), the fusion drain
  (``fusion_windows_total``, ``fusion_retrace_total``, plan-cache
  hit/miss, window-size histograms), the distributed exchange sites
  (``exchanges_total{op,chunks}``, ``exchange_bytes_total{op}`` — bytes
  are PER-SHARD ICI volume, matching circuit.remap_exchange_bytes's
  cost model), and the resilience layer (``checkpoint_commit_seconds``,
  ``checkpoint_io_retries_total``, ``watchdog_verdicts_total``).  The
  legacy registries (env._CACHE_STATS, resilience.DEGRADATIONS) are
  folded into the same namespace at read time, so ``snapshot()`` is the
  one consolidated view.

* **Spans** — ``with telemetry.span("drain"):`` records a Chrome-trace
  "X" event (Perfetto-loadable via ``write_trace``), observes the
  duration into the ``span_seconds{name}`` histogram, and
  simultaneously opens a ``jax.profiler.TraceAnnotation`` so the same
  region lands inside XLA device traces captured by
  utils/profiling.trace.

* **Export** — ``snapshot()`` (nested dict), ``prometheus_text()``
  (text exposition format), ``write_trace()`` (Chrome trace JSON), and
  ``report_perf(env)`` / ``reportPerf`` mirroring the reference's
  ``report*`` print family.

* **Request-scoped traces** — ``trace_begin``/``trace_point``/
  ``trace_end`` record a per-``trace_id`` span tree (the serve layer
  threads a job id through its whole lifecycle: admit -> bank ->
  window* -> retry/preempt -> complete), queryable via :func:`tracez`
  and served live at the SimServer ``/tracez`` endpoint.  Active in
  BOTH enabled modes — the span tree is lifecycle observability, not
  deep profiling — and bounded (id + per-id event caps, oldest id
  evicted).

* **Flight recorder** — :func:`flight_event` appends structured events
  (spans, degradations, watchdog verdicts, drift, admission decisions)
  to a bounded ring; :func:`dump_flight` writes the ring as a JSON
  post-mortem artifact.  serve/resilience dump it automatically on
  quarantine, elastic degradation, OOM bisection, and unhandled
  executor failure, so every chaos incident leaves an artifact.

Gating: ``QT_TELEMETRY=off|on|trace`` (default **on** — the whole point
is always-on accounting).  Every recording entry point starts with one
module-global int test, so the disabled path is a no-op check with
measured-negligible overhead on the dispatch hot loop
(scripts/bench_telemetry.py guards BOTH enabled modes — on AND trace —
at <5% on a 1k-gate fusion drain).  Registry upserts take one shared
``threading.Lock`` — serve runs asyncio plus HTTP/executor threads, so
counter increments must be exact across writers, not merely
GIL-approximate; the lock is acquired only on the enabled path, after
the mode test.

Dispatch-time semantics: the distributed wrappers record at *dispatch*
(outside jit).  A quest_tpu call traced inside a user's own ``jax.jit``
records once per trace, not per execution — the same caveat as any
host-side counter in a traced framework.
"""

from __future__ import annotations

import atexit
import bisect
import collections
import contextlib
import json
import math
import os
import threading
import time
from typing import Iterator, Optional

OFF, ON, TRACE = 0, 1, 2
_MODES = {"off": OFF, "on": ON, "trace": TRACE, "0": OFF, "1": ON}
_MODE_NAMES = {OFF: "off", ON: "on", TRACE: "trace"}

_ENV_VAR = "QT_TELEMETRY"
_TRACE_DIR_ENV = "QT_TELEMETRY_TRACE_DIR"
_TRACE_MAX_ENV = "QT_TELEMETRY_TRACE_MAX"
_FLIGHT_MAX_ENV = "QT_FLIGHT_EVENTS"
_FLIGHT_DIR_ENV = "QT_FLIGHT_DIR"
_TRACEZ_IDS_ENV = "QT_TRACEZ_JOBS"
_TRACEZ_EVENTS_ENV = "QT_TRACEZ_EVENTS"


def _env_cap(var: str, default: int) -> int:
    raw = os.environ.get(var, "").strip()
    return max(1, int(raw)) if raw else default


# registry state: key = (metric name, canonical label tuple).  One lock
# guards every upsert: the serve layer writes from asyncio + HTTP +
# executor threads, and counters must be exact across them.  The lock is
# taken only on the enabled path (after the _mode test), so the off path
# stays a single int check.
_LOCK = threading.Lock()
_COUNTERS: dict = {}
_GAUGES: dict = {}
_HISTS: dict = {}
# Chrome-trace span buffer: a BOUNDED ring (a long trace-mode serve
# session must not grow without bound) — overflow drops the OLDEST
# event, counts trace_events_dropped_total, and write_trace notes the
# drop in the emitted JSON.
_TRACE_MAX = _env_cap(_TRACE_MAX_ENV, 65536)
_TRACE_EVENTS: collections.deque = collections.deque()
_TRACE_DROPPED = [0]  # drops since the last write_trace
_TRACE_T0 = time.perf_counter()
# flight recorder: bounded ring of recent structured events (spans,
# degradations, watchdog verdicts, drift, admission decisions) dumped
# as a JSON post-mortem on serve/resilience incidents
_FLIGHT_MAX = _env_cap(_FLIGHT_MAX_ENV, 512)
_FLIGHT: collections.deque = collections.deque(maxlen=_FLIGHT_MAX)
_FLIGHT_SEQ = [0]
# request-scoped trace store: trace_id -> {"events", "stack", "dropped"}
# (bounded: oldest id evicted past _TRACEZ_IDS, per-id events capped)
_TRACEZ_IDS = _env_cap(_TRACEZ_IDS_ENV, 256)
_TRACEZ_EVENTS = _env_cap(_TRACEZ_EVENTS_ENV, 512)
_JOB_TRACES: dict = {}


def _resolve_mode() -> int:
    raw = os.environ.get(_ENV_VAR, "on").strip().lower()
    return _MODES.get(raw, ON)


_mode = _resolve_mode()


def configure(mode: Optional[str] = None) -> str:
    """Set the telemetry mode ("off" / "on" / "trace"), or re-resolve it
    from ``QT_TELEMETRY`` when called with no argument.  Returns the
    active mode name.  Recorded series survive mode flips (reset()
    clears them)."""
    global _mode
    if mode is None:
        _mode = _resolve_mode()
    else:
        key = str(mode).strip().lower()
        if key not in _MODES:
            raise ValueError(
                f"telemetry.configure: unknown mode {mode!r} "
                f"(expected off/on/trace)")
        _mode = _MODES[key]
    return _MODE_NAMES[_mode]


def mode_name() -> str:
    return _MODE_NAMES[_mode]


def enabled() -> bool:
    return _mode != OFF


def reset() -> None:
    """Clear every recorded series, buffered trace event, flight-ring
    entry, and request trace (tests and benchmark harnesses; the mode is
    left unchanged)."""
    with _LOCK:
        _COUNTERS.clear()
        _GAUGES.clear()
        _HISTS.clear()
        _TRACE_EVENTS.clear()
        _TRACE_DROPPED[0] = 0
        _FLIGHT.clear()
        _JOB_TRACES.clear()


# ---------------------------------------------------------------------------
# Recording
# ---------------------------------------------------------------------------


def _label_key(labels: dict) -> tuple:
    if not labels:
        return ()
    return tuple(sorted((k, v if type(v) is str else str(v))
                        for k, v in labels.items()))


def inc(name: str, value: float = 1, /, **labels) -> None:
    """Add ``value`` to the counter series ``name{labels}`` (exact under
    concurrent writers — the upsert holds the registry lock)."""
    if not _mode:
        return
    key = (name, _label_key(labels))
    with _LOCK:
        _COUNTERS[key] = _COUNTERS.get(key, 0) + value


def counter_key(name: str, /, **labels) -> tuple:
    """Precomputed series key for :func:`inc_key` — per-gate dispatch
    sites build their label tuple ONCE at import time so the hot-loop
    cost is a single dict upsert."""
    return (name, _label_key(labels))


def inc_key(key: tuple, value: float = 1) -> None:
    """inc() over a key from :func:`counter_key` (the dispatch fast
    path)."""
    if not _mode:
        return
    with _LOCK:
        _COUNTERS[key] = _COUNTERS.get(key, 0) + value


def set_gauge(name: str, value: float, /, **labels) -> None:
    """Set the gauge series ``name{labels}`` to ``value``."""
    if not _mode:
        return
    with _LOCK:
        _GAUGES[(name, _label_key(labels))] = float(value)


# histogram bucket upper bounds, per metric name; the default suits
# second-valued latencies, the explicit entries are size-valued
_DEFAULT_BOUNDS = (1e-4, 1e-3, 1e-2, 1e-1, 1.0, 10.0, 60.0)
HIST_BOUNDS = {
    # guarded-collective dispatch latency (dist.guarded_dispatch): finer
    # low end than the default — a healthy CPU/ICI exchange dispatch sits
    # in the 10us-10ms decades and the deadline policy needs resolution
    # there
    "exchange_latency_seconds": (1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0, 10.0,
                                 60.0),
    "fusion_drain_gates": (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
    "fusion_window_gates": (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
    "fusion_remap_window_items": (1, 2, 4, 8, 16, 32, 64, 128, 256, 512,
                                  1024),
    # circuit-optimizer rewrite time (optimizer.optimize_items): pure
    # host work that should sit well under a drain's planning cost, so
    # the low decades get extra resolution
    "optimizer_seconds": (1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0, 10.0, 60.0),
    # serving-layer queue wait (serve.SimServer): interactive jobs on a
    # loaded server should sit in the sub-ms..100ms decades, so the low
    # end gets the same extra resolution as exchange latency
    "serve_queue_wait_seconds": (1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0, 10.0,
                                 60.0),
    # first dispatch through a freshly-traced executor (§31 AOT cache,
    # labeled fingerprint_cached=true/false): cached first requests sit
    # near steady-state (ms..100ms), uncached ones in the compile
    # decades (seconds..minutes) — both ends need resolution
    "first_request_seconds": (1e-3, 1e-2, 1e-1, 0.5, 1.0, 5.0, 15.0,
                              60.0, 300.0),
}


class _Hist:
    __slots__ = ("count", "total", "vmin", "vmax", "bounds", "buckets")

    def __init__(self, bounds):
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf
        self.bounds = tuple(bounds)
        self.buckets = [0] * (len(self.bounds) + 1)

    def add(self, v: float) -> None:
        self.count += 1
        self.total += v
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v
        self.buckets[bisect.bisect_left(self.bounds, v)] += 1

    def as_dict(self) -> dict:
        cum = 0
        buckets = {}
        for bound, n in zip(self.bounds, self.buckets):
            cum += n
            buckets[repr(float(bound))] = cum
        buckets["+Inf"] = self.count
        return {
            "count": self.count,
            "sum": self.total,
            "min": self.vmin if self.count else None,
            "max": self.vmax if self.count else None,
            "buckets": buckets,
        }


def observe(name: str, value: float, /, **labels) -> None:
    """Record one observation into the histogram series ``name{labels}``."""
    if not _mode:
        return
    key = (name, _label_key(labels))
    with _LOCK:
        h = _HISTS.get(key)
        if h is None:
            h = _HISTS[key] = _Hist(HIST_BOUNDS.get(name, _DEFAULT_BOUNDS))
        h.add(float(value))


def record_exchange(op: str, count: int = 1, nbytes: int = 0, *,
                    chunks="auto", tier: str = "ici") -> None:
    """One call per dispatched exchange program AND per interconnect
    tier: ``count`` collective transfers moving ``nbytes`` PER-SHARD
    bytes total over ``tier`` ("ici" intra-host / "dcn" cross-host —
    parallel/topology.py; the byte unit matches
    circuit.remap_exchange_bytes), labeled with the op family and the
    resolved chunk configuration.  A mixed-tier program (e.g. a window
    remap whose transpositions straddle the host boundary) records once
    per tier with the exact per-tier split, so summing the tier series
    reproduces the flat totals (pinned in tests/test_telemetry.py).  A
    zero ``count`` still records bytes — byte-only attributions (the
    all-gather's cross-host share) keep the count on one tier."""
    if not _mode:
        return
    if count:
        inc("exchanges_total", count, op=op, chunks=chunks, tier=tier)
    if nbytes:
        inc("exchange_bytes_total", nbytes, op=op, tier=tier)


# ---------------------------------------------------------------------------
# Span tracing
# ---------------------------------------------------------------------------


def _chrome_append(ev: dict) -> None:
    """Append one Chrome-trace event to the BOUNDED ring: overflow drops
    the oldest event and counts trace_events_dropped_total."""
    with _LOCK:
        if len(_TRACE_EVENTS) >= _TRACE_MAX:
            _TRACE_EVENTS.popleft()
            _TRACE_DROPPED[0] += 1
            key = ("trace_events_dropped_total", ())
            _COUNTERS[key] = _COUNTERS.get(key, 0) + 1
        _TRACE_EVENTS.append(ev)


def _chrome_event(name: str, t0: float, dt: float, attrs: dict) -> dict:
    return {
        "name": name,
        "cat": "quest_tpu",
        "ph": "X",
        "ts": (t0 - _TRACE_T0) * 1e6,
        "dur": dt * 1e6,
        "pid": os.getpid(),
        "tid": threading.get_ident(),
        "args": {k: str(v) for k, v in attrs.items()},
    }


@contextlib.contextmanager
def span(name: str, **attrs) -> Iterator[None]:
    """Host-side named region: observes ``span_seconds{name}``, appends a
    Chrome-trace complete event in trace mode, and opens a
    ``jax.profiler.TraceAnnotation`` so the region also appears inside
    XLA device traces.  A no-op (beyond the generator frame) when
    telemetry is off."""
    if not _mode:
        yield
        return
    import jax

    t0 = time.perf_counter()
    with jax.profiler.TraceAnnotation(name):
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            observe("span_seconds", dt, name=name)
            if _mode == TRACE:
                _chrome_append(_chrome_event(name, t0, dt, attrs))


def write_trace(path: Optional[str] = None) -> Optional[str]:
    """Write buffered spans as Chrome trace-event JSON (loadable in
    Perfetto / chrome://tracing) and clear the buffer.  Returns the file
    path, or None (writing nothing) when no events are buffered — so
    ``QT_TELEMETRY=off`` never creates trace files.  Default path:
    ``$QT_TELEMETRY_TRACE_DIR/qt_trace_<pid>.json`` (cwd when the env
    var is unset).  When the bounded ring overflowed since the last
    write, the emitted JSON notes the drop count under
    ``otherData.trace_events_dropped``."""
    if not _TRACE_EVENTS:
        return None
    if path is None:
        d = os.environ.get(_TRACE_DIR_ENV, ".")
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"qt_trace_{os.getpid()}.json")
    with _LOCK:
        events = list(_TRACE_EVENTS)
        _TRACE_EVENTS.clear()
        dropped, _TRACE_DROPPED[0] = _TRACE_DROPPED[0], 0
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    if dropped:
        doc["otherData"] = {"trace_events_dropped": dropped}
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


@atexit.register
def _flush_trace_at_exit() -> None:  # pragma: no cover - process teardown
    if _mode == TRACE and _TRACE_EVENTS and os.environ.get(_TRACE_DIR_ENV):
        try:
            write_trace()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Flight recorder (docs/design.md §30)
# ---------------------------------------------------------------------------


def _jsonable(v):
    return v if isinstance(v, (int, float, str, bool)) or v is None \
        else str(v)


def flight_event(kind: str, /, **fields) -> None:
    """Append one structured event to the bounded flight ring — the
    post-mortem record :func:`dump_flight` writes on incidents.  Feeds:
    serve lifecycle/admission events, degradations, watchdog verdicts,
    model drift, and mirrored request-trace spans.  Non-primitive field
    values are stringified so the ring is always JSON-serializable.
    ``kind`` is positional-only; the reserved ``ts``/``kind`` keys win
    over same-named fields."""
    if not _mode:
        return
    ev = {"ts": round(time.perf_counter() - _TRACE_T0, 6), "kind": kind}
    for k, v in fields.items():
        if k not in ("ts", "kind"):
            ev[k] = _jsonable(v)
    with _LOCK:
        _FLIGHT.append(ev)


def flight_snapshot() -> list:
    """The flight ring's current contents, oldest first (a copy)."""
    with _LOCK:
        return list(_FLIGHT)


def dump_flight(path: Optional[str] = None, *, reason: str = "manual",
                **context) -> Optional[str]:
    """Write the flight ring as a JSON post-mortem artifact:
    ``{"reason", "ts", "context", "events"}``.  The ring is NOT drained
    — each dump is a self-contained snapshot, and a later incident still
    sees the earlier context.  Returns the path, or None when telemetry
    is off (incident hooks fire unconditionally; the off mode must stay
    artifact-free).  Default path:
    ``$QT_FLIGHT_DIR/qt_flight_<pid>_<seq>.json`` (cwd when unset)."""
    if not _mode:
        return None
    if path is None:
        d = os.environ.get(_FLIGHT_DIR_ENV, ".")
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, f"qt_flight_{os.getpid()}_{_FLIGHT_SEQ[0]}.json")
    else:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
    _FLIGHT_SEQ[0] += 1
    doc = {
        "reason": reason,
        "ts": time.time(),  # qlint: allow(nondeterminism): the dump's wall-clock stamp IS the recorded value — a post-mortem artifact label, never program state
        "context": {k: _jsonable(v) for k, v in context.items()},
        "events": flight_snapshot(),
    }
    with open(path, "w") as f:
        json.dump(doc, f, sort_keys=True)
    inc("flight_dumps_total", reason=reason)
    return path


# ---------------------------------------------------------------------------
# Request-scoped tracing (docs/design.md §30)
# ---------------------------------------------------------------------------


def _trace_rec(tid: str) -> dict:
    # caller holds _LOCK
    rec = _JOB_TRACES.get(tid)
    if rec is None:
        while len(_JOB_TRACES) >= _TRACEZ_IDS:
            _JOB_TRACES.pop(next(iter(_JOB_TRACES)))
        rec = _JOB_TRACES[tid] = {"events": [], "stack": [], "dropped": 0}
    return rec


def _trace_emit(tid: str, ev: dict) -> None:
    # caller holds _LOCK; per-id event cap drops the OLDEST event
    rec = _trace_rec(tid)
    if len(rec["events"]) >= _TRACEZ_EVENTS:
        rec["events"].pop(0)
        rec["dropped"] += 1
    rec["events"].append(ev)


def _us(t: float) -> float:
    return round((t - _TRACE_T0) * 1e6, 1)


def trace_begin(tid: str, name: str, **attrs) -> None:
    """Open a span on the request trace ``tid`` (closed by
    :func:`trace_end`; the serve layer opens one root ``"job"`` span per
    submitted job).  Active in both enabled modes."""
    if not _mode:
        return
    with _LOCK:
        rec = _trace_rec(tid)
        rec["stack"].append(
            (name, time.perf_counter(),
             {k: str(v) for k, v in attrs.items()}))


def trace_end(tid: str, **attrs) -> None:
    """Close the innermost open span of ``tid``, recording it as a
    complete event spanning its whole open interval; ``attrs`` merge
    into the span args (e.g. ``status="done"``).  No-op when nothing is
    open."""
    if not _mode:
        return
    now = time.perf_counter()
    with _LOCK:
        rec = _JOB_TRACES.get(tid)
        if rec is None or not rec["stack"]:
            return
        name, t0, args = rec["stack"].pop()
        args.update({k: str(v) for k, v in attrs.items()})
        ev = {"name": name, "ph": "X", "ts": _us(t0),
              "dur": round((now - t0) * 1e6, 1),
              "depth": len(rec["stack"]), "args": args}
        _trace_emit(tid, ev)
        _FLIGHT.append({"ts": round(now - _TRACE_T0, 6), "kind": "span",
                        "trace": tid, "name": name, **args})
        if _mode == TRACE:
            chrome = _chrome_event(name, t0, now - t0, args)
            chrome["args"]["trace_id"] = tid
            if len(_TRACE_EVENTS) >= _TRACE_MAX:
                _TRACE_EVENTS.popleft()
                _TRACE_DROPPED[0] += 1
                key = ("trace_events_dropped_total", ())
                _COUNTERS[key] = _COUNTERS.get(key, 0) + 1
            _TRACE_EVENTS.append(chrome)


def trace_point(tid: str, name: str, **attrs) -> None:
    """Record one instantaneous lifecycle event on ``tid`` (admit,
    bank_join, retry, quarantine, complete, ...), mirrored into the
    flight ring."""
    if not _mode:
        return
    now = time.perf_counter()
    args = {k: str(v) for k, v in attrs.items()}
    with _LOCK:
        rec = _trace_rec(tid)
        _trace_emit(tid, {"name": name, "ph": "i", "ts": _us(now),
                          "depth": len(rec["stack"]), "args": args})
        _FLIGHT.append({"ts": round(now - _TRACE_T0, 6), "kind": "event",
                        "trace": tid, "name": name, **args})


def trace_add(tid: str, name: str, *, t0: float, dur: float,
              **attrs) -> None:
    """Attach an externally-timed complete span (perf_counter start +
    duration) to ``tid`` — e.g. one bank window's measured wall time
    mirrored onto every member job's trace."""
    if not _mode:
        return
    args = {k: str(v) for k, v in attrs.items()}
    with _LOCK:
        rec = _trace_rec(tid)
        _trace_emit(tid, {"name": name, "ph": "X", "ts": _us(t0),
                          "dur": round(dur * 1e6, 1),
                          "depth": len(rec["stack"]), "args": args})
    if _mode == TRACE:
        chrome = _chrome_event(name, t0, dur, attrs)
        chrome["args"]["trace_id"] = tid
        _chrome_append(chrome)


@contextlib.contextmanager
def trace_span(tid: str, name: str, **attrs) -> Iterator[None]:
    """Context-manager sugar over trace_begin/trace_end."""
    trace_begin(tid, name, **attrs)
    try:
        yield
    finally:
        trace_end(tid)


def _trace_tree(events: list) -> list:
    """Nest a trace's events by (ts, depth) containment: a depth-d event
    is a child of the most recent still-open depth<(d) span."""
    roots: list = []
    stack: list = []  # (depth, node)
    order = sorted(events, key=lambda e: (e["ts"], -e.get("dur", 0.0)))
    for ev in order:
        node = {"name": ev["name"], "ph": ev["ph"], "ts": ev["ts"],
                "args": ev.get("args", {}), "children": []}
        if "dur" in ev:
            node["dur"] = ev["dur"]
        d = ev.get("depth", 0)
        while stack and stack[-1][0] >= d:
            stack.pop()
        (stack[-1][1]["children"] if stack else roots).append(node)
        if ev["ph"] == "X":
            stack.append((d, node))
    return roots


def trace_ids() -> list:
    """Currently-held request trace ids, oldest first."""
    with _LOCK:
        return list(_JOB_TRACES)


def tracez(tid: Optional[str] = None):
    """The request-trace query API (served at ``/tracez``).  With no
    argument: an index ``{"traces": {tid: {events, open, complete}}}``.
    With a ``tid``: that trace's full record — flat ``events`` (ts/dur
    in microseconds relative to the process trace epoch), the nested
    ``tree``, still-``open`` span names, and ``complete`` (True when
    every span closed and at least one event was recorded).  Returns
    None for an unknown id."""
    with _LOCK:
        if tid is None:
            return {"traces": {
                t: {"events": len(r["events"]),
                    "open": [s[0] for s in r["stack"]],
                    "complete": not r["stack"] and bool(r["events"])}
                for t, r in _JOB_TRACES.items()}}
        rec = _JOB_TRACES.get(tid)
        if rec is None:
            return None
        events = [dict(e) for e in rec["events"]]
        open_spans = [{"name": s[0], "ts": _us(s[1]), "args": dict(s[2])}
                      for s in rec["stack"]]
        dropped = rec["dropped"]
    return {
        "trace_id": tid,
        "events": sorted(events, key=lambda e: e["ts"]),
        "open": open_spans,
        "complete": not open_spans and bool(events),
        "dropped": dropped,
        "tree": _trace_tree(events),
    }


# ---------------------------------------------------------------------------
# Export surfaces
# ---------------------------------------------------------------------------


def _series():
    """Raw (counters, gauges, hists) with the legacy registries folded in
    as first-class series of the same namespace (satellite: absorb
    env._CACHE_STATS and resilience.DEGRADATIONS)."""
    c = dict(_COUNTERS)
    g = dict(_GAUGES)
    h = dict(_HISTS)
    try:
        from .env import _CACHE_STATS

        c[("compile_cache_hits_total", ())] = float(_CACHE_STATS["hits"])
        c[("compile_cache_misses_total", ())] = float(_CACHE_STATS["misses"])
    # qlint: allow(broad-except): a metrics snapshot must never fail — env can be half-torn-down (interpreter exit) when this import runs
    except Exception:  # pragma: no cover - env not importable mid-teardown
        pass
    try:
        from .resilience import DEGRADATIONS

        for nm in DEGRADATIONS:
            g[("degradation_active", (("name", nm),))] = 1.0
    # qlint: allow(broad-except): same teardown window as the cache-stats absorb above — the snapshot drops the series rather than raising
    except Exception:  # pragma: no cover
        pass
    try:
        # §31 AOT tier (satellite 6): folded as its own aot_cache_*
        # namespace so the persistent-executable tier stays
        # distinguishable from XLA's process-local compile_cache_* —
        # the two answer different questions (deserialize-vs-compile
        # across processes vs jit dedup within one)
        from . import aotcache as _aotcache

        a = _aotcache._STATS
        if _aotcache.enabled() or any(a.values()):
            for nm in ("hits", "misses", "puts", "evictions", "errors"):
                c[(f"aot_cache_{nm}_total", ())] = float(a[nm])
            c[("aot_compile_seconds_saved_total", ())] = float(
                a["saved_seconds"])
            g[("aot_cache_bytes", ())] = float(a["bytes"])
    # qlint: allow(broad-except): same teardown window as the cache-stats absorb above — the snapshot drops the series rather than raising
    except Exception:  # pragma: no cover
        pass
    return c, g, h


def _label_str(labels: tuple) -> str:
    return ",".join(f"{k}={v}" for k, v in labels)


def snapshot() -> dict:
    """The whole registry as a nested dict:
    ``{"mode", "counters": {name: {label_str: value}}, "gauges": ...,
    "histograms": {name: {label_str: {count, sum, min, max, buckets}}}}``.
    Returns ``{}`` when telemetry is off."""
    if not _mode:
        return {}
    c, g, h = _series()
    out = {"mode": mode_name(), "counters": {}, "gauges": {},
           "histograms": {}}
    for (name, labels), v in sorted(c.items()):
        out["counters"].setdefault(name, {})[_label_str(labels)] = v
    for (name, labels), v in sorted(g.items()):
        out["gauges"].setdefault(name, {})[_label_str(labels)] = v
    for (name, labels), hist in sorted(h.items()):
        out["histograms"].setdefault(
            name, {})[_label_str(labels)] = hist.as_dict()
    return out


def counter_total(name: str) -> float:
    """Sum of the counter ``name`` across every label set (0 when absent
    or telemetry is off)."""
    if not _mode:
        return 0.0
    c, _g, _h = _series()
    return float(sum(v for (n, _l), v in c.items() if n == name))


def counter_value(name: str, /, **labels) -> float:
    """One labeled counter series' value (0 when absent)."""
    if not _mode:
        return 0.0
    c, _g, _h = _series()
    return float(c.get((name, _label_key(labels)), 0))


def counter_sum(name: str, /, **labels) -> float:
    """Sum of the counter ``name`` over every series whose labels are a
    SUPERSET of ``labels`` — e.g. ``counter_sum("exchanges_total",
    op="window_remap")`` folds the per-chunk-config series into the one
    total the reconciliation loop compares against its prediction."""
    if not _mode:
        return 0.0
    want = _label_key(labels)
    c, _g, _h = _series()
    return float(sum(
        v for (n, l), v in c.items()
        if n == name and set(want) <= set(l)))


def gauge_max(name: str) -> Optional[float]:
    """Max of the gauge ``name`` across its label sets (None when absent
    or telemetry is off) — e.g. the peak ``hbm_watermark_bytes`` over
    devices for getEnvironmentString / reportPerf."""
    if not _mode:
        return None
    _c, g, _h = _series()
    vals = [v for (n, _l), v in g.items() if n == name]
    return max(vals) if vals else None


def _esc(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _prom_labels(labels: tuple, extra: tuple = ()) -> str:
    items = tuple(labels) + tuple(extra)
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{_esc(str(v))}"' for k, v in items) + "}"


def _num(v: float) -> str:
    f = float(v)
    # the text exposition format spells non-finite values +Inf/-Inf/NaN;
    # Python's repr() says inf/nan, which Prometheus parsers reject
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


def prometheus_text() -> str:
    """The registry in Prometheus text exposition format (counters,
    gauges, and histograms with cumulative ``le`` buckets).  Empty
    string when telemetry is off."""
    if not _mode:
        return ""
    c, g, h = _series()
    lines = []
    seen_type = set()

    def typeline(name, kind):
        if name not in seen_type:
            seen_type.add(name)
            lines.append(f"# TYPE {name} {kind}")

    for (name, labels), v in sorted(c.items()):
        typeline(name, "counter")
        lines.append(f"{name}{_prom_labels(labels)} {_num(v)}")
    for (name, labels), v in sorted(g.items()):
        typeline(name, "gauge")
        lines.append(f"{name}{_prom_labels(labels)} {_num(v)}")
    for (name, labels), hist in sorted(h.items()):
        typeline(name, "histogram")
        cum = 0
        for bound, n in zip(hist.bounds, hist.buckets):
            cum += n
            lines.append(
                f"{name}_bucket"
                f"{_prom_labels(labels, (('le', repr(float(bound))),))}"
                f" {cum}")
        lines.append(
            f"{name}_bucket{_prom_labels(labels, (('le', '+Inf'),))}"
            f" {hist.count}")
        lines.append(f"{name}_sum{_prom_labels(labels)} {_num(hist.total)}")
        lines.append(f"{name}_count{_prom_labels(labels)} {hist.count}")
    return "\n".join(lines) + ("\n" if lines else "")


def summary() -> str:
    """One compact line for getEnvironmentString's ``[telemetry: ...]``
    block: the mode plus every counter total aggregated over labels.
    Consolidates the folded cache tiers too (compile_cache_* = XLA's
    process-local jit cache, aot_cache_* = the §31 persistent
    executable tier) so the two stay distinguishable; zero-valued
    totals are dropped — the folds inject their series unconditionally
    and an all-zero tier is noise here."""
    if not _mode:
        return "off"
    totals: dict = {}
    counters, _gauges, _hists = _series()
    for (name, _labels), v in counters.items():
        totals[name] = totals.get(name, 0) + v
    parts = [mode_name()]
    for name in sorted(totals):
        if not totals[name]:
            continue
        short = name[:-6] if name.endswith("_total") else name
        parts.append(f"{short}={_num(totals[name])}")
    return " ".join(parts)


def perf_report(env=None) -> str:
    """Multi-line human-readable perf report (the string behind
    ``reportPerf``)."""
    lines = [f"quest_tpu perf report (telemetry={mode_name()})"]
    if env is not None:
        from .env import get_environment_string

        lines.append(get_environment_string(env))
    snap = snapshot()
    if not snap:
        lines.append("telemetry is off (QT_TELEMETRY=off)")
        return "\n".join(lines)
    if snap["counters"]:
        lines.append("counters:")
        for name, series in snap["counters"].items():
            for labels, v in series.items():
                tag = f"{{{labels}}}" if labels else ""
                lines.append(f"  {name}{tag} = {_num(v)}")
    if snap["gauges"]:
        lines.append("gauges:")
        for name, series in snap["gauges"].items():
            for labels, v in series.items():
                tag = f"{{{labels}}}" if labels else ""
                lines.append(f"  {name}{tag} = {_num(v)}")
    if snap["histograms"]:
        lines.append("histograms:")
        for name, series in snap["histograms"].items():
            for labels, hd in series.items():
                tag = f"{{{labels}}}" if labels else ""
                mean = hd["sum"] / hd["count"] if hd["count"] else 0.0
                lines.append(
                    f"  {name}{tag}: count={hd['count']} "
                    f"sum={hd['sum']:.6g} mean={mean:.6g} "
                    f"max={hd['max'] if hd['max'] is not None else '-'}")
    # per-tier exchange volume (parallel/topology.py): the ici/dcn split
    # of every exchange series — sums exactly to the flat totals
    tier_lines = []
    for tier in ("ici", "dcn"):
        tc = counter_sum("exchanges_total", tier=tier)
        tb = counter_sum("exchange_bytes_total", tier=tier)
        if tc or tb:
            tier_lines.append(f"  {tier}: exchanges={_num(tc)} "
                              f"bytes/shard={_num(tb)}")
    if tier_lines:
        lines.append("exchange tiers (per-shard bytes by interconnect):")
        lines.extend(tier_lines)
    # circuit-optimizer activity (optimizer.py, docs/design.md §26):
    # stream rewrites ahead of the fusion planner, by transform kind
    removed = counter_total("optimizer_gates_removed_total")
    wmerged = counter_total("optimizer_windows_merged_total")
    if removed or wmerged:
        from . import optimizer as _optimizer

        by_kind = " ".join(
            f"{k}={_num(counter_sum('optimizer_gates_removed_total', kind=k))}"
            for k in ("cancel", "merge", "diag_coalesce", "perm_coalesce")
            if counter_sum("optimizer_gates_removed_total", kind=k))
        lines.append(f"circuit optimizer (mode={_optimizer.mode()}):")
        lines.append(f"  gates removed: total={_num(removed)} {by_kind}")
        lines.append(f"  remap windows merged: {_num(wmerged)}")
        secs = snap["histograms"].get("optimizer_seconds", {})
        tot_n = sum(hd["count"] for hd in secs.values())
        if tot_n:
            tot_s = sum(hd["sum"] for hd in secs.values())
            lines.append(
                f"  optimize time: count={tot_n} "
                f"mean={tot_s / tot_n:.6g}s")
    perm = counter_total("permutation_gates_total")
    sparse = counter_total("sparse_inits_total")
    if perm or sparse:
        lines.append("permutation fast paths (§28):")
        if perm:
            by_route = " ".join(
                f"{r}={_num(counter_sum('permutation_gates_total', route=r))}"
                for r in ("relabel", "gather", "exchange")
                if counter_sum("permutation_gates_total", route=r))
            lines.append(f"  gates: total={_num(perm)} {by_route}")
        if sparse:
            lines.append(
                f"  sparse inits: {_num(sparse)} "
                f"(amps={_num(counter_total('sparse_init_amps_total'))})")
    # §29 window megakernel: per-route dispatch split and the HBM
    # round-trips the last drain paid per fused plan window
    mega_n = counter_total("megakernel_dispatch_total")
    if mega_n:
        from .ops import fused as _fused

        by_route = " ".join(
            f"{r}={_num(counter_sum('megakernel_dispatch_total', route=r))}"
            for r in ("mega", "fallback")
            if counter_sum("megakernel_dispatch_total", route=r))
        lines.append(
            f"window megakernel (§29, mode={_fused.megakernel_mode()}):")
        lines.append(f"  dispatches: total={_num(mega_n)} {by_route}")
        trips = gauge_max("window_hbm_round_trips")
        if trips is not None:
            lines.append(
                f"  hbm_round_trips/plan_window={trips:.3g} "
                f"(1.0 = one read + one write per fused window)")
    # §30 per-op wall-time attribution: each dispatched drain group's
    # wall time, keyed by its dominant plan-entry family (megawin /
    # winfused / permfast / channel / remap).  When the measured
    # per-dispatch mean sits within 10% of the host's measured
    # per-program dispatch floor (introspect.measure_dispatch_floor /
    # scripts/bench_dispatch.py), the route is labeled dispatch_bound —
    # the r04->r05 regression regime, detected live instead of by
    # forensic bisection.
    routes = snap["histograms"].get("plan_route_seconds", {})
    if routes:
        floor = gauge_max("per_program_dispatch_seconds")
        lines.append("per-op attribution (§30, wall time by plan-entry "
                     "route):")
        for labels, hd in sorted(routes.items()):
            mean = hd["sum"] / hd["count"] if hd["count"] else 0.0
            verdict = ""
            if floor and hd["count"] and mean <= floor * 1.10:
                verdict = "  [dispatch_bound: mean within 10% of the " \
                          "host dispatch floor]"
            lines.append(
                f"  {labels}: dispatches={hd['count']} "
                f"total={hd['sum']:.6g}s mean={mean:.6g}s{verdict}")
        if floor:
            lines.append(
                f"  dispatch floor: {floor:.3g}s/program "
                f"(introspect.measure_dispatch_floor)")
    pred_c = counter_sum("predicted_exchanges_total", op="window_remap")
    meas_c = counter_sum("exchanges_total", op="window_remap")
    pred_b = counter_sum("predicted_exchange_bytes_total", op="window_remap")
    meas_b = counter_sum("exchange_bytes_total", op="window_remap")
    drift = counter_total("model_drift_total")
    if pred_c or meas_c or drift:
        lines.append("reconciliation (window remaps, predicted vs measured):")
        lines.append(f"  exchanges: predicted={_num(pred_c)} "
                     f"measured={_num(meas_c)}")
        lines.append(f"  bytes/shard: predicted={_num(pred_b)} "
                     f"measured={_num(meas_b)}")
        verdict = ("MODEL DRIFT" if drift else "cost model holds")
        lines.append(f"  model_drift_total={_num(drift)} ({verdict})")
    # serving layer (quest_tpu.serve): queue pressure, occupancy, and
    # the preemption history — pure counter/gauge reads, so telemetry
    # stays importable without the serve module
    sub = counter_total("serve_jobs_submitted_total")
    if sub:
        done_n = counter_total("serve_jobs_completed_total")
        rej = counter_total("serve_jobs_rejected_total")
        failed = counter_total("serve_jobs_failed_total")
        pre = counter_total("preemptions_total")
        res = counter_total("serve_resumes_total")
        depth = gauge_max("serve_queue_depth")
        occ = gauge_max("serve_bank_occupancy")
        lines.append("serving (continuous batcher):")
        lines.append(
            f"  jobs: submitted={_num(sub)} completed={_num(done_n)} "
            f"rejected={_num(rej)} failed={_num(failed)}")
        lines.append(
            f"  preemptions={_num(pre)} resumes={_num(res)} "
            f"queue_depth={_num(depth) if depth is not None else '-'} "
            f"bank_occupancy="
            f"{f'{occ:.3f}' if occ is not None else '-'}")
        wait = snap["histograms"].get("serve_queue_wait_seconds", {})
        tot_n = sum(hd["count"] for hd in wait.values())
        tot_s = sum(hd["sum"] for hd in wait.values())
        if tot_n:
            wmax = max(hd["max"] for hd in wait.values()
                       if hd["max"] is not None)
            lines.append(
                f"  queue_wait_seconds: count={tot_n} "
                f"mean={tot_s / tot_n:.6g} max={wmax:.6g}")
    # serving resilience (docs/design.md §27): bank retries, poison
    # quarantine, failover/heal history, and the live degraded flag
    retr = counter_total("serve_bank_retries_total")
    quar = counter_total("serve_jobs_quarantined_total")
    fo = counter_total("serve_failovers_total")
    heals = counter_total("serve_heals_total")
    deg = gauge_max("serve_degraded")
    if retr or quar or fo or heals or deg:
        by_reason = " ".join(
            f"{r}={_num(counter_sum('serve_bank_retries_total', reason=r))}"
            for r in ("transient", "failover", "poison")
            if counter_sum("serve_bank_retries_total", reason=r))
        lines.append("serving resilience:")
        lines.append(f"  bank retries: total={_num(retr)}"
                     + (f" ({by_reason})" if by_reason else ""))
        lines.append(
            f"  quarantined={_num(quar)} failovers={_num(fo)} "
            f"heals={_num(heals)} degraded={int(deg or 0)}")
        mttr = gauge_max("serve_failover_mttr_seconds")
        if mttr is not None:
            lines.append(f"  failover_mttr_seconds={mttr:.4g}")
    # §31 AOT executable cache + serve warm pool: the persistent tier's
    # consult/persist history, the compile seconds its hits avoided,
    # and the prewarmer's pool depth/backlog — counter reads via
    # _series' aotcache fold, so the block also appears when the tier
    # ran with telemetry off for part of the process lifetime
    aot_h = counter_total("aot_cache_hits_total")
    aot_m = counter_total("aot_cache_misses_total")
    aot_p = counter_total("aot_cache_puts_total")
    aot_e = counter_total("aot_cache_errors_total")
    if aot_h or aot_m or aot_p or aot_e:
        lines.append("AOT cache / warm pool (§31):")
        lines.append(
            f"  executables: hits={_num(aot_h)} misses={_num(aot_m)} "
            f"puts={_num(aot_p)} "
            f"evictions={_num(counter_total('aot_cache_evictions_total'))} "
            f"errors={_num(aot_e)}")
        size = gauge_max("aot_cache_bytes")
        saved = counter_total("aot_compile_seconds_saved_total")
        lines.append(
            f"  bytes={_num(size or 0)} "
            f"compile_seconds_saved={saved:.4g}")
        depth = gauge_max("serve_warm_pool_depth")
        backlog = gauge_max("serve_prewarm_backlog")
        if depth is not None or backlog is not None:
            lines.append(
                f"  warm pool: depth={_num(depth or 0)} "
                f"peak_backlog={_num(backlog or 0)} "
                f"prewarms={_num(counter_total('serve_prewarm_total'))}")
        first = snap["histograms"].get("first_request_seconds", {})
        for labels, hd in sorted(first.items()):
            mean = hd["sum"] / hd["count"] if hd["count"] else 0.0
            lines.append(
                f"  first_request_seconds{{{labels}}}: "
                f"count={hd['count']} mean={mean:.6g} "
                f"max={hd['max'] if hd['max'] is not None else '-'}")
    # §30 observability surfaces: flight-ring occupancy / dump history
    # and the request-trace store (the /tracez population)
    fl = len(_FLIGHT)
    dumps = counter_total("flight_dumps_total")
    if fl or dumps:
        lines.append(
            f"flight recorder: {fl} event(s) buffered, "
            f"{int(dumps)} dump(s) written")
    if _JOB_TRACES:
        lines.append(
            f"request traces: {len(_JOB_TRACES)} trace(s) held (tracez)")
    peak = gauge_max("hbm_watermark_bytes")
    if peak is not None:
        lines.append(f"memory: hbm_watermark_bytes peak={_num(peak)} "
                     f"({peak / (1 << 20):.1f} MiB)")
    # memory-governor status (budget, residency, spill/OOM history)
    from . import governor as _governor

    gov_line = _governor.summary_line()
    if gov_line:
        lines.append(gov_line)
    return "\n".join(lines)


def report_perf(env=None) -> None:
    """Print the perf report — the telemetry member of the reference's
    ``report*`` family (reportQuESTEnv, reportQuregParams, ...)."""
    print(perf_report(env))
