"""Cost-model-guided circuit optimizer: rewrite the pending gate stream
BEFORE the fusion planner sees it (docs/design.md §26).

The scheduler so far optimized *how* to execute a drain — window remaps
(§14), pipelined exchange (§17), batched banks (§20) — but every gate in
the buffer still reached ``fusion._split_items`` verbatim.  OptQC-style
circuit optimization (PAPERS.md) closes that gap with three families of
semantics-preserving transforms over the buffered item stream:

* **Cancellation / merging** — a gate searches backwards through gates it
  commutes with for a same-target partner; the pair composes via one host
  matmul (``circuit.soa_matmul``).  A product that is EXACTLY the
  identity (bitwise — X·X, CNOT·CNOT, SWAP·SWAP, Z·Z qualify; H·H does
  not, its f64 product is ``1+2e-16`` on the diagonal) cancels outright;
  anything else replaces the partner as one merged gate.  Exact-identity
  gating keeps cancellation bit-identical to the unoptimized stream; the
  near-identity drop (tolerance-scaled) is reserved for ``aggressive``.

* **Diagonal / phase coalescing** — maximal runs of adjacent diagonal
  gates (Z, S, T, phase shifts, controlled phases — anything
  ``circuit.is_diag_gate`` accepts) collapse into ONE diagonal gate on
  the union targets (capped at the fusion gate width), replacing a chain
  of small matmul passes with a single phase-mask application.

* **Permutation coalescing** (§28) — maximal runs of adjacent
  permutation-classified gates (X / CNOT / Toffoli / SWAP chains,
  ``circuit.classify_permutation_gate``) compose by exact integer index
  arithmetic into ONE permutation gate on the union targets; identity
  products drop.  The composed gates still classify as permutations, so
  the fusion layer's gather/relabel lowering fires on the coalesced
  stream.  Gated on ``QT_PERM_FAST`` like the lowering itself.

* **Commutation-aware reordering** (sharded registers) — a dependency
  DAG over the stream (edges between non-commuting items; commutation =
  disjoint supports, diagonal↔diagonal, or same-target matrices that
  numerically commute) is greedily re-linearized to cluster items by
  target-locality so ``circuit.plan_remap_windows`` emits fewer sigmas.
  The candidate order is *scored against the scheduler's own cost
  model* — ``dist.remap_exchange_count`` and the tier-weighted
  ``circuit.remap_exchange_bytes_tiers`` under the live
  logical→physical permutation — and adopted only when strictly
  cheaper, so the optimizer minimizes actual ICI/DCN exchange, not gate
  count alone.  ``aggressive`` widens the search to several candidate
  linearizations.

``QT_OPTIMIZER=off|on|aggressive`` (default ``on``) selects the mode;
``set_circuit_optimizer`` overrides it programmatically.  The mode is
part of the fusion plan-cache key AND the batch structure fingerprint,
so flipping it retraces and never mixes buckets.  Because the rewrite
happens before planning, every downstream consumer — the plan cache,
the governor's drain predictor, telemetry's window accounting, and the
§21 predicted-vs-measured reconciliation — prices the OPTIMIZED stream:
``model_drift_total`` stays 0 on optimized drains by construction.

Channels (``fusion.ChannelItem``) and traced (non-numpy) matrices are
never composed or dropped; they participate in reordering only through
the disjoint-support rule, so probability streams keep their relative
order and value-dependent gates are left untouched.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from . import circuit as C
from . import telemetry as _telemetry

_MODES = ("off", "on", "aggressive")

# programmatic override (setCircuitOptimizer); None = read QT_OPTIMIZER
_OVERRIDE: List[Optional[str]] = [None]

# widest coalesced diagonal gate — mirrors fusion.FUSION_MAX_GATE_QUBITS
# (not imported: fusion imports this module)
MAX_GATE_QUBITS = 7

# reordering is O(items^2) host work; past this the stream is left in
# program order (cancellation/coalescing still run — they are O(k·depth))
_REORDER_MAX_ITEMS = 512

# memoized rewrites: optimizing is pure host work but a hot angle-sweep
# loop re-drains the same stream thousands of times
_CACHE_MAX = 128
_cache: dict = {}

# suppression depth (see suppressed()): >0 forces optimize_items into a
# verbatim no-op regardless of mode
_SUPPRESS: List[int] = [0]


def mode() -> str:
    """Active optimizer mode: the ``set_circuit_optimizer`` override when
    armed, else ``QT_OPTIMIZER`` (default ``on``)."""
    if _OVERRIDE[0] is not None:
        return _OVERRIDE[0]
    m = os.environ.get("QT_OPTIMIZER", "on").strip().lower()
    return m if m in _MODES else "on"


def set_circuit_optimizer(m: Optional[str]) -> None:
    """Override the optimizer mode (``None`` returns control to the
    ``QT_OPTIMIZER`` env var)."""
    if m is not None:
        m = str(m).strip().lower()
        if m not in _MODES:
            from .validation import QuESTError

            raise QuESTError(
                f"setCircuitOptimizer: unknown mode {m!r} "
                f"(expected one of {'/'.join(_MODES)})")
    _OVERRIDE[0] = m


def get_circuit_optimizer() -> str:
    """The active optimizer mode string."""
    return mode()


class suppressed:
    """Context manager forcing :func:`optimize_items` into a verbatim
    no-op for the drains it encloses.

    Window-stepped execution (``resilience.WindowExecutor`` — the shared
    core of ``run_resumable`` and the serving layer) drains one gate
    window at a time through fusion, and its checkpoint cursor indexes
    the RAW gate list; a resumed run may re-enter the stream on a
    DIFFERENT mesh (elastic 8→4 failover, mesh-portable checkpoints) and
    under a different live permutation.  The rewrite is cost-gated on
    exactly those inputs, so letting it fire per window would make the
    executed stream depend on mesh/perm history — breaking the
    bit-identity-across-resume contracts that layer pins.  Those drains
    run under ``suppressed()``; direct drains are unaffected."""

    def __enter__(self):
        _SUPPRESS[0] += 1
        return self

    def __exit__(self, *exc):
        _SUPPRESS[0] -= 1
        return False


# ---------------------------------------------------------------------------
# Item predicates
# ---------------------------------------------------------------------------


def _is_gate(it) -> bool:
    return isinstance(it, C.Gate)


def _concrete(it) -> bool:
    return _is_gate(it) and isinstance(it.mat, np.ndarray) \
        and it.mat.ndim in (3, 4)


def _bits(it) -> frozenset:
    """Logical state-vector bits an item touches (fusion._item_bits as a
    set; channels touch their ket + bra twin bits)."""
    if _is_gate(it):
        return frozenset(it.targets)
    return frozenset((it.target, it.bra))


def _soa_matmul_any(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Complex SoA matrix product for (2, s, s) and per-element
    (B, 2, s, s) stacks, broadcasting a shared operand across a batched
    one.  The 3-dim case delegates to circuit.soa_matmul so a merged
    gate's matrix is bit-identical to the fold the window planner would
    have computed for the same pair."""
    if a.ndim == 3 and b.ndim == 3:
        return C.soa_matmul(a, b)
    ar, ai = a[..., 0, :, :], a[..., 1, :, :]
    br, bi = b[..., 0, :, :], b[..., 1, :, :]
    return np.stack([ar @ br - ai @ bi, ar @ bi + ai @ br], axis=-3)


# exact-identity cancellation gate (see circuit.is_identity_gate: X·X
# cancels bitwise, H·H must merge)
_is_identity = C.is_identity_gate


def _near_identity(m: np.ndarray) -> bool:
    """Identity up to the dtype's diagonal-detection tolerance — the
    ``aggressive``-mode drop for merged pairs like H·H whose product is
    the identity only up to rounding."""
    s = m.shape[-1]
    eye = np.eye(s, dtype=m.dtype)
    tol = 1e-5 if m.dtype == np.float32 else 1e-10
    return bool(np.abs(m[..., 0, :, :] - eye).max() <= tol
                and np.abs(m[..., 1, :, :]).max() <= tol)


def _is_diag(it) -> bool:
    return _concrete(it) and it.mat.ndim == 3 and C.is_diag_gate(it.mat)


def _is_perm(it) -> bool:
    return _concrete(it) and it.mat.ndim == 3 \
        and C.classify_permutation_gate(it.mat) is not None


def _mats_commute(a: np.ndarray, b: np.ndarray) -> bool:
    ab = _soa_matmul_any(a, b)
    ba = _soa_matmul_any(b, a)
    tol = 1e-5 if ab.dtype == np.float32 else 1e-10
    return bool(np.abs(ab - ba).max() <= tol)


def _commutes(a, b, diag_a: bool, diag_b: bool) -> bool:
    """May items ``a`` and ``b`` swap order?  Disjoint supports always
    commute; overlapping gates commute when both are diagonal (covers
    Z/S/T/phase-shift/CZ/CPhase chains sharing controls or targets) or
    when they act on the SAME targets with numerically commuting
    matrices (same-axis rotation runs).  Channels only commute by
    disjointness — their Kraus maps are diagonal-basis-specific."""
    if not (_bits(a) & _bits(b)):
        return True
    if not (_is_gate(a) and _is_gate(b)):
        return False
    if diag_a and diag_b:
        return True
    if (tuple(a.targets) == tuple(b.targets) and _concrete(a)
            and _concrete(b) and a.mat.ndim == 3 and b.mat.ndim == 3):
        return _mats_commute(a.mat, b.mat)
    return False


# ---------------------------------------------------------------------------
# Pass 1: cancellation / merging
# ---------------------------------------------------------------------------


def _cancel_merge(items: list, removed: dict, aggressive: bool) -> list:
    """One left-to-right pass: each concrete gate looks backwards through
    items it commutes with for a same-target partner to compose with.
    An exact-identity product cancels the pair; otherwise the partner is
    replaced by the merged gate (matmul order: partner first, newcomer
    second → ``new @ old``)."""
    out: list = []
    diag: list = []  # _is_diag per out entry, computed once

    for it in items:
        if not _concrete(it):
            out.append(it)
            diag.append(False)
            continue
        d_it = _is_diag(it)
        j = len(out) - 1
        composed = False
        while j >= 0:
            prev = out[j]
            if (_concrete(prev)
                    and tuple(prev.targets) == tuple(it.targets)):
                merged = _soa_matmul_any(it.mat, prev.mat)
                if _is_identity(merged) or (
                        aggressive and _near_identity(merged)):
                    out.pop(j)
                    diag.pop(j)
                    removed["cancel"] += 2
                else:
                    out[j] = C.Gate(prev.targets, merged)
                    diag[j] = _is_diag(out[j])
                    removed["merge"] += 1
                composed = True
                break
            if _commutes(prev, it, diag[j], d_it):
                j -= 1
                continue
            break
        if not composed:
            out.append(it)
            diag.append(d_it)
    return out


# ---------------------------------------------------------------------------
# Pass 2: diagonal / phase coalescing
# ---------------------------------------------------------------------------


def _gate_diag(m: np.ndarray) -> np.ndarray:
    """(2, s) diagonal of a stacked SoA matrix."""
    idx = np.arange(m.shape[-1])
    return m[:, idx, idx]


def _compose_diag_run(run: Sequence[C.Gate]) -> C.Gate:
    """Collapse a run of diagonal gates into ONE diagonal gate on the
    sorted union of their targets: each gate's (2, 2^k) diagonal is
    gathered up to the union index space and the entries multiply
    complex-elementwise in stream order."""
    union = sorted({t for g in run for t in g.targets})
    upos = {t: i for i, t in enumerate(union)}
    d = 1 << len(union)
    idx = np.arange(d)
    dt = np.result_type(*[g.mat.dtype for g in run])
    re = np.ones(d, dtype=dt)
    im = np.zeros(d, dtype=dt)
    for g in run:
        sub = np.zeros(d, dtype=np.int64)
        for i, t in enumerate(g.targets):
            sub |= ((idx >> upos[t]) & 1) << i
        gd = _gate_diag(np.asarray(g.mat, dtype=dt))
        gre, gim = gd[0][sub], gd[1][sub]
        re, im = re * gre - im * gim, re * gim + im * gre
    mat = np.zeros((2, d, d), dtype=dt)
    mat[0][idx, idx] = re
    mat[1][idx, idx] = im
    return C.Gate(tuple(union), mat)


def _coalesce_diag(items: list, removed: dict, nloc: int) -> list:
    """Collapse maximal runs of ADJACENT diagonal gates (adjacency after
    the cancel/merge and reorder passes) whose union target set fits one
    fused gate."""
    cap = min(MAX_GATE_QUBITS, nloc)
    out: list = []
    run: list = []
    runbits: set = set()

    def flush():
        if len(run) >= 2:
            out.append(_compose_diag_run(run))
            removed["diag_coalesce"] += len(run) - 1
        else:
            out.extend(run)
        run.clear()
        runbits.clear()

    for it in items:
        if _is_diag(it):
            b = set(it.targets)
            if len(runbits | b) > cap:
                flush()
            run.append(it)
            runbits |= b
        else:
            flush()
            out.append(it)
    flush()
    return out


# ---------------------------------------------------------------------------
# Pass 2b: permutation coalescing (§28)
# ---------------------------------------------------------------------------


def _compose_perm_run(run: Sequence[C.Gate]):
    """ONE permutation gate equal to a run of permutation-classified
    gates: the composed index table comes from exact integer arithmetic
    (circuit.compose_permutation_run), so the 0/1 matrix is bit-identical
    to applying the run gate-by-gate.  Returns None when the run
    composes to the identity (e.g. SWAP·SWAP across distinct pairs)."""
    union, pi = C.compose_permutation_run(run)
    d = 1 << len(union)
    idx = np.arange(d)
    if np.array_equal(np.asarray(pi), idx):
        return None
    dt = np.result_type(*[g.mat.dtype for g in run])
    mat = np.zeros((2, d, d), dtype=dt)
    mat[0, idx, np.asarray(pi)] = 1.0
    return C.Gate(tuple(union), mat)


def _coalesce_perm(items: list, removed: dict, nloc: int) -> list:
    """Collapse maximal runs of ADJACENT permutation-classified gates
    (X / CNOT / Toffoli / SWAP chains) whose union target set fits one
    fused gate into a single permutation gate; runs composing to the
    identity drop outright.  Long chains shrink to short runs of wide
    gates that still classify as permutations, so the fusion layer's
    §28 gather lowering fires on the coalesced stream too."""
    if not C.perm_fast_enabled():
        return items
    cap = min(MAX_GATE_QUBITS, nloc)
    out: list = []
    run: list = []
    runbits: set = set()

    def flush():
        if len(run) >= 2:
            g = _compose_perm_run(run)
            if g is None:
                removed["perm_coalesce"] += len(run)
            else:
                out.append(g)
                removed["perm_coalesce"] += len(run) - 1
        else:
            out.extend(run)
        run.clear()
        runbits.clear()

    for it in items:
        if _is_perm(it):
            b = set(it.targets)
            if len(runbits | b) > cap:
                flush()
            run.append(it)
            runbits |= b
        else:
            flush()
            out.append(it)
    flush()
    return out


# ---------------------------------------------------------------------------
# Pass 3: commutation-aware reordering (sharded registers)
# ---------------------------------------------------------------------------


def _stream_cost(items: Sequence, n: int, nloc: int, perm0) -> tuple:
    """Cost-model score of draining ``items`` in this order from the
    live permutation ``perm0``: (tier-weighted exchange bytes, exchange
    count, remap windows) — the same quantities explain_circuit reports
    and reconcile_drain verifies, plus the canonical-read remap the next
    ``Qureg.amps`` pays, so clustering cannot win by deferring cost to
    the read."""
    from . import fusion as F
    from .parallel import dist as PAR
    from .parallel import topology as _topo

    nsh = n - nloc
    weights = _topo.tier_weights()
    # entries MUST come from fusion._item_entry — the same constructor
    # the sharded planner and §21 reconciliation use — so relabel-tagged
    # permutation gates fold here exactly as they will at dispatch
    segments, final_perm = C.plan_remap_windows(
        [F._item_entry(it) for it in items], n, nloc, perm0)
    sigmas = [s for _ij, s, _p in segments if s is not None]
    if final_perm is not None and list(final_perm) != list(range(n)):
        sigmas.append(PAR.canonical_sigma(tuple(final_perm)))
    count = 0
    weighted = 0.0
    for sigma in sigmas:
        count += PAR.remap_exchange_count(tuple(sigma), nloc, nsh)
        for tier, b in C.remap_exchange_bytes_tiers(
                tuple(sigma), n, nloc).items():
            weighted += weights.get(tier, 1.0) * b
    return (weighted, count, len(segments))


def _greedy_order(items: Sequence, nloc: int, prefer_overlap: bool) -> list:
    """Greedy DAG linearization clustering ready items by target
    locality: schedule the ready item whose bits grow the current
    window's qubit set least (``prefer_overlap`` breaks ties toward the
    largest overlap instead of program order — the extra ``aggressive``
    candidate)."""
    k = len(items)
    bits = [_bits(it) for it in items]
    diag = [_is_diag(it) for it in items]
    preds = [0] * k
    succs: List[List[int]] = [[] for _ in range(k)]
    for i in range(k):
        for j in range(i + 1, k):
            if not _commutes(items[i], items[j], diag[i], diag[j]):
                preds[j] += 1
                succs[i].append(j)
    ready = [i for i in range(k) if preds[i] == 0]
    order: list = []
    window: set = set()
    while ready:
        best = None
        for i in ready:
            grow = len(bits[i] - window)
            if len(window | bits[i]) > nloc:
                grow = len(bits[i]) + nloc  # forces a fresh window
            key = (grow, -len(bits[i] & window), i) if prefer_overlap \
                else (grow, i)
            if best is None or key < best[0]:
                best = (key, i)
        i = best[1]
        if len(window | bits[i]) > nloc:
            window = set()
        window |= bits[i]
        order.append(i)
        ready.remove(i)
        for j in succs[i]:
            preds[j] -= 1
            if preds[j] == 0:
                ready.append(j)
    return order


def _reorder(items: list, n: int, nloc: int, perm0,
             aggressive: bool) -> tuple:
    """Try greedy locality-clustering linearizations of the commutation
    DAG and keep the first that the cost model scores STRICTLY cheaper
    than program order.  Returns (items, reordered, cost_before,
    cost_after)."""
    base = _stream_cost(items, n, nloc, perm0)
    if len(items) < 3 or len(items) > _REORDER_MAX_ITEMS:
        return items, False, base, base
    variants = (False, True) if aggressive else (False,)
    best_items, best_cost = items, base
    for prefer_overlap in variants:
        order = _greedy_order(items, nloc, prefer_overlap)
        if order == list(range(len(items))):
            continue
        cand = [items[i] for i in order]
        cost = _stream_cost(cand, n, nloc, perm0)
        if cost < best_cost:
            best_items, best_cost = cand, cost
    return best_items, best_items is not items, base, best_cost


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------


def _freeze_out(items, out) -> tuple:
    """Cache-storable form of a rewritten stream: channel items are
    replaced by their INPUT index.  Channels key on (kind, target, bra)
    — ``prob`` is a runtime value — so a cache hit must splice in the
    CURRENT call's channel objects, not replay the first call's
    probabilities."""
    pos = {id(it): i for i, it in enumerate(items)}
    return tuple(it if _is_gate(it) else ("__chan__", pos[id(it)])
                 for it in out)


def _thaw_out(items, frozen) -> list:
    return [items[e[1]]
            if isinstance(e, tuple) and e and e[0] == "__chan__" else e
            for e in frozen]


def _content_key(items, n: int, nloc: int, nsh: int, perm0, m: str):
    """Memoization key mirroring fusion._plan_key: item content bytes +
    the planning context the transforms depend on (None when any matrix
    is traced — such streams are skipped anyway)."""
    parts = []
    for it in items:
        if _is_gate(it):
            mat = it.mat
            if not isinstance(mat, np.ndarray):
                return None
            parts.append((tuple(it.targets), mat.dtype.str, mat.shape,
                          mat.tobytes()))
        else:
            parts.append(("chan", it.kind, it.target, it.bra))
    if nsh:
        from .parallel import topology as _topo

        topo_sig = _topo.signature(1 << nsh)
    else:
        topo_sig = None
    # QT_PERM_FAST flips change both the coalesce pass and the tagged
    # stream-cost entries — flips must miss, like the fusion plan key
    return (m, n, nloc, nsh, perm0, topo_sig, C.perm_fast_enabled(),
            tuple(parts))


def _rewrite(items: list, nloc: int, aggressive: bool,
             coalesce: bool) -> tuple:
    """cancel/merge (+ optional diagonal coalescing) to a small fixpoint
    — the two passes feed each other (a coalesced diagonal may cancel
    against its inverse).  Returns (items, removed)."""
    removed = {"cancel": 0, "merge": 0, "diag_coalesce": 0,
               "perm_coalesce": 0}
    out = list(items)
    for _ in range(3):
        before = len(out)
        out = _cancel_merge(out, removed, aggressive)
        if coalesce:
            out = _coalesce_diag(out, removed, nloc)
            out = _coalesce_perm(out, removed, nloc)
        if len(out) == before:
            break
    return out, removed


def _optimize(items: list, n: int, nloc: int, nsh: int, perm0,
              m: str) -> tuple:
    """The actual rewrite (cache miss path): returns (items, stats)."""
    aggressive = m == "aggressive"
    gates_in = sum(1 for it in items if _is_gate(it))

    reordered = False
    cost_before = cost_after = None
    windows_before = windows_after = None
    if not nsh:
        # single-shard: no exchange cost to trade against — fewer gates
        # is strictly better, so take the full rewrite unconditionally
        out, removed = _rewrite(items, nloc, aggressive, True)
    else:
        # sharded: every transform is a CANDIDATE scored against the
        # exchange cost model, original program order included — a
        # rewrite that shrinks the gate count but widens targets (e.g.
        # a union-diagonal spanning cold qubits) can force extra remap
        # windows, and must lose to the cheaper stream
        out, removed = _rewrite(items, nloc, aggressive, True)
        try:
            candidates = [(out, removed)]
            if removed["diag_coalesce"]:
                candidates.append(_rewrite(items, nloc, aggressive, False))
            best = None
            for cand, rem in candidates:
                cand, reord, _pre, cost = _reorder(
                    cand, n, nloc, perm0, aggressive)
                ngates = sum(1 for it in cand if _is_gate(it))
                key = (cost, ngates)
                if best is None or key < best[0]:
                    best = (key, cand, rem, reord, cost)
            cost_before = _stream_cost(items, n, nloc, perm0)
            orig_key = (cost_before, gates_in)
            if best[0] < orig_key:
                _k, out, removed, reordered, cost_after = best
            else:  # nothing beat program order: keep the stream as-is
                out = list(items)
                removed = {"cancel": 0, "merge": 0, "diag_coalesce": 0,
                           "perm_coalesce": 0}
                reordered = False
                cost_after = cost_before
            windows_before = int(cost_before[2])
            windows_after = int(cost_after[2])
        except ValueError:
            # the stream is not plannable in the remap-window model
            # (e.g. a directly-injected gate wider than the shard-local
            # space — capture_unitary never buffers those); keep the
            # rewrite, leave program order, skip the cost accounting
            cost_before = cost_after = None
            windows_before = windows_after = None

    gates_out = sum(1 for it in out if _is_gate(it))
    stats = {
        "mode": m,
        "gates_in": int(gates_in),
        "gates_out": int(gates_out),
        "removed": {k: int(v) for k, v in removed.items()},
        "reordered": bool(reordered),
        "windows_before": windows_before,
        "windows_after": windows_after,
        "weighted_cost_before":
            None if cost_before is None else float(cost_before[0]),
        "weighted_cost_after":
            None if cost_after is None else float(cost_after[0]),
        "exchanges_before":
            None if cost_before is None else int(cost_before[1]),
        "exchanges_after":
            None if cost_after is None else int(cost_after[1]),
    }
    return out, stats


def optimize_items(items: Sequence, *, n: int, nloc: int, nsh: int = 0,
                   perm0=None, quiet: bool = False) -> Tuple[list, dict]:
    """Rewrite a drain's item stream under the active mode; returns
    (items, stats).  ``quiet`` suppresses telemetry (the explain /
    governor dry-run contract — fusion.plan_items_quiet).  Streams with
    any traced matrix are returned untouched: value transforms need
    concrete entries, and a partial rewrite would desynchronize the
    batched-bank skeleton contract."""
    m = mode() if not _SUPPRESS[0] else "off"
    items = list(items)
    if (m == "off" or len(items) < 2
            or any(_is_gate(it) and not isinstance(it.mat, np.ndarray)
                   for it in items)):
        ngates = sum(1 for it in items if _is_gate(it))
        return items, {"mode": m, "gates_in": ngates, "gates_out": ngates,
                       "removed": {"cancel": 0, "merge": 0,
                                   "diag_coalesce": 0, "perm_coalesce": 0},
                       "reordered": False, "windows_before": None,
                       "windows_after": None,
                       "weighted_cost_before": None,
                       "weighted_cost_after": None,
                       "exchanges_before": None, "exchanges_after": None}
    key = _content_key(items, n, nloc, nsh, perm0, m)
    hit = _cache.get(key) if key is not None else None
    if hit is not None:
        out, stats = _thaw_out(items, hit[0]), hit[1]
    else:
        t0 = time.perf_counter()
        out, stats = _optimize(items, n, nloc, nsh, perm0, m)
        seconds = time.perf_counter() - t0
        if not quiet:
            _telemetry.observe("optimizer_seconds", seconds)
        if key is not None:
            if len(_cache) >= _CACHE_MAX:
                _cache.pop(next(iter(_cache)))
            _cache[key] = (_freeze_out(items, out), stats)
    if not quiet and _telemetry.enabled():
        for kind, v in stats["removed"].items():
            if v:
                _telemetry.inc("optimizer_gates_removed_total", v,
                               kind=kind)
        wb, wa = stats["windows_before"], stats["windows_after"]
        if wb is not None and wa is not None and wb > wa:
            _telemetry.inc("optimizer_windows_merged_total", wb - wa)
    return list(out), stats


def clear_cache() -> None:
    """Drop memoized rewrites (tests; a mode flip does not need this —
    the mode is part of the key)."""
    _cache.clear()


def summary_line() -> str:
    """``Optimizer=...`` fragment for getEnvironmentString: the active
    mode plus cumulative gates-removed/windows-merged when any work has
    been recorded."""
    m = mode()
    removed = _telemetry.counter_total("optimizer_gates_removed_total")
    merged = _telemetry.counter_total("optimizer_windows_merged_total")
    s = f"Optimizer={m}"
    if removed or merged:
        s += f"(removed={int(removed)} windows_merged={int(merged)})"
    return s


# camelCase mirrors (the reference-style API surface)
setCircuitOptimizer = set_circuit_optimizer
getCircuitOptimizer = get_circuit_optimizer
