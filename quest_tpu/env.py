"""Execution environment: device mesh discovery and seeding.

TPU-native analogue of the reference's ``QuESTEnv`` (QuEST.h:361, {rank,
numRanks}) and ``createQuESTEnv`` (MPI_Init + rank discovery,
QuEST_cpu_distributed.c:129-160; GPU probe, QuEST_gpu.cu:446-478).  Instead
of MPI ranks, the environment owns a 1-D ``jax.sharding.Mesh`` over the
amplitude axis; a Qureg's amplitudes are sharded over it by their leading
(most-significant-qubit) index bits — exactly the reference's chunk scheme
(QuEST.h:330-338) expressed as a NamedSharding.  Multi-host TPU slices join
the same mesh via ``jax.distributed`` (the analogue of MPI_Init), and the
collectives ride ICI/DCN instead of MPI.
"""

from __future__ import annotations

import dataclasses
import inspect
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from . import rng

AMP_AXIS = "amps"

# --- shard_map compat shim -------------------------------------------------
# jax >= 0.6 exposes jax.shard_map (kwarg check_vma=); 0.4.x only has
# jax.experimental.shard_map.shard_map (kwarg check_rep=).  Every module
# imports shard_map from HERE so the whole package tracks one spelling.
try:
    from jax import shard_map as _shard_map_impl  # type: ignore[attr-defined]
except ImportError:  # jax < 0.6
    from jax.experimental.shard_map import shard_map as _shard_map_impl

_SHARD_MAP_PARAMS = frozenset(
    inspect.signature(_shard_map_impl).parameters)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: Optional[bool] = None):
    """Version-portable shard_map: forwards ``check_vma`` under whichever
    name the installed jax accepts (``check_vma`` new, ``check_rep`` old);
    omitted -> the jax default."""
    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    if check_vma is not None:
        if "check_vma" in _SHARD_MAP_PARAMS:
            kwargs["check_vma"] = check_vma
        else:
            kwargs["check_rep"] = check_vma
    return _shard_map_impl(f, **kwargs)


@dataclasses.dataclass
class QuESTEnv:
    """Holds the device mesh. ``rank``/``num_ranks`` kept for reference-API
    parity: rank = jax.process_index(), num_ranks = number of mesh devices."""

    mesh: Mesh
    rank: int
    num_ranks: int
    seeds: tuple
    # hierarchical hosts x chips arrangement of the amplitude mesh
    # (parallel/topology.py; resolved from QT_TOPOLOGY at creation and
    # carried through shrink_env so a failed-over env keeps classifying
    # its surviving interconnect correctly even while the env var still
    # describes the old shape).  None only on hand-built envs; accessors
    # fall back to the flat single-host arrangement.
    topology: Optional[object] = None

    @property
    def num_devices(self) -> int:
        return int(np.prod(self.mesh.devices.shape))

    def amp_sharding(self) -> NamedSharding:
        """For SoA state arrays (2, num_amps): shard the amplitude axis."""
        return NamedSharding(self.mesh, PartitionSpec(None, AMP_AXIS))

    def vec_sharding(self) -> NamedSharding:
        """For flat per-amplitude vectors (e.g. DiagonalOp channels)."""
        return NamedSharding(self.mesh, PartitionSpec(AMP_AXIS))

    def replicated_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    def sharding_for_dim(self, dim: int) -> NamedSharding:
        """Per-amplitude vector sharding when the vector spans the mesh,
        replicated otherwise (small registers replicate rather than being
        rejected — see validation.validate_num_qubits)."""
        return (self.vec_sharding() if dim >= self.num_devices
                else self.replicated_sharding())


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join a multi-host run — the analogue of the reference's ``MPI_Init``
    (QuEST_cpu_distributed.c:129-160).  Call once per host BEFORE
    ``create_quest_env``; afterwards ``jax.devices()`` spans every host and
    the amplitude mesh covers the whole slice (collectives ride ICI within
    a slice and DCN across slices).  On TPU pods all arguments are
    auto-detected from the environment."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


_CACHE_WIRED = [False]

# persistent-cache observability: hit/miss counts from jax.monitoring
# events, reported by getEnvironmentString — a long-lived serving process
# can tell whether its restarts are actually warm (bench_r05 measured up
# to 7.7 s compile_s per bench config, re-paid on every cold start)
_CACHE_STATS = {"hits": 0, "misses": 0, "dir": None}
_CACHE_LISTENERS = [False]


def _register_cache_listeners() -> None:
    if _CACHE_LISTENERS[0]:
        return
    _CACHE_LISTENERS[0] = True
    try:  # pragma: no cover - monitoring API is version-dependent
        import jax.monitoring as _mon

        def _on_event(event: str, **kw) -> None:
            if event == "/jax/compilation_cache/cache_hits":
                _CACHE_STATS["hits"] += 1

        def _on_duration(event: str, duration: float, **kw) -> None:
            if event == "/jax/compilation_cache/cache_misses":
                _CACHE_STATS["misses"] += 1

        _mon.register_event_listener(_on_event)
        _mon.register_event_duration_secs_listener(_on_duration)
    except (ImportError, AttributeError):
        pass


def compile_cache_stats() -> dict:
    """{'hits': int, 'misses': int, 'dir': str | None} for the persistent
    compilation cache this process is using (dir None = not wired)."""
    return dict(_CACHE_STATS)


def _enable_compilation_cache() -> None:
    """Persistent XLA compilation cache (opt out: QT_NO_COMPILE_CACHE=1;
    relocate: QT_COMPILE_CACHE=<dir> — QT_COMPILE_CACHE_DIR kept as an
    alias).  A traced-program framework re-pays compilation EVERY session
    where the reference's CMake build compiles once — round-3 measured
    22-47 s per 30q workload and 173-300 s for the config-4 noise block
    per session (BASELINE.md), and bench_r05 shows up to 7.7 s compile_s
    per bench config paid on every process start; the cache makes every
    session after the first start warm.  Cache hits/misses are counted
    (jax.monitoring listeners) and surfaced by getEnvironmentString.  No
    reference analogue needed (VERDICT r3 item 5)."""
    if _CACHE_WIRED[0] or os.environ.get("QT_NO_COMPILE_CACHE") == "1":
        return
    _CACHE_WIRED[0] = True
    explicit_dir = (os.environ.get("QT_COMPILE_CACHE")
                    or os.environ.get("QT_COMPILE_CACHE_DIR"))
    try:
        # respect a user-configured cache location (standard JAX env var
        # or an explicit jax.config set before createQuESTEnv); inside
        # the try so a JAX version lacking the config attribute skips the
        # best-effort cache instead of breaking createQuESTEnv
        user_dir = (os.environ.get("JAX_COMPILATION_CACHE_DIR")
                    or jax.config.jax_compilation_cache_dir)
        if user_dir:
            _CACHE_STATS["dir"] = user_dir
            _register_cache_listeners()
            return
        # CPU AOT cache entries embed the compile host's microarch
        # features and can SIGILL on a different host (XLA warns on
        # load); the compile cost being killed is the accelerator
        # programs' anyway — default the cache on only off-CPU
        # (QT_COMPILE_CACHE / QT_COMPILE_CACHE_DIR force it on anywhere)
        if jax.default_backend() == "cpu" and explicit_dir is None:
            return
    # qlint: allow(broad-except): cache is best-effort — any config/backend probe failure (version-dependent attribute set) must skip the cache, never break createQuESTEnv
    except Exception:  # pragma: no cover - cache is best-effort
        return
    cache_dir = explicit_dir or os.path.join(
        os.path.expanduser("~"), ".cache", "quest_tpu_xla")
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache EVERY compiled program: the per-pass chained executor's
        # programs each compile in ~2 s or less, and re-tracing them per
        # session is exactly the cost being killed — the default
        # thresholds would skip them
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        _CACHE_STATS["dir"] = cache_dir
        _register_cache_listeners()
    # qlint: allow(broad-except): cache is best-effort — mkdir/config failures (read-only FS, old JAX) degrade to uncached compiles rather than failing env creation
    except Exception:  # pragma: no cover - cache is best-effort
        pass


def create_quest_env(
    devices: Optional[Sequence[jax.Device]] = None,
    num_devices: Optional[int] = None,
) -> QuESTEnv:
    """createQuESTEnv (QuEST.h:1851).

    Uses all visible devices by default, truncated to the largest power of
    two — the reference enforces power-of-2 ranks (validateNumRanks,
    QuEST_validation.c:331-343) because amplitude chunks split on index bits;
    the same constraint holds for the mesh.  Also wires the persistent
    XLA compilation cache (see _enable_compilation_cache).
    """
    _enable_compilation_cache()
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    n = len(devices)
    pow2 = 1 << (n.bit_length() - 1)
    devices = devices[:pow2]
    mesh = Mesh(np.array(devices), (AMP_AXIS,))
    from .parallel import topology as _topo

    env = QuESTEnv(
        mesh=mesh,
        rank=jax.process_index(),
        num_ranks=pow2,
        seeds=(),
        topology=_topo.resolve(pow2),
    )
    seed_quest_default(env)
    return env


def shrink_env(env: QuESTEnv, num_devices: int, *,
               exclude_index: Optional[int] = None,
               exclude_indices: Optional[Sequence[int]] = None) -> QuESTEnv:
    """A degraded environment over a power-of-two subset of ``env``'s
    devices — the mesh half of the elastic failover path
    (resilience._failover) and of loadQureg's auto-reshard.

    ``exclude_index`` drops one device (the presumed-dead shard) before
    truncating; ``exclude_indices`` drops a set — the host-loss path
    excludes the dead host's whole device range
    (topology.host_range) so the surviving mesh is built from intact
    hosts only.  The result keeps ``env``'s seeds WITHOUT reseeding —
    the RNG streams belong to the run, not the mesh, and a failover
    restores them from the checkpoint anyway.  The degraded topology is
    derived with topology.shrink: a whole-host loss keeps the
    chips-per-host arrangement (2x4 -> 1x4), a sub-host shrink
    collapses to single-host."""
    dead = set() if exclude_indices is None else {
        int(i) for i in exclude_indices}
    if exclude_index is not None:
        dead.add(int(exclude_index))
    devs = [d for i, d in enumerate(env.mesh.devices.reshape(-1).tolist())
            if i not in dead]
    num_devices = int(num_devices)
    if num_devices < 1 or num_devices & (num_devices - 1):
        raise ValueError(
            f"shrink_env: num_devices must be a positive power of two, "
            f"got {num_devices}")
    if num_devices > len(devs):
        raise ValueError(
            f"shrink_env: asked for {num_devices} devices but only "
            f"{len(devs)} survive in this environment")
    mesh = Mesh(np.array(devs[:num_devices]), (AMP_AXIS,))
    from .parallel import topology as _topo

    return QuESTEnv(mesh=mesh, rank=env.rank, num_ranks=num_devices,
                    seeds=env.seeds,
                    topology=_topo.shrink(env.topology, num_devices))


def destroy_quest_env(env: QuESTEnv) -> None:
    """destroyQuESTEnv (QuEST.h:1864) — nothing to free; arrays are GC'd."""


def sync_quest_env(env: QuESTEnv) -> None:
    """syncQuESTEnv (QuEST.h:1875): the reference issues an MPI_Barrier /
    cudaDeviceSynchronize.  XLA program order makes a barrier unnecessary;
    we block on outstanding async dispatches for timing parity."""
    (jax.device_put(0) + 0).block_until_ready()


def sync_quest_success(success_code: int = 1) -> int:
    """syncQuESTSuccess (QuEST_cpu_distributed.c:166-170) AND-reduces a flag
    across ranks; single-process JAX returns it unchanged."""
    return int(success_code)


def report_quest_env(env: QuESTEnv) -> None:
    """Print execution-environment parameters (QuEST.h:1893)."""
    print(get_environment_string(env))


def get_environment_string(env: QuESTEnv) -> str:
    """getEnvironmentString (QuEST.h:1912) — reference format:
    'CUDA=.. OpenMP=.. MPI=.. threads=.. ranks=..'; ours reports the mesh,
    plus any recorded graceful degradations (e.g. a Pallas kernel that
    failed to lower and fell back to the XLA path — resilience.py)."""
    backend = jax.default_backend()
    s = (
        f"EnvType=quest_tpu Backend={backend} Devices={env.num_devices} "
        f"MeshAxes={AMP_AXIS} Processes={jax.process_count()}"
    )
    from . import resilience
    from .parallel import dist
    from .parallel import topology as _topo

    t = env.topology if env.topology is not None \
        else _topo.resolve(env.num_devices)
    s += f" Topology={t.describe()}"
    s += f" ExchangeChunks={dist.exchange_config_key() or 'auto'}"
    # reproducibility surface: when the measurement RNG is still on its
    # time+pid default seed, report the chosen keys so the run can be
    # replayed exactly with seedQuEST(env, <keys>) (rng.py contract)
    if getattr(rng.GLOBAL_RNG, "default_seeded", False):
        s += " DefaultSeed=" + ",".join(str(k) for k in rng.GLOBAL_RNG._keys)
    cache = compile_cache_stats()
    if cache["dir"]:
        s += (f" CompileCache={cache['dir']}"
              f"(hits={cache['hits']} misses={cache['misses']})")
    # §31 persistent AOT executable tier — a distinct line from the XLA
    # compile cache above: AotCache hits skip compilation ACROSS
    # processes (deserialize), CompileCache hits dedup within one.
    # Lazy import: env(rank 5) may not import dist-stratum modules at
    # module level (analysis/rules_layering.py)
    from . import aotcache as _aotcache

    if _aotcache.enabled():
        aot = _aotcache.stats()
        s += (f" AotCache={aot['dir']}"
              f"(hits={aot['hits']} misses={aot['misses']} "
              f"puts={aot['puts']} bytes={aot['bytes']})")
    degraded = resilience.degradation_report()
    if degraded:
        s += " Degraded=[" + "; ".join(
            f"{k}: {v}" for k, v in sorted(degraded.items())) + "]"
    # consolidated observability block (telemetry.py absorbs the cache
    # counters and degradation registry above as series of the same
    # namespace; the legacy fields stay for compatibility)
    from . import telemetry

    # elastic-recovery surface: completed failovers and guarded-collective
    # timeouts, pulled from the registry so operators see degraded-mesh
    # history without parsing the telemetry block
    failovers = telemetry.counter_total("failovers_total")
    if failovers:
        s += f" Failovers={int(failovers)}"
    timeouts = telemetry.counter_total("exchange_timeouts_total")
    if timeouts:
        s += f" ExchangeTimeouts={int(timeouts)}"
    # serving-resilience surface (serve.py, docs/design.md §27): retry /
    # quarantine / failover+heal history and the live degraded flag
    s_retr = telemetry.counter_total("serve_bank_retries_total")
    s_quar = telemetry.counter_total("serve_jobs_quarantined_total")
    s_fail = telemetry.counter_total("serve_failovers_total")
    s_heal = telemetry.counter_total("serve_heals_total")
    s_deg = telemetry.gauge_max("serve_degraded")
    if s_retr or s_quar or s_fail or s_heal or s_deg:
        s += (f" Serve=retries:{int(s_retr)},"
              f"quarantined:{int(s_quar)},failovers:{int(s_fail)},"
              f"heals:{int(s_heal)},degraded:{int(s_deg or 0)}")
    # peak HBM watermark over devices (hbm_watermark_bytes gauge, sampled
    # by the fusion drain at window boundaries — utils/profiling.py)
    peak = telemetry.gauge_max("hbm_watermark_bytes")
    if peak is not None:
        s += f" HbmPeak={int(peak)}"
    # memory-governor surface: policy + budget when active, plus any
    # spill / OOM-retry history (governor.py; degradations above carry
    # the per-rung reasons)
    from . import governor

    if governor.enabled():
        s += (f" MemGovernor={governor.policy()}"
              f"(budget={governor.budget_bytes()}"
              f" resident={governor.resident_bytes()})")
    # circuit-optimizer surface (optimizer.py): active mode plus
    # cumulative rewrite work when any has been recorded
    from . import optimizer

    s += f" {optimizer.summary_line()}"
    # §28 permutation fast paths (QT_PERM_FAST): flagged when disabled,
    # plus cumulative per-route history once any gate lowered this way
    from . import circuit as _circuit

    pf = _circuit.perm_fast_enabled()
    pg = telemetry.counter_total("permutation_gates_total")
    if not pf or pg:
        s += f" PermFast={'on' if pf else 'off'}"
        routes = ",".join(
            f"{r}:{int(telemetry.counter_sum('permutation_gates_total', route=r))}"
            for r in ("relabel", "gather", "exchange")
            if telemetry.counter_sum("permutation_gates_total", route=r))
        if routes:
            s += f"({routes})"
    # §29 window megakernel (QT_MEGAKERNEL): mode plus the planning
    # verdict in parentheses, and cumulative per-route dispatch history
    # once any fused window executed through either arm
    from .ops import fused as _fused

    mk = _fused.megakernel_mode()
    mk_total = telemetry.counter_total("megakernel_dispatch_total")
    if mk != "auto" or _fused.megakernel_planning() or mk_total:
        s += (f" Megakernel={mk}"
              f"({'on' if _fused.megakernel_planning() else 'off'})")
        mk_routes = ",".join(
            f"{r}:{int(telemetry.counter_sum('megakernel_dispatch_total', route=r))}"
            for r in ("mega", "fallback")
            if telemetry.counter_sum("megakernel_dispatch_total", route=r))
        if mk_routes:
            s += f"[{mk_routes}]"
    spills = telemetry.counter_total("spills_total")
    if spills:
        s += f" Spills={int(spills)}"
    ooms = telemetry.counter_total("oom_retries_total")
    if ooms:
        s += f" OomRetries={int(ooms)}"
    s += f" [telemetry: {telemetry.summary()}]"
    return s


def seed_quest(env: QuESTEnv, seeds: Sequence[int]) -> None:
    """seedQuEST (QuEST.h:3341): seeds the measurement RNG identically on
    every process (reference broadcasts the key,
    QuEST_cpu_distributed.c:1384-1395; with jax.distributed every process
    already passes the same seeds)."""
    env.seeds = tuple(int(s) for s in seeds)
    rng.GLOBAL_RNG.seed(env.seeds)
    from .ops import measurement

    measurement.KEYS.seed(env.seeds)


def seed_quest_default(env: QuESTEnv) -> None:
    """seedQuESTDefault (QuEST.h:3324): time+pid key."""
    rng.GLOBAL_RNG.seed_default()
    env.seeds = tuple(rng.GLOBAL_RNG._keys)
    from .ops import measurement

    measurement.KEYS.seed(env.seeds)
