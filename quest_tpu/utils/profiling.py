"""Profiling hooks — a real tracing subsystem, beyond reference parity.

The reference has no profiler at all (SURVEY.md §5.1: the only
introspection is reportQuregParams / reportState).  quest_tpu wires the
JAX/XLA profiler in as a first-class utility: traces capture kernel-level
TPU timelines viewable in TensorBoard/Perfetto, and ``annotate`` marks
circuit phases inside a trace.
"""

from __future__ import annotations

import contextlib
import sys
import time
from typing import Iterator, Optional

import jax

from .. import telemetry as _telemetry


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture an XLA device trace for the enclosed block::

        with quest_tpu.utils.profiling.trace("/tmp/qt_trace"):
            run_circuit()

    Open the directory in TensorBoard (or xprof) to see per-kernel HBM/MXU
    timelines."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a trace (jax.profiler.TraceAnnotation)."""
    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def timed(label: str, sync: Optional[object] = None) -> Iterator[dict]:
    """Wall-clock a block, blocking on ``sync`` (an array) if given; the
    yielded dict gains {'seconds': ...} on exit.  The result is also
    observed into the ``timed_seconds{label}`` telemetry histogram, so
    ad-hoc timings accumulate in the same registry snapshot/Prometheus
    export as the built-in instrumentation."""
    out: dict = {"label": label}
    t0 = time.perf_counter()
    try:
        yield out
    finally:
        if sync is not None:
            jax.block_until_ready(sync)
        out["seconds"] = time.perf_counter() - t0
        _telemetry.observe("timed_seconds", out["seconds"], label=label)


def _maxrss_bytes(res=None, platform: Optional[str] = None) -> int:
    """Host process peak RSS in BYTES.  ``getrusage`` reports
    ``ru_maxrss`` in kilobytes on Linux but in bytes on macOS (the BSD
    heritage, see getrusage(2) on each) — scaling unconditionally by
    1024 inflated the Darwin watermark 1024x.  ``res``/``platform``
    default to the live ``resource`` module and ``sys.platform`` and
    exist so tests can pin both branches."""
    if res is None:
        import resource as res
    if platform is None:
        platform = sys.platform
    scale = 1 if platform.startswith("darwin") else 1024
    return int(res.getrusage(res.RUSAGE_SELF).ru_maxrss) * scale


def memory_watermark() -> dict:
    """Per-device HBM statistics: ``{device: memory_stats() dict}`` via
    ``jax.local_devices()[i].memory_stats()``, with a graceful fallback
    to an empty dict on backends that expose none (CPU returns None).
    Byte watermarks are also published as telemetry gauges
    (``device_bytes_in_use`` / ``device_peak_bytes_in_use{device}``, and
    the consolidated ``hbm_watermark_bytes{device}`` the fusion drain
    samples at window boundaries — peak surfaced in
    getEnvironmentString and reportPerf).  Every watermark sample is
    mirrored into ``device_memory_watermark_bytes{device}`` — the
    Prometheus-facing series the serve layer refreshes at bank
    boundaries so HBM pressure is live in ``/metrics`` (docs/design.md
    §30).  When NO device exposes
    memory_stats (the CPU backend), the memory governor's modeled
    per-device peak stands in under ``device="model"`` when a budget is
    active (so the CPU dryrun's watermark agrees with the predictor —
    the explain/reconcile contract), and the host process max-RSS under
    ``device="host"`` otherwise so the watermark loop stays testable."""
    out: dict = {}
    saw_device_stats = False
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        # qlint: allow(broad-except): memory_stats() support and failure types are backend-dependent; the sampler records "no stats" and moves on
        except Exception:  # pragma: no cover - backend-dependent API
            stats = None
        stats = dict(stats) if stats else {}
        out[str(d)] = stats
        if "bytes_in_use" in stats:
            _telemetry.set_gauge("device_bytes_in_use",
                                 stats["bytes_in_use"], device=str(d))
        if "peak_bytes_in_use" in stats:
            _telemetry.set_gauge("device_peak_bytes_in_use",
                                 stats["peak_bytes_in_use"], device=str(d))
        peak = stats.get("peak_bytes_in_use", stats.get("bytes_in_use"))
        if peak is not None:
            saw_device_stats = True
            _telemetry.set_gauge("hbm_watermark_bytes", peak,
                                 device=str(d))
            _telemetry.set_gauge("device_memory_watermark_bytes", peak,
                                 device=str(d))
    if not saw_device_stats:
        from .. import governor as _governor

        modeled = _governor.modeled_watermark_bytes()
        if modeled is not None:
            out["model"] = {"modeled_peak_bytes_in_use": int(modeled)}
            _telemetry.set_gauge("hbm_watermark_bytes", modeled,
                                 device="model")
            _telemetry.set_gauge("device_memory_watermark_bytes", modeled,
                                 device="model")
        else:
            try:
                rss = _maxrss_bytes()
                _telemetry.set_gauge("hbm_watermark_bytes", rss,
                                     device="host")
                _telemetry.set_gauge("device_memory_watermark_bytes", rss,
                                     device="host")
            # qlint: allow(broad-except): max-RSS is a best-effort POSIX probe; a non-POSIX host just skips the watermark sample
            except Exception:  # pragma: no cover - non-POSIX host
                pass
    return out
