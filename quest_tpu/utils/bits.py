"""Bit-index algebra over amplitude index space.

The reference builds every kernel on a handful of inline bit helpers
(``QuEST/src/CPU/QuEST_cpu_internal.h:26-53``: extractBit, flipBit,
maskContainsBit, isOddParity, insertZeroBit, insertTwoZeroBits).  On TPU we
never iterate over amplitudes in Python; instead the same algebra appears in
two forms:

- *host-side* helpers on Python ints (masks for validation, pair-rank
  computation in the distributed layer), and
- *traced* helpers producing whole bit-pattern arrays via ``lax.iota``
  broadcasting, which XLA fuses into the surrounding elementwise kernels.

Qubit convention matches the reference: amplitude index ``i`` assigns qubit
``q`` the value of bit ``q`` of ``i`` (little-endian; qubit 0 is the least
significant index bit).
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
from jax import lax

# ---------------------------------------------------------------------------
# Host-side (Python int) helpers
# ---------------------------------------------------------------------------


def get_bit_mask(qubits: Sequence[int]) -> int:
    """OR of 1<<q — reference getQubitBitMask (QuEST_common.c:50)."""
    mask = 0
    for q in qubits:
        mask |= 1 << int(q)
    return mask


def extract_bit(bit_index: int, number: int) -> int:
    return (number >> bit_index) & 1


def flip_bit(number: int, bit_index: int) -> int:
    return number ^ (1 << bit_index)


def insert_zero_bit(number: int, index: int) -> int:
    """Insert a 0 bit at position ``index`` (QuEST_cpu_internal.h:42)."""
    left = (number >> index) << (index + 1)
    right = number & ((1 << index) - 1)
    return left | right


def insert_zero_bits(number: int, indices: Sequence[int]) -> int:
    """Insert 0 bits at each (sorted ascending) position."""
    for idx in sorted(indices):
        number = insert_zero_bit(number, idx)
    return number


def is_odd_parity(number: int, *bit_indices: int) -> int:
    acc = 0
    for b in bit_indices:
        acc ^= (number >> b) & 1
    return acc


# ---------------------------------------------------------------------------
# Traced helpers (arrays of bit patterns)
# ---------------------------------------------------------------------------


def index_iota(num_amps: int, dtype=jnp.int32):
    """Flat amplitude-index array [0, 2^n).  int32 suffices for n<=31;
    callers with n>31 amplitudes per shard pass dtype=jnp.int64."""
    return lax.iota(dtype, num_amps)


def bits_of(indices, qubit: int):
    """Per-amplitude value of one qubit's bit: (indices >> q) & 1."""
    return lax.shift_right_logical(indices, jnp.asarray(qubit, indices.dtype)) & 1


def parity_of(indices, qubits: Sequence[int]):
    """Per-amplitude XOR-parity of a qubit subset — vectorized form of the
    reference's bit-parity sign trick (QuEST_cpu.c:3268-3275)."""
    acc = jnp.zeros_like(indices)
    for q in qubits:
        acc = acc ^ bits_of(indices, q)
    return acc


def decode_subregister(indices, qubits: Sequence[int], twos_complement: bool):
    """Decode integer values of a sub-register from index bits.

    ``qubits[0]`` is the least-significant bit of the encoded value, matching
    the reference's applyPhaseFunc sub-register convention
    (QuEST_cpu.c:4228-4303).  With ``twos_complement``, the top qubit is the
    sign bit.
    """
    val = jnp.zeros_like(indices)
    for j, q in enumerate(qubits):
        val = val + (bits_of(indices, q) << j)
    if twos_complement:
        nbits = len(qubits)
        val = jnp.where(val >= (1 << (nbits - 1)), val - (1 << nbits), val)
    return val
