"""Circuit scheduler: fold gate streams into fused window passes.

The reference executes circuits gate-at-a-time through its dispatch layer
(QuEST/src/QuEST.c) — every gate is one full sweep of the amplitude array.
This module is the TPU-native replacement for that dispatch loop: a
*scheduler* that plans a whole gate list into a short program of HBM
passes.  The DEFAULT planner (plan_circuit_windowed) emits

    ('winfused', k, As, Bs, apply_a, apply_b[, mask])
                              one zero-relocation HBM pass applying the
                              rank-R operator [mask (.)] sum_r B_r (x) A_r
                              with A on lane qubits [0,7) and B on the
                              contiguous window [k, k+7) — k is chosen per
                              pass, so high qubits are reached by AIMING
                              the window at them (ops/fused.py
                              apply_window_stack).  The optional trailing
                              mask (SoA (2,128,128), absent in 6-tuple
                              producers like fused_qft and the native
                              materializer) holds diagonal crossing gates
                              as one elementwise multiply (fold_mask)
    ('apply',   targets, mat) fallback standard kernel (gates no window
                              covers, e.g. a dense 2q gate on two
                              far-apart high qubits)

2q gates straddling lane x window fold through their operator-Schmidt
terms (schmidt_terms_2q): rank x2 for controlled gates, x4 generically,
capped at RANK_CAP per pass.

The legacy 'paged' planner (plan_circuit_py, QT_PLANNER=paged) instead
pins the window to [7,14) and relocates high qubits into it:

    ('fused',    matA, matB)  cluster pass on qubits [0,14)
    ('swapfused', h, b, m, As, Bs)  segment swap fused into a cluster pass
    ('segswap',  a, b, m)     exchange bit segments [a,a+m) <-> [b,b+m) as
                              ONE tile-aligned transpose — the single-chip
                              analogue of the reference's distributed
                              SWAP-relocalization
                              (QuEST_cpu_distributed.c:1503-1545)

Planning is pure Python over *static* gate structure (targets), so it runs
once at trace time; gate matrices stay traced values, so parameterised
circuits recompile only when their shape changes, never when angles change.

Both planning algorithms are implemented natively in C++
(native/scheduler.cc) for large gate streams; plan_circuit() transparently
uses the native planner when the library is built (see native/__init__.py).
"""

from __future__ import annotations

import itertools
import math
from dataclasses import dataclass
from functools import lru_cache
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .ops import cplx, fused, kernels

LANE = fused.LANE_QUBITS            # 7
WINDOW = fused.CLUSTER_QUBITS       # 14
DIM = fused.CLUSTER_DIM             # 128
_LOOKAHEAD = 256                    # next-use horizon for eviction choice


@dataclass(frozen=True)
class Gate:
    """One dense gate: ``mat`` is stacked SoA (2, 2^k, 2^k) over ``targets``
    (targets[0] = least-significant matrix bit, reference convention)."""

    targets: Tuple[int, ...]
    mat: object  # array-like; may be a traced jnp value


def controlled_dense(mat_soa, num_controls: int, control_states=()):
    """Embed a k-qubit SoA matrix as a (num_controls+k)-qubit controlled
    matrix (controls = the high matrix bits; control i is matrix bit k+i,
    conditioned on ``control_states[i]``, default 1) so controlled gates can
    enter the dense scheduling path.  Concrete numpy inputs stay numpy so
    the scheduler can still Schmidt-decompose the result."""
    m = np.asarray(mat_soa) if not isinstance(mat_soa, jnp.ndarray) else mat_soa
    d = m.shape[-1]
    nc = int(num_controls)
    full = d << nc
    states = tuple(int(s) for s in control_states) or (1,) * nc
    active = 0
    for i, s in enumerate(states):
        active |= (s & 1) << i
    idx = np.arange(full)
    ci, ti = idx // d, idx % d
    same_c = ci[:, None] == ci[None, :]
    gate_mask = same_c & (ci == active)[:, None]
    eye_mask = same_c & (ci != active)[:, None] & (idx[:, None] == idx[None, :])
    row = np.broadcast_to(ti[:, None], (full, full))
    col = np.broadcast_to(ti[None, :], (full, full))
    if isinstance(m, np.ndarray):
        out = m[:, row, col] * gate_mask.astype(m.dtype)
        out[0] += eye_mask.astype(m.dtype)
        return out
    out = m[:, row, col] * jnp.asarray(gate_mask, m.dtype)
    return out.at[0].add(jnp.asarray(eye_mask, m.dtype))


# ---------------------------------------------------------------------------
# Permutation gate family: classification + gather-shaped lowering
# (docs/design.md §28)
# ---------------------------------------------------------------------------

# Composed gather tables are 2^|union| entries: past this width a run is
# split into several gather passes instead of one giant index table.
PERM_GATHER_MAX_BITS = 10


def perm_fast_enabled() -> bool:
    """QT_PERM_FAST gate for the permutation fast paths (default ON; any
    of off/0/false/no disables, rerouting the family through the dense
    matmul pipeline — the A/B baseline scripts/bench_sparse.py times)."""
    import os

    raw = os.environ.get("QT_PERM_FAST", "").strip().lower()
    return raw not in ("off", "0", "false", "no")


def _classify_pi(pi):
    """Classify an index permutation ``new[i] = old[pi[i]]`` into its
    cheapest lowering family: ``("xor", c)`` when pi is ``i ^ c``
    (multi-qubit NOT — one static bit flip, no gather), ``("relabel", s)``
    when pi only reroutes index BITS (output matrix bit j reads input
    matrix bit s[j] — pure qubit relabeling, foldable into Qureg._perm),
    else ``("gather", pi)`` (general one-hot row permutation, e.g. the
    Toffoli's conditional flip)."""
    pi = np.asarray(pi, dtype=np.int64)
    d = len(pi)
    k = d.bit_length() - 1
    idx = np.arange(d)
    c = int(pi[0])
    if np.array_equal(pi, idx ^ c):
        return ("xor", c)
    if c == 0:
        s = []
        for j in range(k):
            img = int(pi[1 << j])
            if img and not (img & (img - 1)):
                s.append(img.bit_length() - 1)
        if len(s) == k and len(set(s)) == k:
            lin = np.zeros(d, dtype=np.int64)
            for j in range(k):
                lin |= ((idx >> j) & 1) << s[j]
            if np.array_equal(pi, lin):
                return ("relabel", tuple(s))
    return ("gather", tuple(int(p) for p in pi))


@lru_cache(maxsize=512)
def _classify_perm_cached(shape, dstr, buf):
    m = np.frombuffer(buf, dtype=np.dtype(dstr)).reshape(shape)
    if m[1].any():
        return None
    re = m[0]
    if not np.all((re == 0) | (re == 1)):
        return None
    if not (np.all(re.sum(axis=0) == 1) and np.all(re.sum(axis=1) == 1)):
        return None
    return _classify_pi(re.argmax(axis=1))


def classify_permutation_gate(mat):
    """``None | ("xor", c) | ("relabel", s) | ("gather", pi)`` for a
    concrete stacked SoA gate matrix (X, CNOT, Toffoli/MCX, SWAP,
    multi-qubit NOT and products thereof).  Traced values and
    non-permutation matrices return None.  Cached on the matrix bytes —
    permutation-dominated streams repeat a handful of tiny matrices."""
    if not isinstance(mat, np.ndarray) or mat.ndim != 3:
        return None
    if mat.shape[0] != 2 or mat.shape[1] != mat.shape[2]:
        return None
    return _classify_perm_cached(mat.shape, mat.dtype.str, mat.tobytes())


def compose_permutation_run(gates):
    """Fold a run of permutation-classified gates (stream order) into ONE
    index permutation over the sorted union of their targets: returns
    ``(union, pi)`` with ``new[i] = old[pi[i]]`` in union-bit order, or
    None when any gate fails classification.  Exact integer arithmetic
    throughout, so executing the composed table is bit-identical to the
    dense matrix product."""
    union = sorted({t for g in gates for t in g.targets})
    upos = {q: j for j, q in enumerate(union)}
    d = 1 << len(union)
    idx = np.arange(d)
    total = idx.copy()
    for g in gates:
        cls = classify_permutation_gate(g.mat)
        if cls is None:
            return None
        kind, payload = cls
        pos = [upos[t] for t in g.targets]
        if kind == "xor":
            mask = 0
            for b, p in enumerate(pos):
                if (payload >> b) & 1:
                    mask |= 1 << p
            lifted = idx ^ mask
        else:
            if kind == "relabel":
                kg = len(pos)
                gidx = np.arange(1 << kg)
                pi_g = np.zeros(1 << kg, dtype=np.int64)
                for j in range(kg):
                    pi_g |= ((gidx >> j) & 1) << payload[j]
            else:
                pi_g = np.asarray(payload, dtype=np.int64)
            sub = np.zeros(d, dtype=np.int64)
            for b, p in enumerate(pos):
                sub |= ((idx >> p) & 1) << b
            mapped = pi_g[sub]
            lifted = idx
            for p in pos:
                lifted = lifted & ~(1 << p)
            for b, p in enumerate(pos):
                lifted |= ((mapped >> b) & 1) << p
        total = total[lifted]
    return tuple(union), tuple(int(p) for p in total)


def lower_permutation_run(gates, num_qubits: int):
    """Lower a permutation-classified gate run to matrix-free plan ops:
    greedy-group stream neighbors while the composed gather table stays
    within PERM_GATHER_MAX_BITS, then emit per group the cheapest op its
    composed permutation admits — ``("xor", flips)`` static flip,
    ``("permute", perm)`` full-register bit relabel (one coalesced
    transpose pass, kernels.permute_qubits), or
    ``("gatherperm", union, pi)`` (kernels.apply_index_permutation)."""
    ops: List[tuple] = []
    group: List[Gate] = []
    gbits: set = set()

    def flush():
        if not group:
            return
        union, pi = compose_permutation_run(group)
        kind, payload = _classify_pi(pi)
        if kind == "xor":
            flips = tuple(union[j] for j in range(len(union))
                          if (payload >> j) & 1)
            if flips:
                ops.append(("xor", flips))
        elif kind == "relabel":
            perm = list(range(num_qubits))
            for j, q in enumerate(union):
                perm[q] = union[payload[j]]
            if perm != list(range(num_qubits)):
                ops.append(("permute", tuple(perm)))
        else:
            ops.append(("gatherperm", tuple(union), tuple(payload)))
        group.clear()
        gbits.clear()

    for g in gates:
        b = set(g.targets)
        if group:
            nb = gbits | b
            # cap the composed table AND the kernel's contiguous gather
            # field — grouping distant gates would force the gather
            # lowering onto its dense-matrix fallback
            if (len(nb) > PERM_GATHER_MAX_BITS
                    or max(nb) - min(nb) >= kernels._GATHER_FIELD_MAX_BITS):
                flush()
        group.append(g)
        gbits |= b
    flush()
    return ops


def perm_item_entry(targets, mat):
    """Window-planner entry for one gate: ``("relabel", pairs)`` when the
    gate is a pure bit relabel under QT_PERM_FAST — pairs =
    ``((q, rho(q)), ...)`` meaning qubit q's new content comes from qubit
    rho(q), the fold plan_remap_windows applies to the live permutation
    with ZERO data motion — else the plain sorted bit tuple the dense
    window planner localizes."""
    if perm_fast_enabled():
        cls = classify_permutation_gate(mat)
        if cls is not None and cls[0] == "relabel":
            s = cls[1]
            pairs = tuple(sorted(
                (targets[j], targets[s[j]])
                for j in range(len(targets)) if s[j] != j))
            return ("relabel", pairs) if pairs else ()
    return tuple(sorted(targets))


def _is_relabel_entry(entry) -> bool:
    """True for the tagged ``("relabel", pairs)`` window-planner entry
    (robust to the list-of-list mangling introspect._predict_cached
    applies to its memo key)."""
    return len(entry) == 2 and isinstance(entry[0], str) \
        and entry[0] == "relabel"


# ---------------------------------------------------------------------------
# Cluster embedding: k-qubit matrix -> 128x128 via static index arrays
# ---------------------------------------------------------------------------


@lru_cache(maxsize=None)
def _embed_indices(bits: Tuple[int, ...]):
    """Static (row, col, mask) arrays embedding a 2^k matrix on cluster bits
    ``bits`` into the 128x128 cluster space: E[i,j] = U[r[i,j], c[i,j]] *
    mask[i,j] — the insertZeroBit index algebra of the reference
    (QuEST_cpu.c:1901-1985) expressed as precomputed gathers."""
    k = len(bits)
    idx = np.arange(DIM)
    sub = np.zeros(DIM, dtype=np.int64)
    for pos, b in enumerate(bits):
        sub |= ((idx >> b) & 1) << pos
    rest = idx.copy()
    for b in bits:
        rest &= ~(1 << b)
    # qlint: allow(f64-literal): host-side plan-table constant — cast to the register dtype at embed time, never shipped to the device as f64
    mask = (rest[:, None] == rest[None, :]).astype(np.float64)
    row = sub[:, None] * np.ones((1, DIM), dtype=np.int64)
    col = np.ones((DIM, 1), dtype=np.int64) * sub[None, :]
    return row, col, mask


def embed_in_cluster(mat_soa, bits: Tuple[int, ...]):
    """SoA (2, 2^k, 2^k) gate on cluster bits -> SoA (2, 128, 128).

    Concrete numpy inputs stay numpy: plan materialization outside jit
    (fusion drains) must not issue per-gate eager device ops — through the
    TPU relay that measured ~50x slower than host numpy for a Trotter
    stream."""
    row, col, mask = _embed_indices(tuple(bits))
    if isinstance(mat_soa, np.ndarray):
        return mat_soa[:, row, col] * mask.astype(mat_soa.dtype)
    m = jnp.asarray(mat_soa)
    return m[:, row, col] * jnp.asarray(mask, m.dtype)


def soa_matmul(a, b):
    """Complex matrix product of stacked SoA matrices (numpy in ->
    numpy out, see embed_in_cluster)."""
    if isinstance(a, np.ndarray) and isinstance(b, np.ndarray):
        re = a[0] @ b[0] - a[1] @ b[1]
        im = a[0] @ b[1] + a[1] @ b[0]
        return np.stack([re, im])
    hi = jax.lax.Precision.HIGHEST
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    re = jnp.matmul(a[0], b[0], precision=hi) - jnp.matmul(a[1], b[1], precision=hi)
    im = jnp.matmul(a[0], b[1], precision=hi) + jnp.matmul(a[1], b[0], precision=hi)
    return jnp.stack([re, im])


_EYE128 = None


def _eye_cluster():
    global _EYE128
    if _EYE128 is None:
        _EYE128 = np.stack([np.eye(DIM), np.zeros((DIM, DIM))])
    return _EYE128


# ---------------------------------------------------------------------------
# Operator-Schmidt decomposition of concrete 2q gates (cross folds)
# ---------------------------------------------------------------------------


_SCHMIDT_TOL = 1e-7


_SCHMIDT_CACHE_MAX = 4096
_schmidt_cache: dict = {}


def schmidt_terms_2q(mat_soa) -> Optional[List[tuple]]:
    """Operator-Schmidt decomposition of a CONCRETE SoA (2,4,4) 2q gate:
    U = sum_r hi_r (x) lo_r over (matrix bit 1, matrix bit 0).  Returns
    [(lo_soa, hi_soa), ...] (each SoA (2,2,2)) with len = the operator
    Schmidt rank — 1 for product gates, 2 for CNOT/CZ/controlled-phase,
    4 generically — or None for traced matrices (rank unknowable at plan
    time).  Cuts the cross-fold rank of the dominant controlled gates from
    4 to 2 vs the generic |a><b| decomposition."""
    if isinstance(mat_soa, jax.core.Tracer):
        return None
    try:
        m = np.asarray(mat_soa)
    # qlint: allow(broad-except): non-materializable values raise framework-version-dependent types; any failure means "not concrete" and the Schmidt path is skipped
    except Exception:  # pragma: no cover - any non-materializable value
        return None
    if m.dtype == object or m.shape != (2, 4, 4):
        return None
    key = (m.dtype.str, m.tobytes())
    hit = _schmidt_cache.get(key)
    if hit is not None:
        return hit
    if len(_schmidt_cache) >= _SCHMIDT_CACHE_MAX:  # bound: drop oldest
        _schmidt_cache.pop(next(iter(_schmidt_cache)))
    u = m[0] + 1j * m[1]
    # row index = 2*b1 + b0; regroup to T[(b1,b1'),(b0,b0')]
    t = u.reshape(2, 2, 2, 2).transpose(0, 2, 1, 3).reshape(4, 4)
    uu, s, vh = np.linalg.svd(t)
    # Truncation threshold scales with the dtype's working precision and is
    # relative to the largest singular value: a fixed 1e-7 would silently
    # flatten small-angle f64 controlled rotations to rank 1 (~1e-7 error
    # where eager f64 dispatch gives ~1e-16).  A zero matrix keeps its
    # leading (zero) term so the rank is always >= 1 and fold_cross never
    # sees an empty decomposition.
    eps = _SCHMIDT_TOL if m.dtype == np.float32 else 1e-12
    tol = eps * max(float(s[0]), 1.0)
    keep = [r for r in range(4) if s[r] > tol] or [0]
    terms = []
    for r in keep:
        hi = (np.sqrt(s[r]) * uu[:, r]).reshape(2, 2)
        lo = (np.sqrt(s[r]) * vh[r, :]).reshape(2, 2)
        terms.append(
            (
                np.stack([lo.real, lo.imag]).astype(m.dtype),
                np.stack([hi.real, hi.imag]).astype(m.dtype),
            )
        )
    _schmidt_cache[key] = terms
    return terms


# ---------------------------------------------------------------------------
# Controlled-form decomposition: crossing gates as diagonal masks
# ---------------------------------------------------------------------------


def _concrete44(mat_soa):
    """np (2,4,4) array or None for traced/odd-shaped matrices."""
    if isinstance(mat_soa, jax.core.Tracer):
        return None
    try:
        m = np.asarray(mat_soa)
    # qlint: allow(broad-except): materialization failure of any type means "traced/odd value" — the concrete-matrix fast path just declines
    except Exception:  # pragma: no cover
        return None
    if m.dtype == object or m.shape != (2, 4, 4):
        return None
    return m


def _diag_tol(m) -> float:
    return 1e-6 if m.dtype == np.float32 else 1e-11


def diag4_2q(mat_soa):
    """The (4,) complex diagonal of a CONCRETE diagonal 2q gate (matrix-bit
    order: index = 2*b1 + b0), or None when traced/non-diagonal.  Diagonal
    crossing gates fold into a window pass's elementwise mask at NO rank
    cost (cf. the reference's phase kernels, which likewise touch no
    amplitude pairs: QuEST_cpu.c:3146-3361)."""
    m = _concrete44(mat_soa)
    if m is None:
        return None
    u = m[0] + 1j * m[1]
    d = np.diag(u)
    if np.abs(u - np.diag(d)).max() > _diag_tol(m) * max(np.abs(u).max(), 1.0):
        return None
    return d


_CTRL_CACHE_MAX = 4096
_ctrl_cache: dict = {}


def controlled_form_2q(mat_soa):
    """Decompose a CONCRETE 2q gate that is diagonal in one matrix bit
    ("controlled form": U = |0><0|_c (x) U0 + |1><1|_c (x) U1, covering
    CNOT / controlled-V / control-on-0 variants) into

        U = (post on acted bit) . diag(d4) . (pre on acted bit)

    with pre = W^H, post = U0 @ W for the eigendecomposition
    U0^H U1 = W diag(ev) W^H.  Returns (pre_soa(2,2,2), d4_soa(2,4),
    post_soa(2,2,2), acted_bit) or None (traced / not controlled-form /
    already fully diagonal).  The planner rewrites such gates so a
    lane-x-window crossing costs one elementwise mask instead of a
    rank-2 Kronecker fold (18.6 -> 4.5 ms measured per rank-4 pass)."""
    m = _concrete44(mat_soa)
    if m is None or diag4_2q(mat_soa) is not None:
        return None
    key = (m.dtype.str, m.tobytes())
    hit = _ctrl_cache.get(key, "miss")
    if hit != "miss":
        return hit
    if len(_ctrl_cache) >= _CTRL_CACHE_MAX:
        _ctrl_cache.pop(next(iter(_ctrl_cache)))
    u = m[0] + 1j * m[1]
    tol = _diag_tol(m) * max(np.abs(u).max(), 1.0)
    result = None
    for cb in (0, 1):
        # coupling between the two values of bit cb must vanish
        v4 = u.reshape(2, 2, 2, 2)  # [b1, b0, b1', b0']
        if cb == 0:
            coupling = np.abs(v4[:, 0, :, 1]).max() + np.abs(v4[:, 1, :, 0]).max()
            blocks = [v4[:, v, :, v] for v in (0, 1)]
        else:
            coupling = np.abs(v4[0, :, 1, :]).max() + np.abs(v4[1, :, 0, :]).max()
            blocks = [v4[v, :, v, :] for v in (0, 1)]
        if coupling > tol:
            continue
        u0, u1 = blocks
        v = u0.conj().T @ u1
        # eigendecomposition of the unitary V (normal matrix)
        if np.abs(v - np.diag(np.diag(v))).max() <= tol:
            w = np.eye(2, dtype=complex)
            ev = np.diag(v)
        else:
            ev, w = np.linalg.eig(v)
            w, _ = np.linalg.qr(w)  # orthonormalize (degenerate safety)
            # recompute ev against the orthonormalized columns
            ev = np.diag(w.conj().T @ v @ w)
        pre = w.conj().T
        post = u0 @ w
        acted = 1 - cb
        d4 = np.ones(4, dtype=complex)
        for ba in (0, 1):
            idx = (2 * ba + 1) if cb == 0 else (2 + ba)
            d4[idx] = ev[ba]
        # Verify the decomposition reconstructs the input: the eig + QR
        # orthonormalization can silently mis-decompose a pathological
        # near-degenerate or slightly non-unitary V (diag(W^H V W) drops
        # any off-diagonal residue).  On failure return None so the gate
        # takes the exact rank-2 Schmidt fold instead.
        if acted == 0:
            full_pre = np.kron(np.eye(2), pre)
            full_post = np.kron(np.eye(2), post)
        else:
            full_pre = np.kron(pre, np.eye(2))
            full_post = np.kron(post, np.eye(2))
        recon = full_post @ np.diag(d4) @ full_pre
        if np.abs(recon - u).max() > 16 * tol:
            continue
        dt = m.dtype
        result = (
            np.stack([pre.real, pre.imag]).astype(dt),
            np.stack([d4.real, d4.imag]).astype(dt),
            np.stack([post.real, post.imag]).astype(dt),
            acted,
        )
        break
    _ctrl_cache[key] = result
    return result


def rewrite_controlled_gates(glist: List[Gate]) -> List[Gate]:
    """Rewrite every concrete controlled-form 2q gate g as
    [pre(acted qubit), diagonal 2q gate, post(acted qubit)] so that if the
    gate ends up straddling a lane-x-window boundary, the diagonal part
    folds into the pass mask (rank-free) while pre/post fold as ordinary
    dense 1q gates.  Non-crossing placements lose nothing: all three
    pieces fold into the same side product."""
    out: List[Gate] = []
    for g in glist:
        cf = controlled_form_2q(g.mat) if len(g.targets) == 2 else None
        if cf is None:
            out.append(g)
            continue
        pre, d4, post, acted = cf
        tq = g.targets[acted]
        dd = np.zeros((2, 4, 4), dtype=d4.dtype)
        dd[0][np.diag_indices(4)] = d4[0]
        dd[1][np.diag_indices(4)] = d4[1]
        out.append(Gate((tq,), pre))
        out.append(Gate(g.targets, dd))
        out.append(Gate((tq,), post))
    return out


def is_identity_gate(mat_soa) -> bool:
    """Concrete and EXACTLY the identity, bitwise — the circuit
    optimizer's cancellation gate (optimizer.py): only a pair whose
    product hits exact 1.0/0.0 entries (X·X, CNOT·CNOT, SWAP·SWAP, any
    permutation pair) may be dropped without perturbing the drained
    state; a merely-near-identity product (H·H is ``1+2e-16`` on the
    f64 diagonal) must merge instead.  Accepts (2, s, s) and batched
    (B, 2, s, s) stacks (all elements must be the identity)."""
    if isinstance(mat_soa, jax.core.Tracer):
        return False
    m = np.asarray(mat_soa)
    if m.dtype == object or m.ndim not in (3, 4):
        return False
    eye = np.eye(m.shape[-1], dtype=m.dtype)
    return bool((m[..., 0, :, :] == eye).all()
                and (m[..., 1, :, :] == 0.0).all())


def is_diag_gate(mat_soa) -> bool:
    """Concrete and diagonal (any size) — such gates commute with a pass's
    diagonal mask and may keep folding after it."""
    if isinstance(mat_soa, jax.core.Tracer):
        return False
    try:
        m = np.asarray(mat_soa)
    # qlint: allow(broad-except): materialization failure of any type means "not concrete" — a non-diagonal answer is always safe (pass merely stops folding)
    except Exception:  # pragma: no cover
        return False
    if m.dtype == object or m.ndim != 3:
        return False
    u = m[0] + 1j * m[1]
    off = np.abs(u - np.diag(np.diag(u))).max()
    return bool(off <= _diag_tol(m) * max(np.abs(u).max(), 1.0))


# ---------------------------------------------------------------------------
# Scheduler
# ---------------------------------------------------------------------------


def _stack_sides(As, Bs):
    """Stack per-rank side matrices (None = identity) into (R, 2, 128, 128)
    arrays; stays numpy when every term is concrete (plan materialization
    outside jit must not issue eager device ops)."""
    eye = _eye_cluster()
    if all(x is None or isinstance(x, np.ndarray) for x in As + Bs):
        dts = [x.dtype for x in As + Bs if x is not None]
        # qlint: allow(f64-literal): all-identity fallback dtype for a host-side numpy plan table; the register dtype overrides it whenever any real term exists
        dt = dts[0] if dts else np.float64
        a = np.stack([x if x is not None else eye.astype(dt) for x in As])
        b = np.stack([x if x is not None else eye.astype(dt) for x in Bs])
        return a, b
    a = jnp.stack([jnp.asarray(x) if x is not None else jnp.asarray(eye)
                   for x in As])
    b = jnp.stack([jnp.asarray(x) if x is not None else jnp.asarray(eye)
                   for x in Bs])
    return a, b


_CROSS_RANK = 4  # rank of the |a><b| (x) U_ab decomposition of a 2q gate


class _FoldAcc:
    """Accumulator for the window operator as a rank-R Kronecker sum
    sum_r B_r (x) A_r (A_r on lanes 0-6, B_r on sublanes 7-13): pure
    cluster gates multiply into every term; one lane-x-sublane 2q gate
    raises R from 1 to 4 via its |a><b| block decomposition
    (fused.apply_cluster_stack executes the sum in one HBM pass).  Shared
    by the planner (_Plan) and the native-plan materializer."""

    def __init__(self):
        self.As = [None]  # per-rank traced (2,128,128); None = identity
        self.Bs = [None]
        self.rank = 1
        self.count = 0

    def fold(self, cluster: str, bits: Tuple[int, ...], mat):
        e = embed_in_cluster(mat, bits)
        accs = self.As if cluster == "A" else self.Bs
        for r in range(self.rank):
            accs[r] = e if accs[r] is None else soa_matmul(e, accs[r])
        self.count += 1

    def fold_cross(self, phys: Tuple[int, ...], mat):
        """Fold a 2q gate with one lane and one sublane target; requires
        rank == 1 (caller flushes first otherwise)."""
        assert self.rank == 1
        mat = jnp.asarray(mat)
        if phys[0] < LANE:
            la, sb = phys[0], phys[1]
            def block(a, b):
                return mat[:, 2 * a:2 * a + 2, 2 * b:2 * b + 2]
        else:
            sb, la = phys[0], phys[1]
            def block(a, b):
                return mat[:, a::2, b::2]
        A0, B0 = self.As[0], self.Bs[0]
        As, Bs = [], []
        for a in (0, 1):
            for b in (0, 1):
                ea = embed_in_cluster(block(a, b), (la,))
                eb_np = np.zeros((2, 2, 2))
                eb_np[0, a, b] = 1.0
                eb = embed_in_cluster(eb_np, (sb - LANE,))
                As.append(ea if A0 is None else soa_matmul(ea, A0))
                Bs.append(eb if B0 is None else soa_matmul(eb, B0))
        self.As, self.Bs = As, Bs
        self.rank = _CROSS_RANK
        self.count += 1

    def stacks(self):
        return _stack_sides(self.As, self.Bs)

    def reset(self):
        self.As, self.Bs = [None], [None]
        self.rank = 1
        self.count = 0


class _WinAcc:
    """Accumulator for one offset-window pass: the operator on
    {lane qubits [0,7)} x {window qubits [k, k+7)} as a rank-R Kronecker
    sum sum_r B_r (x) A_r.  Like _FoldAcc but bound to a window offset and
    using the operator-Schmidt decomposition for concrete cross gates
    (rank x2 for CNOT/CZ instead of x4), with rank capped by the planner."""

    def __init__(self, k: int):
        self.k = k
        self.As: List[Optional[object]] = [None]
        self.Bs: List[Optional[object]] = [None]
        self.rank = 1
        self.count = 0
        self.a_used = False
        self.b_used = False
        # elementwise post-mask over (window bit, lane bit) from diagonal
        # crossing gates: out = mask (.) (sum_r B_r (x) A_r) x
        self.mask: Optional[np.ndarray] = None  # complex (128, 128)

    def fold_side(self, side: str, bits: Tuple[int, ...], mat):
        e = embed_in_cluster(mat, bits)
        accs = self.As if side == "A" else self.Bs
        for r in range(self.rank):
            accs[r] = e if accs[r] is None else soa_matmul(e, accs[r])
        if side == "A":
            self.a_used = True
        else:
            self.b_used = True
        self.count += 1

    def fold_cross(self, lane_bit: int, win_bit: int, mat,
                   lane_is_bit0: bool):
        """Fold a 2q gate with one lane target and one window target.
        ``win_bit`` is window-relative (0-6).  Concrete matrices use their
        Schmidt terms; traced matrices the generic 4-term |a><b| split."""
        terms = schmidt_terms_2q(mat)
        if terms is not None:
            pairs = [
                (lo, hi) if lane_is_bit0 else (hi, lo) for lo, hi in terms
            ]
        else:
            mat = jnp.asarray(mat)
            pairs = []
            for a in (0, 1):
                for b in (0, 1):
                    if lane_is_bit0:
                        lane_m = mat[:, 2 * a:2 * a + 2, 2 * b:2 * b + 2]
                    else:
                        lane_m = mat[:, a::2, b::2]
                    win_m = np.zeros((2, 2, 2))
                    win_m[0, a, b] = 1.0
                    pairs.append((lane_m, win_m))
        As, Bs = [], []
        for lane_m, win_m in pairs:
            ea = embed_in_cluster(lane_m, (lane_bit,))
            eb = embed_in_cluster(win_m, (win_bit,))
            for r in range(self.rank):
                As.append(ea if self.As[r] is None
                          else soa_matmul(ea, self.As[r]))
                Bs.append(eb if self.Bs[r] is None
                          else soa_matmul(eb, self.Bs[r]))
        self.As, self.Bs = As, Bs
        self.rank = len(As)
        self.a_used = True
        self.b_used = True
        self.count += 1

    def fold_mask(self, lane_bit: int, win_bit: int, d4, lane_is_bit0: bool):
        """Fold a DIAGONAL crossing 2q gate as an elementwise post-mask:
        no rank growth, one VPU multiply in the kernel.  ``d4``: complex
        (4,) diagonal in matrix-bit order (index 2*b1 + b0)."""
        lb = (np.arange(DIM) >> lane_bit) & 1
        wb = (np.arange(DIM) >> win_bit) & 1
        if lane_is_bit0:
            idx = 2 * wb[:, None] + lb[None, :]
        else:
            idx = 2 * lb[None, :] + wb[:, None]
        m = np.asarray(d4, dtype=complex)[idx]          # (win/sublane, lane)
        self.mask = m if self.mask is None else self.mask * m
        self.count += 1

    def mask_soa(self):
        """SoA (2, 128, 128) mask array, or None."""
        if self.mask is None:
            return None
        return np.stack([self.mask.real, self.mask.imag])

    def stacks(self):
        return _stack_sides(self.As, self.Bs)


class _Plan:
    """Mutable planning state; emits the op program."""

    def __init__(self, num_qubits: int):
        self.n = num_qubits
        # pos[logical qubit] = current physical position
        self.pos = list(range(num_qubits))
        self.ops: List[tuple] = []
        self.acc = _FoldAcc()
        # relocation segment (page) size bounds: m <= seg_max by available
        # high bits; m >= seg_min = 3 keeps the 2^m segment axis a multiple
        # of the 8-sublane tile (no transpose padding) except when fewer
        # high bits exist at all
        self.seg_max = min(LANE, max(0, num_qubits - WINDOW))
        self.seg_min = min(3, self.seg_max) if self.seg_max > 0 else 0

    def _fold(self, cluster: str, bits: Tuple[int, ...], mat):
        self.acc.fold(cluster, bits, mat)

    def flush(self):
        if self.acc.count == 0:
            return
        a, b = self.acc.stacks()
        self.ops.append(("fused", a, b))
        self.acc.reset()

    def _emit_segswap(self, h: int, b: int, m: int):
        """Exchange bit segments [h, h+m) <-> [b, b+m)."""
        self.flush()
        self.ops.append(("segswap", h, b, m))
        newpos = []
        for p in self.pos:
            if b <= p < b + m:
                newpos.append(h + (p - b))
            elif h <= p < h + m:
                newpos.append(b + (p - h))
            else:
                newpos.append(p)
        self.pos = newpos

    def final_restore(self):
        """Return every qubit label to its home position with a MINIMAL
        greedy block-sort of segment swaps (replaying the whole swap stack
        in reverse would cost one transpose pass per historical swap; the
        net permutation usually collapses to a handful)."""
        self.flush()
        n = self.n
        while True:
            q = next((i for i in range(n) if self.pos[i] != i), None)
            if q is None:
                break
            assert q >= LANE  # lane bits are never relocated
            p = self.pos[q]  # where logical q currently lives (p > q)
            m = 1
            while (
                q + m < p
                and q + m < n
                and p + m < n
                and self.pos[q + m] == p + m
            ):
                m += 1
            self._emit_segswap(p, q, m)


def _cluster_of(phys: Sequence[int]) -> Optional[str]:
    if all(p < LANE for p in phys):
        return "A"
    if all(LANE <= p < WINDOW for p in phys):
        return "B"
    return None


def _is_cross2(phys: Sequence[int]) -> bool:
    """2q gate with one lane (0-6) and one sublane (7-13) target — foldable
    as a rank-4 Kronecker sum (_Plan._fold_cross)."""
    if len(phys) != 2:
        return False
    a, b = phys
    return (a < LANE <= b < WINDOW) or (b < LANE <= a < WINDOW)


def materialize_plan(structural: Sequence[tuple],
                     gates: Sequence[Gate]) -> List[tuple]:
    """Turn a structural plan (gate indices, from the native C++ scheduler)
    into the executable op list by folding the referenced gate matrices.

    Fused ops carry an ordered entry list [(side, gate_idx, bits), ...]
    with side 0 = lane cluster A, 1 = sublane cluster B, 2 = cross
    (bits = the two physical targets); replayed through _FoldAcc so the
    result is numerically identical to the Python planner's."""
    ops: List[tuple] = []
    for op in structural:
        if op[0] == "fused":
            acc = _FoldAcc()
            for side, gi, bits in op[1]:
                if side == 2:
                    acc.fold_cross(tuple(bits), gates[gi].mat)
                else:
                    acc.fold("A" if side == 0 else "B", tuple(bits),
                             gates[gi].mat)
            a, b = acc.stacks()
            ops.append(("fused", a, b))
        elif op[0] == "apply":
            ops.append(("apply", op[2], gates[op[1]].mat))
        else:
            ops.append(op)
    return ops


def _peephole(ops: List[tuple], num_qubits: int) -> List[tuple]:
    """Merge each segment swap with the cluster pass that follows it into
    one fused swap+cluster HBM pass (fused.apply_swap_cluster_stack) when
    the swap's 2^m super-block fits in VMEM."""
    out: List[tuple] = []
    i = 0
    while i < len(ops):
        op = ops[i]
        if (
            op[0] == "segswap"
            and i + 1 < len(ops)
            and ops[i + 1][0] == "fused"
            and op[3] <= fused.MAX_FUSED_SWAP_M
            and op[1] >= WINDOW
            and LANE <= op[2]
            and op[2] + op[3] <= WINDOW
        ):
            out.append(("swapfused", op[1], op[2], op[3],
                        ops[i + 1][1], ops[i + 1][2]))
            i += 2
        else:
            out.append(op)
            i += 1
    return out


def _side_split_enabled() -> bool:
    import os

    return os.environ.get("QT_SIDE_SPLIT", "0") == "1"


def split_plan_sides(ops: Sequence[tuple]) -> List[tuple]:
    """Side-minimisation rewrite (VERDICT r3 item 6): a run of rank-1
    maskless dual-side window passes (B_i (x) A_i applied in order)
    equals (prod B_i) o (prod A_i) because the A side always acts on lane
    qubits [0,7) and the B sides on window qubits >= 7 — disjoint, so
    they commute.  The round-3 profile prices a single-side pass at the
    ~1.25 ms HBM floor but a dual-side pass at ~2.1 ms (the second
    side's bf16 MXU decomposition can't hide under one sweep's
    bandwidth), so rewriting j >= 2 dual passes into j B-only passes +
    ONE merged A pass trades j*0.85 ms of side cost for one 1.25 ms
    sweep — a win from j = 2.

    Barriers (anything whose lane action is tied to the window or
    non-commuting): rank > 1 passes, masked passes, and every
    non-winfused op.  Regions with fewer than 2 deferrable A sides are
    left untouched (splitting a lone dual pass LOSES: 2.5 vs 2.1 ms)."""
    def deferrable(op):
        return (op[0] == "winfused" and np.shape(op[2])[0] == 1
                and (len(op) < 7 or op[6] is None) and op[4]
                and isinstance(op[2], np.ndarray))

    def mask_commutes(op, touched: set) -> bool:
        """A masked B-only pass is transparent to a pending A product
        when the mask's lane dependence misses every lane bit the
        product touches: m[w, l] must be constant over each touched
        bit's flip."""
        if not isinstance(op[6], np.ndarray):
            return False
        m = op[6][0] + 1j * op[6][1]           # (window, lane)
        cols = np.arange(DIM)
        for l in touched:
            if not np.allclose(m, m[:, cols ^ (1 << l)], atol=1e-12):
                return False
        return True

    def lane_bits_of(a) -> set:
        """Lane bits a (2,128,128) concrete A-operator acts on
        non-trivially: bit l is untouched iff A factors as I_l (x) A',
        i.e. BOTH off-blocks over l vanish (every A[i, j] with bit l of
        i and j differing — not just the single-flip diagonal, which
        misses multi-bit operators like X_l X_m) AND the two same-bit
        blocks are equal."""
        u = a[0] + 1j * a[1]
        idx = np.arange(DIM)
        out = set()
        for l in range(LANE):
            r0 = idx[((idx >> l) & 1) == 0]
            r1 = r0 ^ (1 << l)
            off = max(np.abs(u[np.ix_(r0, r1)]).max(),
                      np.abs(u[np.ix_(r1, r0)]).max())
            sym = np.abs(u[np.ix_(r0, r0)] - u[np.ix_(r1, r1)]).max()
            if off > 1e-12 or sym > 1e-12:
                out.add(l)
        return out

    out: List[tuple] = []
    region: List[tuple] = []

    def region_defer_count():
        return sum(1 for op, d in region if d)

    def flush_region():
        if region_defer_count() < 2:
            out.extend(op for op, _ in region)
            region.clear()
            return
        a_prod = None
        for op, d in region:
            if d:
                a_prod = (op[2][0] if a_prod is None
                          else soa_matmul(op[2][0], a_prod))
                if op[5]:  # B side survives as a single-side pass
                    out.append(("winfused", op[1], op[2], op[3],
                                False, True, None))
            else:
                out.append(op)
        out.append(("winfused", LANE, a_prod[None],
                    _eye_cluster().astype(a_prod.dtype)[None],
                    True, False, None))
        region.clear()

    touched: set = set()
    for op in ops:
        if deferrable(op):
            region.append((op, True))
            touched |= lane_bits_of(op[2][0])
            continue
        # transparent: pure-B rank-any maskless passes never touch lanes;
        # masked B-only passes are transparent when the mask's lane
        # dependence misses every touched bit
        if op[0] == "winfused" and not op[4]:
            if len(op) < 7 or op[6] is None or mask_commutes(op, touched):
                region.append((op, False))
                continue
        flush_region()
        touched = set()
        out.append(op)
    flush_region()
    return out


# Matrix operands of one megawin group are ALL VMEM-resident at once
# (per-pass state temporaries are sequential, the matrices are not), so the
# group closes when their total passes this budget — 4 MB leaves the
# 16 MB scoped VMEM room for the G-row state block in+out plus the active
# pass's temporaries at the megawin_row_cap sizing.
MEGA_MAT_BYTES = 4 << 20


def _winfused_mat_bytes(op) -> int:
    """f32 VMEM bytes of one winfused pass's matrix operands as the
    megakernel stages them (dual-side passes upload 256x256 real reps)."""
    rank = int(np.shape(op[2])[0])
    dual = op[4] and op[5]
    per = 2 * (2 * DIM) * (2 * DIM) * 4 if dual else 2 * 2 * DIM * DIM * 4
    nbytes = rank * per
    if len(op) > 6 and op[6] is not None:
        nbytes += 2 * DIM * DIM * 4
    return nbytes


def group_megawins(ops: Sequence[tuple], num_qubits: int) -> List[tuple]:
    """Megakernel grouping rewrite (docs/design.md §29): fold each run of
    consecutive winfused passes into ``("megawin", (passes...))`` groups
    that execute as ONE pallas_call — one HBM round-trip for the run.

    A pass joins the open group while the group stays inside the VMEM
    budget: G = 2^(kmax-7) block rows (every member's window bits must be
    block-local) can't exceed any member's row cap
    (fused.megawin_row_cap), the shard's row count, or the matrix-operand
    budget (MEGA_MAT_BYTES).  Wider-window passes (k > 10 at the default
    caps) stay on the per-pass route — already one HBM trip each.
    Groups of one are pointless and left ungrouped."""
    if num_qubits < WINDOW:
        return list(ops)
    nb = 1 << (num_qubits - WINDOW)
    out: List[tuple] = []
    group: List[tuple] = []
    kmax = allowed = mat_bytes = 0

    def close():
        nonlocal group, kmax, allowed, mat_bytes
        if len(group) >= 2:
            out.append(("megawin", tuple(group)))
        else:
            out.extend(group)
        group, kmax, allowed, mat_bytes = [], 0, 0, 0

    for op in ops:
        if op[0] != "winfused":
            close()
            out.append(op)
            continue
        cap = min(fused.megawin_row_cap(int(np.shape(op[2])[0]),
                                        num_qubits), nb)
        nbytes = _winfused_mat_bytes(op)
        if (1 << (op[1] - LANE)) > cap:
            close()
            out.append(op)           # window too wide to ever be grouped
            continue
        if group:
            nk = max(kmax, op[1])
            na = min(allowed, cap)
            if ((1 << (nk - LANE)) <= na
                    and mat_bytes + nbytes <= MEGA_MAT_BYTES):
                group.append(op)
                kmax, allowed, mat_bytes = nk, na, mat_bytes + nbytes
                continue
            close()
        group, kmax, allowed, mat_bytes = [op], op[1], cap, nbytes
    close()
    return out


def plan_circuit(gates: Sequence[Gate], num_qubits: int,
                 use_native: Optional[bool] = None,
                 planner: Optional[str] = None) -> List[tuple]:
    """Plan a gate list.

    ``planner``: 'windowed' (default — offset-window passes, zero
    relocation) or 'paged' (the segswap-relocation scheduler).  Overridable
    via QT_PLANNER.  The native C++ scheduler (native/scheduler.cc) is used
    when built; Python fallback otherwise — identical algorithm/output."""
    import os

    from . import native

    if planner is None:
        planner = os.environ.get("QT_PLANNER", "windowed")
    if planner not in ("windowed", "paged"):
        raise ValueError(
            f"unknown planner {planner!r}: expected 'windowed' or 'paged'"
        )
    if planner == "windowed":
        if use_native is None:
            use_native = native.native_available()
        ops = None
        if use_native and num_qubits >= WINDOW:
            # the controlled-form rewrite happens here so the C++ planner
            # sees the same (rewritten) gate stream as the Python one
            glist = rewrite_controlled_gates(list(gates))
            structural = native.plan_native_windowed(
                [g.targets for g in glist], num_qubits,
                _gate_xranks(glist), _gate_flags(glist))
            if structural is not None:
                ops = materialize_windowed_plan(structural, glist)
        if ops is None:
            ops = plan_circuit_windowed(gates, num_qubits)
        if _side_split_enabled() and num_qubits >= WINDOW:
            ops = split_plan_sides(ops)
        if fused.megakernel_planning() and num_qubits >= WINDOW:
            ops = group_megawins(ops, num_qubits)
        return ops
    if use_native is None:
        use_native = native.native_available()
    if use_native:
        structural = native.plan_native([g.targets for g in gates], num_qubits)
        if structural is not None:
            return _peephole(materialize_plan(structural, gates), num_qubits)
    return plan_circuit_py(gates, num_qubits)


def _gate_flags(gates: Sequence[Gate]) -> List[int]:
    """Per-gate diagonality flags for the native planner: bit 0 = diagonal
    matrix (commutes with a pass mask), bit 1 = concrete diagonal 2q
    (mask-foldable when crossing lane x window)."""
    out = []
    for g in gates:
        f = 0
        if is_diag_gate(g.mat):
            f |= 1
        if len(g.targets) == 2 and diag4_2q(g.mat) is not None:
            f |= 2
        out.append(f)
    return out


def _gate_xranks(gates: Sequence[Gate]) -> List[int]:
    """Per-gate cross-fold rank for the native planner: Schmidt rank for
    concrete 2q matrices, 4 for traced 2q matrices, 0 otherwise."""
    out = []
    for g in gates:
        if len(g.targets) == 2:
            terms = schmidt_terms_2q(g.mat)
            out.append(len(terms) if terms is not None else _CROSS_RANK)
        else:
            out.append(0)
    return out


def materialize_windowed_plan(structural: Sequence[tuple],
                              gates: Sequence[Gate]) -> List[tuple]:
    """Structural windowed plan (from native/scheduler.cc) -> executable op
    list.  Winfused ops carry (k, [(kind, gate_idx, bits), ...]) with kind
    0 = lane side A, 1 = window side B, 2 = cross (bits = (lane_bit,
    win_bit, lane_is_bit0)); replayed through _WinAcc so the result is
    numerically identical to the Python planner's."""
    ops: List[tuple] = []
    for op in structural:
        if op[0] == "winfused":
            k, entries = op[1], op[2]
            acc = _WinAcc(k)
            for kind, gi, bits in entries:
                if kind == 3:
                    acc.fold_mask(bits[0], bits[1], diag4_2q(gates[gi].mat),
                                  bool(bits[2]))
                elif kind == 2:
                    acc.fold_cross(bits[0], bits[1], gates[gi].mat,
                                   bool(bits[2]))
                else:
                    acc.fold_side("A" if kind == 0 else "B", tuple(bits),
                                  gates[gi].mat)
            a, b = acc.stacks()
            ops.append(("winfused", k, a, b, acc.a_used, acc.b_used,
                        acc.mask_soa()))
        elif op[0] == "apply":
            ops.append(("apply", op[2], gates[op[1]].mat))
        else:
            ops.append(op)
    return ops


def plan_circuit_py(gates: Sequence[Gate], num_qubits: int) -> List[tuple]:
    """Dependency-DAG list scheduler.

    Gates sharing no qubit commute, so the per-qubit program-order queues
    define the only real ordering constraints.  The scheduler repeatedly
    (1) folds every *ready* gate that sits inside a cluster, (2) when
    nothing folds, picks the segment swap that makes the most ready gates
    foldable (>= 2, else not worth the extra pass), (3) otherwise pops the
    smallest ready gate through the standard layout-safe kernel.  This
    batches a whole circuit layer per cluster pass instead of flushing at
    the first non-resident gate (the reference has no such scheduler at
    all — it dispatches gate-at-a-time, QuEST/src/QuEST.c)."""
    n = num_qubits
    glist = list(gates)
    if n < WINDOW:
        # Too small for the cluster kernel: program = plain per-gate applies.
        return [("apply", g.targets, g.mat) for g in glist]

    plan = _Plan(n)
    num_gates = len(glist)
    queues: List[List[int]] = [[] for _ in range(n)]
    for gi, g in enumerate(glist):
        for t in g.targets:
            queues[t].append(gi)
    heads = [0] * n

    def is_ready(gi):
        return all(
            heads[t] < len(queues[t]) and queues[t][heads[t]] == gi
            for t in glist[gi].targets
        )

    ready = sorted(gi for gi in range(num_gates) if is_ready(gi))
    done = 0

    def pop(gi):
        nonlocal done, ready
        for t in glist[gi].targets:
            heads[t] += 1
        done += 1
        ready.remove(gi)
        # gates newly at all their heads
        for t in glist[gi].targets:
            if heads[t] < len(queues[t]):
                cand = queues[t][heads[t]]
                if cand not in ready and is_ready(cand):
                    ready.append(cand)
        ready.sort()

    def phys_of(gi):
        return tuple(plan.pos[t] for t in glist[gi].targets)

    def try_fold(gi):
        phys = phys_of(gi)
        cl = _cluster_of(phys)
        if cl is not None:
            bits = tuple(p if cl == "A" else p - LANE for p in phys)
            plan._fold(cl, bits, glist[gi].mat)
            pop(gi)
            return True
        if _is_cross2(phys):
            if plan.acc.rank > 1:
                plan.flush()
            plan.acc.fold_cross(phys, glist[gi].mat)
            pop(gi)
            return True
        return False

    def swapped_pos(p, h, b, m):
        if b <= p < b + m:
            return h + (p - b)
        if h <= p < h + m:
            return b + (p - h)
        return p

    def best_swap():
        """(h, b, m) of the segment swap enabling the most ready folds;
        None if no swap enables >= 2.  Variable width m lets a swap pull a
        high page in while KEEPING a window-resident partner qubit — e.g. a
        gate on (sublane 8, grid 21) folds after a 3-bit swap that evicts
        [9, 12) only."""
        if plan.seg_max <= 0:
            return None
        cand_hm = []
        for gi in ready:
            high = [p for p in phys_of(gi) if p >= WINDOW]
            if not high:
                continue
            span = max(high) - min(high) + 1
            for m in range(max(plan.seg_min, span), plan.seg_max + 1):
                lo_h = max(WINDOW, max(high) - m + 1)
                hi_h = min(n - m, min(high))
                if lo_h <= hi_h and (hi_h, m) not in cand_hm:
                    cand_hm.append((hi_h, m))
        if not cand_hm:
            return None
        cand_hm.sort()
        # next-use distance per physical position (capped horizon), over
        # pending gate-target occurrences in gate-index order (queues are
        # sorted, so gi is pending on qubit t iff gi >= queues[t][heads[t]])
        next_use = {}
        d = 0
        for gi in range(num_gates):
            if d > _LOOKAHEAD:
                break
            for t in glist[gi].targets:
                if d > _LOOKAHEAD:
                    break
                q = queues[t]
                hpos = heads[t]
                if hpos < len(q) and gi >= q[hpos]:
                    p = plan.pos[t]
                    if p not in next_use:
                        next_use[p] = d
                    d += 1
        best = None
        for h, m in cand_hm:
            for b in range(LANE, WINDOW - m + 1):
                count = 0
                for gi in ready:
                    pp = tuple(swapped_pos(p, h, b, m) for p in phys_of(gi))
                    if _cluster_of(pp) is not None or _is_cross2(pp):
                        count += 1
                evict = min(
                    (next_use.get(p, _LOOKAHEAD + 1) for p in range(b, b + m)),
                    default=0,
                )
                key = (count, evict, -m, -h, -b)
                if best is None or key > best[0]:
                    best = (key, h, b, m)
        # a swap pass costs the same as one transpose (~copy speed) while a
        # standalone apply pass is 2-8x that, so relocating for even ONE
        # foldable gate wins
        if best is None or best[0][0] < 1:
            return None
        return best[1], best[2], best[3]

    while done < num_gates:
        progressed = True
        while progressed:
            progressed = False
            for gi in list(ready):
                if try_fold(gi):
                    progressed = True
        if done == num_gates:
            break
        sw = best_swap()
        if sw is not None:
            h, b, m = sw
            plan._emit_segswap(h, b, m)
            continue
        gi = ready[0]
        plan.flush()
        plan.ops.append(("apply", phys_of(gi), glist[gi].mat))
        pop(gi)
    plan.final_restore()
    return _peephole(plan.ops, n)


RANK_CAP = 4  # max Kronecker-sum rank per window pass (FLOPs scale with it)


def plan_circuit_windowed(gates: Sequence[Gate],
                          num_qubits: int) -> List[tuple]:
    """Offset-window DAG list scheduler — zero-relocation planning.

    Each emitted pass applies a rank-R operator on {lane qubits [0,7)} x
    {window qubits [k, k+7)} where the window offset k is chosen PER PASS:
    the window kernel (ops/fused.py apply_window_stack) views the strided
    bit-window directly through its BlockSpec, so high qubits never have to
    be relocated at all — where the paged planner (plan_circuit_py) pays
    segswap/transpose passes to pull high qubits into [7,14), this planner
    just aims the window at them.  The scheduler greedily picks, per pass,
    the offset k whose transitive fold closure over the ready frontier
    covers the most gates; 2q gates straddling lane x window fold through
    their operator-Schmidt terms (schmidt_terms_2q — rank x2 for
    controlled gates) with pass rank capped at RANK_CAP.  Gates no window
    covers (e.g. a dense 2q gate on two far-apart high qubits) fall back to
    one standard layout-safe kernel pass.

    Concrete controlled-form 2q gates are first rewritten as
    pre/diagonal/post (rewrite_controlled_gates); the diagonal part of a
    crossing gate then folds into the pass's elementwise MASK at zero rank
    cost — after a mask is set, only gates commuting with it (disjoint
    bits, or diagonal) may keep folding into the pass."""
    n = num_qubits
    glist = list(gates)
    if n < WINDOW:
        return [("apply", g.targets, g.mat) for g in glist]
    glist = rewrite_controlled_gates(glist)

    num_gates = len(glist)
    queues: List[List[int]] = [[] for _ in range(n)]
    for gi, g in enumerate(glist):
        for t in g.targets:
            queues[t].append(gi)
    heads = [0] * n

    # cross-fold rank per 2q gate: Schmidt rank when concrete, 4 otherwise
    xrank = _gate_xranks(glist)
    # diagonal crossing gates mask-fold (rank-free); diagonal gates of any
    # size commute with an existing mask
    gdiag4 = [diag4_2q(g.mat) if len(g.targets) == 2 else None for g in glist]
    gdiag = [is_diag_gate(g.mat) for g in glist]

    k_lo, k_hi = LANE, n - LANE  # valid window offsets (inclusive)

    def classify(targets: Tuple[int, ...], k: int):
        """How ``targets`` folds for window [k, k+7): ('A', bits),
        ('B', window-relative bits), ('X', lane_bit, win_bit, lane_is_bit0)
        for a 2q lane x window straddle, or None."""
        lane = all(t < LANE for t in targets)
        if lane:
            return ("A", targets)
        win = all(k <= t < k + LANE for t in targets)
        if win:
            return ("B", tuple(t - k for t in targets))
        if len(targets) == 2:
            t0, t1 = targets
            if t0 < LANE and k <= t1 < k + LANE:
                return ("X", t0, t1 - k, True)
            if t1 < LANE and k <= t0 < k + LANE:
                return ("X", t1, t0 - k, False)
        return None

    def is_ready(gi, hd):
        return all(
            hd[t] < len(queues[t]) and queues[t][hd[t]] == gi
            for t in glist[gi].targets
        )

    ready = sorted(gi for gi in range(num_gates) if is_ready(gi, heads))

    def advance(gi, hd, rdy):
        """Pop gate gi from (hd, rdy) in place."""
        for t in glist[gi].targets:
            hd[t] += 1
        rdy.remove(gi)
        for t in glist[gi].targets:
            if hd[t] < len(queues[t]):
                cand = queues[t][hd[t]]
                if cand not in rdy and is_ready(cand, hd):
                    rdy.append(cand)
        rdy.sort()

    def simulate(k):
        """Transitive fold closure for window k over copies of the DAG
        state: (count, final_rank, folds in fold order).  Mirrors the
        mask rules: a diagonal crossing gate folds into the pass mask
        (rank-free); once the mask is set, a gate may only fold if it
        commutes with the mask (disjoint bits or diagonal)."""
        hd = heads[:]
        rdy = list(ready)
        rank, count, folds = 1, 0, []
        mask_bits: set = set()
        progressed = True
        while progressed:
            progressed = False
            for gi in list(rdy):
                c = classify(glist[gi].targets, k)
                if c is None:
                    continue
                blocked = (
                    mask_bits
                    and not gdiag[gi]
                    and (mask_bits & set(glist[gi].targets))
                )
                if c[0] == "X":
                    if gdiag4[gi] is not None:
                        mask_bits |= set(glist[gi].targets)
                    else:
                        if blocked:
                            continue
                        r = xrank[gi]
                        if rank * r > RANK_CAP:
                            continue
                        rank *= r
                elif blocked:
                    continue
                count += 1
                folds.append(gi)
                advance(gi, hd, rdy)
                progressed = True
        return count, rank, folds

    ops: List[tuple] = []
    while ready:
        # candidate offsets: windows that cover some ready gate's high
        # targets, plus the home window k=7
        cands = {k_lo}
        for gi in ready:
            for t in glist[gi].targets:
                if t >= LANE:
                    for k in range(max(k_lo, t - LANE + 1),
                                   min(k_hi, t) + 1):
                        cands.add(k)
        # Windows k in {8, 9} force the collapsed 4-d state view (mid < 8,
        # ops/fused.py): its layout differs from the canonical T(8,128)
        # tiling, so XLA inserts full-state retile copies at the pass
        # boundary — measured 5.9 ms vs 1.3 ms per pass at 26q, and an
        # 8 GB OOM copy at 30q.  Pruned from the primary candidate set;
        # the rare gates ONLY these windows cover (targets spanning
        # exactly bits [8,14] or [9,15]) are caught by the last-resort
        # retry below — do not delete that fallback.
        if k_hi >= 10:
            cands -= {8, 9}
        best = None
        for k in sorted(cands):
            count, rank, folds = simulate(k)
            key = (count, -rank, -k)
            if best is None or key > best[0]:
                best = (key, k, folds)
        if best is None or best[0][0] == 0:
            # last resort: retry the pruned offsets {8, 9} — a gate whose
            # targets span exactly bits [8,14] or [9,15] is coverable by
            # NO other window, and even the slow collapsed-4-d-view pass
            # beats a per-gate full-state apply
            for k in (8, 9):
                if k_lo <= k <= k_hi:
                    count, rank, folds = simulate(k)
                    key = (count, -rank, -k)
                    if count and (best is None or key > best[0]):
                        best = (key, k, folds)
        if best is None or best[0][0] == 0:
            gi = ready[0]
            ops.append(("apply", glist[gi].targets, glist[gi].mat))
            advance(gi, heads, ready)
            continue
        _, k, folds = best
        acc = _WinAcc(k)
        for gi in folds:
            c = classify(glist[gi].targets, k)
            if c[0] == "X":
                if gdiag4[gi] is not None:
                    acc.fold_mask(c[1], c[2], gdiag4[gi], c[3])
                else:
                    acc.fold_cross(c[1], c[2], glist[gi].mat, c[3])
            else:
                acc.fold_side(c[0], c[1], glist[gi].mat)
            advance(gi, heads, ready)
        a, b = acc.stacks()
        ops.append(("winfused", k, a, b, acc.a_used, acc.b_used,
                    acc.mask_soa()))
    return ops


# ---------------------------------------------------------------------------
# Sharded-register relocalization pass: communication at WINDOW granularity
# ---------------------------------------------------------------------------


_REMAP_LOOKAHEAD = 256  # next-use horizon for the eviction choice


def remap_exchange_bytes(sigma: Tuple[int, ...], num_qubits: int, nloc: int,
                         itemsize: int = 8) -> int:
    """ICI bytes ONE shard exchanges executing the batched remap ``sigma``
    — the scheduling-layer cost model for a window relocalization: each
    mixed local<->mesh transposition moves half the shard
    (dist._swap_halves_in_shard), a residual composed mesh permutation
    moves the whole shard, and the per-shard axis permutation moves
    nothing over ICI.  Used by bench_suite config 7's exchange-volume
    accounting and by the pipelined-exchange tests to size the expected
    chunk payloads (each listed payload is what dist.exchange_chunks
    splits)."""
    from .parallel import dist as PAR

    r = num_qubits - nloc
    mixed, _local_perm, mesh_tau = PAR.decompose_sigma(sigma, nloc, r)
    shard = 2 * (1 << nloc) * itemsize          # SoA: re + im planes
    total = len(mixed) * (shard // 2)
    if mesh_tau is not None:
        total += shard
    return total


def remap_exchange_bytes_tiers(sigma: Tuple[int, ...], num_qubits: int,
                               nloc: int, itemsize: int = 8,
                               topology=None) -> Dict[str, int]:
    """Per-interconnect-tier split of :func:`remap_exchange_bytes` —
    ``{"ici": bytes, "dcn": bytes}`` summing exactly to the flat total
    (dist.remap_exchange_tiers on the byte axis).  Feeds the per-tier
    columns of introspect.explain_circuit, the governor's weighted drain
    cost and scripts/bench_pod.py's modeled A/B gate."""
    from .parallel import dist as PAR

    r = num_qubits - nloc
    tiers = PAR.remap_exchange_tiers(sigma, nloc, r, itemsize, topology)
    return {tier: b for tier, (_c, b) in tiers.items()}


def plan_remap_windows(bit_sets: Sequence[Tuple[int, ...]], num_qubits: int,
                       nloc: int, perm=None):
    """Relocalization pass for a SHARDED register: group a LOGICAL item
    stream (``bit_sets[i]`` = state-vector bits item i touches) into
    windows whose cumulative distinct-qubit set fits the shard-local space,
    and schedule ONE batched remap per window instead of two half-shard
    exchanges per sharded-target gate (the reference's per-gate scheme,
    QuEST_cpu_distributed.c:1447-1545; window-level reordering is the
    mpiQulacs / qHiPSTER communication-avoidance design,
    arXiv:2203.16044 / arXiv:1601.07195).

    Crucially the permutation is NOT undone between windows: it persists
    into ``final_perm`` (carried by Qureg._perm across drains) and
    canonical order only rematerializes on a state read.

    Returns (segments, final_perm) with segments =
    [((start, end), sigma | None, perm_during_window), ...]: apply the
    physical permutation ``sigma`` (dist.remap_sharded /
    dist._remap_in_shard), then run items [start, end) with their bits
    rewritten through ``perm_during_window``.

    Raises ValueError when a single item touches more than ``nloc``
    distinct qubits — no permutation can localize it (callers fall back
    to the per-gate explicit path; the reference instead REJECTS such
    ops, QuEST_validation.c:469-471)."""
    from .parallel import dist as PAR

    n = num_qubits
    perm = tuple(perm) if perm is not None else tuple(range(n))
    segments: List[tuple] = []
    i = 0
    total = len(bit_sets)
    while i < total:
        if _is_relabel_entry(bit_sets[i]):
            # permutation fold: a run of relabel-tagged items composes
            # straight into the live logical->physical permutation — no
            # sigma, no data motion; the composed exchange (if any) is
            # deferred to the next canonical read like every other perm
            j = i
            while j < total and _is_relabel_entry(bit_sets[j]):
                rho = dict(bit_sets[j][1])
                perm = tuple(perm[rho.get(q, q)] for q in range(n))
                j += 1
            segments.append(((i, j), None, perm))
            i = j
            continue
        w: set = set()
        j = i
        while j < total:
            if _is_relabel_entry(bit_sets[j]):
                break
            b = set(bit_sets[j])
            if len(w | b) > nloc:
                break
            w |= b
            j += 1
        if j == i:
            raise ValueError(
                f"plan_remap_windows: item {i} touches {len(set(bit_sets[i]))}"
                f" qubits but only {nloc} can be shard-local")
        # next-use distances over the remaining stream: evict the local
        # residents needed furthest in the future (capped horizon, same
        # policy as the paged planner's eviction choice)
        next_use: dict = {}
        d = 0
        for k in range(j, min(total, j + _REMAP_LOOKAHEAD)):
            if _is_relabel_entry(bit_sets[k]):
                continue
            for q in bit_sets[k]:
                if q not in next_use:
                    next_use[q] = d
                d += 1
        sigma, new_perm = PAR.plan_window_remap(
            n, nloc, perm, sorted(w), next_use)
        assert new_perm is not None  # |w| <= nloc makes the remap feasible
        perm = new_perm
        segments.append(((i, j), sigma, perm))
        i = j
    return segments, perm


def execute_plan(amps, ops: Sequence[tuple], num_qubits: int,
                 interpret: Optional[bool] = None,
                 precision: Optional[str] = None):
    n = num_qubits
    # resolve the config at trace time so callers caching compiled plans can
    # key on fused.matmul_precision_name()
    precision = precision or fused.matmul_precision_name()
    for op in ops:
        if op[0] == "fused":
            amps = fused.apply_cluster_stack(
                amps, jnp.asarray(op[1], amps.dtype), jnp.asarray(op[2], amps.dtype),
                num_qubits=n, interpret=interpret, precision=precision,
            )
        elif op[0] == "apply":
            amps = kernels.apply_matrix(
                amps, jnp.asarray(op[2], amps.dtype), num_qubits=n,
                targets=tuple(op[1]),
            )
        elif op[0] == "segswap":
            amps = kernels.swap_bit_segments(
                amps, num_qubits=n, a=op[1], b=op[2], m=op[3]
            )
        elif op[0] == "swapfused":
            amps = fused.apply_swap_cluster_stack(
                amps, jnp.asarray(op[4], amps.dtype),
                jnp.asarray(op[5], amps.dtype),
                num_qubits=n, h=op[1], b=op[2], m=op[3],
                interpret=interpret, precision=precision,
            )
        elif op[0] == "winfused":
            mask = op[6] if len(op) > 6 else None
            amps = fused.apply_window_stack(
                amps, jnp.asarray(op[2], amps.dtype),
                jnp.asarray(op[3], amps.dtype),
                mask=None if mask is None else jnp.asarray(mask, amps.dtype),
                num_qubits=n, k=op[1], apply_a=op[4], apply_b=op[5],
                interpret=interpret, precision=precision,
            )
        elif op[0] == "megawin":
            # §29: the fused route when executable on this backend/dtype;
            # otherwise decompose to the bit-identical per-pass sequence
            # (the megakernel fallback ladder's bottom rung)
            if fused.megakernel_executable(amps.dtype):
                amps = fused.apply_window_megastack(
                    amps, op[1], num_qubits=n, interpret=interpret,
                    precision=precision,
                )
            else:
                amps = execute_plan(amps, op[1], n, interpret=interpret,
                                    precision=precision)
        elif op[0] == "permute":
            amps = kernels.permute_qubits(amps, num_qubits=n, perm=op[1])
        elif op[0] == "xor":
            amps = kernels.apply_multi_qubit_not(
                amps, num_qubits=n, targets=tuple(op[1]))
        elif op[0] == "gatherperm":
            amps = kernels.apply_index_permutation(
                amps, num_qubits=n, targets=tuple(op[1]), pi=tuple(op[2]))
        elif op[0] == "sigma_swap":
            from .ops import bigstate
            amps = bigstate.apply_sigma_swap(
                amps, num_qubits=n, group_bits=op[1], interpret=interpret)
        else:  # pragma: no cover
            raise ValueError(f"unknown op {op[0]}")
    return amps


def plan_checkpoint_boundaries(num_gates: int, every: int,
                               start: int = 0) -> List[int]:
    """Gate cursors where a resumable run may checkpoint: every ``every``
    gates plus the stream end.  Boundaries fall BETWEEN fusion drains —
    the resilience driver (resilience.run_resumable) opens one fusion
    window per [boundary, boundary) span, so a checkpoint never lands
    mid-window and an interrupted run re-plans the identical window
    sequence on resume (same spans -> same plan-cache keys -> bit-exact
    replay)."""
    if every < 1:
        raise ValueError("plan_checkpoint_boundaries: every must be >= 1")
    out = list(range(start + every, num_gates, every))
    if num_gates > start:
        out.append(num_gates)
    return out


def apply_circuit(amps, gates: Sequence[Gate], num_qubits: int,
                  interpret: Optional[bool] = None):
    """Plan + execute in one call (both happen at trace time under jit)."""
    return execute_plan(amps, plan_circuit(gates, num_qubits), num_qubits,
                        interpret=interpret)


# ---------------------------------------------------------------------------
# Chained per-pass execution: many small cached programs, canonical layout
# ---------------------------------------------------------------------------


def canonical_view(amps, num_qubits: int):
    """The state in its canonical tiled view (2, 2^(n-14), 128, 128) —
    sublanes = amp bits [7,14), lanes = bits [0,7).  All per-pass kernels
    accept and return this shape, and a jit parameter of this shape gets
    the same T(8,128) device layout the kernel views use, so every jit
    boundary is a free bitcast.  A flat (2, 2^n) parameter instead carries
    a different layout and XLA inserts a FULL-STATE copy at the program
    boundary — 537 MB at 26q, 8 GB at 30q (the round-2 OOM that blocked
    the 30-qubit benchmark)."""
    if num_qubits < WINDOW:
        return amps
    return amps.reshape(2, 1 << (num_qubits - WINDOW), DIM, DIM)


def plan_to_device(ops: Sequence[tuple], dtype) -> List[tuple]:
    """Upload every concrete pass operand once (numpy -> device array) so a
    chained executor does not re-transfer matrices on every call."""
    out: List[tuple] = []
    for op in ops:
        if op[0] in ("winfused",):
            mask = op[6] if len(op) > 6 else None
            out.append(("winfused", op[1], jnp.asarray(op[2], dtype),
                        jnp.asarray(op[3], dtype), op[4], op[5],
                        None if mask is None else jnp.asarray(mask, dtype)))
        elif op[0] == "megawin":
            out.append(("megawin", tuple(plan_to_device(op[1], dtype))))
        elif op[0] == "fused":
            out.append(("fused", jnp.asarray(op[1], dtype),
                        jnp.asarray(op[2], dtype)))
        elif op[0] == "swapfused":
            out.append(("swapfused", op[1], op[2], op[3],
                        jnp.asarray(op[4], dtype), jnp.asarray(op[5], dtype)))
        elif op[0] == "apply":
            out.append(("apply", op[1], jnp.asarray(op[2], dtype)))
        else:
            out.append(op)
    return out


def execute_plan_chained(amps, ops: Sequence[tuple], num_qubits: int,
                         precision: Optional[str] = None):
    """Execute a plan as a CHAIN of per-pass cached jits (eager dispatch)
    instead of one monolithic traced program.

    Why this exists: tracing a whole 28-30q circuit into one XLA program
    costs 7-14 minutes of AOT compile and, at 30q, an OOM (see
    canonical_view).  Each pass here is its own tiny jitted program —
    compiled once per distinct (kernel, k, rank, flags) signature in ~2 s,
    reused across the whole circuit and across sizes with the same
    signature.  Dispatch is async, so the host enqueues passes while the
    device works; measured per-pass device time at 26q matches the HBM
    floor (~1.3 ms), i.e. chaining costs nothing over the monolithic
    program.  The state must be (and stays) in the canonical view.

    This is the executor the 30q+ benchmark sizes use; the reference's
    whole distributed design exists to reach those sizes
    (QuEST/include/QuEST.h:463-479).
    """
    n = num_qubits
    amps = canonical_view(amps, n)
    return execute_plan(amps, ops, n, precision=precision)


def stats(ops: Sequence[tuple]) -> dict:
    """Pass-count accounting for logging/benchmark output."""
    from collections import Counter

    c = Counter(op[0] for op in ops)
    return {"fused": c.get("fused", 0), "swapfused": c.get("swapfused", 0),
            "winfused": c.get("winfused", 0),
            "megawin": c.get("megawin", 0),
            "megawin_ops": sum(len(op[1]) for op in ops
                               if op[0] == "megawin"),
            "apply": c.get("apply", 0), "segswap": c.get("segswap", 0),
            "permute": c.get("permute", 0),
            "xor": c.get("xor", 0),
            "gatherperm": c.get("gatherperm", 0),
            "sigma_swap": c.get("sigma_swap", 0),
            "total_passes": sum(c.values())}


# ---------------------------------------------------------------------------
# Fused QFT: ladder passes + one scheduled low-qubit pass + one permute
# ---------------------------------------------------------------------------


def _qft_layer_dense(tr: int, conj: bool, dt) -> np.ndarray:
    """Dense matrix of one low QFT layer on tr+1 contiguous qubits (matrix
    bit tr = the layer target): Hadamard on the target followed by the
    controlled-phase ladder diag(1, e^{i*pi*low/2^tr}) against the lower
    bits."""
    d = 1 << tr
    low = np.arange(d)
    sgn = -1.0 if conj else 1.0
    ph = np.exp(sgn * 1j * np.pi * low / d)
    inv = 1.0 / math.sqrt(2.0)
    m = np.zeros((2 * d, 2 * d), complex)
    m[low, low] = inv
    m[low, d + low] = inv
    m[d + low, low] = inv * ph
    m[d + low, d + low] = -inv * ph
    return np.stack([m.real, m.imag]).astype(dt)


def fused_qft(amps, num_qubits: int, start: int, count: int,
              shifts: Sequence[int] = (0,),
              interpret: Optional[bool] = None,
              conj_first: bool = False):
    """QFT on the contiguous qubits [start, start+count) — plus a
    conjugated twin per extra entry of ``shifts`` (the density-matrix bra
    half) — as:

      * one fused elementwise ladder pass per high layer
        (kernels.apply_qft_ladder: Hadamard + whole controlled-phase
        ladder, ONE HBM sweep each),
      * the <= 7-qubit low layers folded by the windowed scheduler
        (typically one pass),
      * the final swap network of ALL halves as ONE bit-reversal axis
        permutation.

    vs the reference's per-layer dispatch (agnostic_applyQFT,
    QuEST_common.c:836-898): ~n+2 passes instead of ~2.5n.  Requires
    start == 0 or start >= 7 (layout-safe ladder views) — callers fall
    back to the layered path otherwise."""
    from .ops import kernels as K

    n = num_qubits
    if not (start == 0 or start >= LANE):
        raise ValueError("fused_qft needs start == 0 or start >= 7")
    dt = np.float64 if amps.dtype == jnp.float64 else np.float32
    if (start == 0 and tuple(shifts) == (0,) and count >= 15
            and fused.qft_multilayer_enabled(amps.dtype)):
        return _fused_qft_multilayer(amps, n, count, interpret,
                                     conj=conj_first)
    dense_gates: List[Gate] = []
    for si, sh in enumerate(shifts):
        conj = si > 0 or conj_first
        base = start + sh
        for qq in range(count - 1, -1, -1):
            if qq >= LANE:
                amps = K.apply_qft_ladder(
                    amps, num_qubits=n, target=base + qq, base=base,
                    conj=conj)
            else:
                dense_gates.append(Gate(
                    tuple(range(base, base + qq + 1)),
                    _qft_layer_dense(qq, conj, dt)))
    if dense_gates:
        amps = execute_plan(amps, plan_circuit(dense_gates, n), n,
                            interpret=interpret)
    runs = [(start + sh, count) for sh in shifts]
    rev_ops = bit_reversal_ops(n, runs, dt)
    if rev_ops is None:
        perm = list(range(n))
        for b, c in runs:
            for i in range(c // 2):
                perm[b + i], perm[b + c - 1 - i] = (
                    perm[b + c - 1 - i], perm[b + i])
        rev_ops = [("permute", tuple(perm))] if perm != list(range(n)) else []
    amps = execute_plan(amps, rev_ops, n, interpret=interpret)
    return amps


def _fused_qft_multilayer(amps, n: int, count: int,
                          interpret: Optional[bool], conj: bool = False):
    """Radix-2^k QFT (full or [0, count) run of a statevector register):

      * layers t >= 14 in chunks of QT_QFT_RADIX (default 4) per HBM
        sweep (fused.apply_qft_multi_hi — pair bits co-resident in VMEM,
        classic high-radix FFT blocking),
      * ALL seven sublane layers (t = 13..7) as ONE sweep
        (fused.apply_qft_cluster_multi),
      * the seven lane layers (t = 6..0) FOLDED with the lane+sublane
        within-group bit reversals into a single dense window pass,
      * then only the high-group reversal passes and the group-order
        permute remain from bit_reversal_ops(skip_low_group=True) — the
        merged lane+sublane reversal pass it would normally emit first is
        the fold above.

    Pass count at 26q: 3 + 1 + 1 + 3 = 8 vs the per-layer path's 24; the
    reference's per-gate dispatch is ~2.5n sweeps (agnostic_applyQFT,
    QuEST_common.c:836-898)."""
    dt = np.float64 if amps.dtype == jnp.float64 else np.float32
    amps = fused.apply_qft_multilayer_ladders(
        amps, num_qubits=n, t_top=count - 1, conj=conj, interpret=interpret)
    dense_gates = [Gate(tuple(range(qq + 1)), _qft_layer_dense(qq, conj, dt))
                   for qq in range(LANE - 1, -1, -1)]
    rev7 = _rev_perm_mat(LANE, dt)
    dense_gates.append(Gate(tuple(range(LANE)), rev7))
    dense_gates.append(Gate(tuple(range(LANE, 2 * LANE)), rev7))
    ops = plan_circuit(dense_gates, n)
    rev_ops = bit_reversal_ops(n, [(0, count)], dt, skip_low_group=True)
    return execute_plan(amps, list(ops) + rev_ops, n, interpret=interpret)


# ---------------------------------------------------------------------------
# Fast bit reversal: group decomposition instead of one all-axes transpose
# ---------------------------------------------------------------------------


def _rev_perm_mat(bits: int, dt, off: int = 0) -> np.ndarray:
    """SoA 128x128 permutation matrix reversing bits [off, off+bits) of a
    7-bit cluster index (other bits untouched)."""
    d = 1 << LANE
    mask = ((1 << bits) - 1) << off
    m = np.zeros((d, d))
    for i in range(d):
        seg = (i & mask) >> off
        rev = int(format(seg, f"0{bits}b")[::-1], 2) if bits else 0
        m[(i & ~mask) | (rev << off), i] = 1.0
    return np.stack([m, np.zeros((d, d))]).astype(dt)


def _bit_reversal_big(n: int, dt, skip_low_group: bool = False) -> List[tuple]:
    """Bit reversal of the FULL state without any out-of-place transpose:
    rev[0,n) = (within-group reversals, in-place window passes) o sigma
    for the palindromic group split (7, 7, n-28, 7, 7), where sigma (swap
    bits [0,7)<->[n-7,n) and [7,14)<->[n-14,n-7)) runs as the in-place
    block-pair DMA kernel (ops/bigstate.py).  At 30q a full-state XLA
    transpose OOMs (8 GB state + 8 GB output > 15.75 GB HBM); this path
    is 5 in-place passes."""
    r = n - 28
    ops: List[tuple] = []
    rev7 = jnp.asarray(_rev_perm_mat(LANE, dt))
    eye = jnp.asarray(_eye_cluster(), rev7.dtype)
    if not skip_low_group:
        ops.append(("winfused", LANE, rev7[None], rev7[None], True, True))
    if r:
        m = jnp.asarray(_rev_perm_mat(r, dt, off=0))
        ops.append(("winfused", WINDOW, eye[None], m[None], False, True))
    for k in (WINDOW + r, n - LANE):
        ops.append(("winfused", k, eye[None], rev7[None], False, True))
    ops.append(("sigma_swap", LANE))
    return ops


def bit_reversal_ops(n: int, runs: Sequence[Tuple[int, int]],
                     dt, skip_low_group: bool = False
                     ) -> Optional[List[tuple]]:
    """Ops reversing the qubit order of each contiguous run
    (start, count), or None when no fast decomposition applies.

    One all-axes-reversed transpose is pathological for XLA — no adjacent
    axes merge (measured 426 ms / 2.5 GB/s at 26 qubits).  Instead each
    run splits into 7-bit groups: rev(run) = (reverse the ORDER of the
    groups) o (reverse WITHIN each group).  The within-group reversals are
    window-pass permutation matrices at the groups' original positions
    (the lane group rides the A side of the first window pass), and the
    group-order reversal of ALL runs is ONE axis permutation whose long
    order-preserving segments XLA transposes at near copy speed.

    Full-state runs at n >= 30 take the in-place palindromic path
    instead (_bit_reversal_big): the XLA transpose needs a second
    full-state buffer, which no longer fits in HBM there.

    ``skip_low_group=True`` omits the merged lane+sublane within-group
    reversal pass (the caller folds those two rev7 matrices into its own
    dense window pass — circuit._fused_qft_multilayer); it requires a
    single run starting at 0 with two full 7-bit low groups."""
    if skip_low_group and not (
            len(runs) == 1 and runs[0][0] == 0 and runs[0][1] >= 14):
        raise ValueError("skip_low_group needs one run = (0, count >= 14)")
    if (len(runs) == 1 and runs[0] == (0, n) and 30 <= n < 35
            and np.dtype(dt) == np.float32
            and not fused._interpret_default()):
        return _bit_reversal_big(n, dt, skip_low_group=skip_low_group)
    ops: List[tuple] = []
    perm = list(range(n))
    eye = None
    for start, count in runs:
        if count <= 1:
            continue
        if not (start == 0 or start >= LANE):
            return None
        groups = []
        o = start
        while o < start + count:
            sz = min(LANE, start + count - o)
            groups.append((o, sz))
            o += sz
        # within-group reversal passes (merge the lane group into the
        # second group's window pass when both exist)
        i0 = 0
        if groups[0][0] == 0:
            if len(groups) > 1 and groups[1][1] > 1:
                if skip_low_group:
                    i0 = 2   # caller folds both low-group reversals
                else:
                    a_mat = jnp.asarray(_rev_perm_mat(groups[0][1], dt))
                    o1, sz1 = groups[1]
                    k1 = min(o1, n - LANE)
                    b_mat = jnp.asarray(_rev_perm_mat(sz1, dt, off=o1 - k1))
                    ops.append(("winfused", k1, a_mat[None],
                                b_mat[None], True, True))
                    i0 = 2
            else:
                a_mat = jnp.asarray(_rev_perm_mat(groups[0][1], dt))
                eye = jnp.asarray(_eye_cluster(), a_mat.dtype) if eye is None else eye
                ops.append(("winfused", LANE, a_mat[None], eye[None],
                            True, False))
                i0 = 1
        for o, sz in groups[i0:]:
            if sz <= 1:
                continue
            k = min(o, n - LANE)
            b_mat = jnp.asarray(_rev_perm_mat(sz, dt, off=o - k))
            eye = jnp.asarray(_eye_cluster(), b_mat.dtype) if eye is None else eye
            ops.append(("winfused", k, eye[None], b_mat[None], False, True))
        # group-order reversal: new offset of group i = start + total size
        # of the groups after it (order-preserving within groups)
        off = start
        for o, sz in reversed(groups):
            for j in range(sz):
                perm[off + j] = o + j
            off += sz
    if perm != list(range(n)):
        ops.append(("permute", tuple(perm)))
    return ops


# ---------------------------------------------------------------------------
# Plan (de)composition: static skeleton + array operands
# ---------------------------------------------------------------------------


def split_plan(ops: Sequence[tuple]):
    """(hashable skeleton, array list): separates an executable plan into
    its static structure and its array operands so callers can jit (and
    cache) an executor keyed on the skeleton while the matrices stay
    traced arguments (fusion drains, sharded executors)."""
    skeleton: List[tuple] = []
    arrays: List[object] = []
    for op in ops:
        if op[0] == "winfused":
            mask = op[6] if len(op) > 6 else None
            skeleton.append(("winfused", op[1], tuple(np.shape(op[2])),
                             op[4], op[5], mask is not None))
            arrays.extend([op[2], op[3]])
            if mask is not None:
                arrays.append(mask)
        elif op[0] == "megawin":
            sub_sk, sub_arrays = split_plan(op[1])
            skeleton.append(("megawin", sub_sk))
            arrays.extend(sub_arrays)
        elif op[0] == "apply":
            skeleton.append(("apply", tuple(op[1]), tuple(np.shape(op[2]))))
            arrays.append(op[2])
        elif op[0] == "fused":
            skeleton.append(("fused", tuple(np.shape(op[1]))))
            arrays.extend([op[1], op[2]])
        elif op[0] == "swapfused":
            skeleton.append(("swapfused", op[1], op[2], op[3],
                             tuple(np.shape(op[4]))))
            arrays.extend([op[4], op[5]])
        else:  # segswap / permute: fully static
            skeleton.append(tuple(op))
    return tuple(skeleton), arrays


def rebuild_plan(skeleton: Sequence[tuple], arrays: Sequence) -> List[tuple]:
    """Inverse of split_plan given the (possibly traced) array operands."""
    return _rebuild_plan_iter(skeleton, iter(arrays))


def _rebuild_plan_iter(skeleton: Sequence[tuple], it) -> List[tuple]:
    ops: List[tuple] = []
    for sk in skeleton:
        if sk[0] == "winfused":
            a, b = next(it), next(it)
            mask = next(it) if len(sk) > 5 and sk[5] else None
            ops.append(("winfused", sk[1], a, b, sk[3], sk[4], mask))
        elif sk[0] == "megawin":
            ops.append(("megawin", tuple(_rebuild_plan_iter(sk[1], it))))
        elif sk[0] == "apply":
            ops.append(("apply", sk[1], next(it)))
        elif sk[0] == "fused":
            ops.append(("fused", next(it), next(it)))
        elif sk[0] == "swapfused":
            a, b = next(it), next(it)
            ops.append(("swapfused", sk[1], sk[2], sk[3], a, b))
        else:
            ops.append(sk)
    return ops
