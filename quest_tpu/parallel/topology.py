"""Hierarchical DCN x ICI device topology: the 2-level mesh model.

The flat 1-D amplitude mesh (dist.py, docs/design.md §12) treats every
inter-shard hop as equal.  Real pods are hierarchical: chips within a
host talk over ICI (fast, ~100s of GB/s per link), hosts talk over DCN
(slow, ~10s of GB/s per NIC) — and both qHiPSTER (arXiv:1601.07195) and
mpiQulacs (arXiv:2203.16044) attribute large-simulator scale to a
communication layer that distinguishes the two.  This module is that
layer's MODEL: it never issues a collective (qlint confines those to
dist.py) and never touches jax — it only classifies WHERE bytes move.

Mapping onto the amplitude mesh: with ``2^r`` devices arranged as
``hosts x chips`` (both powers of two, ``hosts * chips = 2^r``), device
``i`` is chip ``i % chips`` of host ``i // chips``.  Mesh-coordinate
bit ``b`` (state-vector qubit ``nloc + b``) is therefore an **ICI bit**
when ``b < log2(chips)`` — its XOR partner lives on the same host — and
a **DCN bit** otherwise.  An exchange program's tier:

* XOR-partner hop on mesh bit ``b``  -> ``tier_of_bit(b)``;
* composed shard-index permutation   -> DCN iff any moved pair crosses
  a host boundary (``tier_of_pair``);
* HLO ``collective-permute`` pair    -> DCN iff ``src ^ dst >= chips``
  (the classification hlocheck.py pins against compiled programs).

Emulation: ``QT_TOPOLOGY=HxC`` forces an H-host x C-chip arrangement on
any backend (the CPU test meshes use ``2x4`` over the 8 emulated
devices).  A spec that does not factor the live device count is
silently ignored (fallback: one host — every bit ICI, byte-identical to
the flat model), which is what makes elastic failover onto a smaller
mesh well-defined while the env var still says the old shape.

Per-tier bandwidth weights (``QT_TIER_WEIGHT_ICI`` /
``QT_TIER_WEIGHT_DCN``, defaults 1 / 8 — the ~8x ICI:DCN bandwidth
ratio of current TPU pods) feed the remap planner's eviction choice
(dist.plan_window_remap keeps hot qubits on intra-host axes), the
weighted cost totals in introspect.explain_circuit, and the A/B gate in
scripts/bench_pod.py.  ``QT_TOPOLOGY_PLANNER=flat`` disables the
tier-aware planning (keeping classification + accounting) for A/B runs;
results are bit-identical either way — topology only changes where
bytes move, never what is computed.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

TOPOLOGY_ENV = "QT_TOPOLOGY"
PLANNER_ENV = "QT_TOPOLOGY_PLANNER"          # "hier" (default) | "flat"
WEIGHT_ICI_ENV = "QT_TIER_WEIGHT_ICI"
WEIGHT_DCN_ENV = "QT_TIER_WEIGHT_DCN"

TIERS = ("ici", "dcn")

# default ICI:DCN bandwidth ratio — v5e-class ICI (~400 GB/s/chip
# aggregate) vs per-host DCN (~50 GB/s): one DCN byte costs ~8 ICI bytes
DEFAULT_TIER_WEIGHTS = {"ici": 1.0, "dcn": 8.0}


def _is_pow2(x: int) -> bool:
    return x >= 1 and (x & (x - 1)) == 0


@dataclass(frozen=True)
class Topology:
    """An ``hosts x chips`` arrangement of the amplitude mesh.

    ``hosts == 1`` is the flat (single-host) model every pre-topology
    code path assumed: all mesh bits ICI, all classification trivially
    "ici" — so the default topology changes nothing."""

    hosts: int
    chips: int

    def __post_init__(self):
        if not _is_pow2(self.hosts) or not _is_pow2(self.chips):
            raise ValueError(
                f"Topology: hosts={self.hosts} chips={self.chips} must "
                f"both be powers of two")

    @property
    def num_devices(self) -> int:
        return self.hosts * self.chips

    @property
    def ici_bits(self) -> int:
        """Mesh-coordinate bits addressing the chip within a host."""
        return int(math.log2(self.chips))

    @property
    def dcn_bits(self) -> int:
        """Mesh-coordinate bits addressing the host."""
        return int(math.log2(self.hosts))

    def tier_of_bit(self, mesh_bit: int) -> str:
        """Tier of an XOR-partner exchange on mesh-coordinate bit
        ``mesh_bit`` (state-vector qubit ``nloc + mesh_bit``)."""
        return "ici" if mesh_bit < self.ici_bits else "dcn"

    def tier_of_mask(self, xor_mask: int) -> str:
        """Tier of a composed XOR hop (e.g. the double-flip pair-channel
        partner): DCN iff any flipped bit addresses the host."""
        return "dcn" if (xor_mask >> self.ici_bits) else "ici"

    def tier_of_pair(self, src: int, dst: int) -> str:
        """Tier of one ``collective-permute`` source-target pair — the
        classification hlocheck.py applies to compiled HLO."""
        return self.tier_of_mask(src ^ dst)

    def host_of(self, shard: int) -> int:
        return shard // self.chips

    def host_range(self, host: int) -> range:
        """Device indices belonging to ``host``."""
        return range(host * self.chips, (host + 1) * self.chips)

    def describe(self) -> str:
        """``HxC (ici=a, dcn=b)`` — the getEnvironmentString line body."""
        return (f"{self.hosts}x{self.chips} "
                f"(ici={self.ici_bits}, dcn={self.dcn_bits})")

    def signature(self) -> Tuple:
        """Hashable planning-relevant identity — a cache-key component
        for plans/predictions that depend on the topology (fusion's plan
        cache, introspect's prediction cache)."""
        w = tier_weights()
        return (self.hosts, self.chips, planner_mode(), w["ici"], w["dcn"])


def parse_spec(spec: Optional[str]) -> Optional[Tuple[int, int]]:
    """``"HxC"`` (also ``H×C``) -> (hosts, chips); None / unparseable ->
    None.  Validation against the live device count happens in
    :func:`resolve` — a non-factoring spec falls back to single-host."""
    if not spec:
        return None
    raw = str(spec).strip().lower().replace("×", "x")
    if raw.count("x") != 1:
        return None
    h, _, c = raw.partition("x")
    try:
        hosts, chips = int(h), int(c)
    except ValueError:
        return None
    if hosts < 1 or chips < 1:
        return None
    return hosts, chips


def planner_mode() -> str:
    """``"hier"`` (tier-aware remap planning, the default) or ``"flat"``
    (classification + accounting only — the A/B baseline)."""
    raw = os.environ.get(PLANNER_ENV, "hier").strip().lower()
    return "flat" if raw == "flat" else "hier"


def tier_weights() -> Dict[str, float]:
    """Relative per-byte cost of each tier (higher = slower link)."""
    out = dict(DEFAULT_TIER_WEIGHTS)
    for tier, env in (("ici", WEIGHT_ICI_ENV), ("dcn", WEIGHT_DCN_ENV)):
        raw = os.environ.get(env)
        if raw:
            try:
                v = float(raw)
            except ValueError:
                continue
            if v > 0:
                out[tier] = v
    return out


def resolve(num_devices: int, spec: Optional[str] = None) -> Topology:
    """The live topology for a ``num_devices``-shard amplitude mesh:
    ``spec`` (default ``$QT_TOPOLOGY``) when it exactly factors the
    device count into power-of-two hosts x chips, else the flat
    single-host arrangement.  The silent fallback is load-bearing for
    elastic failover: after a host loss the mesh is smaller than the
    spec describes, and the survivors must keep classifying consistently
    (see env.shrink_env / resilience._failover)."""
    ndev = max(1, int(num_devices))
    if spec is None:
        spec = os.environ.get(TOPOLOGY_ENV)
    parsed = parse_spec(spec)
    if parsed is not None:
        hosts, chips = parsed
        if _is_pow2(hosts) and _is_pow2(chips) and hosts * chips == ndev:
            return Topology(hosts, chips)
    return Topology(1, ndev)


def shrink(topo: Optional[Topology], num_devices: int) -> Topology:
    """Topology of a degraded mesh: keep the chips-per-host arrangement
    when the survivor count is a whole number of hosts (a host loss:
    ``2x4 -> 1x4``), else collapse to single-host (a sub-host shrink has
    no cross-host axis left worth modeling)."""
    ndev = max(1, int(num_devices))
    if topo is not None and topo.chips <= ndev and ndev % topo.chips == 0:
        hosts = ndev // topo.chips
        if _is_pow2(hosts):
            return Topology(hosts, topo.chips)
    return Topology(1, ndev)


def grow(topo: Optional[Topology], num_devices: int) -> Topology:
    """Topology of a HEALED mesh — the inverse of :func:`shrink` for the
    serving layer's mesh-heal path (serve.SimServer.heal).  The spec
    (``QT_TOPOLOGY``) wins when it factors the recovered device count —
    healing restores the arrangement the operator declared (``1x4`` back
    to ``2x4``); otherwise re-host the surviving chips-per-host shape."""
    ndev = max(1, int(num_devices))
    spec_topo = resolve(ndev)
    if spec_topo.hosts > 1 or topo is None:
        return spec_topo
    if topo.chips <= ndev and ndev % topo.chips == 0 \
            and _is_pow2(ndev // topo.chips):
        return Topology(ndev // topo.chips, topo.chips)
    return spec_topo


# ---------------------------------------------------------------------------
# Mesh loss/heal notification hooks
# ---------------------------------------------------------------------------

# Subsystems whose cached state depends on the live mesh shape register a
# callback here: the serving layer hooks the memory governor's budget
# re-derivation, dist.guarded_dispatch announces a declared shard/host
# loss the instant it raises ShardLossError, and serve.SimServer
# announces failover/heal after swapping its environment.  Callbacks take
# ``(event: str, info: dict)``; an exception inside one is swallowed with
# a warning — a notification fan-out that can fail would turn an
# already-degraded moment into a crash.
MESH_EVENT_LISTENERS: list = []


def add_mesh_listener(cb) -> None:
    if cb not in MESH_EVENT_LISTENERS:
        MESH_EVENT_LISTENERS.append(cb)


def remove_mesh_listener(cb) -> None:
    try:
        MESH_EVENT_LISTENERS.remove(cb)
    except ValueError:
        pass


def notify_mesh_event(event: str, **info) -> None:
    """Fan ``event`` ("shard_loss" / "host_loss" / "serve_failover" /
    "serve_heal") out to every registered listener."""
    import warnings

    for cb in list(MESH_EVENT_LISTENERS):
        try:
            cb(event, dict(info))
        except Exception as e:  # qlint: allow(broad-except): notification fan-out must never crash an already-degraded run
            warnings.warn(f"mesh-event listener failed on {event!r}: {e!r}",
                          RuntimeWarning, stacklevel=2)


def hierarchical_enabled(topo: Optional[Topology]) -> bool:
    """Whether tier-aware remap planning is active: a multi-host
    topology AND the planner not forced flat.  Single-host meshes always
    plan flat — bit-for-bit the pre-topology behaviour."""
    return (topo is not None and topo.dcn_bits > 0
            and planner_mode() == "hier")


def signature(num_devices: int) -> Tuple:
    """resolve(num_devices).signature() — the one call plan caches key
    on (fusion._plan_key, introspect._predict_cached)."""
    return resolve(num_devices).signature()


def split_pair_list(pairs, chips: int) -> Dict[str, int]:
    """Histogram of ``(src, dst)`` collective pairs by tier — the HLO
    ``source_target_pairs`` classifier (introspect.AuditReport
    .tier_counts / hlocheck's per-tier verification).  Self-pairs
    (src == dst) move nothing and are not counted."""
    chips = max(1, int(chips))
    out = {"ici": 0, "dcn": 0}
    for src, dst in pairs:
        if src == dst:
            continue
        out["dcn" if (src ^ dst) >= chips else "ici"] += 1
    return out
