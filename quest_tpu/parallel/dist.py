"""Explicit distributed kernels: shard_map + ppermute over the amplitude mesh.

TPU-native re-design of the reference's MPI orchestration layer
(QuEST/src/CPU/QuEST_cpu_distributed.c).  The state of n qubits is sharded
over a 1-D device mesh on its leading (most-significant) index bits: with
2^r devices, qubits 0..n-r-1 are *local* (inside each shard) and qubits
n-r..n-1 are *sharded* (their bit IS a mesh-coordinate bit) — exactly the
reference's chunkId scheme (QuEST.h:330-338).

Mapping of the reference's five MPI primitives (SURVEY.md §5.8):

- pairwise full-chunk ``MPI_Sendrecv`` with the XOR-partner rank
  (exchangeStateVectors, :489-517) -> ``lax.ppermute`` with the static
  hypercube permutation [(i, i ^ 2^b)];
- the locality predicate target < log2(chunkSize)
  (halfMatrixBlockFitsInChunk, :366-371) -> a Python-level static branch:
  local targets run the ordinary kernels un-communicated;
- SWAP-relocalization of multi-qubit ops (:1447-1545) -> half-shard
  ppermute swaps (``swap_sharded``) pulling high targets down to free low
  qubits, op applied locally, swaps undone;
- ``MPI_Allreduce`` (:35-117) -> ``lax.psum``;
- ``MPI_Bcast`` replication loops (:379-423) -> ``lax.all_gather``.

Two structural wins over the reference: no pairStateVec — the reference
permanently holds a 2x receive buffer (QuEST_cpu.c:1279-1315) while
ppermute's transient buffer exists only inside one fused program; and the
elementwise combine fuses with the communication epilogue under XLA instead
of being a second pass over memory.

These kernels are *compile-time* alternatives invoked by the API layer when
a gate touches sharded qubits (quest_tpu.api routes there); the GSPMD path
(plain jit + sharding propagation) remains available via
``use_explicit_dist(False)`` for benchmarking one against the other
(SURVEY.md §7 layer 5 calls for exactly this comparison).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from ..env import AMP_AXIS
from ..ops import cplx, kernels

_CONFIG = {"explicit": True}


def use_explicit_dist(enabled: bool) -> None:
    """Toggle the explicit ppermute path vs GSPMD propagation."""
    _CONFIG["explicit"] = bool(enabled)


def explicit_dist_enabled() -> bool:
    return _CONFIG["explicit"]


def amp_axis_size(mesh: Mesh) -> int:
    """Size of the amplitude axis — NOT mesh.devices.size: meshes may carry
    extra axes (e.g. the (dp, amps) training mesh)."""
    return int(mesh.shape[AMP_AXIS])


def num_shard_bits(mesh: Mesh) -> int:
    return int(math.log2(amp_axis_size(mesh)))


def _hypercube_perm(ndev: int, bit: int):
    """Static XOR-partner permutation — the reference's pair-rank computation
    chunkId ^ (2^t / chunkSize) (QuEST_cpu_distributed.c:313-333) as a
    ppermute table."""
    return [(i, i ^ (1 << bit)) for i in range(ndev)]


def _shard_coeffs(rmat_like, mybit):
    """Per-shard gate coefficients a = m[b,b], b_coef = m[b,1-b] selected by
    the shard's target-bit value (statevec_compactUnitaryDistributed,
    QuEST_cpu.c:1841-1900 uses rankIsUpper the same way)."""
    row = mybit
    a_re = rmat_like[0, row, row]
    a_im = rmat_like[1, row, row]
    b_re = rmat_like[0, row, 1 - row]
    b_im = rmat_like[1, row, 1 - row]
    return a_re, a_im, b_re, b_im


@partial(
    jax.jit,
    static_argnames=("mesh", "num_qubits", "target", "controls", "control_states"),
    donate_argnums=0,
)
def apply_matrix_1q_sharded(
    amps,
    matrix,
    *,
    mesh: Mesh,
    num_qubits: int,
    target: int,
    controls: Tuple[int, ...] = (),
    control_states: Tuple[int, ...] = (),
):
    """One-qubit dense gate on a *sharded* target qubit: full-shard ppermute
    exchange + fused elementwise combine — the reference's non-local gate
    pattern (QuEST_cpu_distributed.c:854-928).

    Low (local) controls restrict the exchanged+combined sub-block; sharded
    controls become a per-shard mask (the reference instead skips ranks
    whose chunk fails the control condition, :1093-1112 — SPMD cannot skip,
    but masked shards do no extra communication since the exchange is
    collective anyway)."""
    ndev = amp_axis_size(mesh)
    r = num_shard_bits(mesh)
    n = num_qubits
    nloc = n - r
    assert target >= nloc, "local targets take the ordinary kernel"
    bit = target - nloc
    perm = _hypercube_perm(ndev, bit)

    states = control_states or (1,) * len(controls)
    local_controls = tuple((c, s) for c, s in zip(controls, states) if c < nloc)
    shard_controls = tuple((c - nloc, s) for c, s in zip(controls, states) if c >= nloc)

    def kernel(local, m):
        # local: (2, amps_per_shard); m: (2, 2, 2) stacked SoA
        idx = lax.axis_index(AMP_AXIS)
        mybit = (idx >> bit) & 1
        recv = lax.ppermute(local, AMP_AXIS, perm)
        a_re, a_im, b_re, b_im = _shard_coeffs(m, mybit)

        def combine(own_block, recv_block):
            return cplx.cmul(own_block, a_re, a_im) + cplx.cmul(recv_block, b_re, b_im)

        if local_controls:
            shape, sel = kernels._interleaved_sel(nloc, local_controls)
            lv = local.reshape(shape)
            rv = recv.reshape(shape)
            new = lv.at[sel].set(combine(lv[sel], rv[sel]))
            new = new.reshape(2, -1)
        else:
            new = combine(local, recv)
        for cbit, s in shard_controls:
            cond = ((idx >> cbit) & 1) == s
            new = jnp.where(cond, new, local)
        return new

    return shard_map(
        kernel,
        mesh=mesh,
        in_specs=(P(None, AMP_AXIS), P()),
        out_specs=P(None, AMP_AXIS),
    )(amps, jnp.asarray(matrix, amps.dtype))


@partial(jax.jit, static_argnames=("mesh", "num_qubits", "qb_low", "qb_high"), donate_argnums=0)
def swap_sharded(amps, *, mesh: Mesh, num_qubits: int, qb_low: int, qb_high: int):
    """SWAP between a local qubit and a sharded qubit: exchange only the
    mismatched half-shard with the XOR partner (statevec_swapQubitAmps
    routing, QuEST_cpu_distributed.c:1397-1436: 'pair processes only swap
    half their amps').

    Derivation: for shard-coordinate bit u (the high qubit's value) and
    local bit v (the low qubit), elements with v == u stay; elements with
    v != u land on the pair rank at local bit position unchanged-in-value.
    So each shard sends its v = 1-u half and splices the received half back
    at the same position."""
    ndev = amp_axis_size(mesh)
    r = num_shard_bits(mesh)
    nloc = num_qubits - r
    assert qb_high >= nloc and qb_low < nloc
    bit = qb_high - nloc
    perm = _hypercube_perm(ndev, bit)

    def kernel(local):
        idx = lax.axis_index(AMP_AXIS)
        u = (idx >> bit) & 1
        lv = local.reshape(2, 1 << (nloc - 1 - qb_low), 2, 1 << qb_low)
        # dynamic half-selection: take(lv, 1-u) along the low-qubit axis
        send = lax.dynamic_index_in_dim(lv, 1 - u, axis=2, keepdims=False)
        recv = lax.ppermute(send, AMP_AXIS, perm)
        new = lax.dynamic_update_index_in_dim(lv, recv, 1 - u, axis=2)
        return new.reshape(2, -1)

    return shard_map(
        kernel, mesh=mesh, in_specs=P(None, AMP_AXIS), out_specs=P(None, AMP_AXIS)
    )(amps)


@partial(jax.jit, static_argnames=("mesh",))
def total_prob_sharded(amps, *, mesh: Mesh):
    """|amps|^2 with an explicit psum — the reference's local-reduce +
    MPI_Allreduce(SUM) (QuEST_cpu_distributed.c:1308-1322)."""

    def kernel(local):
        return lax.psum(jnp.sum(cplx.abs2(local)), AMP_AXIS)

    return shard_map(
        kernel, mesh=mesh, in_specs=P(None, AMP_AXIS), out_specs=P()
    )(amps)


@partial(jax.jit, static_argnames=("mesh",))
def gather_replicated(amps, *, mesh: Mesh):
    """Replicate the full state onto every device — the analogue of the
    reference's ring-of-broadcasts copyVecIntoMatrixPairState
    (QuEST_cpu_distributed.c:379-423), used to build rho = |psi><psi|."""

    def kernel(local):
        return lax.all_gather(local, AMP_AXIS, axis=1, tiled=True)

    return shard_map(
        kernel, mesh=mesh, in_specs=P(None, AMP_AXIS), out_specs=P(),
        check_vma=False,
    )(amps)


def _pair_channel_weights(kind: str, p, ktv, btv, dt):
    """(w1, w2) weights for the double-flip pair channels given the ket /
    bra target-bit values (traced scalars or broadcastable arrays):
    depol:   w1 = kt==bt ? 1-2p/3 : 1-4p/3 ; w2 = kt==bt ? 2p/3 : 0
    damping: w1 = [[1, s], [s, 1-p]][bt, kt] (s = sqrt(1-p));
             w2 = p at (kt,bt)=(0,0) else 0."""
    p = jnp.asarray(p, dt)
    same = ktv == btv
    if kind == "depol":
        w1 = jnp.where(same, 1 - 2 * p / 3, 1 - 4 * p / 3).astype(dt)
        w2 = jnp.where(same, 2 * p / 3, 0.0).astype(dt)
        return w1, w2
    s = jnp.sqrt(1 - p)
    w1 = jnp.where(same, jnp.where(ktv == 0, 1.0, 1 - p),
                   s).astype(dt)
    w2 = jnp.where((ktv == 0) & (btv == 0), p, 0.0).astype(dt)
    return w1, w2


@partial(jax.jit,
         static_argnames=("mesh", "num_qubits", "target", "kind"),
         donate_argnums=0)
def mix_pair_channel_sharded(amps, prob, *, mesh: Mesh, num_qubits: int,
                             target: int, kind: str):
    """Explicit distributed depolarise / damping on a sharded density
    matrix: ONE full-shard ppermute to the double-flip partner + a fused
    elementwise combine — the TPU-native redesign of the reference's
    pack-and-exchange distributed decoherence
    (QuEST_cpu_distributed.c:553-852).  GSPMD compiles the same channel to
    3 collective-permutes (depol) or 3 permutes + 10 all-to-alls
    (damping); this path is exactly one collective.

    ``kind``: "depol" | "damping".  Requires the bra target bit
    (target + num_qubits) to be a mesh-coordinate bit; local-bra channels
    take the elementwise kernels (ops/density.py)."""
    nq = num_qubits
    nn = 2 * nq
    ndev = amp_axis_size(mesh)
    r = num_shard_bits(mesh)
    nloc = nn - r
    t, b = target, target + nq
    assert b >= nloc, "local channels take ops/density.py"
    bbit = b - nloc
    dt = amps.dtype

    def kernel(local, p):
        idx = lax.axis_index(AMP_AXIS)
        btv = (idx >> bbit) & 1
        if t >= nloc:
            # both target bits sharded: partner shard = double XOR
            tbit = t - nloc
            perm = [(i, i ^ (1 << bbit) ^ (1 << tbit)) for i in range(ndev)]
            recv = lax.ppermute(local, AMP_AXIS, perm)
            ktv = (idx >> tbit) & 1
            w1, w2 = _pair_channel_weights(kind, p, ktv, btv, dt)
            return local * w1 + recv * w2
        # ket bit local, bra bit sharded: exchange on the bra mesh bit,
        # partner element = received block with the LOCAL ket bit flipped
        perm = _hypercube_perm(ndev, bbit)
        recv = lax.ppermute(local, AMP_AXIS, perm)
        shape = (2, 1 << (nloc - 1 - t), 2, 1 << t)
        v = local.reshape(shape)
        pv = jnp.flip(recv.reshape(shape), axis=2)
        ktv = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 2, 1), 2)
        w1, w2 = _pair_channel_weights(kind, p, ktv, btv, dt)
        return (v * w1 + pv * w2).reshape(local.shape)

    return shard_map(
        kernel, mesh=mesh, in_specs=(P(None, AMP_AXIS), P()),
        out_specs=P(None, AMP_AXIS),
    )(amps, jnp.asarray(prob, dt))


def _ladder_phase_chunks(nbits: int, t_eff: int, sgn: float, dt):
    """Host tables factorizing exp(sgn*i*pi*li / 2^t_eff) over 7-bit chunks
    of the ``nbits``-bit index li (an exponential of a sum of per-bit
    contributions — cf. kernels.apply_qft_ladder's table factorization).
    Returns [(width, (2, 2^width) table), ...] low chunk first."""
    import numpy as np

    out = []
    p = 0
    while p < nbits:
        w = min(7, nbits - p)
        j = np.arange(1 << w, dtype=np.float64)
        ang = sgn * np.pi * (j * (1 << p)) / (1 << t_eff)
        out.append((w, np.stack([np.cos(ang), np.sin(ang)]).astype(dt)))
        p += w
    return out


def _apply_local_phase(local, chunks):
    """Elementwise multiply by the factored phase over the local index."""
    widths = [w for w, _ in chunks]
    shape = [2] + [1 << w for w in reversed(widths)]
    v = local.reshape(shape)
    ndim = len(shape) - 1
    for ci, (w, tab) in enumerate(chunks):
        bshape = [1] * ndim
        bshape[ndim - 1 - ci] = 1 << w
        v = cplx.cmul(v, jnp.asarray(tab[0]).reshape(bshape),
                      jnp.asarray(tab[1]).reshape(bshape))
    return v.reshape(local.shape)


@partial(jax.jit, static_argnames=("mesh", "num_qubits", "conj"),
         donate_argnums=0)
def fused_qft_sharded(amps, *, mesh: Mesh, num_qubits: int,
                      conj: bool = False):
    """Full-register QFT on a SHARDED statevector, one shard_map end to
    end — the explicit-collective redesign of the reference's distributed
    QFT (agnostic_applyQFT, QuEST_common.c:836-898, whose H sweeps ride
    exchangeStateVectors):

      * mesh-bit layers (target >= nloc): ONE full-shard ``ppermute``
        (the reference's pairwise exchange) + a fused elementwise
        H-combine x controlled-phase ladder, with the phase split into a
        per-shard scalar (the sharded index part) times factored local
        tables;
      * local layers: the same Pallas ladder kernels every backend uses
        (QuEST_internal.h:63-292 one-kernel-set contract), running
        per-shard inside the shard_map;
      * the final bit reversal: two LOCAL reversals + ONE
        ``lax.all_to_all`` — the lanes<->mesh-bits block swap
        rev[0,n) = rev[0,r) o all_to_all o (rev[0,r) x rev[r,nloc)).

    Collectives: r ppermutes + 1 all_to_all, all riding ICI.
    """
    from ..ops import fused as _fused

    n = num_qubits
    ndev = amp_axis_size(mesh)
    r = num_shard_bits(mesh)
    nloc = n - r
    dt = amps.dtype
    sgn = -1.0 if conj else 1.0
    inv = 0.7071067811865476
    use_multilayer = (_fused.qft_multilayer_enabled(dt)
                      and nloc >= _fused.CLUSTER_QUBITS + 1)
    radix = _fused._qft_radix()

    # host-precomputed local phase tables per mesh layer
    layer_chunks = {
        t: _ladder_phase_chunks(nloc, t, sgn, dt)
        for t in range(nloc, n)
    }

    def kernel(local):
        idx = lax.axis_index(AMP_AXIS)
        # mesh-bit layers, high to low
        for t in range(n - 1, nloc - 1, -1):
            bit = t - nloc
            perm = _hypercube_perm(ndev, bit)
            mybit = (idx >> bit) & 1
            recv = lax.ppermute(local, AMP_AXIS, perm)
            s = jnp.where(mybit == 0, jnp.asarray(1.0, dt),
                          jnp.asarray(-1.0, dt))
            comb = (local * s + recv) * jnp.asarray(inv, dt)
            # ladder phase on the |1> half (mybit == 1 shards): scalar
            # from the sharded low bits x factored local tables
            mlow = (idx & ((1 << bit) - 1)).astype(dt)
            theta = jnp.asarray(sgn * math.pi, dt) * mlow / (1 << bit)
            ph = _apply_local_phase(comb, layer_chunks[t])
            ph = cplx.cmul(ph, jnp.cos(theta), jnp.sin(theta))
            local = jnp.where(mybit == 1, ph, comb)
        # local layers, per shard: multilayer (radix-2^k) passes when the
        # shard is big enough — the SAME grouping helper the unsharded
        # path uses (fused.apply_qft_multilayer_ladders) — else per-layer
        # Pallas ladders for t >= 7 and the XLA elementwise ladder below
        # (a dense window-pass fold here can overflow scoped VMEM when
        # XLA promotes a small shard into VMEM inside this one big
        # program).  NB use_multilayer/radix resolve at TRACE time (the
        # env toggles are frozen into any enclosing jit's cache).
        if use_multilayer:
            local = _fused.apply_qft_multilayer_ladders(
                local, num_qubits=nloc, conj=conj, t_top=nloc - 1,
                radix=radix)
            low_start = _fused.LANE_QUBITS - 1
        else:
            low_start = nloc - 1
        for t in range(low_start, -1, -1):
            local = kernels.apply_qft_ladder(
                local, num_qubits=nloc, target=t, conj=conj)
        # bit reversal: L1 local, all_to_all block swap, L2 local
        # (L1 = rev[0,r) x rev[r,nloc); perm[q] = input qubit at output q)
        if r:
            perm1 = tuple([r - 1 - q for q in range(r)]
                          + [r + (nloc - 1 - q) for q in range(r, nloc)])
            local = kernels.permute_qubits(local, num_qubits=nloc,
                                           perm=perm1)
            v = local.reshape(2, 1 << (nloc - r), 1 << r)
            v = lax.all_to_all(v, AMP_AXIS, split_axis=2, concat_axis=2,
                               tiled=False)
            local = v.reshape(2, -1)
            perm2 = tuple([r - 1 - q for q in range(r)]
                          + list(range(r, nloc)))
            local = kernels.permute_qubits(local, num_qubits=nloc,
                                           perm=perm2)
        else:
            perm = tuple(nloc - 1 - q for q in range(nloc))
            local = kernels.permute_qubits(local, num_qubits=nloc, perm=perm)
        return local

    return shard_map(
        kernel, mesh=mesh, in_specs=P(None, AMP_AXIS),
        out_specs=P(None, AMP_AXIS), check_vma=False,
    )(amps)


def plan_relocalization(
    num_qubits: int,
    nloc: int,
    targets: Tuple[int, ...],
    controls: Tuple[int, ...] = (),
):
    """Choose swap pairs pulling every sharded target down to a free local
    qubit (reference picks the lowest free qubit and patches the control
    mask on collision, QuEST_cpu_distributed.c:1508-1531; we instead exclude
    controls from the free pool so the mask never needs patching).

    Returns (swaps, new_targets), or (None, None) when there aren't enough
    free local qubits — the caller falls back to the GSPMD path (the
    reference instead *rejects* such ops via validateMultiQubitUnitaryMatrix,
    QuEST_validation.c:469-471, so this is strictly more capable)."""
    targets = list(targets)
    blocked = set(targets) | set(controls)
    free_local = [q for q in range(nloc) if q not in blocked]
    swaps = []
    for i, t in enumerate(targets):
        if t >= nloc:
            if not free_local:
                return None, None
            fq = free_local.pop(0)
            swaps.append((fq, t))
            targets[i] = fq
    return tuple(swaps), tuple(targets)
