"""Explicit distributed kernels: shard_map + ppermute over the amplitude mesh.

TPU-native re-design of the reference's MPI orchestration layer
(QuEST/src/CPU/QuEST_cpu_distributed.c).  The state of n qubits is sharded
over a 1-D device mesh on its leading (most-significant) index bits: with
2^r devices, qubits 0..n-r-1 are *local* (inside each shard) and qubits
n-r..n-1 are *sharded* (their bit IS a mesh-coordinate bit) — exactly the
reference's chunkId scheme (QuEST.h:330-338).

Mapping of the reference's five MPI primitives (SURVEY.md §5.8):

- pairwise full-chunk ``MPI_Sendrecv`` with the XOR-partner rank
  (exchangeStateVectors, :489-517) -> ``lax.ppermute`` with the static
  hypercube permutation [(i, i ^ 2^b)];
- the locality predicate target < log2(chunkSize)
  (halfMatrixBlockFitsInChunk, :366-371) -> a Python-level static branch:
  local targets run the ordinary kernels un-communicated;
- SWAP-relocalization of multi-qubit ops (:1447-1545) -> half-shard
  ppermute swaps (``swap_sharded``) pulling high targets down to free low
  qubits, op applied locally, swaps undone;
- ``MPI_Allreduce`` (:35-117) -> ``lax.psum``;
- ``MPI_Bcast`` replication loops (:379-423) -> ``lax.all_gather``.

Two structural wins over the reference: no pairStateVec — the reference
permanently holds a 2x receive buffer (QuEST_cpu.c:1279-1315) while
ppermute's transient buffer exists only inside one fused program; and the
elementwise combine fuses with the communication epilogue under XLA instead
of being a second pass over memory.  A third (round-8): every exchange is
CHUNK-PIPELINED — ``exchange_pipelined`` splits the payload into C chunks
and issues the ppermute for chunk i+1 before the combine consuming chunk
i, overlapping ICI transfer with VPU work and shrinking the transient
recv buffer to one chunk (qHiPSTER's pipelined exchange,
arXiv:1601.07195 §III; docs/design.md §17).

These kernels are *compile-time* alternatives invoked by the API layer when
a gate touches sharded qubits (quest_tpu.api routes there); the GSPMD path
(plain jit + sharding propagation) remains available via
``use_explicit_dist(False)`` for benchmarking one against the other
(SURVEY.md §7 layer 5 calls for exactly this comparison).
"""

from __future__ import annotations

import math
import os
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import telemetry as _telemetry
from ..contracts import sharded_contract
from ..env import AMP_AXIS, shard_map
from ..ops import cplx, kernels
from . import topology as topo

_CONFIG = {"explicit": True, "lazy_remap": True}


def mesh_topology(mesh: Mesh) -> topo.Topology:
    """The live hierarchical arrangement of this mesh's amplitude axis
    (``QT_TOPOLOGY``; single-host fallback — parallel/topology.py)."""
    return topo.resolve(amp_axis_size(mesh))


def _record_exchange(amps, op: str, count: int, nbytes: int, chunks,
                     tier: str = "ici") -> None:
    """Dispatch-time exchange accounting (telemetry.record_exchange):
    skipped for traced operands — a wrapper reached from inside a user
    jit body would otherwise count once per TRACE, not per execution."""
    if not _telemetry.enabled() or isinstance(amps, jax.core.Tracer):
        return
    _telemetry.record_exchange(op, count, nbytes, chunks=str(chunks),
                               tier=tier)


def _record_exchange_tiers(amps, op: str, parts, chunks) -> None:
    """Per-tier dispatch accounting: ``parts`` maps tier ->
    (count, nbytes); one record_exchange per nonzero tier, so the tier
    series sum exactly to the flat accounting of the same program."""
    if not _telemetry.enabled() or isinstance(amps, jax.core.Tracer):
        return
    for tier, (count, nbytes) in parts.items():
        if count or nbytes:
            _telemetry.record_exchange(op, count, nbytes,
                                       chunks=str(chunks), tier=tier)


def _sweep_exchange_tiers(nex: int, r: int, payload: int,
                          t: "topo.Topology", composed: bool) -> dict:
    """Tier split of a mesh-bit SWEEP op (Trotter / PauliSum rotation
    layers): ``nex`` full-shard exchanges spread uniformly over the
    ``r`` mesh bits, so the DCN share is exactly ``nex * dcn_bits / r``
    (nex is a multiple of r for the layered bodies).  ``composed`` marks
    the direct-gather bodies whose single composed mesh-flip ppermute
    per term may touch ANY mesh bit — conservatively DCN on a multi-host
    topology."""
    if composed:
        tier = "dcn" if t.dcn_bits else "ici"
        return {tier: (nex, nex * payload)}
    dcn_n = nex * t.dcn_bits // max(r, 1)
    return {"ici": (nex - dcn_n, (nex - dcn_n) * payload),
            "dcn": (dcn_n, dcn_n * payload)}


# ---------------------------------------------------------------------------
# Guarded collectives (elastic recovery, docs/design.md §19)
#
# On a healthy mesh an exchange dispatch either completes or raises; on a
# degraded pod it can also hang (a peer stopped answering) or fail with a
# runtime error long after the circuit started.  Every sharded dispatch
# below goes through guarded_dispatch: bounded attempts with exponential
# backoff (retry_io's policy, shared knobs), dispatch latency observed
# into the exchange_latency_seconds histogram, a post-hoc deadline that
# counts exchange_timeouts_total when a dispatch came back slower than
# QT_EXCHANGE_DEADLINE_S, and — when the retry budget is exhausted — a
# ShardLossError that the resilience layer's failover loop converts into
# rollback + mesh shrink (resilience.run_resumable).  Deterministic
# fault injection enters through EXCHANGE_FAULT_HOOK, armed per window
# by resilience.FaultPlan (`stall` / `shard_loss` modes).
# ---------------------------------------------------------------------------

_DEADLINE_ENV = "QT_EXCHANGE_DEADLINE_S"
_GUARD_ATTEMPTS_ENV = "QT_EXCHANGE_RETRIES"

# fault-injection slot: resilience.run_resumable installs the active
# FaultPlan's take_exchange_fault here (a plain module slot rather than
# an import so dist <-> resilience stays acyclic).  The hook takes the
# op name and returns None, "stall", or "shard_loss".
EXCHANGE_FAULT_HOOK: list = [None]


class ShardLossError(RuntimeError):
    """A shard is presumed dead: an exchange dispatch kept failing past
    its retry budget, or the fault plan declared the loss outright.
    Deliberately NOT a QuESTError — it signals infrastructure failure,
    not API misuse — so the resilience layer can catch it for failover
    without masking validation bugs."""

    def __init__(self, msg: str, *, shard: Optional[int] = None,
                 op: str = "exchange"):
        super().__init__(msg)
        self.shard = shard
        self.op = op


def exchange_deadline() -> Optional[float]:
    """The live per-dispatch deadline in seconds (None = no deadline)."""
    raw = os.environ.get(_DEADLINE_ENV)
    if not raw:
        return None
    try:
        d = float(raw)
    except ValueError:
        return None
    return d if d > 0 else None


def guarded_dispatch(fn, *args, op: str = "exchange", shards: int = 1,
                     **kwargs):
    """Run one exchange dispatch under the collective guard.

    Passthrough for traced operands (a dispatch reached from inside a
    user jit can neither be timed nor retried — it is a trace).  For
    concrete operands: up to QT_EXCHANGE_RETRIES attempts (default 3)
    with retry_io-style exponential backoff (QT_RETRY_BASE_SECONDS base);
    each attempt first consumes one injected fault from
    EXCHANGE_FAULT_HOOK — ``stall`` burns the attempt as a timed-out
    dispatch (exchange_timeouts_total), ``shard_loss`` raises
    ShardLossError immediately — then dispatches, observing the host
    dispatch latency into exchange_latency_seconds{op,shards} and
    counting a timeout when it exceeded QT_EXCHANGE_DEADLINE_S (the
    result is still used: a late synchronous dispatch has already
    completed — the deadline is SLO accounting, not cancellation).  A
    real dispatch exception is retried; note most inner programs donate
    their operand, so a retry after a partially-executed dispatch may
    surface a deleted-buffer error — the guard converts either into
    ShardLossError after the budget."""
    import time as _time

    if args and isinstance(args[0], jax.core.Tracer):
        return fn(*args, **kwargs)
    attempts = max(1, int(os.environ.get(_GUARD_ATTEMPTS_ENV, "3")))
    base_delay = float(os.environ.get("QT_RETRY_BASE_SECONDS", "0.05"))
    deadline = exchange_deadline()
    shards = str(shards)
    last = None
    for k in range(attempts):
        hook = EXCHANGE_FAULT_HOOK[0]
        fault = hook(op) if hook is not None else None
        if fault == "shard_loss":
            _telemetry.inc("exchange_timeouts_total", op=op)
            topo.notify_mesh_event("shard_loss", op=op, shard=None)
            raise ShardLossError(
                f"injected shard loss during {op} dispatch", op=op)
        if fault == "host_loss":
            # a whole host's shards die at once: report the highest shard
            # as the observed casualty — the failover maps it back to its
            # host (topology.host_of) and excludes that host's entire
            # device range from the surviving mesh
            _telemetry.inc("exchange_timeouts_total", op=op)
            topo.notify_mesh_event("host_loss", op=op,
                                   shard=int(shards) - 1)
            raise ShardLossError(
                f"injected host loss during {op} dispatch", op=op,
                shard=int(shards) - 1)
        if fault == "stall":
            _telemetry.inc("exchange_timeouts_total", op=op)
            last = TimeoutError(f"injected stall during {op} dispatch")
        else:
            t0 = _time.perf_counter()
            try:
                out = fn(*args, **kwargs)
            # qlint: allow(broad-except): guarded dispatch retries transient runtime failures of any class (backend RPC errors surface under several types); the final attempt re-raises via ShardLossError with the last error chained
            except Exception as e:  # runtime dispatch failure: retry
                last = e
            else:
                elapsed = _time.perf_counter() - t0
                _telemetry.observe("exchange_latency_seconds", elapsed,
                                   op=op, shards=shards)
                if deadline is not None and elapsed > deadline:
                    _telemetry.inc("exchange_timeouts_total", op=op)
                return out
        if k + 1 < attempts:
            _time.sleep(base_delay * (1 << k))
    topo.notify_mesh_event("shard_loss", op=op, shard=None,
                           exhausted_attempts=attempts)
    raise ShardLossError(
        f"{op} dispatch failed after {attempts} attempts "
        f"(last error: {last!r})", op=op) from last


def use_explicit_dist(enabled: bool) -> None:
    """Toggle the explicit ppermute path vs GSPMD propagation."""
    _CONFIG["explicit"] = bool(enabled)


def explicit_dist_enabled() -> bool:
    return _CONFIG["explicit"]


def use_lazy_remap(enabled: bool) -> None:
    """Toggle the communication-avoiding lazy logical->physical
    permutation (mpiQulacs-style, arXiv:2203.16044).  Disabled, every
    sharded-target relocalization swaps back eagerly (the reference's
    per-gate scheme, QuEST_cpu_distributed.c:1447-1545) — kept for A/B
    benchmarking (bench_suite dist_remap config) and bit-identity tests."""
    _CONFIG["lazy_remap"] = bool(enabled)


def lazy_remap_enabled() -> bool:
    return _CONFIG["lazy_remap"]


# ---------------------------------------------------------------------------
# Pipelined chunked exchange (communication/computation overlap)
#
# Every sharded-qubit op below used to move its data in ONE monolithic
# ppermute — the ICI link idle while the combine math ran, the VPU idle
# while amplitudes were in flight, and the transient recv buffer a full
# extra shard of HBM.  qHiPSTER (arXiv:1601.07195 §III) gets most of its
# distributed speedup from splitting the exchange into chunks and
# pipelining communication with computation; the reference itself chunks
# its MPI exchange when buffers are tight, without overlapping
# (exchangeStateVectors, QuEST_cpu_distributed.c:489-517).
# exchange_pipelined is the shared engine: the payload splits into C
# chunks along the amplitude axis and the loop is software-pipelined —
# the ppermute for chunk i+1 is issued BEFORE the combine consuming
# chunk i (an unrolled two-stage schedule with explicit prologue and
# epilogue), so XLA's latency-hiding scheduler lowers each exchange to a
# collective-permute-start/done pair with the previous chunk's combine
# between them, and the transient recv buffer is one chunk instead of
# the whole payload (docs/design.md §17).
# ---------------------------------------------------------------------------

_EXCHANGE_ENV = "QT_EXCHANGE_CHUNKS"

# Small-shard fallback: below this many payload bytes the monolithic
# exchange wins — per-chunk dispatch/slicing overhead exceeds any
# overlap.  Measured on the 8-shard CPU dryrun (bench_suite config 7
# chunk sweep, docs/design.md §17): C=4 costs a steady 21-41% over
# monolithic across 16 KiB..4 MiB shards when there is NO asynchrony to
# recoup it (the CPU backend's collective-permute is a synchronous
# copy), which is why the auto heuristic only engages off-CPU at all;
# there, the overhead side bounds the loss and the threshold sits where
# a shard's transfer time is worth hiding (~2 MiB at v5e ICI rates).
PIPELINE_MIN_BYTES = 1 << 21

# Steady-state chunk sizing: big enough that per-chunk collective setup
# amortizes, small enough that two in-flight chunks hide under a combine.
_TARGET_CHUNK_BYTES = 1 << 22

MAX_EXCHANGE_CHUNKS = 8


# per-drain chunk escalation set by the memory governor's degradation
# ladder (governor.govern_drain rung 1) and cleared in the drain's
# finally (governor.end_drain) — published through exchange_config_key
# so the compiled-executor cache, the telemetry byte accounting, and
# the reconcile prediction all see ONE consistent chunk policy.  The
# explicit QT_EXCHANGE_CHUNKS env override always wins.
_GOVERNOR_CHUNKS: list = [None]


def exchange_config_key() -> Optional[str]:
    """The live chunk-policy override — a cache-key component for
    programs that bake the chunk count in at trace time
    (fusion._plan_runner keys its compiled drain executor on this, so
    flipping the env var between drains retraces instead of silently
    reusing a stale chunk schedule).  ``QT_EXCHANGE_CHUNKS`` first,
    then the memory governor's per-drain escalation."""
    v = os.environ.get(_EXCHANGE_ENV)
    if v is not None:
        return v
    g = _GOVERNOR_CHUNKS[0]
    return None if g is None else str(int(g))


def _pow2_floor(x: int) -> int:
    return 1 << (max(int(x), 1).bit_length() - 1)


def exchange_chunks(payload_bytes: int, limit: int = 1 << 30,
                    backend: Optional[str] = None) -> int:
    """Chunk count for one exchange of ``payload_bytes`` bytes.

    ``QT_EXCHANGE_CHUNKS`` overrides unconditionally (rounded down to a
    power of two — chunks must divide the power-of-two payload — with the
    rounding recorded once in the degradation registry); otherwise the
    heuristic: monolithic on the CPU backend (its collective-permute is
    a synchronous copy — chunking measured a flat 21-41% loss with no
    overlap to recoup, bench_suite config 7) and monolithic below
    PIPELINE_MIN_BYTES (pipeline overhead loses on small shards), else
    ~_TARGET_CHUNK_BYTES chunks capped at MAX_EXCHANGE_CHUNKS.
    ``limit`` is the structural cap of the call site (the payload axis
    the combine must keep intact); always respected.  ``backend``
    defaults to the live jax backend (tests pass it explicitly)."""
    limit = max(1, _pow2_floor(limit))
    override = exchange_config_key()
    if override is not None:
        try:
            c = max(1, int(override))
        except ValueError:
            from .. import resilience

            resilience.record_degradation(
                "exchange_chunks",
                f"unparseable {_EXCHANGE_ENV}={override!r}; monolithic")
            return 1
        if c != _pow2_floor(c):
            from .. import resilience

            resilience.record_degradation(
                "exchange_chunks",
                f"{_EXCHANGE_ENV}={c} not a power of two; "
                f"using {_pow2_floor(c)}")
        return min(_pow2_floor(c), limit)
    if backend is None:
        backend = jax.default_backend()
    if backend == "cpu" or payload_bytes < PIPELINE_MIN_BYTES:
        return 1
    c = _pow2_floor(payload_bytes // _TARGET_CHUNK_BYTES)
    return max(1, min(c, MAX_EXCHANGE_CHUNKS, limit))


def _shard_payload_bytes(amps, mesh: Mesh) -> int:
    """Bytes of ONE shard of a (2, N)-global SoA state — the full-shard
    exchange payload (wrappers resolve chunk counts OUTSIDE the jit so
    the env override participates in dispatch, not in a stale trace).
    A batched (B, 2, N) register bank's shard carries all B elements'
    slices, so its exchange payload (and the telemetry byte accounting
    built on it) scales with the batch size."""
    b = int(amps.shape[0]) if amps.ndim == 3 else 1
    return (b * 2 * (int(amps.shape[-1]) // amp_axis_size(mesh))
            * amps.dtype.itemsize)


def exchange_pipelined(send, perm, combine_fn, *, chunks: int):
    """Chunked double-buffered ppermute INSIDE a shard_map body.

    Splits ``send`` into ``chunks`` equal contiguous pieces along its
    LAST axis (= the top log2(chunks) bits of the per-shard amplitude
    index) and software-pipelines the exchange:

        prologue : ppermute chunk 0
        steady   : ppermute chunk i+1; combine chunk i   (i = 0..C-2)
        epilogue : combine chunk C-1

    The loop is fully unrolled so every chunk gets its own HLO
    collective-permute — the form XLA's latency-hiding scheduler splits
    into start/done pairs with the neighbouring combine scheduled between
    them — and the transient recv footprint is at most two chunks (the
    one being consumed plus the one in flight) instead of the whole
    payload.  ``combine_fn(i, own_chunk, recv_chunk)`` receives the
    STATIC chunk index, so call sites can resolve chunk-constant bit
    conditions (e.g. high local controls) at trace time.

    ``chunks`` <= 1 (or a non-dividing count) is the monolithic path:
    one ppermute, one combine — bit-identical output either way, since
    the combines are elementwise on disjoint chunks."""
    m = int(send.shape[-1])
    if chunks <= 1 or m % chunks or m // chunks == 0:
        recv = lax.ppermute(send, AMP_AXIS, perm)
        return combine_fn(0, send, recv)
    step = m // chunks
    parts = jnp.split(send, chunks, axis=-1)
    in_flight = lax.ppermute(parts[0], AMP_AXIS, perm)     # prologue
    out = send
    zeros = (0,) * (send.ndim - 1)
    for i in range(chunks):
        recv = in_flight
        if i + 1 < chunks:
            # issue chunk i+1 before consuming chunk i: the combine below
            # is what the transfer hides behind
            in_flight = lax.ppermute(parts[i + 1], AMP_AXIS, perm)
        # update-slice chain rather than a concat: a concat epilogue costs
        # a second full-payload staging buffer (measured on the CPU
        # dryrun), the chain lets buffer assignment grow the output in
        # place once the source chunks are dead
        out = lax.dynamic_update_slice(
            out, combine_fn(i, parts[i], recv), zeros + (i * step,))
    return out


def _swap_halves_in_shard(local, lb: int, mb: int, nloc: int, ndev: int,
                          chunks: int = 1):
    """Half-shard SWAP exchange inside a shard_map body: send the local
    half whose bit ``lb`` mismatches this shard's mesh bit ``mb`` to the
    XOR partner and splice the received half back (the reference's
    'pair processes only swap half their amps', statevec_swapQubitAmps,
    QuEST_cpu_distributed.c:1397-1436), with the half-payload exchange
    chunk-pipelined.  Shared by swap_sharded, _remap_in_shard's mixed
    transpositions, and _reverse_run_sharded."""
    idx = lax.axis_index(AMP_AXIS)
    u = (idx >> mb) & 1
    lv = local.reshape(2, 1 << (nloc - 1 - lb), 2, 1 << lb)
    send = lax.dynamic_index_in_dim(lv, 1 - u, axis=2, keepdims=False)
    recv = exchange_pipelined(
        send.reshape(2, -1), _hypercube_perm(ndev, mb),
        lambda i, own, rv: rv, chunks=chunks)
    return lax.dynamic_update_index_in_dim(
        lv, recv.reshape(send.shape), 1 - u, axis=2).reshape(2, -1)


def amp_axis_size(mesh: Mesh) -> int:
    """Size of the amplitude axis — NOT mesh.devices.size: meshes may carry
    extra axes (e.g. the (dp, amps) training mesh)."""
    return int(mesh.shape[AMP_AXIS])


def num_shard_bits(mesh: Mesh) -> int:
    return int(math.log2(amp_axis_size(mesh)))


def _hypercube_perm(ndev: int, bit: int):
    """Static XOR-partner permutation — the reference's pair-rank computation
    chunkId ^ (2^t / chunkSize) (QuEST_cpu_distributed.c:313-333) as a
    ppermute table."""
    return [(i, i ^ (1 << bit)) for i in range(ndev)]


def _shard_coeffs(rmat_like, mybit):
    """Per-shard gate coefficients a = m[b,b], b_coef = m[b,1-b] selected by
    the shard's target-bit value (statevec_compactUnitaryDistributed,
    QuEST_cpu.c:1841-1900 uses rankIsUpper the same way)."""
    row = mybit
    a_re = rmat_like[0, row, row]
    a_im = rmat_like[1, row, row]
    b_re = rmat_like[0, row, 1 - row]
    b_im = rmat_like[1, row, 1 - row]
    return a_re, a_im, b_re, b_im


@sharded_contract(collectives={"collective-permute": 1},
                  max_exchange_bytes=1 << 10,
                  max_tier_bytes={"ici": 1 << 10, "dcn": 1 << 10})
def apply_matrix_1q_sharded(
    amps,
    matrix,
    *,
    mesh: Mesh,
    num_qubits: int,
    target: int,
    controls: Tuple[int, ...] = (),
    control_states: Tuple[int, ...] = (),
    chunks: Optional[int] = None,
):
    """One-qubit dense gate on a *sharded* target qubit: full-shard
    chunk-pipelined ppermute exchange + fused elementwise combine — the
    reference's non-local gate pattern (QuEST_cpu_distributed.c:854-928)
    with the exchange split into chunks so the ICI transfer of chunk i+1
    overlaps the VPU combine of chunk i (exchange_pipelined).

    Low (local) controls restrict the exchanged+combined sub-block; sharded
    controls become a per-shard mask (the reference instead skips ranks
    whose chunk fails the control condition, :1093-1112 — SPMD cannot skip,
    but masked shards do no extra communication since the exchange is
    collective anyway).  ``chunks`` defaults to the per-op heuristic
    (exchange_chunks over the shard bytes); resolved HERE, outside the
    jit, so the env override acts at dispatch time."""
    if chunks is None:
        chunks = exchange_chunks(_shard_payload_bytes(amps, mesh))
    _record_exchange(amps, "matrix_1q", 1, _shard_payload_bytes(amps, mesh),
                     chunks,
                     tier=mesh_topology(mesh).tier_of_bit(
                         target - (num_qubits - num_shard_bits(mesh))))
    return guarded_dispatch(
        _apply_matrix_1q_sharded, amps, matrix,
        op="matrix_1q", shards=amp_axis_size(mesh),
        mesh=mesh, num_qubits=num_qubits, target=target,
        controls=tuple(controls), control_states=tuple(control_states),
        chunks=int(chunks))


@partial(
    jax.jit,
    static_argnames=("mesh", "num_qubits", "target", "controls",
                     "control_states", "chunks"),
    donate_argnums=0,
)
def _apply_matrix_1q_sharded(
    amps,
    matrix,
    *,
    mesh: Mesh,
    num_qubits: int,
    target: int,
    controls: Tuple[int, ...],
    control_states: Tuple[int, ...],
    chunks: int,
):
    ndev = amp_axis_size(mesh)
    r = num_shard_bits(mesh)
    n = num_qubits
    nloc = n - r
    assert target >= nloc, "local targets take the ordinary kernel"
    bit = target - nloc
    perm = _hypercube_perm(ndev, bit)

    states = control_states or (1,) * len(controls)
    local_controls = tuple((c, s) for c, s in zip(controls, states) if c < nloc)
    shard_controls = tuple((c - nloc, s) for c, s in zip(controls, states) if c >= nloc)
    # power-of-two, never more chunks than per-shard amplitudes: the
    # chunk-index bit arithmetic below must agree with the engine's split
    chunks = min(_pow2_floor(chunks), 1 << nloc)
    c_bits = chunks.bit_length() - 1
    nch = nloc - c_bits          # local index bits inside one chunk

    def kernel(local, m):
        # local: (2, amps_per_shard); m: (2, 2, 2) stacked SoA
        idx = lax.axis_index(AMP_AXIS)
        mybit = (idx >> bit) & 1
        a_re, a_im, b_re, b_im = _shard_coeffs(m, mybit)

        def cm(own_block, recv_block):
            return cplx.cmul(own_block, a_re, a_im) + cplx.cmul(recv_block, b_re, b_im)

        def combine(i, own, recv):
            # local controls at bit >= nch are chunk-CONSTANT: resolve
            # them statically from the chunk index (a failing chunk keeps
            # its own amplitudes — the exchange still moved it, matching
            # the monolithic kernel's collective-anyway semantics)
            if any(cb >= nch and ((i >> (cb - nch)) & 1) != s
                   for cb, s in local_controls):
                new = own
            else:
                low = tuple((cb, s) for cb, s in local_controls if cb < nch)
                if low:
                    shape, sel = kernels._interleaved_sel(nch, low)
                    lv = own.reshape(shape)
                    rv = recv.reshape(shape)
                    new = lv.at[sel].set(cm(lv[sel], rv[sel])).reshape(2, -1)
                else:
                    new = cm(own, recv)
            for cbit, s in shard_controls:
                cond = ((idx >> cbit) & 1) == s
                new = jnp.where(cond, new, own)
            return new

        return exchange_pipelined(local, perm, combine, chunks=chunks)

    return shard_map(
        kernel,
        mesh=mesh,
        in_specs=(P(None, AMP_AXIS), P()),
        out_specs=P(None, AMP_AXIS),
    )(amps, jnp.asarray(matrix, amps.dtype))


@sharded_contract(collectives={"collective-permute": 1},
                  max_exchange_bytes=1 << 9,
                  max_tier_bytes={"ici": 1 << 9, "dcn": 1 << 9})
def swap_sharded(amps, *, mesh: Mesh, num_qubits: int, qb_low: int,
                 qb_high: int, chunks: Optional[int] = None):
    """SWAP between a local qubit and a sharded qubit: exchange only the
    mismatched half-shard with the XOR partner (statevec_swapQubitAmps
    routing, QuEST_cpu_distributed.c:1397-1436: 'pair processes only swap
    half their amps'), the half-payload chunk-pipelined
    (_swap_halves_in_shard -> exchange_pipelined).

    Derivation: for shard-coordinate bit u (the high qubit's value) and
    local bit v (the low qubit), elements with v == u stay; elements with
    v != u land on the pair rank at local bit position unchanged-in-value.
    So each shard sends its v = 1-u half and splices the received half back
    at the same position."""
    if chunks is None:
        chunks = exchange_chunks(_shard_payload_bytes(amps, mesh) // 2)
    _record_exchange(amps, "swap", 1, _shard_payload_bytes(amps, mesh) // 2,
                     chunks,
                     tier=mesh_topology(mesh).tier_of_bit(
                         qb_high - (num_qubits - num_shard_bits(mesh))))
    return guarded_dispatch(
        _swap_sharded, amps, op="swap", shards=amp_axis_size(mesh),
        mesh=mesh, num_qubits=num_qubits,
        qb_low=qb_low, qb_high=qb_high, chunks=int(chunks))


@partial(jax.jit,
         static_argnames=("mesh", "num_qubits", "qb_low", "qb_high", "chunks"),
         donate_argnums=0)
def _swap_sharded(amps, *, mesh: Mesh, num_qubits: int, qb_low: int,
                  qb_high: int, chunks: int):
    ndev = amp_axis_size(mesh)
    r = num_shard_bits(mesh)
    nloc = num_qubits - r
    assert qb_high >= nloc and qb_low < nloc
    bit = qb_high - nloc
    chunks = min(_pow2_floor(chunks), 1 << (nloc - 1))

    def kernel(local):
        return _swap_halves_in_shard(local, qb_low, bit, nloc, ndev, chunks)

    return shard_map(
        kernel, mesh=mesh, in_specs=P(None, AMP_AXIS), out_specs=P(None, AMP_AXIS)
    )(amps)


@partial(jax.jit, static_argnames=("mesh",))
def total_prob_sharded(amps, *, mesh: Mesh):
    """|amps|^2 with an explicit psum — the reference's local-reduce +
    MPI_Allreduce(SUM) (QuEST_cpu_distributed.c:1308-1322)."""

    def kernel(local):
        return lax.psum(jnp.sum(cplx.abs2(local)), AMP_AXIS)

    return shard_map(
        kernel, mesh=mesh, in_specs=P(None, AMP_AXIS), out_specs=P()
    )(amps)


@sharded_contract(collectives={"all-gather": 1},
                  max_exchange_bytes=1 << 13)
def gather_replicated(amps, *, mesh: Mesh):
    """Replicate the full state onto every device — the analogue of the
    reference's ring-of-broadcasts copyVecIntoMatrixPairState
    (QuEST_cpu_distributed.c:379-423), used to build rho = |psi><psi|."""
    ndev = amp_axis_size(mesh)
    t = mesh_topology(mesh)
    payload = _shard_payload_bytes(amps, mesh)
    # each shard receives ndev-1 peer shards: chips-1 of them over ICI,
    # the rest across hosts — the count rides the slower tier
    dcn_b = payload * (ndev - t.chips)
    _record_exchange_tiers(
        amps, "gather",
        {"ici": (0 if dcn_b else 1, payload * (t.chips - 1)),
         "dcn": (1 if dcn_b else 0, dcn_b)}, 1)
    return guarded_dispatch(_gather_replicated, amps, op="gather",
                            shards=ndev, mesh=mesh)


@partial(jax.jit, static_argnames=("mesh",))
def _gather_replicated(amps, *, mesh: Mesh):

    def kernel(local):
        return lax.all_gather(local, AMP_AXIS, axis=1, tiled=True)

    return shard_map(
        kernel, mesh=mesh, in_specs=P(None, AMP_AXIS), out_specs=P(),
        check_vma=False,
    )(amps)


def _pair_channel_weights(kind: str, p, ktv, btv, dt):
    """(w1, w2) weights for the double-flip pair channels given the ket /
    bra target-bit values (traced scalars or broadcastable arrays):
    depol:   w1 = kt==bt ? 1-2p/3 : 1-4p/3 ; w2 = kt==bt ? 2p/3 : 0
    damping: w1 = [[1, s], [s, 1-p]][bt, kt] (s = sqrt(1-p));
             w2 = p at (kt,bt)=(0,0) else 0."""
    p = jnp.asarray(p, dt)
    same = ktv == btv
    if kind == "depol":
        w1 = jnp.where(same, 1 - 2 * p / 3, 1 - 4 * p / 3).astype(dt)
        w2 = jnp.where(same, 2 * p / 3, 0.0).astype(dt)
        return w1, w2
    s = jnp.sqrt(1 - p)
    w1 = jnp.where(same, jnp.where(ktv == 0, 1.0, 1 - p),
                   s).astype(dt)
    w2 = jnp.where((ktv == 0) & (btv == 0), p, 0.0).astype(dt)
    return w1, w2


@sharded_contract(collectives={"collective-permute": 1},
                  max_exchange_bytes=1 << 10,
                  max_tier_bytes={"ici": 1 << 10, "dcn": 1 << 10})
def mix_pair_channel_sharded(amps, prob, *, mesh: Mesh, num_qubits: int,
                             target: int, kind: str,
                             chunks: Optional[int] = None):
    """Explicit distributed depolarise / damping on a sharded density
    matrix: one chunk-pipelined full-shard ppermute to the double-flip
    partner + a fused elementwise combine — the TPU-native redesign of the
    reference's pack-and-exchange distributed decoherence
    (QuEST_cpu_distributed.c:553-852).  GSPMD compiles the same channel to
    3 collective-permutes (depol) or 3 permutes + 10 all-to-alls
    (damping); this path is exactly one (chunked) collective.

    ``kind``: "depol" | "damping".  Requires the bra target bit
    (target + num_qubits) to be a mesh-coordinate bit; local-bra channels
    take the elementwise kernels (ops/density.py)."""
    if chunks is None:
        chunks = exchange_chunks(_shard_payload_bytes(amps, mesh))
    # partner shard = XOR on the bra mesh bit (and the ket mesh bit too
    # when both are sharded) — the hop crosses DCN iff any flipped
    # mesh-coordinate bit addresses the host
    nloc = 2 * num_qubits - num_shard_bits(mesh)
    xor_mask = 1 << (target + num_qubits - nloc)
    if target >= nloc:
        xor_mask |= 1 << (target - nloc)
    _record_exchange(amps, "pair_channel", 1,
                     _shard_payload_bytes(amps, mesh), chunks,
                     tier=mesh_topology(mesh).tier_of_mask(xor_mask))
    return guarded_dispatch(
        _mix_pair_channel_sharded, amps, prob,
        op="pair_channel", shards=amp_axis_size(mesh),
        mesh=mesh, num_qubits=num_qubits, target=target,
        kind=kind, chunks=int(chunks))


@partial(jax.jit,
         static_argnames=("mesh", "num_qubits", "target", "kind", "chunks"),
         donate_argnums=0)
def _mix_pair_channel_sharded(amps, prob, *, mesh: Mesh, num_qubits: int,
                              target: int, kind: str, chunks: int):
    nq = num_qubits
    nn = 2 * nq
    ndev = amp_axis_size(mesh)
    r = num_shard_bits(mesh)
    nloc = nn - r
    t, b = target, target + nq
    assert b >= nloc, "local channels take ops/density.py"
    bbit = b - nloc
    dt = amps.dtype
    # the bra-sharded/ket-local branch flips the local ket-bit axis inside
    # each chunk: chunk bits must stay strictly above it
    limit = (1 << nloc) if t >= nloc else (1 << (nloc - 1 - t))
    chunks = min(_pow2_floor(chunks), limit)

    def kernel(local, p):
        idx = lax.axis_index(AMP_AXIS)
        btv = (idx >> bbit) & 1
        if t >= nloc:
            # both target bits sharded: partner shard = double XOR;
            # weights are per-shard scalars, the combine chunks freely
            tbit = t - nloc
            perm = [(i, i ^ (1 << bbit) ^ (1 << tbit)) for i in range(ndev)]
            ktv = (idx >> tbit) & 1
            w1, w2 = _pair_channel_weights(kind, p, ktv, btv, dt)
            return exchange_pipelined(
                local, perm, lambda i, own, rv: own * w1 + rv * w2,
                chunks=chunks)
        # ket bit local, bra bit sharded: exchange on the bra mesh bit,
        # partner element = received block with the LOCAL ket bit flipped
        perm = _hypercube_perm(ndev, bbit)
        hi_per_chunk = (1 << (nloc - 1 - t)) // chunks

        def combine(i, own, rv):
            shape = (2, hi_per_chunk, 2, 1 << t)
            v = own.reshape(shape)
            pv = jnp.flip(rv.reshape(shape), axis=2)
            ktv = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 2, 1), 2)
            w1, w2 = _pair_channel_weights(kind, p, ktv, btv, dt)
            return (v * w1 + pv * w2).reshape(own.shape)

        return exchange_pipelined(local, perm, combine, chunks=chunks)

    return shard_map(
        kernel, mesh=mesh, in_specs=(P(None, AMP_AXIS), P()),
        out_specs=P(None, AMP_AXIS),
    )(amps, jnp.asarray(prob, dt))


def _apply_1q_mesh_bit(local, m, bit: int, ndev: int, chunks: int = 1):
    """Dense 1q gate on mesh-coordinate bit ``bit`` INSIDE a shard_map body:
    one chunk-pipelined full-shard ppermute + fused elementwise combine —
    the apply_matrix_1q_sharded kernel body factored out so scan-based
    composites (Trotter, PauliSum expectation) can apply rotation layers
    to sharded qubits with the same exchange pattern the reference's
    distributed compactUnitary uses (QuEST_cpu_distributed.c:854-928).
    ``m`` may be a TRACED (2, 2, 2) SoA matrix (e.g. indexed by a scanned
    Pauli code): an identity simply combines with b-coefficients of zero —
    the ppermute still happens, matching the reference, whose distributed
    basis rotations also exchange regardless of the rotation angle."""
    idx = lax.axis_index(AMP_AXIS)
    mybit = (idx >> bit) & 1
    a_re, a_im, b_re, b_im = _shard_coeffs(m, mybit)
    return exchange_pipelined(
        local, _hypercube_perm(ndev, bit),
        lambda i, own, rv: cplx.cmul(own, a_re, a_im) + cplx.cmul(rv, b_re, b_im),
        chunks=chunks)


def _split_parity_mask(zlo, zhi, nloc: int, r: int):
    """Split TRACED uint32 z-mask halves over global state bits (lo =
    bits [0,31), hi = bits [31,62) — ops/paulis.py convention) at the
    static local/shard boundary ``nloc``: returns (local_lo, local_hi,
    shard_mask) where shard_mask bit j corresponds to global bit
    nloc + j.  Parity factorises over the split, so a global parity sign
    is the product of a per-shard scalar sign and the local sign."""
    from ..ops.paulis import _PAR_LO_BITS as _L

    if nloc <= _L:
        loc_lo = zlo & jnp.uint32((1 << nloc) - 1)
        loc_hi = jnp.uint32(0)
        sm = zlo >> nloc
        if nloc + r > _L:
            sm = sm | (zhi << (_L - nloc))
    else:
        loc_lo = zlo
        loc_hi = zhi & jnp.uint32((1 << (nloc - _L)) - 1)
        sm = zhi >> (nloc - _L)
    return loc_lo, loc_hi, sm & jnp.uint32((1 << r) - 1)


def _shard_parity_sign(shard_mask, dt):
    """(+1/-1) scalar sign of parity(shard_index & shard_mask)."""
    idx = lax.axis_index(AMP_AXIS).astype(jnp.uint32)
    odd = lax.population_count(idx & shard_mask) & jnp.uint32(1)
    return 1.0 - 2.0 * odd.astype(dt)


def _parity_phase_sharded(local, theta, zlo, zhi, nloc: int, r: int):
    """exp(-i theta/2 (-1)^parity(global_idx & zmask)) per shard — the
    sharded form of ops/paulis._parity_phase_mask: the global parity sign
    is the local-index sign times a per-shard scalar."""
    from ..ops import paulis as _paulis

    loc_lo, loc_hi, sm = _split_parity_mask(zlo, zhi, nloc, r)
    s_loc = _paulis._parity_sign_dynamic(loc_lo, loc_hi, nloc, local.dtype)
    s_sh = _shard_parity_sign(sm, local.dtype)
    ang = -0.5 * theta
    return cplx.cmul(local, jnp.cos(ang), jnp.sin(ang) * s_sh * s_loc)


def _split_flip_mask(codes, nq: int, offset: int, nloc: int, r: int):
    """TRACED X|Y flip mask of a Pauli-code row acting on qubits
    [offset, offset+nq), split at the static local/shard boundary:
    (fm_lo, fm_hi) over the LOCAL bits — the row/lane split of
    ops/paulis._flip_gather at _GATHER_LO_BITS — plus the mesh-coordinate
    flip mask (bit j = global bit nloc + j), which selects the static
    ppermute branch in _mesh_flip_gather."""
    from ..ops import paulis as _paulis

    lo = min(_paulis._GATHER_LO_BITS, nloc)
    fm_lo = jnp.uint32(0)
    fm_hi = jnp.uint32(0)
    sfm = jnp.uint32(0)
    for q in range(nq):
        c = codes[q]
        fbit = ((c == _paulis.PAULI_X) | (c == _paulis.PAULI_Y)) \
            .astype(jnp.uint32)
        pos = q + offset
        if pos < lo:
            fm_lo = fm_lo | (fbit << pos)
        elif pos < nloc:
            fm_hi = fm_hi | (fbit << (pos - lo))
        else:
            sfm = sfm | (fbit << (pos - nloc))
    return fm_lo, fm_hi, sfm


def _mesh_flip_gather(local, fm_lo, fm_hi, sfm, nloc: int, ndev: int):
    """psi[global_idx ^ fm] restricted to this shard, with a TRACED flip
    mask whose mesh-coordinate part ``sfm`` cannot ride a static
    ppermute directly: lax.switch over the 2^r possible mesh-flip masks,
    each branch ONE composed static XOR ppermute (branch 0 = identity),
    composed with the local split-axis gather.  r <= 4 keeps the branch
    count <= 16 and the whole term is ONE compiled body — all shards
    take the same branch (``sfm`` derives from the replicated code row),
    so the collective inside the conditional is uniform SPMD."""
    from ..ops import paulis as _paulis

    def _branch(k):
        if k == 0:
            return lambda x: x
        perm = [(i, i ^ k) for i in range(ndev)]
        return lambda x, _p=perm: lax.ppermute(x, AMP_AXIS, _p)

    recv = lax.switch(sfm.astype(jnp.int32),
                      [_branch(k) for k in range(ndev)], local)
    return _paulis._flip_gather(recv, fm_lo, fm_hi, nloc)


def _apply_pauli_sharded(local, codes, nq: int, offset: int, nloc: int,
                         r: int, ndev: int, conj: bool):
    """(P psi) on this shard's slab + the all-identity flag — the direct
    split-axis-gather term body (ops/paulis._apply_pauli_traced) lifted
    into a shard_map kernel: the flip permutation factors into a mesh-bit
    XOR (one composed static ppermute via _mesh_flip_gather) times a
    local XOR gather, and the parity sign into a per-shard scalar times
    the local sign vector (both exact +-1, so the result is bit-identical
    to the unsharded body on the gathered state)."""
    from ..ops import paulis as _paulis

    dt = local.dtype
    n = nloc + r
    fm_lo, fm_hi, sfm = _split_flip_mask(codes, nq, offset, nloc, r)
    # parity mask / Y count over GLOBAL bits (the flip split above is
    # what differs from the unsharded _direct_masks)
    _, _, zlo, zhi, ny = _paulis._direct_masks(codes, nq, offset, n)
    loc_lo, loc_hi, sm = _split_parity_mask(zlo, zhi, nloc, r)
    s = _shard_parity_sign(sm, dt) \
        * _paulis._parity_sign_dynamic(loc_lo, loc_hi, nloc, dt)
    c_re, c_im = _paulis._iexp_factor(ny, dt)
    if conj:
        c_im = -c_im
    pv = _mesh_flip_gather(local, fm_lo, fm_hi, sfm, nloc, ndev)
    pr = s * (c_re * pv[0] - c_im * pv[1])
    pi = s * (c_re * pv[1] + c_im * pv[0])
    return jnp.stack([pr, pi]), (fm_lo | fm_hi | sfm | zlo | zhi) == 0


def _direct_rotation_sharded(local, codes, ang, nq: int, offset: int,
                             nloc: int, r: int, ndev: int, conj: bool):
    """e^{-i ang/2 P} psi on this shard in ONE (possibly exchanged)
    gather + fused combine — the sharded form of
    ops/paulis._direct_rotation, closing the one-kernel-set performance
    gap (~8x) the rotate/phase/unrotate conjugation body left on meshes
    (VERDICT round 5 item (a))."""
    dt = local.dtype
    pv, is_identity = _apply_pauli_sharded(local, codes, nq, offset, nloc,
                                           r, ndev, conj)
    theta = jnp.where(is_identity, jnp.asarray(0.0, dt), ang)
    co = jnp.cos(0.5 * theta)
    si = jnp.sin(0.5 * theta)
    return jnp.stack([co * local[0] + si * pv[1],
                      co * local[1] - si * pv[0]])


def trotter_scan_sharded(amps, codes_seq, angles, *, mesh: Mesh,
                         num_qubits: int, rep_qubits: int,
                         chunks: Optional[int] = None):
    """The whole Trotter gate stream on a SHARDED register as ONE
    shard_map(lax.scan) program — the same one-compiled-term-body design
    as ops/paulis.trotter_scan, with the per-term basis-rotation layers
    applying local qubits through the per-shard window kernels and
    mesh-coordinate qubits through chunk-pipelined ppermute exchange
    (_apply_1q_mesh_bit -> exchange_pipelined), and the parity phase
    split into local x per-shard-scalar signs.  This makes the
    one-kernel-set contract (QuEST_internal.h:63-292) hold for
    applyTrotterCircuit on real multi-chip meshes: the reference's
    agnostic_applyTrotterCircuit (QuEST_common.c:752-834) likewise rides
    the same distributed kernels.

    Term body: the DIRECT Pauli rotation (one mesh-flip ppermute branch
    + local split-axis XOR gather + fused combine, _direct_rotation_
    sharded) whenever the shard-local space fits the gather's int32
    invariant — at most 1 composed ppermute per rotation (2 per term for
    a density matrix: ket + bra twin).  Beyond _DIRECT_MAX_N local bits
    the rotate/phase/unrotate conjugation body with its 2*r*C chunked
    ppermutes per term remains as the fallback."""
    from ..ops import paulis as _paulis

    r = num_shard_bits(mesh)
    nloc = num_qubits - r
    direct = nloc <= _paulis._DIRECT_MAX_N
    if chunks is None:
        chunks = exchange_chunks(_shard_payload_bytes(amps, mesh))
    nterms = int(codes_seq.shape[0])
    if direct:
        chunks = 1  # the switch branch exchange is monolithic
        nex = (2 if num_qubits == 2 * rep_qubits else 1) * nterms
    else:
        nex = 2 * r * nterms
    if nex:
        _record_exchange_tiers(
            amps, "trotter",
            _sweep_exchange_tiers(nex, r, _shard_payload_bytes(amps, mesh),
                                  mesh_topology(mesh), direct), chunks)
    return _trotter_scan_sharded(
        amps, codes_seq, angles, mesh=mesh, num_qubits=num_qubits,
        rep_qubits=rep_qubits, chunks=int(chunks))


@partial(jax.jit,
         static_argnames=("mesh", "num_qubits", "rep_qubits", "chunks"),
         donate_argnums=0)
def _trotter_scan_sharded(amps, codes_seq, angles, *, mesh: Mesh,
                          num_qubits: int, rep_qubits: int, chunks: int):
    from ..ops import paulis as _paulis

    n, nq = num_qubits, rep_qubits
    ndev = amp_axis_size(mesh)
    r = num_shard_bits(mesh)
    nloc = n - r
    dt = amps.dtype
    is_density = n == 2 * nq
    chunks = min(_pow2_floor(chunks), 1 << nloc)
    direct = nloc <= _paulis._DIRECT_MAX_N

    if direct:
        def body(carry, inp):
            codes, ang = inp
            ang = ang.astype(dt)
            carry = _direct_rotation_sharded(carry, codes, ang, nq, 0,
                                             nloc, r, ndev, conj=False)
            if is_density:
                carry = _direct_rotation_sharded(carry, codes, -ang, nq,
                                                 nq, nloc, r, ndev,
                                                 conj=True)
            return carry, None
    else:
        def layer(local, mats):
            local = _paulis._product_layer(local, mats[:nloc], nloc)
            for q in range(nloc, n):
                local = _apply_1q_mesh_bit(local, mats[q], q - nloc, ndev,
                                           chunks)
            return local

        body = _paulis.make_trotter_body(
            dt, nq, is_density, layer=layer,
            parity_phase=lambda carry, theta, zlo, zhi:
                _parity_phase_sharded(carry, theta, zlo, zhi, nloc, r),
        )

    def kernel(local, codes_seq, angles):
        out, _ = jax.lax.scan(body, local, (codes_seq, angles))
        return out

    return shard_map(
        kernel, mesh=mesh,
        in_specs=(P(None, AMP_AXIS), P(), P()),
        out_specs=P(None, AMP_AXIS), check_vma=False,
    )(amps, codes_seq, angles)


def expec_pauli_sum_scan_sharded(amps, codes_seq, coeffs, *, mesh: Mesh,
                                 num_qubits: int, quad: bool = False,
                                 chunks: Optional[int] = None):
    """Re <psi| sum_t c_t P_t |psi> on a SHARDED statevector as ONE
    shard_map(lax.scan) — the sharded form of
    ops/paulis.expec_pauli_sum_scan: per term, basis-rotate per shard
    (chunk-pipelined ppermute for sharded qubits), reduce the
    parity-signed norm locally with the shard-scalar sign factored out,
    and psum ONCE at the end (the reference's local-reduce +
    MPI_Allreduce, QuEST_cpu_distributed.c:35-51).

    Term body: the direct form Re <psi| P |psi> = sum_i (psi_r pr +
    psi_i pi) with (pr, pi) = P psi from ONE mesh-flip ppermute branch +
    local XOR gather (_apply_pauli_sharded) — at most 1 composed
    ppermute per term — whenever the shard-local space fits the gather;
    the rotate-layer fallback (r*C ppermutes per term) covers the rest."""
    from ..ops import paulis as _paulis

    r = num_shard_bits(mesh)
    nloc = num_qubits - r
    direct = nloc <= _paulis._DIRECT_MAX_N
    if chunks is None:
        chunks = exchange_chunks(_shard_payload_bytes(amps, mesh))
    nterms = int(codes_seq.shape[0])
    if direct:
        chunks = 1  # the switch branch exchange is monolithic
        nex = nterms
    else:
        nex = r * nterms
    if nex:
        _record_exchange_tiers(
            amps, "expec",
            _sweep_exchange_tiers(nex, r, _shard_payload_bytes(amps, mesh),
                                  mesh_topology(mesh), direct), chunks)
    return _expec_pauli_sum_scan_sharded(
        amps, codes_seq, coeffs, mesh=mesh, num_qubits=num_qubits,
        quad=quad, chunks=int(chunks))


@partial(jax.jit, static_argnames=("mesh", "num_qubits", "quad", "chunks"))
def _expec_pauli_sum_scan_sharded(amps, codes_seq, coeffs, *, mesh: Mesh,
                                  num_qubits: int, quad: bool, chunks: int):
    from ..ops import paulis as _paulis

    n = num_qubits
    ndev = amp_axis_size(mesh)
    r = num_shard_bits(mesh)
    nloc = n - r
    dt = amps.dtype
    chunks = min(_pow2_floor(chunks), 1 << nloc)
    direct = nloc <= _paulis._DIRECT_MAX_N

    def layer(local, mats):
        phi = _paulis._product_layer(local, mats[:nloc], nloc)
        for q in range(nloc, n):
            phi = _apply_1q_mesh_bit(phi, mats[q], q - nloc, ndev, chunks)
        return phi

    def signed_norm(phi, zlo, zhi):
        loc_lo, loc_hi, sm = _split_parity_mask(zlo, zhi, nloc, r)
        s = _paulis._parity_sign_dynamic(loc_lo, loc_hi, nloc, dt)
        s_sh = _shard_parity_sign(sm, dt)
        if quad:
            from ..ops import calculations as _calc
            return s_sh * _calc.quad_sum2(s * phi[0] * phi[0],
                                          s * phi[1] * phi[1])
        return s_sh * jnp.sum(s * (phi[0] * phi[0] + phi[1] * phi[1]))

    def kernel(local, codes_seq, coeffs):
        from ..ops import calculations as _calc
        if direct:
            def body(acc, inp):
                codes, coeff = inp
                pv, _ = _apply_pauli_sharded(local, codes, n, 0, nloc, r,
                                             ndev, conj=False)
                if quad:
                    v = _calc.quad_sum2(local[0] * pv[0], local[1] * pv[1])
                else:
                    v = jnp.sum(local[0] * pv[0] + local[1] * pv[1])
                v = coeff.astype(dt) * v
                return acc + v, v
        else:
            body = _paulis.make_expec_term_value(
                dt, n, layer=layer, signed_norm=signed_norm)(local)
        tot, vals = jax.lax.scan(body, jnp.zeros((), dt),
                                 (codes_seq, coeffs))
        if not quad:
            return lax.psum(tot, AMP_AXIS)
        # quad: per-shard double-double partials, then ONE all-gather of
        # the (T,) per-shard term values and a deterministic Neumaier
        # combine over the (T, ndev) grid — a plain psum would re-lose
        # cross-shard cancellation at f64 exactly where the reference's
        # MPI_Allreduce of long doubles would not
        # (QuEST_cpu_distributed.c:35-51).  The gathered payload is
        # T*ndev scalars — not a state gather.
        g = lax.all_gather(vals, AMP_AXIS)          # (ndev, T)
        return _calc.neumaier_sum(g.T.reshape(-1))

    return shard_map(
        kernel, mesh=mesh,
        in_specs=(P(None, AMP_AXIS), P(), P()),
        out_specs=P(), check_vma=False,
    )(amps, codes_seq, coeffs)


def mix_two_qubit_depol_sharded(amps, prob, *, mesh: Mesh, num_qubits: int,
                                qubit1: int, qubit2: int):
    """Explicit distributed two-qubit depolarising: the double-flip orbit
    sum S = (1 + F2)(1 + F1) rho computed with AT MOST 2 collectives
    (one ppermute per flip whose bra bit is a mesh-coordinate bit — the
    recursive-doubling trick makes the 4-partner sum cost 2 exchanges,
    where the reference's distributed algorithm is a 3-part
    pack-and-exchange, QuEST_cpu_distributed.c:553-852), then one fused
    elementwise combine (see ops/density.mix_two_qubit_depolarising for
    the block formula)."""
    nloc = 2 * num_qubits - num_shard_bits(mesh)
    t = mesh_topology(mesh)
    payload = _shard_payload_bytes(amps, mesh)
    parts = {"ici": [0, 0], "dcn": [0, 0]}
    for q in (qubit1, qubit2):
        b = q + num_qubits
        if b < nloc:
            continue  # double flip fully shard-local: no exchange
        xor_mask = 1 << (b - nloc)
        if q >= nloc:
            xor_mask |= 1 << (q - nloc)
        acc = parts[t.tier_of_mask(xor_mask)]
        acc[0] += 1
        acc[1] += payload
    if parts["ici"][0] or parts["dcn"][0]:
        _record_exchange_tiers(
            amps, "depol2", {k: tuple(v) for k, v in parts.items()}, 1)
    return _mix_two_qubit_depol_sharded(
        amps, prob, mesh=mesh, num_qubits=num_qubits, qubit1=qubit1,
        qubit2=qubit2)


@partial(jax.jit,
         static_argnames=("mesh", "num_qubits", "qubit1", "qubit2"),
         donate_argnums=0)
def _mix_two_qubit_depol_sharded(amps, prob, *, mesh: Mesh, num_qubits: int,
                                 qubit1: int, qubit2: int):
    nq = num_qubits
    nn = 2 * nq
    ndev = amp_axis_size(mesh)
    r = num_shard_bits(mesh)
    nloc = nn - r
    dt = amps.dtype
    t1, b1 = qubit1, qubit1 + nq
    t2, b2 = qubit2, qubit2 + nq
    from ..ops import kernels as K

    hi, lo = K._split2(nloc)

    def kernel(local, p):
        idx = lax.axis_index(AMP_AXIS)

        def dflip(x, t, b):
            # flip ket bit t AND bra bit b (t < b always: t < nq <= b)
            if b < nloc:
                return K._flip_bits_flat(
                    x.reshape(2, -1), nloc, (t, b)).reshape(x.shape)
            if t < nloc:
                perm = _hypercube_perm(ndev, b - nloc)
                recv = lax.ppermute(x, AMP_AXIS, perm)
                return K._flip_bits_flat(
                    recv.reshape(2, -1), nloc, (t,)).reshape(x.shape)
            perm = [(i, i ^ (1 << (t - nloc)) ^ (1 << (b - nloc)))
                    for i in range(ndev)]
            return lax.ppermute(x, AMP_AXIS, perm)

        s = local + dflip(local, t1, b1)
        s = s + dflip(s, t2, b2)

        def bitval(pos):
            if pos < nloc:
                return K.bit_2d(nloc, pos).astype(dt)
            return ((idx >> (pos - nloc)) & 1).astype(dt)

        def same(t, b):
            d = bitval(t) - bitval(b)
            return 1 - d * d

        block = same(t1, b1) * same(t2, b2)     # scalar/2-d broadcast mix
        c1 = 1 - 16 * p / 15
        c2 = 4 * p / 15
        v = local.reshape(2, 1 << hi, 1 << lo)
        sv = s.reshape(2, 1 << hi, 1 << lo)
        out = v * c1 + sv * jnp.broadcast_to(
            c2 * block, (1 << hi, 1 << lo))[None]
        return out.reshape(local.shape)

    return shard_map(
        kernel, mesh=mesh, in_specs=(P(None, AMP_AXIS), P()),
        out_specs=P(None, AMP_AXIS),
    )(amps, jnp.asarray(prob, dt))


@partial(jax.jit, static_argnames=("mesh", "num_qubits"), donate_argnums=0)
def apply_diag_op_density_sharded(amps, op_re, op_im, *, mesh: Mesh,
                                  num_qubits: int):
    """applyDiagonalOp on a SHARDED rho: D.rho scales element (row, col)
    by D[row]; rows live in the LOW n index bits, so every shard needs
    the whole operator — replicate the (small) op with exactly TWO
    explicit all_gathers (re, im), never touching the state's sharding:
    the reference's copyDiagOpIntoMatrixPairState ring-of-broadcasts
    (QuEST_cpu_distributed.c:1548-1587)."""
    nq = num_qubits
    nn = 2 * nq
    r = num_shard_bits(mesh)
    nloc = nn - r
    assert nloc >= nq, "op rows must be shard-local (r <= num_qubits)"
    dt = amps.dtype

    def kernel(local, re, im):
        re_full = lax.all_gather(re, AMP_AXIS, axis=0, tiled=True)
        im_full = lax.all_gather(im, AMP_AXIS, axis=0, tiled=True)
        v = local.reshape(2, 1 << (nloc - nq), 1 << nq)
        out = cplx.cmul(v, re_full.astype(dt)[None], im_full.astype(dt)[None])
        return out.reshape(local.shape)

    return shard_map(
        kernel, mesh=mesh,
        in_specs=(P(None, AMP_AXIS), P(AMP_AXIS), P(AMP_AXIS)),
        out_specs=P(None, AMP_AXIS), check_vma=False,
    )(amps, op_re, op_im)


def _ladder_phase_chunks(nbits: int, t_eff: int, sgn: float, dt):
    """Host tables factorizing exp(sgn*i*pi*li / 2^t_eff) over 7-bit chunks
    of the ``nbits``-bit index li (an exponential of a sum of per-bit
    contributions — cf. kernels.apply_qft_ladder's table factorization).
    Returns [(width, (2, 2^width) table), ...] low chunk first."""
    import numpy as np

    out = []
    p = 0
    while p < nbits:
        w = min(7, nbits - p)
        j = np.arange(1 << w, dtype=np.float64)
        ang = sgn * np.pi * (j * (1 << p)) / (1 << t_eff)
        out.append((w, np.stack([np.cos(ang), np.sin(ang)]).astype(dt)))
        p += w
    return out


def _apply_local_phase(local, chunks, skip: int = 0):
    """Elementwise multiply by the factored phase over the local index
    bits [skip, nloc) — ``skip`` > 0 leaves a trailing untouched 2^skip
    axis (partial-run ladders whose low end starts above bit 0)."""
    widths = [w for w, _ in chunks]
    shape = [2] + [1 << w for w in reversed(widths)]
    if skip:
        shape.append(1 << skip)
    v = local.reshape(shape)
    ndim = len(shape) - 1
    off = 1 if skip else 0
    for ci, (w, tab) in enumerate(chunks):
        bshape = [1] * ndim
        bshape[ndim - 1 - ci - off] = 1 << w
        v = cplx.cmul(v, jnp.asarray(tab[0]).reshape(bshape),
                      jnp.asarray(tab[1]).reshape(bshape))
    return v.reshape(local.shape)


def _qft_mesh_layer(local, idx, t: int, base: int, nloc: int, ndev: int,
                    sgn: float, dt):
    """One mesh-bit QFT layer (target t >= nloc) inside a shard_map body:
    full-shard ppermute H-exchange (the reference's pairwise exchange,
    QuEST_cpu_distributed.c:854-928) + the controlled-phase ladder over
    run bits [base, t), its phase split into a per-shard scalar (the
    sharded ladder bits) times factored local tables.  Shared by
    fused_qft_sharded (base = 0) and fused_qft_runs_sharded (any base)."""
    bit = t - nloc
    mybit = (idx >> bit) & 1
    recv = lax.ppermute(local, AMP_AXIS, _hypercube_perm(ndev, bit))
    s = jnp.where(mybit == 0, jnp.asarray(1.0, dt), jnp.asarray(-1.0, dt))
    comb = (local * s + recv) * jnp.asarray(0.7071067811865476, dt)
    sb = max(base - nloc, 0)       # shard-bit start of the ladder
    width = bit - sb
    ph = comb
    if base < nloc:
        chunks = _ladder_phase_chunks(nloc - base, t - base, sgn, dt)
        ph = _apply_local_phase(ph, chunks, skip=base)
    if width:
        mlow = ((idx >> sb) & ((1 << width) - 1)).astype(dt)
        theta = jnp.asarray(sgn * math.pi, dt) * mlow / (1 << width)
        ph = cplx.cmul(ph, jnp.cos(theta), jnp.sin(theta))
    return jnp.where(mybit == 1, ph, comb)


def fused_qft_sharded(amps, *, mesh: Mesh, num_qubits: int,
                      conj: bool = False):
    """Full-register QFT on a SHARDED statevector, one shard_map end to
    end — the explicit-collective redesign of the reference's distributed
    QFT (agnostic_applyQFT, QuEST_common.c:836-898, whose H sweeps ride
    exchangeStateVectors):

      * mesh-bit layers (target >= nloc): ONE full-shard ``ppermute``
        (the reference's pairwise exchange) + a fused elementwise
        H-combine x controlled-phase ladder, with the phase split into a
        per-shard scalar (the sharded index part) times factored local
        tables;
      * local layers: the same Pallas ladder kernels every backend uses
        (QuEST_internal.h:63-292 one-kernel-set contract), running
        per-shard inside the shard_map;
      * the final bit reversal: two LOCAL reversals + ONE
        ``lax.all_to_all`` — the lanes<->mesh-bits block swap
        rev[0,n) = rev[0,r) o all_to_all o (rev[0,r) x rev[r,nloc)).

    Collectives: r ppermutes + 1 all_to_all, all riding ICI.
    """
    r = num_shard_bits(mesh)
    if r:
        payload = _shard_payload_bytes(amps, mesh)
        ndev = amp_axis_size(mesh)
        t = mesh_topology(mesh)
        # r full-shard H-exchanges (one per mesh bit, so the tier split
        # is exactly per-bit) + the reversal all_to_all, which moves
        # every block but the diagonal one: (ndev-1)/ndev of a shard —
        # ndev-chips of those blocks cross hosts
        a2a_total = (payload * (ndev - 1)) // ndev
        a2a_dcn = (payload * (ndev - t.chips)) // ndev
        multi = t.dcn_bits > 0
        _record_exchange_tiers(
            amps, "qft",
            {"ici": (t.ici_bits + (0 if multi else 1),
                     t.ici_bits * payload + (a2a_total - a2a_dcn)),
             "dcn": (t.dcn_bits + (1 if multi else 0),
                     t.dcn_bits * payload + a2a_dcn)}, 1)
    return _fused_qft_sharded(amps, mesh=mesh, num_qubits=num_qubits,
                              conj=conj)


@partial(jax.jit, static_argnames=("mesh", "num_qubits", "conj"),
         donate_argnums=0)
def _fused_qft_sharded(amps, *, mesh: Mesh, num_qubits: int,
                       conj: bool = False):
    from ..ops import fused as _fused

    n = num_qubits
    ndev = amp_axis_size(mesh)
    r = num_shard_bits(mesh)
    nloc = n - r
    dt = amps.dtype
    sgn = -1.0 if conj else 1.0
    use_multilayer = (_fused.qft_multilayer_enabled(dt)
                      and nloc >= _fused.CLUSTER_QUBITS + 1)
    radix = _fused._qft_radix()

    def kernel(local):
        idx = lax.axis_index(AMP_AXIS)
        # mesh-bit layers, high to low (shared helper — see _qft_mesh_layer)
        for t in range(n - 1, nloc - 1, -1):
            local = _qft_mesh_layer(local, idx, t, 0, nloc, ndev, sgn, dt)
        # local layers, per shard: multilayer (radix-2^k) passes when the
        # shard is big enough — the SAME grouping helper the unsharded
        # path uses (fused.apply_qft_multilayer_ladders) — else per-layer
        # Pallas ladders for t >= 7 and the XLA elementwise ladder below
        # (a dense window-pass fold here can overflow scoped VMEM when
        # XLA promotes a small shard into VMEM inside this one big
        # program).  NB use_multilayer/radix resolve at TRACE time (the
        # env toggles are frozen into any enclosing jit's cache).
        if use_multilayer:
            local = _fused.apply_qft_multilayer_ladders(
                local, num_qubits=nloc, conj=conj, t_top=nloc - 1,
                radix=radix)
            low_start = _fused.LANE_QUBITS - 1
        else:
            low_start = nloc - 1
        for t in range(low_start, -1, -1):
            local = kernels.apply_qft_ladder(
                local, num_qubits=nloc, target=t, conj=conj)
        # bit reversal: L1 local, all_to_all block swap, L2 local
        # (L1 = rev[0,r) x rev[r,nloc); perm[q] = input qubit at output q)
        if r:
            perm1 = tuple([r - 1 - q for q in range(r)]
                          + [r + (nloc - 1 - q) for q in range(r, nloc)])
            local = kernels.permute_qubits(local, num_qubits=nloc,
                                           perm=perm1)
            v = local.reshape(2, 1 << (nloc - r), 1 << r)
            v = lax.all_to_all(v, AMP_AXIS, split_axis=2, concat_axis=2,
                               tiled=False)
            local = v.reshape(2, -1)
            perm2 = tuple([r - 1 - q for q in range(r)]
                          + list(range(r, nloc)))
            local = kernels.permute_qubits(local, num_qubits=nloc,
                                           perm=perm2)
        else:
            perm = tuple(nloc - 1 - q for q in range(nloc))
            local = kernels.permute_qubits(local, num_qubits=nloc, perm=perm)
        return local

    return shard_map(
        kernel, mesh=mesh, in_specs=P(None, AMP_AXIS),
        out_specs=P(None, AMP_AXIS), check_vma=False,
    )(amps)


def _reverse_run_sharded(local, base: int, count: int, nloc: int,
                         ndev: int):
    """Bit reversal of the contiguous run [base, base+count) of a sharded
    register, inside a shard_map body.  The reversal is a set of disjoint
    bit swaps (base+i <-> base+count-1-i); each class costs:

      * local-local  : folded into ONE per-shard axis permutation;
      * mesh-mesh    : folded into ONE composed full-shard ppermute
        (a pure shard-index permutation);
      * local-mesh   : one half-shard ppermute each (the swap_sharded
        exchange: only the mismatched half moves,
        QuEST_cpu_distributed.c:1397-1436).
    """
    top = base + count
    perm_local = list(range(nloc))
    mesh_pairs = []
    mixed = []
    for i in range(count // 2):
        p, q = base + i, top - 1 - i
        if q < nloc:
            perm_local[p], perm_local[q] = perm_local[q], perm_local[p]
        elif p >= nloc:
            mesh_pairs.append((p - nloc, q - nloc))
        else:
            mixed.append((p, q - nloc))
    if perm_local != list(range(nloc)):
        local = kernels.permute_qubits(local, num_qubits=nloc,
                                       perm=tuple(perm_local))
    if mesh_pairs:
        def sig(i):
            j = i
            for a, b in mesh_pairs:
                ba, bb = (i >> a) & 1, (i >> b) & 1
                j = (j & ~((1 << a) | (1 << b))) | (ba << b) | (bb << a)
            return j

        local = lax.ppermute(local, AMP_AXIS,
                             [(i, sig(i)) for i in range(ndev)])
    for lb, mb in mixed:
        # QFT bit reversals stay monolithic (chunks=1): the reversal is a
        # pure relabeling with no combine math to hide the transfer behind
        local = _swap_halves_in_shard(local, lb, mb, nloc, ndev)
    return local


def qft_runs_exchange_model(runs, nloc: int, itemsize: int = 8):
    """(collective count, per-shard ICI bytes) of fused_qft_runs_sharded
    for ``runs`` — the cost-model companion of circuit.remap_exchange_bytes:
    per run reaching mesh bits, one full-shard ppermute per mesh-bit
    layer, one half-shard exchange per mixed reversal pair, and one
    composed full-shard ppermute when any mesh<->mesh reversal pairs
    fold (matching _reverse_run_sharded's class folding).  Fully-local
    runs cost zero."""
    shard = 2 * (1 << nloc) * itemsize
    count = 0
    nbytes = 0
    for base, cnt, _conj in runs:
        top = base + cnt
        layers = max(0, top - max(base, nloc))
        count += layers
        nbytes += layers * shard
        mixed = mesh_pairs = 0
        for i in range(cnt // 2):
            p, q = base + i, top - 1 - i
            if q < nloc:
                continue
            if p >= nloc:
                mesh_pairs += 1
            else:
                mixed += 1
        if mesh_pairs:
            count += 1
            nbytes += shard
        count += mixed
        nbytes += mixed * (shard // 2)
    return count, nbytes


def qft_runs_exchange_tiers(runs, nloc: int, itemsize: int = 8,
                            topology: Optional["topo.Topology"] = None):
    """Tier split of qft_runs_exchange_model: each mesh-bit layer and
    each mixed reversal pair carries a specific mesh bit (its tier is
    that bit's), the composed mesh<->mesh reversal ppermute is DCN iff
    it moves a host bit.  Sums exactly to the flat model."""
    t = topology
    shard = 2 * (1 << nloc) * itemsize
    parts = {"ici": [0, 0], "dcn": [0, 0]}

    def tier_of(mesh_bit):
        return t.tier_of_bit(mesh_bit) if t is not None else "ici"

    for base, cnt, _conj in runs:
        top = base + cnt
        for q in range(max(base, nloc), top):      # mesh-bit layers
            acc = parts[tier_of(q - nloc)]
            acc[0] += 1
            acc[1] += shard
        mesh_mask = 0
        for i in range(cnt // 2):
            p, q = base + i, top - 1 - i
            if q < nloc:
                continue
            if p >= nloc:
                mesh_mask |= (1 << (p - nloc)) | (1 << (q - nloc))
            else:
                acc = parts[tier_of(q - nloc)]     # mixed half-shard swap
                acc[0] += 1
                acc[1] += shard // 2
        if mesh_mask:
            tier = (t.tier_of_mask(mesh_mask) if t is not None else "ici")
            parts[tier][0] += 1
            parts[tier][1] += shard
    return {k: (v[0], v[1]) for k, v in parts.items()}


def fused_qft_runs_sharded(amps, *, mesh: Mesh, num_qubits: int,
                           runs: Tuple[Tuple[int, int, bool], ...]):
    """QFT over contiguous qubit runs [(base, count, conj), ...] of a
    SHARDED register, one shard_map end to end — the general-run
    companion of fused_qft_sharded covering partial-register QFTs and the
    density-matrix twin (runs = ket run + conjugated bra run), so
    applyQFT / applyFullQFT run the SAME fused kernel set on real
    multi-chip meshes instead of falling back to the layered path
    (one-kernel-set contract, QuEST_internal.h:63-292; reference
    agnostic_applyQFT, QuEST_common.c:836-898).

    Per run: a FULLY-LOCAL run executes circuit.fused_qft per shard —
    identical multilayer/window passes to the unsharded path; a run
    reaching mesh-coordinate bits runs ppermute H-exchange layers
    (one full-shard ppermute each, phase split into per-shard scalar x
    factored local tables), per-shard ladder kernels for its local
    layers, and the mixed bit reversal of _reverse_run_sharded.

    Collectives for a run with s sharded bits: s ppermutes (layers) +
    at most s reversal ppermutes; fully-local runs cost zero."""
    nloc = num_qubits - num_shard_bits(mesh)
    cnt, _nbytes = qft_runs_exchange_model(runs, nloc, amps.dtype.itemsize)
    if cnt:
        _record_exchange_tiers(
            amps, "qft_runs",
            qft_runs_exchange_tiers(runs, nloc, amps.dtype.itemsize,
                                    mesh_topology(mesh)), 1)
    return _fused_qft_runs_sharded(amps, mesh=mesh, num_qubits=num_qubits,
                                   runs=tuple(runs))


@partial(jax.jit, static_argnames=("mesh", "num_qubits", "runs"),
         donate_argnums=0)
def _fused_qft_runs_sharded(amps, *, mesh: Mesh, num_qubits: int,
                            runs: Tuple[Tuple[int, int, bool], ...]):
    from .. import circuit as CIRC

    n = num_qubits
    ndev = amp_axis_size(mesh)
    r = num_shard_bits(mesh)
    nloc = n - r
    dt = amps.dtype

    def kernel(local):
        idx = lax.axis_index(AMP_AXIS)
        for base, count, conj in runs:
            top = base + count
            sgn = -1.0 if conj else 1.0
            if top <= nloc and nloc >= CIRC.WINDOW:
                # fully-local run on a window-sized shard: the unsharded
                # fused kernels per shard (shards below window size use
                # the per-layer ladder path below instead)
                local = CIRC.fused_qft(local, nloc, base, count,
                                       shifts=(0,), conj_first=conj)
                continue
            # mesh-bit layers, top down (shared helper, _qft_mesh_layer)
            for t in range(top - 1, max(base, nloc) - 1, -1):
                local = _qft_mesh_layer(local, idx, t, base, nloc, ndev,
                                        sgn, dt)
            # local layers per shard (same ladder kernels as unsharded)
            for t in range(min(top, nloc) - 1, base - 1, -1):
                local = kernels.apply_qft_ladder(
                    local, num_qubits=nloc, target=t, base=base, conj=conj)
            local = _reverse_run_sharded(local, base, count, nloc, ndev)
        return local

    return shard_map(
        kernel, mesh=mesh, in_specs=P(None, AMP_AXIS),
        out_specs=P(None, AMP_AXIS), check_vma=False,
    )(amps)


# ---------------------------------------------------------------------------
# Lazy logical->physical qubit remapping (communication avoidance)
#
# mpiQulacs (arXiv:2203.16044) and qHiPSTER (arXiv:1601.07195) both amortize
# the distributed simulator's dominant cost — relocalizing sharded target
# qubits — with circuit-level qubit reordering: the state is kept in a
# PERMUTED physical order, later gate targets are rewritten through the live
# permutation, and data only moves when an upcoming window of gates needs a
# different set of local qubits.  The kernels below implement the batched
# exchange realizing one permutation step; quest_tpu.qureg carries the
# logical->physical map (Qureg._perm) and rematerializes canonical order
# lazily on the first state read.
# ---------------------------------------------------------------------------


def decompose_sigma(sigma: Tuple[int, ...], nloc: int, r: int):
    """Split a physical bit permutation (``sigma[p]`` = destination
    position of the bit currently at physical position ``p``) into the
    cheapest exchange classes — the class folding of _reverse_run_sharded
    generalized from bit reversals to arbitrary permutations:

      * mixed  : one (local_bit, mesh_bit) transposition per local<->mesh
        boundary crossing, each ONE half-shard ppermute (the swap_sharded
        exchange — only the mismatched half moves);
      * local  : everything left on the local side, ONE per-shard axis
        permutation (a permute_qubits arg: out bit q <- in bit perm[q]);
      * mesh   : everything left on the mesh side, ONE composed full-shard
        ppermute (``mesh_tau[b]`` = destination mesh bit of coordinate
        bit b).

    Returns (mixed, local_perm | None, mesh_tau | None), applied in that
    order."""
    n = nloc + r
    cur = list(sigma)
    assert sorted(cur) == list(range(n)), sigma
    mixed = []
    from_local = [p for p in range(nloc) if cur[p] >= nloc]
    from_mesh = {p for p in range(nloc, n) if cur[p] < nloc}
    assert len(from_local) == len(from_mesh)
    for l in from_local:
        # pair each crossing local bit with its DESTINATION mesh slot when
        # that slot itself crosses down — a transposition sigma (the window
        # planner's output) then decomposes into pure mixed swaps with no
        # residual composed mesh permute
        m = cur[l] if cur[l] in from_mesh else min(from_mesh)
        from_mesh.discard(m)
        mixed.append((l, m - nloc))
        cur[l], cur[m] = cur[m], cur[l]
    local_perm = None
    if cur[:nloc] != list(range(nloc)):
        inv = [0] * nloc
        for p in range(nloc):
            inv[cur[p]] = p
        local_perm = tuple(inv)
    mesh_tau = None
    tau = [cur[nloc + b] - nloc for b in range(r)]
    if tau != list(range(r)):
        mesh_tau = tuple(tau)
    return tuple(mixed), local_perm, mesh_tau


def remap_exchange_count(sigma: Tuple[int, ...], nloc: int, r: int) -> int:
    """Number of exchange programs one remap of ``sigma`` dispatches —
    one half-shard ppermute per mixed transposition plus one composed
    full-shard ppermute when a residual mesh permute remains.  This is
    the ``exchanges_total`` increment remap_sharded / the fusion drain
    record per (unbatched) remap; introspect.predict_window_exchanges
    re-derives drain telemetry from it (companion of
    circuit.remap_exchange_bytes on the count axis)."""
    mixed, _local_perm, mesh_tau = decompose_sigma(tuple(sigma), nloc, r)
    return len(mixed) + (1 if mesh_tau is not None else 0)


def remap_exchange_tiers(sigma: Tuple[int, ...], nloc: int, r: int,
                         itemsize: int = 8,
                         topology: Optional["topo.Topology"] = None):
    """Per-tier (count, per-shard bytes) split of one remap's exchange
    program — circuit.remap_exchange_bytes refined by interconnect: each
    mixed half-shard swap carries exactly its mesh bit's tier; the
    composed full-shard ppermute is DCN iff it moves any host bit.
    Tier sums equal the flat (remap_exchange_count,
    remap_exchange_bytes) pair exactly."""
    t = topology if topology is not None else topo.resolve(1 << r)
    mixed, _local_perm, mesh_tau = decompose_sigma(tuple(sigma), nloc, r)
    shard = 2 * (1 << nloc) * itemsize
    parts = {"ici": [0, 0], "dcn": [0, 0]}
    for _lb, mb in mixed:
        acc = parts[t.tier_of_bit(mb)]
        acc[0] += 1
        acc[1] += shard // 2
    if mesh_tau is not None:
        moved = 0
        for b, dst in enumerate(mesh_tau):
            if b != dst:
                moved |= (1 << b) | (1 << dst)
        acc = parts[t.tier_of_mask(moved)]
        acc[0] += 1
        acc[1] += shard
    return {k: (v[0], v[1]) for k, v in parts.items()}


def remap_chunk_plan(nloc: int, itemsize: int = 8,
                     backend: Optional[str] = None) -> Tuple[int, int]:
    """The (half_shard_chunks, full_shard_chunks) pair the
    PIPELINE_MIN_BYTES policy resolves for one per-element shard of
    ``2 * 2^nloc * itemsize`` bytes — the default _remap_in_shard
    computes at trace time, exposed so the plan explainer can predict
    the pipeline split without tracing."""
    nbytes = 2 * (1 << nloc) * itemsize
    return (exchange_chunks(nbytes // 2, backend=backend),
            exchange_chunks(nbytes, backend=backend))


def _remap_in_shard(local, sigma: Tuple[int, ...], nloc: int, ndev: int,
                    chunks: Optional[Tuple[int, int]] = None):
    """Apply the physical bit permutation ``sigma`` INSIDE a shard_map
    body: the mixed half-shard swaps (chunk-pipelined), then one per-shard
    axis permutation, then one composed shard-index ppermute (chunked so
    its transient recv buffer is one chunk) — decompose_sigma.  Shared by
    the standalone remap_sharded program and the fusion drain's
    ("remap", sigma) parts.

    ``chunks``: (half_shard_chunks, full_shard_chunks); None resolves the
    per-op heuristic from the (static) per-shard payload size at trace
    time — the drain executor keys its compiled-program cache on
    exchange_config_key() so an env-override flip retraces."""
    r = int(math.log2(ndev))
    mixed, local_perm, mesh_tau = decompose_sigma(sigma, nloc, r)
    t = topo.resolve(ndev)
    if t.dcn_bits and len(mixed) > 1:
        # DCN-overlap schedule (§17 generalized, docs/design.md §25):
        # issue the slow cross-host half-shard swaps FIRST so XLA's
        # latency-hiding scheduler overlaps their transfers against the
        # subsequent intra-host swaps and the local permute.  Mixed
        # transpositions touch disjoint (local, mesh) bit pairs, so any
        # ordering computes the identical state.
        mixed = tuple(sorted(mixed, key=lambda lm: lm[1] < t.ici_bits))
    if chunks is None:
        chunks = remap_chunk_plan(nloc, local.dtype.itemsize)
    ch_half = min(_pow2_floor(chunks[0]), 1 << max(nloc - 1, 0))
    ch_full = min(_pow2_floor(chunks[1]), 1 << nloc)
    for lb, mb in mixed:
        local = _swap_halves_in_shard(local, lb, mb, nloc, ndev, ch_half)
    if local_perm is not None:
        local = kernels.permute_qubits(local, num_qubits=nloc,
                                       perm=local_perm)
    if mesh_tau is not None:
        def dest(i):
            j = 0
            for b, t in enumerate(mesh_tau):
                j |= ((i >> b) & 1) << t
            return j

        local = exchange_pipelined(
            local, [(i, dest(i)) for i in range(ndev)],
            lambda i, own, rv: rv, chunks=ch_full)
    return local


@sharded_contract(collectives={"collective-permute": 1},
                  max_exchange_bytes=1 << 9,
                  max_tier_bytes={"ici": 1 << 9, "dcn": 1 << 9})
def remap_sharded(amps, *, mesh: Mesh, num_qubits: int,
                  sigma: Tuple[int, ...],
                  chunks: Optional[Tuple[int, int]] = None):
    """ONE batched physical-bit permutation of a sharded register: at most
    (#local<->mesh crossings) chunk-pipelined half-shard exchanges + one
    per-shard axis permutation + one composed (chunked) full-shard
    ppermute, regardless of how many gates the window it serves contains.
    This is the communication the window planner schedules ONCE per window
    where the reference pays two half-shard exchanges per sharded-target
    gate (QuEST_cpu_distributed.c:1447-1545)."""
    if chunks is None:
        nbytes = _shard_payload_bytes(amps, mesh)
        chunks = (exchange_chunks(nbytes // 2), exchange_chunks(nbytes))
    if _telemetry.enabled() and not isinstance(amps, jax.core.Tracer):
        r = num_shard_bits(mesh)
        nloc = num_qubits - r
        bw = int(amps.shape[0]) if amps.ndim == 3 else 1
        tiers = remap_exchange_tiers(tuple(sigma), nloc, r,
                                     amps.dtype.itemsize,
                                     mesh_topology(mesh))
        _record_exchange_tiers(
            amps, "remap",
            {k: (c * bw, b * bw) for k, (c, b) in tiers.items()},
            str(chunks))
    return guarded_dispatch(
        _remap_sharded, amps, op="remap", shards=amp_axis_size(mesh),
        mesh=mesh, num_qubits=num_qubits, sigma=tuple(sigma),
        chunks=(int(chunks[0]), int(chunks[1])))


@partial(jax.jit, static_argnames=("mesh", "num_qubits", "sigma", "chunks"),
         donate_argnums=0)
def _remap_sharded(amps, *, mesh: Mesh, num_qubits: int,
                   sigma: Tuple[int, ...], chunks: Tuple[int, int]):
    ndev = amp_axis_size(mesh)
    r = num_shard_bits(mesh)
    nloc = num_qubits - r
    # a (B, 2, 2^n) register bank (batch.BatchedQureg) remaps every batch
    # element with the SAME sigma — one vmap inside the shard_map kernel,
    # batch-outer/amps-inner, so the composed ppermute moves all elements'
    # shard slices in one exchange
    batched = amps.ndim == 3

    def kernel(local):
        if batched:
            return jax.vmap(
                lambda a: _remap_in_shard(a, sigma, nloc, ndev, chunks)
            )(local)
        return _remap_in_shard(local, sigma, nloc, ndev, chunks)

    spec = P(None, None, AMP_AXIS) if batched else P(None, AMP_AXIS)
    return shard_map(
        kernel, mesh=mesh, in_specs=spec,
        out_specs=spec, check_vma=False,
    )(amps)


def canonical_sigma(perm: Tuple[int, ...]) -> Tuple[int, ...]:
    """The physical permutation rematerializing canonical order from a
    live logical->physical ``perm`` (sigma = perm^-1: the bit at physical
    perm[q] returns to position q)."""
    sigma = [0] * len(perm)
    for q, p in enumerate(perm):
        sigma[p] = q
    return tuple(sigma)


def plan_window_remap(num_qubits: int, nloc: int, perm: Tuple[int, ...],
                      want_local, next_use=None, topology=None):
    """Choose the minimal-movement permutation making every logical qubit
    in ``want_local`` shard-local: qubits already local stay put; each
    sharded one swaps with the local slot whose resident logical qubit is
    needed FURTHEST in the future (``next_use``: logical qubit -> distance
    of its next use; absent = never used again, evicted first — the same
    lookahead policy as the paged planner's eviction choice).

    On a hierarchical topology (``topology``; default resolved from the
    mesh size via QT_TOPOLOGY) the planner is additionally TIER-aware:
    wanted qubits currently parked on DCN mesh bits are serviced first,
    so the coldest evictees (front of the eviction pool) land on the
    slow cross-host slots and the hotter ones stay on intra-host ICI
    axes — later windows that re-fetch them pay ICI, not DCN, bytes.
    The permutation itself is identical in shape (same number of mixed
    swaps), results are bit-identical; only WHERE evictees park changes.
    QT_TOPOLOGY_PLANNER=flat restores the flat ordering for A/B runs.

    Returns (sigma | None, new_perm): ``sigma`` is None when nothing
    moves; (None, None) when ``want_local`` exceeds the local capacity —
    the caller must split the window."""
    n = num_qubits
    perm = list(perm)
    want_local = sorted(set(want_local))
    if len(want_local) > nloc:
        return None, None
    inv = [0] * n
    for q, p in enumerate(perm):
        inv[p] = q
    need = [q for q in want_local if perm[q] >= nloc]
    if not need:
        return None, tuple(perm)
    if topology is None:
        topology = topo.resolve(1 << max(num_qubits - nloc, 0))
    if topo.hierarchical_enabled(topology):
        # DCN-resident wanted qubits first (highest mesh bit first within
        # the tier): they consume the coldest pool slots, which are the
        # ones later evictions would otherwise have to push cross-host.
        need.sort(key=lambda q: (perm[q] - nloc < topology.ici_bits,
                                 -(perm[q] - nloc)))
    wanted = set(want_local)
    pool = [p for p in range(nloc) if inv[p] not in wanted]
    assert len(pool) >= len(need)  # guaranteed by |want_local| <= nloc
    if next_use is None:
        next_use = {}
    pool.sort(key=lambda p: next_use.get(inv[p], 1 << 60), reverse=True)
    sigma = list(range(n))
    for q in need:
        p_high = perm[q]
        p_slot = pool.pop(0)
        q_evicted = inv[p_slot]
        sigma[p_slot], sigma[p_high] = p_high, p_slot
        perm[q], perm[q_evicted] = p_slot, p_high
        inv[p_slot], inv[p_high] = q, q_evicted
    return tuple(sigma), tuple(perm)


def plan_relocalization(
    num_qubits: int,
    nloc: int,
    targets: Tuple[int, ...],
    controls: Tuple[int, ...] = (),
    free_order=None,
):
    """Choose swap pairs pulling every sharded target down to a free local
    qubit (reference picks the lowest free qubit and patches the control
    mask on collision, QuEST_cpu_distributed.c:1508-1531; we instead exclude
    controls from the free pool so the mask never needs patching).

    ``free_order``: optional eviction-preference ordering of the local
    slots (coldest first) — under the lazy permutation the dispatch layer
    passes a least-recently-used ordering so a relocation never evicts the
    qubits the circuit is actively using (the ping-pong that would
    otherwise re-pay the exchange every alternation); default is the
    reference's lowest-first choice.

    Returns (swaps, new_targets), or (None, None) when there aren't enough
    free local qubits — the caller falls back to the GSPMD path (the
    reference instead *rejects* such ops via validateMultiQubitUnitaryMatrix,
    QuEST_validation.c:469-471, so this is strictly more capable)."""
    targets = list(targets)
    blocked = set(targets) | set(controls)
    order = free_order if free_order is not None else range(nloc)
    free_local = [q for q in order if q not in blocked]
    swaps = []
    for i, t in enumerate(targets):
        if t >= nloc:
            if not free_local:
                return None, None
            fq = free_local.pop(0)
            swaps.append((fq, t))
            targets[i] = fq
    return tuple(swaps), tuple(targets)
