"""Explicit distributed kernels: shard_map + ppermute over the amplitude mesh.

TPU-native re-design of the reference's MPI orchestration layer
(QuEST/src/CPU/QuEST_cpu_distributed.c).  The state of n qubits is sharded
over a 1-D device mesh on its leading (most-significant) index bits: with
2^r devices, qubits 0..n-r-1 are *local* (inside each shard) and qubits
n-r..n-1 are *sharded* (their bit IS a mesh-coordinate bit) — exactly the
reference's chunkId scheme (QuEST.h:330-338).

Mapping of the reference's five MPI primitives (SURVEY.md §5.8):

- pairwise full-chunk ``MPI_Sendrecv`` with the XOR-partner rank
  (exchangeStateVectors, :489-517) -> ``lax.ppermute`` with the static
  hypercube permutation [(i, i ^ 2^b)];
- the locality predicate target < log2(chunkSize)
  (halfMatrixBlockFitsInChunk, :366-371) -> a Python-level static branch:
  local targets run the ordinary kernels un-communicated;
- SWAP-relocalization of multi-qubit ops (:1447-1545) -> half-shard
  ppermute swaps (``swap_sharded``) pulling high targets down to free low
  qubits, op applied locally, swaps undone;
- ``MPI_Allreduce`` (:35-117) -> ``lax.psum``;
- ``MPI_Bcast`` replication loops (:379-423) -> ``lax.all_gather``.

Two structural wins over the reference: no pairStateVec — the reference
permanently holds a 2x receive buffer (QuEST_cpu.c:1279-1315) while
ppermute's transient buffer exists only inside one fused program; and the
elementwise combine fuses with the communication epilogue under XLA instead
of being a second pass over memory.

These kernels are *compile-time* alternatives invoked by the API layer when
a gate touches sharded qubits (quest_tpu.api routes there); the GSPMD path
(plain jit + sharding propagation) remains available via
``use_explicit_dist(False)`` for benchmarking one against the other
(SURVEY.md §7 layer 5 calls for exactly this comparison).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from ..env import AMP_AXIS
from ..ops import cplx, kernels

_CONFIG = {"explicit": True}


def use_explicit_dist(enabled: bool) -> None:
    """Toggle the explicit ppermute path vs GSPMD propagation."""
    _CONFIG["explicit"] = bool(enabled)


def explicit_dist_enabled() -> bool:
    return _CONFIG["explicit"]


def amp_axis_size(mesh: Mesh) -> int:
    """Size of the amplitude axis — NOT mesh.devices.size: meshes may carry
    extra axes (e.g. the (dp, amps) training mesh)."""
    return int(mesh.shape[AMP_AXIS])


def num_shard_bits(mesh: Mesh) -> int:
    return int(math.log2(amp_axis_size(mesh)))


def _hypercube_perm(ndev: int, bit: int):
    """Static XOR-partner permutation — the reference's pair-rank computation
    chunkId ^ (2^t / chunkSize) (QuEST_cpu_distributed.c:313-333) as a
    ppermute table."""
    return [(i, i ^ (1 << bit)) for i in range(ndev)]


def _shard_coeffs(rmat_like, mybit):
    """Per-shard gate coefficients a = m[b,b], b_coef = m[b,1-b] selected by
    the shard's target-bit value (statevec_compactUnitaryDistributed,
    QuEST_cpu.c:1841-1900 uses rankIsUpper the same way)."""
    row = mybit
    a_re = rmat_like[0, row, row]
    a_im = rmat_like[1, row, row]
    b_re = rmat_like[0, row, 1 - row]
    b_im = rmat_like[1, row, 1 - row]
    return a_re, a_im, b_re, b_im


@partial(
    jax.jit,
    static_argnames=("mesh", "num_qubits", "target", "controls", "control_states"),
    donate_argnums=0,
)
def apply_matrix_1q_sharded(
    amps,
    matrix,
    *,
    mesh: Mesh,
    num_qubits: int,
    target: int,
    controls: Tuple[int, ...] = (),
    control_states: Tuple[int, ...] = (),
):
    """One-qubit dense gate on a *sharded* target qubit: full-shard ppermute
    exchange + fused elementwise combine — the reference's non-local gate
    pattern (QuEST_cpu_distributed.c:854-928).

    Low (local) controls restrict the exchanged+combined sub-block; sharded
    controls become a per-shard mask (the reference instead skips ranks
    whose chunk fails the control condition, :1093-1112 — SPMD cannot skip,
    but masked shards do no extra communication since the exchange is
    collective anyway)."""
    ndev = amp_axis_size(mesh)
    r = num_shard_bits(mesh)
    n = num_qubits
    nloc = n - r
    assert target >= nloc, "local targets take the ordinary kernel"
    bit = target - nloc
    perm = _hypercube_perm(ndev, bit)

    states = control_states or (1,) * len(controls)
    local_controls = tuple((c, s) for c, s in zip(controls, states) if c < nloc)
    shard_controls = tuple((c - nloc, s) for c, s in zip(controls, states) if c >= nloc)

    def kernel(local, m):
        # local: (2, amps_per_shard); m: (2, 2, 2) stacked SoA
        idx = lax.axis_index(AMP_AXIS)
        mybit = (idx >> bit) & 1
        recv = lax.ppermute(local, AMP_AXIS, perm)
        a_re, a_im, b_re, b_im = _shard_coeffs(m, mybit)

        def combine(own_block, recv_block):
            return cplx.cmul(own_block, a_re, a_im) + cplx.cmul(recv_block, b_re, b_im)

        if local_controls:
            shape, sel = kernels._interleaved_sel(nloc, local_controls)
            lv = local.reshape(shape)
            rv = recv.reshape(shape)
            new = lv.at[sel].set(combine(lv[sel], rv[sel]))
            new = new.reshape(2, -1)
        else:
            new = combine(local, recv)
        for cbit, s in shard_controls:
            cond = ((idx >> cbit) & 1) == s
            new = jnp.where(cond, new, local)
        return new

    return shard_map(
        kernel,
        mesh=mesh,
        in_specs=(P(None, AMP_AXIS), P()),
        out_specs=P(None, AMP_AXIS),
    )(amps, jnp.asarray(matrix, amps.dtype))


@partial(jax.jit, static_argnames=("mesh", "num_qubits", "qb_low", "qb_high"), donate_argnums=0)
def swap_sharded(amps, *, mesh: Mesh, num_qubits: int, qb_low: int, qb_high: int):
    """SWAP between a local qubit and a sharded qubit: exchange only the
    mismatched half-shard with the XOR partner (statevec_swapQubitAmps
    routing, QuEST_cpu_distributed.c:1397-1436: 'pair processes only swap
    half their amps').

    Derivation: for shard-coordinate bit u (the high qubit's value) and
    local bit v (the low qubit), elements with v == u stay; elements with
    v != u land on the pair rank at local bit position unchanged-in-value.
    So each shard sends its v = 1-u half and splices the received half back
    at the same position."""
    ndev = amp_axis_size(mesh)
    r = num_shard_bits(mesh)
    nloc = num_qubits - r
    assert qb_high >= nloc and qb_low < nloc
    bit = qb_high - nloc
    perm = _hypercube_perm(ndev, bit)

    def kernel(local):
        idx = lax.axis_index(AMP_AXIS)
        u = (idx >> bit) & 1
        lv = local.reshape(2, 1 << (nloc - 1 - qb_low), 2, 1 << qb_low)
        # dynamic half-selection: take(lv, 1-u) along the low-qubit axis
        send = lax.dynamic_index_in_dim(lv, 1 - u, axis=2, keepdims=False)
        recv = lax.ppermute(send, AMP_AXIS, perm)
        new = lax.dynamic_update_index_in_dim(lv, recv, 1 - u, axis=2)
        return new.reshape(2, -1)

    return shard_map(
        kernel, mesh=mesh, in_specs=P(None, AMP_AXIS), out_specs=P(None, AMP_AXIS)
    )(amps)


@partial(jax.jit, static_argnames=("mesh",))
def total_prob_sharded(amps, *, mesh: Mesh):
    """|amps|^2 with an explicit psum — the reference's local-reduce +
    MPI_Allreduce(SUM) (QuEST_cpu_distributed.c:1308-1322)."""

    def kernel(local):
        return lax.psum(jnp.sum(cplx.abs2(local)), AMP_AXIS)

    return shard_map(
        kernel, mesh=mesh, in_specs=P(None, AMP_AXIS), out_specs=P()
    )(amps)


@partial(jax.jit, static_argnames=("mesh",))
def gather_replicated(amps, *, mesh: Mesh):
    """Replicate the full state onto every device — the analogue of the
    reference's ring-of-broadcasts copyVecIntoMatrixPairState
    (QuEST_cpu_distributed.c:379-423), used to build rho = |psi><psi|."""

    def kernel(local):
        return lax.all_gather(local, AMP_AXIS, axis=1, tiled=True)

    return shard_map(
        kernel, mesh=mesh, in_specs=P(None, AMP_AXIS), out_specs=P(),
        check_vma=False,
    )(amps)


def plan_relocalization(
    num_qubits: int,
    nloc: int,
    targets: Tuple[int, ...],
    controls: Tuple[int, ...] = (),
):
    """Choose swap pairs pulling every sharded target down to a free local
    qubit (reference picks the lowest free qubit and patches the control
    mask on collision, QuEST_cpu_distributed.c:1508-1531; we instead exclude
    controls from the free pool so the mask never needs patching).

    Returns (swaps, new_targets), or (None, None) when there aren't enough
    free local qubits — the caller falls back to the GSPMD path (the
    reference instead *rejects* such ops via validateMultiQubitUnitaryMatrix,
    QuEST_validation.c:469-471, so this is strictly more capable)."""
    targets = list(targets)
    blocked = set(targets) | set(controls)
    free_local = [q for q in range(nloc) if q not in blocked]
    swaps = []
    for i, t in enumerate(targets):
        if t >= nloc:
            if not free_local:
                return None, None
            fq = free_local.pop(0)
            swaps.append((fq, t))
            targets[i] = fq
    return tuple(swaps), tuple(targets)
