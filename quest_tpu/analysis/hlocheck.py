"""Verify @sharded_contract declarations against compiled HLO.

Each wrapper in :data:`quest_tpu.contracts.REQUIRED_WRAPPERS` declares
the exact collective-opcode histogram and a per-shard exchange-byte cap
for its CANONICAL verification dispatch — a fixed 8-shard CPU-dryrun
configuration chosen here (n=10 state bits, r=3 mesh bits, float32,
monolithic chunking) so the compiled shape is deterministic across
backends and the x64 test flag.  The check compiles each dispatch with
``introspect.audit`` (the same machinery the HLO pin tests use) and
fails when:

* the histogram of collective FAMILIES (``-start`` async variants folded
  into their base opcode) differs from the declaration;
* the bytes moved by the largest collective's operands exceed
  ``max_exchange_bytes`` (parsed from the HLO output shapes);
* a contract declares per-tier caps (``max_tier_bytes``) and the
  largest payload riding either interconnect tier exceeds its cap —
  the 8-shard mesh is read as a forced 2x4 hosts x chips arrangement
  and each collective-permute's compiled ``source_target_pairs`` table
  is classified as ICI (within a host) or DCN (crossing hosts);
* a required wrapper is missing a contract, or a contract names a
  wrapper that no longer exists.

Promoted from scripts/tpu_sharded_contract.py (the on-chip evidence
script); ``make verify-static`` runs this on the virtual 8-device CPU
mesh (XLA_FLAGS=--xla_force_host_platform_device_count=8).
"""

from __future__ import annotations

import os
import re
from typing import Callable, Dict, List, Tuple

# f32[2,64]{1,0} etc. — HLO array shape with element type
_SHAPE_RE = re.compile(r"\b([a-z]+)(8|16|32|64|128)\[([0-9,]*)\]")
_ELEM_BYTES = {"8": 1, "16": 2, "32": 4, "64": 8, "128": 16}

CANONICAL_N = 10          # state bits of the canonical dispatch
CANONICAL_SHARDS = 8      # r = 3 mesh bits

# forced hosts x chips arrangement of the canonical mesh for the
# per-tier byte caps (ShardedContract.max_tier_bytes): the 8 shards are
# read as 2 hosts x 4 chips, so mesh bits 0-1 are ICI and bit 2 is DCN —
# purely a CLASSIFICATION of the compiled routing tables
# (source_target_pairs), no env var or recompilation involved
VERIFY_HOSTS = 2
VERIFY_CHIPS = 4


def _shape_bytes(segment: str) -> int:
    """Largest single-array byte size among the shapes in an HLO text
    segment (the collective's output tuple for -start variants includes
    context scalars; max picks the payload)."""
    best = 0
    for m in _SHAPE_RE.finditer(segment):
        elems = 1
        dims = m.group(3)
        if dims:
            for d in dims.split(","):
                elems *= int(d)
        best = max(best, elems * _ELEM_BYTES[m.group(2)])
    return best


def _family_histogram(collectives: Dict[str, int]) -> Dict[str, int]:
    """Fold -start/-done async opcode variants into their base family
    (a started collective is still one collective)."""
    out: Dict[str, int] = {}
    for op, n in collectives.items():
        fam = op
        for suffix in ("-start", "-done"):
            if fam.endswith(suffix):
                fam = fam[:-len(suffix)]
        if op.endswith("-done"):
            continue  # the matching -start already counted it
        out[fam] = out.get(fam, 0) + n
    return out


def _measured_exchange_bytes(hlo_text: str, families) -> int:
    """Max payload bytes over the contract's collective instructions."""
    best = 0
    for line in hlo_text.splitlines():
        if any(f" {fam}(" in line or f" {fam}-start(" in line
               for fam in families):
            best = max(best, _shape_bytes(line))
    return best


def _measured_tier_bytes(hlo_text: str, families,
                         chips: int) -> Dict[str, int]:
    """Max payload bytes per interconnect tier over the contract's
    collective instructions, reading the canonical mesh as
    ``hosts x chips``.

    Each instruction's compiled ``source_target_pairs`` routing table is
    classified arithmetically: a pair crosses DCN iff the shard ids
    disagree above the chip bits (``src ^ dst >= chips``); an
    instruction rides DCN when any of its pairs cross.  Collectives
    without a routing table (all-gather and friends) span the whole
    mesh and count toward both tiers.
    """
    from quest_tpu.introspect import _PAIR_RE, _PAIRS_RE
    from quest_tpu.parallel import topology

    best = {"ici": 0, "dcn": 0}
    for line in hlo_text.splitlines():
        if not any(f" {fam}(" in line or f" {fam}-start(" in line
                   for fam in families):
            continue
        nbytes = _shape_bytes(line)
        m = _PAIRS_RE.search(line)
        if m is None:
            for tier in best:
                best[tier] = max(best[tier], nbytes)
            continue
        pairs = [(int(a), int(b)) for a, b in _PAIR_RE.findall(m.group(1))]
        split = topology.split_pair_list(pairs, chips)
        if not (split["ici"] or split["dcn"]):
            continue  # self-pairs only: no wire traffic
        tier = "dcn" if split["dcn"] else "ici"
        best[tier] = max(best[tier], nbytes)
    return best


def ensure_mesh():
    """The 8-device virtual CPU mesh the canonical dispatches compile
    against.  Raises RuntimeError (with the fix) when the backend came
    up with fewer devices — XLA_FLAGS must be set before jax's backend
    initializes, so the CLI cannot set it retroactively."""
    import quest_tpu as qt
    env = qt.createQuESTEnv()
    if env.num_ranks < CANONICAL_SHARDS:
        raise RuntimeError(
            f"contract verification needs the {CANONICAL_SHARDS}-device "
            f"virtual mesh, got {env.num_ranks} — run with XLA_FLAGS="
            f"--xla_force_host_platform_device_count="
            f"{CANONICAL_SHARDS} (make verify-static does)")
    return env


def canonical_cases(env) -> Dict[str, Tuple[Callable, object, bool]]:
    """wrapper name -> (dispatch thunk, sharded input, donate flag).

    The configs mirror the HLO pin tests (tests/test_distributed_hlo.py)
    scaled to n=10 so the whole sweep compiles in a couple of seconds:
    every wrapper exercises its collective path (sharded target / mesh
    bit / bra mesh bit / mixed local-mesh sigma) with chunks pinned to
    monolithic so the histogram is chunk-independent.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from quest_tpu.parallel import dist as PAR

    n = CANONICAL_N

    def state(seed):
        rng = np.random.default_rng(seed)
        a = rng.standard_normal((2, 1 << n)).astype(np.float32)
        a /= np.sqrt((a ** 2).sum())
        return jax.device_put(jnp.asarray(a), env.amp_sharding())

    h = (1 / np.sqrt(2)) * np.asarray([[1, 1], [1, -1]], np.float32)
    m = jnp.asarray(np.stack([h, np.zeros((2, 2), np.float32)]))
    # bit 0 <-> bit n-1: one mixed local/mesh transposition
    sigma = PAR.canonical_sigma(
        (n - 1,) + tuple(range(1, n - 1)) + (0,))

    return {
        "apply_matrix_1q_sharded": (
            lambda a: PAR.apply_matrix_1q_sharded(
                a, m, mesh=env.mesh, num_qubits=n, target=n - 1,
                chunks=1),
            state(1), True),
        "swap_sharded": (
            lambda a: PAR.swap_sharded(
                a, mesh=env.mesh, num_qubits=n, qb_low=0, qb_high=n - 1,
                chunks=1),
            state(2), True),
        "gather_replicated": (
            lambda a: PAR.gather_replicated(a, mesh=env.mesh),
            state(3), False),
        "mix_pair_channel_sharded": (
            lambda a: PAR.mix_pair_channel_sharded(
                a, 0.3, mesh=env.mesh, num_qubits=n // 2,
                target=n // 2 - 1, kind="depol", chunks=1),
            state(4), True),
        "remap_sharded": (
            lambda a: PAR.remap_sharded(
                a, mesh=env.mesh, num_qubits=n, sigma=sigma,
                chunks=(1, 1)),
            state(5), True),
    }


def verify_sharded_contracts(env=None, contracts=None) -> List[str]:
    """Compile every canonical dispatch and diff against declarations.
    Returns a list of human-readable failures (empty = all verified).
    ``contracts`` overrides the registry (the drift test passes a
    perturbed copy)."""
    from quest_tpu import introspect
    from quest_tpu.contracts import REQUIRED_WRAPPERS, SHARDED_CONTRACTS
    # decorating module must be imported for the registry to populate
    from quest_tpu.parallel import dist as _dist  # noqa: F401

    if env is None:
        env = ensure_mesh()
    if contracts is None:
        contracts = dict(SHARDED_CONTRACTS)

    errors: List[str] = []
    for name in REQUIRED_WRAPPERS:
        if name not in contracts:
            errors.append(
                f"{name}: required wrapper carries no @sharded_contract "
                f"declaration")
    for name in contracts:
        if name not in REQUIRED_WRAPPERS:
            errors.append(
                f"{name}: contract declared for a wrapper not in "
                f"contracts.REQUIRED_WRAPPERS — add it there or drop "
                f"the decorator")
    if errors:
        return errors

    cases = canonical_cases(env)
    for name in REQUIRED_WRAPPERS:
        decl = contracts[name]
        fn, amps, donate = cases[name]
        report = introspect.audit(fn, amps, donate=donate)
        measured = _family_histogram(report.collectives)
        if measured != dict(decl.collectives):
            errors.append(
                f"{name}: compiled HLO holds {measured or '{}'} but the "
                f"@sharded_contract declares {dict(decl.collectives)} "
                f"(canonical {CANONICAL_SHARDS}-shard dispatch, "
                f"n={CANONICAL_N})")
            continue
        got_bytes = _measured_exchange_bytes(report.text,
                                             decl.collectives.keys())
        if got_bytes > decl.max_exchange_bytes:
            errors.append(
                f"{name}: largest collective payload is {got_bytes} B, "
                f"over the declared max_exchange_bytes="
                f"{decl.max_exchange_bytes}")
            continue
        if decl.max_tier_bytes:
            tiers = _measured_tier_bytes(report.text,
                                         decl.collectives.keys(),
                                         VERIFY_CHIPS)
            for tier in sorted(decl.max_tier_bytes):
                cap = decl.max_tier_bytes[tier]
                got = tiers.get(tier, 0)
                if got > cap:
                    errors.append(
                        f"{name}: {tier} collective payload is {got} B, "
                        f"over the declared max_tier_bytes[{tier}]="
                        f"{cap} (mesh read as {VERIFY_HOSTS}x"
                        f"{VERIFY_CHIPS} hosts x chips)")
    return errors


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        errors = verify_sharded_contracts()
    except RuntimeError as e:
        print(f"qlint contracts: ERROR {e}")
        return 2
    if errors:
        for e in errors:
            print(f"qlint contracts: FAIL {e}")
        return 1
    from quest_tpu.contracts import SHARDED_CONTRACTS
    for name, c in sorted(SHARDED_CONTRACTS.items()):
        tiers = (f" tiers<={dict(sorted(c.max_tier_bytes.items()))}"
                 if c.max_tier_bytes else "")
        print(f"qlint contracts: ok {name} {dict(c.collectives)} "
              f"<= {c.max_exchange_bytes} B{tiers}")
    return 0
