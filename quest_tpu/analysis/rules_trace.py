"""qlint rule family 1: trace-safety & hygiene.

The hazards these rules catch are the ones the repo's hard invariants
hang on (docs/design.md §23):

* a host sync (``.item()``, ``float()``, ``np.asarray``,
  ``block_until_ready``) inside a traced entry point turns a fused
  device program into a per-call round-trip — or fails outright under
  ``jit``;
* a Python ``if``/``while`` on a tracer-valued expression raises a
  ConcretizationTypeError only on the code path that reaches it;
* telemetry counter mutation inside traced code counts once per TRACE,
  not per execution (the PR-4 Tracer guard exists exactly for this);
* ``time.time()`` / unseeded ``random`` anywhere in the product package
  undermines bit-identical resume (resilience.py's core contract);
* ``float64`` literals outside precision.py / host table constants
  silently de-optimize the TPU path (f64 is software-emulated, ~10x);
* a broad ``except Exception`` without a justified pragma swallows the
  structured error taxonomy (QuESTError / ShardLossError /
  MemoryAdmissionError) the recovery layers dispatch on;
* swallowing ``RESOURCE_EXHAUSTED`` anywhere but governor.oom_net
  bypasses the governor's evict-and-retry-once protocol.

**Traced scopes** are detected three ways: a ``jax.jit`` decorator
(including ``partial(jax.jit, static_argnames=...)``), nesting inside a
traced scope (shard_map kernel bodies), or membership in
:data:`TRACED_REGISTRY` — the explicit list of functions that execute
under trace despite carrying no decorator (fusion program parts,
parallel/dist shard-kernel helpers, ops/* kernels called from jitted
programs).  Inside a traced scope a light taint pass marks the traced
parameters (everything not named in ``static_argnames``; for
registry-traced functions, positional parameters — keyword-only
arguments are static config by package idiom) and propagates through
assignments, stopping at static metadata (``.shape``/``.ndim``/
``.dtype``), ``len``/``isinstance``, and ``is``/``is not`` tests.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Set, Tuple

from .engine import Finding, Rule, _all_nodes, register

# ---------------------------------------------------------------------------
# Traced-scope registry
# ---------------------------------------------------------------------------

# (path, function name) -> "traced": the body executes under trace.
# (path, function name) -> "container": the body is host-side planning
# but every function DEFINED INSIDE it is traced (fusion._plan_runner
# builds the drain executor: _apply_part/_apply/run/kernel all trace).
TRACED_REGISTRY: Dict[Tuple[str, str], str] = {
    # fusion program parts (the drain executor factory)
    ("quest_tpu/fusion.py", "_plan_runner"): "container",
    # parallel/dist shard-kernel helpers (called inside shard_map bodies)
    ("quest_tpu/parallel/dist.py", "exchange_pipelined"): "traced",
    ("quest_tpu/parallel/dist.py", "_swap_halves_in_shard"): "traced",
    ("quest_tpu/parallel/dist.py", "_remap_in_shard"): "traced",
    ("quest_tpu/parallel/dist.py", "_apply_1q_mesh_bit"): "traced",
    ("quest_tpu/parallel/dist.py", "_shard_coeffs"): "traced",
    ("quest_tpu/parallel/dist.py", "_parity_phase_sharded"): "traced",
    ("quest_tpu/parallel/dist.py", "_shard_parity_sign"): "traced",
    ("quest_tpu/parallel/dist.py", "_mesh_flip_gather"): "traced",
    ("quest_tpu/parallel/dist.py", "_apply_pauli_sharded"): "traced",
    ("quest_tpu/parallel/dist.py", "_direct_rotation_sharded"): "traced",
    ("quest_tpu/parallel/dist.py", "_qft_mesh_layer"): "traced",
    ("quest_tpu/parallel/dist.py", "_reverse_run_sharded"): "traced",
    ("quest_tpu/parallel/dist.py", "_apply_local_phase"): "traced",
}

# whole modules whose top-level functions execute under trace when
# reached from the fusion drain / sharded kernels (ops/* kernel files).
# element.py is deliberately absent: it is the host accessor layer
# (getAmp / reportState stream concrete arrays).
TRACED_MODULES: Tuple[str, ...] = (
    "quest_tpu/ops/kernels.py",
    "quest_tpu/ops/cplx.py",
    "quest_tpu/ops/density.py",
    "quest_tpu/ops/paulis.py",
    "quest_tpu/ops/bigstate.py",
    "quest_tpu/ops/phasefunc.py",
)

# The canonical state-array parameter names.  Registry/module-traced
# functions carry no static_argnames declaration, so the taint seed is
# name-based: the package idiom passes the traced state as the first
# positional under one of these names and static config as annotated
# ints/tuples after it.  Precision over recall — a host helper in a
# kernel module (kraus-table builders, soa converters) takes differently
# named params and stays clean.
ARRAY_PARAM_NAMES = {"amps", "local", "send", "a", "state", "rho",
                     "shard", "amps_shard"}

# attribute reads that yield STATIC metadata on a tracer (do not
# propagate taint)
_STATIC_ATTRS = {"ndim", "shape", "dtype", "size", "itemsize", "nbytes",
                 "sharding", "weak_type", "aval", "names"}

_STATIC_CALLS = {"len", "isinstance", "type", "getattr", "hasattr",
                 "range", "enumerate", "zip",
                 # package routing predicates that read ONLY static
                 # metadata of their array argument (dtype/shape/ndim)
                 # and return a host bool at trace time
                 "_pl_routable", "qft_multilayer_enabled"}

_NP_NAMES = {"np", "numpy", "_np", "onp"}


def _jit_decorator_info(fn: ast.AST) -> Optional[Set[str]]:
    """None if ``fn`` carries no jit decorator; otherwise the set of
    static argument names the decorator declares."""
    for dec in getattr(fn, "decorator_list", ()):
        target = dec.func if isinstance(dec, ast.Call) else dec
        names = _dotted(target)
        if names is None:
            continue
        if names[-1] == "jit":
            static: Set[str] = set()
            if isinstance(dec, ast.Call):
                # partial(jax.jit, static_argnames=(...)) or
                # jax.jit(..., static_argnames=...)
                for kw in dec.keywords:
                    if kw.arg == "static_argnames":
                        static |= _str_elements(kw.value)
                for a in dec.args:
                    # partial(jax.jit, ...): jit is the first positional
                    an = _dotted(a)
                    if an is not None and an[-1] == "jit":
                        continue
            return static
        if names[-1] == "partial" and isinstance(dec, ast.Call):
            inner = [_dotted(a) for a in dec.args]
            if any(n is not None and n[-1] == "jit" for n in inner):
                static = set()
                for kw in dec.keywords:
                    if kw.arg == "static_argnames":
                        static |= _str_elements(kw.value)
                return static
    return None


def _dotted(node) -> Optional[Tuple[str, ...]]:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _str_elements(node) -> Set[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    out: Set[str] = set()
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.add(el.value)
    return out


class _Scope:
    """One function's trace context: traced flag, tainted names, and the
    function's OWN statement nodes (nested defs excluded — they get
    their own scope).  ``has_tracer_guard`` is resolved lazily from the
    own-node list (only the telemetry rule needs it)."""

    def __init__(self, fn, traced: bool, taint: Set[str], own: list):
        self.fn = fn
        self.traced = traced
        self.taint = set(taint)
        self.own = own
        self._guard: Optional[bool] = None

    @property
    def has_tracer_guard(self) -> bool:
        if self._guard is None:
            self._guard = any(
                (isinstance(n, ast.Attribute) and n.attr == "Tracer")
                or (isinstance(n, ast.Name) and n.id == "Tracer")
                for n in self.own)
        return self._guard


def _function_scopes(tree: ast.Module, path: str):
    """(fn_node, _Scope) for every function in the file, with traced-ness
    resolved from decorators, nesting, and the registry.  Cached on the
    tree: three rules share one scope computation per file."""
    cached = getattr(tree, "_qlint_scopes", None)
    if cached is None:
        cached = list(_compute_scopes(tree, path))
        tree._qlint_scopes = cached
    return cached


def _compute_scopes(tree: ast.Module, path: str):
    module_traced = path in TRACED_MODULES

    def visit(node, enclosing_traced: bool, parent_taint: Set[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                static = _jit_decorator_info(child)
                reg = TRACED_REGISTRY.get((path, child.name))
                traced = (enclosing_traced or static is not None
                          or reg == "traced"
                          or (module_traced and isinstance(
                              node, ast.Module)))
                taint: Set[str] = set()
                args = child.args
                pos = [a.arg for a in args.posonlyargs + args.args]
                kwonly = [a.arg for a in args.kwonlyargs]
                if traced:
                    if static is not None:
                        # decorator declares intent exactly: everything
                        # not named static is a traced operand
                        taint = {p for p in pos + kwonly
                                 if p not in static}
                    else:
                        # registry/module/nesting-traced: seed by the
                        # canonical array-param names, plus the
                        # enclosing scope's taint reaching in through
                        # the closure (minus shadowing params)
                        taint = {p for p in pos + kwonly
                                 if p in ARRAY_PARAM_NAMES}
                        taint |= parent_taint - set(pos) - set(kwonly)
                own = list(_own_nodes(child))
                _propagate_taint(own, taint)
                yield child, _Scope(child, traced, taint, own)
                yield from visit(child, traced or reg == "container",
                                 taint)
            else:
                yield from visit(child, enclosing_traced, parent_taint)

    yield from visit(tree, False, set())


def _expr_tainted(node, taint: Set[str]) -> bool:
    """Does evaluating ``node`` touch a traced value?  Static-metadata
    attribute reads, len/isinstance, and ``is`` tests block taint."""
    if isinstance(node, ast.Name):
        return node.id in taint
    if isinstance(node, ast.Attribute):
        if node.attr in _STATIC_ATTRS:
            return False
        return _expr_tainted(node.value, taint)
    if isinstance(node, ast.Call):
        fname = _dotted(node.func)
        if fname is not None and fname[-1] in _STATIC_CALLS:
            return False
        if fname is not None and fname[0] in _NP_NAMES and \
                fname[-1] in {"dtype", "finfo", "iinfo", "issubdtype"}:
            return False
        return any(_expr_tainted(a, taint) for a in node.args) or \
            any(_expr_tainted(kw.value, taint) for kw in node.keywords) or \
            _expr_tainted(node.func, taint)
    if isinstance(node, ast.Compare):
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            return False
        return _expr_tainted(node.left, taint) or \
            any(_expr_tainted(c, taint) for c in node.comparators)
    if isinstance(node, (ast.Constant, ast.Lambda)):
        return False
    return any(_expr_tainted(c, taint) for c in ast.iter_child_nodes(node))


def _propagate_taint(own_nodes: list, taint: Set[str]) -> None:
    """One forward pass over simple assignments in the function's own
    statements (nested defs excluded — they get their own scope)."""
    if not taint:
        return
    for node in own_nodes:
        if isinstance(node, ast.Assign) and \
                _expr_tainted(node.value, taint):
            for t in node.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        taint.add(n.id)
        elif isinstance(node, ast.AugAssign) and \
                isinstance(node.target, ast.Name) and \
                _expr_tainted(node.value, taint):
            taint.add(node.target.id)


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------


@register
class HostSyncRule(Rule):
    id = "host-sync-in-traced"
    doc = ("host synchronization (.item()/.tolist()/float()/np.asarray/"
           "block_until_ready/device_get) on a traced value inside a "
           "registered traced entry point")

    _SYNC_METHODS = {"item", "tolist", "block_until_ready"}
    _SYNC_CASTS = {"float", "int", "bool", "complex"}

    def check(self, tree, src, path) -> Iterator[Finding]:
        for fn, scope in _function_scopes(tree, path):
            if not scope.traced or not scope.taint:
                continue
            for node in scope.own:
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Attribute) and \
                        f.attr in self._SYNC_METHODS and \
                        _expr_tainted(f.value, scope.taint):
                    yield self.finding(
                        path, node,
                        f"'.{f.attr}()' on a traced value in traced "
                        f"function '{fn.name}' forces a host sync")
                elif isinstance(f, ast.Name) and \
                        f.id in self._SYNC_CASTS and node.args and \
                        _expr_tainted(node.args[0], scope.taint):
                    yield self.finding(
                        path, node,
                        f"'{f.id}()' cast of a traced value in traced "
                        f"function '{fn.name}' forces a host sync")
                else:
                    fname = _dotted(f)
                    if fname is None:
                        continue
                    if (fname[0] in _NP_NAMES
                            and fname[-1] in {"asarray", "array"}
                            and node.args
                            and _expr_tainted(node.args[0], scope.taint)):
                        yield self.finding(
                            path, node,
                            f"'{'.'.join(fname)}' on a traced value in "
                            f"traced function '{fn.name}' materializes "
                            f"the array on host")
                    elif fname[-1] == "device_get" and node.args and \
                            _expr_tainted(node.args[0], scope.taint):
                        yield self.finding(
                            path, node,
                            f"jax.device_get on a traced value in traced "
                            f"function '{fn.name}'")


@register
class TracerBranchRule(Rule):
    id = "tracer-branch"
    doc = ("Python if/while on a tracer-valued expression inside a "
           "traced entry point (ConcretizationTypeError at trace time; "
           "use lax.cond / jnp.where)")

    def check(self, tree, src, path) -> Iterator[Finding]:
        for fn, scope in _function_scopes(tree, path):
            if not scope.traced or not scope.taint:
                continue
            for node in scope.own:
                if isinstance(node, (ast.If, ast.While)) and \
                        _expr_tainted(node.test, scope.taint):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    yield self.finding(
                        path, node,
                        f"Python '{kind}' on a traced expression in "
                        f"traced function '{fn.name}' — use lax.cond / "
                        f"lax.select / jnp.where")
                elif isinstance(node, ast.IfExp) and \
                        _expr_tainted(node.test, scope.taint):
                    yield self.finding(
                        path, node,
                        f"conditional expression on a traced test in "
                        f"traced function '{fn.name}' — use jnp.where")


@register
class TelemetryInTracedRule(Rule):
    id = "telemetry-in-traced"
    doc = ("telemetry counter mutation inside traced code without the "
           "Tracer guard — counts once per trace, not per execution")

    _MUTATORS = {"inc", "observe", "set_gauge", "record_exchange",
                 "inc_key"}
    _MODULES = {"telemetry", "_telemetry"}

    def check(self, tree, src, path) -> Iterator[Finding]:
        for fn, scope in _function_scopes(tree, path):
            if not scope.traced:
                continue
            for node in scope.own:
                if not isinstance(node, ast.Call):
                    continue
                fname = _dotted(node.func)
                if fname is None or len(fname) < 2:
                    continue
                if fname[0] in self._MODULES and \
                        fname[-1] in self._MUTATORS:
                    if scope.has_tracer_guard:
                        continue
                    yield self.finding(
                        path, node,
                        f"telemetry.{fname[-1]} inside traced function "
                        f"'{fn.name}' without an isinstance(x, "
                        f"jax.core.Tracer) guard")


@register
class NondeterminismRule(Rule):
    id = "nondeterminism"
    doc = ("wall-clock / unseeded-RNG source in the product package — "
           "breaks bit-identical resume unless recorded and justified")
    scope = ("quest_tpu/",)

    _LEGACY_NP_SAMPLERS = {"rand", "randn", "random", "random_sample",
                           "randint", "choice", "permutation", "shuffle",
                           "normal", "uniform", "bytes"}
    _STDLIB_SAMPLERS = {"random", "randint", "randrange", "choice",
                        "shuffle", "uniform", "sample", "getrandbits",
                        "gauss"}

    def check(self, tree, src, path) -> Iterator[Finding]:
        has_random_import = any(
            isinstance(n, ast.Import) and
            any(a.name == "random" for a in n.names)
            for n in _all_nodes(tree))
        for node in _all_nodes(tree):
            if not isinstance(node, ast.Call):
                continue
            fname = _dotted(node.func)
            if fname is None:
                continue
            if fname[-1] in {"time", "time_ns"} and len(fname) >= 2 and \
                    fname[-2] in {"time", "_time"}:
                yield self.finding(
                    path, node,
                    "wall-clock time.time() feeds program state — breaks "
                    "bit-identical replay unless the value is recorded")
            elif len(fname) == 2 and fname[0] == "random" and \
                    fname[1] in self._STDLIB_SAMPLERS and \
                    has_random_import:
                yield self.finding(
                    path, node,
                    f"stdlib random.{fname[1]} draws from the unseeded "
                    f"process-global stream")
            elif len(fname) >= 3 and fname[0] in _NP_NAMES and \
                    fname[1] == "random" and \
                    fname[2] in self._LEGACY_NP_SAMPLERS:
                yield self.finding(
                    path, node,
                    f"np.random.{fname[2]} draws from the unseeded "
                    f"legacy global RNG — use a seeded Generator / "
                    f"rng.GLOBAL_RNG")
            elif len(fname) >= 3 and fname[0] in _NP_NAMES and \
                    fname[1] == "random" and fname[2] == "default_rng" \
                    and not node.args:
                yield self.finding(
                    path, node,
                    "np.random.default_rng() without a seed is "
                    "entropy-seeded — pass an explicit seed")


@register
class FaultPlanSpecRule(Rule):
    id = "fault-plan-spec"
    doc = ("string fault schedule passed to resilience.FaultPlan must be "
           "comma-joined kind@N events with registered kinds — a typo'd "
           "kind raises at plan construction, and in an env default it "
           "silently never fires")

    # the registered fault vocabulary, INCLUDING the serve-level kinds
    # (bank_fault/heal/poison_job).  Kept in sync with
    # resilience.FaultPlan._KINDS plus the "io" spec-only form; pinned by
    # tests/test_serve_resilience.py.
    KINDS = frozenset({
        "kill", "killsave", "corrupt", "io", "nan", "inf", "scale",
        "stall", "shard_loss", "host_loss", "oom",
        "bank_fault", "heal", "poison_job",
    })

    def check(self, tree, src, path) -> Iterator[Finding]:
        for node in _all_nodes(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fname = _dotted(node.func)
            if fname is None or fname[-1] != "FaultPlan":
                continue
            spec = node.args[0]
            if not isinstance(spec, ast.Constant) \
                    or not isinstance(spec.value, str):
                continue  # dynamic specs are validated at run time
            for part in spec.value.split(","):
                part = part.strip()
                if not part:
                    continue
                kind, sep, arg = part.partition("@")
                kind = kind.strip()
                if kind not in self.KINDS:
                    yield self.finding(
                        path, spec,
                        f"unknown fault kind {kind!r} in FaultPlan spec "
                        f"{spec.value!r} (registered: "
                        f"{', '.join(sorted(self.KINDS))})")
                elif sep and not arg.strip().lstrip("-").isdigit():
                    yield self.finding(
                        path, spec,
                        f"non-integer argument {arg.strip()!r} for "
                        f"{kind!r} in FaultPlan spec {spec.value!r}")


@register
class F64LiteralRule(Rule):
    id = "f64-literal"
    doc = ("float64/complex128 dtype literal outside precision.py and "
           "host table constants — f64 is software-emulated on TPU "
           "(~10x); route precision through precision.py")
    scope = ("quest_tpu/",)
    exclude = ("quest_tpu/precision.py",)

    _F64 = {"float64", "complex128"}

    def check(self, tree, src, path) -> Iterator[Finding]:
        exempt: set = set()
        dtype_strings: list = []
        for node in _all_nodes(tree):
            # comparisons against a dtype are reads, not selections
            if isinstance(node, ast.Compare):
                for sub in ast.walk(node):
                    exempt.add(id(sub))
            elif isinstance(node, ast.IfExp) and \
                    isinstance(node.test, ast.Compare):
                # the dtype-mirroring idiom:
                # dt = np.float64 if x.dtype == jnp.float64 else np.float32
                # selects to MATCH an input's precision, never to raise it
                for branch in (node.body, node.orelse):
                    if isinstance(branch, ast.Attribute) and \
                            branch.attr in self._F64:
                        exempt.add(id(branch))
            elif isinstance(node, ast.Call):
                fname = _dotted(node.func)
                if fname is None:
                    # e.g. np.diag(...).astype(np.complex128): host
                    # numpy table constant built then cast
                    if isinstance(node.func, ast.Attribute) and \
                            node.func.attr == "astype" and \
                            self._np_rooted(node.func.value):
                        for a in node.args:
                            for sub in ast.walk(a):
                                exempt.add(id(sub))
                    continue
                # np.dtype()/np.finfo()/np.issubdtype(): introspection
                if fname[-1] in {"dtype", "finfo", "iinfo", "issubdtype"}:
                    for a in list(node.args) + [k.value
                                                for k in node.keywords]:
                        for sub in ast.walk(a):
                            exempt.add(id(sub))
                # host numpy table constants: np.arange/zeros/asarray(...,
                # dtype=np.float64) build static pass arrays — the
                # deliberate "table-constant allowlist"
                elif fname[0] in _NP_NAMES:
                    for kw in node.keywords:
                        if kw.arg == "dtype":
                            for sub in ast.walk(kw.value):
                                exempt.add(id(sub))
                    for a in node.args:
                        for sub in ast.walk(a):
                            if isinstance(sub, ast.Attribute) and \
                                    sub.attr in self._F64:
                                exempt.add(id(sub))
                # dtype STRINGS only count in dtype contexts: a
                # dtype= kwarg or an .astype()/asarray() argument —
                # a bare "float64" string elsewhere is just text
                for kw in node.keywords:
                    if kw.arg == "dtype" and \
                            isinstance(kw.value, ast.Constant) and \
                            kw.value.value in self._F64:
                        dtype_strings.append(kw.value)
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr in {"astype", "view"}:
                    for a in node.args:
                        if isinstance(a, ast.Constant) and \
                                a.value in self._F64:
                            dtype_strings.append(a)
        for node in _all_nodes(tree):
            if id(node) in exempt:
                continue
            if isinstance(node, ast.Attribute) and node.attr in self._F64:
                root = _dotted(node)
                yield self.finding(
                    path, node,
                    f"{'.'.join(root) if root else node.attr} dtype "
                    f"literal outside the precision.py/table-constant "
                    f"allowlist")
        for node in dtype_strings:
            if id(node) in exempt:
                continue
            yield self.finding(
                path, node,
                f"'{node.value}' dtype string outside the "
                f"precision.py/table-constant allowlist")

    @classmethod
    def _np_rooted(cls, node) -> bool:
        """Is the expression a call/attribute chain rooted at numpy?"""
        while isinstance(node, (ast.Attribute, ast.Call, ast.Subscript)):
            node = (node.func if isinstance(node, ast.Call)
                    else node.value)
        return isinstance(node, ast.Name) and node.id in _NP_NAMES


@register
class BroadExceptRule(Rule):
    id = "broad-except"
    doc = ("bare/broad except without a justified pragma — swallows the "
           "structured error taxonomy (QuESTError, ShardLossError, "
           "MemoryAdmissionError) the recovery layers dispatch on")
    scope = ("quest_tpu/",)

    def check(self, tree, src, path) -> Iterator[Finding]:
        for node in _all_nodes(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            t = node.type
            broad = t is None or (
                isinstance(t, ast.Name) and
                t.id in {"Exception", "BaseException"})
            if not broad:
                continue
            # cleanup-and-reraise never swallows: the taxonomy still
            # propagates (fusion's drain-requeue is the canonical case)
            if any(isinstance(s, ast.Raise) and s.exc is None
                   for s in node.body):
                continue
            what = "bare except" if t is None else f"except {t.id}"
            yield self.finding(
                path, node,
                f"{what} without narrowing — name the expected "
                f"failure classes or justify with a qlint pragma")


@register
class OomSwallowRule(Rule):
    id = "oom-swallow"
    doc = ("RESOURCE_EXHAUSTED handled outside governor.oom_net — only "
           "the governor may catch allocation failure (evict-and-retry-"
           "once protocol, docs/design.md §22)")
    scope = ("quest_tpu/",)
    exclude = ("quest_tpu/governor.py",)

    def check(self, tree, src, path) -> Iterator[Finding]:
        for node in _all_nodes(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            seg = ast.get_source_segment(src, node) or ""
            if "RESOURCE_EXHAUSTED" in seg or "_is_oom" in seg:
                yield self.finding(
                    path, node,
                    "except handler inspects RESOURCE_EXHAUSTED outside "
                    "governor.oom_net — route OOM recovery through the "
                    "memory governor")


def _own_nodes(fn) -> Iterator[ast.AST]:
    """Walk a function's body EXCLUDING nested function definitions
    (those get their own scope entry)."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.append(child)
