"""qlint CLI: ``python -m quest_tpu.analysis [paths...] [options]``.

Exit codes (bench_regress.py convention):
  0  clean — no unsuppressed findings (and contracts verified, if
     ``--contracts``)
  1  findings / contract drift — each printed as
     ``path:line:col: <rule-id> <message>``
  2  usage or environment error (bad baseline, missing mesh, ...)
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from . import engine


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m quest_tpu.analysis",
        description="qlint: trace-safety, layering, and "
                    "sharded-collective contract checks "
                    "(docs/design.md §23)")
    p.add_argument("paths", nargs="*",
                   help="files/directories to walk (default: "
                        + ", ".join(engine.DEFAULT_WALK) + ")")
    p.add_argument("--rules", metavar="ID[,ID...]",
                   help="run only these rule ids")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalogue and exit")
    p.add_argument("--baseline", default=engine.BASELINE_DEFAULT,
                   help="grandfathered-findings file "
                        "(default: .qlint_baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline from the current findings "
                        "(explicit grandfathering) and exit")
    p.add_argument("--json", action="store_true",
                   help="emit findings as JSON")
    p.add_argument("--contracts", action="store_true",
                   help="also verify @sharded_contract declarations "
                        "against compiled HLO (8-shard CPU dryrun)")
    args = p.parse_args(argv)

    t0 = time.monotonic()
    rules = engine.all_rules()
    if args.list_rules:
        for rid in sorted(rules):
            r = rules[rid]
            where = ("everywhere" if r.scope is None
                     else "|".join(r.scope))
            print(f"{rid:24s} [{where}] {r.doc}")
        return 0

    selected = None
    if args.rules:
        selected = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in selected if r not in rules]
        if unknown:
            print(f"qlint: unknown rule id(s): {', '.join(unknown)} "
                  f"(see --list-rules)", file=sys.stderr)
            return 2

    walk = tuple(args.paths) if args.paths else engine.DEFAULT_WALK
    findings = engine.analyze_paths(walk, rules=selected)

    if args.write_baseline:
        engine.write_baseline(findings, args.baseline)
        print(f"qlint: wrote {len(findings)} grandfathered finding(s) "
              f"to {args.baseline} — fill in per-entry reasons before "
              f"committing")
        return 0

    baseline = []
    if not args.no_baseline:
        try:
            baseline = engine.load_baseline(args.baseline)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"qlint: baseline error: {e}", file=sys.stderr)
            return 2
    new, grandfathered, stale = engine.apply_baseline(findings, baseline)

    if args.json:
        print(json.dumps({
            "findings": [vars(f) for f in new],
            "grandfathered": [vars(f) for f in grandfathered],
            "stale_baseline": stale,
        }, indent=2, sort_keys=True))
    else:
        for f in new:
            print(f.format())
        for e in stale:
            print(f"qlint: stale baseline entry {e['path']}:{e['line']} "
                  f"({e['rule']}) no longer fires — delete it from "
                  f"{args.baseline}")

    rc = 0
    if new or stale:
        rc = 1

    if args.contracts:
        from . import hlocheck
        crc = hlocheck.main()
        rc = max(rc, crc)

    if not args.json:
        dt = time.monotonic() - t0
        n_files = sum(1 for _ in engine.iter_python_files(walk))
        print(f"qlint: {len(new)} finding(s), "
              f"{len(grandfathered)} grandfathered, {len(stale)} stale "
              f"baseline entr{'y' if len(stale) == 1 else 'ies'} over "
              f"{n_files} files in {dt:.1f}s")
    return rc


if __name__ == "__main__":
    sys.exit(main())
