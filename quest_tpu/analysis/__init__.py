"""qlint: static analysis for trace-safety, layering, and
sharded-collective contracts (docs/design.md §23).

Entry points:

* ``python -m quest_tpu.analysis`` — walk quest_tpu/, tests/, scripts/
  and report unsuppressed findings (exit 1 on findings, 2 on usage or
  baseline errors).
* ``python -m quest_tpu.analysis --contracts`` — additionally verify
  every @sharded_contract declaration against compiled HLO on the
  8-shard CPU dryrun.
* :func:`analyze_paths` / :func:`analyze_source` — library API used by
  tests/test_analysis.py.
"""

from .engine import (  # noqa: F401
    BASELINE_DEFAULT,
    DEFAULT_WALK,
    Finding,
    REPO_ROOT,
    Rule,
    all_rules,
    analyze_paths,
    analyze_source,
    apply_baseline,
    iter_python_files,
    load_baseline,
    register,
    write_baseline,
)
