"""qlint rule family 2: layering & sharded-collective contracts.

**Layer DAG.**  The paper's structural rule ("API functions should never
call each other"; all inter-device communication lives in one exchange
layer) maps onto this package as an IMPORT-ORDER DAG over top-level
imports:

    api (api, api_ops, debug, models)          rank 0
      ↓
    serve (serve)                              rank 1
      ↓
    orchestration (fusion, batch, circuit,     rank 2
      optimizer, resilience, checkpoint,
      introspect, governor)
      ↓
    dist (parallel/*)                          rank 3
      ↓
    ops (ops/*)                                rank 4
      ↓
    env (env)                                  rank 5

The serve stratum is the orchestration CONSUMER: the multi-tenant
service composes banks (batch), window stepping + checkpoints
(resilience), and admission pricing (governor) — so orchestration
modules importing serve at module level would invert the dependency
(rank 2 importing rank 1 is flagged as upward).

plus a **shared** stratum (validation, precision, rng, telemetry,
contracts, qureg, qasm, utils, native, analysis) importable from every
layer but itself restricted to shared + env.  Note the DAG ranks what
may IMPORT what at module level, which is not the same as runtime call
flow: dist ranks above ops because dist.py composes ops kernels into
shard bodies (imports them), never the reverse.  Function-scope lazy
imports are the package's documented cycle-breaking idiom (see the
EXCHANGE_FAULT_HOOK note in parallel/dist.py) and are deliberately NOT
flagged — the rule reads only module-level ``import``/``from`` nodes.

**Collective confinement.**  ``lax.ppermute``/``psum``/``all_gather``/
``all_to_all`` callsites are flagged anywhere outside
quest_tpu/parallel/dist.py — the single exchange layer whose wrappers
carry budget guards, fault hooks, and telemetry.  A collective issued
elsewhere bypasses all three.

**Contract presence.**  Every wrapper named in
``quest_tpu.contracts.REQUIRED_WRAPPERS`` must carry the
``@sharded_contract`` decorator; the declaration itself is verified
against compiled HLO by hlocheck.py (``--contracts``).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Tuple

from .engine import Finding, Rule, _all_nodes, register
from ..contracts import REQUIRED_WRAPPERS

PACKAGE = "quest_tpu"

# module key (first path component under quest_tpu/, or module stem) ->
# layer name.  Keep in sync with the diagram in docs/design.md §23.
LAYER_OF = {
    "api": "api", "api_ops": "api", "debug": "api", "models": "api",
    "serve": "serve",
    "fusion": "orch", "batch": "orch", "circuit": "orch",
    "optimizer": "orch", "resilience": "orch", "checkpoint": "orch",
    "introspect": "orch", "governor": "orch",
    "parallel": "dist", "aotcache": "dist",
    "ops": "ops",
    "env": "env",
}

LAYER_RANK = {"api": 0, "serve": 1, "orch": 2, "dist": 3, "ops": 4,
              "env": 5}

# importable from everywhere; may import only shared + env
SHARED = {"validation", "precision", "rng", "telemetry", "contracts",
          "qureg", "qasm", "utils", "native", "analysis"}

COLLECTIVE_NAMES = {"ppermute", "psum", "psum_scatter", "all_gather",
                    "all_to_all", "pshuffle", "pmean", "pmax", "pmin",
                    "axis_index_groups"}
EXCHANGE_LAYER = "quest_tpu/parallel/dist.py"


def _module_key(path: str) -> Optional[str]:
    """quest_tpu/ops/kernels.py -> 'ops'; quest_tpu/env.py -> 'env';
    None for the package root __init__ and non-package files."""
    parts = path.split("/")
    if parts[0] != PACKAGE or len(parts) < 2:
        return None
    if len(parts) == 2:
        stem = parts[1][:-3] if parts[1].endswith(".py") else parts[1]
        return None if stem == "__init__" else stem
    return parts[1]


def _imported_keys(node, path: str) -> Iterator[Tuple[str, ast.AST]]:
    """Module keys (under quest_tpu) a top-level import node pulls in."""
    pkg_parts = path.split("/")[:-1]  # containing package of this file
    if isinstance(node, ast.Import):
        for alias in node.names:
            parts = alias.name.split(".")
            if parts[0] == PACKAGE and len(parts) > 1:
                yield parts[1], node
    elif isinstance(node, ast.ImportFrom):
        if node.level == 0:
            parts = (node.module or "").split(".")
            if parts[0] != PACKAGE:
                return
            if len(parts) > 1:
                yield parts[1], node
            else:
                # `from quest_tpu import fusion, env` — names are modules
                for alias in node.names:
                    yield alias.name, node
            return
        # relative: resolve against the containing package
        base = pkg_parts[:len(pkg_parts) - (node.level - 1)]
        if not base or base[0] != PACKAGE:
            return
        if node.module:
            target = base + node.module.split(".")
            if len(target) > 1:
                yield target[1], node
        else:
            # `from . import x, y` — each name is a module
            for alias in node.names:
                target = base + [alias.name]
                if len(target) > 1:
                    yield target[1], node


def _top_level_imports(tree: ast.Module) -> Iterator[ast.AST]:
    """Module-level import nodes, including those inside top-level
    try/except and `if TYPE_CHECKING:` shims — but NOT function bodies
    (lazy imports are the sanctioned cycle-breaking idiom)."""
    stack = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            yield node
        elif isinstance(node, (ast.Try, ast.If)):
            stack.extend(getattr(node, "body", ()))
            stack.extend(getattr(node, "orelse", ()))
            stack.extend(getattr(node, "finalbody", ()))
            for h in getattr(node, "handlers", ()):
                stack.extend(h.body)


@register
class LayerViolationRule(Rule):
    id = "layer-violation"
    doc = ("module-level import against the layer DAG "
           "(api → orch → dist → ops → env, shared importable by all) — "
           "upward or lateral imports couple layers the design keeps "
           "independent")
    scope = ("quest_tpu/",)
    exclude = ("quest_tpu/__init__.py",)

    def check(self, tree, src, path) -> Iterator[Finding]:
        me = _module_key(path)
        if me is None:
            return
        my_layer = LAYER_OF.get(me)
        for node in _top_level_imports(tree):
            for key, at in _imported_keys(node, path):
                if key == me:
                    continue  # intra-layer submodule import
                dep_layer = LAYER_OF.get(key)
                if me in SHARED:
                    if key in SHARED or dep_layer == "env":
                        continue
                    yield self.finding(
                        path, at,
                        f"shared module '{me}' imports layered module "
                        f"'{key}' at module level — shared modules may "
                        f"import only shared/env")
                    continue
                if key in SHARED or my_layer is None:
                    continue
                if dep_layer is None:
                    continue
                if my_layer == "api" and dep_layer == "api":
                    yield self.finding(
                        path, at,
                        f"api-layer module '{me}' imports api-layer "
                        f"module '{key}' — API functions must not call "
                        f"each other (compose via the orchestration "
                        f"layer)")
                elif LAYER_RANK[dep_layer] < LAYER_RANK[my_layer]:
                    yield self.finding(
                        path, at,
                        f"'{me}' ({my_layer}, rank "
                        f"{LAYER_RANK[my_layer]}) imports '{key}' "
                        f"({dep_layer}, rank {LAYER_RANK[dep_layer]}) — "
                        f"upward import against the layer DAG")


@register
class CollectiveOutsideDistRule(Rule):
    id = "collective-outside-dist"
    doc = ("lax collective callsite outside parallel/dist.py — all "
           "inter-shard communication must go through the exchange "
           "layer's guarded wrappers")
    exclude = (EXCHANGE_LAYER,)

    def check(self, tree, src, path) -> Iterator[Finding]:
        # names imported directly from jax.lax count as collective calls
        from_lax = set()
        for node in _all_nodes(tree):
            if isinstance(node, ast.ImportFrom) and \
                    (node.module or "").endswith("lax"):
                for alias in node.names:
                    if alias.name in COLLECTIVE_NAMES:
                        from_lax.add(alias.asname or alias.name)
        for node in _all_nodes(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = None
            if isinstance(f, ast.Attribute) and \
                    f.attr in COLLECTIVE_NAMES:
                name = f.attr
            elif isinstance(f, ast.Name) and f.id in from_lax:
                name = f.id
            if name is not None:
                yield self.finding(
                    path, node,
                    f"'{name}' issued outside the exchange layer "
                    f"({EXCHANGE_LAYER}) — use the guarded sharded "
                    f"wrappers")


@register
class ContractMissingRule(Rule):
    id = "contract-missing"
    doc = ("registered sharded dispatch wrapper without a "
           "@sharded_contract declaration — its collective shape would "
           "be unpinned against HLO drift")
    scope = (EXCHANGE_LAYER,)

    def check(self, tree, src, path) -> Iterator[Finding]:
        seen = {}
        for node in _all_nodes(tree):
            if isinstance(node, ast.FunctionDef):
                seen[node.name] = node
        for name in REQUIRED_WRAPPERS:
            fn = seen.get(name)
            if fn is None:
                continue  # wrapper moved/renamed; registry drift shows
                # up in hlocheck, not here
            if not any(self._is_contract(dec)
                       for dec in fn.decorator_list):
                yield self.finding(
                    path, fn,
                    f"sharded dispatch wrapper '{name}' carries no "
                    f"@sharded_contract declaration "
                    f"(quest_tpu/contracts.py)")

    @staticmethod
    def _is_contract(dec) -> bool:
        target = dec.func if isinstance(dec, ast.Call) else dec
        while isinstance(target, ast.Attribute):
            if target.attr == "sharded_contract":
                return True
            target = target.value
        return isinstance(target, ast.Name) and \
            target.id == "sharded_contract"
