"""qlint rule engine: findings, suppressions, baseline, tree walking.

The analyzer is a plain stdlib-``ast`` pass (no new dependencies, no jax
import): rules are small classes registered in :data:`RULES`, each
receiving one parsed file and yielding :class:`Finding`s.  Three escape
hatches keep the gate honest rather than noisy:

* **Inline suppressions** — ``# qlint: allow(<rule>): <reason>`` on the
  offending line (or the line directly above) silences exactly that rule
  at that site.  The reason is MANDATORY: a bare ``allow`` or an unknown
  rule id is itself a finding (``bad-pragma``), so every suppression in
  the tree documents why the hazard is intended.
* **Baseline file** — a committed JSON list of grandfathered findings
  (``{"rule", "path", "line", "reason"}``, reason mandatory) matched by
  (rule, path, line).  New findings never enter the baseline silently;
  the CLI's ``--write-baseline`` rewrites it explicitly.
* **Per-rule path scoping** — hygiene rules that only make sense for
  product code (nondeterminism, f64 literals, layering) restrict
  themselves to ``quest_tpu/``; structural rules (collective callsites,
  pragma syntax) run over the full walk (quest_tpu/, tests/, scripts/).

docs/design.md §23 documents the rule catalogue and semantics.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Sequence

# repo root: quest_tpu/analysis/engine.py -> quest_tpu -> repo
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEFAULT_WALK = ("quest_tpu", "tests", "scripts")

BASELINE_DEFAULT = os.path.join(REPO_ROOT, ".qlint_baseline.json")

_PRAGMA_RE = re.compile(
    r"qlint:\s*allow\(([A-Za-z0-9_*-]+)\)\s*(?::\s*(\S.*))?")
# a pragma-looking comment that failed to parse as allow(rule): reason
_PRAGMA_LOOSE_RE = re.compile(r"qlint:\s*allow")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    col: int
    message: str

    def key(self) -> tuple:
        return (self.rule, self.path, self.line)

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} " \
               f"{self.message}"


def _all_nodes(tree) -> list:
    """The file's shared node index (analyze_source caches it on the
    tree); falls back to a fresh walk when a rule is driven directly."""
    nodes = getattr(tree, "_qlint_all_nodes", None)
    if nodes is None:
        nodes = list(ast.walk(tree))
        tree._qlint_all_nodes = nodes
    return nodes


class Rule:
    """Base rule: subclasses set ``id``/``doc``, override ``check``.

    ``scope``: None = every walked file; otherwise a tuple of
    repo-relative path prefixes the rule is restricted to.
    ``exclude``: repo-relative paths (exact or prefix) the rule skips.
    """

    id: str = ""
    doc: str = ""
    scope: Optional[tuple] = None
    exclude: tuple = ()

    def applies_to(self, path: str) -> bool:
        if self.scope is not None and not any(
                path.startswith(p) for p in self.scope):
            return False
        return not any(path == e or path.startswith(e.rstrip("/") + "/")
                       for e in self.exclude)

    def check(self, tree: ast.Module, src: str,
              path: str) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, path: str, node, message: str) -> Finding:
        return Finding(self.id, path, getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0) + 1, message)


RULES: Dict[str, Rule] = {}


def register(cls):
    """Class decorator adding a rule to the registry (import-order
    stable; rules_trace / rules_layering register on import)."""
    rule = cls()
    assert rule.id and rule.id not in RULES, rule.id
    RULES[rule.id] = rule
    return cls


def _ensure_rules_loaded() -> None:
    # rule modules register via the decorator on first import
    from . import rules_layering  # noqa: F401
    from . import rules_trace  # noqa: F401


def all_rules() -> Dict[str, Rule]:
    _ensure_rules_loaded()
    return dict(RULES)


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------


def _comment_lines(src: str):
    """(line number, comment text) for every real COMMENT token — a
    pragma mentioned inside a docstring or string literal is
    documentation, not a suppression (tokenize distinguishes them where
    a line regex cannot).  Files that fail to tokenize fall back to the
    raw-line scan; the parse-error finding covers genuinely broken
    files."""
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        for i, text in enumerate(src.splitlines(), start=1):
            if "#" in text:
                yield i, text[text.index("#"):]


def parse_suppressions(src: str, path: str):
    """(suppressions, pragma_findings): suppressions maps line number ->
    set of rule ids allowed there (a pragma covers its own line and the
    line below, so it can sit above a long statement); pragma_findings
    are bad-pragma diagnostics (missing reason / unparseable form).
    Unknown rule ids are validated by the caller against the registry."""
    sup: Dict[int, set] = {}
    bad: List[Finding] = []
    if "qlint" not in src:
        return sup, bad
    for i, text in _comment_lines(src):
        if "qlint" not in text:
            continue
        m = _PRAGMA_RE.search(text)
        if not m:
            if _PRAGMA_LOOSE_RE.search(text):
                bad.append(Finding(
                    "bad-pragma", path, i, 1,
                    "unparseable qlint pragma — expected "
                    "'# qlint: allow(<rule>): <reason>'"))
            continue
        rule_id, reason = m.group(1), m.group(2)
        if not reason or not reason.strip():
            bad.append(Finding(
                "bad-pragma", path, i, 1,
                f"suppression of '{rule_id}' carries no reason — the "
                f"reason is mandatory"))
            continue
        for ln in (i, i + 1):
            sup.setdefault(ln, set()).add(rule_id)
    return sup, bad


def _validate_pragma_rules(sup: Dict[int, set], path: str,
                           known: Iterable[str]) -> List[Finding]:
    known = set(known) | {"*"}
    out = []
    seen = set()
    for ln in sorted(sup):
        for rid in sorted(sup[ln] - known):
            if (ln - 1, rid) in seen:  # same pragma covers two lines
                continue
            seen.add((ln, rid))
            out.append(Finding(
                "bad-pragma", path, ln, 1,
                f"suppression names unknown rule '{rid}'"))
    return out


# ---------------------------------------------------------------------------
# Per-file analysis
# ---------------------------------------------------------------------------


def analyze_source(src: str, path: str,
                   rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the (selected) rules over one file's source text, applying
    inline suppressions.  ``path`` is the repo-relative path used for
    rule scoping and reporting; it need not exist on disk (the test
    fixtures analyze snippets)."""
    _ensure_rules_loaded()
    path = path.replace(os.sep, "/")
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding("parse-error", path, e.lineno or 0, 1,
                        f"file does not parse: {e.msg}")]
    # one shared node index per file — rules iterate this instead of
    # re-walking the tree (the walk dominates analyzer runtime)
    tree._qlint_all_nodes = list(ast.walk(tree))
    sup, findings = parse_suppressions(src, path)
    findings = list(findings)
    findings += _validate_pragma_rules(sup, path, RULES.keys())
    active = ([RULES[r] for r in rules] if rules is not None
              else RULES.values())
    for rule in active:
        if not rule.applies_to(path):
            continue
        for f in rule.check(tree, src, path):
            if f.rule in sup.get(f.line, ()) or "*" in sup.get(f.line, ()):
                continue
            findings.append(f)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def iter_python_files(paths: Sequence[str] = DEFAULT_WALK,
                      root: str = REPO_ROOT) -> Iterator[str]:
    """Repo-relative paths of every .py file under the walk roots."""
    for base in paths:
        absbase = os.path.join(root, base)
        if os.path.isfile(absbase):
            yield base.replace(os.sep, "/")
            continue
        for dirpath, dirnames, filenames in os.walk(absbase):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__"
                                 and not d.startswith("."))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, fn), root)
                    yield rel.replace(os.sep, "/")


def analyze_paths(paths: Sequence[str] = DEFAULT_WALK,
                  root: str = REPO_ROOT,
                  rules: Optional[Sequence[str]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for rel in iter_python_files(paths, root):
        with open(os.path.join(root, rel), encoding="utf-8") as f:
            src = f.read()
        findings += analyze_source(src, rel, rules=rules)
    return findings


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------


def load_baseline(path: str = BASELINE_DEFAULT) -> List[dict]:
    """The committed grandfathered-findings list.  Every entry must name
    rule/path/line and carry a non-empty reason — a reasonless entry is
    rejected (ValueError) so the baseline cannot become a silent dump."""
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    entries = data.get("findings", data) if isinstance(data, dict) else data
    for e in entries:
        if not all(k in e for k in ("rule", "path", "line")):
            raise ValueError(f"baseline entry missing rule/path/line: {e}")
        if not str(e.get("reason", "")).strip():
            raise ValueError(
                f"baseline entry for {e['path']}:{e['line']} ({e['rule']}) "
                f"carries no reason — every grandfathered finding must be "
                f"justified")
    return list(entries)


def apply_baseline(findings: Sequence[Finding],
                   baseline: Sequence[dict]):
    """(new, grandfathered, stale): findings not in the baseline, those
    matched by it, and baseline entries that no longer fire (candidates
    for deletion — reported so the baseline only shrinks)."""
    index = {(e["rule"], e["path"], int(e["line"])): e for e in baseline}
    new, old = [], []
    hit = set()
    for f in findings:
        if f.key() in index:
            hit.add(f.key())
            old.append(f)
        else:
            new.append(f)
    stale = [e for k, e in index.items() if k not in hit]
    return new, old, stale


def write_baseline(findings: Sequence[Finding],
                   path: str = BASELINE_DEFAULT,
                   reason: str = "grandfathered at baseline capture") -> None:
    entries = [{"rule": f.rule, "path": f.path, "line": f.line,
                "reason": reason, "message": f.message}
               for f in sorted(findings, key=lambda f: f.key())]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"findings": entries}, f, indent=2, sort_keys=True)
        f.write("\n")
