"""Gate fusion for the imperative API: batch gates, execute in few passes.

The reference dispatches every API gate as one full sweep of the amplitude
array (QuEST/src/QuEST.c:177-186 et al.) — there is nothing like this
module in it.  On TPU a sweep is an HBM-bandwidth-bound pass, so the win
is batching: inside a ``gateFusion(qureg)`` context, dense gates issued
through the ordinary imperative API (hadamard, controlledNot, unitary,
multiControlledUnitary, ...) are BUFFERED instead of executed, and drained
through the circuit scheduler (circuit.plan_circuit — offset-window
passes) the moment anything needs the amplitudes:

    with qt.gateFusion(q):
        for d in range(depth):
            for t in range(n):
                qt.hadamard(q, t)
            for t in range(0, n - 1, 2):
                qt.controlledNot(q, t, t + 1)
    p = qt.calcProbOfOutcome(q, 0, 0)      # (any read would have drained)

Semantics are IDENTICAL to the unfused path — validation and QASM
recording still happen per call, in call order, and any operation that
reads or writes the state (calculations, measurement, decoherence, phase
functions, init) transparently drains the buffer first via the
``Qureg.amps`` property — only the number of HBM passes changes.  Gates
kept out of the buffer (too many qubits, explicit-distributed registers)
drain it and execute eagerly, preserving order.
"""

from __future__ import annotations

from contextlib import contextmanager
from functools import lru_cache, partial
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from . import circuit as C
from . import optimizer as _opt
from . import telemetry as _telemetry
from .ops import cplx as _cplx

# largest dense gate (targets + controls) worth buffering; anything bigger
# executes eagerly through the standard layout-safe kernels
FUSION_MAX_GATE_QUBITS = 7


class FusionBuffer:
    __slots__ = ("gates",)

    def __init__(self):
        # C.Gate and ChannelItem entries, executed in order by the drain
        self.gates: List[object] = []


def start_gate_fusion(qureg) -> None:
    """Begin buffering dense gates on ``qureg`` (idempotent)."""
    if getattr(qureg, "_fusion", None) is None:
        qureg._fusion = FusionBuffer()


def stop_gate_fusion(qureg) -> None:
    """Drain any buffered gates and stop buffering.  If execution fails the
    buffer stays attached with its gates intact, so state and QASM log
    cannot silently diverge."""
    drain(qureg)
    qureg._fusion = None


def drain(qureg) -> None:
    """Execute buffered gates now (called from the Qureg.amps property).
    On failure the gates are restored to the buffer — a failed drain must
    not be silently absorbed into a state/log divergence."""
    buf = getattr(qureg, "_fusion", None)
    if buf is not None and buf.gates:
        gates, buf.gates = buf.gates, []
        _telemetry.inc("fusion_drains_total")
        _telemetry.observe("fusion_drain_gates", len(gates))
        try:
            with _telemetry.span("fusion.drain", gates=len(gates)):
                _run(qureg, gates)
        except BaseException:
            buf.gates = gates + buf.gates
            raise
        # window-boundary accounting for the resilience layer: checkpoint
        # cadence is asserted against drains, never mid-window
        qureg._drain_count = getattr(qureg, "_drain_count", 0) + 1
        if _telemetry.enabled():
            # window-boundary HBM watermark sample (hbm_watermark_bytes
            # gauge; peak surfaced in getEnvironmentString / reportPerf)
            from .utils import profiling as _prof

            _prof.memory_watermark()


_PLAN_CACHE_MAX = 64
_plan_cache: dict = {}

# >0 while a dry-run (explain_circuit's memory section / the governor
# predictor) is planning: the per-window telemetry observations below
# are suppressed and nothing is inserted into _plan_cache — the
# explain contract is NO telemetry mutation (plan_items_quiet)
_QUIET: List[int] = [0]


class ChannelItem:
    """A captured depolarise/damping channel (one-pass elementwise pair
    kernel, ops/density.py) buffered BETWEEN gate segments: the drain runs
    gates-and-channels in order inside one jitted program, so a noise
    layer (BASELINE config 4) costs a single dispatch.  ``prob`` enters
    the compiled program as a traced scalar — re-draining with a
    different probability does not recompile."""

    __slots__ = ("kind", "target", "bra", "prob")

    def __init__(self, kind: str, target: int, bra: int, prob: float):
        self.kind = kind
        self.target = target       # ket bit position in the state vector
        self.bra = bra             # bra twin bit (target + numQubitsRepresented)
        self.prob = float(prob)


def _plan_key(items, nloc: int, sweep_ok: bool, perm0=None, nsh: int = 0):
    """Content key for a fully-concrete item list, or None when any matrix
    is traced/non-numpy.  Matrices in a drain are small (2x2..128x128), so
    hashing their bytes is negligible next to planning them (~0.2 s of
    host work per drain for a 13-qubit noise layer).  Channel items key on
    (kind, target) only — the probability is a runtime argument.  On a
    sharded register the key also carries the live logical->physical
    permutation the drain starts from — the same items plan to different
    windows/remaps under a different starting perm — and the topology
    signature (parallel/topology.py): the tier-aware window planner
    parks evictees differently per arrangement, so a QT_TOPOLOGY /
    planner-mode flip must not reuse a stale plan.  The circuit-optimizer
    mode is part of the key for the same reason: flipping QT_OPTIMIZER
    rewrites the stream, so it must retrace rather than replay a plan
    built under the other mode."""
    parts = []
    for it in items:
        if isinstance(it, ChannelItem):
            parts.append(("chan", it.kind, it.target, it.bra))
            continue
        m = it.mat
        if not isinstance(m, np.ndarray):
            return None
        parts.append((it.targets, m.dtype.str, m.shape, m.tobytes()))
    if nsh:
        from .parallel import topology as _topo

        topo_sig = _topo.signature(1 << nsh)
    else:
        topo_sig = None
    # QT_PERM_FAST is part of the key: flipping it reroutes permutation
    # runs between the gather/relabel lowering and the dense matmul
    # pipeline, so a flip must retrace rather than replay a stale plan.
    # QT_MEGAKERNEL likewise: the grouping rewrite (§29) changes the plan
    # skeleton itself, so a knob flip must re-plan rather than replay a
    # plan grouped under the other mode.
    from .ops import fused as _fused

    return (nloc, sweep_ok, perm0, topo_sig, _opt.mode(),
            C.perm_fast_enabled(), _fused.megakernel_planning(),
            tuple(parts))


def _split_items(items, nloc: int, sweep_ok: bool):
    """items -> (program, arrays): ``program`` is a hashable tuple of
    ("plan", skeleton, n_arrays) / ("chan", kind, t, b) /
    ("chansweep", ((kind, t, b), ...)) parts executed in order; ``arrays``
    the concatenated traced pass arrays (channel probabilities are
    appended per item at _run time, not here).  With ``sweep_ok``,
    consecutive sweep-eligible channels (ket bit < 14) collapse into ONE
    chansweep part — a few co-residency HBM sweeps for a whole noise
    layer (fused.apply_pair_channel_sweep)."""
    program = []
    arrays = []
    seg = []
    chans = []

    def flush_gates():
        if seg:
            if not _QUIET[0]:
                _telemetry.observe("fusion_window_gates", len(seg))
            for kind, sub in _perm_runs(seg):
                if kind == "perm":
                    # permutation run: matrix-free static lowering (§28)
                    # — its own window kind, no gate-matrix stacks
                    ops = C.lower_permutation_run(sub, nloc)
                    if ops:
                        program.append(("perm", tuple(ops)))
                else:
                    ops = C.plan_circuit(list(sub), nloc)
                    skeleton, arrs = C.split_plan(ops)
                    program.append(("plan", skeleton, len(arrs)))
                    arrays.extend(arrs)
            seg.clear()

    def flush_chans():
        if not chans:
            return
        sweepable = (sweep_ok and nloc >= 15
                     and all(t < 14 for _, t, _b in chans))
        if sweepable:
            program.append(("chansweep", tuple(chans)))
        else:
            program.extend(("chan", kind, t, b) for kind, t, b in chans)
        chans.clear()

    for it in items:
        if isinstance(it, ChannelItem):
            flush_gates()
            chans.append((it.kind, it.target, it.bra))
        else:
            flush_chans()
            seg.append(it)
    flush_chans()
    flush_gates()
    return tuple(program), tuple(arrays)


def _item_bits(it) -> tuple:
    """Logical state-vector bits an item touches (gate targets incl.
    embedded controls; channel ket + bra bits)."""
    if isinstance(it, ChannelItem):
        return (it.target, it.bra)
    return tuple(it.targets)


# minimum adjacent permutation-classified gates worth splitting out of a
# dense segment: a lone X between dense neighbours fuses better inside
# their window pass than as its own HBM sweep
_PERM_RUN_MIN = 2


def _perm_runs(seg):
    """Partition one gate segment into maximal runs of permutation-
    classified gates and interleaved dense runs, in stream order:
    ``[("perm" | "dense", [gates...]), ...]``.  Runs shorter than
    _PERM_RUN_MIN are demoted to dense; with QT_PERM_FAST off everything
    is one dense run (the A/B baseline)."""
    if not C.perm_fast_enabled():
        return [("dense", list(seg))]
    flags = [C.classify_permutation_gate(g.mat) is not None for g in seg]
    i = 0
    while i < len(seg):
        if flags[i]:
            j = i
            while j < len(seg) and flags[j]:
                j += 1
            if j - i < _PERM_RUN_MIN:
                for k in range(i, j):
                    flags[k] = False
            i = j
        else:
            i += 1
    runs: List[tuple] = []
    for flag, g in zip(flags, seg):
        kind = "perm" if flag else "dense"
        if runs and runs[-1][0] == kind:
            runs[-1][1].append(g)
        else:
            runs.append((kind, [g]))
    return runs


def _item_entry(it):
    """Window-planner entry for one drain item: channels expose their
    (ket, bra) bits; gates go through circuit.perm_item_entry, which tags
    pure bit-relabel gates for the zero-motion permutation fold.  EVERY
    cost-model consumer — the sharded planner here, optimizer._stream_cost,
    introspect.explain_circuit, and the §21 reconciliation — builds its
    entries through this one function, so predictions and the dispatched
    plan price the same stream and model drift stays 0 by construction.
    The §29 megakernel regroups the planner's winfused ops AFTER entries
    are priced (circuit.group_megawins is a pure post-pass inside the
    local plan segment): it changes how many Pallas dispatches execute a
    window, never which amplitudes move between shards, so every entry —
    and therefore the §21 reconciliation and §22 drain-peak predictor —
    prices both QT_MEGAKERNEL arms identically by construction."""
    if isinstance(it, ChannelItem):
        return (it.target, it.bra)
    return C.perm_item_entry(it.targets, it.mat)


def _split_items_sharded(items, n: int, nloc: int, perm0, sweep_ok: bool):
    """Windows + ONE batched remap each for a SHARDED drain: group
    consecutive items whose cumulative qubit set fits the shard-local
    space (circuit.plan_remap_windows), emit a ("remap", sigma) part
    bringing the window's qubits local, then rewrite the window's items
    to their physical bits and fold them with the ordinary local planner.
    The permutation persists across windows AND drains — no swap-back;
    canonical order rematerializes on the next state read (Qureg.amps).
    Returns (program, arrays, final_perm)."""
    entries = [_item_entry(it) for it in items]
    segments, final_perm = C.plan_remap_windows(entries, n, nloc, perm0)
    program: List[tuple] = []
    arrays: List[object] = []
    for (i, j), sigma, perm in segments:
        if not _QUIET[0]:
            _telemetry.observe("fusion_remap_window_items", j - i)
        if C._is_relabel_entry(entries[i]):
            # permutation fold (§28): items [i, j) composed straight into
            # the plan's final permutation — zero data motion, nothing to
            # dispatch; the composed cross-shard hop (if any) is deferred
            # to the next canonical read like every other live perm
            continue
        if sigma is not None:
            program.append(("remap", sigma))
        sub = []
        for it in items[i:j]:
            if isinstance(it, ChannelItem):
                pt, pb = perm[it.target], perm[it.bra]
                # the pair kernels want the ket bit below the bra bit;
                # both channel kinds are (t, b)-symmetric (their weights
                # depend only on the two bits' equality pattern), so a
                # remap that lands the bra below the ket just swaps roles
                sub.append(ChannelItem(it.kind, min(pt, pb), max(pt, pb),
                                       it.prob))
            else:
                sub.append(C.Gate(tuple(perm[t] for t in it.targets),
                                  it.mat))
        p2, a2 = _split_items(sub, nloc, sweep_ok)
        program.extend(p2)
        arrays.extend(a2)
    return tuple(program), tuple(arrays), final_perm


def _items_for_element(items, b: int):
    """Item list for batch element ``b``: per-element matrices — an extra
    leading batch axis on ``Gate.mat`` — are sliced down; shared matrices
    and channels pass through unchanged."""
    out = []
    for it in items:
        if isinstance(it, ChannelItem) or getattr(it.mat, "ndim", 0) != 4:
            out.append(it)
        else:
            out.append(C.Gate(it.targets, it.mat[b]))
    return out


def _run(qureg, items) -> None:
    """Plan with the CONCRETE gate matrices (so controlled gates Schmidt-
    decompose to their true rank), then execute the whole item sequence —
    gate-segment plans interleaved with captured channels — as ONE jitted
    dispatch: the pass arrays and channel probabilities enter as traced
    arguments and the compiled program is cached on the program skeleton,
    so repeated drains of the same shape (e.g. angle sweeps, noise-layer
    reps) never recompile and cost a single host->device round-trip.
    Fully-concrete item lists also cache the MATERIALIZED plan (pass
    matrices), so repeated identical drains skip host planning entirely.

    On a BatchedQureg (batch.py) the same program runs vmapped over the
    leading batch axis of the (B, 2, 2^n) amplitude bank — the plan, the
    live logical->physical permutation, and the window remap schedule are
    SHARED across the batch because every element runs the same gate
    stream.  Per-element gate matrices (a (B, 2, s, s) ``Gate.mat``) are
    planned per element against a shared skeleton and the pass arrays
    enter the program with their own batch axis (vmap in_axes 0)."""
    from . import governor as _gov

    # a prior degradation ladder may have spilled this register to host
    # while it sat idle; bring it back BEFORE reading its permutation —
    # the handle carries the perm the plan must start from
    _gov.ensure_resident(qureg)
    n = qureg.num_qubits_in_state_vec
    nsh = _shard_bits(qureg)
    nloc = n - nsh
    perm0 = qureg._perm if nsh else None
    # circuit-optimizer rewrite (optimizer.py): the plan-cache key, the
    # planners, the governor predictor, and the §21 reconciliation below
    # all see the OPTIMIZED stream — predictions are priced on what is
    # actually drained, so model drift stays 0 by construction
    with _telemetry.span("fusion.optimize", items=len(items)):
        items, _ostats = _opt.optimize_items(
            items, n=n, nloc=nloc, nsh=nsh, perm0=perm0)
    if not items:
        return  # everything cancelled: nothing to execute, perm unchanged
    bsz = int(getattr(qureg, "batch_size", 0) or 0)
    mats_batched = bool(bsz) and any(
        not isinstance(it, ChannelItem) and getattr(it.mat, "ndim", 0) == 4
        for it in items)
    from .ops import fused as _fusedmod
    sweep_ok = _fusedmod.channel_sweep_enabled(qureg.dtype)
    key = _plan_key(items, nloc, sweep_ok, perm0, nsh)
    hit = _plan_cache.get(key) if key is not None else None
    if hit is not None:
        _telemetry.inc("fusion_plan_cache_hits_total")
        program, arrays, final_perm = hit
    else:
        _telemetry.inc("fusion_plan_cache_misses_total")
        with _telemetry.span("fusion.plan", items=len(items)):
            if mats_batched:
                program, arrays, final_perm = _plan_batched_items(
                    items, bsz, n, nloc, nsh, perm0, sweep_ok)
            elif nsh:
                program, arrays, final_perm = _split_items_sharded(
                    items, n, nloc, perm0, sweep_ok)
            else:
                program, arrays = _split_items(items, nloc, sweep_ok)
                final_perm = None
        if key is not None:
            if len(_plan_cache) >= _PLAN_CACHE_MAX:
                _plan_cache.pop(next(iter(_plan_cache)))
            _plan_cache[key] = (program, arrays, final_perm)
    # memory governance: predict this drain's per-device peak and walk
    # the degradation ladder if it exceeds the budget.  Must run BEFORE
    # the telemetry/reconcile block and the executor-key resolution so a
    # chunk escalation is seen consistently by all three (the override
    # is cleared in the finally).
    gov = None
    try:
        gov = _gov.govern_drain(qureg, program, arrays, nloc=nloc, nsh=nsh)
        _run_dispatch(qureg, items, program, arrays, gov,
                      n=n, nsh=nsh, nloc=nloc, bsz=bsz, perm0=perm0,
                      mats_batched=mats_batched, final_perm=final_perm)
    finally:
        _gov.end_drain()


def _group_route(gprog) -> str:
    """Dominant plan-entry family of one dispatch group — the §30
    wall-time attribution label.  Precedence reflects cost dominance: a
    megawin anywhere makes the group megakernel-shaped; else fused
    window passes; else permutation fast paths; else channel sweeps;
    else pure remap exchange."""
    saw = set()
    for part in gprog:
        if part[0] == "plan":
            for sk in part[1]:
                saw.add("megawin" if sk[0] == "megawin" else "winfused")
        elif part[0] == "perm":
            saw.add("permfast")
        elif part[0] in ("chan", "chansweep"):
            saw.add("channel")
        elif part[0] == "remap":
            saw.add("remap")
    for route in ("megawin", "winfused", "permfast", "channel", "remap"):
        if route in saw:
            return route
    return "other"


def _run_dispatch(qureg, items, program, arrays, gov, *, n, nsh, nloc,
                  bsz, perm0, mats_batched, final_perm) -> None:
    """Telemetry accounting + dispatch of a planned drain, in (possibly
    governor-split) program groups, each through the RESOURCE_EXHAUSTED
    net at the dispatch boundary."""
    from . import governor as _gov

    if _telemetry.enabled():
        _telemetry.inc("fusion_windows_total",
                       sum(1 for p in program if p[0] == "plan"))
        # §29 megakernel route accounting: one "mega" per megawin group
        # (ONE pallas_call = one HBM round-trip for its whole run), one
        # "fallback" per winfused pass still on the per-pass route while
        # grouping is active.  The gauge is the drain's mean HBM
        # round-trips per fusion window — the quantity the megakernel
        # exists to shrink.
        from .ops import fused as _fusedops

        mega = fallback = trips = plan_parts = 0
        for part in program:
            if part[0] != "plan":
                continue
            plan_parts += 1
            for sk in part[1]:
                trips += 1
                if sk[0] == "megawin":
                    mega += 1
                elif sk[0] == "winfused":
                    fallback += 1
        if mega:
            _telemetry.inc("megakernel_dispatch_total", mega, route="mega")
        if fallback and _fusedops.megakernel_planning():
            _telemetry.inc("megakernel_dispatch_total", fallback,
                           route="fallback")
        if plan_parts:
            _telemetry.set_gauge("window_hbm_round_trips",
                                 trips / plan_parts)
        # permutation-family route accounting (§28): lowered window ops
        # count by kind (one coalesced transpose = relabel, static
        # xor/gather passes = gather); sharded relabel FOLDS — which
        # dispatch nothing — count per item below
        for part in program:
            if part[0] != "perm":
                continue
            for op in part[1]:
                _telemetry.inc(
                    "permutation_gates_total",
                    route="relabel" if op[0] == "permute" else "gather")
        if nsh:
            p0 = tuple(perm0) if perm0 is not None else tuple(range(n))
            for it in items:
                e = _item_entry(it)
                if C._is_relabel_entry(e):
                    # "exchange" when the fold touches bits resident on
                    # the shard axis at drain start: the composed
                    # cross-shard ppermute is deferred to the canonical
                    # read rather than avoided
                    ex = any(p0[a] >= nloc or p0[b] >= nloc
                             for a, b in e[1])
                    _telemetry.inc(
                        "permutation_gates_total",
                        route="exchange" if ex else "relabel")
        if nsh:
            bw = max(bsz, 1)  # each batch element exchanges its own amps
            # window-remap ICI accounting at dispatch time: each
            # ("remap", sigma) part's per-shard exchange classes and
            # bytes come from the same cost model the tests pin
            # (circuit.remap_exchange_bytes / dist.decompose_sigma)
            from .parallel import dist as PAR

            from .parallel import topology as _topo

            itemsize = np.dtype(qureg.dtype).itemsize
            ck = str(PAR.exchange_config_key() or "auto")
            topology = _topo.resolve(1 << nsh)
            meas_c0 = _telemetry.counter_sum("exchanges_total",
                                             op="window_remap")
            meas_b0 = _telemetry.counter_sum("exchange_bytes_total",
                                             op="window_remap")
            meas_t0 = {t: _telemetry.counter_sum(
                "exchange_bytes_total", op="window_remap", tier=t)
                for t in _topo.TIERS}
            for part in program:
                if part[0] != "remap":
                    continue
                sigma = part[1]
                # per-tier exchange classes straight from the same cost
                # model the tests pin (dist.remap_exchange_tiers sums
                # exactly to remap_exchange_count/remap_exchange_bytes)
                for tier, (cnt, b) in PAR.remap_exchange_tiers(
                        sigma, nloc, nsh, itemsize, topology).items():
                    if cnt or b:
                        _telemetry.record_exchange(
                            "window_remap", cnt * bw, b * bw,
                            chunks=ck, tier=tier)
            # reconcile the drain's measured window-remap deltas against
            # an independent re-plan through the cost model — any
            # disagreement is model drift (introspect, docs/design.md §21)
            from . import introspect as _introspect

            _introspect.reconcile_drain(
                bit_sets=[_item_entry(it) for it in items],
                n=n, nloc=nloc, nsh=nsh, perm0=perm0, itemsize=itemsize,
                batch=bsz,
                measured_count=_telemetry.counter_sum(
                    "exchanges_total", op="window_remap") - meas_c0,
                measured_bytes=_telemetry.counter_sum(
                    "exchange_bytes_total", op="window_remap") - meas_b0,
                measured_chunks=ck,
                measured_tier_bytes={t: _telemetry.counter_sum(
                    "exchange_bytes_total", op="window_remap", tier=t)
                    - meas_t0[t] for t in _topo.TIERS})
    probs = tuple(it.prob for it in items if isinstance(it, ChannelItem))
    from .ops import fused as _fused
    if nsh:
        from .parallel import dist as PAR

        exchange_key = PAR.exchange_config_key()
    else:
        exchange_key = None
    mesh = qureg.env.mesh if nsh else None
    precision = _fused.matmul_precision_name()
    batch_flag = (2 if mats_batched else 1) if bsz else 0
    # bypass the amps property (which would re-enter drain); the live
    # permutation the windowed plan leaves behind is carried on the
    # register — the next drain starts from it, the next READ
    # rematerializes canonical order (Qureg.amps).  The governor's
    # ladder may have split the program into several dispatch groups
    # (bit-identical — part boundaries already carry an
    # optimization_barrier); each group runs through the
    # RESOURCE_EXHAUSTED net, and sharded groups dispatch under the
    # collective guard so a dead peer surfaces as ShardLossError and
    # the resilience layer can fail over (docs/design.md §19)
    groups = (gov or {}).get("groups") or (program,)
    # §30 per-op wall-time attribution: each dispatched group is timed
    # and charged to its dominant plan-entry route (megawin / winfused /
    # permfast / channel / remap) — plan_route_seconds{route} feeds the
    # reportPerf attribution section and its dispatch-bound detector.
    # Trace mode blocks on the group result so the sample is true wall
    # time; the default mode times dispatch only (no added sync on the
    # hot path — the <5% bench_telemetry budget).
    import time as _time

    attrib = _telemetry.enabled()
    deep = attrib and _telemetry.mode_name() == "trace"
    ai = pi = 0
    for gprog in groups:
        a0, p0 = ai, pi
        for part in gprog:
            ai, pi = _part_advance(part, ai, pi)
        garrays, gprobs = arrays[a0:ai], probs[p0:pi]
        runner = _plan_runner(nloc, gprog, mesh, precision, exchange_key,
                              batch_flag)
        if nsh:
            def dispatch(r=runner, ga=garrays, gp=gprobs):
                return PAR.guarded_dispatch(
                    r, qureg._amps, ga, gp,
                    op="drain", shards=qureg.num_chunks)
        else:
            def dispatch(r=runner, ga=garrays, gp=gprobs):
                return r(qureg._amps, ga, gp)
        t0 = _time.perf_counter() if attrib else 0.0
        qureg._amps = _gov.oom_net(dispatch, qureg)
        if attrib:
            if deep:
                jax.block_until_ready(qureg._amps)
            route = _group_route(gprog)
            _telemetry.observe("plan_route_seconds",
                               _time.perf_counter() - t0, route=route)
            _telemetry.inc("plan_route_dispatch_total", route=route)
    if nsh:
        if final_perm is not None and list(final_perm) != list(range(n)):
            qureg._perm = tuple(final_perm)
        else:
            qureg._perm = None


def _plan_batched_items(items, bsz: int, n: int, nloc: int, nsh: int,
                        perm0, sweep_ok: bool):
    """Plan a drain whose items carry PER-ELEMENT matrices: each batch
    element is planned independently (the decomposition of a controlled
    gate is value-dependent) and all elements must produce the SAME
    program skeleton — the compiled executor is shared across the batch,
    only the pass arrays differ.  Returns (program, arrays, final_perm)
    with each pass array stacked to a leading (B, ...) batch axis."""
    program = None
    final_perm = None
    per_elem = []
    for b in range(bsz):
        eit = _items_for_element(items, b)
        if nsh:
            pb, ab, fp = _split_items_sharded(eit, n, nloc, perm0, sweep_ok)
        else:
            (pb, ab), fp = _split_items(eit, nloc, sweep_ok), None
        if b == 0:
            program, final_perm = pb, fp
        elif pb != program or fp != final_perm:
            from .validation import QuESTError

            raise QuESTError(
                "batched drain: batch element %d's gate stream plans to a "
                "different program skeleton than element 0 (value-dependent "
                "decomposition, e.g. a controlled gate of different Schmidt "
                "rank) — such submissions cannot share one batched program; "
                "run them in separate ensemble groups" % b)
        per_elem.append(ab)
    arrays = tuple(
        np.stack([np.asarray(per_elem[b][j]) for b in range(bsz)])
        for j in range(len(per_elem[0])))
    return program, arrays, final_perm


def _part_advance(part, ai: int, pi: int):
    """Walk the (pass-array, channel-probability) offsets past one
    program part — shared by the compiled executor and the governor's
    grouped-dispatch split, so both slice the argument streams
    identically."""
    if part[0] == "plan":
        return ai + part[2], pi
    if part[0] == "chansweep":
        return ai, pi + len(part[1])
    if part[0] in ("remap", "perm"):
        return ai, pi
    return ai, pi + 1


def plan_items_quiet(qureg, items):
    """Plan ``items`` exactly as _run would — same program parts, pass
    arrays, and final permutation — WITHOUT touching telemetry or the
    plan cache: the dry-run planning path behind explain_circuit's
    ``memory`` section and the governor predictor.  A cached plan is
    read (identical values), but a miss is NOT inserted — explaining a
    circuit must not flip the cache status the introspection tests pin.
    Returns (program, arrays, final_perm, nloc, nsh)."""
    n = qureg.num_qubits_in_state_vec
    nsh = _shard_bits(qureg)
    nloc = n - nsh
    perm0 = qureg._perm if nsh else None
    if not items:
        return (), (), None, nloc, nsh
    # the same optimizer rewrite _run applies, quietly — a dry run must
    # predict the stream that would actually drain
    items, _ostats = _opt.optimize_items(
        items, n=n, nloc=nloc, nsh=nsh, perm0=perm0, quiet=True)
    if not items:
        return (), (), None, nloc, nsh
    bsz = int(getattr(qureg, "batch_size", 0) or 0)
    mats_batched = bool(bsz) and any(
        not isinstance(it, ChannelItem) and getattr(it.mat, "ndim", 0) == 4
        for it in items)
    from .ops import fused as _fusedmod
    sweep_ok = _fusedmod.channel_sweep_enabled(qureg.dtype)
    key = _plan_key(items, nloc, sweep_ok, perm0, nsh)
    hit = _plan_cache.get(key) if key is not None else None
    if hit is not None:
        program, arrays, final_perm = hit
        return program, arrays, final_perm, nloc, nsh
    _QUIET[0] += 1
    try:
        if mats_batched:
            program, arrays, final_perm = _plan_batched_items(
                items, bsz, n, nloc, nsh, perm0, sweep_ok)
        elif nsh:
            program, arrays, final_perm = _split_items_sharded(
                items, n, nloc, perm0, sweep_ok)
        else:
            program, arrays = _split_items(items, nloc, sweep_ok)
            final_perm = None
    finally:
        _QUIET[0] -= 1
    return program, arrays, final_perm, nloc, nsh


def aot_plan_info(qureg, items):
    """Quiet planning PLUS the dispatch-key derivation _run_dispatch
    applies (mesh / precision / exchange key / batch flag / channel-prob
    slot count) — everything the AOT tier (§31) needs to name or prewarm
    the executor a drain of ``items`` would dispatch, without touching
    telemetry or the plan cache.  Returns None for an empty plan.

    Single-group assumption: the prediction names the ungoverned
    whole-program runner; a governor ladder split dispatches per-group
    executors with their own (sub-program) keys."""
    program, arrays, _fp, nloc, nsh = plan_items_quiet(qureg, items)
    if not program:
        return None
    n = qureg.num_qubits_in_state_vec
    bsz = int(getattr(qureg, "batch_size", 0) or 0)
    mats_batched = False
    if bsz:
        perm0 = qureg._perm if nsh else None
        oitems, _ostats = _opt.optimize_items(
            items, n=n, nloc=nloc, nsh=nsh, perm0=perm0, quiet=True)
        mats_batched = any(
            not isinstance(it, ChannelItem)
            and getattr(it.mat, "ndim", 0) == 4 for it in oitems)
    if nsh:
        from .parallel import dist as PAR

        exchange_key = PAR.exchange_config_key()
        mesh = qureg.env.mesh
    else:
        exchange_key = None
        mesh = None
    from .ops import fused as _fusedmod

    ai = pi = 0
    for part in program:
        ai, pi = _part_advance(part, ai, pi)
    return {
        "program": program, "arrays": arrays, "nloc": nloc, "nsh": nsh,
        "mesh": mesh, "precision": _fusedmod.matmul_precision_name(),
        "exchange_key": exchange_key,
        "batch_flag": (2 if mats_batched else 1) if bsz else 0,
        "batch_size": bsz, "nprobs": pi, "final_perm": _fp,
    }


def aot_probe(qureg, items):
    """Side-effect-free AOT-tier prediction for the drain ``items``
    would dispatch — explainCircuit's ``compile`` section (§31).
    Returns {"enabled", "status", "key"} with status in disabled /
    uncacheable / memory / hit / miss."""
    from . import aotcache as _aotcache

    if not _aotcache.enabled():
        return {"enabled": False, "status": "disabled", "key": None}
    info = aot_plan_info(qureg, items)
    if info is None:
        return {"enabled": True, "status": "uncacheable", "key": None}
    amps = _aotcache.amps_struct(
        qureg.num_amps_total, info["batch_size"], qureg.dtype,
        info["mesh"])
    probs = tuple(0.5 for _ in range(info["nprobs"]))
    sig = _aotcache.arg_sig(amps, info["arrays"], probs)
    return _aotcache.probe(
        info["nloc"], info["program"], info["mesh"], info["precision"],
        info["exchange_key"], info["batch_flag"], sig)


@lru_cache(maxsize=256)
def _plan_runner(nloc: int, program: tuple, mesh, precision: str = None,
                 exchange_key: str = None, batch: int = 0):
    """Jitted whole-program executor over ("plan", skeleton, n_arrays) /
    ("chan", kind, t, b) parts in order.  For a sharded register the
    program (all items shard-local by capture policy) runs inside ONE
    shard_map over the amplitude mesh — the multi-chip analogue of the
    drain.  ``exchange_key`` is dist.exchange_config_key(): the remap
    parts bake the pipelined-exchange chunk count in at trace time, so
    the compiled executor must be keyed on the QT_EXCHANGE_CHUNKS
    override (a stale cache entry would silently keep the old chunk
    schedule).

    ``batch``: 0 = scalar register; 1 = (B, 2, 2^n) register bank, pass
    arrays shared across the batch; 2 = bank + per-element pass arrays
    (leading (B, ...) axis, vmap in_axes 0).  The batched program is the
    SAME ``_apply`` body vmapped over the batch axis — on a mesh the
    vmap sits INSIDE the shard_map kernel (batch-outer/amps-inner:
    collectives move every element's shard slice in one exchange)."""
    # this body runs only on an lru_cache MISS: each execution is a new
    # compiled-executor shape — the drain's retrace count
    _telemetry.inc("fusion_retrace_total")
    from .ops import density as _density

    if mesh is not None:
        from .parallel import dist as PAR

        _ndev = PAR.amp_axis_size(mesh)

    def _apply_part(part, amps, arrays, probs, ai, pi):
        if part[0] == "plan":
            _, skeleton, na = part
            amps = C.execute_plan(
                amps, C.rebuild_plan(skeleton, arrays[ai:ai + na]),
                nloc, precision=precision)
        elif part[0] == "perm":
            # matrix-free permutation window (§28): xor / gatherperm /
            # permute ops are fully static — zero pass arrays
            amps = C.execute_plan(amps, list(part[1]), nloc,
                                  precision=precision)
        elif part[0] == "remap":
            # ONE batched window relocalization (mixed half-shard
            # swaps + per-shard axis permutation + composed shard
            # ppermute) — only emitted inside the mesh path's
            # shard_map body
            from .parallel import dist as PAR
            amps = PAR._remap_in_shard(
                amps.reshape(2, -1), part[1], nloc, _ndev
            ).reshape(amps.shape)
        elif part[0] == "chansweep":
            entries = part[1]
            from .ops import fused as _fusedmod
            amps = _fusedmod.apply_pair_channel_sweep(
                amps.reshape(2, -1), entries,
                probs[pi:pi + len(entries)],
                num_bits=nloc).reshape(amps.shape)
        else:
            _, kind, t, b = part
            amps = _density.apply_pair_channel(
                amps, kind, probs[pi], nn=nloc, t=t, b=b)
        return amps

    def _apply(amps, arrays, probs):
        ai = pi = 0
        for part in program:
            amps = _apply_part(part, amps, arrays, probs, ai, pi)
            ai, pi = _part_advance(part, ai, pi)
            # without this barrier XLA:TPU's memory assignment keeps every
            # part's temporaries live to the end of the program (measured:
            # +1.25 GiB PER CHANNEL at 13q rho -> 21 GiB OOM; flat 1.75 GiB
            # with it)
            amps = jax.lax.optimization_barrier(amps)
        return amps

    if batch:
        def _apply_fn(amps, arrays, probs):
            # vmap part by part: optimization_barrier has no batching rule,
            # and keeping it between (rather than inside) the vmapped parts
            # preserves the same per-part liveness cut for the whole bank
            ai = pi = 0
            for part in program:
                step = partial(_apply_part, part, ai=ai, pi=pi)
                amps = jax.vmap(
                    step, in_axes=(0, 0 if batch == 2 else None, None)
                )(amps, arrays, probs)
                ai, pi = _part_advance(part, ai, pi)
                amps = jax.lax.optimization_barrier(amps)
            return amps
    else:
        _apply_fn = _apply

    @partial(jax.jit, donate_argnums=0)
    def run(amps, arrays, probs):
        if mesh is None:
            return _apply_fn(amps, arrays, probs)
        from jax.sharding import PartitionSpec as P

        from .env import AMP_AXIS, shard_map

        def kernel(local, *arrs):
            return _apply_fn(local, arrs[:len(arrays)], arrs[len(arrays):])

        amp_spec = P(None, None, AMP_AXIS) if batch else P(None, AMP_AXIS)
        return shard_map(
            kernel, mesh=mesh,
            in_specs=(amp_spec,) + (P(),) * (len(arrays) + len(probs)),
            out_specs=amp_spec,
            check_vma=False,  # pallas_call inside shard_map has no vma info
        )(amps, *arrays, *probs)

    # §31 persistent AOT tier: with QT_AOT_CACHE set the runner is
    # wrapped consult-before-compile / persist-on-miss (and gains the
    # .prewarm entry point the serve warm pool drives); unset, this is
    # an identity pass-through
    from . import aotcache as _aotcache

    return _aotcache.wrap_runner(
        run, nloc=nloc, program=program, mesh=mesh, precision=precision,
        exchange_key=exchange_key, batch=batch)


def _shard_bits(qureg) -> int:
    """Number of leading qubits held as mesh coordinates (0 when the
    register is single-device or replicated)."""
    env = qureg.env
    if env.mesh is None:
        return 0
    from .parallel import dist as PAR

    nd = PAR.amp_axis_size(env.mesh)
    if nd <= 1 or qureg.num_amps_total < env.num_devices:
        return 0
    return PAR.num_shard_bits(env.mesh)


def _capturable(qureg, bits) -> bool:
    """Can a dense gate on qubit positions ``bits`` be buffered?  Size-
    capped; on a sharded register the drain runs the whole plan inside
    one shard_map, relocalizing gates that touch mesh-coordinate bits at
    WINDOW granularity through the lazy logical->physical permutation
    (_split_items_sharded) — one batched remap per window instead of two
    half-shard exchanges per gate.  Only gates too wide for the
    shard-local space (or the GSPMD-opt-out mode, which has no remap
    kernel) fall back to eager execution."""
    buf = getattr(qureg, "_fusion", None)
    if buf is None:
        return False
    bits = tuple(bits)
    if len(bits) > FUSION_MAX_GATE_QUBITS:
        return False
    nsh = _shard_bits(qureg)
    if nsh:
        nloc = qureg.num_qubits_in_state_vec - nsh
        if len(set(bits)) > nloc:
            return False
        if max(bits) >= nloc:
            from .parallel import dist as PAR

            if not (PAR.explicit_dist_enabled()
                    and PAR.lazy_remap_enabled()):
                return False
    return True


def capture_unitary(qureg, stacked, targets, controls=(),
                    control_states=()) -> bool:
    """Buffer a dense gate (with the density-matrix conjugate twin,
    QuEST.c:181-183) if fusion is active and the gate qualifies; returns
    False to tell the caller to execute eagerly (after draining, so order
    is preserved)."""
    base_bits = tuple(targets) + tuple(controls)
    ok = _capturable(qureg, base_bits)
    if ok and qureg.is_density_matrix:
        sh = qureg.num_qubits_represented
        ok = _capturable(qureg, tuple(b + sh for b in base_bits))
    if not ok:
        drain(qureg)
        return False
    mat = stacked
    if controls:
        mat = C.controlled_dense(stacked, len(controls), control_states)
    buf = qureg._fusion
    buf.gates.append(C.Gate(tuple(targets) + tuple(controls), mat))
    if qureg.is_density_matrix:
        sh = qureg.num_qubits_represented
        cmat = _cplx.conj(stacked)
        if controls:
            cmat = C.controlled_dense(cmat, len(controls), control_states)
        buf.gates.append(
            C.Gate(tuple(t + sh for t in targets)
                   + tuple(c + sh for c in controls), cmat)
        )
    return True


def capture_raw(qureg, stacked, targets) -> bool:
    """Buffer an arbitrary dense matrix on STATE-VECTOR qubit positions
    ``targets`` with NO density-matrix twin — used for decoherence-channel
    superoperators, which already act on the combined (T, T+n) targets
    (mixDepolarising et al., QuEST_common.c:630-652).  Captured channels
    fold into the same window passes as gates, so a noise-heavy density
    workload (BASELINE config 4) runs as a handful of fused passes instead
    of one dispatch per channel."""
    if not _capturable(qureg, tuple(targets)):
        drain(qureg)
        return False
    qureg._fusion.gates.append(C.Gate(tuple(targets), stacked))
    return True


_X = np.stack([np.array([[0.0, 1.0], [1.0, 0.0]]), np.zeros((2, 2))])


def capture_pair_channel(qureg, kind: str, target: int, prob) -> bool:
    """Buffer a depolarise/damping channel as a ChannelItem — the one-pass
    elementwise pair kernel runs INSIDE the drain program, interleaved in
    call order with the gate segments, so a whole noise layer is one
    dispatch.  Deliberately NOT a superoperator fold (capture_raw): these
    channels' superoperators have operator-Schmidt rank 4 across
    (t, t+n), and a rank-4 window pass per channel measured slower than
    the elementwise kernel (BASELINE.md round-3)."""
    sh = qureg.num_qubits_represented
    bits = (target, target + sh)
    if not _capturable(qureg, bits):
        drain(qureg)
        return False
    qureg._fusion.gates.append(ChannelItem(kind, target, target + sh, prob))
    return True


def capture_not(qureg, targets, controls=(), control_states=()) -> bool:
    """Buffer a (multi-controlled) multi-qubit NOT: uncontrolled targets
    become independent 1q X gates; controlled ones one dense gate."""
    if not controls:
        buf = getattr(qureg, "_fusion", None)
        if buf is None:
            return False
        sh = qureg.num_qubits_represented
        bits = list(targets)
        if qureg.is_density_matrix:
            bits += [t + sh for t in targets]
        if not all(_capturable(qureg, (b,)) for b in bits):
            drain(qureg)
            return False
        for t in targets:
            buf.gates.append(C.Gate((t,), _X))
            if qureg.is_density_matrix:
                buf.gates.append(C.Gate((t + sh,), _X))
        return True
    # controlled: one dense gate, X^(x)nt (the bit-COMPLEMENT permutation
    # i -> i ^ (2^nt - 1)) under the controls.  Size-check BEFORE
    # densifying — 2^nt x 2^nt would be catastrophic for a wide
    # multiQubitNot outside the cap.
    if not _capturable(qureg, tuple(targets) + tuple(controls)):
        drain(qureg)
        return False
    nt = len(targets)
    d = 1 << nt
    xr = np.zeros((d, d))
    for i in range(d):
        xr[i, i ^ (d - 1)] = 1.0
    mat = np.stack([xr, np.zeros((d, d))])
    return capture_unitary(qureg, mat, targets, controls, control_states)


def capture_diag(qureg, diag_stacked, targets, controls=(),
                 control_states=()) -> bool:
    """Buffer a diagonal gate as its dense matrix."""
    if not _capturable(qureg, tuple(targets) + tuple(controls)):
        drain(qureg)
        return False
    diag = diag_stacked
    d = diag.shape[-1]
    if isinstance(diag, np.ndarray):
        mat = np.zeros((2, d, d), dtype=diag.dtype)
        mat[0][np.diag_indices(d)] = diag[0]
        mat[1][np.diag_indices(d)] = diag[1]
    else:
        mat = jnp.zeros((2, d, d), diag.dtype)
        mat = mat.at[0, np.arange(d), np.arange(d)].set(diag[0])
        mat = mat.at[1, np.arange(d), np.arange(d)].set(diag[1])
    return capture_unitary(qureg, mat, targets, controls, control_states)


@contextmanager
def gate_fusion(qureg):
    """Context manager: buffer dense imperative-API gates on ``qureg`` and
    execute them through the fused circuit scheduler on exit (or the
    moment any operation needs the amplitudes).  Nesting-safe: an inner
    context reuses the outer buffer and leaves it active on exit."""
    created = getattr(qureg, "_fusion", None) is None
    start_gate_fusion(qureg)
    try:
        yield qureg
    finally:
        if created:
            stop_gate_fusion(qureg)
