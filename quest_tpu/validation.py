"""Input validation: the TPU-native re-implementation of the reference's
validation layer (QuEST_validation.c: 80-code error enum :32-197, ~70
validate* functions :331-984).

The reference reports errors through the overridable weak symbol
``invalidQuESTInputError`` which by default prints and exit(1)s
(QuEST_validation.c:199-210); its test-suite overrides it to throw.  Here
errors are always a raised ``QuESTError`` — the Pythonic equivalent of the
overridden hook — and small-matrix numeric checks (unitarity to REAL_EPS,
CPTP) run host-side on NumPy before any tracing.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from .precision import real_eps


class QuESTError(ValueError):
    """Raised on invalid user input (reference invalidQuESTInputError,
    QuEST.h:5354)."""


def _raise(msg: str, func: str):
    raise QuESTError(f"{func}: {msg}")


def validate_num_qubits(num_qubits: int, func: str):
    if num_qubits <= 0:
        _raise("Invalid number of qubits. Must create >0.", func)
    if num_qubits > 62:
        _raise("Invalid number of qubits. The maximum representable is 62.", func)


def validate_target(qureg, target: int, func: str):
    if target < 0 or target >= qureg.num_qubits_represented:
        _raise("Invalid target qubit. Note that qubit indices begin with 0.", func)


def validate_control_target(qureg, control: int, target: int, func: str):
    validate_target(qureg, target, func)
    validate_target(qureg, control, func)
    if control == target:
        _raise("Control qubit cannot equal target qubit.", func)


def validate_unique_targets(qureg, qb1: int, qb2: int, func: str):
    validate_target(qureg, qb1, func)
    validate_target(qureg, qb2, func)
    if qb1 == qb2:
        _raise("Qubits must be unique.", func)


def validate_multi_qubits(qureg, qubits: Sequence[int], func: str, what="qubits"):
    if len(qubits) < 1 or len(qubits) > qureg.num_qubits_represented:
        _raise(f"Invalid number of {what}. Must be >0 and <=numQubits.", func)
    for q in qubits:
        validate_target(qureg, q, func)
    if len(set(qubits)) != len(qubits):
        _raise(f"The {what} must be unique.", func)


def validate_multi_controls_targets(
    qureg, controls: Sequence[int], targets: Sequence[int], func: str
):
    validate_multi_qubits(qureg, targets, func, "target qubits")
    if len(controls) > 0:
        validate_multi_qubits(qureg, controls, func, "control qubits")
    if set(controls) & set(targets):
        _raise("Control qubits cannot equal target qubits.", func)


def validate_control_states(controls, control_states, func: str):
    for s in control_states:
        if s not in (0, 1):
            _raise("Invalid control-qubit state. Must be 0 or 1.", func)
    if len(control_states) != len(controls):
        _raise("Number of control states must match number of control qubits.", func)


def validate_outcome(outcome: int, func: str):
    if outcome not in (0, 1):
        _raise("Invalid measurement outcome. Must be 0 or 1.", func)


def validate_measurement_prob(prob: float, func: str):
    if prob < real_eps():
        _raise("Can't collapse to state with zero probability.", func)


def validate_prob(prob: float, func: str, max_prob: float = 1.0, name="probability"):
    if prob < 0 or prob > max_prob + real_eps():
        _raise(f"Invalid {name}. Must be in [0, {max_prob}].", func)


def validate_density_matrix(qureg, func: str):
    if not qureg.is_density_matrix:
        _raise("Operation valid only for density matrices.", func)


def validate_state_vector(qureg, func: str):
    if qureg.is_density_matrix:
        _raise("Operation valid only for state-vectors.", func)


def validate_matching_qureg_dims(q1, q2, func: str):
    if q1.num_qubits_represented != q2.num_qubits_represented:
        _raise("Dimensions of the qubit registers don't match.", func)


def validate_matching_qureg_types(q1, q2, func: str):
    if q1.is_density_matrix != q2.is_density_matrix:
        _raise(
            "Registers must both be state-vectors or both be density matrices.", func
        )


def _as_matrix(u) -> np.ndarray:
    return np.asarray(u, dtype=np.complex128)


def validate_matrix_size(u, num_targets: int, func: str):
    m = _as_matrix(u)
    dim = 1 << num_targets
    if m.shape != (dim, dim):
        _raise(
            f"Matrix size (2^{num_targets} x 2^{num_targets}) doesn't match the "
            "number of target qubits.",
            func,
        )


def validate_unitary(u, num_targets: int, func: str):
    """Unitarity to REAL_EPS (macro_isMatrixUnitary,
    QuEST_validation.c:232-258)."""
    validate_matrix_size(u, num_targets, func)
    m = _as_matrix(u)
    if not np.allclose(m @ m.conj().T, np.eye(m.shape[0]), atol=64 * real_eps()):
        _raise("Matrix is not unitary.", func)


def validate_unit_vector(x, y, z, func: str):
    if abs(x) + abs(y) + abs(z) < real_eps():
        _raise("Invalid axis. Must be a non-zero vector.", func)


def validate_kraus_ops(ops, num_targets: int, func: str):
    """CPTP check: sum K^dag K = I to REAL_EPS (validateKrausOps,
    QuEST_validation.c)."""
    if len(ops) < 1 or len(ops) > (1 << (2 * num_targets)):
        _raise(
            f"Invalid number of Kraus operators. Must be >0 and <= {1 << (2*num_targets)}.",
            func,
        )
    dim = 1 << num_targets
    acc = np.zeros((dim, dim), dtype=np.complex128)
    for op in ops:
        m = _as_matrix(op)
        if m.shape != (dim, dim):
            _raise("Invalid Kraus operator dimensions.", func)
        acc += m.conj().T @ m
    if not np.allclose(acc, np.eye(dim), atol=1024 * real_eps()):
        _raise("The specified Kraus map is not completely positive and trace preserving (CPTP).", func)


def validate_pauli_codes(codes, func: str):
    for c in codes:
        if int(c) not in (0, 1, 2, 3):
            _raise(
                "Invalid Pauli code. Codes must be 0 (I), 1 (X), 2 (Y) or 3 (Z).",
                func,
            )


def validate_hamil_params(num_qubits: int, num_terms: int, func: str):
    if num_qubits <= 0 or num_terms <= 0:
        _raise("Invalid PauliHamil parameters. Must be >0.", func)


def validate_pauli_hamil(hamil, func: str):
    validate_hamil_params(hamil.num_qubits, hamil.num_sum_terms, func)
    validate_pauli_codes(np.asarray(hamil.pauli_codes).ravel(), func)


def validate_hamil_matches_qureg(hamil, qureg, func: str):
    if hamil.num_qubits != qureg.num_qubits_represented:
        _raise("PauliHamil dimensions don't match the qubit register.", func)


def validate_diag_op_matches_qureg(op, qureg, func: str):
    if op.num_qubits != qureg.num_qubits_represented:
        _raise("DiagonalOp dimensions don't match the qubit register.", func)


def validate_num_amps(qureg, start: int, num_amps: int, func: str):
    if start < 0 or start >= qureg.num_amps_total:
        _raise("Invalid amplitude index.", func)
    if num_amps < 0 or start + num_amps > qureg.num_amps_total:
        _raise("Invalid number of amplitudes.", func)


def validate_trotter_params(order: int, reps: int, func: str):
    if order <= 0 or (order % 2 and order != 1):
        _raise("Invalid Trotter order. Must be 1, or an even number.", func)
    if reps <= 0:
        _raise("Invalid number of Trotter repetitions. Must be >=1.", func)


def validate_phase_func_name(name: int, func: str):
    if name < 0 or name > 13:
        _raise("Invalid named phase function.", func)


def validate_bit_encoding(encoding: int, func: str):
    if encoding not in (0, 1):
        _raise("Invalid bit encoding. Must be UNSIGNED (0) or TWOS_COMPLEMENT (1).", func)


def validate_phase_func_overrides(num_regs_qubits, encoding, override_inds, func: str):
    """Override indices must be representable by each sub-register's encoding
    (validatePhaseFuncOverrides, QuEST_validation.c:753-984)."""
    for ind_tuple in override_inds:
        for nq, ind in zip(num_regs_qubits, ind_tuple):
            if encoding == 0:
                if ind < 0 or ind >= (1 << nq):
                    _raise(
                        "Invalid phase-function override index for the UNSIGNED encoding.",
                        func,
                    )
            else:
                half = 1 << (nq - 1)
                if ind < -half or ind >= half:
                    _raise(
                        "Invalid phase-function override index for the TWOS_COMPLEMENT encoding.",
                        func,
                    )
