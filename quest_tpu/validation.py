"""Input validation: the TPU-native re-implementation of the reference's
validation layer (QuEST_validation.c: 80-code error enum :32-117, message
table :119-197, ~70 validate* functions :331-984).

Every raise carries the reference's message text VERBATIM (from the
``errorMessages`` table), so test suites that assert on message substrings
— the reference's ``REQUIRE_THROWS_WITH(..., Contains("..."))`` pattern in
SECTION("input validation") blocks — port directly.

The reference reports errors through the overridable weak symbol
``invalidQuESTInputError`` which by default prints and exit(1)s
(QuEST_validation.c:199-210); its test-suite overrides it to throw.  Here
errors are always a raised ``QuESTError`` — the Pythonic equivalent of the
overridden hook — and small-matrix numeric checks (unitarity to REAL_EPS,
CPTP) run host-side on NumPy before any tracing.

Where the reference REJECTS inputs its backend cannot execute but this
framework can (multi-qubit matrices spanning more amplitudes than one
shard, E_CANNOT_FIT_MULTI_QUBIT_MATRIX — our SWAP-relocalization handles
them), validation issues a ``warnings.warn`` with the reference message
instead of raising, preserving observability without losing capability.
"""

from __future__ import annotations

import warnings
from typing import Optional, Sequence

import numpy as np

from .precision import (MAX_NUM_REGS_APPLY_ARBITRARY_PHASE,
                        real_eps, validation_eps)


class QuESTError(ValueError):
    """Raised on invalid user input (reference invalidQuESTInputError,
    QuEST.h:5354)."""


# The reference's error message table, verbatim
# (QuEST_validation.c:119-197).  %s/%d placeholders are filled by callers.
ERROR_MESSAGES = {
    "E_INVALID_NUM_RANKS": "Invalid number of nodes. Distributed simulation can only make use of a power-of-2 number of node.",
    "E_INVALID_NUM_CREATE_QUBITS": "Invalid number of qubits. Must create >0.",
    "E_INVALID_QUBIT_INDEX": "Invalid qubit index. Must be >=0 and <numQubits.",
    "E_INVALID_TARGET_QUBIT": "Invalid target qubit. Must be >=0 and <numQubits.",
    "E_INVALID_CONTROL_QUBIT": "Invalid control qubit. Must be >=0 and <numQubits.",
    "E_INVALID_STATE_INDEX": "Invalid state index. Must be >=0 and <2^numQubits.",
    "E_INVALID_AMP_INDEX": "Invalid amplitude index. Must be >=0 and <2^numQubits.",
    "E_INVALID_ELEM_INDEX": "Invalid element index. Must be >=0 and <2^numQubits.",
    "E_INVALID_NUM_AMPS": "Invalid number of amplitudes. Must be >=0 and <=2^numQubits.",
    "E_INVALID_NUM_ELEMS": "Invalid number of elements. Must be >=0 and <=2^numQubits.",
    "E_INVALID_OFFSET_NUM_AMPS_QUREG": "More amplitudes given than exist in the statevector from the given starting index.",
    "E_INVALID_OFFSET_NUM_ELEMS_DIAG": "More elements given than exist in the diagonal operator from the given starting index.",
    "E_TARGET_IS_CONTROL": "Control qubit cannot equal target qubit.",
    "E_TARGET_IN_CONTROLS": "Control qubits cannot include target qubit.",
    "E_CONTROL_TARGET_COLLISION": "Control and target qubits must be disjoint.",
    "E_QUBITS_NOT_UNIQUE": "The qubits must be unique.",
    "E_TARGETS_NOT_UNIQUE": "The target qubits must be unique.",
    "E_CONTROLS_NOT_UNIQUE": "The control qubits should be unique.",
    "E_INVALID_NUM_QUBITS": "Invalid number of qubits. Must be >0 and <=numQubits.",
    "E_INVALID_NUM_TARGETS": "Invalid number of target qubits. Must be >0 and <=numQubits.",
    "E_INVALID_NUM_CONTROLS": "Invalid number of control qubits. Must be >0 and <numQubits.",
    "E_NON_UNITARY_MATRIX": "Matrix is not unitary.",
    "E_NON_UNITARY_COMPLEX_PAIR": "Compact matrix formed by given complex numbers is not unitary.",
    "E_ZERO_VECTOR": "Invalid axis vector. Must be non-zero.",
    "E_SYS_TOO_BIG_TO_PRINT": "Invalid system size. Cannot print output for systems greater than 5 qubits.",
    "E_COLLAPSE_STATE_ZERO_PROB": "Can't collapse to state with zero probability.",
    "E_INVALID_QUBIT_OUTCOME": "Invalid measurement outcome -- must be either 0 or 1.",
    "E_CANNOT_OPEN_FILE": "Could not open file (%s).",
    "E_SECOND_ARG_MUST_BE_STATEVEC": "Second argument must be a state-vector.",
    "E_MISMATCHING_QUREG_DIMENSIONS": "Dimensions of the qubit registers don't match.",
    "E_MISMATCHING_QUREG_TYPES": "Registers must both be state-vectors or both be density matrices.",
    "E_DEFINED_ONLY_FOR_STATEVECS": "Operation valid only for state-vectors.",
    "E_DEFINED_ONLY_FOR_DENSMATRS": "Operation valid only for density matrices.",
    "E_INVALID_PROB": "Probabilities must be in [0, 1].",
    "E_UNNORM_PROBS": "Probabilities must sum to ~1.",
    "E_INVALID_ONE_QUBIT_DEPHASE_PROB": "The probability of a single qubit dephase error cannot exceed 1/2, which maximally mixes.",
    "E_INVALID_TWO_QUBIT_DEPHASE_PROB": "The probability of a two-qubit qubit dephase error cannot exceed 3/4, which maximally mixes.",
    "E_INVALID_ONE_QUBIT_DEPOL_PROB": "The probability of a single qubit depolarising error cannot exceed 3/4, which maximally mixes.",
    "E_INVALID_TWO_QUBIT_DEPOL_PROB": "The probability of a two-qubit depolarising error cannot exceed 15/16, which maximally mixes.",
    "E_INVALID_ONE_QUBIT_PAULI_PROBS": "The probability of any X, Y or Z error cannot exceed the probability of no error.",
    "E_INVALID_CONTROLS_BIT_STATE": "The state of the control qubits must be a bit sequence (0s and 1s).",
    "E_INVALID_PAULI_CODE": "Invalid Pauli code. Codes must be 0 (or PAULI_I), 1 (PAULI_X), 2 (PAULI_Y) or 3 (PAULI_Z) to indicate the identity, X, Y and Z operators respectively.",
    "E_INVALID_NUM_SUM_TERMS": "Invalid number of terms in the Pauli sum. The number of terms must be >0.",
    "E_CANNOT_FIT_MULTI_QUBIT_MATRIX": "The specified matrix targets too many qubits; the batches of amplitudes to modify cannot all fit in a single distributed node's memory allocation.",
    "E_INVALID_UNITARY_SIZE": "The matrix size does not match the number of target qubits.",
    "E_COMPLEX_MATRIX_NOT_INIT": "The ComplexMatrixN was not successfully created (possibly insufficient memory available).",
    "E_INVALID_NUM_ONE_QUBIT_KRAUS_OPS": "At least 1 and at most 4 single qubit Kraus operators may be specified.",
    "E_INVALID_NUM_TWO_QUBIT_KRAUS_OPS": "At least 1 and at most 16 two-qubit Kraus operators may be specified.",
    "E_INVALID_NUM_N_QUBIT_KRAUS_OPS": "At least 1 and at most 4*N^2 of N-qubit Kraus operators may be specified.",
    "E_INVALID_KRAUS_OPS": "The specified Kraus map is not a completely positive, trace preserving map.",
    "E_MISMATCHING_NUM_TARGS_KRAUS_SIZE": "Every Kraus operator must be of the same number of qubits as the number of targets.",
    "E_DISTRIB_QUREG_TOO_SMALL": "Too few qubits. The created qureg must have at least one amplitude per node used in distributed simulation.",
    "E_DISTRIB_DIAG_OP_TOO_SMALL": "Too few qubits. The created DiagonalOp must contain at least one element per node used in distributed simulation.",
    "E_NUM_AMPS_EXCEED_TYPE": "Too many qubits (max of log2(SIZE_MAX)). Cannot store the number of amplitudes per-node in the size_t type.",
    "E_INVALID_PAULI_HAMIL_PARAMS": "The number of qubits and terms in the PauliHamil must be strictly positive.",
    "E_INVALID_PAULI_HAMIL_FILE_PARAMS": "The number of qubits and terms in the PauliHamil file (%s) must be strictly positive.",
    "E_CANNOT_PARSE_PAULI_HAMIL_FILE_COEFF": "Failed to parse the next expected term coefficient in PauliHamil file (%s).",
    "E_CANNOT_PARSE_PAULI_HAMIL_FILE_PAULI": "Failed to parse the next expected Pauli code in PauliHamil file (%s).",
    "E_INVALID_PAULI_HAMIL_FILE_PAULI_CODE": "The PauliHamil file (%s) contained an invalid pauli code (%d). Codes must be 0 (or PAULI_I), 1 (PAULI_X), 2 (PAULI_Y) or 3 (PAULI_Z) to indicate the identity, X, Y and Z operators respectively.",
    "E_MISMATCHING_PAULI_HAMIL_QUREG_NUM_QUBITS": "The PauliHamil must act on the same number of qubits as exist in the Qureg.",
    "E_INVALID_TROTTER_ORDER": "The Trotterisation order must be 1, or an even number (for higher-order Suzuki symmetrized expansions).",
    "E_INVALID_TROTTER_REPS": "The number of Trotter repetitions must be >=1.",
    "E_MISMATCHING_QUREG_DIAGONAL_OP_SIZE": "The qureg must represent an equal number of qubits as that in the applied diagonal operator.",
    "E_DIAGONAL_OP_NOT_INITIALISED": "The diagonal operator has not been initialised through createDiagonalOperator().",
    "E_PAULI_HAMIL_NOT_DIAGONAL": "The Pauli Hamiltonian contained operators other than PAULI_Z and PAULI_I, and hence cannot be expressed as a diagonal matrix.",
    "E_MISMATCHING_PAULI_HAMIL_DIAGONAL_OP_SIZE": "The Pauli Hamiltonian and diagonal operator have different, incompatible dimensions.",
    "E_INVALID_NUM_SUBREGISTERS": "Invalid number of qubit subregisters, which must be >0 and <=100.",
    "E_INVALID_NUM_PHASE_FUNC_TERMS": "Invalid number of terms in the phase function specified. Must be >0.",
    "E_INVALID_NUM_PHASE_FUNC_OVERRIDES": "Invalid number of phase function overrides specified. Must be >=0, and for single-variable phase functions, <=2^numQubits (the maximum unique binary values of the sub-register). Note that uniqueness of overriding indices is not checked.",
    "E_INVALID_PHASE_FUNC_OVERRIDE_UNSIGNED_INDEX": "Invalid phase function override index, in the UNSIGNED encoding. Must be >=0, and <= the maximum index possible of the corresponding qubit subregister (2^numQubits-1).",
    "E_INVALID_PHASE_FUNC_OVERRIDE_TWOS_COMPLEMENT_INDEX": "Invalid phase function override index, in the TWOS_COMPLEMENT encoding. Must be between (inclusive) -2^(N-1) and +2^(N-1)-1, where N is the number of qubits (including the sign qubit).",
    "E_INVALID_PHASE_FUNC_NAME": "Invalid named phase function, which must be one of {NORM, SCALED_NORM, INVERSE_NORM, SCALED_INVERSE_NORM, PRODUCT, SCALED_PRODUCT, INVERSE_PRODUCT, SCALED_INVERSE_PRODUCT, DISTANCE, SCALED_DISTANCE, INVERSE_DISTANCE, SCALED_INVERSE_DISTANCE}.",
    "E_INVALID_NUM_NAMED_PHASE_FUNC_PARAMS": "Invalid number of parameters passed for the given named phase function. {NORM, PRODUCT, DISTANCE} accept 0 parameters, {INVERSE_NORM, INVERSE_PRODUCT, INVERSE_DISTANCE} accept 1 parameter (the phase at the divergence), {SCALED_NORM, SCALED_INVERSE_NORM, SCALED_PRODUCT} accept 1 parameter (the scaling coefficient), {SCALED_INVERSE_PRODUCT, SCALED_DISTANCE, SCALED_INVERSE_DISTANCE} accept 2 parameters (the coefficient then divergence phase), SCALED_INVERSE_SHIFTED_NORM accepts 2 + (number of sub-registers) parameters (the coefficient, then the divergence phase, followed by the offset for each sub-register), SCALED_INVERSE_SHIFTED_DISTANCE accepts 2 + (number of sub-registers) / 2 parameters (the coefficient, then the divergence phase, followed by the offset for each pair of sub-registers).",
    "E_INVALID_BIT_ENCODING": "Invalid bit encoding. Must be one of {UNSIGNED, TWOS_COMPLEMENT}.",
    "E_INVALID_NUM_QUBITS_TWOS_COMPLEMENT": "A sub-register contained too few qubits to employ TWOS_COMPLEMENT encoding. Must use >1 qubits (allocating one for the sign).",
    "E_NEGATIVE_EXPONENT_WITHOUT_ZERO_OVERRIDE": "The phase function contained a negative exponent which would diverge at zero, but the zero index was not overriden.",
    "E_FRACTIONAL_EXPONENT_WITHOUT_NEG_OVERRIDE": "The phase function contained a fractional exponent, which in TWOS_COMPLEMENT encoding, requires all negative indices are overriden. However, one or more negative indices were not overriden.",
    "E_NEGATIVE_EXPONENT_MULTI_VAR": "The phase function contained an illegal negative exponent. One must instead call applyPhaseFuncOverrides() once for each register, so that the zero index of each register is overriden, independent of the indices of all other registers.",
    "E_FRACTIONAL_EXPONENT_MULTI_VAR": "The phase function contained a fractional exponent, which is illegal in TWOS_COMPLEMENT encoding, since it cannot be (efficiently) checked that all negative indices were overriden. One must instead call applyPhaseFuncOverrides() once for each register, so that each register's negative indices can be overriden, independent of the indices of all other registers.",
    "E_INVALID_NUM_REGS_DISTANCE_PHASE_FUNC": "Phase functions DISTANCE, INVERSE_DISTANCE, SCALED_DISTANCE and SCALED_INVERSE_DISTANCE require a strictly even number of sub-registers.",
    # extension (no reference analogue): the reference's C API cannot
    # receive NaN/Inf without UB downstream; here they must be rejected
    # up front or they poison every later amplitude (ISSUE 2).
    "E_NOT_FINITE": "Invalid input. Matrix, diagonal-operator and amplitude values must be finite (no NaN or Inf).",
}


def _raise(code: str, func: str, *fmt):
    msg = ERROR_MESSAGES[code]
    if fmt:
        msg = msg % fmt
    raise QuESTError(f"{func}: {msg}")


def strict_parity() -> bool:
    """QT_STRICT_VALIDATION=1 escalates the two deliberately-warn-only
    codes (E_CANNOT_FIT_MULTI_QUBIT_MATRIX, E_DISTRIB_QUREG_TOO_SMALL) to
    QuESTError so test suites ported verbatim from the reference (which
    REQUIRE_THROWS_WITH on them) pass unchanged.  By default they warn:
    quest_tpu can actually execute both cases (SWAP-relocalization /
    mesh replication) where the reference must reject them."""
    import os

    return os.environ.get("QT_STRICT_VALIDATION") == "1"


def _warn(code: str, func: str):
    if strict_parity():
        _raise(code, func)
    warnings.warn(f"{func}: {ERROR_MESSAGES[code]} "
                  "(quest_tpu executes this via SWAP-relocalization instead "
                  "of rejecting it)", stacklevel=3)


def _warn_replicated(code: str, func: str):
    if strict_parity():
        _raise(code, func)
    warnings.warn(f"{func}: {ERROR_MESSAGES[code]} "
                  "(quest_tpu replicates such small registers across the "
                  "mesh instead of rejecting them)", stacklevel=3)


# ---------------------------------------------------------------------------
# Environment / register creation (QuEST_validation.c:331-371)
# ---------------------------------------------------------------------------


def validate_num_ranks(num_ranks: int, func: str = "createQuESTEnv"):
    """validateNumRanks (:331-343): power-of-2 node counts only."""
    if num_ranks < 1 or (num_ranks & (num_ranks - 1)):
        _raise("E_INVALID_NUM_RANKS", func)


def validate_num_qubits(num_qubits: int, func: str, num_ranks: int = 1):
    """validateNumQubitsInQureg (:345-355): >0, fits the index type, and
    >= 1 amplitude per node.  The reference REJECTS registers smaller than
    the node count (its chunked allocation cannot represent them); ours
    replicates such registers across the mesh instead, so this warns with
    the reference's message rather than raising."""
    if num_qubits <= 0:
        _raise("E_INVALID_NUM_CREATE_QUBITS", func)
    if num_qubits > 62:
        _raise("E_NUM_AMPS_EXCEED_TYPE", func)
    if (1 << num_qubits) < num_ranks:
        _warn_replicated("E_DISTRIB_QUREG_TOO_SMALL", func)


def validate_num_qubits_in_matrix(num_qubits: int, func: str):
    """validateNumQubitsInMatrix (:357-359)."""
    if num_qubits <= 0:
        _raise("E_INVALID_NUM_CREATE_QUBITS", func)


def validate_num_qubits_in_diag_op(num_qubits: int, num_ranks: int, func: str):
    """validateNumQubitsInDiagOp (:361-371); see validate_num_qubits for
    why the per-node size check warns instead of raising."""
    if num_qubits <= 0:
        _raise("E_INVALID_NUM_CREATE_QUBITS", func)
    if (1 << num_qubits) < num_ranks:
        _warn_replicated("E_DISTRIB_DIAG_OP_TOO_SMALL", func)


# ---------------------------------------------------------------------------
# Index / qubit-set validation (:373-467)
# ---------------------------------------------------------------------------


def validate_state_index(qureg, state_ind: int, func: str):
    """validateStateIndex (:373-376)."""
    if state_ind < 0 or state_ind >= (1 << qureg.num_qubits_represented):
        _raise("E_INVALID_STATE_INDEX", func)


def validate_amp_index(qureg, amp_ind: int, func: str):
    """validateAmpIndex (:378-381)."""
    if amp_ind < 0 or amp_ind >= (1 << qureg.num_qubits_represented):
        _raise("E_INVALID_AMP_INDEX", func)


def validate_num_amps(qureg, start: int, num_amps: int, func: str):
    """validateNumAmps (:383-387)."""
    validate_amp_index(qureg, start, func)
    if num_amps < 0 or num_amps > qureg.num_amps_total:
        _raise("E_INVALID_NUM_AMPS", func)
    if num_amps + start > qureg.num_amps_total:
        _raise("E_INVALID_OFFSET_NUM_AMPS_QUREG", func)


def validate_num_elems(op, start: int, num_elems: int, func: str):
    """validateNumElems (:389-394)."""
    dim = 1 << op.num_qubits
    if start < 0 or start >= dim:
        _raise("E_INVALID_ELEM_INDEX", func)
    if num_elems < 0 or num_elems > dim:
        _raise("E_INVALID_NUM_ELEMS", func)
    if num_elems + start > dim:
        _raise("E_INVALID_OFFSET_NUM_ELEMS_DIAG", func)


def validate_target(qureg, target: int, func: str):
    """validateTarget (:396-398)."""
    if target < 0 or target >= qureg.num_qubits_represented:
        _raise("E_INVALID_TARGET_QUBIT", func)


def validate_control(qureg, control: int, func: str):
    """validateControl (:400-402)."""
    if control < 0 or control >= qureg.num_qubits_represented:
        _raise("E_INVALID_CONTROL_QUBIT", func)


def validate_control_target(qureg, control: int, target: int, func: str):
    """validateControlTarget (:404-408)."""
    validate_target(qureg, target, func)
    validate_control(qureg, control, func)
    if control == target:
        _raise("E_TARGET_IS_CONTROL", func)


def validate_unique_targets(qureg, qb1: int, qb2: int, func: str):
    """validateUniqueTargets (:410-414)."""
    validate_target(qureg, qb1, func)
    validate_target(qureg, qb2, func)
    if qb1 == qb2:
        _raise("E_TARGETS_NOT_UNIQUE", func)


def validate_num_targets(qureg, num_targets: int, func: str):
    """validateNumTargets (:416-418)."""
    if num_targets < 1 or num_targets > qureg.num_qubits_represented:
        _raise("E_INVALID_NUM_TARGETS", func)


def validate_num_controls(qureg, num_controls: int, func: str):
    """validateNumControls (:420-422): note the strict < numQubits."""
    if num_controls < 1 or num_controls >= qureg.num_qubits_represented:
        _raise("E_INVALID_NUM_CONTROLS", func)


def validate_multi_targets(qureg, targets: Sequence[int], func: str):
    """validateMultiTargets (:424-430)."""
    validate_num_targets(qureg, len(targets), func)
    for q in targets:
        validate_target(qureg, q, func)
    if len(set(targets)) != len(targets):
        _raise("E_TARGETS_NOT_UNIQUE", func)


def validate_multi_controls(qureg, controls: Sequence[int], func: str):
    """validateMultiControls (:432-438)."""
    validate_num_controls(qureg, len(controls), func)
    for q in controls:
        validate_control(qureg, q, func)
    if len(set(controls)) != len(controls):
        _raise("E_CONTROLS_NOT_UNIQUE", func)


def validate_multi_qubits(qureg, qubits: Sequence[int], func: str,
                          what: str = "qubits"):
    """validateMultiQubits (:440-446)."""
    if len(qubits) < 1 or len(qubits) > qureg.num_qubits_represented:
        _raise("E_INVALID_NUM_QUBITS", func)
    for q in qubits:
        if q < 0 or q >= qureg.num_qubits_represented:
            _raise("E_INVALID_QUBIT_INDEX", func)
    if len(set(qubits)) != len(qubits):
        _raise("E_QUBITS_NOT_UNIQUE", func)


def validate_multi_controls_target(qureg, controls: Sequence[int],
                                   target: int, func: str):
    """validateMultiControlsTarget (:448-453)."""
    validate_target(qureg, target, func)
    validate_multi_controls(qureg, controls, func)
    if target in set(controls):
        _raise("E_TARGET_IN_CONTROLS", func)


def validate_multi_controls_targets(
    qureg, controls: Sequence[int], targets: Sequence[int], func: str
):
    """validateMultiControlsMultiTargets (:455-462)."""
    validate_multi_targets(qureg, targets, func)
    if len(controls) > 0:
        validate_multi_controls(qureg, controls, func)
    if set(controls) & set(targets):
        _raise("E_CONTROL_TARGET_COLLISION", func)


def validate_control_states(controls, control_states, func: str):
    """validateControlState (:464-467)."""
    if len(control_states) != len(controls):
        _raise("E_INVALID_CONTROLS_BIT_STATE", func)
    for s in control_states:
        if s not in (0, 1):
            _raise("E_INVALID_CONTROLS_BIT_STATE", func)


def validate_multi_qubit_matrix_fits_in_node(qureg, num_targets: int,
                                             func: str):
    """validateMultiQubitMatrixFitsInNode (:469-471).  The reference
    REJECTS a matrix whose 2^numTargets amplitude batches exceed one
    node's chunk; our SWAP-relocalization executes it anyway, so this
    warns (with the reference's message) instead of raising."""
    env = getattr(qureg, "env", None)
    num_ranks = getattr(env, "num_ranks", 1) if env is not None else 1
    if num_ranks > 1 and (1 << num_targets) > qureg.num_amps_total // num_ranks:
        _warn("E_CANNOT_FIT_MULTI_QUBIT_MATRIX", func)


# ---------------------------------------------------------------------------
# Matrices / unitarity (:473-509; macro_isMatrixUnitary :232-258)
# ---------------------------------------------------------------------------


def _as_matrix(u) -> np.ndarray:
    return np.asarray(u, dtype=np.complex128)


def validate_finite(values, func: str):
    """Reject NaN/Inf in user-supplied numeric payloads (matrices,
    diagonal operators, setAmps/initStateFromAmps amplitudes).  EXTENSION:
    the reference never checks finiteness — a single NaN silently poisons
    the whole register on the first sweep; the numerical-health watchdog
    (resilience.py) would catch it only K gates later, so the cheap host
    check here names the offending call instead.  Traced values (inside
    jit) are skipped — they are unknowable at validation time."""
    try:
        arr = np.asarray(values)
    # qlint: allow(broad-except): tracer materialization raises framework-version-dependent types (ConcretizationTypeError and friends); any failure here means "traced value" and the check is simply skipped
    except Exception:
        return  # traced / non-materializable: nothing to check host-side
    if arr.dtype == object or not np.issubdtype(arr.dtype, np.number):
        return
    if not np.all(np.isfinite(arr)):
        _raise("E_NOT_FINITE", func)


def validate_matrix_size(u, num_targets: int, func: str):
    """part of validateMultiQubitMatrix (:492-496); also rejects
    non-finite entries (validate_finite) — this validator guards both the
    unitary and the no-unitarity-check apply* families, so the finiteness
    gate holds even where unitarity is deliberately skipped."""
    m = _as_matrix(u)
    dim = 1 << num_targets
    if m.shape != (dim, dim):
        _raise("E_INVALID_UNITARY_SIZE", func)
    validate_finite(m, func)


def validate_unitary(u, num_targets: int, func: str):
    """Unitarity to REAL_EPS (macro_isMatrixUnitary,
    QuEST_validation.c:232-258; validate*UnitaryMatrix :473-501)."""
    validate_matrix_size(u, num_targets, func)
    m = _as_matrix(u)
    if not np.allclose(m @ m.conj().T, np.eye(m.shape[0]), atol=64 * validation_eps()):
        _raise("E_NON_UNITARY_MATRIX", func)


def validate_unitary_complex_pair(alpha, beta, func: str):
    """validateUnitaryComplexPair (:503-505): |alpha|^2 + |beta|^2 = 1."""
    if abs(abs(alpha) ** 2 + abs(beta) ** 2 - 1) > validation_eps():
        _raise("E_NON_UNITARY_COMPLEX_PAIR", func)


def validate_matrix_init(matr, func: str):
    """validateMatrixInit (:482-490)."""
    if matr is None or (hasattr(matr, "real") and getattr(matr, "real") is None):
        _raise("E_COMPLEX_MATRIX_NOT_INIT", func)


def validate_unit_vector(x, y, z, func: str):
    """validateVector (:507-509): magnitude must exceed REAL_EPS (compare
    the squared magnitude against eps^2 to keep units consistent)."""
    if (x * x + y * y + z * z) <= validation_eps() ** 2:
        _raise("E_ZERO_VECTOR", func)


# ---------------------------------------------------------------------------
# Register kinds / outcomes / probabilities (:511-593)
# ---------------------------------------------------------------------------


def validate_state_vector(qureg, func: str):
    """validateStateVecQureg (:511-513)."""
    if qureg.is_density_matrix:
        _raise("E_DEFINED_ONLY_FOR_STATEVECS", func)


def validate_density_matrix(qureg, func: str):
    """validateDensityMatrQureg (:515-517)."""
    if not qureg.is_density_matrix:
        _raise("E_DEFINED_ONLY_FOR_DENSMATRS", func)


def validate_outcome(outcome: int, func: str):
    """validateOutcome (:519-521)."""
    if outcome not in (0, 1):
        _raise("E_INVALID_QUBIT_OUTCOME", func)


def validate_measurement_prob(prob: float, func: str):
    """validateMeasurementProb (:523-525)."""
    # stays on real_eps (NOT validation_eps): a tiny probability from
    # the compensated prec-4 reductions is legitimate data, not an f64
    # rounding artifact — the reference's quad build compares REAL_EPS
    if prob < real_eps():
        _raise("E_COLLAPSE_STATE_ZERO_PROB", func)


def validate_matching_qureg_dims(q1, q2, func: str):
    """validateMatchingQuregDims (:527-529)."""
    if q1.num_qubits_represented != q2.num_qubits_represented:
        _raise("E_MISMATCHING_QUREG_DIMENSIONS", func)


def validate_matching_qureg_types(q1, q2, func: str):
    """validateMatchingQuregTypes (:531-533)."""
    if q1.is_density_matrix != q2.is_density_matrix:
        _raise("E_MISMATCHING_QUREG_TYPES", func)


def validate_second_qureg_state_vec(q2, func: str):
    """validateSecondQuregStateVec (:535-537)."""
    if q2.is_density_matrix:
        _raise("E_SECOND_ARG_MUST_BE_STATEVEC", func)


def validate_file_opened(opened: bool, fn: str, func: str):
    """validateFileOpened (:539-545)."""
    if not opened:
        _raise("E_CANNOT_OPEN_FILE", func, fn)


def validate_prob(prob: float, func: str):
    """validateProb (:547-549); channel caps have dedicated validators
    below."""
    if prob < 0 or prob > 1:
        _raise("E_INVALID_PROB", func)


def validate_norm_probs(prob1: float, prob2: float, func: str):
    """validateNormProbs (:551-557)."""
    validate_prob(prob1, func)
    validate_prob(prob2, func)
    if abs(1 - (prob1 + prob2)) >= real_eps():
        _raise("E_UNNORM_PROBS", func)


def validate_one_qubit_dephase_prob(prob: float, func: str):
    """validateOneQubitDephaseProb (:559-562)."""
    validate_prob(prob, func)
    if prob > 1 / 2.0:
        _raise("E_INVALID_ONE_QUBIT_DEPHASE_PROB", func)


def validate_two_qubit_dephase_prob(prob: float, func: str):
    """validateTwoQubitDephaseProb (:564-567)."""
    validate_prob(prob, func)
    if prob > 3 / 4.0:
        _raise("E_INVALID_TWO_QUBIT_DEPHASE_PROB", func)


def validate_one_qubit_depol_prob(prob: float, func: str):
    """validateOneQubitDepolProb (:569-572)."""
    validate_prob(prob, func)
    if prob > 3 / 4.0:
        _raise("E_INVALID_ONE_QUBIT_DEPOL_PROB", func)


def validate_one_qubit_damping_prob(prob: float, func: str):
    """validateOneQubitDampingProb (:574-577): cap 1, but the reference
    reports it under the DEPOL error code."""
    validate_prob(prob, func)
    if prob > 1.0:
        _raise("E_INVALID_ONE_QUBIT_DEPOL_PROB", func)


def validate_two_qubit_depol_prob(prob: float, func: str):
    """validateTwoQubitDepolProb (:579-582)."""
    validate_prob(prob, func)
    if prob > 15 / 16.0:
        _raise("E_INVALID_TWO_QUBIT_DEPOL_PROB", func)


def validate_one_qubit_pauli_probs(px: float, py: float, pz: float, func: str):
    """validateOneQubitPauliProbs (:584-593)."""
    validate_prob(px, func)
    validate_prob(py, func)
    validate_prob(pz, func)
    prob_no_error = 1 - px - py - pz
    if px > prob_no_error or py > prob_no_error or pz > prob_no_error:
        _raise("E_INVALID_ONE_QUBIT_PAULI_PROBS", func)


# ---------------------------------------------------------------------------
# Pauli sums / Kraus maps (:595-645)
# ---------------------------------------------------------------------------


def validate_pauli_codes(codes, func: str):
    """validatePauliCodes (:595-600)."""
    for c in np.asarray(codes).ravel():
        if int(c) not in (0, 1, 2, 3):
            _raise("E_INVALID_PAULI_CODE", func)


def validate_num_pauli_sum_terms(num_terms: int, func: str):
    """validateNumPauliSumTerms (:602-604)."""
    if num_terms <= 0:
        _raise("E_INVALID_NUM_SUM_TERMS", func)


def validate_kraus_ops(ops, num_targets: int, func: str):
    """validate{One,Two,Multi}QubitKrausMap (:606-645): operator-count
    bounds per arity, matching dimensions, CPTP to REAL_EPS."""
    max_ops = 1 << (2 * num_targets)
    if len(ops) < 1 or len(ops) > max_ops:
        code = {
            1: "E_INVALID_NUM_ONE_QUBIT_KRAUS_OPS",
            2: "E_INVALID_NUM_TWO_QUBIT_KRAUS_OPS",
        }.get(num_targets, "E_INVALID_NUM_N_QUBIT_KRAUS_OPS")
        _raise(code, func)
    dim = 1 << num_targets
    acc = np.zeros((dim, dim), dtype=np.complex128)
    for op in ops:
        m = _as_matrix(op)
        if m.shape != (dim, dim):
            _raise("E_MISMATCHING_NUM_TARGS_KRAUS_SIZE", func)
        acc += m.conj().T @ m
    if not np.allclose(acc, np.eye(dim), atol=1024 * validation_eps()):
        _raise("E_INVALID_KRAUS_OPS", func)


# ---------------------------------------------------------------------------
# PauliHamil / Trotter / DiagonalOp (:647-751)
# ---------------------------------------------------------------------------


def validate_hamil_params(num_qubits: int, num_terms: int, func: str):
    """validateHamilParams (:647-649)."""
    if num_qubits <= 0 or num_terms <= 0:
        _raise("E_INVALID_PAULI_HAMIL_PARAMS", func)


def validate_pauli_hamil(hamil, func: str):
    """validatePauliHamil (:651-654)."""
    validate_hamil_params(hamil.num_qubits, hamil.num_sum_terms, func)
    validate_pauli_codes(np.asarray(hamil.pauli_codes).ravel(), func)


def validate_hamil_matches_qureg(hamil, qureg, func: str):
    """validateMatchingQuregPauliHamilDims (:656-658)."""
    if hamil.num_qubits != qureg.num_qubits_represented:
        _raise("E_MISMATCHING_PAULI_HAMIL_QUREG_NUM_QUBITS", func)


def validate_hamil_file_params(num_qubits: int, num_terms: int, fn: str,
                               func: str):
    """validateHamilFileParams (:660-667)."""
    if num_qubits <= 0 or num_terms <= 0:
        _raise("E_INVALID_PAULI_HAMIL_FILE_PARAMS", func, fn)


def validate_hamil_file_coeff_parsed(parsed: bool, fn: str, func: str):
    """validateHamilFileCoeffParsed (:669-677)."""
    if not parsed:
        _raise("E_CANNOT_PARSE_PAULI_HAMIL_FILE_COEFF", func, fn)


def validate_hamil_file_pauli_parsed(parsed: bool, fn: str, func: str):
    """validateHamilFilePauliParsed (:679-687)."""
    if not parsed:
        _raise("E_CANNOT_PARSE_PAULI_HAMIL_FILE_PAULI", func, fn)


def validate_hamil_file_pauli_code(code: int, fn: str, func: str):
    """validateHamilFilePauliCode (:689-697)."""
    if int(code) not in (0, 1, 2, 3):
        _raise("E_INVALID_PAULI_HAMIL_FILE_PAULI_CODE", func, fn, int(code))


def validate_trotter_params(order: int, reps: int, func: str):
    """validateTrotterParams (:699-703)."""
    if order <= 0 or (order % 2 and order != 1):
        _raise("E_INVALID_TROTTER_ORDER", func)
    if reps <= 0:
        _raise("E_INVALID_TROTTER_REPS", func)


def validate_diag_op_init(op, func: str):
    """validateDiagOpInit (:705-707): the reference checks the real/imag
    allocations succeeded (DiagonalOp stores SoA real+imag vectors)."""
    if op is None or getattr(op, "real", None) is None \
            or getattr(op, "imag", None) is None:
        _raise("E_DIAGONAL_OP_NOT_INITIALISED", func)


def validate_diag_op_matches_qureg(op, qureg, func: str):
    """validateDiagonalOp (:709-712)."""
    validate_diag_op_init(op, func)
    if op.num_qubits != qureg.num_qubits_represented:
        _raise("E_MISMATCHING_QUREG_DIAGONAL_OP_SIZE", func)


def validate_diag_pauli_hamil(op, hamil, func: str):
    """validateDiagPauliHamil (:714-721): only I/Z terms, matching dims."""
    validate_diag_op_init(op, func)
    validate_hamil_params(hamil.num_qubits, hamil.num_sum_terms, func)
    if op.num_qubits != hamil.num_qubits:
        _raise("E_MISMATCHING_PAULI_HAMIL_DIAGONAL_OP_SIZE", func)
    for c in np.asarray(hamil.pauli_codes).ravel():
        if int(c) not in (0, 3):
            _raise("E_PAULI_HAMIL_NOT_DIAGONAL", func)


def validate_diag_hamil_from_file(hamil, num_ranks: int, func: str):
    """validateDiagPauliHamilFromFile (:723-751)."""
    validate_hamil_params(hamil.num_qubits, hamil.num_sum_terms, func)
    if (1 << hamil.num_qubits) < num_ranks:
        _raise("E_DISTRIB_DIAG_OP_TOO_SMALL", func)
    for c in np.asarray(hamil.pauli_codes).ravel():
        if int(c) not in (0, 3):
            _raise("E_PAULI_HAMIL_NOT_DIAGONAL", func)


# ---------------------------------------------------------------------------
# Phase functions (:753-984)
# ---------------------------------------------------------------------------


def validate_qubit_subregs(qureg, qubits_per_reg: Sequence[Sequence[int]],
                           func: str):
    """validateQubitSubregs (:753-767)."""
    num_regs = len(qubits_per_reg)
    if num_regs <= 0 or num_regs > MAX_NUM_REGS_APPLY_ARBITRARY_PHASE:
        _raise("E_INVALID_NUM_SUBREGISTERS", func)
    flat = []
    for reg in qubits_per_reg:
        if len(reg) <= 0 or len(reg) > qureg.num_qubits_represented:
            _raise("E_INVALID_NUM_QUBITS", func)
        for q in reg:
            if q < 0 or q >= qureg.num_qubits_represented:
                _raise("E_INVALID_QUBIT_INDEX", func)
            flat.append(q)
    if len(set(flat)) != len(flat):
        _raise("E_QUBITS_NOT_UNIQUE", func)


def validate_phase_func_terms(num_qubits: int, encoding: int, coeffs,
                              exponents, override_inds, func: str):
    """validatePhaseFuncTerms (:769-831): term count, negative exponents
    need a zero override, fractional exponents in TWOS_COMPLEMENT need all
    negative indices overriden."""
    exponents = list(exponents)
    if len(exponents) <= 0:
        _raise("E_INVALID_NUM_PHASE_FUNC_TERMS", func)
    has_fraction = any(np.floor(e) != e for e in exponents)
    has_negative = any(e < 0 for e in exponents)
    inds = [int(i) for i in override_inds]
    if has_negative and 0 not in inds:
        _raise("E_NEGATIVE_EXPONENT_WITHOUT_ZERO_OVERRIDE", func)
    if has_fraction and encoding == 1:  # TWOS_COMPLEMENT
        num_neg = 1 << (num_qubits - 1)
        neg_overriden = {(-1 - i) for i in inds if i < 0}
        if len(inds) < num_neg or (
            num_qubits < 16 and any(j not in neg_overriden
                                    for j in range(num_neg))
        ):
            _raise("E_FRACTIONAL_EXPONENT_WITHOUT_NEG_OVERRIDE", func)


def validate_multi_var_phase_func_terms(num_qubits_per_reg, encoding,
                                        exponents_per_reg, func: str):
    """validateMultiVarPhaseFuncTerms (:831-855)."""
    num_regs = len(num_qubits_per_reg)
    if num_regs <= 0 or num_regs > MAX_NUM_REGS_APPLY_ARBITRARY_PHASE:
        _raise("E_INVALID_NUM_SUBREGISTERS", func)
    for exps in exponents_per_reg:
        if len(list(exps)) <= 0:
            _raise("E_INVALID_NUM_PHASE_FUNC_TERMS", func)
    all_exps = [e for exps in exponents_per_reg for e in exps]
    if any(e < 0 for e in all_exps):
        _raise("E_NEGATIVE_EXPONENT_MULTI_VAR", func)
    if encoding == 1 and any(np.floor(e) != e for e in all_exps):
        _raise("E_FRACTIONAL_EXPONENT_MULTI_VAR", func)


def validate_phase_func_overrides(num_regs_qubits, encoding, override_inds,
                                  func: str):
    """validatePhaseFuncOverrides / validateMultiVarPhaseFuncOverrides
    (:857-906): override indices representable per sub-register."""
    num_overrides = len(list(override_inds))
    if len(num_regs_qubits) == 1 and num_overrides > (1 << num_regs_qubits[0]):
        _raise("E_INVALID_NUM_PHASE_FUNC_OVERRIDES", func)
    for ind_tuple in override_inds:
        for nq, ind in zip(num_regs_qubits, ind_tuple):
            if encoding == 0:  # UNSIGNED
                if ind < 0 or ind > (1 << nq) - 1:
                    _raise("E_INVALID_PHASE_FUNC_OVERRIDE_UNSIGNED_INDEX",
                           func)
            else:  # TWOS_COMPLEMENT
                half = 1 << (nq - 1)
                if ind < -half or ind > half - 1:
                    _raise(
                        "E_INVALID_PHASE_FUNC_OVERRIDE_TWOS_COMPLEMENT_INDEX",
                        func)


def validate_phase_func_name(name: int, num_regs: int, num_params: int,
                             func: str):
    """validatePhaseFuncName (:908-959): legal code, per-function parameter
    count, even sub-register count for the DISTANCE family."""
    from .ops import phasefunc as _pf

    if name < 0 or name > 13:
        _raise("E_INVALID_PHASE_FUNC_NAME", func)
    expected = {
        _pf.NORM: 0, _pf.PRODUCT: 0, _pf.DISTANCE: 0,
        _pf.INVERSE_NORM: 1, _pf.INVERSE_PRODUCT: 1, _pf.INVERSE_DISTANCE: 1,
        _pf.SCALED_NORM: 1, _pf.SCALED_PRODUCT: 1, _pf.SCALED_DISTANCE: 1,
        _pf.SCALED_INVERSE_NORM: 2, _pf.SCALED_INVERSE_PRODUCT: 2,
        _pf.SCALED_INVERSE_DISTANCE: 2,
        _pf.SCALED_INVERSE_SHIFTED_NORM: 2 + num_regs,
        _pf.SCALED_INVERSE_SHIFTED_DISTANCE: 2 + num_regs // 2,
    }
    if num_params != expected[name]:
        _raise("E_INVALID_NUM_NAMED_PHASE_FUNC_PARAMS", func)
    if name in (_pf.DISTANCE, _pf.INVERSE_DISTANCE, _pf.SCALED_DISTANCE,
                _pf.SCALED_INVERSE_DISTANCE,
                _pf.SCALED_INVERSE_SHIFTED_DISTANCE) and num_regs % 2:
        _raise("E_INVALID_NUM_REGS_DISTANCE_PHASE_FUNC", func)


def validate_bit_encoding(encoding: int, func: str,
                          num_qubits: Optional[int] = None):
    """validateBitEncoding (:961-969)."""
    if encoding not in (0, 1):
        _raise("E_INVALID_BIT_ENCODING", func)
    if encoding == 1 and num_qubits is not None and num_qubits <= 1:
        _raise("E_INVALID_NUM_QUBITS_TWOS_COMPLEMENT", func)


def validate_multi_reg_bit_encoding(num_qubits_per_reg, encoding: int,
                                    func: str):
    """validateMultiRegBitEncoding (:971-981)."""
    if encoding not in (0, 1):
        _raise("E_INVALID_BIT_ENCODING", func)
    if encoding == 1:
        for nq in num_qubits_per_reg:
            if nq <= 1:
                _raise("E_INVALID_NUM_QUBITS_TWOS_COMPLEMENT", func)
