"""Multi-tenant simulation service: continuous batching with admission
control and preemption.

The platform layers shipped so far — structure-fingerprinted ensemble
banks (batch.py), window-granular resumable execution (resilience.py),
analytic HBM admission (governor.py), and labeled telemetry — are
composed here into the serving front end the ROADMAP's north star asks
for: many small heterogeneous circuits from many tenants, arriving as an
open-loop stream, kept saturating the device.  qHiPSTER and mpiQulacs
(PAPERS.md) both stop at throughput-oriented *batch* engines; the piece
they lack, borrowed from LLM serving, is CONTINUOUS batching — admission
of new work between fusion windows of work already in flight, instead of
batch-at-once draining.

**Execution model.**  :class:`SimServer` is a synchronous scheduling
core driven by repeated :meth:`SimServer.step` calls (the asyncio front
end :class:`Service` just steps it between awaits).  One step advances
exactly ONE fusion window of ONE bank:

- submitted jobs land in structure-fingerprinted **buckets** (the
  EnsembleScheduler grouping, extended with the measurement schedule);
- each bucket coalesces waiting jobs into a **bank** — a
  :class:`~quest_tpu.batch.BatchedQureg` padded to a power-of-two batch
  — which stays OPEN (absorbing late arrivals at no cost) until its
  first window executes;
- a started bank advances through a
  :class:`~quest_tpu.resilience.WindowExecutor`, the window-stepping
  loop shared with ``run_resumable``, so between any two windows the
  scheduler can switch banks, admit arrivals, or checkpoint.

Because every bank element shares one program cursor, continuous
batching happens at window granularity: arrivals coalesce into the next
bank of their bucket while the current banks execute, and no arrival
ever waits for a full system drain (the batch-at-once failure mode
``scripts/bench_serve.py`` quantifies).

**Scheduling policy.**  Two strict priority classes — ``interactive``
before ``batch`` — and weighted fair queuing within a class: each
tenant carries a virtual time advanced by ``window / weight`` whenever
a bank holding its jobs runs, and the runnable bank whose owning
tenants have the smallest virtual time goes next (stride scheduling;
an idle tenant's vtime catches up to the clock on its next submit, so
idle periods bank no credit).

**Admission control.**  ``submit`` is the backpressure point; it raises
a structured :class:`QuotaExceededError` (never queues unboundedly)
when the global queue is full, the tenant's pending cap is hit, the
tenant's in-flight analytic bytes exceed its quota, or the job could
never fit the governor's HBM budget — the same ``B x 2 x 2^n x
itemsize`` pricing ``governor.admit_new`` applies at register creation.

**Preemption.**  When an interactive bank is runnable while batch banks
hold device memory mid-flight, the batch banks are preempted AT THEIR
CURRENT WINDOW BOUNDARY — the executor's cursor is always at one
between steps — via the resilience generation protocol
(``preempt="checkpoint"``: commit a generation, drop the device bank)
or kept resident but descheduled (``preempt="pause"``).  Resume reloads
the generation (raw permuted amplitudes, live perm, per-element
measurement key/shot bank) and continues bit-identically to an
uninterrupted run; tests/test_serve.py pins that equivalence.

**Fault tolerance** (docs/design.md §27).  A bank hit by a transient
fault (ShardLossError, exchange-timeout exhaustion, checkpoint IO
failure) is DISSOLVED, not failed: member jobs return to their bucket
with per-job retry budgets and decorrelated-jitter backoff
(resilience.backoff_delay), re-bucket into fresh banks, and only exhaust
to FAILED — each wrapped per-job in :class:`JobFailedError` with the
attempt count and cause chain.  A retried job re-runs from gate 0 under
its own measurement seed (``seed`` or the job id), so a
completed-under-retry job is bit-identical to a fault-free run.  A bank
dying of :class:`~quest_tpu.resilience.NumericalHealthError` or repeated
OOM is BISECTED: the watchdog's worst-element attribution (or batch
halving when unattributed) re-runs members in smaller banks down to
singletons, the culprit is quarantined behind a per-(tenant, structure)
circuit breaker (closed/open/half-open; ``QT_SERVE_QUARANTINE``), and
innocent bank-mates complete.  On host/shard loss the server FAILS OVER
onto the shrunk mesh (env.shrink_env + the §19/§25 elastic-restore path)
without dropping queued work — the governor budget is re-derived and
admission re-priced — and :meth:`SimServer.heal` drains resident banks
to checkpoint boundaries and re-expands onto the recovered full mesh via
the mesh-portable restore (serving is the first consumer of checkpoint
REGROW).  The seeded chaos harness ``scripts/chaos_serve.py``
(``make verify-chaos``) drives all of it end-to-end.

**Observability** (docs/design.md §30).  Every submitted job carries a
``trace_id``; the server threads it through the whole lifecycle
(admit -> bank_join -> window -> preempt/resume/retry -> complete or
failed) as request-scoped span trees queryable via :meth:`SimServer.tracez`
and ``telemetry.tracez``.  Incidents — quarantine verdicts, elastic
degradation, OOM/poison bisection, terminal executor failure — dump the
telemetry flight recorder (the bounded ring of recent structured
events) to JSON under ``QT_SERVE_FLIGHT_DIR`` automatically.
:meth:`SimServer.serve_http` starts a stdlib HTTP thread exposing
``/metrics`` (the Prometheus exposition, byte-identical to
``telemetry.prometheus_text()``), ``/healthz`` (degraded / queue-depth
/ quarantine state), and ``/tracez`` (+ ``/tracez/<trace_id>``).

Environment knobs (all optional, constructor args win):

- ``QT_SERVE_WINDOW``       gates per fusion window        (default 16)
- ``QT_SERVE_MAX_BATCH``    bank size cap, power of two    (default 16)
- ``QT_SERVE_MAX_PENDING``  global queued-job cap          (default 1024)
- ``QT_SERVE_PREEMPT``      checkpoint | pause | off       (default checkpoint)
- ``QT_SERVE_CKPT_DIR``     preemption checkpoint root     (default: temp dir)
- ``QT_SERVE_RETRIES``      per-job retry budget           (default 3)
- ``QT_SERVE_QUARANTINE``   breaker ``count:open_seconds`` (default 2:30)
- ``QT_SERVE_WATCHDOG``     health-check cadence, windows  (default 8; 0=only
  at bank completion — completion is always checked)
- ``QT_SERVE_FLIGHT_DIR``   incident flight-record dump dir (default:
  ``<ckpt root>/flight``)
- ``QT_SERVE_PREWARM``      1 = AOT-prewarm the observed hot
  fingerprint set, including the shrunk-mesh variants elastic failover
  would restore onto (default 0; needs ``QT_AOT_CACHE`` to persist)
"""

from __future__ import annotations

import asyncio
import itertools
import json
import os
import shutil
import tempfile
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import batch as _batch
from . import circuit as C
from . import governor as _governor
from . import resilience as _resilience
from . import telemetry as _telemetry
from .env import QuESTEnv, shrink_env
from .parallel import dist as _dist
from .parallel import topology as _ptopo
from .validation import QuESTError

__all__ = [
    "INTERACTIVE",
    "BATCH",
    "Job",
    "JobFailedError",
    "QuotaExceededError",
    "Service",
    "SimServer",
    "Tenant",
]

# priority classes, strict order: interactive preempts batch
INTERACTIVE = "interactive"
BATCH = "batch"
_PRIORITIES = (INTERACTIVE, BATCH)
_CLASS_RANK = {INTERACTIVE: 0, BATCH: 1}

# job states
QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"

_PREEMPT_MODES = ("checkpoint", "pause", "off")

_WINDOW_ENV = "QT_SERVE_WINDOW"
_MAX_BATCH_ENV = "QT_SERVE_MAX_BATCH"
_MAX_PENDING_ENV = "QT_SERVE_MAX_PENDING"
_PREEMPT_ENV = "QT_SERVE_PREEMPT"
_CKPT_DIR_ENV = "QT_SERVE_CKPT_DIR"
_RETRIES_ENV = "QT_SERVE_RETRIES"
_QUARANTINE_ENV = "QT_SERVE_QUARANTINE"
_WATCHDOG_ENV = "QT_SERVE_WATCHDOG"
_FLIGHT_DIR_ENV = "QT_SERVE_FLIGHT_DIR"
_PREWARM_ENV = "QT_SERVE_PREWARM"

# server serial numbers keep trace ids ("s<serial>-j<jid>") globally
# unique across SimServer instances sharing one telemetry registry (the
# chaos harness runs baseline and chaos servers in one process)
_SERVER_SEQ = itertools.count()

# bank-dissolve reasons (the serve_bank_retries_total label values)
_RETRY_REASONS = ("transient", "failover", "poison")


class QuotaExceededError(QuESTError):
    """A submission was refused by admission control — the structured
    backpressure signal (HTTP-429 analogue).  ``kind`` names the
    exhausted resource:

    - ``backpressure`` — the server's global queued-job cap;
    - ``pending``      — the tenant's queued+running job cap;
    - ``bytes``        — the tenant's in-flight analytic byte quota;
    - ``memory``       — the job could never fit the governor's
      per-device HBM budget (governor.admit_new pricing);
    - ``quarantine``   — this (tenant, circuit-structure) pair is behind
      an OPEN poison-quarantine circuit breaker (``limit`` is the trip
      threshold, ``value`` the recorded poison verdicts).

    Carries the numbers so clients can implement informed retry."""

    def __init__(self, msg: str, *, tenant: str, kind: str,
                 limit: float, value: float):
        super().__init__(msg)
        self.tenant = tenant
        self.kind = kind
        self.limit = limit
        self.value = value


class JobFailedError(QuESTError):
    """A job exhausted its retry budget or was quarantined.  Raised by
    :meth:`Job.result` / :meth:`Service.wait` — constructed fresh per
    call so concurrent callers never share (and mutate the traceback of)
    one exception object across the bank's jobs.  ``cause`` is the final
    underlying error (also chained as ``__cause__``); ``job.errors``
    holds the full per-attempt chain."""

    def __init__(self, *, tenant: str, jid: int, attempts: int,
                 cause: BaseException):
        super().__init__(
            f"job {jid} (tenant {tenant!r}) failed after {attempts} "
            f"attempt(s): {type(cause).__name__}: {cause}")
        self.tenant = tenant
        self.jid = jid
        self.attempts = attempts
        self.cause = cause


class _Breaker:
    """Per-(tenant, structure-fingerprint) quarantine circuit breaker:
    ``closed`` counts poison verdicts, trips ``open`` at the threshold
    (submissions rejected with kind="quarantine"), decays to
    ``half_open`` after ``open_seconds`` (ONE probe admitted), and
    closes again only when a probe completes — another verdict while
    half-open re-opens immediately."""

    __slots__ = ("threshold", "open_seconds", "failures", "opened_at",
                 "state", "probing")

    def __init__(self, threshold: int, open_seconds: float):
        self.threshold = max(1, int(threshold))
        self.open_seconds = float(open_seconds)
        self.failures = 0
        self.opened_at = 0.0
        self.state = "closed"
        self.probing = False

    def record_failure(self) -> None:
        self.failures += 1
        if self.state == "half_open" or self.failures >= self.threshold:
            self.state = "open"
            self.opened_at = time.monotonic()
            self.probing = False

    def record_success(self) -> None:
        if self.state == "half_open":
            self.state = "closed"
            self.failures = 0
        self.probing = False

    def admits(self) -> bool:
        if self.state == "closed":
            return True
        if self.state == "open" \
                and time.monotonic() - self.opened_at >= self.open_seconds:
            self.state = "half_open"
        if self.state == "half_open" and not self.probing:
            self.probing = True
            return True
        return False


class Tenant:
    """Per-tenant scheduling state: fair-share ``weight`` (bigger =
    more windows per unit virtual time), a queued+running job cap, and
    an optional analytic in-flight byte quota priced exactly as the
    governor prices registers."""

    def __init__(self, name: str, *, weight: float = 1.0,
                 max_pending: int = 64,
                 max_bytes: Optional[int] = None):
        if weight <= 0:
            raise QuESTError(
                f"Tenant: weight must be > 0, got {weight}")
        self.name = str(name)
        self.weight = float(weight)
        self.max_pending = int(max_pending)
        self.max_bytes = None if max_bytes is None else int(max_bytes)
        self.vtime = 0.0          # weighted-fair virtual time
        self.inflight = 0         # queued + running jobs
        self.inflight_bytes = 0   # analytic bytes of those jobs
        self.submitted = 0
        self.completed = 0

    def __repr__(self):
        return (f"Tenant({self.name!r}, weight={self.weight}, "
                f"inflight={self.inflight}, vtime={self.vtime:.3f})")


class Job:
    """One submitted circuit.  Lifecycle: ``queued`` -> ``running``
    (its bank's first window executed) -> ``done`` / ``failed``.  On
    completion :attr:`amps` holds the element's canonical (2, 2^n)
    amplitudes (post-measurement when a measurement schedule was
    given), :attr:`outcomes` the per-measured-qubit ``(outcome,
    probability)`` pairs in schedule order, and :attr:`key_state` the
    element's final measurement key/shot-counter pair — the serving
    analogue of BatchedQureg.key_state, recorded so clients (and the
    preemption bit-identity tests) can audit the RNG stream."""

    __slots__ = ("id", "tenant", "gates", "num_qubits", "priority",
                 "seed", "measure", "state", "amps", "outcomes",
                 "key_state", "error", "errors", "bytes", "t_submit",
                 "t_start", "t_done", "attempts", "not_before",
                 "backoff", "bisect_group", "trace_id")

    def __init__(self, jid: int, tenant: str, gates: list,
                 num_qubits: int, priority: str, seed, measure: tuple,
                 nbytes: int):
        self.id = jid
        self.tenant = tenant
        self.gates = gates
        self.num_qubits = num_qubits
        self.priority = priority
        self.seed = seed
        self.measure = measure
        self.bytes = nbytes
        self.state = QUEUED
        self.amps = None
        self.outcomes: List[Tuple[int, float]] = []
        self.key_state: Optional[dict] = None
        self.error: Optional[BaseException] = None
        self.errors: List[str] = []   # per-attempt failure chain
        self.t_submit = time.perf_counter()
        self.t_start: Optional[float] = None
        self.t_done: Optional[float] = None
        self.attempts = 0             # banks this job has started in
        self.not_before = 0.0         # retry backoff gate (monotonic)
        self.backoff: Optional[float] = None  # last backoff delay
        # quarantine bisection: (group-tag, bank-size cap) or None —
        # jobs only share a bank with the same group
        self.bisect_group: Optional[Tuple[str, int]] = None
        # request-scoped trace id ("s<serial>-j<jid>", set at admit)
        self.trace_id = ""

    @property
    def done(self) -> bool:
        return self.state in (DONE, FAILED)

    def _failure(self) -> JobFailedError:
        return JobFailedError(tenant=self.tenant, jid=self.id,
                              attempts=max(1, self.attempts),
                              cause=self.error)

    def result(self):
        """The final amplitudes, re-raising the job's failure as a fresh
        per-job :class:`JobFailedError` (and refusing while the job is
        still in flight)."""
        if self.state == FAILED:
            raise self._failure() from self.error
        if self.state != DONE:
            raise QuESTError(
                f"Job {self.id}: result() before completion "
                f"(state={self.state!r}) — drive the server "
                "(step/run_until_idle) or await Service.wait")
        return self.amps

    def __repr__(self):
        return (f"Job(id={self.id}, tenant={self.tenant!r}, "
                f"priority={self.priority!r}, state={self.state!r})")


class _Bank:
    """One padded batch of same-fingerprint jobs moving through a
    WindowExecutor.  OPEN until its first window (jobs may still join);
    then RUNNING, possibly PREEMPTED (device state checkpointed and
    dropped, or just descheduled under ``pause``), and finally drained
    + finalized."""

    __slots__ = ("seq", "key", "jobs", "num_qubits", "is_density",
                 "measure", "priority", "qureg", "ex", "items", "B",
                 "started", "preempted", "paused", "cursor", "sfp",
                 "ckpt_dir", "group")

    def __init__(self, seq: int, key: tuple, num_qubits: int,
                 is_density: bool, measure: tuple,
                 group: Optional[Tuple[str, int]] = None):
        self.seq = seq
        self.key = key
        self.group = group  # quarantine-bisection cohort (job.bisect_group)
        self.jobs: List[Job] = []
        self.num_qubits = num_qubits
        self.is_density = is_density
        self.measure = measure
        self.priority = BATCH
        self.qureg = None
        self.ex: Optional[_resilience.WindowExecutor] = None
        self.items: Optional[list] = None
        self.B = 0
        self.started = False
        self.preempted = False
        self.paused = False
        self.cursor = 0
        self.sfp = ""
        self.ckpt_dir = ""

    def add(self, job: Job) -> None:
        self.jobs.append(job)
        if _CLASS_RANK[job.priority] < _CLASS_RANK[self.priority]:
            self.priority = job.priority

    @property
    def running(self) -> bool:
        return self.started and self.ex is not None \
            and not self.ex.done

    def min_vtime(self, tenants: Dict[str, Tenant]) -> float:
        return min(tenants[j.tenant].vtime for j in self.jobs)


def _env_int(var: str, default: int) -> int:
    raw = os.environ.get(var, "").strip()
    return int(raw) if raw else default


class _PlanStub:
    """The minimal register-shaped object ``fusion.aot_plan_info``
    needs: the prewarmer (§31) plans a bank's executor from analytic
    parameters — no amplitudes are ever allocated for a warm-up."""

    __slots__ = ("env", "num_qubits_in_state_vec", "_perm",
                 "batch_size", "dtype", "num_amps_total")

    def __init__(self, env: QuESTEnv, n: int, batch: int, dtype):
        self.env = env
        self.num_qubits_in_state_vec = n
        self._perm = None  # banks (re)start drains from canonical order
        self.batch_size = batch
        self.dtype = np.dtype(dtype)
        self.num_amps_total = 1 << n


def _job_bytes_per_device(num_qubits: int, env: QuESTEnv,
                          is_density: bool, batch: int = 1) -> int:
    """Analytic per-device footprint of ``batch`` elements of an
    ``num_qubits``-qubit register — the same ``B x 2 x 2^n x itemsize``
    model ``governor.register_bytes_per_device`` applies, computed from
    parameters so admission can price a job BEFORE any register
    exists."""
    from . import precision as P

    n = num_qubits * (2 if is_density else 1)
    amps = 1 << n
    total = batch * 2 * amps * np.dtype(P.real_dtype()).itemsize
    if env.mesh is not None and amps >= env.num_devices:
        return total // env.num_devices
    return total


class SimServer:
    """The synchronous multi-tenant scheduling core (see the module
    docstring for the execution model).  Drive it with :meth:`step`
    (one window of one bank) or :meth:`run_until_idle`; wrap it in
    :class:`Service` for an asyncio front end.

    Parameters default from the ``QT_SERVE_*`` environment knobs;
    explicit arguments win.  ``max_batch`` must be a power of two (the
    EnsembleScheduler bucket rule, bounding jit retraces per structure
    by the bucket count)."""

    def __init__(self, env: QuESTEnv, *, window: Optional[int] = None,
                 max_batch: Optional[int] = None,
                 max_pending: Optional[int] = None,
                 preempt: Optional[str] = None,
                 ckpt_dir: Optional[str] = None,
                 retries: Optional[int] = None,
                 quarantine: Optional[Tuple[int, float]] = None,
                 watchdog: Optional[int] = None,
                 faults: Optional[_resilience.FaultPlan] = None,
                 prewarm: Optional[bool] = None):
        self.env = env
        self.window = window if window is not None \
            else _env_int(_WINDOW_ENV, 16)
        self.max_batch = max_batch if max_batch is not None \
            else _env_int(_MAX_BATCH_ENV, 16)
        self.max_pending = max_pending if max_pending is not None \
            else _env_int(_MAX_PENDING_ENV, 1024)
        self.preempt = preempt if preempt is not None \
            else (os.environ.get(_PREEMPT_ENV, "").strip()
                  or "checkpoint")
        if self.window < 1:
            raise QuESTError(
                f"SimServer: window must be >= 1, got {self.window}")
        if self.max_batch < 1 or (self.max_batch & (self.max_batch - 1)):
            raise QuESTError(
                f"SimServer: max_batch must be a power of two, got "
                f"{self.max_batch}")
        if self.preempt not in _PREEMPT_MODES:
            raise QuESTError(
                f"SimServer: unknown preempt mode {self.preempt!r} "
                f"(expected one of {_PREEMPT_MODES})")
        root = ckpt_dir or os.environ.get(_CKPT_DIR_ENV, "").strip()
        self._own_ckpt_root = not root
        self._ckpt_root = root or tempfile.mkdtemp(prefix="qt_serve_")
        self.retries = retries if retries is not None \
            else _env_int(_RETRIES_ENV, 3)
        if quarantine is None:
            raw = os.environ.get(_QUARANTINE_ENV, "").strip() or "2:30"
            thr, _, secs = raw.partition(":")
            quarantine = (int(thr), float(secs or "30"))
        self._q_threshold = max(1, int(quarantine[0]))
        self._q_open_seconds = float(quarantine[1])
        self.watchdog = watchdog if watchdog is not None \
            else _env_int(_WATCHDOG_ENV, 8)
        self.faults = faults if faults is not None \
            else _resilience.FaultPlan.from_env()
        self._backoff_base = float(
            os.environ.get(_resilience._RETRY_BASE_ENV, "0.05"))
        self.tenants: Dict[str, Tenant] = {}
        self._buckets: Dict[tuple, List[Job]] = {}
        self._banks: List[_Bank] = []
        self._breakers: Dict[tuple, _Breaker] = {}
        self._next_job = 0
        self._next_bank = 0
        self._vclock = 0.0
        self._queued = 0
        self._closed = False
        self.completed = 0
        self._step_count = 0
        self._inject_bank_fault = False
        # the environment to heal back onto (degraded = env is not this)
        self._full_env = env
        # a declared shard/host loss (dist.guarded_dispatch) invalidates
        # the governor's per-device budget the moment it is announced —
        # before the ShardLossError even unwinds to _advance
        self._mesh_cb = lambda _event, _info: _governor.refresh_budget()
        _ptopo.add_mesh_listener(self._mesh_cb)
        self._serial = next(_SERVER_SEQ)
        self._flight_dir = os.environ.get(_FLIGHT_DIR_ENV, "").strip() \
            or os.path.join(self._ckpt_root, "flight")
        self.flight_dumps: List[str] = []
        self._http = None
        self._http_thread: Optional[threading.Thread] = None
        # §31 warm pool: a daemon prewarmer AOT-compiles (or disk-loads)
        # the executors for every observed bank fingerprint — on the
        # live mesh AND the next failover's shrunk mesh — off the
        # scheduling thread, so neither a fresh replica's first request
        # nor a failover's first degraded drain pays an XLA compile
        self.prewarm = bool(prewarm) if prewarm is not None \
            else bool(_env_int(_PREWARM_ENV, 0))
        self._warm_specs: Dict[tuple, dict] = {}  # dedup key -> spec
        self._warm_keys: set = set()              # specs warmed so far
        self._prewarm_q: List[dict] = []
        self._prewarm_pending = 0
        self._prewarm_lock = threading.Lock()
        self._prewarm_wake = threading.Condition(self._prewarm_lock)
        self._prewarm_thread: Optional[threading.Thread] = None
        _telemetry.set_gauge("serve_degraded", 0.0)

    # -- tenants ---------------------------------------------------------

    def register_tenant(self, name: str, *, weight: float = 1.0,
                        max_pending: int = 64,
                        max_bytes: Optional[int] = None) -> Tenant:
        """Create (or reconfigure) a tenant.  Unregistered tenant names
        are auto-created at first submit with default limits."""
        t = self.tenants.get(name)
        if t is None:
            t = Tenant(name, weight=weight, max_pending=max_pending,
                       max_bytes=max_bytes)
            t.vtime = self._vclock
            self.tenants[name] = t
        else:
            t.weight = float(weight)
            t.max_pending = int(max_pending)
            t.max_bytes = None if max_bytes is None else int(max_bytes)
        return t

    # -- admission -------------------------------------------------------

    def submit(self, gates: Sequence, *, num_qubits: int,
               tenant: str = "default", priority: str = BATCH,
               seed=None, measure: Sequence[int] = (),
               is_density_matrix: bool = False) -> Job:
        """Queue one circuit for execution; returns its :class:`Job`
        handle.  ``gates`` is a sequence of
        :class:`quest_tpu.circuit.Gate` with concrete numpy SoA
        matrices (the EnsembleScheduler submission format);
        ``measure`` optionally schedules qubit measurements (in order)
        after the last gate — part of the batching fingerprint, so only
        identically-measured circuits share a bank.  ``seed`` gives the
        element its measurement stream (default: the job id).

        Raises :class:`QuotaExceededError` instead of queueing beyond
        any limit — admission control IS the backpressure."""
        if self._closed:
            raise QuESTError("SimServer: submit after close()")
        if priority not in _PRIORITIES:
            raise QuESTError(
                f"SimServer.submit: unknown priority {priority!r} "
                f"(expected one of {_PRIORITIES})")
        gates = [g if isinstance(g, C.Gate) else C.Gate(tuple(g[0]), g[1])
                 for g in gates]
        for g in gates:
            if not isinstance(g.mat, np.ndarray):
                raise QuESTError(
                    "SimServer.submit: gate matrices must be concrete "
                    "numpy arrays (traced values cannot be stacked "
                    "across submissions)")
        t = self.tenants.get(tenant)
        if t is None:
            t = self.register_tenant(tenant)
        if self._queued >= self.max_pending:
            self._reject(t, "backpressure", self.max_pending,
                         self._queued)
        if t.inflight >= t.max_pending:
            self._reject(t, "pending", t.max_pending, t.inflight)
        nbytes = _job_bytes_per_device(int(num_qubits), self.env,
                                       is_density_matrix)
        if t.max_bytes is not None \
                and t.inflight_bytes + nbytes > t.max_bytes:
            self._reject(t, "bytes", t.max_bytes,
                         t.inflight_bytes + nbytes)
        budget = _governor.budget_bytes()
        if _governor.enabled() and budget is not None \
                and nbytes > budget:
            self._reject(t, "memory", budget, nbytes)
        measure = tuple(int(m) for m in measure)
        for qb in measure:
            if not 0 <= qb < int(num_qubits):
                raise QuESTError(
                    f"SimServer.submit: measured qubit {qb} out of "
                    f"range for {num_qubits} qubits")
        key = (_batch._structure_fingerprint(
            gates, int(num_qubits), bool(is_density_matrix)), measure)
        br = self._breakers.get((t.name, key))
        if br is not None and not br.admits():
            self._reject(t, "quarantine", self._q_threshold, br.failures)
        jid = self._next_job
        self._next_job += 1
        job = Job(jid, t.name, gates, int(num_qubits), priority,
                  seed, measure, nbytes)
        self._buckets.setdefault(key, []).append(job)
        # an idle tenant's vtime catches up to the scheduler clock so
        # idle periods bank no fair-share credit
        t.vtime = max(t.vtime, self._vclock)
        t.inflight += 1
        t.inflight_bytes += nbytes
        t.submitted += 1
        self._queued += 1
        job.trace_id = f"s{self._serial}-j{jid}"
        _telemetry.trace_begin(job.trace_id, "job", tenant=t.name,
                               priority=priority,
                               qubits=int(num_qubits))
        _telemetry.trace_point(job.trace_id, "serve.admit",
                               queue_depth=self._queued)
        _telemetry.inc("serve_jobs_submitted_total", tenant=t.name)
        _telemetry.set_gauge("serve_queue_depth", self._queued)
        return job

    def _reject(self, t: Tenant, kind: str, limit, value) -> None:
        _telemetry.inc("serve_jobs_rejected_total", tenant=t.name,
                       kind=kind)
        _telemetry.flight_event("admission_rejected", tenant=t.name,
                                reason=kind, limit=limit, value=value)
        raise QuotaExceededError(
            f"SimServer.submit: tenant {t.name!r} over {kind} limit "
            f"({value} > {limit}) — back off and retry",
            tenant=t.name, kind=kind, limit=float(limit),
            value=float(value))

    # -- continuous batching: bucket -> bank coalescing ------------------

    def _form_banks(self) -> None:
        """Move waiting jobs into banks.  A bucket's newest bank stays
        OPEN (absorbing arrivals) until its first window executes —
        this is the continuous-batching admission point: work arriving
        while other banks execute coalesces here instead of waiting for
        a global drain.  Jobs backing off after a dissolve
        (``not_before`` in the future) wait; jobs in a bisection cohort
        only share a bank with their cohort, capped at its size."""
        now = time.monotonic()
        for key, waiting in self._buckets.items():
            if not waiting:
                continue
            taken: List[Job] = []
            for job in waiting:
                if job.not_before > now:
                    continue
                group = job.bisect_group
                cap = group[1] if group is not None else self.max_batch
                bank = next((b for b in self._banks
                             if b.key == key and b.group == group
                             and not b.started and len(b.jobs) < cap),
                            None)
                if bank is None:
                    sfp, measure = key
                    bank = _Bank(self._next_bank, key,
                                 num_qubits=job.num_qubits,
                                 is_density=bool(sfp[0][2]),
                                 measure=measure, group=group)
                    self._next_bank += 1
                    self._banks.append(bank)
                bank.add(job)
                taken.append(job)
            for job in taken:
                waiting.remove(job)

    def _start(self, bank: _Bank) -> None:
        """Close an open bank: pad to a power-of-two batch, build the
        fused bank program (shared-matrix collapse / per-element
        stacking), create the governed register, and arm its
        WindowExecutor."""
        jobs = bank.jobs
        real = len(jobs)
        cap = bank.group[1] if bank.group is not None else self.max_batch
        bank.B = _batch._bucket_size(real, cap)
        padded = jobs + [jobs[-1]] * (bank.B - real)
        seeds = [j.seed if j.seed is not None else j.id for j in padded]
        q = _batch.createBatchedQureg(
            bank.num_qubits, self.env, bank.B,
            is_density_matrix=bank.is_density, seeds=seeds)
        bank.items = _batch.bank_gate_items(
            [j.gates for j in padded], bank.num_qubits,
            bank.is_density, qureg=q)
        from . import api as _api

        _telemetry.inc_key(_api._K_UNITARY,
                           bank.B * len(jobs[0].gates))
        bank.sfp = _resilience.circuit_fingerprint(
            bank.items, q.num_qubits_in_state_vec, self.window)
        bank.ckpt_dir = os.path.join(self._ckpt_root,
                                     f"bank-{bank.seq}")
        bank.qureg = q
        bank.ex = _resilience.WindowExecutor(
            q, bank.items, every=self.window, fingerprint=bank.sfp)
        bank.started = True
        now = time.perf_counter()
        for j in jobs:
            j.state = RUNNING
            j.t_start = now
            j.attempts += 1
            self._queued -= 1
            _telemetry.observe("serve_queue_wait_seconds",
                               now - j.t_submit, tenant=j.tenant)
            _telemetry.trace_point(j.trace_id, "serve.bank_join",
                                   bank=bank.seq, attempt=j.attempts,
                                   batch=bank.B)
        _telemetry.inc("serve_banks_total")
        _telemetry.set_gauge("serve_queue_depth", self._queued)
        self._publish_occupancy(bank)
        self._refresh_watermark()
        self._warm_variants(bank)

    def _publish_occupancy(self, bank: _Bank) -> None:
        occ = _batch.bank_occupancy(bank.qureg, real=len(bank.jobs))
        _telemetry.set_gauge("serve_bank_occupancy", occ["occupancy"])
        _telemetry.observe("ensemble_bucket_occupancy",
                           occ["occupancy"])
        per_tenant: Dict[str, int] = {}
        for j in bank.jobs:
            per_tenant[j.tenant] = per_tenant.get(j.tenant, 0) + 1
        for name, count in per_tenant.items():
            _telemetry.set_gauge("bank_occupancy", count / bank.B,
                                 tenant=name)

    # -- preemption protocol ---------------------------------------------

    def _preempt(self, bank: _Bank) -> None:
        """Preempt a mid-flight bank at its current window boundary.
        ``checkpoint`` mode commits a resilience generation (raw
        permuted amplitudes + live perm + per-element key/shot bank)
        and DROPS the device state, freeing its governed footprint;
        ``pause`` mode merely deschedules (state stays resident)."""
        if self.preempt == "off" or not bank.running or bank.paused:
            return
        _telemetry.inc("preemptions_total", mode=self.preempt)
        if _telemetry.enabled():
            for j in bank.jobs:
                _telemetry.trace_point(j.trace_id, "serve.preempt",
                                       bank=bank.seq,
                                       mode=self.preempt)
        if self.preempt == "pause":
            bank.paused = True
            return
        with _telemetry.span("serve.preempt", bank=bank.seq):
            bank.ex.checkpoint(bank.ckpt_dir)
        bank.cursor = bank.ex.cursor
        _governor.release(bank.qureg)
        bank.qureg = None
        bank.ex = None
        bank.preempted = True

    def _resume(self, bank: _Bank) -> None:
        """Reload a checkpoint-preempted bank and continue from its
        saved cursor — the other half of the bit-identical preemption
        contract."""
        with _telemetry.span("serve.resume", bank=bank.seq):
            loaded = _resilience.load_latest(bank.ckpt_dir, self.env)
        if loaded is None:
            raise QuESTError(
                f"SimServer: preempted bank {bank.seq} has no loadable "
                f"generation under {bank.ckpt_dir}")
        q, meta = loaded
        cursor = int(meta.get("cursor", 0))
        if cursor != bank.cursor:
            raise QuESTError(
                f"SimServer: bank {bank.seq} checkpoint cursor "
                f"{cursor} != preemption cursor {bank.cursor}")
        bank.qureg = q
        bank.ex = _resilience.WindowExecutor(
            q, bank.items, every=self.window, start=cursor,
            fingerprint=bank.sfp)
        bank.preempted = False
        _telemetry.inc("serve_resumes_total")
        if _telemetry.enabled():
            for j in bank.jobs:
                _telemetry.trace_point(j.trace_id, "serve.resume",
                                       bank=bank.seq, cursor=cursor)

    # -- scheduling ------------------------------------------------------

    def _runnable(self) -> List[_Bank]:
        return [b for b in self._banks
                if b.jobs and (not b.started or b.preempted
                               or b.paused or b.running)]

    def _pick(self) -> Optional[_Bank]:
        """Strict priority class, then weighted fair (smallest owning
        vtime), then bank age."""
        runnable = self._runnable()
        if not runnable:
            return None
        return min(runnable, key=lambda b: (
            _CLASS_RANK[b.priority], b.min_vtime(self.tenants), b.seq))

    def _charge(self, bank: _Bank) -> None:
        per_tenant: Dict[str, int] = {}
        for j in bank.jobs:
            per_tenant[j.tenant] = per_tenant.get(j.tenant, 0) + 1
        for name, count in per_tenant.items():
            t = self.tenants[name]
            t.vtime += (count / len(bank.jobs)) / t.weight
            self._vclock = max(self._vclock, t.vtime)

    def step(self) -> bool:
        """One scheduling quantum: coalesce arrivals into banks, pick
        the next bank under the policy, preempt lower-priority work if
        the pick is interactive, and advance the pick by ONE fusion
        window (finalizing it when the stream ends).  Returns False
        when nothing is runnable (the idle signal for drivers); jobs
        merely backing off still count as runnable — the step waits out
        the earliest ``not_before`` instead of reporting idle."""
        if self._closed:
            return False
        step_idx = self._step_count
        self._step_count += 1
        plan = self.faults
        installed = False
        if plan is not None:
            kind = plan.take_serve_fault(step_idx)
            if kind == "heal":
                self.heal()
            elif kind in ("host_loss", "shard_loss"):
                # a host loss names its observed shard (highest index);
                # a bare shard loss is anonymous — sub-host shrink
                shard = self.env.num_devices - 1 \
                    if kind == "host_loss" else None
                self._failover(_dist.ShardLossError(
                    f"injected {kind} at serve step {step_idx}",
                    op="serve", shard=shard))
            elif kind == "bank_fault":
                self._inject_bank_fault = True
            # io / oom events flow through the shared slots retry_io and
            # governor.oom_net consult while this step runs
            plan.arm_oom(step_idx)
            if _resilience._ACTIVE_FAULTS[0] is None:
                _resilience._ACTIVE_FAULTS[0] = plan
                installed = True
        try:
            self._form_banks()
            bank = self._pick()
            if bank is None:
                gates = [j.not_before for w in self._buckets.values()
                         for j in w]
                if not gates:
                    return False
                # everything queued is backing off: wait (bounded) for
                # the earliest retry gate rather than going idle
                delay = min(gates) - time.monotonic()
                if delay > 0:
                    time.sleep(min(delay, 0.05))
                return True
            if bank.priority == INTERACTIVE and self.preempt != "off":
                for other in self._banks:
                    if other is not bank and other.priority == BATCH:
                        try:
                            self._preempt(other)
                        except (QuESTError, OSError, TimeoutError) as e:
                            # checkpoint IO died mid-preempt: the device
                            # state is suspect — dissolve and retry
                            self._dissolve(other, e, reason="transient")
            self._advance(bank)
            return True
        finally:
            if installed:
                _resilience._ACTIVE_FAULTS[0] = None

    def _advance(self, bank: _Bank) -> None:
        try:
            if self._inject_bank_fault:
                self._inject_bank_fault = False
                raise TimeoutError(
                    f"injected bank fault (chaos) on bank {bank.seq}")
            if not bank.started:
                self._start(bank)
            elif bank.preempted:
                self._resume(bank)
            bank.paused = False
            w = bank.ex.window
            t0 = time.perf_counter()
            with _telemetry.span("serve.window", bank=bank.seq,
                                 window=w):
                bank.ex.step()
            if _telemetry.enabled():
                dur = time.perf_counter() - t0
                for j in bank.jobs:
                    _telemetry.trace_add(j.trace_id, "serve.window",
                                         t0=t0, dur=dur,
                                         bank=bank.seq, window=w)
            _telemetry.inc("serve_windows_total")
            self._charge(bank)
            self._maybe_poison(bank)
            if bank.ex.done or self._watchdog_due(bank):
                bank.ex.check_health()
            if bank.ex.done:
                self._finalize(bank)
        except _dist.ShardLossError as e:
            # infrastructure loss: fail over EVERYTHING onto the shrunk
            # mesh; this bank's jobs retry or resume there
            self._failover(e)
        except _resilience.NumericalHealthError as e:
            # poisoned amplitudes: bisect toward the culprit (must
            # precede the QuESTError arm — it is a subclass)
            self._quarantine_or_bisect(bank, e)
        except _governor.MemoryAdmissionError as e:
            # the bank does not fit next to the resident set: preempt a
            # lower-priority resident bank to checkpoint and retry the
            # start on a later step; with nothing left to evict the
            # refusal is final
            _telemetry.inc("serve_admission_stalls_total")
            if not self._preempt_for_memory(bank):
                self._fail(bank, e)
        # qlint: allow(oom-swallow): classification only — the governor's oom_net already spent its evict-and-retry before this surfaced; serve routes the verdict to culprit bisection, it does not re-attempt allocation
        except (QuESTError, OSError, TimeoutError) as e:
            # transient (exhausted IO retries, exchange timeout, injected
            # bank fault): dissolve — jobs retry in fresh banks against
            # their budgets.  A repeated-OOM verdict bisects instead.
            if _governor._is_oom(e):
                self._quarantine_or_bisect(bank, e)
            else:
                self._dissolve(bank, e, reason="transient")
        # qlint: allow(oom-swallow): same classification-only inspection as above — post-oom_net verdict feeds bisection, never a retry of the allocation
        except RuntimeError as e:
            # the governor's OOM net retries once and re-raises — a
            # bank that STILL OOMs is treated as poison and bisected;
            # any other RuntimeError is a real bug: propagate
            if _governor._is_oom(e):
                self._quarantine_or_bisect(bank, e)
            else:
                raise

    def _watchdog_due(self, bank: _Bank) -> bool:
        """Health-check cadence: every ``watchdog``-th executed window
        of a bank (0 disables the periodic check; bank completion is
        always checked in _advance)."""
        if self.watchdog <= 0 or bank.ex is None:
            return False
        return bank.ex.window % self.watchdog == 0

    def _maybe_poison(self, bank: _Bank) -> None:
        """Chaos injection: NaN-poison the batch element of any resident
        job marked ``poison_job@J`` in the fault plan.  Persistent by
        design — the job re-poisons on every retry, so the bisection
        converges on it instead of exonerating it."""
        plan = self.faults
        if plan is None or not plan.poisoned_jobs or bank.qureg is None:
            return
        for i, j in enumerate(bank.jobs):
            if not plan.poisoned(j.id):
                continue
            q = bank.qureg
            amps = q._amps_raw()
            amps = amps.at[i, 0, amps.shape[-1] - 1].set(np.nan)
            q._set_amps_permuted(amps, q._perm)
            plan.log.append(f"poison_job@{j.id}")

    def _preempt_for_memory(self, needy: _Bank) -> bool:
        """Free governed bytes for ``needy`` by checkpoint-preempting
        one resident batch-class bank.  Returns False when nothing is
        evictable (pause mode keeps state resident, so it cannot
        help)."""
        if self.preempt != "checkpoint":
            return False
        for other in self._banks:
            if other is not needy and other.qureg is not None \
                    and other.started and other.priority == BATCH \
                    and other.running:
                self._preempt(other)
                return True
        return False

    # -- fault tolerance: dissolve / quarantine / failover / heal --------

    def _drop_bank(self, bank: _Bank) -> None:
        """Release a bank's device state and remove it from scheduling
        (jobs are the caller's responsibility)."""
        if bank.qureg is not None:
            _governor.release(bank.qureg)
        bank.qureg = None
        bank.ex = None
        if bank in self._banks:
            self._banks.remove(bank)
        if bank.ckpt_dir and os.path.isdir(bank.ckpt_dir):
            shutil.rmtree(bank.ckpt_dir, ignore_errors=True)

    def _fail_job(self, job: Job, err: BaseException, *,
                  quarantined: bool = False) -> None:
        """Terminal per-job failure: records the cause for
        :meth:`Job.result`'s JobFailedError and settles accounting."""
        if job.t_start is None and job.state == QUEUED:
            self._queued -= 1
        job.state = FAILED
        job.error = err
        job.errors.append(
            f"attempt {max(1, job.attempts)}: "
            f"{type(err).__name__}: {err}")
        job.t_done = time.perf_counter()
        t = self.tenants[job.tenant]
        t.inflight -= 1
        t.inflight_bytes -= job.bytes
        _telemetry.trace_point(job.trace_id, "serve.failed",
                               error=type(err).__name__,
                               attempts=max(1, job.attempts),
                               quarantined=quarantined)
        _telemetry.trace_end(job.trace_id, status="failed")
        _telemetry.inc("serve_jobs_failed_total", tenant=job.tenant)
        if quarantined:
            _telemetry.inc("serve_jobs_quarantined_total",
                           tenant=job.tenant)

    def _dissolve(self, bank: _Bank, err: BaseException, *, reason: str,
                  charge: bool = True,
                  requeue: Optional[List[Job]] = None) -> None:
        """Failure isolation: tear a faulted bank down WITHOUT failing
        its jobs.  Members return to their bucket and re-bucket into
        fresh banks; a retried job re-runs from gate 0 under its own
        measurement seed, so completing under retry is bit-identical to
        a fault-free run.  ``charge=True`` burns one unit of each job's
        retry budget and gates its return behind decorrelated-jitter
        backoff; ``charge=False`` (failover, poison bisection) requeues
        immediately and free of charge — the fault was infrastructure's
        or a bank-mate's, not the job's.  Jobs past their budget exhaust
        to FAILED with the full per-attempt error chain."""
        jobs = requeue if requeue is not None else list(bank.jobs)
        _telemetry.inc("serve_bank_retries_total", reason=reason)
        _telemetry.flight_event("bank_dissolved", bank=bank.seq,
                                reason=reason, jobs=len(jobs),
                                error=f"{type(err).__name__}: {err}")
        now = time.monotonic()
        for job in jobs:
            started = job.t_start is not None
            if charge and job.attempts > self.retries:
                self._fail_job(job, err)  # records its attempt line
                continue
            job.errors.append(
                f"attempt {max(1, job.attempts)}: "
                f"{type(err).__name__}: {err}")
            job.state = QUEUED
            job.error = err
            job.t_start = None
            if charge:
                job.backoff = _resilience.backoff_delay(
                    self._backoff_base, job.backoff)
                job.not_before = now + job.backoff
            if started:
                self._queued += 1
            self._buckets.setdefault(bank.key, []).append(job)
            _telemetry.trace_point(
                job.trace_id, "serve.retry", reason=reason,
                attempt=job.attempts,
                backoff=round(job.backoff or 0.0, 4))
        self._drop_bank(bank)
        _telemetry.set_gauge("serve_queue_depth", self._queued)

    def _quarantine(self, job: Job, bank: _Bank,
                    err: BaseException) -> None:
        """Terminal poison verdict: fail the job and charge its
        (tenant, structure) circuit breaker."""
        key = (job.tenant, bank.key)
        br = self._breakers.get(key)
        if br is None:
            br = self._breakers[key] = _Breaker(self._q_threshold,
                                                self._q_open_seconds)
        br.record_failure()
        _telemetry.trace_point(job.trace_id, "serve.quarantine",
                               breaker=br.state,
                               failures=br.failures)
        self._fail_job(job, err, quarantined=True)
        _telemetry.set_gauge("serve_queue_depth", self._queued)
        self._flight_dump("quarantine", tenant=job.tenant, job=job.id,
                          trace_id=job.trace_id, breaker=br.state,
                          error=f"{type(err).__name__}: {err}")

    def _quarantine_or_bisect(self, bank: _Bank,
                              err: BaseException) -> None:
        """Poison-job containment.  A singleton bank IS the culprit:
        quarantine it.  A multi-job bank re-runs its members in smaller
        cohorts — the watchdog's worst-element attribution sends the
        suspect straight to a singleton (one extra round), an
        unattributed verdict (repeated OOM) halves the bank
        (log2(B) rounds) — with bank-mates requeued free of charge, so
        innocents always complete."""
        jobs = list(bank.jobs)
        _telemetry.flight_event(
            "bisect", bank=bank.seq, jobs=len(jobs),
            attributed=getattr(err, "element", None) is not None,
            error=f"{type(err).__name__}: {err}")
        if _governor._is_oom(err):
            self._flight_dump("oom_bisect", bank=bank.seq,
                              jobs=len(jobs))
        if len(jobs) == 1:
            self._quarantine(jobs[0], bank, err)
            self._dissolve(bank, err, reason="poison", charge=False,
                           requeue=[])
            return
        element = getattr(err, "element", None)
        if element is not None and 0 <= int(element) < max(bank.B, 1):
            # element i of the padded batch belongs to job i (padding
            # duplicates the LAST job, so clamp)
            culprit = jobs[min(int(element), len(jobs) - 1)]
            culprit.bisect_group = (f"bisect-{bank.seq}-culprit", 1)
            for j in jobs:
                if j is not culprit:
                    j.bisect_group = None
        else:
            half = (len(jobs) + 1) // 2
            cap = 1
            while cap < half:
                cap <<= 1
            for idx, j in enumerate(jobs):
                j.bisect_group = (f"bisect-{bank.seq}-{idx // half}",
                                  cap)
        self._dissolve(bank, err, reason="poison", charge=False)

    def _failover(self, err: BaseException) -> None:
        """Elastic degraded-mode failover: shrink the serving mesh and
        keep EVERY queued and running job.  Running banks with a
        committed generation roll back to it (the elastic restore
        reshards onto the shrunk mesh at resume — the §19/§25 path);
        banks without one dissolve free of charge and retry.  Queued
        work is untouched; admission re-prices automatically because
        _job_bytes_per_device reads the live env."""
        t0 = time.perf_counter()
        old_n = self.env.num_devices
        if old_n <= 1:
            # nowhere left to shrink: treat as transient infrastructure
            for bank in [b for b in self._banks if b.started]:
                self._dissolve(bank, err, reason="failover",
                               charge=False)
            return
        new_n = old_n // 2
        excl = None
        dead_host = None
        topo = getattr(self.env, "topology", None)
        shard = getattr(err, "shard", None)
        if shard is not None and topo is not None and topo.hosts > 1:
            # host-aware exclusion: drop the dead host's whole device
            # range so the survivors are intact hosts (2x4 -> 1x4)
            dead_host = topo.host_of(int(shard))
            excl = list(topo.host_range(dead_host))
            if old_n - len(excl) < new_n:
                excl = excl[:old_n - new_n]
        new_env = shrink_env(self.env, new_n, exclude_indices=excl)
        for bank in [b for b in self._banks if b.started]:
            if bank.preempted:
                continue  # its generation restores elastically on resume
            cursor = _resilience.latest_committed_cursor(bank.ckpt_dir) \
                if bank.ckpt_dir else None
            if cursor is not None and self.preempt == "checkpoint":
                # roll back to the committed generation: resume reloads
                # it onto whatever mesh is then live
                if bank.qureg is not None:
                    _governor.release(bank.qureg)
                bank.qureg = None
                bank.ex = None
                bank.cursor = int(cursor)
                bank.preempted = True
                bank.paused = False
                _telemetry.inc("serve_bank_retries_total",
                               reason="failover")
            else:
                self._dissolve(bank, err, reason="failover",
                               charge=False)
        self.env = new_env
        # keep the warm pool one failover ahead: the executors for THIS
        # mesh were prewarmed at bank start; queue the next shrink level
        # so a second loss stays compile-free too
        if self.prewarm:
            with self._prewarm_lock:
                known = list(self._warm_specs.values())
            for spec in known:
                nxt = dict(spec)
                nxt["ndev"] = max(1, new_n // 2)
                self._enqueue_prewarm(nxt)
        _ptopo.notify_mesh_event("serve_failover", from_devices=old_n,
                                 to_devices=new_n, dead_host=dead_host)
        _resilience.record_degradation(
            f"serve_failover_{old_n}to{new_n}",
            f"{err}; serving degraded onto {new_n} devices"
            + (f" (host {dead_host} excluded)"
               if dead_host is not None else ""))
        _telemetry.inc("serve_failovers_total")
        _telemetry.set_gauge("serve_degraded", 1.0)
        _telemetry.set_gauge("serve_failover_mttr_seconds",
                             time.perf_counter() - t0)
        self._flight_dump("failover", from_devices=old_n,
                          to_devices=new_n, dead_host=dead_host,
                          error=f"{type(err).__name__}: {err}")

    def heal(self) -> bool:
        """Re-expand onto the recovered full mesh — the operator signal
        after infrastructure comes back.  Resident banks drain to their
        current checkpoint boundary (a committed generation on the
        DEGRADED mesh), the serving env swaps back to the full mesh, and
        every bank resumes through the mesh-portable elastic restore —
        checkpoint REGROW, with serving as its first consumer.
        Subsequent submissions are priced and run on the full mesh.
        Returns False when not degraded."""
        if self._closed or self.env is self._full_env:
            return False
        t0 = time.perf_counter()
        for bank in [b for b in self._banks if b.started]:
            if bank.qureg is None:
                continue  # already at a checkpoint boundary
            try:
                with _telemetry.span("serve.heal_drain", bank=bank.seq):
                    bank.ex.checkpoint(bank.ckpt_dir)
                bank.cursor = bank.ex.cursor
                _governor.release(bank.qureg)
                bank.qureg = None
                bank.ex = None
                bank.preempted = True
                bank.paused = False
            except (QuESTError, OSError, TimeoutError) as e:
                # drain failed: this bank retries from scratch on the
                # healed mesh instead of blocking the heal
                self._dissolve(bank, e, reason="transient",
                               charge=False)
        import dataclasses

        healed = self._full_env
        # re-derive the topology through the declared spec: healing
        # restores the operator's arrangement (1x4 back to 2x4)
        healed = dataclasses.replace(
            healed, topology=_ptopo.grow(
                getattr(self.env, "topology", None),
                healed.num_devices))
        self.env = self._full_env = healed
        _ptopo.notify_mesh_event("serve_heal",
                                 to_devices=healed.num_devices)
        _telemetry.inc("serve_heals_total")
        _telemetry.set_gauge("serve_degraded", 0.0)
        _telemetry.set_gauge("serve_heal_seconds",
                             time.perf_counter() - t0)
        return True

    def _finalize(self, bank: _Bank) -> None:
        """Drain the finished bank: run the measurement schedule
        (per-element key streams), hand each job its canonical
        amplitudes + outcomes + final key state, and release the
        register."""
        q = bank.qureg
        for qb in bank.measure:
            outs, probs = _batch.measureBatched(q, qb)
            for i, job in enumerate(bank.jobs):
                job.outcomes.append((int(outs[i]), float(probs[i])))
        amps = np.asarray(q.amps)
        keys = q.key_state()
        now = time.perf_counter()
        for i, job in enumerate(bank.jobs):
            job.amps = amps[i]
            job.key_state = {"key": keys["keys"][i],
                             "counter": keys["counters"][i]}
            job.state = DONE
            job.t_done = now
            job.bisect_group = None
            t = self.tenants[job.tenant]
            t.inflight -= 1
            t.inflight_bytes -= job.bytes
            t.completed += 1
            self.completed += 1
            # a completed probe closes its (tenant, structure) breaker
            br = self._breakers.get((job.tenant, bank.key))
            if br is not None:
                br.record_success()
            _telemetry.trace_point(job.trace_id, "serve.complete",
                                   outcomes=len(job.outcomes),
                                   attempts=job.attempts)
            _telemetry.trace_end(job.trace_id, status="done")
            _telemetry.inc("serve_jobs_completed_total",
                           tenant=job.tenant)
            _telemetry.observe("serve_job_seconds", now - job.t_submit,
                               tenant=job.tenant)
        self._publish_occupancy(bank)
        self._refresh_watermark()
        self._banks.remove(bank)
        _governor.release(q)
        bank.qureg = None
        bank.ex = None
        if bank.ckpt_dir and os.path.isdir(bank.ckpt_dir):
            shutil.rmtree(bank.ckpt_dir, ignore_errors=True)

    def _fail(self, bank: _Bank, err: BaseException) -> None:
        """Terminal bank failure (memory refusal with nothing left to
        evict): every member exhausts to FAILED — each wrapped per-job
        by Job.result's JobFailedError, never a shared raise."""
        _telemetry.flight_event("executor_failure", bank=bank.seq,
                                jobs=len(bank.jobs),
                                error=f"{type(err).__name__}: {err}")
        for job in bank.jobs:
            self._fail_job(job, err)
        self._drop_bank(bank)
        _telemetry.set_gauge("serve_queue_depth", self._queued)
        self._flight_dump("executor_failure", bank=bank.seq,
                          error=f"{type(err).__name__}: {err}")

    # -- observability front door ----------------------------------------

    def _refresh_watermark(self) -> None:
        """Refresh the ``device_memory_watermark_bytes{device}`` gauges
        at a bank boundary (start/finalize) so HBM pressure in /metrics
        tracks the resident set, not just drains."""
        if not _telemetry.enabled():
            return
        from .utils import profiling as _prof

        _prof.memory_watermark()

    def _flight_dump(self, reason: str, **context):
        """Dump the telemetry flight recorder for one serve incident.
        Best-effort: a dump failure is counted
        (``flight_dump_errors_total``), never raised — the incident
        handler this rides on must still run.  Returns the written path
        (also appended to :attr:`flight_dumps`) or None."""
        if not _telemetry.enabled():
            return None
        path = os.path.join(
            self._flight_dir,
            f"flight_s{self._serial}_{len(self.flight_dumps)}"
            f"_{reason}.json")
        try:
            out = _telemetry.dump_flight(path, reason=reason,
                                         server=self._serial, **context)
        except OSError:
            _telemetry.inc("flight_dump_errors_total", reason=reason)
            return None
        if out:
            self.flight_dumps.append(out)
        return out

    def tracez(self, job=None) -> Optional[dict]:
        """The reconstructed span tree of one job — the server's view
        over ``telemetry.tracez``.  ``job`` may be a :class:`Job`
        handle, a job id (mapped through this server's trace-id
        namespace), or a raw trace-id string; None returns the index of
        every held trace.  Unknown ids return None."""
        if job is None:
            return _telemetry.tracez(None)
        if isinstance(job, Job):
            tid = job.trace_id
        elif isinstance(job, int):
            tid = f"s{self._serial}-j{job}"
        else:
            tid = str(job)
        return _telemetry.tracez(tid)

    # -- warm pool (§31) -------------------------------------------------

    def _warm_variants(self, bank) -> None:
        """Queue this bank's executor family for AOT prewarm: the live
        mesh AND the half-mesh the next failover would shrink onto, so
        neither a fresh replica's first request nor a failover's first
        degraded drain pays an XLA compile."""
        if not self.prewarm or bank.qureg is None:
            return
        q = bank.qureg
        ndev = self.env.num_devices
        for nd in dict.fromkeys((ndev, max(1, ndev // 2))):
            self._enqueue_prewarm({
                "v": 1, "items": list(bank.items), "sfp": bank.sfp,
                "n": q.num_qubits_in_state_vec, "batch": bank.B,
                "dtype": str(np.dtype(q.dtype)), "ndev": int(nd),
            })

    def _enqueue_prewarm(self, spec: dict) -> bool:
        """Deduplicated enqueue onto the prewarmer thread (started
        lazily — a server that never sees QT_SERVE_PREWARM work never
        owns a thread).  Returns True when the spec was new."""
        key = (spec.get("sfp"), int(spec["n"]), int(spec["batch"]),
               str(spec["dtype"]), int(spec["ndev"]))
        with self._prewarm_lock:
            if key in self._warm_specs or self._closed:
                return False
            self._warm_specs[key] = spec
            self._prewarm_q.append((key, spec))
            self._prewarm_pending += 1
            _telemetry.set_gauge("serve_prewarm_backlog",
                                 float(self._prewarm_pending))
            if self._prewarm_thread is None:
                self._prewarm_thread = threading.Thread(
                    target=self._prewarm_loop, name="qt-serve-prewarm",
                    daemon=True)
                self._prewarm_thread.start()
            self._prewarm_wake.notify_all()
        return True

    def _prewarm_loop(self) -> None:
        while True:
            with self._prewarm_lock:
                while not self._prewarm_q and not self._closed:
                    self._prewarm_wake.wait(0.1)
                if self._closed:
                    return
                key, spec = self._prewarm_q.pop(0)
            try:
                status = self._prewarm_one(spec)
            # qlint: allow(broad-except): a failed warm-up must never hurt the serving thread — the executor just compiles lazily at first dispatch instead
            except Exception:
                status = "error"
            with self._prewarm_lock:
                self._prewarm_pending -= 1
                if status in ("compiled", "hit", "present"):
                    self._warm_keys.add(key)
                _telemetry.set_gauge("serve_prewarm_backlog",
                                     float(self._prewarm_pending))
                _telemetry.set_gauge("serve_warm_pool_depth",
                                     float(len(self._warm_keys)))
                self._prewarm_wake.notify_all()
            _telemetry.inc("serve_prewarm_total", status=status)

    def _prewarm_one(self, spec: dict) -> str:
        """Plan and AOT-materialize one bank spec's window executors —
        exactly the window sequence a WindowExecutor would dispatch
        (same checkpoint boundaries, optimizer suppressed, the live
        permutation threaded window to window), so the live drains hit
        what this thread warmed."""
        from . import aotcache as _aotcache
        from . import fusion as F
        from . import optimizer as _opt

        if not _aotcache.enabled():
            return "disabled"
        ndev = int(spec["ndev"])
        env = self.env
        if ndev != env.num_devices:
            if (env.mesh is None or ndev < 1
                    or env.num_devices % ndev):
                return "skipped"
            env = shrink_env(env, ndev)
        stub = _PlanStub(env, int(spec["n"]), int(spec["batch"]),
                         spec["dtype"])
        items = list(spec["items"])
        bounds = C.plan_checkpoint_boundaries(len(items), self.window)
        statuses = set()
        cursor = 0
        with _opt.suppressed():
            for end in bounds:
                window_items = items[cursor:end]
                cursor = end
                info = F.aot_plan_info(stub, list(window_items))
                if info is None:
                    continue
                runner = F._plan_runner(
                    info["nloc"], info["program"], info["mesh"],
                    info["precision"], info["exchange_key"],
                    info["batch_flag"])
                if not hasattr(runner, "prewarm"):
                    return "disabled"
                amps = _aotcache.amps_struct(
                    stub.num_amps_total, stub.batch_size, stub.dtype,
                    info["mesh"])
                probs = tuple(0.5 for _ in range(info["nprobs"]))
                statuses.add(runner.prewarm(amps, info["arrays"], probs))
                fp = info["final_perm"]
                if (info["nsh"] and fp is not None
                        and list(fp) != list(range(stub.num_qubits_in_state_vec))):
                    stub._perm = tuple(fp)
                else:
                    stub._perm = None
        if not statuses:
            return "empty"
        for s in ("compiled", "hit", "present"):
            if s in statuses:
                return s
        return statuses.pop()

    def prewarm_join(self, timeout: float = 60.0) -> bool:
        """Block until the prewarm queue drains (replica boot, tests).
        Returns False on timeout."""
        deadline = time.perf_counter() + float(timeout)
        with self._prewarm_lock:
            while self._prewarm_pending > 0:
                left = deadline - time.perf_counter()
                if left <= 0:
                    return False
                self._prewarm_wake.wait(min(left, 0.1))
        return True

    def export_warmset(self) -> List[dict]:
        """The observed hot fingerprint set as picklable specs.  Ship
        to a fresh replica's :meth:`warm_from` so it boots hot: with a
        shared ``QT_AOT_CACHE`` volume the executables travel as disk
        hits; without one the replica AOT-compiles off-thread before
        its first request instead of during it."""
        with self._prewarm_lock:
            return [dict(s) for s in self._warm_specs.values()]

    def warm_from(self, warmset: Sequence[dict]) -> int:
        """Adopt another replica's exported warm set; every spec is
        queued for prewarm against THIS server's mesh family (a spec
        from a bigger mesh warms our live size instead).  Returns the
        number of new specs queued."""
        count = 0
        for spec in warmset:
            spec = dict(spec)
            if int(spec.get("ndev", 0)) > self.env.num_devices \
                    or int(spec.get("ndev", 0)) < 1:
                spec["ndev"] = self.env.num_devices
            if self._enqueue_prewarm(spec):
                count += 1
        return count

    def _healthz(self) -> dict:
        """Health snapshot behind ``/healthz``.  stats() iterates live
        dicts the scheduling thread mutates; a concurrent resize raises
        RuntimeError, so the HTTP thread retries the snapshot instead
        of locking the scheduling hot path."""
        for _ in range(8):
            try:
                s = self.stats()
                break
            except RuntimeError:
                continue
        else:
            s = {"queued": self._queued, "completed": self.completed}
        degraded = bool(s.get("degraded"))
        breakers = int(s.get("open_breakers", 0))
        return {
            "status": "degraded" if degraded or breakers else "ok",
            "degraded": degraded,
            "devices": int(s.get("devices", self.env.num_devices)),
            "queue_depth": int(s.get("queued", 0)),
            "waiting_unbanked": int(s.get("waiting_unbanked", 0)),
            "banks": int(s.get("banks", 0)),
            "preempted_banks": int(s.get("preempted_banks", 0)),
            "completed": int(s.get("completed", 0)),
            "open_breakers": breakers,
            "flight_dumps": len(self.flight_dumps),
            "warm_pool_depth": len(self._warm_keys),
            "prewarm_backlog": int(self._prewarm_pending),
        }

    def serve_http(self, host: str = "127.0.0.1",
                   port: int = 0) -> Tuple[str, int]:
        """Start the live observability endpoint on a daemon thread and
        return its bound ``(host, port)`` (``port=0`` picks a free
        one).  Endpoints:

        - ``GET /metrics``  — the Prometheus exposition, byte-identical
          to ``telemetry.prometheus_text()``;
        - ``GET /healthz``  — JSON health: degraded flag, queue depth,
          open quarantine breakers (non-"ok" status when either);
        - ``GET /tracez``   — JSON index of held request traces;
          ``/tracez/<trace_id>`` (or ``?id=``) one reconstructed span
          tree (404 for unknown ids).

        Idempotent: a second call returns the existing address.  The
        thread dies with :meth:`close` (or the process — daemon)."""
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        if self._http is not None:
            return self._http.server_address
        server = self

        class _ObsHandler(BaseHTTPRequestHandler):
            def log_message(self, *_args):  # no stderr chatter
                pass

            def _send(self, code: int, body: str, ctype: str) -> None:
                data = body.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _json(self, code: int, doc: dict) -> None:
                self._send(code, json.dumps(doc, sort_keys=True),
                           "application/json")

            def do_GET(self) -> None:
                path, _, query = self.path.partition("?")
                if path == "/metrics":
                    self._send(
                        200, _telemetry.prometheus_text(),
                        "text/plain; version=0.0.4; charset=utf-8")
                elif path == "/healthz":
                    self._json(200, server._healthz())
                elif path == "/tracez" or path.startswith("/tracez/"):
                    tid = path[len("/tracez/"):]
                    if not tid and query.startswith("id="):
                        tid = query[len("id="):]
                    doc = server.tracez(tid or None)
                    if doc is None:
                        self._json(404, {"error":
                                         f"unknown trace id {tid!r}"})
                    else:
                        self._json(200, doc)
                else:
                    self._json(404, {
                        "error": f"no route {path!r}",
                        "endpoints": ["/metrics", "/healthz",
                                      "/tracez"]})

        self._http = ThreadingHTTPServer((host, int(port)), _ObsHandler)
        self._http.daemon_threads = True
        self._http_thread = threading.Thread(
            target=self._http.serve_forever, name="qt-serve-obs",
            daemon=True)
        self._http_thread.start()
        return self._http.server_address

    # -- drivers ---------------------------------------------------------

    def run_until_idle(self, max_steps: Optional[int] = None) -> int:
        """Step until nothing is runnable; returns the number of
        windows executed.  ``max_steps`` bounds runaway loops in
        tests."""
        steps = 0
        while self.step():
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return steps

    def stats(self) -> dict:
        """Live queue/occupancy snapshot (the serving section of
        reportPerf reads the telemetry counters; this is the
        programmatic view)."""
        waiting = sum(len(v) for v in self._buckets.values())
        return {
            "queued": self._queued,
            "waiting_unbanked": waiting,
            "banks": len(self._banks),
            "preempted_banks": sum(1 for b in self._banks
                                   if b.preempted or b.paused),
            "completed": self.completed,
            "degraded": self.env is not self._full_env,
            "devices": self.env.num_devices,
            "open_breakers": sum(1 for br in self._breakers.values()
                                 if br.state != "closed"),
            "tenants": {
                name: {"weight": t.weight, "vtime": t.vtime,
                       "inflight": t.inflight,
                       "inflight_bytes": t.inflight_bytes,
                       "submitted": t.submitted,
                       "completed": t.completed}
                for name, t in self.tenants.items()},
        }

    def close(self) -> None:
        """Release live banks and (when the server created it) the
        preemption checkpoint root."""
        if self._closed:
            return
        self._closed = True
        t = self._prewarm_thread
        if t is not None:
            with self._prewarm_lock:
                self._prewarm_wake.notify_all()
            t.join(timeout=5.0)
            self._prewarm_thread = None
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()
            self._http = None
            self._http_thread = None
        _ptopo.remove_mesh_listener(self._mesh_cb)
        for bank in self._banks:
            if bank.qureg is not None:
                _governor.release(bank.qureg)
            bank.qureg = None
            bank.ex = None
        self._banks.clear()
        self._buckets.clear()
        if self._own_ckpt_root:
            shutil.rmtree(self._ckpt_root, ignore_errors=True)

    def __enter__(self) -> "SimServer":
        return self

    def __exit__(self, *_exc) -> None:
        self.close()


class Service:
    """Asyncio front end over a :class:`SimServer`: a cooperative
    stepping loop plus awaitable submission.  Single event loop, no
    threads — the scheduling core stays synchronous and deterministic,
    the service yields to the loop between fusion windows (exactly the
    safe points WindowExecutor guarantees).

    Usage::

        server = SimServer(env)
        async with Service(server) as svc:
            job = await svc.submit(gates, num_qubits=8,
                                   tenant="alice",
                                   priority="interactive")
            amps = (await svc.wait(job)).amps
    """

    def __init__(self, server: SimServer, *, idle_sleep: float = 0.001):
        self.server = server
        self.idle_sleep = float(idle_sleep)
        self._task: Optional[asyncio.Task] = None
        self._stopping = False

    async def submit(self, gates, **kwargs) -> Job:
        """Admit one job (QuotaExceededError propagates to the caller
        — the await point IS the backpressure)."""
        return self.server.submit(gates, **kwargs)

    async def wait(self, job: Job) -> Job:
        """Await a job's completion; re-raises its failure as the same
        fresh per-job :class:`JobFailedError` Job.result raises."""
        while not job.done:
            await asyncio.sleep(0)
        if job.state == FAILED:
            raise job._failure() from job.error
        return job

    async def submit_and_wait(self, gates, **kwargs) -> Job:
        return await self.wait(await self.submit(gates, **kwargs))

    async def _run(self) -> None:
        while not self._stopping:
            progressed = self.server.step()
            # yield between windows so submissions/awaits interleave
            # with execution — the continuous half of the batcher
            await asyncio.sleep(0 if progressed else self.idle_sleep)

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._stopping = False
            self._task = asyncio.get_running_loop().create_task(
                self._run())

    async def aclose(self) -> None:
        self._stopping = True
        if self._task is not None:
            await self._task
            self._task = None

    async def __aenter__(self) -> "Service":
        self.start()
        return self

    async def __aexit__(self, *_exc) -> None:
        await self.aclose()
