"""Gate-matrix builders.

The reference decomposes rotations into (alpha, beta) Givens pairs fed to
compactUnitary (QuEST_common.c:120-139, 306-372); here every 1/2-qubit gate
is just its dense matrix.  Matrices are built host-side with NumPy — they
are 4..16 complex numbers, and building them on-device would add a dispatch
round-trip per gate call.  They enter jitted kernels as *dynamic* arguments,
so a parameterised gate never recompiles when only its angle changes
(SURVEY.md §7 hard-part (c)).

Conventions match the reference exactly: rotateX/Y/Z = exp(-i theta/2 P).
"""

from __future__ import annotations

import numpy as np

PAULI_I = np.eye(2, dtype=np.complex128)
PAULI_X = np.array([[0, 1], [1, 0]], dtype=np.complex128)
PAULI_Y = np.array([[0, -1j], [1j, 0]], dtype=np.complex128)
PAULI_Z = np.array([[1, 0], [0, -1]], dtype=np.complex128)
HADAMARD = np.array([[1, 1], [1, -1]], dtype=np.complex128) / np.sqrt(2)
S_GATE_DIAG = np.array([1, 1j], dtype=np.complex128)
T_GATE_DIAG = np.array([1, np.exp(1j * np.pi / 4)], dtype=np.complex128)
Z_DIAG = np.array([1, -1], dtype=np.complex128)

PAULI_MATRICES = (PAULI_I, PAULI_X, PAULI_Y, PAULI_Z)

# Basis rotations taking Z to X / Y (multiRotatePauli decomposition,
# QuEST_common.c:424-462) — the single source for both the per-term gate
# path (api._multi_rotate_pauli) and the scan tables (paulis._rot_tables)
RY_M90 = (1 / np.sqrt(2)) * np.array([[1, 1], [-1, 1]], dtype=np.complex128)
RX_P90 = (1 / np.sqrt(2)) * np.array([[1, -1j], [-1j, 1]], dtype=np.complex128)

# (reference sqrtSwap matrix, QuEST_common.c:397-421)
SQRT_SWAP = np.array(
    [
        [1, 0, 0, 0],
        [0, 0.5 + 0.5j, 0.5 - 0.5j, 0],
        [0, 0.5 - 0.5j, 0.5 + 0.5j, 0],
        [0, 0, 0, 1],
    ],
    dtype=np.complex128,
)


def compact_unitary_matrix(alpha, beta) -> np.ndarray:
    """[[alpha, -conj(beta)], [beta, conj(alpha)]] (QuEST.h compactUnitary)."""
    a, b = complex(alpha), complex(beta)
    return np.array([[a, -np.conj(b)], [b, np.conj(a)]])


def rotate_x_matrix(theta) -> np.ndarray:
    t = float(theta) / 2
    c, s = np.cos(t), np.sin(t)
    return np.array([[c, -1j * s], [-1j * s, c]])


def rotate_y_matrix(theta) -> np.ndarray:
    t = float(theta) / 2
    c, s = np.cos(t), np.sin(t)
    return np.array([[c, -s], [s, c]], dtype=np.complex128)


def rotate_z_diag(theta) -> np.ndarray:
    t = float(theta) / 2
    return np.array([np.exp(-1j * t), np.exp(1j * t)])


def phase_shift_diag(theta) -> np.ndarray:
    """diag(1, e^{i theta}) (reference phaseShift, QuEST.h:1595)."""
    return np.array([1.0, np.exp(1j * float(theta))])


def rotate_around_axis_matrix(theta, axis_xyz) -> np.ndarray:
    """exp(-i theta/2 n.sigma), n normalised (reference
    getComplexPairFromRotation, QuEST_common.c:120-139)."""
    ax = np.asarray(axis_xyz, dtype=np.float64)
    ax = ax / np.linalg.norm(ax)
    t = float(theta) / 2
    c, s = np.cos(t), np.sin(t)
    nx, ny, nz = ax
    return np.array(
        [
            [c - 1j * s * nz, -s * ny - 1j * s * nx],
            [s * ny - 1j * s * nx, c + 1j * s * nz],
        ]
    )


def pauli_product_matrix(codes) -> np.ndarray:
    """Dense matrix of a Pauli string; codes[0] acts on the least-significant
    (first-target) qubit, matching apply_matrix's target convention."""
    m = None
    for code in codes:
        p = PAULI_MATRICES[int(code)]
        m = p if m is None else np.kron(p, m)
    return m
