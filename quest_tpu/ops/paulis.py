"""Pauli-string application, expectation values.

Re-implements the reference's workspace-based Pauli machinery
(QuEST_common.c:505-569: clone + apply X/Y/Z kernels + inner product) the
TPU way: a whole PauliHamil expectation is one jitted program — per term the
Pauli product is applied with permutation/sign fast kernels (X = axis flip,
Z = parity sign, Y = flip then +/-i sign; no dense 2x2 matmuls) and reduced
against the original state, so XLA fuses and pipelines across terms instead
of paying T full clone+dispatch round-trips.

States are SoA ``(2, num_amps)`` real arrays (see ops/cplx.py).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from . import cplx

PAULI_I, PAULI_X, PAULI_Y, PAULI_Z = 0, 1, 2, 3


def apply_pauli_string(view, n: int, targets: Tuple[int, ...], codes: Tuple[int, ...]):
    """Apply a Pauli product to a (2,) + (2,)*n SoA view using only flips
    (X), broadcast sign masks (Z), and their composition
    (Y: amp'_b = (+/-i) amp_{1-b}).

    Matches statevec_applyPauliProd (QuEST_common.c:505-516) semantics.
    """
    flip_axes = []
    factors = []  # (qubit-axis-sans-channel, re-vec or None, im-vec or None)
    for t, c in zip(targets, codes):
        ax = n - 1 - t  # axis in the channel-less (2,)*n layout
        if c == PAULI_I:
            continue
        elif c == PAULI_X:
            flip_axes.append(1 + ax)
        elif c == PAULI_Z:
            factors.append((ax, jnp.array([1.0, -1.0]), None))
        elif c == PAULI_Y:
            # Y|0> = i|1>, Y|1> = -i|0>: flip, then multiply by i*[-1, +1]
            # indexed by the NEW bit value.
            flip_axes.append(1 + ax)
            factors.append((ax, None, jnp.array([-1.0, 1.0])))
    if flip_axes:
        view = jnp.flip(view, axis=tuple(flip_axes))
    if factors:
        f_re = jnp.ones((1,) * n, dtype=view.dtype)
        f_im = jnp.zeros((1,) * n, dtype=view.dtype)
        for ax, re_vec, im_vec in factors:
            shape = [1] * n
            shape[ax] = 2
            if re_vec is not None:
                v = re_vec.astype(view.dtype).reshape(shape)
                f_re = f_re * v
                f_im = f_im * v
            else:
                v = im_vec.astype(view.dtype).reshape(shape)
                f_re, f_im = -f_im * v, f_re * v
        view = cplx.cmul(view, f_re, f_im)
    return view


@partial(jax.jit, static_argnames=("num_qubits", "targets", "codes"), donate_argnums=0)
def apply_pauli_prod(amps, *, num_qubits: int, targets: Tuple[int, ...], codes: Tuple[int, ...]):
    view = amps.reshape((2,) + (2,) * num_qubits)
    return apply_pauli_string(view, num_qubits, targets, codes).reshape(2, -1)


@partial(jax.jit, static_argnames=("num_qubits", "codes_flat", "num_terms"))
def calc_expec_pauli_sum_statevec(amps, coeffs, *, num_qubits: int,
                                  codes_flat: Tuple[int, ...], num_terms: int):
    """Re <psi| sum_t c_t P_t |psi> as ONE fused program (reference loops
    clone+apply+innerProduct per term, QuEST_common.c:534-546)."""
    n = num_qubits
    view = amps.reshape((2,) + (2,) * n)
    coeffs = jnp.asarray(coeffs, amps.dtype)
    total = jnp.zeros((), amps.dtype)
    for t in range(num_terms):
        codes = codes_flat[t * n:(t + 1) * n]
        pv = apply_pauli_string(view, n, tuple(range(n)), codes)
        # Re <view|pv>
        total = total + coeffs[t] * jnp.sum(view[0] * pv[0] + view[1] * pv[1])
    return total


@partial(jax.jit, static_argnames=("num_qubits", "codes_flat", "num_terms"))
def calc_expec_pauli_sum_density(amps, coeffs, *, num_qubits: int,
                                 codes_flat: Tuple[int, ...], num_terms: int):
    """Re Tr(rho sum_t c_t P_t): apply P to the ket qubits of the flattened
    rho, then take the diagonal trace (reference routes this through
    densmatr_calcTotalProb of a workspace, QuEST_common.c:519-546)."""
    n = num_qubits
    nn = 2 * n
    dim = 1 << n
    view = amps.reshape((2,) + (2,) * nn)
    coeffs = jnp.asarray(coeffs, amps.dtype)
    total = jnp.zeros((), amps.dtype)
    for t in range(num_terms):
        codes = codes_flat[t * n:(t + 1) * n]
        pv = apply_pauli_string(view, nn, tuple(range(n)), codes)
        tr_re = jnp.sum(jnp.diagonal(pv[0].reshape(dim, dim)))
        total = total + coeffs[t] * tr_re
    return total


@partial(jax.jit, static_argnames=("num_qubits", "num_state_qubits", "codes_flat", "num_terms"), donate_argnums=2)
def apply_pauli_sum(amps, coeffs, out_amps, *, num_qubits: int,
                    num_state_qubits: int, codes_flat: Tuple[int, ...],
                    num_terms: int):
    """out = sum_t c_t P_t |in> (statevec_applyPauliSum,
    QuEST_common.c:547-569). NOTE apply*-family: on rho this left-multiplies
    (SURVEY.md §2.3 semantic trap): num_state_qubits = 2*num_qubits and the
    codes act on the ket (low) qubits only."""
    n = num_qubits
    nsv = num_state_qubits
    view = amps.reshape((2,) + (2,) * nsv)
    coeffs = jnp.asarray(coeffs, amps.dtype)
    acc = jnp.zeros_like(view)
    for t in range(num_terms):
        codes = codes_flat[t * n:(t + 1) * n]
        pv = apply_pauli_string(view, nsv, tuple(range(n)), codes)
        acc = acc + coeffs[t] * pv
    del out_amps  # donated buffer re-used by XLA for the result
    return acc.reshape(2, -1)
