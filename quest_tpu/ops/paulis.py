"""Pauli-string application, expectation values.

Re-implements the reference's workspace-based Pauli machinery
(QuEST_common.c:505-569: clone + apply X/Y/Z kernels + inner product) the
TPU way: a whole PauliHamil expectation is one jitted program — per term the
Pauli product is applied with permutation/sign fast kernels (X = axis flip,
Z = parity sign, Y = flip then +/-i sign; no dense 2x2 matmuls) and reduced
against the original state, so XLA fuses and pipelines across terms instead
of paying T full clone+dispatch round-trips.

States are SoA ``(2, num_amps)`` real arrays (see ops/cplx.py).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from . import cplx

PAULI_I, PAULI_X, PAULI_Y, PAULI_Z = 0, 1, 2, 3


def apply_pauli_string(amps, n: int, targets: Tuple[int, ...], codes: Tuple[int, ...]):
    """Apply a Pauli product to flat (2, 2^n) SoA amps using only axis flips
    (X), a parity sign mask (Z), and their composition (Y).

    Factorization: flipping all X and Y targets, the residual elementwise
    factor is (-i)^{#Y} * (-1)^{parity(Z and Y bits)} — Y|b> = i(2b'-1)|b'>
    with b' the flipped bit, and i(2b'-1) = -i * (-1)^{b'}.  So one multi-
    flip plus one fused parity multiply, never a high-rank broadcast.
    Matches statevec_applyPauliProd (QuEST_common.c:505-516) semantics.
    """
    from .kernels import _flip_bits_flat, parity_sign_2d

    flips = []
    par = []
    num_y = 0
    for t, c in zip(targets, codes):
        if c == PAULI_X:
            flips.append(t)
        elif c == PAULI_Z:
            par.append(t)
        elif c == PAULI_Y:
            flips.append(t)
            par.append(t)
            num_y += 1
    amps = _flip_bits_flat(amps, n, tuple(flips))
    if not par and num_y % 4 == 0:
        return amps
    # constant (-i)^{#Y}: one of 1, -i, -1, i
    c_re, c_im = [(1.0, 0.0), (0.0, -1.0), (-1.0, 0.0), (0.0, 1.0)][num_y % 4]
    if par:
        s = parity_sign_2d(n, par, amps.dtype)
        view = amps.reshape(2, s.shape[0], s.shape[1])
        return cplx.cmul(view, c_re * s, c_im * s).reshape(2, -1)
    return cplx.cmul(amps, jnp.asarray(c_re, amps.dtype),
                     jnp.asarray(c_im, amps.dtype))


@partial(jax.jit, static_argnames=("num_qubits", "targets", "codes"), donate_argnums=0)
def apply_pauli_prod(amps, *, num_qubits: int, targets: Tuple[int, ...], codes: Tuple[int, ...]):
    return apply_pauli_string(amps, num_qubits, targets, codes)


@partial(jax.jit, static_argnames=("num_qubits", "codes_flat", "num_terms"))
def calc_expec_pauli_sum_statevec(amps, coeffs, *, num_qubits: int,
                                  codes_flat: Tuple[int, ...], num_terms: int):
    """Re <psi| sum_t c_t P_t |psi> as ONE fused program (reference loops
    clone+apply+innerProduct per term, QuEST_common.c:534-546)."""
    n = num_qubits
    coeffs = jnp.asarray(coeffs, amps.dtype)
    total = jnp.zeros((), amps.dtype)
    for t in range(num_terms):
        codes = codes_flat[t * n:(t + 1) * n]
        pv = apply_pauli_string(amps, n, tuple(range(n)), codes)
        # Re <amps|pv>
        total = total + coeffs[t] * jnp.sum(amps[0] * pv[0] + amps[1] * pv[1])
    return total


@partial(jax.jit, static_argnames=("num_qubits", "codes_flat", "num_terms"))
def calc_expec_pauli_sum_density(amps, coeffs, *, num_qubits: int,
                                 codes_flat: Tuple[int, ...], num_terms: int):
    """Re Tr(rho sum_t c_t P_t): apply P to the ket qubits of the flattened
    rho, then take the diagonal trace (reference routes this through
    densmatr_calcTotalProb of a workspace, QuEST_common.c:519-546)."""
    n = num_qubits
    nn = 2 * n
    dim = 1 << n
    coeffs = jnp.asarray(coeffs, amps.dtype)
    total = jnp.zeros((), amps.dtype)
    for t in range(num_terms):
        codes = codes_flat[t * n:(t + 1) * n]
        pv = apply_pauli_string(amps, nn, tuple(range(n)), codes)
        tr_re = jnp.sum(jnp.diagonal(pv[0].reshape(dim, dim)))
        total = total + coeffs[t] * tr_re
    return total


@partial(jax.jit, static_argnames=("num_qubits", "num_state_qubits", "codes_flat", "num_terms"), donate_argnums=2)
def apply_pauli_sum(amps, coeffs, out_amps, *, num_qubits: int,
                    num_state_qubits: int, codes_flat: Tuple[int, ...],
                    num_terms: int):
    """out = sum_t c_t P_t |in> (statevec_applyPauliSum,
    QuEST_common.c:547-569). NOTE apply*-family: on rho this left-multiplies
    (SURVEY.md §2.3 semantic trap): num_state_qubits = 2*num_qubits and the
    codes act on the ket (low) qubits only."""
    n = num_qubits
    nsv = num_state_qubits
    coeffs = jnp.asarray(coeffs, amps.dtype)
    acc = jnp.zeros_like(amps)
    for t in range(num_terms):
        codes = codes_flat[t * n:(t + 1) * n]
        pv = apply_pauli_string(amps, nsv, tuple(range(n)), codes)
        acc = acc + coeffs[t] * pv
    del out_amps  # donated buffer re-used by XLA for the result
    return acc
