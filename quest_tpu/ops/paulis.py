"""Pauli-string application, expectation values.

Re-implements the reference's workspace-based Pauli machinery
(QuEST_common.c:505-569: clone + apply X/Y/Z kernels + inner product) the
TPU way: a whole PauliHamil expectation is one jitted program — per term the
Pauli product is applied with permutation/sign fast kernels (X = axis flip,
Z = parity sign, Y = flip then +/-i sign; no dense 2x2 matmuls) and reduced
against the original state, so XLA fuses and pipelines across terms instead
of paying T full clone+dispatch round-trips.

States are SoA ``(2, num_amps)`` real arrays (see ops/cplx.py).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from . import cplx

PAULI_I, PAULI_X, PAULI_Y, PAULI_Z = 0, 1, 2, 3


def apply_pauli_string(amps, n: int, targets: Tuple[int, ...], codes: Tuple[int, ...]):
    """Apply a Pauli product to flat (2, 2^n) SoA amps using only axis flips
    (X), a parity sign mask (Z), and their composition (Y).

    Factorization: flipping all X and Y targets, the residual elementwise
    factor is (-i)^{#Y} * (-1)^{parity(Z and Y bits)} — Y|b> = i(2b'-1)|b'>
    with b' the flipped bit, and i(2b'-1) = -i * (-1)^{b'}.  So one multi-
    flip plus one fused parity multiply, never a high-rank broadcast.
    Matches statevec_applyPauliProd (QuEST_common.c:505-516) semantics.
    """
    from .kernels import _flip_bits_flat, parity_sign_2d

    flips = []
    par = []
    num_y = 0
    for t, c in zip(targets, codes):
        if c == PAULI_X:
            flips.append(t)
        elif c == PAULI_Z:
            par.append(t)
        elif c == PAULI_Y:
            flips.append(t)
            par.append(t)
            num_y += 1
    amps = _flip_bits_flat(amps, n, tuple(flips))
    if not par and num_y % 4 == 0:
        return amps
    # constant (-i)^{#Y}: one of 1, -i, -1, i
    c_re, c_im = [(1.0, 0.0), (0.0, -1.0), (-1.0, 0.0), (0.0, 1.0)][num_y % 4]
    if par:
        s = parity_sign_2d(n, par, amps.dtype)
        view = amps.reshape(2, s.shape[0], s.shape[1])
        return cplx.cmul(view, c_re * s, c_im * s).reshape(2, -1)
    return cplx.cmul(amps, jnp.asarray(c_re, amps.dtype),
                     jnp.asarray(c_im, amps.dtype))


@partial(jax.jit, static_argnames=("num_qubits", "targets", "codes"), donate_argnums=0)
def apply_pauli_prod(amps, *, num_qubits: int, targets: Tuple[int, ...], codes: Tuple[int, ...]):
    return apply_pauli_string(amps, num_qubits, targets, codes)


@partial(jax.jit, static_argnames=("num_qubits", "codes_flat", "num_terms",
                                   "quad"))
def calc_expec_pauli_sum_statevec(amps, coeffs, *, num_qubits: int,
                                  codes_flat: Tuple[int, ...], num_terms: int,
                                  quad: bool = False):
    """Re <psi| sum_t c_t P_t |psi> as ONE fused program (reference loops
    clone+apply+innerProduct per term, QuEST_common.c:534-546).  ``quad``
    (prec 4) accumulates each term's signed inner product — and the
    cross-term combine — in double-double."""
    from . import calculations as _calc

    n = num_qubits
    coeffs = jnp.asarray(coeffs, amps.dtype)
    vals = []
    for t in range(num_terms):
        codes = codes_flat[t * n:(t + 1) * n]
        pv = apply_pauli_string(amps, n, tuple(range(n)), codes)
        # Re <amps|pv>
        if quad:
            r = _calc.quad_sum2(amps[0] * pv[0], amps[1] * pv[1])
        else:
            r = jnp.sum(amps[0] * pv[0] + amps[1] * pv[1])
        vals.append(coeffs[t] * r)
    stacked = jnp.stack(vals)
    return _calc.neumaier_sum(stacked) if quad else jnp.sum(stacked)


@partial(jax.jit, static_argnames=("num_qubits", "codes_flat", "num_terms",
                                   "quad"))
def calc_expec_pauli_sum_density(amps, coeffs, *, num_qubits: int,
                                 codes_flat: Tuple[int, ...], num_terms: int,
                                 quad: bool = False):
    """Re Tr(rho sum_t c_t P_t): apply P to the ket qubits of the flattened
    rho, then take the diagonal trace (reference routes this through
    densmatr_calcTotalProb of a workspace, QuEST_common.c:519-546)."""
    from . import calculations as _calc

    n = num_qubits
    nn = 2 * n
    dim = 1 << n
    coeffs = jnp.asarray(coeffs, amps.dtype)
    red = _calc.quad_sum if quad else jnp.sum
    vals = []
    for t in range(num_terms):
        codes = codes_flat[t * n:(t + 1) * n]
        pv = apply_pauli_string(amps, nn, tuple(range(n)), codes)
        vals.append(coeffs[t] * red(jnp.diagonal(pv[0].reshape(dim, dim))))
    stacked = jnp.stack(vals)
    return _calc.neumaier_sum(stacked) if quad else jnp.sum(stacked)


@partial(jax.jit, static_argnames=("num_qubits", "num_state_qubits", "codes_flat", "num_terms"), donate_argnums=2)
def apply_pauli_sum(amps, coeffs, out_amps, *, num_qubits: int,
                    num_state_qubits: int, codes_flat: Tuple[int, ...],
                    num_terms: int):
    """out = sum_t c_t P_t |in> (statevec_applyPauliSum,
    QuEST_common.c:547-569). NOTE apply*-family: on rho this left-multiplies
    (SURVEY.md §2.3 semantic trap): num_state_qubits = 2*num_qubits and the
    codes act on the ket (low) qubits only."""
    n = num_qubits
    nsv = num_state_qubits
    coeffs = jnp.asarray(coeffs, amps.dtype)
    acc = jnp.zeros_like(amps)
    for t in range(num_terms):
        codes = codes_flat[t * n:(t + 1) * n]
        pv = apply_pauli_string(amps, nsv, tuple(range(n)), codes)
        acc = acc + coeffs[t] * pv
    del out_amps  # donated buffer re-used by XLA for the result
    return acc


# ---------------------------------------------------------------------------
# Scan-based Trotter body (agnostic_applyTrotterCircuit, QuEST_common.c:752-834)
# ---------------------------------------------------------------------------

def _rot_tables(dt):
    """SoA (4, 2, 2, 2) basis-rotation tables indexed by Pauli code:
    I/Z -> identity, X -> Ry(-90) (Z->X), Y -> Rx(+90) (Z->Y); plus the
    dagger and the conjugated (bra-twin) variants."""
    import numpy as np

    from . import gatedefs as G

    eye = np.eye(2, dtype=complex)
    tab = np.stack([eye, G.RY_M90, G.RX_P90, eye])
    tabd = np.conj(np.transpose(tab, (0, 2, 1)))

    def soa(t):
        return jnp.asarray(np.stack([t.real, t.imag], axis=1), dt)

    return soa(tab), soa(tabd), soa(np.conj(tab)), soa(np.conj(tabd))


_PAR_LO_BITS = 31  # uint32 iota stays exact up to 2^31 entries


def _parity_sign_dynamic(zm_lo, zm_hi, n, dt):
    """(2^n,)-shaped (+1/-1) sign of parity(idx & zmask) with a TRACED
    64-bit mask carried as two uint32 halves (bits [0,31) / [31,62)) —
    parity factorises over the split, so the sign is an outer product of
    two <=2^31-entry factors and no index arithmetic ever exceeds 32 bits
    (the reference's isOddParity runs on 64-bit masks,
    QuEST_cpu_internal.h:38).  Everything fuses; nothing materialises
    beyond the output sign."""
    lo = min(n, _PAR_LO_BITS)
    idx_lo = jax.lax.iota(jnp.uint32, 1 << lo)
    s_lo = 1.0 - 2.0 * (
        (jax.lax.population_count(idx_lo & zm_lo) & jnp.uint32(1))
        .astype(dt))
    if n <= _PAR_LO_BITS:
        return s_lo
    idx_hi = jax.lax.iota(jnp.uint32, 1 << (n - lo))
    s_hi = 1.0 - 2.0 * (
        (jax.lax.population_count(idx_hi & zm_hi) & jnp.uint32(1))
        .astype(dt))
    return (s_hi[:, None] * s_lo[None, :]).reshape(-1)


def _parity_phase_mask(amps, theta, zm_lo, zm_hi, n):
    """exp(-i theta/2 (-1)^parity(idx & zmask)) with a TRACED mask —
    the data-driven variant of kernels.apply_parity_phase (reference
    multiRotateZ bit-parity trick, QuEST_cpu.c:3268-3317)."""
    s = _parity_sign_dynamic(zm_lo, zm_hi, n, amps.dtype)
    ang = -0.5 * theta
    return cplx.cmul(amps, jnp.cos(ang), jnp.sin(ang) * s)


def _zmask_halves(codes, qbit_offset, nq):
    """(lo, hi) uint32 halves of sum_q [codes_q != I] << (q + offset)."""
    zm_lo = jnp.uint32(0)
    zm_hi = jnp.uint32(0)
    for q in range(nq):
        bit = (codes[q] != 0).astype(jnp.uint32)
        pos = q + qbit_offset
        if pos < _PAR_LO_BITS:
            zm_lo = zm_lo | (bit << pos)
        else:
            zm_hi = zm_hi | (bit << (pos - _PAR_LO_BITS))
    return zm_lo, zm_hi


def _product_layer(amps, mats, n):
    """Apply the 1q-gate product layer (x)_q mats[q] to all n state-vector
    qubits.  For n >= 14 the layer folds into ceil(n/7) window passes
    (lane side + one 7-qubit window each, circuit.py embedding); below
    that, per-qubit dense kernels."""
    from . import fused, kernels

    if n < fused.CLUSTER_QUBITS:
        for q in range(n):
            amps = kernels.apply_matrix(amps, mats[q], num_qubits=n,
                                        targets=(q,))
        return amps
    from .. import circuit as C

    def side(qs, rel_off):
        acc = None
        for q in qs:
            e = C.embed_in_cluster(mats[q], (q - rel_off,))
            acc = e if acc is None else C.soa_matmul(e, acc)
        return acc

    a = side(range(fused.LANE_QUBITS), 0)
    b7 = side(range(fused.LANE_QUBITS, fused.CLUSTER_QUBITS), fused.LANE_QUBITS)
    amps = fused.apply_window_stack(amps, a[None], b7[None],
                                    num_qubits=n, k=fused.LANE_QUBITS)
    eye = jnp.asarray(C._eye_cluster(), amps.dtype)[None]
    s = fused.CLUSTER_QUBITS
    while s < n:
        e = min(s + fused.LANE_QUBITS, n)
        k = min(s, n - fused.LANE_QUBITS)
        b = side(range(s, e), k)
        amps = fused.apply_window_stack(amps, eye, b[None],
                                        num_qubits=n, k=k, apply_a=False)
        s = e
    return amps


def make_trotter_body(dt, nq: int, is_density: bool, layer, parity_phase):
    """The per-term Trotter scan body (rotate -> parity phase [+ bra
    twin] -> unrotate), parameterized by the layer applier
    ``layer(carry, mats)`` and the parity phase
    ``parity_phase(carry, theta, zlo, zhi)`` so the unsharded scan
    (trotter_scan) and the shard_map scan
    (parallel.dist.trotter_scan_sharded) share ONE body — including the
    non-obvious all-identity-term angle zeroing (such terms contribute
    only a global phase the unfused path skips)."""
    tab, tabd, tabc, tabcd = _rot_tables(dt)

    def mats_for(codes, t, tc):
        m = t[codes]                        # (nq, 2, 2, 2)
        if is_density:
            m = jnp.concatenate([m, tc[codes]], axis=0)
        return m

    def body(carry, inp):
        codes, ang = inp
        ang = ang.astype(dt)
        carry = layer(carry, mats_for(codes, tab, tabc))
        zlo, zhi = _zmask_halves(codes, 0, nq)
        theta = jnp.where((zlo | zhi) == 0, jnp.asarray(0.0, dt), ang)
        carry = parity_phase(carry, theta, zlo, zhi)
        if is_density:
            blo, bhi = _zmask_halves(codes, nq, nq)
            carry = parity_phase(carry, -theta, blo, bhi)
        carry = layer(carry, mats_for(codes, tabd, tabcd))
        return carry, None

    return body


def make_expec_term_value(dt, n: int, layer, signed_norm):
    """The per-term PauliSum expectation body: basis-rotate a copy of the
    state (``layer``), then reduce the parity-signed norm
    (``signed_norm(phi, zlo, zhi)``).  Shared by expec_pauli_sum_scan and
    parallel.dist.expec_pauli_sum_scan_sharded."""
    tab, _, _, _ = _rot_tables(dt)

    def body_of(amps):
        def body(acc, inp):
            codes, coeff = inp
            phi = layer(amps, tab[codes])
            zlo, zhi = _zmask_halves(codes, 0, n)
            v = coeff.astype(dt) * signed_norm(phi, zlo, zhi)
            # per-term value also emitted as scan output so the quad
            # path can Neumaier-combine ACROSS terms instead of trusting
            # the f64 carry accumulation
            return acc + v, v
        return body

    return body_of


# ---------------------------------------------------------------------------
# Direct Pauli rotation: e^{-i th/2 P} psi = cos(th/2) psi
#                                            - i sin(th/2) (P psi)
# with (P psi)[i] = (-i)^{#Y} * (-1)^{parity(i & zm)} * psi[i ^ fm]
# (fm = X|Y bits, zm = Z|Y bits, P^2 = I).  ONE split-axis gather + one
# fused elementwise combine per term — measured ~2.2 ms/term at 24q vs
# ~17 ms/term for the rotate-layer -> parity-phase -> unrotate-layer
# body it replaces (scripts/probes/probe_trotter_direct_result.json:
# direct_rowcol 0.0345 s vs window_scan 0.277 s for 16 terms, same
# session; a flat 2^24 gather is ~160x slower — the (hi, lo) row/lane
# split is what makes the permutation DMA-friendly).  The reference's
# multiRotatePauli instead conjugates by basis rotations
# (QuEST_common.c:424-462).
# ---------------------------------------------------------------------------

_GATHER_LO_BITS = 12   # lane-axis width of the split gather (4096)
# Direct-rotation cap, DERIVED from the gather split and the int32
# max-index invariant rather than hand-counted: _flip_gather's hi-axis
# index vector is an int32 iota over 2^(n - _GATHER_LO_BITS) rows, so its
# largest value 2^(n - _GATHER_LO_BITS) - 1 must fit int32 — at most 31
# hi bits on top of the lane split.
_DIRECT_MAX_N = _GATHER_LO_BITS + 31
assert (1 << (_DIRECT_MAX_N - _GATHER_LO_BITS)) - 1 <= 2**31 - 1, (
    "_DIRECT_MAX_N violates the int32 row-index invariant")


def _direct_masks(codes, nq: int, offset: int, n: int):
    """(fm_lo, fm_hi, zlo, zhi, ny) for a Pauli-code row acting on qubits
    [offset, offset+nq): the flip mask split at _GATHER_LO_BITS for the
    row/lane gather, the parity mask split at _PAR_LO_BITS for the sign,
    and the Y count for the (-i)^{#Y} factor."""
    lo = min(_GATHER_LO_BITS, n)
    fm_lo = jnp.uint32(0)
    fm_hi = jnp.uint32(0)
    zlo = jnp.uint32(0)
    zhi = jnp.uint32(0)
    ny = jnp.uint32(0)
    for q in range(nq):
        c = codes[q]
        is_x = (c == PAULI_X).astype(jnp.uint32)
        is_y = (c == PAULI_Y).astype(jnp.uint32)
        is_z = (c == PAULI_Z).astype(jnp.uint32)
        pos = q + offset
        fbit = is_x | is_y
        if pos < lo:
            fm_lo = fm_lo | (fbit << pos)
        else:
            fm_hi = fm_hi | (fbit << (pos - lo))
        zbit = is_y | is_z
        if pos < _PAR_LO_BITS:
            zlo = zlo | (zbit << pos)
        else:
            zhi = zhi | (zbit << (pos - _PAR_LO_BITS))
        ny = ny + is_y
    return fm_lo, fm_hi, zlo, zhi, ny


def _flip_gather(amps, fm_lo, fm_hi, n: int):
    """psi[i ^ fm] for the whole (2, 2^n) state with a TRACED flip mask:
    one row-axis take (contiguous 2^lo-element rows) + one lane-axis
    take — the split keeps both index vectors small and the row reads
    contiguous."""
    lo = min(_GATHER_LO_BITS, n)
    hi = n - lo
    idx_lo = jax.lax.iota(jnp.uint32, 1 << lo) ^ fm_lo
    v = amps.reshape(2, 1 << hi, 1 << lo)
    if hi:
        idx_hi = jax.lax.iota(jnp.uint32, 1 << hi) ^ fm_hi
        v = jnp.take(v, idx_hi, axis=1)
    return jnp.take(v, idx_lo, axis=2).reshape(2, -1)


def _iexp_factor(ny, dt):
    """(-i)^{ny} as (re, im) scalars."""
    k = ny % 4
    c_re = jnp.where(k == 0, 1.0, jnp.where(k == 2, -1.0, 0.0)).astype(dt)
    c_im = jnp.where(k == 1, -1.0, jnp.where(k == 3, 1.0, 0.0)).astype(dt)
    return c_re, c_im


def _apply_pauli_traced(amps, codes, nq: int, offset: int, n: int,
                        conj: bool):
    """(P psi) with traced codes: gather + sign + (-i)^{#Y} factor
    (conj negates the factor's imaginary part — conj(P) flips Y's
    sign)."""
    dt = amps.dtype
    fm_lo, fm_hi, zlo, zhi, ny = _direct_masks(codes, nq, offset, n)
    s = _parity_sign_dynamic(zlo, zhi, n, dt)
    c_re, c_im = _iexp_factor(ny, dt)
    if conj:
        c_im = -c_im
    pv = _flip_gather(amps, fm_lo, fm_hi, n)
    pr = s * (c_re * pv[0] - c_im * pv[1])
    pi = s * (c_re * pv[1] + c_im * pv[0])
    return jnp.stack([pr, pi]), (fm_lo | fm_hi | zlo | zhi) == 0


def _direct_rotation(amps, codes, ang, nq: int, offset: int, n: int,
                     conj: bool):
    """e^{-i ang/2 P} psi (or e^{-i ang/2 conj(P)} psi when ``conj``) in
    ONE gather + combine; all-identity terms contribute only a global
    phase the gate stream skips (the same zeroing as make_trotter_body)."""
    dt = amps.dtype
    pv, is_identity = _apply_pauli_traced(amps, codes, nq, offset, n, conj)
    theta = jnp.where(is_identity, jnp.asarray(0.0, dt), ang)
    co = jnp.cos(0.5 * theta)
    si = jnp.sin(0.5 * theta)
    # out = cos*psi - i sin * (P psi)
    return jnp.stack([co * amps[0] + si * pv[1],
                      co * amps[1] - si * pv[0]])


# ---------------------------------------------------------------------------
# Pallas fused direct rotation: the whole term in ONE HBM pass per block
# (scripts/probes/probe_flip_pallas.py measured 2.3x over the take-take
# gather at 24q, bit-identical).  The XOR permutation decomposes as
#   - block-level row XOR: the flip input's BlockSpec index_map reads
#     block (i ^ (fm_row >> 8)) — pure DMA redirection;
#   - in-block row XOR (8 bits) and lane XOR (7 bits): dynamically built
#     0/1 permutation matmuls (256x256 and 128x128) on the MXU — Mosaic
#     has no rev lowering, and at HIGHEST precision a permutation matmul
#     is exact;
# parity signs factor as s_row (x) s_lane, built OUTSIDE the kernel.
# ---------------------------------------------------------------------------

_PL_BR = 256            # rows per block (n >= _PL_MIN_N so R >= _PL_BR)
_PL_MIN_N = 15

# one-shot Pallas lowering probe result (None = not yet probed).  A
# failed probe downgrades the direct-rotation/expectation path to the
# XLA gather form for the rest of the process — graceful degradation
# instead of a trace-time crash on a Mosaic/driver regression — and
# records itself in the env report (resilience.record_degradation).
_PALLAS_OK: dict = {}


def _probe_pallas_lowering() -> None:
    """Lower (don't run) a minimal rotation-kernel pallas_call at the
    smallest routable size; raises on any Mosaic/lowering failure."""
    probe_n = _PL_MIN_N
    amps = jax.ShapeDtypeStruct((2, 1 << probe_n), jnp.float32)
    codes = jax.ShapeDtypeStruct((probe_n,), jnp.int32)
    ang = jax.ShapeDtypeStruct((), jnp.float32)

    def f(a, c, t):
        return _direct_rotation_pallas(a, c, t, probe_n, 0, probe_n,
                                       conj=False)

    # compile, not just lower: Mosaic failures surface at compile time
    jax.jit(f).lower(amps, codes, ang).compile()


def pallas_lowering_ok() -> bool:
    """True when the fused Pallas term kernels lower on this backend;
    cached per process.  On failure, warn once, record the downgrade in
    the env report, and route through the XLA gather path instead."""
    hit = _PALLAS_OK.get("ok")
    if hit is not None:
        return hit
    try:
        _probe_pallas_lowering()
        ok = True
    # qlint: allow(broad-except): Pallas lowering failures span XlaRuntimeError/NotImplementedError/TypeError depending on backend and version; every one of them means "use the XLA gather path" and is recorded as a degradation
    except Exception as e:
        from .. import resilience

        resilience.record_degradation(
            "pallas-direct-rotation",
            "fused Pallas term kernel failed to lower; falling back to "
            f"the XLA gather path ({type(e).__name__}: {e})")
        ok = False
    _PALLAS_OK["ok"] = ok
    return ok


def _pl_routable(amps, n: int) -> bool:
    return (_PL_MIN_N <= n <= 32 and amps.dtype == jnp.float32
            and jax.default_backend() == "tpu" and pallas_lowering_ok())


def _pl_flip_signed(meta, fvals, x_ref, f_ref, srow_ref, slane_ref):
    """Shared kernel-body algebra: load the two blocks, apply the
    in-block row XOR and lane XOR as exact permutation matmuls, and
    return (x, pr, pi) with the parity sign and (-i)^{#Y} factor folded
    in — used by both the rotation and the expectation kernels."""
    from jax import lax

    rb = meta[1]
    fl = meta[2]
    x = x_ref[...]                  # (2, BR, 128)
    f = f_ref[...]
    hi = lax.Precision.HIGHEST
    ri = lax.broadcasted_iota(jnp.int32, (_PL_BR, _PL_BR), 0)
    rj = lax.broadcasted_iota(jnp.int32, (_PL_BR, _PL_BR), 1)
    prow = ((ri ^ rb) == rj).astype(x.dtype)
    f = jnp.concatenate([
        jnp.dot(prow, f[0], preferred_element_type=x.dtype,
                precision=hi)[None],
        jnp.dot(prow, f[1], preferred_element_type=x.dtype,
                precision=hi)[None],
    ])
    li = lax.broadcasted_iota(jnp.int32, (128, 128), 0)
    lj = lax.broadcasted_iota(jnp.int32, (128, 128), 1)
    perm = ((li ^ fl) == lj).astype(x.dtype)
    pv = jnp.dot(f.reshape(2 * _PL_BR, 128), perm,
                 preferred_element_type=x.dtype,
                 precision=hi).reshape(2, _PL_BR, 128)
    s = (srow_ref[...][:, 0][None, :, None]
         * slane_ref[...][0][None, None, :])[0]
    c_re = fvals[0, 2]
    c_im = fvals[0, 3]
    pr = s * (c_re * pv[0] - c_im * pv[1])
    pi = s * (c_re * pv[1] + c_im * pv[0])
    return x, pr, pi


def _pl_rotation_kernel(meta, fvals, x_ref, f_ref, srow_ref, slane_ref,
                        out_ref):
    x, pr, pi = _pl_flip_signed(meta, fvals, x_ref, f_ref, srow_ref,
                                slane_ref)
    co = fvals[0, 0]
    si = fvals[0, 1]
    out_ref[0, :, :] = co * x[0] + si * pi
    out_ref[1, :, :] = co * x[1] - si * pr


def _pl_expec_kernel(meta, fvals, x_ref, f_ref, srow_ref, slane_ref,
                     out_ref):
    """Per-term expectation contribution Re <x| P |x>: flip (same
    permutation algebra as the rotation kernel) + sign + product-reduce,
    one HBM pass — emitting ONE PARTIAL PER GRID BLOCK.  The (G,)
    partials are tree-reduced OUTSIDE the kernel (_expec_term_pallas):
    chaining every block through a single f32 accumulator cell makes the
    rounding error grow linearly in the block count and loses
    cross-block cancellation exactly where terms with opposing signs
    should cancel (ADVICE r5)."""
    x, pr, pi = _pl_flip_signed(meta, fvals, x_ref, f_ref, srow_ref,
                                slane_ref)
    out_ref[...] = jnp.sum(x[0] * pr + x[1] * pi).reshape(1, 1)


def _pl_term_inputs(amps, codes, ang, nq: int, offset: int, n: int,
                    conj: bool):
    """(meta, fvals, view, s_row, s_lane) shared by the two Pallas term
    kernels."""
    dt = amps.dtype
    R = 1 << (n - 7)
    fm_lo, fm_hi, zlo, zhi, ny = _direct_masks(codes, nq, offset, n)
    fm = fm_lo.astype(jnp.uint32)
    if n > _GATHER_LO_BITS:
        fm = fm | (fm_hi << _GATHER_LO_BITS)
    fm_lane = (fm & jnp.uint32(127)).astype(jnp.int32)
    fm_row = (fm >> 7).astype(jnp.int32)
    meta = jnp.stack([fm_row >> 8, fm_row & 255, fm_lane])
    s_full = _parity_sign_dynamic(zlo, zhi, n, dt)
    # parity factorises: s(r*128 + l) = s_row(r) * s_lane(l)
    s_lane = s_full[:128].reshape(1, 128)
    s_row = s_full.reshape(R, 128)[:, :1]
    theta = jnp.where((fm_lo | fm_hi | zlo | zhi) == 0,
                      jnp.asarray(0.0, dt), ang)
    c_re, c_im = _iexp_factor(ny, dt)
    if conj:
        c_im = -c_im
    fvals = jnp.stack([jnp.cos(0.5 * theta), jnp.sin(0.5 * theta),
                       c_re, c_im]).reshape(1, 4)
    return meta, fvals, amps.reshape(2, R, 128), s_row, s_lane


def _pl_grid_spec(R, out_blockspec):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(R // _PL_BR,),
        in_specs=[
            pl.BlockSpec((1, 4), lambda i, meta: (0, 0)),
            pl.BlockSpec((2, _PL_BR, 128), lambda i, meta: (0, i, 0)),
            pl.BlockSpec((2, _PL_BR, 128),
                         lambda i, meta: (0, i ^ meta[0], 0)),
            pl.BlockSpec((_PL_BR, 1), lambda i, meta: (i, 0)),
            pl.BlockSpec((1, 128), lambda i, meta: (0, 0)),
        ],
        out_specs=out_blockspec,
    )


def _expec_term_pallas(amps, codes, n: int):
    """Re <amps| P |amps> with a traced code row, one fused HBM pass:
    the kernel writes one partial per grid block and the (G,) partials
    tree-reduce here under XLA — O(log G) error depth instead of the
    former single-cell sequential accumulation's O(G)."""
    import jax
    import jax.experimental.pallas as pl

    from . import fused as _fused

    meta, fvals, view, s_row, s_lane = _pl_term_inputs(
        amps, codes, jnp.zeros((), amps.dtype), n, 0, n, conj=False)
    R = view.shape[1]
    out = pl.pallas_call(
        _pl_expec_kernel,
        grid_spec=_pl_grid_spec(
            R, pl.BlockSpec((1, 1), lambda i, meta: (i, 0))),
        out_shape=jax.ShapeDtypeStruct((R // _PL_BR, 1), view.dtype),
        interpret=_fused._interpret_default(),
    )(meta, fvals, view, view, s_row, s_lane)
    return jnp.sum(out)


def _direct_rotation_pallas(amps, codes, ang, nq: int, offset: int,
                            n: int, conj: bool):
    """One fused-HBM-pass direct rotation (15 <= n <= 32); bit-identical
    to _direct_rotation by construction (exact permutation matmuls + the
    same sign/factor algebra)."""
    import jax
    import jax.experimental.pallas as pl

    from . import fused as _fused

    meta, fvals, view, s_row, s_lane = _pl_term_inputs(
        amps, codes, ang, nq, offset, n, conj)
    R = view.shape[1]
    out = pl.pallas_call(
        _pl_rotation_kernel,
        grid_spec=_pl_grid_spec(
            R, pl.BlockSpec((2, _PL_BR, 128),
                            lambda i, meta: (0, i, 0))),
        out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
        interpret=_fused._interpret_default(),
    )(meta, fvals, view, view, s_row, s_lane)
    return out.reshape(amps.shape)


@partial(jax.jit, static_argnames=("num_qubits", "rep_qubits"),
         donate_argnums=0)
def trotter_scan(amps, codes_seq, angles, *, num_qubits: int,
                 rep_qubits: int):
    """The whole Trotter gate stream as ONE lax.scan over a (T, nq)
    Pauli-code table + (T,) angle vector: compile cost is a single term
    body regardless of term count, replacing the unrolled per-term
    multiRotatePauli stream whose first-call compile took minutes at
    config-5 scale (agnostic_applyTrotterCircuit, QuEST_common.c:752-834).

    The term body is the direct Pauli rotation (one split-axis gather +
    elementwise combine; density matrices add the conjugated bra twin at
    -theta) — ~8x the throughput of the rotate/phase/unrotate window
    body at 24q.  Registers beyond _DIRECT_MAX_N state bits (where the
    row-gather iota would overflow int32) keep the rotation-conjugation
    body; the SHARDED scan (parallel.dist.trotter_scan_sharded) carries
    the same direct body with the mesh-bit part of the traced flip mask
    riding a lax.switch over the 2^r static XOR ppermutes
    (dist._mesh_flip_gather); mesh-sweep parity tests pin the forms
    equal."""
    n, nq = num_qubits, rep_qubits
    dt = amps.dtype
    if n > _DIRECT_MAX_N:
        body = make_trotter_body(
            dt, nq, n == 2 * nq,
            layer=lambda carry, mats: _product_layer(carry, mats, n),
            parity_phase=lambda carry, theta, zlo, zhi: _parity_phase_mask(
                carry, theta, zlo, zhi, n),
        )
        amps, _ = jax.lax.scan(body, amps, (codes_seq, angles))
        return amps

    is_density = n == 2 * nq
    # fused Pallas term for block-decomposable sizes (one HBM pass per
    # term, 2.3x the take-take gather; u32 mask recombination caps at 32
    # state bits).  Real-Mosaic only for f32 on TPU: Mosaic has no f64
    # dot lowering (fused._resolve_interpret documents the same
    # constraint), and on CPU the interpreted grid would be far slower
    # than the fused XLA gather — both take the gather form instead
    # (tests/test_direct_rotation.py drives the kernels directly in
    # interpret mode to keep them covered off-TPU).
    rot = (_direct_rotation_pallas if _pl_routable(amps, n)
           else _direct_rotation)

    def body(carry, inp):
        codes, ang = inp
        ang = ang.astype(dt)
        carry = rot(carry, codes, ang, nq, 0, n, conj=False)
        if is_density:
            carry = rot(carry, codes, -ang, nq, nq, n, conj=True)
        return carry, None

    amps, _ = jax.lax.scan(body, amps, (codes_seq, angles))
    return amps


@partial(jax.jit, static_argnames=("num_qubits", "quad"))
def expec_pauli_sum_scan(amps, codes_seq, coeffs, *, num_qubits: int,
                         quad: bool = False):
    """Re <psi| sum_t c_t P_t |psi> as ONE lax.scan over the (T, n)
    Pauli-code table: per term, basis-rotate a COPY of the state so P_t
    becomes a Z-string (the multiRotatePauli trick, QuEST_common.c:424-462
    applied to expectation values), then reduce sum s(idx) |phi|^2 with the
    parity sign fused into the sum.  Compile cost is one term body
    regardless of term count — the unrolled variant took ~100 s to compile
    at 16 terms x 24 qubits.

    ``quad`` (prec 4): the signed per-term norm accumulates in
    double-double (calculations.quad_sum) and the cross-term combine runs
    a Neumaier scan over the emitted term values — the reference's
    QuEST_PREC=4 runs this whole reduction in long double."""
    from . import calculations as _calc

    n = num_qubits
    dt = amps.dtype

    if n > _DIRECT_MAX_N:
        def signed_norm(phi, zlo, zhi):
            s = _parity_sign_dynamic(zlo, zhi, n, dt)
            if quad:
                return _calc.quad_sum2(s * phi[0] * phi[0],
                                       s * phi[1] * phi[1])
            return jnp.sum(s * (phi[0] * phi[0] + phi[1] * phi[1]))

        body = make_expec_term_value(
            dt, n,
            layer=lambda a, mats: _product_layer(a, mats, n),
            signed_norm=signed_norm,
        )(amps)
        total, vals = jax.lax.scan(body, jnp.zeros((), dt),
                                   (codes_seq, coeffs))
        return _calc.neumaier_sum(vals) if quad else total

    # direct form: Re <psi| c_t P_t |psi> = c_t * sum_i (psi_r pr +
    # psi_i pi) with (pr, pi) = P psi — fused flip+sign+reduce Pallas
    # kernel (one HBM pass per term) at block-decomposable sizes; the
    # split-axis gather + reduce otherwise.  Quad keeps the gather form:
    # its channel-split double-double accumulation needs the full
    # product vectors, not f32 block partials.
    use_pl = not quad and _pl_routable(amps, n)

    def body(acc, inp):
        codes, coeff = inp
        if use_pl:
            r = _expec_term_pallas(amps, codes, n)
        else:
            pv, _ = _apply_pauli_traced(amps, codes, n, 0, n, conj=False)
            if quad:
                r = _calc.quad_sum2(amps[0] * pv[0], amps[1] * pv[1])
            else:
                r = jnp.sum(amps[0] * pv[0] + amps[1] * pv[1])
        v = coeff.astype(dt) * r
        return acc + v, v

    total, vals = jax.lax.scan(body, jnp.zeros((), dt),
                               (codes_seq, coeffs))
    return _calc.neumaier_sum(vals) if quad else total


@partial(jax.jit, static_argnames=("num_qubits", "dtype", "sharding"))
def diag_from_z_hamil(zmasks_lo, zmasks_hi, coeffs, *, num_qubits: int,
                      dtype, sharding=None):
    """diag_d = sum_t c_t (-1)^parity(d & zmask_t) entirely ON DEVICE —
    the reference computes this distributed over each node's chunk
    (agnostic_initDiagonalOpFromPauliHamil, QuEST_cpu.c:4188-4227); the
    previous host-numpy version materialised a dense 2^n array per term,
    blowing host memory for exactly the large-n DiagonalOps the type
    exists for.  Scan over the (T,) z-mask table (uint32 lo/hi halves so
    n > 31 stays exact): one compiled body, no host arrays beyond the
    tiny mask/coeff vectors.  ``sharding`` constrains the accumulator so
    the diagonal is built sharded over the mesh rather than on one
    device."""

    def body(acc, inp):
        zlo, zhi, c = inp
        s = _parity_sign_dynamic(zlo, zhi, num_qubits, acc.dtype)
        return acc + c.astype(acc.dtype) * s, None

    acc0 = jnp.zeros((1 << num_qubits,), dtype)
    if sharding is not None:
        acc0 = jax.lax.with_sharding_constraint(acc0, sharding)
    acc, _ = jax.lax.scan(body, acc0, (zmasks_lo, zmasks_hi, coeffs))
    return acc
