"""SoA (structure-of-arrays) complex arithmetic.

The amplitude state is a real array of shape ``(2, ...)`` — channel 0 = real,
channel 1 = imaginary.  This mirrors the reference's ``ComplexArray``
SoA layout (QuEST.h:77: separate real/imag pointers) and is the TPU-native
choice twice over: the last (lane) dimension stays the huge amplitude axis
for full VPU vectorisation, and no complex dtype ever reaches XLA — the TPU
toolchain in this environment does not implement complex element types at
all, and even where it does, explicit real arithmetic gives the compiler
strictly more fusion freedom than decomposed C64.

Host-side helpers convert between NumPy complex and stacked SoA; traced
helpers implement complex multiply / conjugate / abs^2 on stacked arrays.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# Host-side conversions
# ---------------------------------------------------------------------------


def soa(arr, dtype=None) -> np.ndarray:
    """NumPy complex (or real) array -> stacked (2, *shape) real array."""
    a = np.asarray(arr)
    out = np.stack([a.real.astype(np.float64), a.imag.astype(np.float64)])
    if dtype is not None:
        out = out.astype(dtype)
    return out


def unsoa(arr) -> np.ndarray:
    """Stacked (2, *shape) -> NumPy complex."""
    a = np.asarray(arr)
    return a[0] + 1j * a[1]


# ---------------------------------------------------------------------------
# Traced SoA arithmetic (stacked leading channel axis)
# ---------------------------------------------------------------------------


def cmul(s, f_re, f_im):
    """(2, ...) state times a broadcastable complex factor (f_re, f_im)."""
    return jnp.stack(
        [s[0] * f_re - s[1] * f_im, s[0] * f_im + s[1] * f_re]
    )


def cmul_s(a, b):
    """Elementwise product of two stacked arrays."""
    return jnp.stack([a[0] * b[0] - a[1] * b[1], a[0] * b[1] + a[1] * b[0]])


def conj(s):
    if isinstance(s, np.ndarray):
        return np.stack([s[0], -s[1]])
    return jnp.stack([s[0], -s[1]])


def abs2(s):
    """|z|^2, shape = trailing dims."""
    return s[0] * s[0] + s[1] * s[1]


def scale(s, f_re):
    """Real scaling (applies to both channels)."""
    return s * f_re


def vdot(a, b):
    """<a|b> = sum conj(a)*b over all trailing dims -> stacked (2,) scalar."""
    re = jnp.sum(a[0] * b[0] + a[1] * b[1])
    im = jnp.sum(a[0] * b[1] - a[1] * b[0])
    return jnp.stack([re, im])


def real_matrix_rep(m):
    """Stacked matrix (2, D, D) -> real 4-block tensor R[c, d] with
    R[0,0]=Re, R[0,1]=-Im, R[1,0]=Im, R[1,1]=Re, shape (2, 2, D, D):
    complex matvec y = M x becomes the real einsum contraction
    y[c] = sum_d R[c,d] @ x[d] — one MXU-shaped contraction instead of four
    separate real matmuls."""
    return jnp.stack(
        [jnp.stack([m[0], -m[1]]), jnp.stack([m[1], m[0]])]
    )
