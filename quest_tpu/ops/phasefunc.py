"""Phase-function kernels: applyPhaseFunc / applyMultiVarPhaseFunc /
applyNamedPhaseFunc (+Overrides, +Params variants).

Re-implementation of the reference's per-amplitude phase kernels
(QuEST_cpu.c:4228-4564): decode sub-register integers from global amplitude
index bits, evaluate theta(x1..xm), multiply amp by exp(i*theta).  On TPU the
decode is a handful of shift/and ops on a broadcast iota that XLA fuses with
the complex multiply into one HBM sweep — phase functions are the single
best-suited op family for this hardware (pure elementwise, zero
communication under sharding: "embarrassingly parallel", QuEST_cpu.c:4414).

Phase-function name codes match ``enum phaseFunc`` (QuEST.h:231-234).
Divergence parameters and override matching follow
statevec_applyParamNamedPhaseFuncOverrides (QuEST_cpu.c:4406-4564) exactly.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from . import cplx
from ..utils import bits

# enum phaseFunc (QuEST.h:231-234)
NORM = 0
SCALED_NORM = 1
INVERSE_NORM = 2
SCALED_INVERSE_NORM = 3
SCALED_INVERSE_SHIFTED_NORM = 4
PRODUCT = 5
SCALED_PRODUCT = 6
INVERSE_PRODUCT = 7
SCALED_INVERSE_PRODUCT = 8
DISTANCE = 9
SCALED_DISTANCE = 10
INVERSE_DISTANCE = 11
SCALED_INVERSE_DISTANCE = 12
SCALED_INVERSE_SHIFTED_DISTANCE = 13

UNSIGNED = 0
TWOS_COMPLEMENT = 1

_NORM_FUNCS = (NORM, SCALED_NORM, INVERSE_NORM, SCALED_INVERSE_NORM,
               SCALED_INVERSE_SHIFTED_NORM)
_PROD_FUNCS = (PRODUCT, SCALED_PRODUCT, INVERSE_PRODUCT, SCALED_INVERSE_PRODUCT)
_DIST_FUNCS = (DISTANCE, SCALED_DISTANCE, INVERSE_DISTANCE,
               SCALED_INVERSE_DISTANCE, SCALED_INVERSE_SHIFTED_DISTANCE)


def _index_dtype(num_qubits: int):
    return jnp.int64 if num_qubits > 31 else jnp.int32


def _phase_inds(num_amps: int, reg_qubits, encoding: int, idx_dtype):
    """Per-register decoded integer arrays, shape (num_regs, num_amps)."""
    idx = bits.index_iota(num_amps, idx_dtype)
    return [
        bits.decode_subregister(idx, qs, encoding == TWOS_COMPLEMENT)
        for qs in reg_qubits
    ]


def _apply_overrides(phase, inds, override_inds, override_phases):
    """First-match-wins override scan (QuEST_cpu.c:4464-4480): iterate in
    reverse so earlier entries overwrite later ones."""
    num_overrides = override_inds.shape[0]
    for i in range(num_overrides - 1, -1, -1):
        match = jnp.ones(phase.shape, dtype=bool)
        for r, ind_arr in enumerate(inds):
            match = match & (ind_arr == override_inds[i, r])
        phase = jnp.where(match, override_phases[i], phase)
    return phase


def _mul_phase(amps, phase, conj: bool):
    """amp *= exp(i*phase) on the SoA state — explicit cos/sin, exactly the
    reference's update (QuEST_cpu.c:4552-4562)."""
    if conj:
        phase = -phase
    return cplx.cmul(amps, jnp.cos(phase), jnp.sin(phase))


@partial(
    jax.jit,
    static_argnames=("num_qubits", "reg_qubits", "encoding", "func_name", "conj"),
    donate_argnums=0,
)
def apply_named_phase_func(
    amps,
    params,
    override_inds,
    override_phases,
    *,
    num_qubits: int,
    reg_qubits: Tuple[Tuple[int, ...], ...],
    encoding: int,
    func_name: int,
    conj: bool = False,
):
    num_amps = amps.shape[1]
    idt = _index_dtype(num_qubits)
    inds = _phase_inds(num_amps, reg_qubits, encoding, idt)
    rdt = amps.dtype
    params = jnp.asarray(params, rdt)
    find = [x.astype(rdt) for x in inds]
    num_regs = len(reg_qubits)

    if func_name in _NORM_FUNCS:
        acc = jnp.zeros((num_amps,), rdt)
        for r in range(num_regs):
            x = find[r]
            if func_name == SCALED_INVERSE_SHIFTED_NORM:
                x = x - params[2 + r]
            acc = acc + x * x
        val = jnp.sqrt(acc)
        if func_name == NORM:
            phase = val
        elif func_name == INVERSE_NORM:
            phase = jnp.where(val == 0, params[0], 1 / jnp.where(val == 0, 1, val))
        elif func_name == SCALED_NORM:
            phase = params[0] * val
        else:  # SCALED_INVERSE_NORM, SCALED_INVERSE_SHIFTED_NORM
            phase = jnp.where(val == 0, params[1], params[0] / jnp.where(val == 0, 1, val))
    elif func_name in _PROD_FUNCS:
        prod = jnp.ones((num_amps,), rdt)
        for r in range(num_regs):
            prod = prod * find[r]
        if func_name == PRODUCT:
            phase = prod
        elif func_name == INVERSE_PRODUCT:
            phase = jnp.where(prod == 0, params[0], 1 / jnp.where(prod == 0, 1, prod))
        elif func_name == SCALED_PRODUCT:
            phase = params[0] * prod
        else:
            phase = jnp.where(prod == 0, params[1], params[0] / jnp.where(prod == 0, 1, prod))
    elif func_name in _DIST_FUNCS:
        acc = jnp.zeros((num_amps,), rdt)
        for r in range(0, num_regs, 2):
            d = find[r + 1] - find[r]
            if func_name == SCALED_INVERSE_SHIFTED_DISTANCE:
                d = d - params[2 + r // 2]
            acc = acc + d * d
        val = jnp.sqrt(acc)
        if func_name == DISTANCE:
            phase = val
        elif func_name == INVERSE_DISTANCE:
            phase = jnp.where(val == 0, params[0], 1 / jnp.where(val == 0, 1, val))
        elif func_name == SCALED_DISTANCE:
            phase = params[0] * val
        else:
            phase = jnp.where(val == 0, params[1], params[0] / jnp.where(val == 0, 1, val))
    else:
        raise ValueError(f"unknown phase function {func_name}")

    phase = _apply_overrides(phase, inds, override_inds, override_phases)
    return _mul_phase(amps, phase, conj)


@partial(
    jax.jit,
    static_argnames=("num_qubits", "reg_qubits", "encoding", "terms_per_reg", "conj"),
    donate_argnums=0,
)
def apply_multi_var_phase_func(
    amps,
    coeffs,
    exponents,
    override_inds,
    override_phases,
    *,
    num_qubits: int,
    reg_qubits: Tuple[Tuple[int, ...], ...],
    encoding: int,
    terms_per_reg: Tuple[int, ...],
    conj: bool = False,
):
    """theta = sum_r sum_t coeff_{r,t} * x_r^exp_{r,t}
    (statevec_applyMultiVarPhaseFuncOverrides, QuEST_cpu.c:4305-4404).
    ``coeffs``/``exponents`` are flat over registers (reference layout)."""
    num_amps = amps.shape[1]
    idt = _index_dtype(num_qubits)
    inds = _phase_inds(num_amps, reg_qubits, encoding, idt)
    rdt = amps.dtype
    coeffs = jnp.asarray(coeffs, rdt)
    exponents = jnp.asarray(exponents, rdt)

    phase = jnp.zeros((num_amps,), rdt)
    flat = 0
    for r in range(len(reg_qubits)):
        x = inds[r].astype(rdt)
        for _ in range(terms_per_reg[r]):
            phase = phase + coeffs[flat] * jnp.power(x, exponents[flat])
            flat += 1
    phase = _apply_overrides(phase, inds, override_inds, override_phases)
    return _mul_phase(amps, phase, conj)


@partial(
    jax.jit,
    static_argnames=("num_qubits", "qubits", "encoding", "conj"),
    donate_argnums=0,
)
def apply_phase_func(
    amps,
    coeffs,
    exponents,
    override_inds,
    override_phases,
    *,
    num_qubits: int,
    qubits: Tuple[int, ...],
    encoding: int,
    conj: bool = False,
):
    """Single-register polynomial theta(x) = sum_i c_i x^{e_i}
    (statevec_applyPhaseFuncOverrides, QuEST_cpu.c:4228-4303)."""
    num_amps = amps.shape[1]
    idt = _index_dtype(num_qubits)
    idx = bits.index_iota(num_amps, idt)
    ind = bits.decode_subregister(idx, qubits, encoding == TWOS_COMPLEMENT)
    rdt = amps.dtype
    coeffs = jnp.asarray(coeffs, rdt)
    exponents = jnp.asarray(exponents, rdt)
    x = ind.astype(rdt)
    phase = jnp.zeros((num_amps,), rdt)
    for i in range(coeffs.shape[0]):
        phase = phase + coeffs[i] * jnp.power(x, exponents[i])
    phase = _apply_overrides(phase, [ind], override_inds, override_phases)
    return _mul_phase(amps, phase, conj)
