"""Reduction kernels: probabilities, inner products, purity, fidelity.

TPU-native re-implementation of the reference's ``calc*`` kernels
(QuEST_cpu.c:3363-3645 OpenMP reductions; QuEST_gpu.cu:1930-2146 two-level
shared-memory tree reductions).  Every reduction is a single fused XLA
reduce over the SoA state (see ops/cplx.py); under a sharded mesh the same
code lowers to per-shard partial sums plus one ``psum`` over ICI (the
analogue of the reference's MPI_Allreduce, QuEST_cpu_distributed.c:35-117).

Complex results return as stacked (2,) arrays; the API layer converts.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from . import cplx


def _axis(n: int, q: int) -> int:
    return 1 + (n - 1 - q)


@jax.jit
def calc_total_prob_statevec(amps):
    """Sum of |amp|^2 (reference uses Kahan summation, QuEST_cpu_local.c:118;
    a single XLA reduce is at least as accurate at f64, and the f32 TPU path
    accumulates in f32 vector lanes like the reference's OpenMP loop)."""
    return jnp.sum(cplx.abs2(amps))


def _diag(amps, num_qubits: int):
    """Diagonal of the column-major flattened rho: (2, dim) stacked."""
    dim = 1 << num_qubits
    mat = amps.reshape(2, dim, dim)  # [channel, col, row]
    return jnp.diagonal(mat, axis1=1, axis2=2)


@partial(jax.jit, static_argnames=("num_qubits",))
def calc_total_prob_density(amps, *, num_qubits: int):
    """Re(trace(rho)) (densmatr_calcTotalProb,
    QuEST_cpu_distributed.c:53-86)."""
    return jnp.sum(_diag(amps, num_qubits)[0])


@partial(jax.jit, static_argnames=("num_qubits", "target", "outcome"))
def calc_prob_of_outcome_statevec(amps, *, num_qubits: int, target: int, outcome: int):
    """(statevec_calcProbOfOutcome, QuEST_cpu.c:3418-3508)."""
    n = num_qubits
    view = amps.reshape((2,) + (2,) * n)
    sel = [slice(None)] * (n + 1)
    sel[_axis(n, target)] = outcome
    return jnp.sum(cplx.abs2(view[tuple(sel)]))


@partial(jax.jit, static_argnames=("num_qubits", "target", "outcome"))
def calc_prob_of_outcome_density(amps, *, num_qubits: int, target: int, outcome: int):
    """Sum of diagonal rho elements whose target bit equals outcome
    (densmatr_calcProbOfOutcome via findProbabilityOfZero,
    QuEST_cpu.c:3363-3417)."""
    n = num_qubits
    diag_re = _diag(amps, num_qubits)[0].reshape((2,) * n)
    sel = [slice(None)] * n
    sel[n - 1 - target] = outcome
    return jnp.sum(diag_re[tuple(sel)])


@partial(jax.jit, static_argnames=("num_qubits", "qubits"))
def calc_prob_of_all_outcomes_statevec(amps, *, num_qubits: int, qubits: Tuple[int, ...]):
    """2^k-outcome histogram; outcome index bit j <-> qubits[j]
    (calcProbOfAllOutcomes, QuEST_cpu.c:3510-3574 — the reference builds it
    with an omp-atomic scatter; a transpose+reduce is the vectorized form)."""
    n = num_qubits
    k = len(qubits)
    probs = cplx.abs2(amps).reshape((2,) * n)
    axes = tuple(n - 1 - q for q in reversed(qubits))
    moved = jnp.moveaxis(probs, axes, range(k))
    return jnp.sum(moved.reshape(2 ** k, -1), axis=1)


@partial(jax.jit, static_argnames=("num_qubits", "qubits"))
def calc_prob_of_all_outcomes_density(amps, *, num_qubits: int, qubits: Tuple[int, ...]):
    n = num_qubits
    k = len(qubits)
    diag_re = _diag(amps, num_qubits)[0].reshape((2,) * n)
    axes = tuple(n - 1 - q for q in reversed(qubits))
    moved = jnp.moveaxis(diag_re, axes, range(k))
    return jnp.sum(moved.reshape(2 ** k, -1), axis=1)


@jax.jit
def calc_inner_product(bra_amps, ket_amps):
    """<bra|ket> -> stacked (2,) (statevec_calcInnerProductLocal,
    QuEST_cpu.c:1071)."""
    return cplx.vdot(bra_amps, ket_amps)


@jax.jit
def calc_density_inner_product(rho1_amps, rho2_amps):
    """Tr(rho1^dagger rho2) real part (densmatr_calcInnerProductLocal,
    QuEST_cpu.c:958)."""
    return jnp.sum(rho1_amps[0] * rho2_amps[0] + rho1_amps[1] * rho2_amps[1])


@jax.jit
def calc_purity(rho_amps):
    """Tr(rho^2) = sum |rho_rc|^2 for Hermitian rho (calcPurityLocal,
    QuEST_cpu.c:861)."""
    return jnp.sum(cplx.abs2(rho_amps))


@partial(jax.jit, static_argnames=("num_qubits",))
def calc_fidelity_density(rho_amps, psi_amps, *, num_qubits: int):
    """<psi|rho|psi> (densmatr_calcFidelityLocal, QuEST_cpu.c:990)."""
    dim = 1 << num_qubits
    m = rho_amps.reshape(2, dim, dim)  # [channel, col, row]; m[., c, r] = rho_{r,c}
    p0, p1 = psi_amps[0], psi_amps[1]
    hi = jax.lax.Precision.HIGHEST
    # v_c = sum_r rho_{r,c} conj(psi_r)
    v_re = jnp.matmul(m[0], p0, precision=hi) + jnp.matmul(m[1], p1, precision=hi)
    v_im = jnp.matmul(m[1], p0, precision=hi) - jnp.matmul(m[0], p1, precision=hi)
    # Re( sum_c psi_c v_c )
    return jnp.sum(p0 * v_re - p1 * v_im)


@jax.jit
def calc_hilbert_schmidt_distance(rho1_amps, rho2_amps):
    """sqrt(sum |rho1-rho2|^2) (calcHilbertSchmidtDistanceSquaredLocal,
    QuEST_cpu.c:923)."""
    return jnp.sqrt(jnp.sum(cplx.abs2(rho1_amps - rho2_amps)))


@jax.jit
def calc_expec_diagonal_statevec(amps, op_real, op_imag):
    """sum_i |amp_i|^2 d_i -> stacked (2,) (statevec_calcExpecDiagonalOp,
    QuEST_cpu.c:4094-4126)."""
    p = cplx.abs2(amps)
    return jnp.stack([jnp.sum(p * op_real), jnp.sum(p * op_imag)])


@partial(jax.jit, static_argnames=("num_qubits",))
def calc_expec_diagonal_density(amps, op_real, op_imag, *, num_qubits: int):
    """sum_r d_r rho_rr -> stacked (2,) — diagonal elements are node-local by
    construction in the reference (densmatr_calcExpecDiagonalOp,
    QuEST_cpu.c:4127-4186)."""
    d = _diag(amps, num_qubits)
    re = jnp.sum(d[0] * op_real - d[1] * op_imag)
    im = jnp.sum(d[0] * op_imag + d[1] * op_real)
    return jnp.stack([re, im])
