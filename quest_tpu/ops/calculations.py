"""Reduction kernels: probabilities, inner products, purity, fidelity.

TPU-native re-implementation of the reference's ``calc*`` kernels
(QuEST_cpu.c:3363-3645 OpenMP reductions; QuEST_gpu.cu:1930-2146 two-level
shared-memory tree reductions).  Every reduction is a single fused XLA
reduce over the SoA state (see ops/cplx.py); under a sharded mesh the same
code lowers to per-shard partial sums plus one ``psum`` over ICI (the
analogue of the reference's MPI_Allreduce, QuEST_cpu_distributed.c:35-117).

Complex results return as stacked (2,) arrays; the API layer converts.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import cplx


def _axis(n: int, q: int) -> int:
    return 1 + (n - 1 - q)


@jax.jit
def calc_total_prob_statevec(amps):
    """Sum of |amp|^2 (reference uses Kahan summation, QuEST_cpu_local.c:118;
    a single XLA reduce is at least as accurate at f64, and the f32 TPU path
    accumulates in f32 vector lanes like the reference's OpenMP loop)."""
    return jnp.sum(cplx.abs2(amps))


# ---------------------------------------------------------------------------
# Quad-precision (QuEST_PREC=4) reductions: double-double accumulation
# ---------------------------------------------------------------------------

_QUAD_BLOCK = 256


def neumaier_sum(vals):
    """Neumaier error-free-transform scan over a 1-D vector: the serial
    double-double combine used on block partials (quad_sum) and on small
    signed sequences (per-term expectation contributions)."""

    def body(carry, v):
        s, c = carry
        t = s + v
        c = c + jnp.where(jnp.abs(s) >= jnp.abs(v),
                          (s - t) + v, (v - t) + s)
        return (t, c), None

    z = jnp.zeros((), vals.dtype)
    (s, c), _ = jax.lax.scan(body, (z, z), vals)
    return s + c


def quad_sum2(x, y):
    """Channel-split compensated sum: quad_sum(x) + quad_sum(y).

    THE invariant for every two-channel quad reduction (inner products,
    norms, signed expectation summands): the two product grids enter
    SEPARATE compensated sums — a per-element f64 pre-add of x + y
    would round the smaller channel's contribution away before
    compensation ever sees it (the failure class the prec-4 contract
    exists to prevent)."""
    return quad_sum(x) + quad_sum(y)


def quad_sum(x):
    """Double-double-compensated sum of a vector — the quad-precision
    (QuEST_PREC=4, QuEST_precision.h:55-68) accumulation mode for the
    reductions where extended precision is observable.  Pairwise block
    partials (XLA tree reduce, error eps*log B within a block) are
    combined with a Neumaier error-free-transform scan, so cross-block
    cancellation and magnitude disparity accumulate at double-double
    precision instead of f64."""
    flat = x.reshape(-1)
    nb = max(1, flat.size // _QUAD_BLOCK)
    partials = flat.reshape(nb, -1).sum(axis=1)
    # cap the serial compensated scan at _QUAD_BLOCK steps: a second
    # pairwise level costs only eps*log(B) within each super-block while
    # keeping the scan O(256) instead of O(size/256) (a 26q state would
    # otherwise be a 262k-step scalar chain)
    if nb > _QUAD_BLOCK:
        partials = partials.reshape(_QUAD_BLOCK, -1).sum(axis=1)
    return neumaier_sum(partials)


@jax.jit
def calc_total_prob_statevec_quad(amps):
    return quad_sum2(amps[0] * amps[0], amps[1] * amps[1])


@partial(jax.jit, static_argnames=("num_qubits",))
def calc_total_prob_density_quad(amps, *, num_qubits: int):
    return quad_sum(_diag(amps, num_qubits)[0])


@jax.jit
def calc_inner_product_quad(bra_amps, ket_amps):
    """<bra|ket> with double-double accumulation (signed terms — the
    case where cross-block cancellation actually bites)."""
    br, bi = bra_amps[0], bra_amps[1]
    kr, ki = ket_amps[0], ket_amps[1]
    re = quad_sum2(br * kr, bi * ki)
    im = quad_sum2(br * ki, -(bi * kr))
    return jnp.stack([re, im])


# The remaining observable reductions take a static ``quad`` flag
# selecting the double-double reducer — ONE kernel body per family, so
# the prec-4 path cannot diverge from the plain one.  The reference's
# QuEST_PREC=4 makes EVERY calc* accumulate in long double
# (QuEST_precision.h:55-68; QuEST_cpu.c:861-1071, 3363-3645).


def _diag(amps, num_qubits: int):
    """Diagonal of the column-major flattened rho: (2, dim) stacked."""
    dim = 1 << num_qubits
    mat = amps.reshape(2, dim, dim)  # [channel, col, row]
    return jnp.diagonal(mat, axis1=1, axis2=2)


@partial(jax.jit, static_argnames=("num_qubits",))
def calc_total_prob_density(amps, *, num_qubits: int):
    """Re(trace(rho)) (densmatr_calcTotalProb,
    QuEST_cpu_distributed.c:53-86)."""
    return jnp.sum(_diag(amps, num_qubits)[0])


@partial(jax.jit, static_argnames=("num_qubits", "target", "outcome",
                                   "quad"))
def calc_prob_of_outcome_statevec(amps, *, num_qubits: int, target: int,
                                  outcome: int, quad: bool = False):
    """(statevec_calcProbOfOutcome, QuEST_cpu.c:3418-3508)."""
    from .kernels import bit_indicator_2d

    n = num_qubits
    ind = bit_indicator_2d(n, ((target, outcome),), amps.dtype)
    view = amps.reshape(2, ind.shape[0], ind.shape[1])
    if quad:
        return quad_sum2(view[0] * view[0] * ind, view[1] * view[1] * ind)
    return jnp.sum(cplx.abs2(view) * ind)


@partial(jax.jit, static_argnames=("num_qubits", "target", "outcome",
                                   "quad"))
def calc_prob_of_outcome_density(amps, *, num_qubits: int, target: int,
                                 outcome: int, quad: bool = False):
    """Sum of diagonal rho elements whose target bit equals outcome
    (densmatr_calcProbOfOutcome via findProbabilityOfZero,
    QuEST_cpu.c:3363-3417)."""
    from .kernels import bit_indicator_2d

    n = num_qubits
    diag_re = _diag(amps, num_qubits)[0]
    ind = bit_indicator_2d(n, ((target, outcome),), amps.dtype)
    red = quad_sum if quad else jnp.sum
    return red(diag_re.reshape(ind.shape) * ind)


def _outcome_histogram(vals, n: int, qubits: Tuple[int, ...]):
    """sum vals over amps grouped by the bits of ``qubits`` (outcome index
    bit j <-> qubits[j]): hist = A_hi^T (V A_lo) with {0,1} indicator
    matrices built from iotas — two MXU matmuls, no scatter (the reference
    uses an omp-atomic scatter, QuEST_cpu.c:3510-3574) and no small-minor
    reshape."""
    from ..utils import bits as bits_mod
    from .kernels import _split2

    k = len(qubits)
    hi, lo = _split2(n)
    qlo = [q for q in qubits if q < lo]
    qhi = [q for q in qubits if q >= lo]
    ilo = jax.lax.iota(jnp.int32, 1 << lo)
    ihi = jax.lax.iota(jnp.int32, 1 << hi)

    def onehot(iota, qs, offset):
        """(len(iota), 2^len(qs)) {0,1} indicator of the qs bit pattern."""
        code = jnp.zeros_like(iota)
        for j, q in enumerate(qs):
            code = code + (bits_mod.bits_of(iota, q - offset) << j)
        return (code[:, None] == jnp.arange(1 << len(qs))[None, :]).astype(vals.dtype)

    a_lo = onehot(ilo, qlo, 0)          # (2^lo, 2^kl)
    a_hi = onehot(ihi, qhi, lo)         # (2^hi, 2^kh)
    v = vals.reshape(1 << hi, 1 << lo)
    inner = jnp.matmul(v, a_lo, precision=jax.lax.Precision.HIGHEST)
    hist2 = jnp.matmul(a_hi.T, inner,
                       precision=jax.lax.Precision.HIGHEST)  # (2^kh, 2^kl)
    # hist2[ch, cl]: ch bit j <-> qhi[j], cl bit j <-> qlo[j]; remap to the
    # outcome convention (bit j <-> qubits[j]) with a tiny static gather.
    hist_flat = hist2.reshape(-1)  # index = ch * 2^kl + cl
    res = np.zeros(1 << k, dtype=np.int64)
    for o in range(1 << k):
        ch = 0
        cl = 0
        for j, q in enumerate(qubits):
            bitv = (o >> j) & 1
            if q < lo:
                cl |= bitv << qlo.index(q)
            else:
                ch |= bitv << qhi.index(q)
        res[o] = ch * (1 << len(qlo)) + cl
    return hist_flat[jnp.asarray(res)]


@partial(jax.jit, static_argnames=("num_qubits", "qubits"))
def calc_prob_of_all_outcomes_statevec(amps, *, num_qubits: int, qubits: Tuple[int, ...]):
    """2^k-outcome histogram; outcome index bit j <-> qubits[j]
    (calcProbOfAllOutcomes, QuEST_cpu.c:3510-3574 — the reference builds it
    with an omp-atomic scatter; a reshape+reduce is the vectorized form)."""
    return _outcome_histogram(cplx.abs2(amps), num_qubits, qubits)


@partial(jax.jit, static_argnames=("num_qubits", "qubits"))
def calc_prob_of_all_outcomes_density(amps, *, num_qubits: int, qubits: Tuple[int, ...]):
    return _outcome_histogram(_diag(amps, num_qubits)[0], num_qubits, qubits)


@jax.jit
def calc_inner_product(bra_amps, ket_amps):
    """<bra|ket> -> stacked (2,) (statevec_calcInnerProductLocal,
    QuEST_cpu.c:1071)."""
    return cplx.vdot(bra_amps, ket_amps)


@partial(jax.jit, static_argnames=("quad",))
def calc_density_inner_product(rho1_amps, rho2_amps, *, quad: bool = False):
    """Tr(rho1^dagger rho2) real part (densmatr_calcInnerProductLocal,
    QuEST_cpu.c:958)."""
    if quad:
        return quad_sum2(rho1_amps[0] * rho2_amps[0],
                         rho1_amps[1] * rho2_amps[1])
    return jnp.sum(rho1_amps[0] * rho2_amps[0] + rho1_amps[1] * rho2_amps[1])


@partial(jax.jit, static_argnames=("quad",))
def calc_purity(rho_amps, *, quad: bool = False):
    """Tr(rho^2) = sum |rho_rc|^2 for Hermitian rho (calcPurityLocal,
    QuEST_cpu.c:861)."""
    if quad:
        return quad_sum2(rho_amps[0] * rho_amps[0],
                         rho_amps[1] * rho_amps[1])
    return jnp.sum(cplx.abs2(rho_amps))


@partial(jax.jit, static_argnames=("num_qubits", "quad"))
def calc_fidelity_density(rho_amps, psi_amps, *, num_qubits: int,
                          quad: bool = False):
    """<psi|rho|psi> (densmatr_calcFidelityLocal, QuEST_cpu.c:990).

    Quad switches to the fully elementwise form: w_{rc} =
    Re[conj(psi_r) rho_{rc} psi_c] quad-summed over ALL dim^2 terms, so
    the signed cross terms see double-double accumulation end-to-end
    (the matmul form would round the inner contraction at f64)."""
    dim = 1 << num_qubits
    m = rho_amps.reshape(2, dim, dim)  # [channel, col, row]; m[., c, r] = rho_{r,c}
    p0, p1 = psi_amps[0], psi_amps[1]
    if quad:
        # conj(psi_r) psi_c = A[c,r] + i B[c,r]
        a = p0[:, None] * p0[None, :] + p1[:, None] * p1[None, :]
        b = p1[:, None] * p0[None, :] - p0[:, None] * p1[None, :]
        return quad_sum2(m[0] * a, -(m[1] * b))
    hi = jax.lax.Precision.HIGHEST
    # v_c = sum_r rho_{r,c} conj(psi_r)
    v_re = jnp.matmul(m[0], p0, precision=hi) + jnp.matmul(m[1], p1, precision=hi)
    v_im = jnp.matmul(m[1], p0, precision=hi) - jnp.matmul(m[0], p1, precision=hi)
    # Re( sum_c psi_c v_c )
    return jnp.sum(p0 * v_re - p1 * v_im)


@partial(jax.jit, static_argnames=("quad",))
def calc_hilbert_schmidt_distance(rho1_amps, rho2_amps, *,
                                  quad: bool = False):
    """sqrt(sum |rho1-rho2|^2) (calcHilbertSchmidtDistanceSquaredLocal,
    QuEST_cpu.c:923)."""
    d = rho1_amps - rho2_amps
    if quad:
        return jnp.sqrt(quad_sum2(d[0] * d[0], d[1] * d[1]))
    return jnp.sqrt(jnp.sum(cplx.abs2(d)))


@partial(jax.jit, static_argnames=("quad",))
def calc_expec_diagonal_statevec(amps, op_real, op_imag, *,
                                 quad: bool = False):
    """sum_i |amp_i|^2 d_i -> stacked (2,) (statevec_calcExpecDiagonalOp,
    QuEST_cpu.c:4094-4126)."""
    if quad:
        sq0, sq1 = amps[0] * amps[0], amps[1] * amps[1]
        return jnp.stack(
            [quad_sum2(sq0 * op_real, sq1 * op_real),
             quad_sum2(sq0 * op_imag, sq1 * op_imag)])
    p = cplx.abs2(amps)
    return jnp.stack([jnp.sum(p * op_real), jnp.sum(p * op_imag)])


@partial(jax.jit, static_argnames=("num_qubits", "quad"))
def calc_expec_diagonal_density(amps, op_real, op_imag, *, num_qubits: int,
                                quad: bool = False):
    """sum_r d_r rho_rr -> stacked (2,) — diagonal elements are node-local by
    construction in the reference (densmatr_calcExpecDiagonalOp,
    QuEST_cpu.c:4127-4186)."""
    d = _diag(amps, num_qubits)
    if quad:
        return jnp.stack(
            [quad_sum2(d[0] * op_real, -(d[1] * op_imag)),
             quad_sum2(d[0] * op_imag, d[1] * op_real)])
    re = jnp.sum(d[0] * op_real - d[1] * op_imag)
    im = jnp.sum(d[0] * op_imag + d[1] * op_real)
    return jnp.stack([re, im])
