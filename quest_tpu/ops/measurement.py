"""Fused measurement: prob -> threshold -> conditional collapse, ONE program.

The reference's measure is a host loop: a full-state probability reduce,
a host Mersenne-Twister draw, then a collapse sweep
(statevec_measureWithStats, QuEST_common.c:374-380; the outcome draw
generateMeasurementOutcome, :168-183) — two dispatches and two
device->host syncs per shot.  Here the threshold draw happens ON DEVICE
from a jax.random key (the key is replicated to every shard, preserving
the reference's same-outcome-on-all-ranks semantics — it broadcasts the
MT seed instead, QuEST_cpu_distributed.c:1384-1395), the outcome is a
traced scalar, and the collapse is an elementwise multiply conditioned
on it: ONE dispatch per shot (measure_fused), or one dispatch for a
whole measurement sequence (measure_sequence — all 26 qubits of a
config-2-sized register in a single program).

The host-MT path stays available for reference-seeded stream parity:
QT_HOST_MEASURE=1 (or QT_STRICT_VALIDATION=1) routes measure through
the original calcProb -> host RNG -> collapse sequence.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from ..precision import real_eps


def host_path_enabled() -> bool:
    """Route measure through the host Mersenne-Twister path (the
    reference's exact sampling stream) instead of the fused device
    program."""
    from .. import validation as V

    return os.environ.get("QT_HOST_MEASURE") == "1" or V.strict_parity()


class _KeyState:
    """Global measurement key + shot counter.  Seeded alongside the host
    MT by seedQuEST (env.seed_quest) so device-side outcome streams are
    deterministic per seed; the counter is folded into the key per shot,
    so no per-shot host split (and no recompile — the shot index enters
    the program as a traced scalar)."""

    __slots__ = ("key", "counter")

    def __init__(self):
        self.key = None
        self.counter = 0

    def seed(self, seeds) -> None:
        key = jax.random.PRNGKey(int(seeds[0]) & 0xFFFFFFFF if seeds else 0)
        for s in seeds[1:]:
            key = jax.random.fold_in(key, int(s) & 0xFFFFFFFF)
        self.key = key
        self.counter = 0

    def next_shots(self, count: int = 1) -> Tuple[object, int]:
        """(key, first shot index) reserving ``count`` consecutive shot
        indices."""
        if self.key is None:
            from ..rng import GLOBAL_RNG

            self.seed(GLOBAL_RNG._keys)
        shot = self.counter
        self.counter += count
        return self.key, shot

    # -- state round-trip (resumable execution, resilience.py) --

    def get_state(self) -> dict:
        """JSON-serializable (key, shot counter) snapshot so the
        device-side outcome stream resumes exactly where it left off."""
        key = None
        if self.key is not None:
            import numpy as np

            raw = jax.random.key_data(self.key) \
                if jnp.issubdtype(self.key.dtype, jax.dtypes.prng_key) \
                else self.key
            key = [int(x) for x in np.asarray(raw).ravel()]
        return {"key": key, "counter": int(self.counter)}

    def set_state(self, state: dict) -> None:
        import numpy as np

        data = state.get("key")
        self.key = None if data is None else jnp.asarray(
            np.array(data, dtype=np.uint32))
        self.counter = int(state.get("counter", 0))


KEYS = _KeyState()


def _bit_factor(n: int, pos: int, outcome, dtype):
    """Indicator of (index bit ``pos`` == TRACED ``outcome``) as a factor
    broadcastable over the (2, 2^hi, 2^lo) state view, plus the axis it
    applies to (iota-built, fuses into the consuming multiply like
    kernels.bit_indicator_2d, whose outcome is static)."""
    from ..utils import bits as bits_mod
    from .kernels import _split2

    hi, lo = _split2(n)
    if pos < lo:
        i = jax.lax.iota(jnp.int32, 1 << lo)
        return (bits_mod.bits_of(i, pos) == outcome).astype(dtype)[
            None, None, :]
    i = jax.lax.iota(jnp.int32, 1 << hi)
    return (bits_mod.bits_of(i, pos - lo) == outcome).astype(dtype)[
        None, :, None]


def _collapse_traced_sv(amps, n: int, target: int, outcome, prob):
    """Zero the discarded half, scale the kept half by 1/sqrt(prob), with
    a TRACED outcome/prob (statevec_collapseToKnownProbOutcomeLocal,
    QuEST_cpu.c:3727-3815)."""
    from .kernels import _split2

    hi, lo = _split2(n)
    dt = amps.dtype
    v = amps.reshape(2, 1 << hi, 1 << lo)
    scale = jax.lax.rsqrt(jnp.asarray(prob, dt))
    ind = _bit_factor(n, target, outcome, dt)
    return (v * (ind * scale)).reshape(amps.shape)


def _collapse_traced_dm(amps, nq: int, target: int, outcome, prob):
    """Zero all rho elements whose ket or bra target bit differs from the
    TRACED outcome, renormalise by 1/prob
    (densmatr_collapseToKnownProbOutcome, QuEST_cpu.c:785-860)."""
    from .kernels import _split2

    n = 2 * nq
    hi, lo = _split2(n)
    dt = amps.dtype
    v = amps.reshape(2, 1 << hi, 1 << lo)
    scale = 1.0 / jnp.asarray(prob, dt)
    ket = _bit_factor(n, target, outcome, dt)
    bra = _bit_factor(n, target + nq, outcome, dt)
    return (v * (ket * scale) * bra).reshape(amps.shape)


def _draw_outcome(p0, key, shot, dt):
    """Traced generateMeasurementOutcome (QuEST_common.c:168-183):
    degenerate probabilities short-circuit; otherwise threshold a
    device-drawn uniform against p0 (u <= p0 -> outcome 0, matching the
    host path's comparison direction)."""
    eps = real_eps()
    u = jax.random.uniform(jax.random.fold_in(key, shot), dtype=dt)
    outcome = jnp.where(
        p0 < eps, 1,
        jnp.where(1 - p0 < eps, 0, jnp.where(u <= p0, 0, 1))
    ).astype(jnp.int32)
    prob = jnp.where(outcome == 0, p0, 1 - p0).astype(dt)
    return outcome, prob


def _measure_once(amps, key, shot, num_qubits: int, target: int,
                  is_density: bool, quad: bool = False):
    from . import calculations as C

    dt = amps.dtype
    if is_density:
        p0 = C.calc_prob_of_outcome_density(
            amps, num_qubits=num_qubits, target=target, outcome=0,
            quad=quad)
    else:
        p0 = C.calc_prob_of_outcome_statevec(
            amps, num_qubits=num_qubits, target=target, outcome=0,
            quad=quad)
    outcome, prob = _draw_outcome(p0, key, shot, dt)
    if is_density:
        amps = _collapse_traced_dm(amps, num_qubits, target, outcome, prob)
    else:
        amps = _collapse_traced_sv(amps, num_qubits, target, outcome, prob)
    return amps, outcome, prob


@partial(jax.jit,
         static_argnames=("num_qubits", "target", "is_density", "quad"),
         donate_argnums=0)
def measure_fused(amps, key, shot, *, num_qubits: int, target: int,
                  is_density: bool, quad: bool = False):
    """One measurement shot as one compiled program: probability reduce,
    on-device threshold draw, conditional collapse.  Returns
    (new_amps, outcome int32, outcome probability).  ``num_qubits`` is
    the REPRESENTED count (state bits = 2x for a density matrix).
    ``quad`` (prec 4) runs the probability reduce in double-double, so
    the fused path honours the same accumulation contract as
    calcProbOfOutcome."""
    return _measure_once(amps, key, shot, num_qubits, target, is_density,
                         quad)


@partial(jax.jit,
         static_argnames=("num_qubits", "targets", "is_density", "quad"),
         donate_argnums=0)
def measure_sequence(amps, key, shot, *, num_qubits: int,
                     targets: Tuple[int, ...], is_density: bool,
                     quad: bool = False):
    """Measure a SEQUENCE of qubits in one compiled program — each step
    collapses before the next qubit's probability is computed, exactly as
    a loop of measure() calls would, but with a single dispatch for the
    whole sequence (the reference has no analogue; its measure is
    irreducibly one host round-trip per qubit).  Shot indices
    shot..shot+len(targets)-1 are consumed, so outcome streams match a
    loop of measure_fused calls."""
    outs, probs = [], []
    for j, t in enumerate(targets):
        amps, o, p = _measure_once(amps, key, shot + j, num_qubits, t,
                                   is_density, quad)
        outs.append(o)
        probs.append(p)
    return amps, jnp.stack(outs), jnp.stack(probs)
