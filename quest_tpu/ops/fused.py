"""Fused cluster-pair Pallas kernel: many gates, ONE pass over HBM.

The reference applies one kernel sweep per gate (QuEST.c dispatch; e.g.
compactUnitaryLocal, QuEST/src/CPU/QuEST_cpu.c:1743-1800), so a depth-d
circuit costs d full passes over the 2^n-amplitude array.  On TPU the state
sweep is HBM-bandwidth-bound, so the win is to apply MANY gates per pass.

Design: the flat amplitude index is split little-endian as

    [ qubits 14..n-1 | qubits 7..13 | qubits 0..6 ]
         grid rows       sublanes       lanes

so a (2, R, 128, 128) VMEM block holds R*16384 amplitudes with qubits 0..6
as the lane dimension and 7..13 as the sublane dimension — both exactly
TPU-tile-aligned for f32.  Any sequence of gates confined to qubits 0..6
multiplies into ONE 128x128 "cluster" matrix A (likewise 7..13 into B), and
the kernel applies A (right-contraction over lanes) and B (left-contraction
over sublanes) to each block while it is VMEM-resident: two MXU matmuls,
one HBM read + one write, regardless of how many gates were folded in.

Complex arithmetic stays SoA (ops/cplx.py): the two channels are
concatenated along the contracted axis and each cluster matrix becomes the
256x256 real representation [[Re,Im],[-Im,Re]] (lanes) / [[Re,-Im],[Im,Re]]
(sublanes), so each cluster costs exactly one real matmul.

Gates on qubits >= 14 are handled by the circuit scheduler (circuit.py)
with a one-pass axis permutation (kernels.permute_qubits) that relabels
high qubits into the cluster window — the single-chip analogue of the
reference's distributed SWAP-relocalization
(QuEST/src/CPU/QuEST_cpu_distributed.c:1503-1545).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE_QUBITS = 7          # qubits 0..6  -> lane dim (128)
SUBLANE_QUBITS = 7       # qubits 7..13 -> sublane dim (128)
CLUSTER_QUBITS = LANE_QUBITS + SUBLANE_QUBITS   # 14
CLUSTER_DIM = 128
BLOCK_AMPS = CLUSTER_DIM * CLUSTER_DIM           # 16384


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _resolve_interpret(interpret, amps) -> bool:
    """Pallas only on real TPU AND a Mosaic-supported dtype: f64 dots raise
    NotImplementedError in the Mosaic lowering, so double-precision states
    (set_precision(2), the reference's default qreal) run the same kernel
    bodies in interpret mode — plain XLA ops, which the TPU executes via
    its software-f64 path."""
    if interpret is not None:
        return interpret
    return _interpret_default() or amps.dtype == jnp.float64


# MXU contraction precision for the cluster/window matmuls.  f32 inputs on
# TPU decompose into bf16 MXU passes: HIGHEST = 6 passes (full f32
# accuracy), DEFAULT = 1 pass (bf16, ~1e-3 — too coarse for amplitudes).
# The window pass is MXU-bound at HIGHEST (measured on v5e: rank-1 A+B
# 4.45 ms vs a 1.3 ms HBM floor at 2^26 amps; rank-4 18.6 ms), so the
# "bf16_3x" mode implements the 3-pass split Mosaic's dot lowering lacks
# (Precision.HIGH raises NotImplementedError): x@m = xh@mh + xh@ml + xl@mh
# with xh/xl (mh/ml) the bf16 hi/lo halves of each f32 operand and f32
# accumulation.  Dropped term xl@ml is O(2^-16) relative — inside the f32
# REAL_EPS = 1e-5 tolerance the reference's single-precision mode already
# grants (QuEST_precision.h:34).
_PRECISIONS = {
    "highest": jax.lax.Precision.HIGHEST,
    "bf16_3x": "bf16_3x",
    "default": jax.lax.Precision.DEFAULT,
}
_CONFIG = {"precision": "highest"}


def set_matmul_precision(name: str) -> None:
    """Set the window-kernel contraction precision ("highest"|"bf16_3x"|
    "default").  Callers that cache compiled plans key on the name via
    matmul_precision_name()."""
    if name not in _PRECISIONS:
        raise ValueError(f"unknown precision {name!r}; use one of {list(_PRECISIONS)}")
    _CONFIG["precision"] = name


def matmul_precision_name() -> str:
    return _CONFIG["precision"]


def _resolve_precision(name):
    return _PRECISIONS[name or _CONFIG["precision"]]


def _kdot(x, m, dims, prec):
    """dot_general at the requested precision; "bf16_3x" is the manual
    3-pass bf16 split (f64 inputs fall back to HIGHEST — the split is an
    f32 decomposition)."""
    if prec == "bf16_3x" and x.dtype == jnp.float32:
        f32 = jnp.float32
        xh = x.astype(jnp.bfloat16)
        xl = (x - xh.astype(f32)).astype(jnp.bfloat16)
        mh = m.astype(jnp.bfloat16)
        ml = (m - mh.astype(f32)).astype(jnp.bfloat16)
        d = partial(jax.lax.dot_general, dimension_numbers=dims,
                    preferred_element_type=f32)
        return d(xh, mh) + d(xh, ml) + d(xl, mh)
    if prec == "bf16_3x":
        prec = jax.lax.Precision.HIGHEST
    return jax.lax.dot_general(
        x, m, dimension_numbers=dims,
        preferred_element_type=x.dtype, precision=prec,
    )


# Largest segment width whose 2^m-block super-block (plus the kernel's
# transpose/concat temporaries) fits in the 16 MB scoped VMEM for the fused
# swap+cluster kernel (8 blocks = 1 MB per buffer; m=4 overflows).
MAX_FUSED_SWAP_M = 3


def lane_real_rep(mat_soa):
    """(2,128,128) SoA cluster matrix -> (256,256) real right-multiplier.

    For x = [xr | xi] concatenated on the lane axis, x @ M computes the
    complex product U x with U acting on the lane index:
    M = [[Ar^T, Ai^T], [-Ai^T, Ar^T]].
    """
    ar, ai = mat_soa[0], mat_soa[1]
    top = jnp.concatenate([ar.T, ai.T], axis=1)
    bot = jnp.concatenate([-ai.T, ar.T], axis=1)
    return jnp.concatenate([top, bot], axis=0)


def sublane_real_rep(mat_soa):
    """(2,128,128) SoA cluster matrix -> (256,256) real left-multiplier.

    For y = [yr ; yi] stacked on the sublane axis, M @ y computes the
    complex product: M = [[Br, -Bi], [Bi, Br]].
    """
    br, bi = mat_soa[0], mat_soa[1]
    top = jnp.concatenate([br, -bi], axis=1)
    bot = jnp.concatenate([bi, br], axis=1)
    return jnp.concatenate([top, bot], axis=0)


def _cluster_kernel_rank(rank, prec=jax.lax.Precision.HIGHEST):
    """Kernel applying sum_r B_r X A_r to each VMEM-resident block: the
    operator on the 14-qubit window is a rank-``rank`` sum of (sublane op)
    x (lane op) Kronecker products.  rank=1 is the plain cluster pair;
    rank=4 absorbs one lane-x-sublane-crossing 2q gate (circuit.py folds
    the |a><b| (x) U_ab decomposition).  All matmuls hit the MXU; one HBM
    read + one write regardless of rank."""

    def kernel(a_ref, ma_ref, mb_ref, o_ref):
        x = a_ref[...]                  # (2, R, 128, 128)  R = block rows
        xr, xi = x[0], x[1]
        xc0 = jnp.concatenate([xr, xi], axis=-1)         # (R, 128, 256)
        acc = None
        for r in range(rank):
            # lane op: right-contract lanes with the 256x256 real rep
            xc = _kdot(xc0, ma_ref[r], (((2,), (0,)), ((), ())), prec)                                            # (R, 128, 256)
            yr, yi = xc[..., :CLUSTER_DIM], xc[..., CLUSTER_DIM:]
            # sublane op: left-contract sublanes
            yc = jnp.concatenate([yr, yi], axis=1)       # (R, 256, 128)
            out = _kdot(mb_ref[r], yc, (((1,), (1,)), ((), ())), prec)                                            # (256, R, 128)
            acc = out if acc is None else acc + out
        acc = jnp.moveaxis(acc, 0, 1)                    # (R, 256, 128)
        o_ref[...] = jnp.stack(
            [acc[:, :CLUSTER_DIM], acc[:, CLUSTER_DIM:]], axis=0
        )

    return kernel


@partial(jax.jit, static_argnames=("num_qubits", "block_rows", "interpret",
                                   "precision"),
         donate_argnums=0)
def _apply_cluster_pair_jit(
    amps,
    mat_a,
    mat_b,
    *,
    num_qubits: int,
    block_rows: int = 8,
    interpret: bool | None = None,
    precision: str | None = None,
):
    """Apply 7-qubit cluster unitaries A (qubits 0-6) and B (qubits 7-13)
    to the whole state in one HBM pass.

    ``amps``: SoA (2, 2^n), n >= 14.  ``mat_a``/``mat_b``: stacked SoA
    (2, 128, 128) — products of all folded gates, built by circuit.py.
    """
    return _apply_cluster_stack_jit(
        amps, mat_a[None], mat_b[None], num_qubits=num_qubits,
        block_rows=block_rows, interpret=interpret, precision=precision,
    )


def _cluster_swap_kernel(rank, m, b_local, prec=jax.lax.Precision.HIGHEST):
    """Kernel fusing a bit-segment swap [h, h+m) <-> [b, b+m) (b in the
    sublane range, h in the grid range) with a rank-``rank`` cluster pass:
    the 2^m source blocks of the swap arrive as one VMEM super-block, the
    sublane/grid bit exchange is a free in-VMEM transpose, and the cluster
    matmuls run on the swapped data — one HBM read + write for what was
    previously a transpose pass plus a cluster pass."""
    M = 1 << m

    def kernel(a_ref, ma_ref, mb_ref, o_ref):
        x = a_ref[...]                   # (2, 1, M, 1, 128, 128)
        x = x.reshape(2, M, CLUSTER_DIM, CLUSTER_DIM)
        rhi = CLUSTER_DIM >> (b_local + m)
        rlo = 1 << b_local
        y = x.reshape(2, M, rhi, M, rlo, CLUSTER_DIM)
        y = jnp.transpose(y, (0, 3, 2, 1, 4, 5))   # grid bits <-> sublane bits
        x = y.reshape(2, M, CLUSTER_DIM, CLUSTER_DIM)
        xr, xi = x[0], x[1]
        xc0 = jnp.concatenate([xr, xi], axis=-1)
        acc = None
        for r in range(rank):
            xc = _kdot(xc0, ma_ref[r], (((2,), (0,)), ((), ())), prec)
            yr, yi = xc[..., :CLUSTER_DIM], xc[..., CLUSTER_DIM:]
            yc = jnp.concatenate([yr, yi], axis=1)
            out = _kdot(mb_ref[r], yc, (((1,), (1,)), ((), ())), prec)
            acc = out if acc is None else acc + out
        acc = jnp.moveaxis(acc, 0, 1)
        out = jnp.stack([acc[:, :CLUSTER_DIM], acc[:, CLUSTER_DIM:]], axis=0)
        o_ref[...] = out.reshape(2, 1, M, 1, CLUSTER_DIM, CLUSTER_DIM)

    return kernel


@partial(jax.jit,
         static_argnames=("num_qubits", "h", "b", "m", "interpret",
                          "precision"),
         donate_argnums=0)
def _apply_swap_cluster_stack_jit(
    amps,
    mats_a,
    mats_b,
    *,
    num_qubits: int,
    h: int,
    b: int,
    m: int,
    interpret: bool | None = None,
    precision: str | None = None,
):
    """Segment swap [h, h+m) <-> [b, b+m) followed by the rank-R window
    operator sum_r B_r (x) A_r, in ONE HBM pass (see _cluster_swap_kernel).
    Requires h >= 14, 7 <= b and b + m <= 14, m <= MAX_FUSED_SWAP_M.
    Result shape = input shape."""
    n = num_qubits
    in_shape = amps.shape
    interpret = _resolve_interpret(interpret, amps)
    rank = mats_a.shape[0]
    M = 1 << m
    nb = 1 << (n - CLUSTER_QUBITS)
    glo = 1 << (h - CLUSTER_QUBITS)
    ghi = nb // (glo * M)
    ma = jax.vmap(lane_real_rep)(jnp.asarray(mats_a, amps.dtype))
    mb = jax.vmap(sublane_real_rep)(jnp.asarray(mats_b, amps.dtype))
    view = amps.reshape(2, ghi, M, glo, CLUSTER_DIM, CLUSTER_DIM)
    out = pl.pallas_call(
        _cluster_swap_kernel(rank, m, b - LANE_QUBITS,
                             _resolve_precision(precision)),
        grid=(ghi, glo),
        in_specs=[
            pl.BlockSpec((2, 1, M, 1, CLUSTER_DIM, CLUSTER_DIM),
                         lambda i, j: (0, i, 0, j, 0, 0)),
            pl.BlockSpec((rank, 2 * CLUSTER_DIM, 2 * CLUSTER_DIM),
                         lambda i, j: (0, 0, 0)),
            pl.BlockSpec((rank, 2 * CLUSTER_DIM, 2 * CLUSTER_DIM),
                         lambda i, j: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((2, 1, M, 1, CLUSTER_DIM, CLUSTER_DIM),
                               lambda i, j: (0, i, 0, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(view, ma, mb)
    return out.reshape(in_shape)


def _window_block_body(x, ma, mb, mask, rank, apply_a, apply_b, prec):
    """Shared window-pass algebra on one VMEM-resident 5-d value
    (2, R, 128, M, 128) — window index on axis 2, lanes on axis 4, R/M
    pure batch axes.  Used verbatim by both the single-pass kernel
    (_window_kernel) and the megakernel (_mega_window_kernel) so the two
    routes issue IDENTICAL dot_generals in identical order and stay
    bit-exact against each other (tests/test_megakernel.py pins this)."""
    xr, xi = x[0], x[1]
    if apply_a and apply_b:
        # both sides: the lane-concat real rep keeps each side ONE
        # 256-contraction (beats 4 separate 128-dots per side,
        # measured both rounds)
        xc0 = jnp.concatenate([xr, xi], axis=-1)     # (R, 128, M, 256)
        acc = None
        for r in range(rank):
            xc = _kdot(xc0, ma[r], (((3,), (0,)), ((), ())), prec)                                        # (R, 128, M, 256)
            yr, yi = xc[..., :CLUSTER_DIM], xc[..., CLUSTER_DIM:]
            # sublane op: left-contract the window axis (dim 1)
            yc = jnp.concatenate([yr, yi], axis=1)   # (R, 256, M, 128)
            out = _kdot(mb[r], yc, (((1,), (1,)), ((), ())), prec)                                        # (256, R, M, 128)
            out = jnp.moveaxis(out, 0, 1)            # (R, 256, M, 128)
            acc = out if acc is None else acc + out
        rr, ri = acc[:, :CLUSTER_DIM], acc[:, CLUSTER_DIM:]
    elif apply_b:
        # B-only: separate-channel dots — skips the lane concat AND
        # the lane slice the generic path paid for nothing
        # (measured ~20-30% faster per pass at 26q)
        rr = ri = None
        for r in range(rank):
            br, bi = mb[r, 0], mb[r, 1]
            db = (((1,), (1,)), ((), ()))
            pr = _kdot(br, xr, db, prec) - _kdot(bi, xi, db, prec)
            pi = _kdot(br, xi, db, prec) + _kdot(bi, xr, db, prec)
            pr = jnp.moveaxis(pr, 0, 1)              # (R, 128, M, 128)
            pi = jnp.moveaxis(pi, 0, 1)
            rr = pr if rr is None else rr + pr
            ri = pi if ri is None else ri + pi
    else:
        # A-only: separate-channel right-dots on the lane axis
        # (y[l'] = sum_l A[l',l] x[l] -> contract the matrix's col dim)
        rr = ri = None
        for r in range(rank):
            ar, ai = ma[r, 0], ma[r, 1]
            da = (((3,), (1,)), ((), ()))
            pr = _kdot(xr, ar, da, prec) - _kdot(xi, ai, da, prec)
            pi = _kdot(xr, ai, da, prec) + _kdot(xi, ar, da, prec)
            rr = pr if rr is None else rr + pr
            ri = pi if ri is None else ri + pi
    if mask is not None:
        mr = mask[0][:, None, :]                     # (128, 1, 128)
        mi = mask[1][:, None, :]
        rr, ri = rr * mr - ri * mi, rr * mi + ri * mr
    return jnp.stack([rr, ri], axis=0)               # (2, R, 128, M, 128)


def _window_kernel(rank, apply_a, apply_b, prec=jax.lax.Precision.HIGHEST,
                   with_mask=False):
    """Kernel applying [mask (.)] sum_r B_r (x) A_r where A_r acts on the
    lane qubits [0,7) and B_r on an ARBITRARY contiguous sublane window
    [k, k+7) — the block spec (not the kernel) encodes k.  Block shape
    (2, R, 128, M, 128): R hi-axis blocks x M mid-axis blocks; both are
    pure batch axes of the two MXU contractions, so no in-kernel
    transposes are needed.  ``apply_a``/``apply_b`` skip the corresponding
    matmul when that side of the window operator is identity (half the
    FLOPs of a full pass).  ``with_mask`` appends one complex elementwise
    multiply by a (2, 128, 128) (window x lane) mask — how diagonal
    crossing gates (CZ/CPhase, and CNOT via its H-sandwich rewrite) are
    applied at zero rank cost (circuit.fold_mask)."""

    def kernel(a_ref, ma_ref, mb_ref, *rest):
        mask_ref, o_ref = (rest[0], rest[1]) if with_mask else (None, rest[0])
        xflat = a_ref[...]              # (2, R, 128, M*128) or (2, R, 128, M, 128)
        x = xflat.reshape(
            2, xflat.shape[1], CLUSTER_DIM,
            -1, CLUSTER_DIM,
        )                               # (2, R, 128, M, 128)
        res = _window_block_body(
            x, ma_ref, mb_ref,
            mask_ref[...] if with_mask else None,
            rank, apply_a, apply_b, prec)
        o_ref[...] = res.reshape(xflat.shape)

    return kernel


@partial(jax.jit,
         static_argnames=("num_qubits", "k", "apply_a", "apply_b",
                          "block_amps", "interpret", "precision"),
         donate_argnums=0)
def _apply_window_stack_jit(
    amps,
    mats_a,
    mats_b,
    mask=None,
    *,
    num_qubits: int,
    k: int = SUBLANE_QUBITS,
    apply_a: bool = True,
    apply_b: bool = True,
    block_amps: int = 8 * BLOCK_AMPS,
    interpret: bool | None = None,
    precision: str | None = None,
):
    """Apply the rank-R operator sum_r B_r (x) A_r with A on lane qubits
    [0,7) and B on the contiguous window [k, k+7), 7 <= k <= n-7, in ONE
    HBM pass with NO data relocation: the state is viewed as
    (2, hi, 128, mid, 128) so the window bits land on the sublane axis of
    each block (strided-row DMA).  k = 7 reproduces apply_cluster_stack;
    k > 7 replaces a segswap-relocate + cluster + restore sequence — the
    single-chip analogue of choosing which qubits are "local", cf. the
    reference's SWAP-relocalization (QuEST_cpu_distributed.c:1503-1545).

    ``amps`` may be any full-size view of the state (flat (2, 2^n) or the
    canonical (2, nb, 128, 128)); the result is returned in the SAME
    shape.  Chained per-pass callers (circuit.execute_plan_chained) keep
    the canonical view across jit boundaries — a flat (2, 2^n) parameter
    carries a device layout that differs from the kernels' T(8,128) tiled
    view, forcing XLA to insert a FULL-STATE layout copy at the program
    boundary (8 GB at 30q: the round-2 "30q never reaches the chip" OOM).
    """
    n = num_qubits
    in_shape = amps.shape
    if not (LANE_QUBITS <= k <= n - SUBLANE_QUBITS):
        raise ValueError(f"window offset {k} out of range for n={n}")
    interpret = _resolve_interpret(interpret, amps)
    rank = mats_a.shape[0]
    hi = 1 << (n - k - SUBLANE_QUBITS)
    mid = 1 << (k - LANE_QUBITS)
    # batch mid first — a block's contiguous HBM span per sublane row is
    # M*512 bytes (the trailing (mid, lane) axis is memory-contiguous), so
    # small M means descriptor-bound strided DMA (M=1 -> 512 B chunks);
    # then batch hi with what remains.  Scale the total down with rank —
    # the unrolled rank loop multiplies the scoped VMEM for temporaries.
    # Empirical limits (16 MB scoped VMEM): rank-4 A+B overflows at 8
    # blocks (18.4M) but fits at 4; rank-1 A+B overflows at 16 blocks
    # (17.0M) but fits at 8; rank-1 B-only fits at 16 (fewer temporaries
    # with the lane matmul skipped).
    block_amps = max(BLOCK_AMPS, 2 * block_amps // rank)
    if n <= 21:
        # small states (<= 16 MB) can be VMEM-promoted wholesale by XLA
        # inside larger programs; an 8-block pass then overflows the 16 MB
        # scoped VMEM (measured 18.55M at n=20).  4 blocks always fit.
        block_amps = min(block_amps, 4 * BLOCK_AMPS)
    if rank == 1 and (apply_a == apply_b or mask is not None or mid < 8):
        # 16 blocks sit at/over the 16M scoped VMEM limit when extra
        # temporaries are live: the dual-side kernel overflowed at 17.0M
        # with the lane matmul, the separate-channel single-side kernels
        # at 25.8M with a mask, and the single-side NON-five_d layout
        # (mid < 8, e.g. k=7 B-only in the QFT bit reversal) at 19.0M —
        # all capped at 8.  Only unmasked single-side passes in the 5-d
        # layout keep 16 (fewer temporaries; compiles at <= 16M).
        block_amps = min(block_amps, 8 * BLOCK_AMPS)
    # View choice is LAYOUT-critical: with mid >= 8 the 5-d view
    # (2, hi, 128, mid, 128) under the default T(8,128) tiling of its two
    # minor dims is PHYSICALLY IDENTICAL to the canonical k=7 view
    # (2, nb, 128, 128) — both tile 8 consecutive values of amp bits 7-9
    # by the 128 lanes — so consecutive passes at different offsets
    # exchange state via free bitcasts.  The collapsed 4-d view
    # (2, hi, 128, mid*128) instead puts window bits in the tile's sublane
    # dim, forcing XLA to insert a full-state retile copy (~4 ms at 26q)
    # at EVERY pass boundary (measured: a 26-pass plan spent ~60 ms in
    # such copies).  k in {8, 9} (mid 2, 4) keeps the 4-d view (the 5-d
    # form would pad mid to 8, up to 4x memory), as do rank>2 passes whose
    # VMEM budget cannot afford the 8-block minimum tile the 5-d layout
    # requires (rank-4 A+B overflows scoped VMEM at 8 blocks).
    five_d = mid >= 8 and block_amps >= 8 * BLOCK_AMPS
    M = min(mid, max(1, block_amps // BLOCK_AMPS))
    if five_d and M % 8:
        M = 8
    while mid % M:
        M //= 2
    R = min(hi, max(1, block_amps // (M * BLOCK_AMPS)))
    while hi % R:
        R //= 2
    if apply_a and apply_b:
        # dual-side kernel consumes the 256x256 real representations
        ma = jax.vmap(lane_real_rep)(jnp.asarray(mats_a, amps.dtype))
        mb = jax.vmap(sublane_real_rep)(jnp.asarray(mats_b, amps.dtype))
        mat_dim = 2 * CLUSTER_DIM
        mat_spec = (rank, mat_dim, mat_dim)
    else:
        # single-side kernels consume the raw SoA matrices
        ma = jnp.asarray(mats_a, amps.dtype)
        mb = jnp.asarray(mats_b, amps.dtype)
        mat_spec = (rank, 2, CLUSTER_DIM, CLUSTER_DIM)
    with_mask = mask is not None
    if five_d:
        view = amps.reshape(2, hi, CLUSTER_DIM, mid, CLUSTER_DIM)
        state_spec = pl.BlockSpec((2, R, CLUSTER_DIM, M, CLUSTER_DIM),
                                  lambda i, j: (0, i, 0, j, 0))
    else:
        view = amps.reshape(2, hi, CLUSTER_DIM, mid * CLUSTER_DIM)
        state_spec = pl.BlockSpec((2, R, CLUSTER_DIM, M * CLUSTER_DIM),
                                  lambda i, j: (0, i, 0, j))
    zmap = (lambda i, j: (0,) * len(mat_spec))
    in_specs = [
        state_spec,
        pl.BlockSpec(mat_spec, zmap),
        pl.BlockSpec(mat_spec, zmap),
    ]
    operands = [view, ma, mb]
    if with_mask:
        in_specs.append(pl.BlockSpec((2, CLUSTER_DIM, CLUSTER_DIM),
                                     lambda i, j: (0, 0, 0)))
        operands.append(jnp.asarray(mask, amps.dtype))
    out = pl.pallas_call(
        _window_kernel(rank, apply_a, apply_b,
                       _resolve_precision(precision), with_mask),
        grid=(hi // R, mid // M),
        in_specs=in_specs,
        out_specs=state_spec,
        out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(*operands)
    return out.reshape(in_shape)


@partial(jax.jit, static_argnames=("num_qubits", "block_rows", "interpret",
                                   "precision"),
         donate_argnums=0)
def _apply_cluster_stack_jit(
    amps,
    mats_a,
    mats_b,
    *,
    num_qubits: int,
    block_rows: int = 8,
    interpret: bool | None = None,
    precision: str | None = None,
):
    """Apply the rank-R window operator sum_r B_r (x) A_r in one HBM pass.

    ``mats_a``/``mats_b``: stacked SoA (R, 2, 128, 128).  R > 1 encodes
    lane-x-sublane-crossing gates folded by the scheduler (circuit.py)
    through the |a><b| block decomposition — the pass costs R matmul pairs
    but still exactly one state read + write.  Result shape = input shape
    (see _apply_window_stack_jit on canonical views)."""
    n = num_qubits
    in_shape = amps.shape
    if n < CLUSTER_QUBITS:
        raise ValueError(f"apply_cluster_stack needs >= {CLUSTER_QUBITS} qubits")
    interpret = _resolve_interpret(interpret, amps)
    rank = mats_a.shape[0]
    nb = 1 << (n - CLUSTER_QUBITS)
    r = min(block_rows, nb)
    while nb % r:
        r //= 2
    ma = jax.vmap(lane_real_rep)(jnp.asarray(mats_a, amps.dtype))
    mb = jax.vmap(sublane_real_rep)(jnp.asarray(mats_b, amps.dtype))
    view = amps.reshape(2, nb, CLUSTER_DIM, CLUSTER_DIM)
    out = pl.pallas_call(
        _cluster_kernel_rank(rank, _resolve_precision(precision)),
        grid=(nb // r,),
        in_specs=[
            pl.BlockSpec((2, r, CLUSTER_DIM, CLUSTER_DIM),
                         lambda i: (0, i, 0, 0)),
            pl.BlockSpec((rank, 2 * CLUSTER_DIM, 2 * CLUSTER_DIM),
                         lambda i: (0, 0, 0)),
            pl.BlockSpec((rank, 2 * CLUSTER_DIM, 2 * CLUSTER_DIM),
                         lambda i: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((2, r, CLUSTER_DIM, CLUSTER_DIM),
                               lambda i: (0, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(view, ma, mb)
    return out.reshape(in_shape)


def _resolved(precision):
    """Resolve the module default NOW — before the jit cache key is formed —
    so set_matmul_precision() affects subsequent calls instead of silently
    hitting a kernel compiled under the old setting."""
    return precision or _CONFIG["precision"]


def apply_cluster_pair(amps, mat_a, mat_b, *, precision=None, **kw):
    """See _apply_cluster_pair_jit."""
    return _apply_cluster_pair_jit(amps, mat_a, mat_b,
                                   precision=_resolved(precision), **kw)


def apply_swap_cluster_stack(amps, mats_a, mats_b, *, precision=None, **kw):
    """See _apply_swap_cluster_stack_jit."""
    return _apply_swap_cluster_stack_jit(amps, mats_a, mats_b,
                                         precision=_resolved(precision), **kw)


def apply_window_stack(amps, mats_a, mats_b, mask=None, *, precision=None, **kw):
    """See _apply_window_stack_jit."""
    return _apply_window_stack_jit(amps, mats_a, mats_b, mask,
                                   precision=_resolved(precision), **kw)


def apply_cluster_stack(amps, mats_a, mats_b, *, precision=None, **kw):
    """See _apply_cluster_stack_jit."""
    return _apply_cluster_stack_jit(amps, mats_a, mats_b,
                                    precision=_resolved(precision), **kw)


# ---------------------------------------------------------------------------
# Window megakernel (docs/design.md §29): a RUN of window passes in ONE
# pallas_call — one HBM read + one HBM write for the whole run instead of
# one round-trip per pass.  Eligible passes have window offset k <= 7 + g
# where 2^g VMEM-resident canonical rows make every window bit block-local;
# the in-kernel regroup between passes is a PURE reshape (no transpose):
# little-endian bit order means merging the (row_lo, sub_hi) axes IS the
# window index w = row_lo << (14-k) | sub_hi.
# ---------------------------------------------------------------------------


def megakernel_mode() -> str:
    """QT_MEGAKERNEL knob: "off" (never group), "on" (force, including
    interpret mode — the CPU test/bench arm), "auto" (default: group and
    execute fused only on a real TPU with a Mosaic-supported dtype)."""
    import os

    raw = os.environ.get("QT_MEGAKERNEL", "auto").strip().lower()
    if raw in ("off", "0", "false", "no"):
        return "off"
    if raw in ("on", "1", "true", "yes"):
        return "on"
    return "auto"


# one-shot Mosaic lowering probe, same contract as paulis._PALLAS_OK: a
# failed compile downgrades every megawin group to the per-pass route for
# the rest of the process and records itself in the env report.
_MEGA_OK: dict = {}


def _probe_megakernel_lowering() -> None:
    """Compile (don't run) a representative two-pass megakernel at the
    largest row grouping the budget rule admits (G = 8, k = 10): Mosaic
    VMEM overflows and lowering failures both surface at compile time."""
    n = 17
    amps = jax.ShapeDtypeStruct((2, 1 << n), jnp.float32)
    m = jax.ShapeDtypeStruct((1, 2, CLUSTER_DIM, CLUSTER_DIM), jnp.float32)
    spec = ((LANE_QUBITS, 1, True, True, False),
            (LANE_QUBITS + 3, 1, False, True, False))

    def f(x, a1, b1, a2, b2):
        return _apply_megawin_jit(x, a1, b1, a2, b2, num_qubits=n,
                                  spec=spec, interpret=False)

    jax.jit(f).lower(amps, m, m, m, m).compile()


def megakernel_lowering_ok() -> bool:
    """True when the window megakernel compiles on this backend; cached
    per process.  On failure, warn once, record the downgrade in the env
    report, and decompose megawin groups to per-pass dispatches."""
    hit = _MEGA_OK.get("ok")
    if hit is not None:
        return hit
    try:
        _probe_megakernel_lowering()
        ok = True
    # qlint: allow(broad-except): Mosaic failures span XlaRuntimeError/NotImplementedError/TypeError depending on backend and version; every one means "use the per-pass route" and is recorded in the degradation registry
    except Exception as e:
        from .. import resilience

        resilience.record_degradation(
            "pallas-window-megakernel",
            "window megakernel failed to compile; megawin groups decompose "
            f"to per-pass dispatches ({type(e).__name__}: {e})")
        ok = False
    _MEGA_OK["ok"] = ok
    return ok


def megakernel_planning() -> bool:
    """Whether the planner should FORM megawin groups at all.  "auto"
    groups only when a real TPU backs the process (the interpret-mode
    expansion of a fused group is *larger* XLA than per-pass dispatch, so
    CPU keeps the old plans bit-for-bit); QT_MEGAKERNEL=on forces grouping
    everywhere — the knob tests and the CPU A/B bench arm use."""
    mode = megakernel_mode()
    if mode == "off":
        return False
    if mode == "on":
        return True
    return not _interpret_default()


def megakernel_executable(dtype=None) -> bool:
    """Whether a megawin group should EXECUTE through the fused kernel.
    The fallback ladder below "auto" (each rung decomposes the group to
    the existing per-pass route, bit-identically): non-TPU backend ->
    interpret mode is slower fused than split; f64 state -> Mosaic can't
    lower the dots; Mosaic compile failure -> degradation registry."""
    mode = megakernel_mode()
    if mode == "off":
        return False
    if mode == "on":
        return True
    if _interpret_default():
        return False
    if dtype is not None and jnp.dtype(dtype) == jnp.float64:
        return False
    return megakernel_lowering_ok()


def megawin_row_cap(rank: int, num_qubits: int) -> int:
    """Largest VMEM block-row grouping a sub-pass of this rank tolerates,
    mirroring the empirical scoped-VMEM rules of _apply_window_stack_jit
    (rank-1 dual-side overflows 16 MB at 16 rows, fits at 8; rank-4 fits
    at 4; n <= 21 states risk wholesale XLA VMEM promotion, cap 4).  A
    group's G = 2^(kmax-7) must stay <= min over its sub-passes."""
    cap = 8 if rank <= 2 else 4
    if num_qubits <= 21:
        cap = min(cap, 4)
    return cap


def _mega_window_kernel(spec, prec=jax.lax.Precision.HIGHEST):
    """Kernel applying a run of window passes to one VMEM-resident block
    of G consecutive canonical rows.  ``spec``: per-pass statics
    (k, rank, apply_a, apply_b, with_mask).  Each pass regroups the block
    (2, G, 128, 128) -> (2, G/2^(k-7), 128, 2^(k-7), 128) by reshape only
    (the merged (row_lo, sub_hi) axis IS the window index — little-endian
    flat order), runs the SAME block body as the per-pass kernel
    (_window_block_body, so numerics are bit-identical), and reshapes
    back for the next pass.  One HBM read + one write for the whole run."""

    def kernel(a_ref, *refs):
        o_ref = refs[-1]
        x = a_ref[...]                       # (2, G, 128, 128)
        g_rows = x.shape[1]
        ri = 0
        for (k, rank, apply_a, apply_b, with_mask) in spec:
            ma_ref, mb_ref = refs[ri], refs[ri + 1]
            ri += 2
            mask = None
            if with_mask:
                mask = refs[ri][...]
                ri += 1
            wg = 1 << (k - LANE_QUBITS)      # window bits on the row axis
            whi = CLUSTER_DIM >> (k - LANE_QUBITS)  # ... on the sublanes
            ghi = g_rows // wg
            x5 = x.reshape(2, ghi, wg, whi, wg, CLUSTER_DIM)
            x5 = x5.reshape(2, ghi, CLUSTER_DIM, wg, CLUSTER_DIM)
            res = _window_block_body(x5, ma_ref, mb_ref, mask,
                                     rank, apply_a, apply_b, prec)
            x = res.reshape(2, g_rows, CLUSTER_DIM, CLUSTER_DIM)
        o_ref[...] = x

    return kernel


@partial(jax.jit,
         static_argnames=("num_qubits", "spec", "interpret", "precision"),
         donate_argnums=0)
def _apply_megawin_jit(
    amps,
    *arrays,
    num_qubits: int,
    spec: tuple,
    interpret: bool | None = None,
    precision: str | None = None,
):
    """Apply the window-pass run described by ``spec`` (per-pass statics
    (k, rank, apply_a, apply_b, with_mask); ``arrays`` = the flattened
    (a, b[, mask]) operands in pass order) in ONE pallas_call: grid over
    2^(n-14)/G super-blocks of G = 2^(kmax-7) consecutive canonical rows,
    so every pass's window bits are block-local.  Result shape = input
    shape (canonical-view layout notes as in _apply_window_stack_jit)."""
    n = num_qubits
    in_shape = amps.shape
    interpret = _resolve_interpret(interpret, amps)
    kmax = max(s[0] for s in spec)
    g_rows = 1 << (kmax - LANE_QUBITS)
    if n < CLUSTER_QUBITS:
        raise ValueError(f"megawin needs >= {CLUSTER_QUBITS} qubits")
    nb = 1 << (n - CLUSTER_QUBITS)
    if g_rows > nb or any(not (LANE_QUBITS <= s[0] <= n - SUBLANE_QUBITS)
                          for s in spec):
        raise ValueError(f"megawin window offsets out of range for n={n}")
    state_spec = pl.BlockSpec((2, g_rows, CLUSTER_DIM, CLUSTER_DIM),
                              lambda i: (0, i, 0, 0))
    in_specs = [state_spec]
    operands = []
    ai = 0
    for (k, rank, apply_a, apply_b, with_mask) in spec:
        a = jnp.asarray(arrays[ai], amps.dtype)
        b = jnp.asarray(arrays[ai + 1], amps.dtype)
        ai += 2
        if apply_a and apply_b:
            # dual-side passes consume the 256x256 real representations
            ma, mb = jax.vmap(lane_real_rep)(a), jax.vmap(sublane_real_rep)(b)
            mat_spec = (rank, 2 * CLUSTER_DIM, 2 * CLUSTER_DIM)
        else:
            # single-side passes consume the raw SoA matrices
            ma, mb = a, b
            mat_spec = (rank, 2, CLUSTER_DIM, CLUSTER_DIM)
        zmap = lambda i, _d=len(mat_spec): (0,) * _d
        in_specs += [pl.BlockSpec(mat_spec, zmap),
                     pl.BlockSpec(mat_spec, zmap)]
        operands += [ma, mb]
        if with_mask:
            in_specs.append(pl.BlockSpec((2, CLUSTER_DIM, CLUSTER_DIM),
                                         lambda i: (0, 0, 0)))
            operands.append(jnp.asarray(arrays[ai], amps.dtype))
            ai += 1
    view = amps.reshape(2, nb, CLUSTER_DIM, CLUSTER_DIM)
    out = pl.pallas_call(
        _mega_window_kernel(spec, _resolve_precision(precision)),
        grid=(nb // g_rows,),
        in_specs=in_specs,
        out_specs=state_spec,
        out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(view, *operands)
    return out.reshape(in_shape)


def apply_window_megastack(amps, subops, *, num_qubits, interpret=None,
                           precision=None):
    """Apply a planned run of winfused passes — ``subops`` is a sequence of
    ("winfused", k, a, b, apply_a, apply_b[, mask]) tuples — as ONE
    pallas_call (see _apply_megawin_jit).  This is the megawin plan op's
    fused route; circuit.execute_plan decomposes to per-pass dispatches
    instead when megakernel_executable() says no."""
    spec = []
    arrays = []
    for op in subops:
        mask = op[6] if len(op) > 6 else None
        spec.append((int(op[1]), int(np.shape(op[2])[0]),
                     bool(op[4]), bool(op[5]), mask is not None))
        arrays += [op[2], op[3]]
        if mask is not None:
            arrays.append(mask)
    return _apply_megawin_jit(amps, *arrays, num_qubits=num_qubits,
                              spec=tuple(spec), interpret=interpret,
                              precision=_resolved(precision))


# ---------------------------------------------------------------------------
# QFT ladder pass (Hadamard + whole controlled-phase ladder) as one Pallas
# kernel — the XLA elementwise formulation measured ~9.2 ms per 26q layer
# (it splits into multiple fusions around the pair-axis slice/stack); this
# kernel is one HBM read + write with the phase from two host tables.
# Reference layer semantics: agnostic_applyQFT, QuEST_common.c:836-898.
# ---------------------------------------------------------------------------


_TL_SPLIT = 1 << 11   # SMEM phase-table halves stay <= 2*2048*4 B = 16 KB


def _qft_ladder_kernel(inv, RL):
    def kernel(x_ref, tab_ref, tlo_ref, thi_ref, o_ref):
        # x_ref: (2, 1, 2, RL, 128, 128); tlo/thi: SMEM factor tables over
        # the low/high halves of the L index (each <= 16 KB regardless of
        # target), phase_L(l) = tlo[l % SPLIT] * thi[l // SPLIT]
        tab_re = tab_ref[0]                # (128, 128): bits 7-13 x 0-6
        tab_im = tab_ref[1]
        j = pl.program_id(1)
        for r in range(RL):                # static unroll
            x0r = x_ref[0, 0, 0, r]
            x0i = x_ref[1, 0, 0, r]
            x1r = x_ref[0, 0, 1, r]
            x1i = x_ref[1, 0, 1, r]
            l = j * RL + r
            alo = tlo_ref[0, l % _TL_SPLIT]
            blo = tlo_ref[1, l % _TL_SPLIT]
            ahi = thi_ref[0, l // _TL_SPLIT]
            bhi = thi_ref[1, l // _TL_SPLIT]
            tlr = alo * ahi - blo * bhi
            tli = alo * bhi + blo * ahi
            ph_re = tlr * tab_re - tli * tab_im
            ph_im = tlr * tab_im + tli * tab_re
            dr = (x0r - x1r) * inv
            di = (x0i - x1i) * inv
            o_ref[0, 0, 0, r] = (x0r + x1r) * inv
            o_ref[1, 0, 0, r] = (x0i + x1i) * inv
            o_ref[0, 0, 1, r] = dr * ph_re - di * ph_im
            o_ref[1, 0, 1, r] = dr * ph_im + di * ph_re

    return kernel


def _qft_ladder_jit(amps, tab, tlo, thi, *, num_qubits: int, target: int,
                    interpret: bool | None = None):
    n, t = num_qubits, target
    in_shape = amps.shape
    L = 1 << (t - CLUSTER_QUBITS)          # bits 14..t-1
    H = 1 << (n - 1 - t)                   # bits t+1..n-1
    if interpret is None:
        interpret = _interpret_default()
    RL = min(L, 8)
    view = amps.reshape(2, H, 2, L, CLUSTER_DIM, CLUSTER_DIM)
    inv = 0.7071067811865476
    out = pl.pallas_call(
        _qft_ladder_kernel(inv, RL),
        grid=(H, L // RL),
        in_specs=[
            pl.BlockSpec((2, 1, 2, RL, CLUSTER_DIM, CLUSTER_DIM),
                         lambda i, j: (0, i, 0, j, 0, 0)),
            pl.BlockSpec((2, CLUSTER_DIM, CLUSTER_DIM),
                         lambda i, j: (0, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((2, 1, 2, RL, CLUSTER_DIM, CLUSTER_DIM),
                               lambda i, j: (0, i, 0, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(view, tab, tlo, thi)
    return out.reshape(in_shape)


_qft_ladder_pallas_inner = partial(
    jax.jit, static_argnames=("num_qubits", "target", "interpret"),
    donate_argnums=0)(_qft_ladder_jit)


def qft_ladder_supported(amps_dtype, num_qubits: int, target: int,
                         base: int) -> bool:
    """The Pallas ladder needs base 0, the pair bit above the 14-qubit
    block (t >= 14), and a Mosaic-supported dtype on a real TPU."""
    import numpy as _np

    return (base == 0 and target >= LANE_QUBITS
            and num_qubits > target
            and num_qubits >= CLUSTER_QUBITS + 1
            and _np.dtype(amps_dtype) == _np.float32
            and not _interpret_default())


def apply_qft_ladder_pallas(amps, *, num_qubits: int, target: int,
                            conj: bool = False,
                            interpret: bool | None = None):
    """One QFT layer (H on ``target`` + controlled-phase ladder against
    bits [0, target)) in ONE Pallas pass.  The phase e^{i pi low/2^t}
    factorizes into a host (128, 128) table over bits [0, 14) and two
    SMEM factor tables over the [14, t) index (split at 2^11 so each
    stays <= 16 KB for any target)."""
    import numpy as _np

    n, t = num_qubits, target
    sgn = -1.0 if conj else 1.0
    dt = _np.dtype(amps.dtype)
    if t < CLUSTER_QUBITS:
        jlo = _np.arange(1 << t, dtype=_np.float64)
        ang = sgn * _np.pi * jlo / (1 << t)
        tab = _np.stack([_np.cos(ang), _np.sin(ang)]).reshape(
            2, 1 << (t - LANE_QUBITS), CLUSTER_DIM).astype(dt)
        return _qft_ladder_lo_jit(amps, jnp.asarray(tab),
                                  num_qubits=n, target=t,
                                  interpret=interpret)
    j14 = _np.arange(1 << CLUSTER_QUBITS, dtype=_np.float64)
    ang14 = sgn * _np.pi * j14 / (1 << t)
    tab = _np.stack([_np.cos(ang14), _np.sin(ang14)]).reshape(
        2, CLUSTER_DIM, CLUSTER_DIM).astype(dt)
    L = 1 << (t - CLUSTER_QUBITS)
    nlo = min(L, _TL_SPLIT)
    jlo = _np.arange(nlo, dtype=_np.float64)
    alo = sgn * _np.pi * jlo * (1 << CLUSTER_QUBITS) / (1 << t)
    tlo = _np.stack([_np.cos(alo), _np.sin(alo)]).astype(dt)
    nhi = max(1, L // _TL_SPLIT)
    jhi = _np.arange(nhi, dtype=_np.float64)
    ahi = (sgn * _np.pi * jhi * float(_TL_SPLIT)
           * (1 << CLUSTER_QUBITS) / (1 << t))
    thi = _np.stack([_np.cos(ahi), _np.sin(ahi)]).astype(dt)
    return _qft_ladder_pallas_inner(
        amps, jnp.asarray(tab), jnp.asarray(tlo), jnp.asarray(thi),
        num_qubits=n, target=t, interpret=interpret)


def _qft_ladder_lo_kernel(inv, t):
    """Ladder layer for 7 <= t <= 13: the pair bit lives inside the
    128-sublane axis, so the block reshapes its sublane factor and the
    phase table (2, 2^(t-7), 128) aligns with in-block axes directly."""
    s_hi = 1 << (13 - t)
    s_lo = 1 << (t - LANE_QUBITS)

    def kernel(x_ref, tab_ref, o_ref):
        x = x_ref[...]                      # (2, R, 128, 128)
        R = x.shape[1]
        v = x.reshape(2, R, s_hi, 2, s_lo, CLUSTER_DIM)
        x0 = v[:, :, :, 0]                  # (2, R, s_hi, s_lo, 128)
        x1 = v[:, :, :, 1]
        y0 = (x0 + x1) * inv
        d = (x0 - x1) * inv
        tr = tab_ref[0]                     # (s_lo, 128)
        ti = tab_ref[1]
        y1r = d[0] * tr - d[1] * ti
        y1i = d[0] * ti + d[1] * tr
        out_re = jnp.stack([y0[0], y1r], axis=2)   # (R, s_hi, 2, s_lo, 128)
        out_im = jnp.stack([y0[1], y1i], axis=2)
        out = jnp.stack([out_re, out_im])
        o_ref[...] = out.reshape(2, R, CLUSTER_DIM, CLUSTER_DIM)

    return kernel


@partial(jax.jit, static_argnames=("num_qubits", "target", "interpret"),
         donate_argnums=0)
def _qft_ladder_lo_jit(amps, tab, *, num_qubits: int, target: int,
                       interpret: bool | None = None):
    n, t = num_qubits, target
    in_shape = amps.shape
    HI = 1 << (n - CLUSTER_QUBITS)
    if interpret is None:
        interpret = _interpret_default()
    R = min(HI, 8)
    view = amps.reshape(2, HI, CLUSTER_DIM, CLUSTER_DIM)
    out = pl.pallas_call(
        _qft_ladder_lo_kernel(0.7071067811865476, t),
        grid=(HI // R,),
        in_specs=[
            pl.BlockSpec((2, R, CLUSTER_DIM, CLUSTER_DIM),
                         lambda i: (0, i, 0, 0)),
            pl.BlockSpec((2, 1 << (t - LANE_QUBITS), CLUSTER_DIM),
                         lambda i: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((2, R, CLUSTER_DIM, CLUSTER_DIM),
                               lambda i: (0, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(view, tab)
    return out.reshape(in_shape)


# ---------------------------------------------------------------------------
# Multi-layer (radix-2^k) QFT ladder passes
# ---------------------------------------------------------------------------
#
# The per-layer ladder above runs ONE butterfly layer per HBM sweep, so a
# full n-qubit QFT costs ~n sweeps even though each sweep does almost no
# arithmetic.  Classic high-radix FFT blocking fixes that: hold 2^k pair
# bits co-resident in VMEM and run k butterfly+phase layers per sweep.
# The reference has no analogue (its QFT is one kernel sweep per H plus
# one per phase ladder, agnostic_applyQFT, QuEST_common.c:836-898); this
# is a TPU-memory-hierarchy design.
#
#   - _qft_multi_hi: layers t in [t_lo, t_hi], all >= 14.  The state view
#     (2, H, 2^k, M, 128, 128) makes bits [t_lo, t_hi] a co-resident block
#     axis; each layer's controlled-phase factorizes into a per-layer
#     (128, 128) VMEM table over bits [0, 14), an SMEM factor over bits
#     [14, t_lo) (the block's mid coordinate), and a compile-time constant
#     over the already-swept block bits below the layer.
#   - _qft_cluster_multi: ALL seven sublane layers (t = 13..7) in one
#     sweep; each layer reshapes the sublane axis exactly like
#     _qft_ladder_lo_kernel and its phase table rows [:2^(t-7)] align with
#     the in-block axes directly.

QFT_RADIX_DEFAULT = 4    # VMEM per high pass: 2 sides * 2^k * 64 KB blocks


def _qft_radix() -> int:
    import os

    try:
        k = int(os.environ.get("QT_QFT_RADIX", str(QFT_RADIX_DEFAULT)))
    except ValueError:
        k = QFT_RADIX_DEFAULT
    return max(1, min(5, k))


def qft_multilayer_enabled(amps_dtype) -> bool:
    """Multi-layer QFT passes: f32 on a real TPU by default; interpret-mode
    execution (CPU tests) opts in via QT_QFT_ML_INTERPRET=1."""
    import os

    if np.dtype(amps_dtype) != np.float32:
        return False
    if os.environ.get("QT_QFT_MULTILAYER", "1") != "1":
        return False
    if not _interpret_default():
        return True
    return os.environ.get("QT_QFT_ML_INTERPRET") == "1"


def _qft_multi_hi_kernel(k: int, sgn: float):
    C = 1 << k
    inv = 0.7071067811865476

    def kernel(x_ref, ctab_ref, mlo_ref, mhi_ref, o_ref):
        j = pl.program_id(1)
        slabs = [[x_ref[0, 0, c, 0], x_ref[1, 0, c, 0]] for c in range(C)]
        for p in range(k - 1, -1, -1):
            ctr = ctab_ref[p, 0]                   # (128, 128) bits [0,14)
            cti = ctab_ref[p, 1]
            ar = mlo_ref[p, 0, j % _TL_SPLIT]      # bits [14, t_lo) factor
            ai = mlo_ref[p, 1, j % _TL_SPLIT]
            br = mhi_ref[p, 0, j // _TL_SPLIT]
            bi = mhi_ref[p, 1, j // _TL_SPLIT]
            mr = ar * br - ai * bi
            mi = ar * bi + ai * br
            for c0 in range(C):
                if (c0 >> p) & 1:
                    continue
                c1 = c0 | (1 << p)
                # block bits below the layer: compile-time phase constant
                clo = c0 & ((1 << p) - 1)
                a = sgn * np.pi * clo / float(1 << p)
                sr = mr * float(np.cos(a)) - mi * float(np.sin(a))
                si = mr * float(np.sin(a)) + mi * float(np.cos(a))
                phr = sr * ctr - si * cti
                phi_ = sr * cti + si * ctr
                x0r, x0i = slabs[c0]
                x1r, x1i = slabs[c1]
                s0r = (x0r + x1r) * inv
                s0i = (x0i + x1i) * inv
                dr = (x0r - x1r) * inv
                di = (x0i - x1i) * inv
                slabs[c0] = [s0r, s0i]
                slabs[c1] = [dr * phr - di * phi_, dr * phi_ + di * phr]
        for c in range(C):
            o_ref[0, 0, c, 0] = slabs[c][0]
            o_ref[1, 0, c, 0] = slabs[c][1]

    return kernel


@partial(jax.jit,
         static_argnames=("num_qubits", "t_hi", "t_lo", "conj", "interpret"),
         donate_argnums=0)
def _qft_multi_hi_jit(amps, ctab, mlo, mhi, *, num_qubits: int, t_hi: int,
                      t_lo: int, conj: bool, interpret: bool | None = None):
    n, k = num_qubits, t_hi - t_lo + 1
    in_shape = amps.shape
    C = 1 << k
    H = 1 << (n - 1 - t_hi)
    M = 1 << (t_lo - CLUSTER_QUBITS)
    if interpret is None:
        interpret = _interpret_default()
    view = amps.reshape(2, H, C, M, CLUSTER_DIM, CLUSTER_DIM)
    sgn = -1.0 if conj else 1.0
    out = pl.pallas_call(
        _qft_multi_hi_kernel(k, sgn),
        grid=(H, M),
        in_specs=[
            pl.BlockSpec((2, 1, C, 1, CLUSTER_DIM, CLUSTER_DIM),
                         lambda i, j: (0, i, 0, j, 0, 0)),
            pl.BlockSpec((k, 2, CLUSTER_DIM, CLUSTER_DIM),
                         lambda i, j: (0, 0, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((2, 1, C, 1, CLUSTER_DIM, CLUSTER_DIM),
                               lambda i, j: (0, i, 0, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(view, ctab, mlo, mhi)
    return out.reshape(in_shape)


def apply_qft_multi_hi(amps, *, num_qubits: int, t_hi: int, t_lo: int,
                       conj: bool = False, interpret: bool | None = None):
    """Layers t = t_hi..t_lo (descending, all >= 14) in ONE pass.

    SMEM budget: the stacked mid-factor tables are (k, 2, <=2048) f32 =
    k x 16 KB (64 KB at the default radix 4) — above the single-table
    16 KB bound the per-layer kernel keeps, but within Mosaic's scalar
    memory: validated on the real chip at the largest enabled size
    (full 30q f32 QFT, first chunk t_lo=26 -> M=4096, amp0 matches
    2^-15)."""
    import numpy as _np

    n = num_qubits
    k = t_hi - t_lo + 1
    if not (CLUSTER_QUBITS <= t_lo <= t_hi < n and 1 <= k <= 5):
        raise ValueError("apply_qft_multi_hi: bad layer chunk")
    dt = _np.dtype(amps.dtype)
    sgn = -1.0 if conj else 1.0
    j14 = _np.arange(1 << CLUSTER_QUBITS, dtype=_np.float64)
    ctab = _np.empty((k, 2, CLUSTER_DIM, CLUSTER_DIM), dtype=dt)
    M = 1 << (t_lo - CLUSTER_QUBITS)
    nlo = min(M, _TL_SPLIT)
    nhi = max(1, M // _TL_SPLIT)
    mlo = _np.empty((k, 2, nlo), dtype=dt)
    mhi = _np.empty((k, 2, nhi), dtype=dt)
    jlo = _np.arange(nlo, dtype=_np.float64)
    jhi = _np.arange(nhi, dtype=_np.float64)
    for p in range(k):
        t = t_lo + p
        a14 = sgn * _np.pi * j14 / (1 << t)
        ctab[p, 0] = _np.cos(a14).reshape(CLUSTER_DIM, CLUSTER_DIM)
        ctab[p, 1] = _np.sin(a14).reshape(CLUSTER_DIM, CLUSTER_DIM)
        alo = sgn * _np.pi * jlo * (1 << CLUSTER_QUBITS) / (1 << t)
        mlo[p, 0], mlo[p, 1] = _np.cos(alo), _np.sin(alo)
        ahi = (sgn * _np.pi * jhi * float(_TL_SPLIT)
               * (1 << CLUSTER_QUBITS) / (1 << t))
        mhi[p, 0], mhi[p, 1] = _np.cos(ahi), _np.sin(ahi)
    return _qft_multi_hi_jit(
        amps, jnp.asarray(ctab), jnp.asarray(mlo), jnp.asarray(mhi),
        num_qubits=n, t_hi=t_hi, t_lo=t_lo, conj=conj, interpret=interpret)


def _qft_cluster_multi_kernel():
    inv = 0.7071067811865476

    def kernel(x_ref, tab_ref, o_ref):
        x = x_ref[...]                      # (2, R, 128, 128)
        R = x.shape[1]
        for t in range(13, LANE_QUBITS - 1, -1):
            idx = 13 - t
            s_hi = 1 << (13 - t)
            s_lo = 1 << (t - LANE_QUBITS)
            v = x.reshape(2, R, s_hi, 2, s_lo, CLUSTER_DIM)
            x0 = v[:, :, :, 0]              # (2, R, s_hi, s_lo, 128)
            x1 = v[:, :, :, 1]
            s0 = (x0 + x1) * inv
            d = (x0 - x1) * inv
            tr = tab_ref[idx, 0, :s_lo]     # (s_lo, 128)
            ti = tab_ref[idx, 1, :s_lo]
            y1r = d[0] * tr - d[1] * ti
            y1i = d[0] * ti + d[1] * tr
            out_re = jnp.stack([s0[0], y1r], axis=2)
            out_im = jnp.stack([s0[1], y1i], axis=2)
            x = jnp.stack([out_re, out_im]).reshape(
                2, R, CLUSTER_DIM, CLUSTER_DIM)
        o_ref[...] = x

    return kernel


@partial(jax.jit, static_argnames=("num_qubits", "interpret"),
         donate_argnums=0)
def _qft_cluster_multi_jit(amps, tab, *, num_qubits: int,
                           interpret: bool | None = None):
    n = num_qubits
    in_shape = amps.shape
    HI = 1 << (n - CLUSTER_QUBITS)
    if interpret is None:
        interpret = _interpret_default()
    R = min(HI, 8)
    view = amps.reshape(2, HI, CLUSTER_DIM, CLUSTER_DIM)
    out = pl.pallas_call(
        _qft_cluster_multi_kernel(),
        grid=(HI // R,),
        in_specs=[
            pl.BlockSpec((2, R, CLUSTER_DIM, CLUSTER_DIM),
                         lambda i: (0, i, 0, 0)),
            pl.BlockSpec((SUBLANE_QUBITS, 2, CLUSTER_DIM, CLUSTER_DIM),
                         lambda i: (0, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((2, R, CLUSTER_DIM, CLUSTER_DIM),
                               lambda i: (0, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(view, tab)
    return out.reshape(in_shape)


def apply_qft_cluster_multi(amps, *, num_qubits: int, conj: bool = False,
                            interpret: bool | None = None):
    """ALL seven sublane ladder layers (t = 13..7) in ONE pass."""
    import numpy as _np

    if num_qubits < CLUSTER_QUBITS + 1:
        raise ValueError("apply_qft_cluster_multi needs n >= 15")
    dt = _np.dtype(amps.dtype)
    sgn = -1.0 if conj else 1.0
    sl = _np.arange(CLUSTER_DIM, dtype=_np.float64)[:, None]
    ll = _np.arange(CLUSTER_DIM, dtype=_np.float64)[None, :]
    tab = _np.empty((SUBLANE_QUBITS, 2, CLUSTER_DIM, CLUSTER_DIM), dtype=dt)
    for t in range(13, LANE_QUBITS - 1, -1):
        idx = 13 - t
        ang = sgn * _np.pi * (sl * CLUSTER_DIM + ll) / (1 << t)
        tab[idx, 0] = _np.cos(ang)
        tab[idx, 1] = _np.sin(ang)
    return _qft_cluster_multi_jit(amps, jnp.asarray(tab),
                                  num_qubits=num_qubits, interpret=interpret)


def apply_qft_multilayer_ladders(amps, *, num_qubits: int, t_top: int,
                                 conj: bool = False,
                                 interpret: bool | None = None,
                                 radix: int | None = None):
    """Ladder layers t = t_top .. 7 (descending) via the multilayer
    kernels: radix-2^k chunks for t >= 14, then ONE cluster pass for the
    seven sublane layers.  Shared by the unsharded QFT
    (circuit._fused_qft_multilayer) and the per-shard local layers of the
    sharded QFT (parallel.dist.fused_qft_sharded) so both use identical
    layer grouping.  Requires t_top >= 13 and num_qubits >= 15."""
    if t_top < CLUSTER_QUBITS - 1:
        raise ValueError("apply_qft_multilayer_ladders needs t_top >= 13 "
                         "(the cluster pass applies ALL sublane layers)")
    if radix is None:
        radix = _qft_radix()
    t = t_top
    while t >= CLUSTER_QUBITS:
        t_lo = max(CLUSTER_QUBITS, t - radix + 1)
        amps = apply_qft_multi_hi(amps, num_qubits=num_qubits, t_hi=t,
                                  t_lo=t_lo, conj=conj, interpret=interpret)
        t = t_lo - 1
    return apply_qft_cluster_multi(amps, num_qubits=num_qubits, conj=conj,
                                   interpret=interpret)


# ---------------------------------------------------------------------------
# Fused pair-channel sweep: many commuting channels per HBM pass
# ---------------------------------------------------------------------------
#
# A depolarise/damping channel on a density register pairs each element
# with its double-bit-flip partner (ket bit t, bra bit b) and combines
# them with block weights (ops/density.py _pair_channel).  Run eagerly,
# each channel costs several HBM passes (flip + combine).  Here the same
# co-residency trick as the multilayer QFT applies: hold 2^k bra (grid)
# bits co-resident in VMEM and run every channel whose bra bit falls in
# that chunk per sweep — partner slabs are in-block, the ket-bit flip is
# a sublane reshape (t >= 7) or an EXACT 3-term bf16 matmul against a
# 0/1 lane permutation (t < 7; 8+8+8 mantissa bits cover f32, so the
# split is lossless and each term is a single MXU pass — Mosaic rejects
# lane-axis reshape flips).  The reference's channel kernels are one
# full sweep per channel (QuEST_cpu.c:125-385).

_CHAN_SWEEP_RADIX = 3   # C=8 slabs; C=16 overflows scoped VMEM (16.8M > 16M)


def channel_sweep_enabled(amps_dtype) -> bool:
    """Fused channel sweeps: f32 on a real TPU by default; interpret-mode
    (CPU tests) opts in via QT_CHAN_SWEEP_INTERPRET=1."""
    import os

    if np.dtype(amps_dtype) != np.float32:
        return False
    if os.environ.get("QT_CHAN_SWEEP", "1") != "1":
        return False
    if not _interpret_default():
        return True
    return os.environ.get("QT_CHAN_SWEEP_INTERPRET") == "1"


def _lane_xmat_np(t: int) -> np.ndarray:
    """0/1 lane permutation matrix for X on lane bit t (y = x @ P)."""
    d = CLUSTER_DIM
    m = np.zeros((d, d), np.float32)
    idx = np.arange(d)
    m[idx ^ (1 << t), idx] = 1.0
    return m


def _exact_lane_perm(x, p_bf16):
    """x @ P for a 0/1 permutation P, exact at f32: 3-term bf16 split of x
    (the terms sum to x exactly; P is exact in bf16), f32 accumulation,
    one MXU pass per term."""
    f32 = jnp.float32
    xh = x.astype(jnp.bfloat16)
    r1 = x - xh.astype(f32)
    xm = r1.astype(jnp.bfloat16)
    xl = (r1 - xm.astype(f32)).astype(jnp.bfloat16)
    dims = (((x.ndim - 1,), (0,)), ((), ()))
    d = partial(jax.lax.dot_general, dimension_numbers=dims,
                preferred_element_type=f32)
    return d(xh, p_bf16) + d(xm, p_bf16) + d(xl, p_bf16)


def _flip_ket_block(x, t: int, xmap, xmats_ref):
    """In-block flip of cluster bit t over a whole (..., 128, 128) array:
    sublane reshape for t >= 7, exact lane-permutation matmul for t < 7."""
    lead = x.shape[:-2]
    if t >= LANE_QUBITS:
        s = t - LANE_QUBITS
        s_hi, s_lo = 1 << (SUBLANE_QUBITS - 1 - s), 1 << s
        v = x.reshape(lead + (s_hi, 2, s_lo, CLUSTER_DIM))
        ax = len(lead) + 1
        f = jnp.concatenate(
            [jax.lax.slice_in_dim(v, 1, 2, axis=ax),
             jax.lax.slice_in_dim(v, 0, 1, axis=ax)], axis=ax)
        return f.reshape(lead + (CLUSTER_DIM, CLUSTER_DIM))
    return _exact_lane_perm(x, xmats_ref[xmap[t]])


def _bit_mask_2d(t: int, dt):
    """(128, 128) {0,1} mask of cluster bit t, iota-built in-kernel."""
    if t < LANE_QUBITS:
        i = jax.lax.broadcasted_iota(jnp.int32, (CLUSTER_DIM, CLUSTER_DIM), 1)
        return ((i >> t) & 1).astype(dt)
    i = jax.lax.broadcasted_iota(jnp.int32, (CLUSTER_DIM, CLUSTER_DIM), 0)
    return ((i >> (t - LANE_QUBITS)) & 1).astype(dt)


def _chan_sweep_kernel(chunk, k: int, xmap):
    """One sweep applying ``chunk`` channels in order, whole-block style
    (per-slab fragmentation measured 1000x slower under Mosaic).  chunk
    entries: (t, b, pbit, wi) — for a grid-bra channel, pbit = the bra
    bit's position within the 2^k block axis; for an in-block channel
    (bra < 14) pbit is None and the partner is the double flip (t, b) on
    the same element block.  Weights (nchan, 5) = (w_same0, w_same1,
    w_diff, w2_00, w2_11) live in SMEM; ket/bra cluster-bit masks are
    iota-built; lane X permutations come in as a stacked bf16 VMEM arg."""
    C = 1 << k

    def kernel(x_ref, w_ref, xmats_ref, o_ref):
        dt = x_ref.dtype
        x = x_ref[...].reshape(2, C, CLUSTER_DIM, CLUSTER_DIM)
        for t, b, pbit, wi in chunk:
            kt = _bit_mask_2d(t, dt)
            ws0 = w_ref[wi, 0]
            ws1 = w_ref[wi, 1]
            wd = w_ref[wi, 2]
            w2_00 = w_ref[wi, 3]
            w2_11 = w_ref[wi, 4]
            if pbit is None:
                bt = _bit_mask_2d(b, dt)
                k1b1 = kt * bt
                k0b0 = (1 - kt) * (1 - bt)
                w1 = wd + (ws0 - wd) * k0b0 + (ws1 - wd) * k1b1
                w2 = w2_00 * k0b0 + w2_11 * k1b1
                f = _flip_ket_block(
                    _flip_ket_block(x, t, xmap, xmats_ref),
                    b, xmap, xmats_ref)
                x = x * w1 + f * w2
                continue
            chi, clo = 1 << (k - 1 - pbit), 1 << pbit
            v = x.reshape(2, chi, 2, clo, CLUSTER_DIM, CLUSTER_DIM)
            x0 = v[:, :, 0]                  # (2, chi, clo, 128, 128)
            x1 = v[:, :, 1]
            f1 = _flip_ket_block(x1, t, xmap, xmats_ref)
            f0 = _flip_ket_block(x0, t, xmap, xmats_ref)
            w1_0 = ws0 * (1 - kt) + wd * kt      # bra bit 0
            w1_1 = wd * (1 - kt) + ws1 * kt      # bra bit 1
            y0 = x0 * w1_0 + f1 * (w2_00 * (1 - kt))
            y1 = x1 * w1_1 + f0 * (w2_11 * kt)
            x = jnp.stack([y0, y1], axis=2).reshape(
                2, C, CLUSTER_DIM, CLUSTER_DIM)
        o_ref[...] = x.reshape(o_ref.shape)

    return kernel


def _chan_sweep_pass(amps, wmat, xmats, *, num_bits: int, b0: int, k: int,
                     chunk: tuple, xmap_items: tuple,
                     interpret: bool | None = None):
    """One pallas sweep over the (2, H, 2^k, M, 128, 128) view with grid
    bits [b0, b0+k) co-resident.  Plain traced function: callers (the
    fusion drain, tests) jit around it."""
    nn = num_bits
    in_shape = amps.shape
    C = 1 << k
    H = 1 << (nn - b0 - k)
    M = 1 << (b0 - CLUSTER_QUBITS)
    if interpret is None:
        interpret = _interpret_default()
    xmap = dict(xmap_items)
    view = amps.reshape(2, H, C, M, CLUSTER_DIM, CLUSTER_DIM)
    nx = max(1, xmats.shape[0])
    out = pl.pallas_call(
        _chan_sweep_kernel(chunk, k, xmap),
        grid=(H, M),
        in_specs=[
            pl.BlockSpec((2, 1, C, 1, CLUSTER_DIM, CLUSTER_DIM),
                         lambda i, j: (0, i, 0, j, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((nx, CLUSTER_DIM, CLUSTER_DIM),
                         lambda i, j: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((2, 1, C, 1, CLUSTER_DIM, CLUSTER_DIM),
                               lambda i, j: (0, i, 0, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(view, wmat, xmats)
    return out.reshape(in_shape)


def channel_weights(kind: str, prob, dtype):
    """(5,) traced weight vector (w_same0, w_same1, w_diff, w2_00, w2_11)
    for one pair channel — the same parametrization ops/density.py's
    eager kernels use."""
    p = jnp.asarray(prob, dtype)
    one = jnp.ones((), dtype)
    if kind == "depol":
        return jnp.stack([1 - 2 * p / 3, 1 - 2 * p / 3, 1 - 4 * p / 3,
                          2 * p / 3 * one, 2 * p / 3 * one])
    if kind == "damping":
        return jnp.stack([one, 1 - p, jnp.sqrt(1 - p),
                          p * one, 0 * one])
    raise ValueError(f"unknown pair channel {kind!r}")


def apply_pair_channel_sweep(amps, program: tuple, probs, *, num_bits: int,
                             interpret: bool | None = None):
    """Run an ordered sequence of pair channels in FEW HBM sweeps.

    ``program``: static tuple of (kind, t, b) with every t, and any
    in-block b, below 14 and num_bits >= 15.  Grid-bra channels are
    grouped into chunks of _CHAN_SWEEP_RADIX co-resident bra bits (one
    sweep each, channels kept in call order within a chunk; channels in
    different chunks act on disjoint (t, b) pairs and commute); in-block
    channels ride the first sweep.  ``probs`` are traced — same program
    with new probabilities reuses the compiled sweeps."""
    nn = num_bits
    if nn < CLUSTER_QUBITS + 1:
        raise ValueError("apply_pair_channel_sweep needs num_bits >= 15")
    pair_of = {}
    for kind, t, b in program:
        if t >= CLUSTER_QUBITS or b >= nn:
            raise ValueError("sweep channels need ket bit < 14")
        # HARD PRECONDITION: chunk assignment must be a function of the
        # bra bit alone — channels sharing a bra bit must share the ket
        # bit, else call order across non-commuting chunks could be
        # silently rearranged (relevant if a future kind carries per-call
        # differing bit pairs, e.g. two-qubit channels)
        if pair_of.setdefault(b, t) != t:
            raise ValueError(
                "apply_pair_channel_sweep: channels sharing a bra bit "
                "must share the ket bit (chunking is keyed on the bra "
                "bit; mixed pairs would reorder non-commuting channels)")
    dt = amps.dtype
    wmat = jnp.stack([channel_weights(kind, p, dt)
                      for (kind, _, _), p in zip(program, probs)])
    lane_ts = sorted({t for _, t, b in program if t < LANE_QUBITS}
                     | {b for _, t, b in program
                        if b < LANE_QUBITS})
    xmap_items = tuple((t, i) for i, t in enumerate(lane_ts))
    if lane_ts:
        xmats = jnp.asarray(np.stack([_lane_xmat_np(t) for t in lane_ts]),
                            jnp.bfloat16)
    else:
        xmats = jnp.zeros((1, CLUSTER_DIM, CLUSTER_DIM), jnp.bfloat16)
    K = _CHAN_SWEEP_RADIX
    # chunk grid-bra channels by bra-bit range, preserving call order
    chunks = []          # (b0, [entries])
    inblock = []
    for wi, (kind, t, b) in enumerate(program):
        if b < CLUSTER_QUBITS:
            inblock.append((t, b, None, wi))
            continue
        placed = False
        for ch in chunks:
            if ch[0] <= b < ch[0] + min(K, nn - ch[0]):
                ch[1].append((t, b, b - ch[0], wi))
                placed = True
                break
        if not placed:
            b0 = max(CLUSTER_QUBITS, min(b, nn - K))
            chunks.append((b0, [(t, b, b - b0, wi)]))
    if not chunks:
        chunks.append((CLUSTER_QUBITS, []))
    if inblock:
        chunks[0][1][:0] = inblock
    for b0, entries in chunks:
        k = min(K, nn - b0)
        amps = _chan_sweep_pass(
            amps, wmat, xmats, num_bits=nn, b0=b0, k=k,
            chunk=tuple(entries), xmap_items=xmap_items,
            interpret=interpret)
    return amps
