"""Fused cluster-pair Pallas kernel: many gates, ONE pass over HBM.

The reference applies one kernel sweep per gate (QuEST.c dispatch; e.g.
compactUnitaryLocal, QuEST/src/CPU/QuEST_cpu.c:1743-1800), so a depth-d
circuit costs d full passes over the 2^n-amplitude array.  On TPU the state
sweep is HBM-bandwidth-bound, so the win is to apply MANY gates per pass.

Design: the flat amplitude index is split little-endian as

    [ qubits 14..n-1 | qubits 7..13 | qubits 0..6 ]
         grid rows       sublanes       lanes

so a (2, R, 128, 128) VMEM block holds R*16384 amplitudes with qubits 0..6
as the lane dimension and 7..13 as the sublane dimension — both exactly
TPU-tile-aligned for f32.  Any sequence of gates confined to qubits 0..6
multiplies into ONE 128x128 "cluster" matrix A (likewise 7..13 into B), and
the kernel applies A (right-contraction over lanes) and B (left-contraction
over sublanes) to each block while it is VMEM-resident: two MXU matmuls,
one HBM read + one write, regardless of how many gates were folded in.

Complex arithmetic stays SoA (ops/cplx.py): the two channels are
concatenated along the contracted axis and each cluster matrix becomes the
256x256 real representation [[Re,Im],[-Im,Re]] (lanes) / [[Re,-Im],[Im,Re]]
(sublanes), so each cluster costs exactly one real matmul.

Gates on qubits >= 14 are handled by the circuit scheduler (circuit.py)
with a one-pass axis permutation (kernels.permute_qubits) that relabels
high qubits into the cluster window — the single-chip analogue of the
reference's distributed SWAP-relocalization
(QuEST/src/CPU/QuEST_cpu_distributed.c:1503-1545).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE_QUBITS = 7          # qubits 0..6  -> lane dim (128)
SUBLANE_QUBITS = 7       # qubits 7..13 -> sublane dim (128)
CLUSTER_QUBITS = LANE_QUBITS + SUBLANE_QUBITS   # 14
CLUSTER_DIM = 128
BLOCK_AMPS = CLUSTER_DIM * CLUSTER_DIM           # 16384


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


# Largest segment width whose 2^m-block super-block (plus the kernel's
# transpose/concat temporaries) fits in the 16 MB scoped VMEM for the fused
# swap+cluster kernel (8 blocks = 1 MB per buffer; m=4 overflows).
MAX_FUSED_SWAP_M = 3


def lane_real_rep(mat_soa):
    """(2,128,128) SoA cluster matrix -> (256,256) real right-multiplier.

    For x = [xr | xi] concatenated on the lane axis, x @ M computes the
    complex product U x with U acting on the lane index:
    M = [[Ar^T, Ai^T], [-Ai^T, Ar^T]].
    """
    ar, ai = mat_soa[0], mat_soa[1]
    top = jnp.concatenate([ar.T, ai.T], axis=1)
    bot = jnp.concatenate([-ai.T, ar.T], axis=1)
    return jnp.concatenate([top, bot], axis=0)


def sublane_real_rep(mat_soa):
    """(2,128,128) SoA cluster matrix -> (256,256) real left-multiplier.

    For y = [yr ; yi] stacked on the sublane axis, M @ y computes the
    complex product: M = [[Br, -Bi], [Bi, Br]].
    """
    br, bi = mat_soa[0], mat_soa[1]
    top = jnp.concatenate([br, -bi], axis=1)
    bot = jnp.concatenate([bi, br], axis=1)
    return jnp.concatenate([top, bot], axis=0)


def _cluster_kernel_rank(rank):
    """Kernel applying sum_r B_r X A_r to each VMEM-resident block: the
    operator on the 14-qubit window is a rank-``rank`` sum of (sublane op)
    x (lane op) Kronecker products.  rank=1 is the plain cluster pair;
    rank=4 absorbs one lane-x-sublane-crossing 2q gate (circuit.py folds
    the |a><b| (x) U_ab decomposition).  All matmuls hit the MXU; one HBM
    read + one write regardless of rank."""

    def kernel(a_ref, ma_ref, mb_ref, o_ref):
        x = a_ref[...]                  # (2, R, 128, 128)  R = block rows
        xr, xi = x[0], x[1]
        xc0 = jnp.concatenate([xr, xi], axis=-1)         # (R, 128, 256)
        acc = None
        for r in range(rank):
            # lane op: right-contract lanes with the 256x256 real rep
            xc = jax.lax.dot_general(
                xc0, ma_ref[r],
                dimension_numbers=(((2,), (0,)), ((), ())),
                preferred_element_type=x.dtype,
                precision=jax.lax.Precision.HIGHEST,
            )                                            # (R, 128, 256)
            yr, yi = xc[..., :CLUSTER_DIM], xc[..., CLUSTER_DIM:]
            # sublane op: left-contract sublanes
            yc = jnp.concatenate([yr, yi], axis=1)       # (R, 256, 128)
            out = jax.lax.dot_general(
                mb_ref[r], yc,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=x.dtype,
                precision=jax.lax.Precision.HIGHEST,
            )                                            # (256, R, 128)
            acc = out if acc is None else acc + out
        acc = jnp.moveaxis(acc, 0, 1)                    # (R, 256, 128)
        o_ref[...] = jnp.stack(
            [acc[:, :CLUSTER_DIM], acc[:, CLUSTER_DIM:]], axis=0
        )

    return kernel


@partial(jax.jit, static_argnames=("num_qubits", "block_rows", "interpret"),
         donate_argnums=0)
def apply_cluster_pair(
    amps,
    mat_a,
    mat_b,
    *,
    num_qubits: int,
    block_rows: int = 8,
    interpret: bool | None = None,
):
    """Apply 7-qubit cluster unitaries A (qubits 0-6) and B (qubits 7-13)
    to the whole state in one HBM pass.

    ``amps``: SoA (2, 2^n), n >= 14.  ``mat_a``/``mat_b``: stacked SoA
    (2, 128, 128) — products of all folded gates, built by circuit.py.
    """
    return apply_cluster_stack(
        amps, mat_a[None], mat_b[None], num_qubits=num_qubits,
        block_rows=block_rows, interpret=interpret,
    )


def _cluster_swap_kernel(rank, m, b_local):
    """Kernel fusing a bit-segment swap [h, h+m) <-> [b, b+m) (b in the
    sublane range, h in the grid range) with a rank-``rank`` cluster pass:
    the 2^m source blocks of the swap arrive as one VMEM super-block, the
    sublane/grid bit exchange is a free in-VMEM transpose, and the cluster
    matmuls run on the swapped data — one HBM read + write for what was
    previously a transpose pass plus a cluster pass."""
    M = 1 << m

    def kernel(a_ref, ma_ref, mb_ref, o_ref):
        x = a_ref[...]                   # (2, 1, M, 1, 128, 128)
        x = x.reshape(2, M, CLUSTER_DIM, CLUSTER_DIM)
        rhi = CLUSTER_DIM >> (b_local + m)
        rlo = 1 << b_local
        y = x.reshape(2, M, rhi, M, rlo, CLUSTER_DIM)
        y = jnp.transpose(y, (0, 3, 2, 1, 4, 5))   # grid bits <-> sublane bits
        x = y.reshape(2, M, CLUSTER_DIM, CLUSTER_DIM)
        xr, xi = x[0], x[1]
        xc0 = jnp.concatenate([xr, xi], axis=-1)
        acc = None
        for r in range(rank):
            xc = jax.lax.dot_general(
                xc0, ma_ref[r],
                dimension_numbers=(((2,), (0,)), ((), ())),
                preferred_element_type=x.dtype,
                precision=jax.lax.Precision.HIGHEST,
            )
            yr, yi = xc[..., :CLUSTER_DIM], xc[..., CLUSTER_DIM:]
            yc = jnp.concatenate([yr, yi], axis=1)
            out = jax.lax.dot_general(
                mb_ref[r], yc,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=x.dtype,
                precision=jax.lax.Precision.HIGHEST,
            )
            acc = out if acc is None else acc + out
        acc = jnp.moveaxis(acc, 0, 1)
        out = jnp.stack([acc[:, :CLUSTER_DIM], acc[:, CLUSTER_DIM:]], axis=0)
        o_ref[...] = out.reshape(2, 1, M, 1, CLUSTER_DIM, CLUSTER_DIM)

    return kernel


@partial(jax.jit,
         static_argnames=("num_qubits", "h", "b", "m", "interpret"),
         donate_argnums=0)
def apply_swap_cluster_stack(
    amps,
    mats_a,
    mats_b,
    *,
    num_qubits: int,
    h: int,
    b: int,
    m: int,
    interpret: bool | None = None,
):
    """Segment swap [h, h+m) <-> [b, b+m) followed by the rank-R window
    operator sum_r B_r (x) A_r, in ONE HBM pass (see _cluster_swap_kernel).
    Requires h >= 14, 7 <= b and b + m <= 14, m <= MAX_FUSED_SWAP_M."""
    n = num_qubits
    if interpret is None:
        interpret = _interpret_default()
    rank = mats_a.shape[0]
    M = 1 << m
    nb = 1 << (n - CLUSTER_QUBITS)
    glo = 1 << (h - CLUSTER_QUBITS)
    ghi = nb // (glo * M)
    ma = jax.vmap(lane_real_rep)(jnp.asarray(mats_a, amps.dtype))
    mb = jax.vmap(sublane_real_rep)(jnp.asarray(mats_b, amps.dtype))
    view = amps.reshape(2, ghi, M, glo, CLUSTER_DIM, CLUSTER_DIM)
    out = pl.pallas_call(
        _cluster_swap_kernel(rank, m, b - LANE_QUBITS),
        grid=(ghi, glo),
        in_specs=[
            pl.BlockSpec((2, 1, M, 1, CLUSTER_DIM, CLUSTER_DIM),
                         lambda i, j: (0, i, 0, j, 0, 0)),
            pl.BlockSpec((rank, 2 * CLUSTER_DIM, 2 * CLUSTER_DIM),
                         lambda i, j: (0, 0, 0)),
            pl.BlockSpec((rank, 2 * CLUSTER_DIM, 2 * CLUSTER_DIM),
                         lambda i, j: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((2, 1, M, 1, CLUSTER_DIM, CLUSTER_DIM),
                               lambda i, j: (0, i, 0, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(view, ma, mb)
    return out.reshape(2, -1)


def _window_kernel(rank, apply_a, apply_b):
    """Kernel applying sum_r B_r (x) A_r where A_r acts on the lane qubits
    [0,7) and B_r on an ARBITRARY contiguous sublane window [k, k+7) — the
    block spec (not the kernel) encodes k.  Block shape (2, R, 128, M, 128):
    R hi-axis blocks x M mid-axis blocks; both are pure batch axes of the
    two MXU contractions, so no in-kernel transposes are needed.
    ``apply_a``/``apply_b`` skip the corresponding matmul when that side of
    the window operator is identity (half the FLOPs of a full pass)."""

    def kernel(a_ref, ma_ref, mb_ref, o_ref):
        xflat = a_ref[...]              # (2, R, 128, M*128)
        x = xflat.reshape(
            2, xflat.shape[1], CLUSTER_DIM,
            xflat.shape[3] // CLUSTER_DIM, CLUSTER_DIM,
        )                               # (2, R, 128, M, 128)
        xr, xi = x[0], x[1]
        xc0 = jnp.concatenate([xr, xi], axis=-1)         # (R, 128, M, 256)
        acc = None
        for r in range(rank):
            if apply_a:
                xc = jax.lax.dot_general(
                    xc0, ma_ref[r],
                    dimension_numbers=(((3,), (0,)), ((), ())),
                    preferred_element_type=x.dtype,
                    precision=jax.lax.Precision.HIGHEST,
                )                                        # (R, 128, M, 256)
            else:
                xc = xc0
            yr, yi = xc[..., :CLUSTER_DIM], xc[..., CLUSTER_DIM:]
            # sublane op: left-contract the window axis (dim 1)
            yc = jnp.concatenate([yr, yi], axis=1)       # (R, 256, M, 128)
            if apply_b:
                out = jax.lax.dot_general(
                    mb_ref[r], yc,
                    dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=x.dtype,
                    precision=jax.lax.Precision.HIGHEST,
                )                                        # (256, R, M, 128)
                out = jnp.moveaxis(out, 0, 1)            # (R, 256, M, 128)
            else:
                out = yc
            acc = out if acc is None else acc + out
        res = jnp.stack(
            [acc[:, :CLUSTER_DIM], acc[:, CLUSTER_DIM:]], axis=0
        )                               # (2, R, 128, M, 128)
        o_ref[...] = res.reshape(xflat.shape)

    return kernel


@partial(jax.jit,
         static_argnames=("num_qubits", "k", "apply_a", "apply_b",
                          "block_amps", "interpret"),
         donate_argnums=0)
def apply_window_stack(
    amps,
    mats_a,
    mats_b,
    *,
    num_qubits: int,
    k: int = SUBLANE_QUBITS,
    apply_a: bool = True,
    apply_b: bool = True,
    block_amps: int = 8 * BLOCK_AMPS,
    interpret: bool | None = None,
):
    """Apply the rank-R operator sum_r B_r (x) A_r with A on lane qubits
    [0,7) and B on the contiguous window [k, k+7), 7 <= k <= n-7, in ONE
    HBM pass with NO data relocation: the state is viewed as
    (2, hi, 128, mid, 128) so the window bits land on the sublane axis of
    each block (strided-row DMA).  k = 7 reproduces apply_cluster_stack;
    k > 7 replaces a segswap-relocate + cluster + restore sequence — the
    single-chip analogue of choosing which qubits are "local", cf. the
    reference's SWAP-relocalization (QuEST_cpu_distributed.c:1503-1545).
    """
    n = num_qubits
    if not (LANE_QUBITS <= k <= n - SUBLANE_QUBITS):
        raise ValueError(f"window offset {k} out of range for n={n}")
    if interpret is None:
        interpret = _interpret_default()
    rank = mats_a.shape[0]
    hi = 1 << (n - k - SUBLANE_QUBITS)
    mid = 1 << (k - LANE_QUBITS)
    # batch hi first (contiguous super-blocks), then mid, to ~block_amps;
    # scale down with rank — the unrolled rank loop multiplies the scoped
    # VMEM for temporaries.  Empirical limits (16 MB scoped VMEM): rank-4
    # A+B overflows at 8 blocks (18.4M) but fits at 4; rank-1 A+B
    # overflows at 16 blocks (17.0M) but fits at 8; rank-1 B-only fits at
    # 16 (fewer temporaries with the lane matmul skipped).
    block_amps = max(BLOCK_AMPS, 2 * block_amps // rank)
    if rank == 1 and apply_a:
        # 16 blocks with the lane matmul live sits right at the 16M scoped
        # VMEM limit — it compiled in one program and overflowed (17.0M)
        # in another for the SAME kernel config, so stay safely at 8;
        # B-only passes (no lane matmul) keep 16
        block_amps = min(block_amps, 8 * BLOCK_AMPS)
    R = min(hi, max(1, block_amps // BLOCK_AMPS))
    while hi % R:
        R //= 2
    M = min(mid, max(1, block_amps // (R * BLOCK_AMPS)))
    while mid % M:
        M //= 2
    ma = jax.vmap(lane_real_rep)(jnp.asarray(mats_a, amps.dtype))
    mb = jax.vmap(sublane_real_rep)(jnp.asarray(mats_b, amps.dtype))
    # 4-d view: the window bits ARE the (second-to-last) sublane tile dim
    # and the trailing dim is (mid, lane) flattened, so every block shape
    # (2, R, 128, M*128) satisfies Mosaic's (8, 128) tiling requirement.
    view = amps.reshape(2, hi, CLUSTER_DIM, mid * CLUSTER_DIM)
    out = pl.pallas_call(
        _window_kernel(rank, apply_a, apply_b),
        grid=(hi // R, mid // M),
        in_specs=[
            pl.BlockSpec((2, R, CLUSTER_DIM, M * CLUSTER_DIM),
                         lambda i, j: (0, i, 0, j)),
            pl.BlockSpec((rank, 2 * CLUSTER_DIM, 2 * CLUSTER_DIM),
                         lambda i, j: (0, 0, 0)),
            pl.BlockSpec((rank, 2 * CLUSTER_DIM, 2 * CLUSTER_DIM),
                         lambda i, j: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((2, R, CLUSTER_DIM, M * CLUSTER_DIM),
                               lambda i, j: (0, i, 0, j)),
        out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(view, ma, mb)
    return out.reshape(2, -1)


@partial(jax.jit, static_argnames=("num_qubits", "block_rows", "interpret"),
         donate_argnums=0)
def apply_cluster_stack(
    amps,
    mats_a,
    mats_b,
    *,
    num_qubits: int,
    block_rows: int = 8,
    interpret: bool | None = None,
):
    """Apply the rank-R window operator sum_r B_r (x) A_r in one HBM pass.

    ``mats_a``/``mats_b``: stacked SoA (R, 2, 128, 128).  R > 1 encodes
    lane-x-sublane-crossing gates folded by the scheduler (circuit.py)
    through the |a><b| block decomposition — the pass costs R matmul pairs
    but still exactly one state read + write."""
    n = num_qubits
    if n < CLUSTER_QUBITS:
        raise ValueError(f"apply_cluster_stack needs >= {CLUSTER_QUBITS} qubits")
    if interpret is None:
        interpret = _interpret_default()
    rank = mats_a.shape[0]
    nb = 1 << (n - CLUSTER_QUBITS)
    r = min(block_rows, nb)
    while nb % r:
        r //= 2
    ma = jax.vmap(lane_real_rep)(jnp.asarray(mats_a, amps.dtype))
    mb = jax.vmap(sublane_real_rep)(jnp.asarray(mats_b, amps.dtype))
    view = amps.reshape(2, nb, CLUSTER_DIM, CLUSTER_DIM)
    out = pl.pallas_call(
        _cluster_kernel_rank(rank),
        grid=(nb // r,),
        in_specs=[
            pl.BlockSpec((2, r, CLUSTER_DIM, CLUSTER_DIM),
                         lambda i: (0, i, 0, 0)),
            pl.BlockSpec((rank, 2 * CLUSTER_DIM, 2 * CLUSTER_DIM),
                         lambda i: (0, 0, 0)),
            pl.BlockSpec((rank, 2 * CLUSTER_DIM, 2 * CLUSTER_DIM),
                         lambda i: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((2, r, CLUSTER_DIM, CLUSTER_DIM),
                               lambda i: (0, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(view, ma, mb)
    return out.reshape(2, -1)
