"""State-vector kernels: the TPU-native re-implementation of the reference's
backend kernel surface (``QuEST/src/QuEST_internal.h:116-272`` ``statevec_*``).

Design (not a port): the reference hand-codes strided amplitude-pair loops
per gate (e.g. compactUnitaryLocal, QuEST_cpu.c:1743-1800; CUDA
thread-per-pair, QuEST_gpu.cu:1037-1092).  Here a state of n qubits is a
real SoA array of shape ``(2, 2**n)`` (channel 0/1 = real/imag — the
reference's own ComplexArray layout, QuEST.h:77, and the TPU-native one:
see ops/cplx.py); a gate on targets T is a reshape / axis-move plus a small
real einsum or a broadcast elementwise multiply, and XLA generates the
strided fused loops.  Qubit q is bit q of the flat amplitude index
(little-endian), i.e. axis ``1 + (n-1-q)`` of the ``(2,) + (2,)*n`` view —
identical index convention to the reference (QuEST.h:393-400).

All functions are pure ``amps -> amps`` (or ``amps -> scalar``) and
jit-compiled with static qubit indices; the state buffer is donated so gate
chains update HBM in place (the reference instead mutates stateVec and pays
a 2x pairStateVec buffer when distributed, QuEST_cpu.c:1279-1315).

Matrices/diagonals enter as *stacked* SoA arrays ``(2, D, D)`` / ``(2, D)``
built host-side (cplx.soa) — dynamic arguments, so a parameterised gate
never recompiles when only its angle changes.

Controlled gates do not scan a control mask per amplitude as the reference
does (QuEST_cpu.c:1802-1895); they statically slice the controlled sub-block
(an axis index per control), apply the target update to the ``2**(n-c)``
surviving amplitudes, and scatter back with a dynamic-update-slice — so
bandwidth scales with the controlled subspace, beating the reference's
full-state scan.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from . import cplx


def _axis(n: int, q: int) -> int:
    """Axis of qubit q in the (2,) + (2,)*n channel-first view."""
    return 1 + (n - 1 - q)


def _control_selector(n: int, controls, control_states):
    sel = [slice(None)] * (n + 1)
    for c, s in zip(controls, control_states):
        sel[_axis(n, c)] = int(s)
    return tuple(sel)


def _remap_for_controls(n: int, controls, targets):
    """Qubit labels inside the control-sliced sub-state."""
    remaining = [q for q in range(n) if q not in controls]
    remap = {q: i for i, q in enumerate(remaining)}
    return len(remaining), tuple(remap[t] for t in targets)


def _apply_matrix_nocontrol(view, n: int, targets, rmat):
    """Complex k-qubit matrix as real block einsum; targets[0] =
    least-significant matrix bit (reference convention)."""
    k = len(targets)
    if k == 1:
        t = targets[0]
        v = view.reshape(2, 2 ** (n - 1 - t), 2, 2 ** t)
        # HIGHEST: stop TPU from doing the 2-wide contraction in bf16 —
        # it is bandwidth-bound, so full f32 costs nothing and keeps ~1e-7
        # gate error instead of ~1e-3 (observed with the default precision).
        out = jnp.einsum("cdab,dpbq->cpaq", rmat, v,
                         precision=jax.lax.Precision.HIGHEST)
        return out.reshape((2,) + (2,) * n)
    axes = tuple(_axis(n, t) for t in reversed(targets))
    moved = jnp.moveaxis(view, axes, range(1, k + 1))
    xs = moved.reshape(2, 2 ** k, -1)
    out = jnp.einsum("cdij,djr->cir", rmat, xs,
                     precision=jax.lax.Precision.HIGHEST)
    out = out.reshape((2,) + (2,) * n)
    return jnp.moveaxis(out, range(1, k + 1), axes)


@partial(
    jax.jit,
    static_argnames=("num_qubits", "targets", "controls", "control_states"),
    donate_argnums=0,
)
def apply_matrix(
    amps,
    matrix,
    *,
    num_qubits: int,
    targets: Tuple[int, ...],
    controls: Tuple[int, ...] = (),
    control_states: Tuple[int, ...] = (),
):
    """Apply a dense 2^k x 2^k matrix to target qubits, optionally controlled.

    Covers the reference's unitary/compactUnitary/twoQubitUnitary/
    multiQubitUnitary and every multi(State)Controlled* variant
    (QuEST_cpu.c:1743-1985) as one kernel; ``control_states`` generalizes to
    control-on-zero (reference multiStateControlledUnitary, QuEST.h:3877).
    ``matrix`` is stacked SoA (2, 2^k, 2^k).
    """
    n = num_qubits
    matrix = jnp.asarray(matrix, amps.dtype)
    rmat = cplx.real_matrix_rep(matrix)
    view = amps.reshape((2,) + (2,) * n)
    if controls:
        if not control_states:
            control_states = (1,) * len(controls)
        sel = _control_selector(n, controls, control_states)
        sub_n, sub_targets = _remap_for_controls(n, controls, targets)
        sub = view[sel].reshape((2,) + (2,) * sub_n)
        sub = _apply_matrix_nocontrol(sub, sub_n, sub_targets, rmat)
        view = view.at[sel].set(sub.reshape(view[sel].shape))
    else:
        view = _apply_matrix_nocontrol(view, n, targets, rmat)
    return view.reshape(2, -1)


def _broadcast_factor(n: int, targets, diag_channel):
    """(2,)*k channel slice -> broadcastable over the (2,)+(2,)*n view's
    qubit axes (without the channel axis: caller multiplies channels)."""
    k = len(targets)
    d = diag_channel.reshape((2,) * k + (1,) * (n - k))
    axes = tuple(_axis(n, t) - 1 for t in reversed(targets))
    return jnp.moveaxis(d, range(k), axes)


def _apply_diagonal_nocontrol(view, n: int, targets, diag):
    f_re = _broadcast_factor(n, targets, diag[0])
    f_im = _broadcast_factor(n, targets, diag[1])
    return cplx.cmul(view, f_re, f_im)


@partial(
    jax.jit,
    static_argnames=("num_qubits", "targets", "controls", "control_states"),
    donate_argnums=0,
)
def apply_diagonal(
    amps,
    diag,
    *,
    num_qubits: int,
    targets: Tuple[int, ...],
    controls: Tuple[int, ...] = (),
    control_states: Tuple[int, ...] = (),
):
    """Multiply amplitudes by ``diag[bits(targets)]`` — the phase-only kernel
    family (reference phaseShiftByTerm/multiControlledPhaseShift/phase-flip,
    QuEST_cpu.c:3146-3361) which needs no amplitude pairing.  ``diag`` is
    stacked SoA (2, 2^k), exponentiated host-side — no transcendental runs
    per amplitude."""
    n = num_qubits
    diag = jnp.asarray(diag, amps.dtype)
    view = amps.reshape((2,) + (2,) * n)
    if controls:
        if not control_states:
            control_states = (1,) * len(controls)
        sel = _control_selector(n, controls, control_states)
        sub_n, sub_targets = _remap_for_controls(n, controls, targets)
        sub = view[sel].reshape((2,) + (2,) * sub_n)
        sub = _apply_diagonal_nocontrol(sub, sub_n, sub_targets, diag)
        view = view.at[sel].set(sub.reshape(view[sel].shape))
    else:
        view = _apply_diagonal_nocontrol(view, n, targets, diag)
    return view.reshape(2, -1)


def parity_sign(n: int, qubits, dtype):
    """+/-1 parity factor over a qubit subset as a broadcast outer product of
    per-axis [1,-1] vectors — vectorized form of the reference's bit-parity
    sign trick (QuEST_cpu.c:3268-3275).  Shape: qubit axes only (no channel
    axis)."""
    pm = jnp.array([1.0, -1.0], dtype=dtype)
    sign = jnp.ones((1,) * n, dtype=dtype)
    for q in qubits:
        shape = [1] * n
        shape[n - 1 - q] = 2
        sign = sign * pm.reshape(shape)
    return sign


@partial(
    jax.jit,
    static_argnames=("num_qubits", "qubits", "controls", "control_states"),
    donate_argnums=0,
)
def apply_parity_phase(
    amps,
    theta,
    *,
    num_qubits: int,
    qubits: Tuple[int, ...],
    controls: Tuple[int, ...] = (),
    control_states: Tuple[int, ...] = (),
):
    """exp(-i theta/2 * Z x Z ... Z) over a qubit subset — reference
    multiRotateZ / multiControlledMultiRotateZ (QuEST_cpu.c:3268-3361)."""
    n = num_qubits
    view = amps.reshape((2,) + (2,) * n)
    theta = jnp.asarray(theta, amps.dtype)

    def phased(sub, sub_n, sub_qubits):
        sign = parity_sign(sub_n, sub_qubits, amps.dtype)
        ang = -0.5 * theta * sign
        return cplx.cmul(sub, jnp.cos(ang), jnp.sin(ang))

    if controls:
        if not control_states:
            control_states = (1,) * len(controls)
        sel = _control_selector(n, controls, control_states)
        sub_n, sub_qubits = _remap_for_controls(n, controls, qubits)
        sub = view[sel].reshape((2,) + (2,) * sub_n)
        sub = phased(sub, sub_n, sub_qubits)
        view = view.at[sel].set(sub.reshape(view[sel].shape))
    else:
        view = phased(view, n, qubits)
    return view.reshape(2, -1)


@partial(jax.jit, static_argnames=("num_qubits", "targets", "controls", "control_states"), donate_argnums=0)
def apply_multi_qubit_not(
    amps,
    *,
    num_qubits: int,
    targets: Tuple[int, ...],
    controls: Tuple[int, ...] = (),
    control_states: Tuple[int, ...] = (),
):
    """X on several targets at once (reference multiControlledMultiQubitNot,
    QuEST.h:2914).  Pure index permutation: axis reversal per target —
    no arithmetic at all, where the reference does an amplitude-pair swap
    loop (QuEST_cpu.c:2554-2660)."""
    n = num_qubits
    view = amps.reshape((2,) + (2,) * n)
    if controls:
        if not control_states:
            control_states = (1,) * len(controls)
        sel = _control_selector(n, controls, control_states)
        sub_n, sub_targets = _remap_for_controls(n, controls, targets)
        sub = view[sel].reshape((2,) + (2,) * sub_n)
        sub = jnp.flip(sub, axis=tuple(_axis(sub_n, t) for t in sub_targets))
        view = view.at[sel].set(sub.reshape(view[sel].shape))
    else:
        view = jnp.flip(view, axis=tuple(_axis(n, t) for t in targets))
    return view.reshape(2, -1)


@partial(jax.jit, static_argnames=("num_qubits", "perm"), donate_argnums=0)
def permute_qubits(amps, *, num_qubits: int, perm: Tuple[int, ...]):
    """Relabel qubits in ONE transpose pass: output qubit q holds what input
    qubit perm[q] held.  Generalizes swap_qubit_amps to arbitrary
    permutations — the single-chip analogue of the reference's distributed
    SWAP-relocalization (QuEST_cpu_distributed.c:1503-1545), used by the
    fused-circuit scheduler (circuit.py) to rotate high qubits into the
    Pallas cluster window at one-HBM-pass cost."""
    n = num_qubits
    view = amps.reshape((2,) + (2,) * n)
    axes = (0,) + tuple(_axis(n, perm[n - 1 - i]) for i in range(n))
    return jnp.transpose(view, axes).reshape(2, -1)


@partial(jax.jit, static_argnames=("num_qubits", "qb1", "qb2"), donate_argnums=0)
def swap_qubit_amps(amps, *, num_qubits: int, qb1: int, qb2: int):
    """SWAP gate = transpose of two index axes (reference swapQubitAmps,
    QuEST_cpu.c:3882-3964, which the distributed layer also uses for
    relocalization, QuEST_cpu_distributed.c:1447-1545)."""
    n = num_qubits
    view = amps.reshape((2,) + (2,) * n)
    return jnp.swapaxes(view, _axis(n, qb1), _axis(n, qb2)).reshape(2, -1)


# ---------------------------------------------------------------------------
# State initialisation (reference QuEST_cpu.c:1453-1729)
# ---------------------------------------------------------------------------


def init_blank_state(num_amps: int, dtype):
    return jnp.zeros((2, num_amps), dtype=dtype)


def init_zero_state(num_amps: int, dtype):
    return jnp.zeros((2, num_amps), dtype=dtype).at[0, 0].set(1.0)


def init_plus_state(num_amps: int, dtype):
    norm = 1.0 / math.sqrt(num_amps)
    return jnp.stack(
        [jnp.full((num_amps,), norm, dtype=dtype), jnp.zeros((num_amps,), dtype=dtype)]
    )


def init_classical_state(num_amps: int, state_index: int, dtype):
    return jnp.zeros((2, num_amps), dtype=dtype).at[0, state_index].set(1.0)


def init_debug_state(num_amps: int, dtype):
    """amp_k = (2k mod 10)/10 + i((2k+1) mod 10)/10 — reference
    initStateDebug (QuEST_cpu.c:1646, QuEST_debug.h)."""
    k = jnp.arange(num_amps, dtype=dtype)
    re = ((2.0 * k) % 10.0) / 10.0
    im = ((2.0 * k + 1.0) % 10.0) / 10.0
    return jnp.stack([re, im])


def init_classical_density(num_qubits: int, state_index: int, dtype):
    """rho = |s><s| as a flattened 2n-qubit vector (column-major,
    ket = low bits; reference densmatr_initClassicalState)."""
    dim = 1 << num_qubits
    idx = state_index + state_index * dim
    return jnp.zeros((2, dim * dim), dtype=dtype).at[0, idx].set(1.0)


def init_plus_density(num_qubits: int, dtype):
    dim = 1 << num_qubits
    return jnp.stack(
        [
            jnp.full((dim * dim,), 1.0 / dim, dtype=dtype),
            jnp.zeros((dim * dim,), dtype=dtype),
        ]
    )


# ---------------------------------------------------------------------------
# Collapse / renormalisation (reference QuEST_cpu.c:3727-3880, 785-860)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("num_qubits", "target", "outcome"), donate_argnums=0)
def collapse_statevec(amps, prob, *, num_qubits: int, target: int, outcome: int):
    """Zero the discarded half, scale kept half by 1/sqrt(prob) — one fused
    broadcast multiply instead of the reference's two-branch loop
    (statevec_collapseToKnownProbOutcomeLocal, QuEST_cpu.c:3727-3815)."""
    n = num_qubits
    view = amps.reshape((2,) + (2,) * n)
    scale = (1.0 / jnp.sqrt(jnp.asarray(prob, amps.dtype)))
    vec = jnp.zeros((2,), dtype=amps.dtype).at[outcome].set(scale)
    shape = [1] * (n + 1)
    shape[_axis(n, target)] = 2
    return (view * vec.reshape(shape)).reshape(2, -1)


@partial(jax.jit, static_argnames=("num_qubits", "target", "outcome"), donate_argnums=0)
def collapse_density(amps, prob, *, num_qubits: int, target: int, outcome: int):
    """rho: zero every element whose ket- or bra-target bit differs from the
    outcome; renormalise by 1/prob (densmatr_collapseToKnownProbOutcome,
    QuEST_cpu.c:785-860)."""
    n = num_qubits
    nn = 2 * n
    view = amps.reshape((2,) + (2,) * nn)
    keep = jnp.zeros((2,), dtype=amps.dtype).at[outcome].set(1.0)
    for q in (target, target + n):
        shape = [1] * (nn + 1)
        shape[_axis(nn, q)] = 2
        view = view * keep.reshape(shape)
    return (view / jnp.asarray(prob, amps.dtype)).reshape(2, -1)


@jax.jit
def set_weighted_qureg(amps_out, amps1, amps2, facs):
    """out = f1*q1 + f2*q2 + fOut*out (reference setWeightedQureg,
    QuEST_cpu.c:3965-4006).  ``facs`` is stacked (2, 3): the three complex
    factors (fOut, f1, f2).  Not donated: callers may alias out with q1/q2."""
    out = cplx.cmul(amps_out, facs[0, 0], facs[1, 0])
    out = out + cplx.cmul(amps1, facs[0, 1], facs[1, 1])
    out = out + cplx.cmul(amps2, facs[0, 2], facs[1, 2])
    return out


@partial(jax.jit, donate_argnums=0)
def apply_full_diagonal(amps, op_real, op_imag):
    """Elementwise multiply by a full-Hilbert diagonal operator given as
    separate real/imag vectors (statevec_applyDiagonalOp,
    QuEST_cpu.c:4007-4041)."""
    return cplx.cmul(amps, op_real.astype(amps.dtype), op_imag.astype(amps.dtype))
