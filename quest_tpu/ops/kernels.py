"""State-vector kernels: the TPU-native re-implementation of the reference's
backend kernel surface (``QuEST/src/QuEST_internal.h:116-272`` ``statevec_*``).

Design (not a port): the reference hand-codes strided amplitude-pair loops
per gate (e.g. compactUnitaryLocal, QuEST_cpu.c:1743-1800; CUDA
thread-per-pair, QuEST_gpu.cu:1037-1092).  Here a state of n qubits is a
real SoA array of shape ``(2, 2**n)`` (channel 0/1 = real/imag — the
reference's own ComplexArray layout, QuEST.h:77, and the TPU-native one:
see ops/cplx.py); a gate on targets T is a reshape / axis-move plus a small
real einsum or a broadcast elementwise multiply, and XLA generates the
strided fused loops.  Qubit q is bit q of the flat amplitude index
(little-endian), i.e. axis ``1 + (n-1-q)`` of the ``(2,) + (2,)*n`` view —
identical index convention to the reference (QuEST.h:393-400).

All functions are pure ``amps -> amps`` (or ``amps -> scalar``) and
jit-compiled with static qubit indices; the state buffer is donated so gate
chains update HBM in place (the reference instead mutates stateVec and pays
a 2x pairStateVec buffer when distributed, QuEST_cpu.c:1279-1315).

Matrices/diagonals enter as *stacked* SoA arrays ``(2, D, D)`` / ``(2, D)``
built host-side (cplx.soa) — dynamic arguments, so a parameterised gate
never recompiles when only its angle changes.

Controlled gates do not scan a control mask per amplitude as the reference
does (QuEST_cpu.c:1802-1895); they statically slice the controlled sub-block
(an axis index per control), apply the target update to the ``2**(n-c)``
surviving amplitudes, and scatter back with a dynamic-update-slice — so
bandwidth scales with the controlled subspace, beating the reference's
full-state scan.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import cplx


def _axis(n: int, q: int) -> int:
    """Axis of qubit q in the (2,) + (2,)*n channel-first view."""
    return 1 + (n - 1 - q)


# ---------------------------------------------------------------------------
# Low-rank bit views
#
# XLA-TPU materializes high-rank reshapes with tiled layouts: an all-2s
# rank-(n+1) view of the state pads each of the two minor dims to the
# (8, 128) tile, a 64x HBM blowup (34 GB at n=26), and transposes of such
# shapes take minutes to compile.  Every kernel therefore views the state
# through *coalesced* reshapes only: one small axis per qubit actually
# touched, one large axis per contiguous bit gap — rank O(k), never O(n).
# ---------------------------------------------------------------------------


def _interleaved(n: int, bits):
    """Shape splitting the flat 2^n axis at each bit (channel axis first).

    Returns (shape, axis_of): ``shape`` interleaves gap axes with one
    size-2 axis per bit in ``bits`` (any order; sorted internally);
    ``axis_of[b]`` is the index of bit b's size-2 axis."""
    bits_desc = sorted(bits, reverse=True)
    shape = [2]
    axis_of = {}
    prev = n
    for b in bits_desc:
        shape.append(1 << (prev - 1 - b))
        axis_of[b] = len(shape)
        shape.append(2)
        prev = b
    shape.append(1 << prev)
    return tuple(shape), axis_of


def _interleaved_sel(n: int, bits_states):
    """(shape, sel): interleaved view shape plus the selector tuple fixing
    each bit to its state — the low-rank control-slice used everywhere the
    reference scans a control mask (QuEST_cpu.c:1802-1895)."""
    shape, axis_of = _interleaved(n, [b for b, _ in bits_states])
    sel = [slice(None)] * len(shape)
    for b, s in bits_states:
        sel[axis_of[b]] = int(s)
    return shape, tuple(sel)


def _remap_targets(controls, targets):
    """Qubit labels inside the control-sliced sub-state (controls removed)."""
    return tuple(t - sum(1 for c in controls if c < t) for t in targets)


def _apply_with_controls(amps, n: int, controls, control_states, targets, body):
    """Run ``body(sub, sub_n, sub_targets)`` on the controlled subspace.

    Controls >= 7 are sliced out as contiguous halves (layout-safe: every
    view keeps a >= 2^7 minor axis) and reassembled by concatenation;
    controls < 7 sit inside the 128-lane block, which cannot be sliced
    without a tiny-minor layout, so the op runs on the whole lane block and
    a static 128-lane indicator mask blends updated and original lanes.
    Replaces the reference's per-amplitude control-mask scan
    (QuEST_cpu.c:1802-1895) with slicing: bandwidth scales with the
    controlled sub-block for the sliced controls."""
    if not control_states:
        control_states = (1,) * len(controls)
    if n < _BIG_N:
        cs = sorted(zip(controls, control_states), key=lambda p: -p[0])
        sub_targets = _remap_targets(controls, targets)

        def rec_small(a, nn, i):
            if i == len(cs):
                return body(a, nn, sub_targets)
            c, s = cs[i]
            v = a.reshape(2, 1 << (nn - 1 - c), 2, 1 << c)
            sub = v[:, :, int(s), :].reshape(2, -1)
            sub = rec_small(sub, nn - 1, i + 1)
            v = v.at[:, :, int(s), :].set(
                sub.reshape(v.shape[0], v.shape[1], v.shape[3])
            )
            return v.reshape(2, -1)

        return rec_small(amps, n, 0)

    high = sorted(((c, s) for c, s in zip(controls, control_states)
                   if c >= _LANE_BITS), key=lambda p: -p[0])
    low = [(c, s) for c, s in zip(controls, control_states) if c < _LANE_BITS]
    high_controls = [c for c, _ in high]
    sub_targets = _remap_targets(high_controls, targets)

    lane_mask = None
    if low:
        idx = np.arange(1 << _LANE_BITS)
        m = np.ones(1 << _LANE_BITS, dtype=bool)
        for c, s in low:
            m &= ((idx >> c) & 1) == int(s)
        lane_mask = jnp.asarray(m)

    def leaf(a, nn):
        new = body(a, nn, sub_targets)
        if lane_mask is None:
            return new
        v = a.reshape(2, -1, 1 << _LANE_BITS)
        nv = new.reshape(2, -1, 1 << _LANE_BITS)
        return jnp.where(lane_mask[None, None, :], nv, v).reshape(2, -1)

    def rec(a, nn, i):
        if i == len(high):
            return leaf(a, nn)
        c, s = high[i]
        lo_half, hi_half = _cslices(a, nn, c)
        if int(s) == 1:
            sub = rec(hi_half.reshape(2, -1), nn - 1, i + 1)
            parts = [lo_half, sub.reshape(lo_half.shape)]
        else:
            sub = rec(lo_half.reshape(2, -1), nn - 1, i + 1)
            parts = [sub.reshape(hi_half.shape), hi_half]
        return jnp.concatenate(parts, axis=2).reshape(2, -1)

    return rec(amps, n, 0)


def _split2(n: int):
    """(hi_bits, lo_bits) split of n index bits, each <= 31 so int32 iotas
    cover density-matrix index spaces (2n up to 62 bits)."""
    lo = n // 2
    return n - lo, lo


def parity_sign_2d(n: int, qubits, dtype):
    """(2^hi, 2^lo) array of (-1)^parity(bits in ``qubits``) built from two
    int32 iotas (XLA fuses it into the consuming multiply) — the vectorized
    form of the reference's bit-parity sign trick (QuEST_cpu.c:3268-3275).
    Callers view the state as (2, 2^hi, 2^lo)."""
    from ..utils import bits as bits_mod

    hi, lo = _split2(n)
    qlo = [q for q in qubits if q < lo]
    qhi = [q - lo for q in qubits if q >= lo]
    plo = bits_mod.parity_of(jax.lax.iota(jnp.int32, 1 << lo), qlo)
    phi = bits_mod.parity_of(jax.lax.iota(jnp.int32, 1 << hi), qhi)
    par = phi[:, None] ^ plo[None, :]
    return (1 - 2 * par).astype(dtype)


def parity_sign_flat(n: int, qubits, dtype):
    """(2^n,) sign vector (-1)^parity(bits in ``qubits``) from ONE flat
    iota.  Under GSPMD a flat iota partitions along the sharded amplitude
    axis with zero communication, where the factored 2-d outer-product
    form (parity_sign_2d) made XLA ALL-GATHER the sharded state to align
    the broadcast (observed: 3 all-gathers per dephasing call on the
    8-way mesh — tests/test_distributed_hlo.py pins the fixed behavior).
    int32 iota limits this to n <= 31; callers fall back to the 2-d form
    beyond that (multi-host scale, where the mask axes are mesh-aligned
    anyway)."""
    from ..utils import bits as bits_mod

    assert n <= 31, "flat parity sign needs an int32-safe index space"
    par = bits_mod.parity_of(jax.lax.iota(jnp.int32, 1 << n), list(qubits))
    return (1 - 2 * par).astype(dtype)


# The lane split: bits 0..6 form the 128-wide minor (lane) block that every
# layout-safe kernel keeps as the minor axis.  States with n >= _BIG_N take
# the layout-safe paths; smaller states use the simple einsum/reshape paths
# (tiny arrays — padding and compile time are irrelevant there).
_LANE_BITS = 7
_BIG_N = 14


def bit_2d(n: int, q: int):
    """Per-amplitude value of qubit q's bit, broadcastable over the
    (2^hi, 2^lo) = _split2(n) view of the state — the shared iota-bit
    convention used by parity_sign_2d / bit_indicator_2d /
    _apply_diagonal_flat and the models."""
    from ..utils import bits as bits_mod

    hi, lo = _split2(n)
    if q < lo:
        return bits_mod.bits_of(jax.lax.iota(jnp.int32, 1 << lo), q)[None, :]
    return bits_mod.bits_of(jax.lax.iota(jnp.int32, 1 << hi), q - lo)[:, None]


def bit_indicator_2d(n: int, bit_states, dtype):
    """(2^hi, 2^lo) {0,1} array: 1 where every (bit, state) pair matches —
    iota-built so XLA fuses it into the consuming multiply (layout-safe at
    any bit position, unlike a size-2-axis broadcast)."""
    from ..utils import bits as bits_mod

    hi, lo = _split2(n)
    ilo = jax.lax.iota(jnp.int32, 1 << lo)
    ihi = jax.lax.iota(jnp.int32, 1 << hi)
    mlo = jnp.ones((1 << lo,), bool)
    mhi = jnp.ones((1 << hi,), bool)
    for b, s in bit_states:
        if b < lo:
            mlo = mlo & (bits_mod.bits_of(ilo, b) == int(s))
        else:
            mhi = mhi & (bits_mod.bits_of(ihi, b - lo) == int(s))
    return (mhi[:, None] & mlo[None, :]).astype(dtype)


def _flip_bits_flat(amps, n: int, targets):
    """X on each target = index-space reversal.  Low targets (< 7) fold into
    one lane-matmul permutation; high targets are a swapped-halves
    concatenation per target — never a small-minor flip."""
    if not targets:
        return amps
    if n < _BIG_N:
        shape, axis_of = _interleaved(n, targets)
        view = amps.reshape(shape)
        return jnp.flip(view, axis=tuple(axis_of[t] for t in targets)).reshape(2, -1)
    low = tuple(t for t in targets if t < _LANE_BITS)
    if low:
        xmat = _embed_lane_from_traced(
            jnp.asarray(_x_product_np(low), amps.dtype), low
        )
        amps = _lane_matmul(amps, xmat)
    for t in targets:
        if t < _LANE_BITS:
            continue
        B = 1 << t
        v = amps.reshape(2, 1 << (n - 1 - t), 2 * B)
        amps = jnp.concatenate([v[:, :, B:], v[:, :, :B]], axis=2).reshape(2, -1)
    return amps


def _x_product_np(low_targets):
    """SoA (2, 2^k, 2^k) matrix of X on each of ``low_targets`` (np)."""
    k = len(low_targets)
    d = 1 << k
    idx = np.arange(d)
    flipped = idx
    for j in range(k):
        flipped = flipped ^ (1 << j)
    m = np.zeros((2, d, d), np.float64)
    m[0, flipped, idx] = 1.0
    return m


def _lane_rep(mat_soa):
    """(2,128,128) SoA -> (256,256) real right-multiplier for lane
    contraction of [re | im] concatenated rows (see ops/fused.py)."""
    ar, ai = mat_soa[0], mat_soa[1]
    top = jnp.concatenate([ar.T, ai.T], axis=1)
    bot = jnp.concatenate([-ai.T, ar.T], axis=1)
    return jnp.concatenate([top, bot], axis=0)


def _lane_matmul(amps, lane_mat_soa):
    """Apply a (2,128,128) SoA matrix to the lane bits (0..6) of the whole
    state: one MXU pass, minor dims (rows, 256) — never padded."""
    r = _lane_rep(lane_mat_soa)
    v = amps.reshape(2, -1, 1 << _LANE_BITS)
    xc = jnp.concatenate([v[0], v[1]], axis=-1)
    out = jax.lax.dot_general(
        xc, r, (((1,), (0,)), ((), ())),
        preferred_element_type=amps.dtype,
        precision=jax.lax.Precision.HIGHEST,
    )
    d = 1 << _LANE_BITS
    return jnp.stack([out[:, :d], out[:, d:]]).reshape(2, -1)


def _cslices(amps, n: int, t: int):
    """Contiguous halves of the state at bit t (t >= _LANE_BITS): two
    (2, A, 2^t) views — minor dim 2^t >= 128, layout-safe."""
    B = 1 << t
    v = amps.reshape(2, 1 << (n - 1 - t), 2 * B)
    return v[:, :, :B], v[:, :, B:]


def _apply_matrix_flat(amps, n: int, targets, msoa):
    """Complex k-qubit matrix (stacked SoA (2, 2^k, 2^k)) on flat (2, 2^n)
    SoA amps; targets[0] = least-significant matrix bit (reference
    convention).

    Layout-safe decomposition (n >= _BIG_N): recursive contiguous halving
    over targets >= 7 (slices and concats keep a >=2^7 minor axis), with the
    residual low-bit (< 7) block applied as one embedded 128x128 lane
    matmul per (i,j) high-block pair.  XLA-TPU materializes any reshape
    whose minor dim is tiny with (8,128)-tile padding — a 64x HBM blowup at
    26 qubits — so the einsum-over-bit-axes form is reserved for small n."""
    if n < _BIG_N:
        return _apply_matrix_small(amps, n, targets, cplx.real_matrix_rep(msoa))
    high = [t for t in targets if t >= _LANE_BITS]
    low = tuple(t for t in targets if t < _LANE_BITS)
    # matrix bit index of each target
    mbit = {t: j for j, t in enumerate(targets)}
    kl = len(low)
    dl = 1 << kl

    def sub_block(ih, jh):
        """SoA (2, 2^kl, 2^kl) sub-block for high-bit rows ih / cols jh."""
        row = 0
        col = 0
        for pos, t in enumerate(high):
            row |= ((ih >> pos) & 1) << mbit[t]
            col |= ((jh >> pos) & 1) << mbit[t]
        rows = [row | _scatter_low(i, low, mbit) for i in range(dl)]
        cols = [col | _scatter_low(j, low, mbit) for j in range(dl)]
        return msoa[:, jnp.asarray(rows)[:, None], jnp.asarray(cols)[None, :]]

    if not high:
        # pure low-bit gate: one lane matmul with the embedded matrix
        emb = _embed_lane_from_traced(msoa, low)
        return _lane_matmul(amps, emb)

    # Iterative slab decomposition: gather the 2^kh slabs by repeated
    # contiguous halving (descending bit order keeps positions valid).
    kh = len(high)
    highs_desc = sorted(high, reverse=True)
    slabs = [(amps, n)]
    for t in highs_desc:
        nxt = []
        for x, nn in slabs:
            a, b = _cslices(x, nn, t)
            nxt.append((a.reshape(2, -1), nn - 1))
            nxt.append((b.reshape(2, -1), nn - 1))
        slabs = nxt
    # slabs index: bit p of slab index = value of highs_desc[p] (MSB-first
    # split order); convert to high-bit tuple order (high[pos] = bit pos)
    def slab_hbits(si):
        h = 0
        for p, t in enumerate(highs_desc):
            bitval = (si >> (kh - 1 - p)) & 1
            h |= bitval << high.index(t)
        return h

    hmap = [slab_hbits(si) for si in range(1 << kh)]
    inv = [0] * (1 << kh)
    for si, hv in enumerate(hmap):
        inv[hv] = si
    outs = []
    for ih in range(1 << kh):
        acc = None
        for jh in range(1 << kh):
            xj = slabs[inv[jh]][0]
            blk = sub_block(ih, jh)
            if kl:
                emb = _embed_lane_from_traced(blk, low)
                term = _lane_matmul(xj, emb)
            else:
                term = cplx.cmul(xj, blk[0, 0, 0], blk[1, 0, 0])
            acc = term if acc is None else acc + term
        outs.append(acc)
    # reassemble in split order (inverse of halving): concat bottom-up
    level = [outs[hmap[si]] for si in range(1 << kh)]
    for t in reversed(highs_desc):
        nxt = []
        for i in range(0, len(level), 2):
            a, b = level[i], level[i + 1]
            nxt.append(jnp.concatenate(
                [a.reshape(2, -1, 1 << t), b.reshape(2, -1, 1 << t)], axis=2
            ).reshape(2, -1))
        level = nxt
    return level[0]


def _scatter_low(i, low, mbit):
    v = 0
    for pos, t in enumerate(low):
        v |= ((i >> pos) & 1) << mbit[t]
    return v


def _embed_lane_from_traced(mat_soa, bits):
    """Embed a traced SoA (2, 2^k, 2^k) matrix onto lane bits ``bits`` of
    the (2,128,128) lane space via precomputed static gather indices."""
    d = 1 << _LANE_BITS
    idx = np.arange(d)
    sub = np.zeros_like(idx)
    for j, b in enumerate(bits):
        sub |= ((idx >> b) & 1) << j
    rest = idx.copy()
    for b in bits:
        rest &= ~(1 << b)
    mask = jnp.asarray((rest[:, None] == rest[None, :]).astype(np.float32),
                       mat_soa.dtype)
    return mat_soa[:, sub[:, None], sub[None, :]] * mask


def _apply_matrix_small(amps, n: int, targets, rmat):
    """Original einsum path for small states (tests / CPU / n < 14)."""
    k = len(targets)
    if k == 1:
        t = targets[0]
        v = amps.reshape(2, 2 ** (n - 1 - t), 2, 2 ** t)
        # HIGHEST: stop TPU from doing the 2-wide contraction in bf16 —
        # it is bandwidth-bound, so full f32 costs nothing and keeps ~1e-7
        # gate error instead of ~1e-3 (observed with the default precision).
        out = jnp.einsum("cdab,dpbq->cpaq", rmat, v,
                         precision=jax.lax.Precision.HIGHEST)
        return out.reshape(2, -1)
    f, g = _targets_to_top_perms(n, targets)
    flat = _permute_impl(amps, n, f)
    xs = flat.reshape(2, 2 ** k, -1)
    out = jnp.einsum("cdij,djr->cir", rmat, xs,
                     precision=jax.lax.Precision.HIGHEST)
    return _permute_impl(out.reshape(2, -1), n, g)


def _targets_to_top_perms(n: int, targets):
    """(forward, inverse) qubit permutations placing ``targets`` at the top
    bit positions (targets[k-1] = MSB), everything else in original order."""
    order_fwd = list(reversed(targets)) + [
        q for q in range(n - 1, -1, -1) if q not in targets
    ]
    f = [0] * n  # f[output position] = input qubit
    for idx, q in enumerate(order_fwd):
        f[n - 1 - idx] = q
    g = [0] * n  # inverse permutation
    for p, q in enumerate(f):
        g[q] = p
    return tuple(f), tuple(g)


@partial(
    jax.jit,
    static_argnames=("num_qubits", "targets", "controls", "control_states"),
    donate_argnums=0,
)
def apply_matrix(
    amps,
    matrix,
    *,
    num_qubits: int,
    targets: Tuple[int, ...],
    controls: Tuple[int, ...] = (),
    control_states: Tuple[int, ...] = (),
):
    """Apply a dense 2^k x 2^k matrix to target qubits, optionally controlled.

    Covers the reference's unitary/compactUnitary/twoQubitUnitary/
    multiQubitUnitary and every multi(State)Controlled* variant
    (QuEST_cpu.c:1743-1985) as one kernel; ``control_states`` generalizes to
    control-on-zero (reference multiStateControlledUnitary, QuEST.h:3877).
    ``matrix`` is stacked SoA (2, 2^k, 2^k).
    """
    n = num_qubits
    in_shape = amps.shape
    matrix = jnp.asarray(matrix, amps.dtype)
    if controls:
        out = _apply_with_controls(
            amps, n, controls, control_states, targets,
            lambda sub, sub_n, sub_t: _apply_matrix_flat(sub, sub_n, sub_t, matrix),
        )
    else:
        out = _apply_matrix_flat(amps, n, targets, matrix)
    return out.reshape(in_shape)


def _apply_diagonal_flat(amps, n: int, targets, diag):
    """Multiply by diag[bits(targets)] — the phase-only kernel family.

    Big states: the factor is a sum of 2^k iota-bit indicators over a
    (2, 2^hi, 2^lo) view (both axes >= 128 — layout-safe, and XLA fuses the
    whole chain into the multiply); small states use an interleaved
    broadcast."""
    k = len(targets)
    if n < _BIG_N:
        shape, axis_of = _interleaved(n, targets)
        view = amps.reshape(shape)
        # diag bit j <-> targets[j]; reorder its axes to the (descending)
        # interleaved bit order, then stretch with singleton gap axes.
        dv = diag.reshape((2,) + (2,) * k)
        order = sorted(targets, reverse=True)
        dv = jnp.transpose(
            dv, (0,) + tuple(1 + (k - 1 - targets.index(t)) for t in order)
        )
        bshape = [1] * len(shape)
        for i, t in enumerate(order):
            bshape[axis_of[t]] = 2
        f_re = dv[0].reshape(bshape[1:])
        f_im = dv[1].reshape(bshape[1:])
        return cplx.cmul(view, f_re, f_im).reshape(2, -1)
    hi, lo = _split2(n)
    bit = partial(bit_2d, n)

    if k <= 6:
        f_re = jnp.zeros((1, 1), amps.dtype)
        f_im = jnp.zeros((1, 1), amps.dtype)
        for v in range(1 << k):
            ind = None
            for j, t in enumerate(targets):
                eq = bit(t) == ((v >> j) & 1)
                ind = eq if ind is None else (ind & eq)
            indf = ind.astype(amps.dtype)
            f_re = f_re + diag[0, v] * indf
            f_im = f_im + diag[1, v] * indf
    else:
        code = jnp.zeros((1, 1), jnp.int32)
        for j, t in enumerate(targets):
            code = code + (bit(t) << j)
        f_re = jnp.take(diag[0], code, axis=0)
        f_im = jnp.take(diag[1], code, axis=0)
    view = amps.reshape(2, 1 << hi, 1 << lo)
    return cplx.cmul(view, f_re, f_im).reshape(2, -1)


@partial(
    jax.jit,
    static_argnames=("num_qubits", "targets", "controls", "control_states"),
    donate_argnums=0,
)
def apply_diagonal(
    amps,
    diag,
    *,
    num_qubits: int,
    targets: Tuple[int, ...],
    controls: Tuple[int, ...] = (),
    control_states: Tuple[int, ...] = (),
):
    """Multiply amplitudes by ``diag[bits(targets)]`` — the phase-only kernel
    family (reference phaseShiftByTerm/multiControlledPhaseShift/phase-flip,
    QuEST_cpu.c:3146-3361) which needs no amplitude pairing.  ``diag`` is
    stacked SoA (2, 2^k), exponentiated host-side — no transcendental runs
    per amplitude."""
    n = num_qubits
    in_shape = amps.shape
    diag = jnp.asarray(diag, amps.dtype)
    if controls:
        out = _apply_with_controls(
            amps, n, controls, control_states, targets,
            lambda sub, sub_n, sub_t: _apply_diagonal_flat(sub, sub_n, sub_t, diag),
        )
    else:
        out = _apply_diagonal_flat(amps, n, targets, diag)
    return out.reshape(in_shape)


@partial(
    jax.jit,
    static_argnames=("num_qubits", "qubits", "controls", "control_states"),
    donate_argnums=0,
)
def apply_parity_phase(
    amps,
    theta,
    *,
    num_qubits: int,
    qubits: Tuple[int, ...],
    controls: Tuple[int, ...] = (),
    control_states: Tuple[int, ...] = (),
):
    """exp(-i theta/2 * Z x Z ... Z) over a qubit subset — reference
    multiRotateZ / multiControlledMultiRotateZ (QuEST_cpu.c:3268-3361)."""
    n = num_qubits
    theta = jnp.asarray(theta, amps.dtype)

    def phased(sub, sub_n, sub_qubits):
        ang = -0.5 * theta
        if sub_n <= 31:
            # flat sign: partitions along the sharded amplitude axis with
            # zero communication (see parity_sign_flat); flatten first so a
            # canonical 4-d view input broadcasts correctly
            sub = sub.reshape(2, -1)
            s = parity_sign_flat(sub_n, sub_qubits, amps.dtype)
            return cplx.cmul(sub, jnp.cos(ang), jnp.sin(ang) * s)
        s = parity_sign_2d(sub_n, sub_qubits, amps.dtype)
        view = sub.reshape(2, s.shape[0], s.shape[1])
        # e^{i ang s} = cos(ang) + i s sin(ang) (cos even, sin odd in s)
        out = cplx.cmul(view, jnp.cos(ang), jnp.sin(ang) * s)
        return out.reshape(2, -1)

    if controls:
        out = _apply_with_controls(
            amps, n, controls, control_states, qubits,
            lambda sub, sub_n, sub_q: phased(sub, sub_n, sub_q),
        )
    else:
        out = phased(amps, n, qubits)
    return out.reshape(amps.shape)


@partial(jax.jit, static_argnames=("num_qubits", "targets", "controls", "control_states"), donate_argnums=0)
def apply_multi_qubit_not(
    amps,
    *,
    num_qubits: int,
    targets: Tuple[int, ...],
    controls: Tuple[int, ...] = (),
    control_states: Tuple[int, ...] = (),
):
    """X on several targets at once (reference multiControlledMultiQubitNot,
    QuEST.h:2914).  Pure index permutation: axis reversal per target —
    no arithmetic at all, where the reference does an amplitude-pair swap
    loop (QuEST_cpu.c:2554-2660)."""
    n = num_qubits
    if controls:
        out = _apply_with_controls(
            amps, n, controls, control_states, targets,
            lambda sub, sub_n, sub_t: _flip_bits_flat(sub, sub_n, sub_t),
        )
    else:
        out = _flip_bits_flat(amps, n, targets)
    return out.reshape(amps.shape)


@partial(jax.jit, static_argnames=("num_qubits", "perm"), donate_argnums=0)
def permute_qubits(amps, *, num_qubits: int, perm: Tuple[int, ...]):
    """Relabel qubits in ONE transpose pass: output qubit q holds what input
    qubit perm[q] held.  Generalizes swap_qubit_amps to arbitrary
    permutations — the single-chip analogue of the reference's distributed
    SWAP-relocalization (QuEST_cpu_distributed.c:1503-1545), used by the
    fused-circuit scheduler (circuit.py) to rotate high qubits into the
    Pallas cluster window at one-HBM-pass cost.

    Contiguous bit runs are coalesced into single axes so the transpose XLA
    sees is low-rank (a rank-(n+1) transpose makes the TPU backend's compile
    time explode past n≈18); permutations that still would not coalesce are
    decomposed into pairwise swaps, each itself a rank-<=6 transpose."""
    return _permute_impl(amps, num_qubits, perm).reshape(amps.shape)


def _permute_impl(amps, n: int, perm: Tuple[int, ...]):
    order = tuple(perm[n - 1 - i] for i in range(n))  # input qubits, MSB->LSB
    runs = _coalesce_runs(order)
    if len(runs) <= _MAX_TRANSPOSE_RANK:
        return _transpose_runs(amps, runs)
    # Fallback: selection-sort into place via pairwise swaps.  cur[q] = input
    # qubit currently at position q; each swap is a cheap coalesced transpose.
    cur = list(range(n))
    for q in range(n):
        if cur[q] != perm[q]:
            j = cur.index(perm[q])
            amps = _swap_impl(amps, n, q, j)
            cur[q], cur[j] = cur[j], cur[q]
    return amps


def _coalesce_runs(order):
    """Merge descending runs of ``order`` (input qubits listed MSB->LSB).
    A descending run hi..lo is a contiguous little-endian bit block, hence a
    single axis of the input layout.  Returns [(hi, len), ...] in output
    order; the runs partition 0..n-1 into disjoint bit intervals."""
    runs = []
    hi = cur = order[0]
    ln = 1
    for q in order[1:]:
        if q == cur - 1:
            cur = q
            ln += 1
        else:
            runs.append((hi, ln))
            hi = cur = q
            ln = 1
    runs.append((hi, ln))
    return runs


# Above this transpose rank, fall back to pairwise swaps (XLA TPU compile
# time grows super-linearly in transpose rank; <=9 axes compiles in ms).
_MAX_TRANSPOSE_RANK = 8


def _transpose_runs(amps, runs):
    """Transpose coalesced bit runs: reshape to one axis per run (input
    order = descending bit position), permute to output order, flatten."""
    in_order = sorted(runs, key=lambda r: -r[0])
    shape = (2,) + tuple(1 << ln for _, ln in in_order)
    axis_of = {r: i + 1 for i, r in enumerate(in_order)}
    axes = (0,) + tuple(axis_of[r] for r in runs)
    return jnp.transpose(amps.reshape(shape), axes).reshape(2, -1)


@partial(jax.jit, static_argnames=("num_qubits", "qb1", "qb2"), donate_argnums=0)
def swap_qubit_amps(amps, *, num_qubits: int, qb1: int, qb2: int):
    """SWAP gate = transpose of two index axes (reference swapQubitAmps,
    QuEST_cpu.c:3882-3964, which the distributed layer also uses for
    relocalization, QuEST_cpu_distributed.c:1447-1545).  Expressed as a
    rank-6 transpose over coalesced bit blocks, independent of n."""
    return _swap_impl(amps, num_qubits, qb1, qb2).reshape(amps.shape)


_SWAP_SOA = np.zeros((2, 4, 4))
_SWAP_SOA[0] = np.eye(4)[[0, 2, 1, 3]]


def _swap_impl(amps, n: int, qb1: int, qb2: int):
    i, j = max(qb1, qb2), min(qb1, qb2)
    if i == j:
        return amps
    if n >= _BIG_N:
        # A low-bit transpose would materialize with a tiny minor dim
        # (tile-padded 64x); the dense-gate decomposition is one fused pass.
        return _apply_matrix_flat(
            amps, n, (j, i), jnp.asarray(_SWAP_SOA, amps.dtype)
        )
    view = amps.reshape(2, 1 << (n - 1 - i), 2, 1 << (i - j - 1), 2, 1 << j)
    return jnp.transpose(view, (0, 1, 4, 3, 2, 5)).reshape(2, -1)


@partial(jax.jit, static_argnames=("num_qubits", "a", "b", "m"), donate_argnums=0)
def swap_bit_segments(amps, *, num_qubits: int, a: int, b: int, m: int):
    """Exchange the m-bit index segments [a, a+m) and [b, b+m) (a >= b+m).

    This is the TPU-native relocalization move used by the circuit
    scheduler: with b >= 7 the transpose keeps the 2^b >= 128 lane block as
    its minor axis and the 2^m segment as second-minor, so XLA's (8,128)
    tiling needs no padding (unlike single-bit swaps).  Plays the role of
    the reference's SWAP-relocalization of high qubits
    (QuEST_cpu_distributed.c:1503-1545), but moves a whole page per pass."""
    n = num_qubits
    assert a >= b + m, (a, b, m)
    view = amps.reshape(
        2, 1 << (n - a - m), 1 << m, 1 << (a - b - m), 1 << m, 1 << b
    )
    return jnp.transpose(view, (0, 1, 4, 3, 2, 5)).reshape(amps.shape)


# Gather field width cap for apply_index_permutation: past this extent the
# static index table (2^width entries) stops being worth materializing and
# the op falls back to the exact 0/1 permutation-matrix pass.
_GATHER_FIELD_MAX_BITS = 16


@partial(jax.jit, static_argnames=("num_qubits", "targets", "pi"), donate_argnums=0)
def apply_index_permutation(
    amps, *, num_qubits: int, targets: Tuple[int, ...], pi: Tuple[int, ...]
):
    """General basis-index permutation on ``targets``: the new amplitude at
    target-field sub-index i is the old amplitude at sub-index ``pi[i]``
    (``new[i] = old[pi[i]]``, matching circuit.classify_permutation_gate's
    row convention).  This is the gather lowering of the permutation gate
    family (circuit.py §28) — CNOT/Toffoli/MCX products execute as ONE
    static gather pass instead of a cluster matmul, and the move is
    bit-exact (amplitudes are relocated, never recombined).

    Layout: the gather runs along a contiguous bit field [lo, hi] covering
    the targets, viewed as (2, pre, 2^field, 2^lo).  At n >= _BIG_N a field
    reaching below the 128-lane block is extended down to bit 0 so the
    gathered axis stays tile-wide (the tiny-minor rule every kernel here
    follows); fields wider than _GATHER_FIELD_MAX_BITS fall back to the
    exact 0/1 permutation matrix through _apply_matrix_flat (single gates
    have <= 7 targets, so the matrix stays <= 128x128)."""
    n = num_qubits
    lo, hi = min(targets), max(targets)
    if n >= _BIG_N and lo < _LANE_BITS:
        lo = 0
        hi = max(hi, _LANE_BITS - 1)
    if hi + 1 - lo > _GATHER_FIELD_MAX_BITS:
        d = 1 << len(targets)
        m = np.zeros((2, d, d), np.float64)
        m[0, np.arange(d), np.asarray(pi, dtype=np.int64)] = 1.0
        return _apply_matrix_flat(
            amps, n, tuple(targets), jnp.asarray(m, amps.dtype)
        ).reshape(amps.shape)
    span = hi + 1 - lo
    d = 1 << span
    idx = np.arange(d)
    sub = np.zeros(d, dtype=np.int64)
    for b, t in enumerate(targets):
        sub |= ((idx >> (t - lo)) & 1) << b
    mapped = np.asarray(pi, dtype=np.int64)[sub]
    lifted = idx.copy()
    for t in targets:
        lifted &= ~(1 << (t - lo))
    for b, t in enumerate(targets):
        lifted |= ((mapped >> b) & 1) << (t - lo)
    view = amps.reshape(2, 1 << (n - hi - 1), d, 1 << lo)
    out = view[:, :, jnp.asarray(lifted), :]
    return out.reshape(amps.shape)


# ---------------------------------------------------------------------------
# State initialisation (reference QuEST_cpu.c:1453-1729)
# ---------------------------------------------------------------------------


def init_blank_state(num_amps: int, dtype):
    return jnp.zeros((2, num_amps), dtype=dtype)


def init_zero_state(num_amps: int, dtype):
    return jnp.zeros((2, num_amps), dtype=dtype).at[0, 0].set(1.0)


def init_plus_state(num_amps: int, dtype):
    norm = 1.0 / math.sqrt(num_amps)
    return jnp.stack(
        [jnp.full((num_amps,), norm, dtype=dtype), jnp.zeros((num_amps,), dtype=dtype)]
    )


def init_classical_state(num_amps: int, state_index: int, dtype):
    return jnp.zeros((2, num_amps), dtype=dtype).at[0, state_index].set(1.0)


def init_sparse_state(num_amps: int, indices, res, ims, dtype):
    """Scatter k nonzero amplitudes into an otherwise-zero state — the
    dense-side materialization of sparse state preparation (circuit.py
    §28, arXiv:2504.08705): cost scales with k for the scatter plus one
    zeros fill, never with explicit per-amplitude host uploads."""
    idx = jnp.asarray(np.asarray(indices, dtype=np.int64))
    re = jnp.asarray(res, dtype=dtype)
    im = jnp.asarray(ims, dtype=dtype)
    return (jnp.zeros((2, num_amps), dtype=dtype)
            .at[0, idx].set(re).at[1, idx].set(im))


def init_debug_state(num_amps: int, dtype):
    """amp_k = (2k mod 10)/10 + i((2k+1) mod 10)/10 — reference
    initStateDebug (QuEST_cpu.c:1646, QuEST_debug.h)."""
    k = jnp.arange(num_amps, dtype=dtype)
    re = ((2.0 * k) % 10.0) / 10.0
    im = ((2.0 * k + 1.0) % 10.0) / 10.0
    return jnp.stack([re, im])


def init_classical_density(num_qubits: int, state_index: int, dtype):
    """rho = |s><s| as a flattened 2n-qubit vector (column-major,
    ket = low bits; reference densmatr_initClassicalState)."""
    dim = 1 << num_qubits
    idx = state_index + state_index * dim
    return jnp.zeros((2, dim * dim), dtype=dtype).at[0, idx].set(1.0)


def init_plus_density(num_qubits: int, dtype):
    dim = 1 << num_qubits
    return jnp.stack(
        [
            jnp.full((dim * dim,), 1.0 / dim, dtype=dtype),
            jnp.zeros((dim * dim,), dtype=dtype),
        ]
    )


# ---------------------------------------------------------------------------
# Collapse / renormalisation (reference QuEST_cpu.c:3727-3880, 785-860)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("num_qubits", "target", "outcome"), donate_argnums=0)
def collapse_statevec(amps, prob, *, num_qubits: int, target: int, outcome: int):
    """Zero the discarded half, scale kept half by 1/sqrt(prob) — one fused
    broadcast multiply instead of the reference's two-branch loop
    (statevec_collapseToKnownProbOutcomeLocal, QuEST_cpu.c:3727-3815)."""
    n = num_qubits
    scale = (1.0 / jnp.sqrt(jnp.asarray(prob, amps.dtype)))
    ind = bit_indicator_2d(n, ((target, outcome),), amps.dtype)
    view = amps.reshape(2, ind.shape[0], ind.shape[1])
    return (view * (scale * ind)[None]).reshape(amps.shape)


@partial(jax.jit, static_argnames=("num_qubits", "target", "outcome"), donate_argnums=0)
def collapse_density(amps, prob, *, num_qubits: int, target: int, outcome: int):
    """rho: zero every element whose ket- or bra-target bit differs from the
    outcome; renormalise by 1/prob (densmatr_collapseToKnownProbOutcome,
    QuEST_cpu.c:785-860)."""
    n = num_qubits
    nn = 2 * n
    ind = bit_indicator_2d(
        nn, ((target, outcome), (target + n, outcome)), amps.dtype
    )
    view = amps.reshape(2, ind.shape[0], ind.shape[1])
    return (view * (ind / jnp.asarray(prob, amps.dtype))[None]).reshape(2, -1)


@jax.jit
def set_weighted_qureg(amps_out, amps1, amps2, facs):
    """out = f1*q1 + f2*q2 + fOut*out (reference setWeightedQureg,
    QuEST_cpu.c:3965-4006).  ``facs`` is stacked (2, 3): the three complex
    factors (fOut, f1, f2).  Not donated: callers may alias out with
    q1/q2 (donating a buffer that is ALSO passed as another live argument
    is undefined); the API layer routes the common non-aliased case
    through set_weighted_qureg_donated instead."""
    out = cplx.cmul(amps_out, facs[0, 0], facs[1, 0])
    out = out + cplx.cmul(amps1, facs[0, 1], facs[1, 1])
    out = out + cplx.cmul(amps2, facs[0, 2], facs[1, 2])
    return out


@partial(jax.jit, donate_argnums=0)
def set_weighted_qureg_donated(amps_out, amps1, amps2, facs):
    """set_weighted_qureg with ``out`` donated — the in-place form for the
    (typical) call where ``out`` is a distinct register from q1/q2, saving
    one full state of HBM on the three-register combine (donation audit,
    tests/test_donation.py)."""
    out = cplx.cmul(amps_out, facs[0, 0], facs[1, 0])
    out = out + cplx.cmul(amps1, facs[0, 1], facs[1, 1])
    out = out + cplx.cmul(amps2, facs[0, 2], facs[1, 2])
    return out


@partial(jax.jit, donate_argnums=0)
def apply_full_diagonal(amps, op_real, op_imag):
    """Elementwise multiply by a full-Hilbert diagonal operator given as
    separate real/imag vectors (statevec_applyDiagonalOp,
    QuEST_cpu.c:4007-4041)."""
    return cplx.cmul(amps, op_real.astype(amps.dtype), op_imag.astype(amps.dtype))


@partial(jax.jit, static_argnames=("num_qubits", "target", "base", "conj"),
         donate_argnums=0)
def apply_qft_ladder(amps, *, num_qubits: int, target: int, base: int = 0,
                     conj: bool = False):
    """One QFT layer in ONE fused elementwise pass: Hadamard on ``target``
    followed by the whole controlled-phase ladder against the contiguous
    qubits [base, target), i.e. diag(1, e^{i*pi*low/2^(target-base)}) on the
    target with low = the integer held in those qubits.  The reference
    builds the same layer from one H sweep plus a SCALED_PRODUCT phase
    sweep (agnostic_applyQFT, QuEST_common.c:836-898) — two HBM passes and
    no fusion; here the pair combine and the index-derived phase fuse into
    a single XLA program.  ``base`` > 0 serves the density-matrix bra twin
    (qubits shifted by numQubits); ``conj`` negates the ladder phases.

    The phase exp(i*pi*low/2^tr) factorizes over 7-bit chunks of ``low``
    into HOST-precomputed tables of <= 128 entries each (it is an
    exponential of a sum of per-bit contributions), applied as broadcast
    complex multiplies.  vs the previous on-device recursive-doubling
    table: compile time for a full 26q QFT dropped from ~300 s (26
    unrolled concat chains blew up XLA) to seconds, and for tr >= 10 the
    view's two minor axes are (bits 7-13 chunk, bits 0-6 chunk) —
    layout-identical to the canonical window views (see ops/fused.py), so
    consecutive ladder passes exchange state via free bitcasts instead of
    ~4 ms retile copies.
    """
    n, t = num_qubits, target
    from . import fused as _fused

    if _fused.qft_ladder_supported(amps.dtype, n, t, base):
        # one Pallas pass (canonical layout, pair halves co-resident):
        # ~3x the XLA elementwise formulation, which splits into several
        # fusions around the pair-axis slice/stack
        return _fused.apply_qft_ladder_pallas(
            amps, num_qubits=n, target=t, conj=conj)
    tr = t - base
    lo = 1 << base         # untouched low axis (bra-twin case)
    hi = 1 << (n - 1 - t)
    dt = amps.dtype
    sgn = -1.0 if conj else 1.0
    inv = jnp.asarray(1.0 / math.sqrt(2.0), dt)

    if tr < 10 and base == 0:
        # small ladder: one table, simple view.  The canonical minor-axes
        # split (bits 7-13, bits 0-6) needs the second-minor axis to span
        # >= 8 values of bits 7-9, i.e. tr >= 10; below that the view
        # cannot be layout-compatible anyway, so keep it flat.
        widths = [tr]
    else:
        widths = []        # 7-bit chunks from the low end
        p = 0
        while p < tr:
            widths.append(min(7, tr - p))
            p += 7
    tabs = []
    p = 0
    for w in widths:
        j = np.arange(1 << w, dtype=np.float64)
        ang = sgn * np.pi * (j * (1 << p)) / (1 << tr)
        tabs.append((np.cos(ang).astype(dt), np.sin(ang).astype(dt)))
        p += w
    # axis order after [2, hi, 2(pair)]: highest chunk first, lowest chunk
    # last, then the untouched lo axis (if any)
    factor_dims = [1 << w for w in reversed(widths)]
    shape = [2, hi, 2] + factor_dims + ([lo] if base else [])
    v = amps.reshape(shape)
    x0r, x0i = v[0, :, 0], v[1, :, 0]
    x1r, x1i = v[0, :, 1], v[1, :, 1]
    y0r, y0i = (x0r + x1r) * inv, (x0i + x1i) * inv
    y1r, y1i = (x0r - x1r) * inv, (x0i - x1i) * inv
    ntail = len(widths) + (1 if base else 0)   # axes after hi in y*
    for ci, (w, (tc, ts)) in enumerate(zip(widths, tabs)):
        axis_from_end = (1 if base else 0) + ci
        bshape = [1] * (1 + ntail)
        bshape[len(bshape) - 1 - axis_from_end] = 1 << w
        pr = jnp.asarray(tc).reshape(bshape)
        pi_ = jnp.asarray(ts).reshape(bshape)
        y1r, y1i = pr * y1r - pi_ * y1i, pr * y1i + pi_ * y1r
    out = jnp.stack([
        jnp.stack([y0r, y1r], axis=1),
        jnp.stack([y0i, y1i], axis=1),
    ])
    return out.reshape(amps.shape)
