"""Density-matrix kernels: decoherence channels and rho-specific ops.

A density matrix of n qubits is stored exactly as the reference stores it
(QuEST.c:8-10, QuEST_common.c:9-11): a flattened 2n-qubit state-vector,
column-major, ket qubits 0..n-1 (low index bits) and bra qubits n..2n-1.
Unitaries on rho are the ket-op followed by the conjugated bra-twin
(handled by the API layer); everything here is the rho-only kernel set
(QuEST_internal.h:63-109 densmatr_*).

Channels are realised through the Choi isomorphism: a Kraus map {K_k} on
targets T becomes the dense superoperator sum_k conj(K_k) (x) K_k applied as
an ordinary 2k-qubit matrix on targets (T, T+n) — the reference's own
generic path (macro_populateKrausOperator, QuEST_common.c:595-652).  The
one- and two-qubit dephasing channels additionally get fused elementwise
fast paths (the reference's dedicated kernels, QuEST_cpu.c:48-123).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import cplx, gatedefs, kernels


def superoperator_from_kraus(kraus_ops):
    """sum_k conj(K_k) (x) K_k — acts on [bra-bits | ket-bits] of the
    column-major vec(rho) (reference macro_populateKrausOperator,
    QuEST_common.c:595-628).  Built host-side: at most 2^{2k} x 2^{2k}
    NumPy work, entering the jitted kernel as a dynamic argument."""
    s = None
    for k in kraus_ops:
        k = np.asarray(k, dtype=np.complex128)
        term = np.kron(np.conj(k), k)
        s = term if s is None else s + term
    return s


def kraus_targets(targets: Sequence[int], num_qubits: int) -> Tuple[int, ...]:
    """Superoperator target list: ket targets then bra twins (t+n)."""
    return tuple(targets) + tuple(t + num_qubits for t in targets)


def apply_kraus_map(amps, kraus_ops, *, num_qubits: int, targets: Tuple[int, ...]):
    """mixKrausMap / mixTwoQubitKrausMap / mixMultiQubitKrausMap
    (QuEST_common.c:630-728)."""
    s = superoperator_from_kraus(kraus_ops)
    return kernels.apply_matrix(
        amps,
        cplx.soa(s),
        num_qubits=2 * num_qubits,
        targets=kraus_targets(targets, num_qubits),
    )


@partial(jax.jit, static_argnames=("num_qubits", "target"), donate_argnums=0)
def mix_dephasing(amps, prob, *, num_qubits: int, target: int):
    """rho -> (1-p) rho + p Z rho Z: multiply elements whose ket/bra target
    bits differ by (1-2p) — fused elementwise fast path
    (densmatr_mixDephasing, QuEST_cpu.c:48-90).  Real factor: scales both
    SoA channels identically."""
    n = num_qubits
    nn = 2 * n
    prob = jnp.asarray(prob, amps.dtype)
    if nn <= 31:
        sign = kernels.parity_sign_flat(nn, (target, target + n), amps.dtype)
        return amps * ((1 - prob) + prob * sign)[None]
    sign = kernels.parity_sign_2d(nn, (target, target + n), amps.dtype)
    view = amps.reshape(2, sign.shape[0], sign.shape[1])
    factor = (1 - prob) + prob * sign
    return (view * factor[None]).reshape(2, -1)


@partial(jax.jit, static_argnames=("num_qubits", "qubit1", "qubit2"), donate_argnums=0)
def mix_two_qubit_dephasing(amps, prob, *, num_qubits: int, qubit1: int, qubit2: int):
    """rho -> (1-p) rho + p/3 (Z1 rho Z1 + Z2 rho Z2 + Z1Z2 rho Z1Z2)
    (densmatr_mixTwoQubitDephasing, QuEST_cpu.c:92-123)."""
    n = num_qubits
    nn = 2 * n
    prob = jnp.asarray(prob, amps.dtype)
    if nn <= 31:
        s1 = kernels.parity_sign_flat(nn, (qubit1, qubit1 + n), amps.dtype)
        s2 = kernels.parity_sign_flat(nn, (qubit2, qubit2 + n), amps.dtype)
        factor = (1 - prob) + (prob / 3) * (s1 + s2 + s1 * s2)
        return amps * factor[None]
    s1 = kernels.parity_sign_2d(nn, (qubit1, qubit1 + n), amps.dtype)
    s2 = kernels.parity_sign_2d(nn, (qubit2, qubit2 + n), amps.dtype)
    view = amps.reshape(2, s1.shape[0], s1.shape[1])
    factor = (1 - prob) + (prob / 3) * (s1 + s2 + s1 * s2)
    return (view * factor[None]).reshape(2, -1)


def _pair_channel(amps, nn: int, t: int, b: int, w_same0, w_same1, w_diff,
                  w2_00, w2_11):
    """out = w1(kt,bt) * rho + w2(kt,bt) * partner, partner = the element
    with BOTH target bits flipped.  Weights by block: w1 = w_same0 at
    (0,0), w_same1 at (1,1), w_diff off-diagonal; w2 = w2_00 at (0,0),
    w2_11 at (1,1), 0 off-diagonal.  Layout-safe at any size: small
    states use the interleaved axis view; big states combine the
    flipped-copy kernel (_flip_bits_flat, never a small-minor flip) with
    iota-bit indicator weights on the (2^hi, 2^lo) view."""
    from . import kernels as K

    dt = amps.dtype
    if nn < K._BIG_N:
        shape = (2, 1 << (nn - 1 - b), 2, 1 << (b - 1 - t), 2, 1 << t)
        v = amps.reshape(shape)
        part = jnp.flip(jnp.flip(v, axis=2), axis=4)
        def tab(a00, a01, a10, a11):
            return jnp.stack([jnp.stack([a00, a01]),
                              jnp.stack([a10, a11])]).reshape(1, 1, 2, 1, 2, 1)
        one = jnp.ones((), dt)
        w1 = tab(w_same0, w_diff, w_diff, w_same1)
        w2 = tab(w2_00, one * 0, one * 0, w2_11)
        return (v * w1 + part * w2).reshape(amps.shape)
    part = K._flip_bits_flat(amps.reshape(2, -1), nn, (t, b))
    kt = K.bit_2d(nn, t).astype(dt)
    bt = K.bit_2d(nn, b).astype(dt)
    same = 1 - (kt - bt) * (kt - bt)     # 1 where kt == bt
    k1b1 = kt * bt
    k0b0 = same - k1b1
    w1 = w_diff + (w_same0 - w_diff) * k0b0 + (w_same1 - w_diff) * k1b1
    w2 = w2_00 * k0b0 + w2_11 * k1b1
    hi, lo = K._split2(nn)
    v = amps.reshape(2, 1 << hi, 1 << lo)
    pv = part.reshape(2, 1 << hi, 1 << lo)
    return (v * w1[None] + pv * w2[None]).reshape(amps.shape)


@partial(jax.jit, static_argnames=("num_qubits", "qubit1", "qubit2"),
         donate_argnums=0)
def mix_two_qubit_depolarising(amps, prob, *, num_qubits: int,
                               qubit1: int, qubit2: int):
    """rho -> (1-p) rho + p/15 sum_{15 non-II Paulis} P rho P as TWO
    double-flip partner sums + one elementwise combine — the dedicated
    form of the reference's 2q depolarise (densmatr_mixTwoQubitDepolarising,
    QuEST_cpu.c:387-733), replacing the 256x-element generic
    superoperator.

    Identity: (1/16) sum_{all 16} P rho P projects the 2q subsystem to
    maximally mixed — element-wise, block-diagonal elements (both ket
    target bits equal to both bra target bits) become the average of
    their 4-element double-flip orbit, off-block elements vanish.  So

        rho' = (1 - 16p/15) rho + (4p/15) * block * S,

    S = the orbit sum, computed as two cumulative double-flips:
    S = (1 + F2)(1 + F1) rho where F_i flips (ket_i, bra_i)."""
    from . import kernels as K

    n = num_qubits
    nn = 2 * n
    dt = amps.dtype
    p = jnp.asarray(prob, dt)
    t1, b1 = qubit1, qubit1 + n
    t2, b2 = qubit2, qubit2 + n
    flat = amps.reshape(2, -1)
    s = flat + K._flip_bits_flat(flat, nn, (t1, b1))
    s = s + K._flip_bits_flat(s, nn, (t2, b2))
    hi, lo = K._split2(nn)

    def same(t, b):
        kt = K.bit_2d(nn, t).astype(dt)
        bt = K.bit_2d(nn, b).astype(dt)
        return 1 - (kt - bt) * (kt - bt)

    block = same(t1, b1) * same(t2, b2)
    c1 = 1 - 16 * p / 15
    c2 = 4 * p / 15
    v = flat.reshape(2, 1 << hi, 1 << lo)
    sv = s.reshape(2, 1 << hi, 1 << lo)
    return (v * c1 + sv * (c2 * block)[None]).reshape(amps.shape)


@partial(jax.jit, static_argnames=("num_qubits", "target"), donate_argnums=0)
def mix_depolarising(amps, prob, *, num_qubits: int, target: int):
    """rho -> (1-p) rho + p/3 (X rho X + Y rho Y + Z rho Z) as ONE
    elementwise pass over the double-flip partner pairing

        rho'[ket bit == bra bit]  = (1-2p/3) rho + (2p/3) partner
        rho'[ket bit != bra bit]  = (1-4p/3) rho

    — the dedicated pair-average kernel form of the reference
    (densmatr_mixDepolarisingLocal, QuEST_cpu.c:125-246), replacing the
    16x-element generic superoperator for this channel."""
    n = num_qubits
    return apply_pair_channel(amps, "depol", prob, nn=2 * n, t=target,
                              b=target + n)


@partial(jax.jit, static_argnames=("num_qubits", "target"), donate_argnums=0)
def mix_damping(amps, prob, *, num_qubits: int, target: int):
    """Amplitude damping as ONE elementwise pass (densmatr_mixDampingLocal,
    QuEST_cpu.c:300-385): population flows |11> -> |00| while coherences
    scale by sqrt(1-p):

        rho'[0,0] = rho[0,0] + p * partner   (partner = the |11> element)
        rho'[0,1] = rho'[1,0] = sqrt(1-p) rho
        rho'[1,1] = (1-p) rho
    """
    n = num_qubits
    return apply_pair_channel(amps, "damping", prob, nn=2 * n, t=target,
                              b=target + n)


def apply_pair_channel(amps, kind: str, prob, *, nn: int, t: int, b: int):
    """The depolarise/damping one-pass kernel with explicit bit positions
    — ``nn`` is the number of qubits in the (possibly shard-local) array
    and (t, b) the ket/bra target bits within it.  Lets the fusion drain
    run captured channels on a shard-local view, where b = t + n_represented
    but nn < 2 * n_represented (fusion.py); ``prob`` may be traced.

    When many channels chain inside ONE program (the fused drain), the
    caller must fence consecutive channels with
    ``lax.optimization_barrier`` — XLA:TPU's memory assignment otherwise
    keeps every channel's temporaries live to the end of the program
    (measured +1.25 GiB per channel at 13q rho -> 21 GiB OOM; see
    fusion._plan_runner).  The interleaved-axis view path is NOT a
    big-state alternative: its size-2 minor axes tile-pad T(8,128) by up
    to 64x (a 32 GiB reshape at 13q rho)."""
    p = jnp.asarray(prob, amps.dtype)
    one = jnp.ones((), amps.dtype)
    if kind == "depol":
        return _pair_channel(amps, nn, t, b,
                             w_same0=1 - 2 * p / 3, w_same1=1 - 2 * p / 3,
                             w_diff=1 - 4 * p / 3,
                             w2_00=2 * p / 3 * one, w2_11=2 * p / 3 * one)
    if kind == "damping":
        return _pair_channel(amps, nn, t, b,
                             w_same0=one, w_same1=1 - p,
                             w_diff=jnp.sqrt(1 - p),
                             w2_00=p * one, w2_11=0 * one)
    raise ValueError(f"unknown pair channel {kind!r}")


def depolarising_kraus(prob, dtype=None):
    """{sqrt(1-p) I, sqrt(p/3) X, sqrt(p/3) Y, sqrt(p/3) Z}
    (mixDepolarising definition, QuEST.h:3496)."""
    p = float(prob)
    return [
        math.sqrt(1 - p) * gatedefs.PAULI_I,
        math.sqrt(p / 3) * gatedefs.PAULI_X,
        math.sqrt(p / 3) * gatedefs.PAULI_Y,
        math.sqrt(p / 3) * gatedefs.PAULI_Z,
    ]


def damping_kraus(prob, dtype=None):
    """Amplitude damping: K0 = diag(1, sqrt(1-p)), K1 = sqrt(p)|0><1|
    (mixDamping, QuEST.h:3534)."""
    p = float(prob)
    k0 = np.array([[1, 0], [0, math.sqrt(1 - p)]], dtype=np.complex128)
    k1 = np.array([[0, math.sqrt(p)], [0, 0]], dtype=np.complex128)
    return [k0, k1]


def pauli_kraus(prob_x, prob_y, prob_z, dtype=None):
    """mixPauli -> 4 Kraus ops (reference densmatr_mixPauli via
    QuEST_common.c:730-750)."""
    p0 = 1 - float(prob_x) - float(prob_y) - float(prob_z)
    return [
        math.sqrt(p0) * gatedefs.PAULI_I,
        math.sqrt(float(prob_x)) * gatedefs.PAULI_X,
        math.sqrt(float(prob_y)) * gatedefs.PAULI_Y,
        math.sqrt(float(prob_z)) * gatedefs.PAULI_Z,
    ]


def two_qubit_depolarising_kraus(prob, dtype=None):
    """{sqrt(1-p) II} + {sqrt(p/15) P_i (x) P_j : (i,j) != (I,I)}
    (mixTwoQubitDepolarising, QuEST.h:3601)."""
    prob = float(prob)
    ops = []
    for i in range(4):
        for j in range(4):
            p = (1 - prob) if (i == 0 and j == 0) else prob / 15
            # kron(second-qubit pauli, first-qubit pauli): targets[0] is the
            # least-significant superop bit.
            ops.append(
                math.sqrt(p)
                * np.kron(gatedefs.PAULI_MATRICES[j], gatedefs.PAULI_MATRICES[i])
            )
    return ops


@partial(jax.jit, donate_argnums=0)
def mix_density_matrix(amps, other_amps, prob):
    """rho -> (1-p) rho + p rho_other (densmatr_mixDensityMatrix,
    QuEST_cpu.c:125-160)."""
    prob = jnp.asarray(prob, amps.dtype)
    return (1 - prob) * amps + prob * other_amps


@partial(jax.jit, static_argnames=("num_qubits",))
def init_pure_state_density(psi_amps, *, num_qubits: int):
    """rho = |psi><psi| flattened column-major: kron(conj(psi), psi)
    (densmatr_initPureStateLocal outer product, QuEST_cpu.c:1184).
    SoA: with u = conj(psi), re = kron(u0,p0) - kron(u1,p1), etc."""
    p0, p1 = psi_amps[0], psi_amps[1]
    re = jnp.kron(p0, p0) + jnp.kron(p1, p1)
    im = jnp.kron(p0, p1) - jnp.kron(p1, p0)
    return jnp.stack([re, im])


@partial(jax.jit, static_argnames=("num_qubits",), donate_argnums=0)
def apply_diagonal_op_density(amps, op_real, op_imag, *, num_qubits: int):
    """Left-multiply D.rho: scale each column elementwise by D over ket bits
    (densmatr_applyDiagonalOpLocal, QuEST_cpu.c:4042-4082). NOTE: this is the
    `apply*` family — no conjugate twin (SURVEY.md §2.3 semantic trap)."""
    dim = 1 << num_qubits
    mat = amps.reshape(2, dim, dim)  # [channel, col, row]; rows are ket bits
    f_re = op_real.astype(amps.dtype)[None, :]
    f_im = op_imag.astype(amps.dtype)[None, :]
    return cplx.cmul(mat, f_re, f_im).reshape(2, -1)
