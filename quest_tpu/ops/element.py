"""Layout-safe element access: jitted slice kernels on the canonical view.

getAmp-class reads and setAmps-class writes must never trigger a
full-state relayout: an eager ``amps[:, index]`` on a canonically-tiled
28q+ state makes XLA first copy the WHOLE state into the default flat
layout — the round-3 30q relayout-OOM diagnosis (BASELINE.md) — where
the reference's getAmp is an O(1) chunk read (QuEST.h:1987,
QuEST_cpu_local.c:225-233).

The kernels here dynamic-slice the canonical (2, 2^(n-14), 128, 128)
view — a free bitcast at the jit boundary for canonically-held states
(circuit.canonical_view) — touching one 128x128 tile per access; flat
(2, 2^n) registers take an equivalent flat dynamic-slice.  Index
components enter as traced scalars, so repeated accesses never
recompile.  Writes decompose a contiguous range into tile-aligned whole
blocks (one dynamic_update_slice) plus at most two edge blocks handled
read-modify-write, one tile each.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .fused import CLUSTER_DIM as DIM, CLUSTER_QUBITS as BLK_BITS

BLK = 1 << BLK_BITS  # amps per canonical block (one 128x128 tile pair)


@jax.jit
def _get_pair_canonical(v, b, s, l):
    return jax.lax.dynamic_slice(v, (0, b, s, l), (2, 1, 1, 1)).reshape(2)


@jax.jit
def _get_pair_flat(v, i):
    return jax.lax.dynamic_slice(v, (0, i), (2, 1))[:, 0]


@jax.jit
def _get_block(v, b):
    return jax.lax.dynamic_slice(
        v, (0, b, 0, 0), (2, 1, DIM, DIM)).reshape(2, DIM, DIM)


@partial(jax.jit, donate_argnums=0)
def _set_blocks(v, blocks, b0):
    return jax.lax.dynamic_update_slice(v, blocks, (0, b0, 0, 0))


@partial(jax.jit, donate_argnums=0)
def _set_flat(v, vals, i):
    return jax.lax.dynamic_update_slice(v, vals, (0, i))


def _as_canonical(amps):
    """Reshape a flat (2, N >= 2^14) register to the canonical 4-d view
    (a bitcast for row-major layouts).  Index components into the 4-d
    view stay < 2^31 for any register size, so traced indices never
    overflow int32 in single-precision (x64-off) mode — a raw flat index
    would at >= 2^31 amps (e.g. a 16q density matrix)."""
    return amps.reshape(2, -1, DIM, DIM)


def get_amp_pair(amps, index: int):
    """(re, im) device pair of amplitude ``index`` without any relayout.
    Accepts the flat (2, 2^n) register form or the canonical 4-d view the
    chained big-state executor keeps (circuit.canonical_view)."""
    if amps.ndim != 4:
        if amps.shape[1] < BLK:
            return _get_pair_flat(amps, index)
        amps = _as_canonical(amps)
    return _get_pair_canonical(
        amps, index >> BLK_BITS, (index >> 7) & (DIM - 1),
        index & (DIM - 1))


def get_block_host(amps, b: int) -> np.ndarray:
    """One canonical 2^14-amp block as a host (2, 2^14) array (a single
    tile-aligned device read — used by streamed reportState and the edge
    blocks of set_amp_range)."""
    if amps.ndim == 4:
        return np.array(_get_block(amps, b)).reshape(2, BLK)
    lo = b * BLK
    return np.array(
        jax.lax.dynamic_slice(amps, (0, lo), (2, min(BLK, amps.shape[1] - lo))))


def set_amp_range(amps, start: int, vals: np.ndarray):
    """Overwrite amplitudes [start, start+m) with host values
    ``vals`` (2, m); returns the updated array in the SAME view/layout.
    Canonical states update tile-aligned whole blocks in one
    dynamic_update_slice plus read-modify-write edge tiles — never a
    full-state relayout (the reference's setAmps writes into the local
    chunk in place, QuEST_cpu.c setAmps path)."""
    m = int(vals.shape[1])
    if m == 0:
        return amps
    orig_shape = amps.shape
    if amps.ndim != 4:
        if amps.shape[1] < BLK:
            return _set_flat(amps, jnp.asarray(vals, amps.dtype), start)
        amps = _as_canonical(amps)  # avoids int32 index overflow, see above
    end = start + m
    fb0 = (start + BLK - 1) >> BLK_BITS     # first fully-covered block
    fb1 = end >> BLK_BITS                   # one past the last full block
    if fb1 > fb0:
        off = (fb0 << BLK_BITS) - start
        blocks = np.ascontiguousarray(
            vals[:, off:off + ((fb1 - fb0) << BLK_BITS)]
        ).reshape(2, fb1 - fb0, DIM, DIM)
        amps = _set_blocks(amps, jnp.asarray(blocks, amps.dtype), fb0)
    edge_blocks = {start >> BLK_BITS, (end - 1) >> BLK_BITS} - set(
        range(fb0, fb1))
    for b in sorted(edge_blocks):
        blk = get_block_host(amps, b)
        lo = max(start, b << BLK_BITS)
        hi = min(end, (b + 1) << BLK_BITS)
        blk[:, lo - (b << BLK_BITS):hi - (b << BLK_BITS)] = (
            vals[:, lo - start:hi - start])
        amps = _set_blocks(
            amps, jnp.asarray(blk.reshape(2, 1, DIM, DIM), amps.dtype), b)
    return amps.reshape(orig_shape)
