"""In-place kernels for states too large for out-of-place ops.

At 30 qubits an f32 SoA state is 8 GB; the v5e chip exposes 15.75 GB of
HBM, so ANY op that allocates a second full-state buffer (XLA transposes,
layout copies) is an OOM.  The reference meets this wall by distributing
(QuEST/include/QuEST.h:463-479 documents the per-node memory doubling);
the fused Pallas passes dodge it with input/output aliasing — but the
QFT's final bit-reversal permutation (agnostic_applyQFT swap network,
QuEST_common.c:836-898) is a full-state transpose that XLA can only do
out-of-place.

This module provides the missing piece: an IN-PLACE "double bit-block
swap" kernel built on manual DMA with the state aliased as its own
output.  It exchanges amp bits [0,g) <-> [n-g, n) and [g,2g) <-> [n-2g,
n-g) simultaneously (bits [2g, n-2g) fixed) — an involution sigma.  The
full bit reversal factors as

    rev[0,n) = (within-group reversals) o sigma

for the palindromic group split (g, g, n-4g, g, g), and the within-group
reversals are ordinary in-place window passes (circuit.bit_reversal_ops).

Why sigma is in-place blockable: fix (G1=c, s=d) and let (G2, l) range —
call that block B(c,d) (a 128x128 slab for g=7).  sigma maps B(c,d) onto
B(d,c) with the slab transposed, so blocks pair up under sigma and a
kernel can stage the two slabs in VMEM, transpose, and write them back
swapped — each element moved exactly once, no second state buffer.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _sigma_kernel():
    """Kernel body: one unordered (c, d) block pair per grid step.

    refs: ctab/dtab (scalar prefetch, SMEM), in_ref/out_ref = the SAME
    HBM buffer (aliased), scratch s1/s2 (2, G, G) VMEM, 4 DMA sems.
    View indexed [ch, G2, G1, b, s, l]; slab (c, d) = [:, :, c, b, d, :].
    """

    def kernel(ctab, dtab, in_ref, out_ref, s1, s2, sems):
        j = pl.program_id(1)
        b = pl.program_id(0)
        c = ctab[j]
        d = dtab[j]

        r1 = pltpu.make_async_copy(
            in_ref.at[:, :, c, b, d, :], s1, sems.at[0])
        r1.start()
        r2 = pltpu.make_async_copy(
            in_ref.at[:, :, d, b, c, :], s2, sems.at[1])
        r2.start()
        r1.wait()
        r2.wait()
        t1 = jnp.swapaxes(s2[...], 1, 2)
        t2 = jnp.swapaxes(s1[...], 1, 2)
        s1[...] = t1
        s2[...] = t2
        # writes serialized: a diagonal step (c == d) writes the same slab
        # twice (same transposed data); concurrent overlapping writes
        # would be a DMA hazard even with identical bytes
        w1 = pltpu.make_async_copy(
            s1, out_ref.at[:, :, c, b, d, :], sems.at[2])
        w1.start()
        w1.wait()
        w2 = pltpu.make_async_copy(
            s2, out_ref.at[:, :, d, b, c, :], sems.at[3])
        w2.start()
        w2.wait()

    return kernel


@partial(jax.jit, static_argnames=("num_qubits", "group_bits", "interpret"),
         donate_argnums=0)
def _sigma_swap_jit(amps, ctab, dtab, *, num_qubits: int, group_bits: int,
                    interpret: bool | None = None):
    n, g = num_qubits, group_bits
    if interpret is None:
        from .fused import _interpret_default

        interpret = _interpret_default()
    G = 1 << g
    r = n - 4 * g
    B = 1 << r
    in_shape = amps.shape
    view = amps.reshape(2, G, G, B, G, G)
    npairs = ctab.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, npairs),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, G, G), view.dtype),
            pltpu.VMEM((2, G, G), view.dtype),
            pltpu.SemaphoreType.DMA((4,)),
        ],
    )
    out = pl.pallas_call(
        _sigma_kernel(),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
        # operand indices count the scalar-prefetch args: 2 = the state
        input_output_aliases={2: 0},
        interpret=interpret,
    )(ctab, dtab, view)
    return out.reshape(in_shape)


def sigma_pair_tables(group_bits: int):
    """(ctab, dtab) int32 arrays enumerating unordered (c, d) pairs,
    diagonal included (a diagonal step writes the same slab twice with the
    same transposed data — harmless and branch-free)."""
    G = 1 << group_bits
    cs, ds = np.triu_indices(G)
    return (np.asarray(cs, np.int32), np.asarray(ds, np.int32))


def apply_sigma_swap(amps, *, num_qubits: int, group_bits: int = 7,
                     interpret: bool | None = None):
    """In-place involution sigma: swap amp bits [0,g) <-> [n-g, n) AND
    [g, 2g) <-> [n-2g, n-g); bits [2g, n-2g) unchanged.  Requires
    4*group_bits <= num_qubits.  One HBM read + one write of the state,
    zero extra HBM (the state buffer is aliased as its own output)."""
    if 4 * group_bits > num_qubits:
        raise ValueError("sigma swap needs n >= 4*group_bits")
    ctab, dtab = sigma_pair_tables(group_bits)
    return _sigma_swap_jit(
        amps, jnp.asarray(ctab), jnp.asarray(dtab),
        num_qubits=num_qubits, group_bits=group_bits, interpret=interpret)


def sigma_perm(num_qubits: int, group_bits: int) -> tuple:
    """The bit permutation sigma implements, as a perm tuple compatible
    with kernels.permute_qubits (output qubit q holds input perm[q])."""
    n, g = num_qubits, group_bits
    perm = list(range(n))
    for j in range(g):
        perm[j], perm[n - g + j] = n - g + j, j
        perm[g + j], perm[n - 2 * g + j] = n - 2 * g + j, g + j
    return tuple(perm)
