"""Declarative sharded-collective contracts.

The distributed layer's reliability story (qHiPSTER arXiv:1601.07195 §IV,
mpiQulacs arXiv:2203.16044 §V) rests on the communication layer staying
auditable: every exchange program has a KNOWN collective shape, and any
change to it is a deliberate, reviewed event — not a silent regression a
refactor smuggles in.  Until now that shape lived only in test pins
(tests/test_distributed_hlo.py); this module moves the declaration onto
the wrapper itself:

    @sharded_contract(collectives={"collective-permute": 1},
                      max_exchange_bytes=1 << 10)
    def swap_sharded(amps, *, mesh, num_qubits, qb_low, qb_high, ...):
        ...

``collectives`` pins the EXACT HLO collective-opcode histogram of the
wrapper's canonical verification dispatch (the 8-shard CPU dryrun config
in quest_tpu/analysis/hlocheck.py — ``-start`` async variants fold into
their base opcode), and ``max_exchange_bytes`` caps the per-shard ICI
bytes the wrapper's own cost model records for that dispatch.  The
declarations are verified against COMPILED HLO by
``python -m quest_tpu.analysis --contracts`` (make verify-static) via
introspect.audit / CollectiveBudget, and the qlint ``contract-missing``
rule statically requires every registered wrapper to carry the decorator
(docs/design.md §23).

stdlib-only on purpose: parallel/dist.py imports this at module level, so
it must sit in the shared layer of the import DAG (no jax, no sibling
modules).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional


@dataclasses.dataclass(frozen=True)
class ShardedContract:
    """One wrapper's declared collective shape (see module docstring)."""

    name: str
    collectives: Dict[str, int]
    max_exchange_bytes: int
    # Optional per-interconnect-tier byte caps ({"ici": .., "dcn": ..})
    # for the canonical dispatch on the forced hierarchical dryrun
    # topology (parallel/topology.py; hlocheck verifies on a 2x4
    # arrangement of the 8-shard mesh).  None = tier-agnostic: only the
    # flat max_exchange_bytes cap applies.
    max_tier_bytes: Optional[Dict[str, int]] = None

    def as_dict(self) -> dict:
        out = {"name": self.name, "collectives": dict(self.collectives),
               "max_exchange_bytes": int(self.max_exchange_bytes)}
        if self.max_tier_bytes is not None:
            out["max_tier_bytes"] = {
                k: int(v) for k, v in self.max_tier_bytes.items()}
        return out


# name -> ShardedContract for every decorated wrapper, in decoration
# order.  hlocheck.verify_sharded_contracts walks this; the static
# contract-missing rule pins the expected membership below.
SHARDED_CONTRACTS: Dict[str, ShardedContract] = {}

# The sharded dispatch wrappers REQUIRED to carry a contract — the five
# guarded_dispatch entry points of parallel/dist.py.  A new wrapper must
# be added here AND decorated, or qlint's contract-missing rule fails the
# tree (quest_tpu/analysis/rules_layering.py).
REQUIRED_WRAPPERS = (
    "apply_matrix_1q_sharded",
    "swap_sharded",
    "gather_replicated",
    "mix_pair_channel_sharded",
    "remap_sharded",
)


def sharded_contract(*, collectives: Dict[str, int],
                     max_exchange_bytes: int,
                     max_tier_bytes: Optional[Dict[str, int]] = None,
                     name: Optional[str] = None) -> Callable:
    """Declare a sharded dispatch wrapper's collective contract.

    Registers the declaration in :data:`SHARDED_CONTRACTS` and attaches
    it to the function as ``__sharded_contract__``.  Purely declarative —
    zero dispatch-time overhead; enforcement happens offline against the
    compiled HLO (analysis/hlocheck.py).  ``max_tier_bytes`` optionally
    caps the per-shard bytes crossing each interconnect tier on the
    hierarchical verification dryrun (see ShardedContract)."""
    decl_collectives = {str(k): int(v) for k, v in collectives.items()}
    decl_tier = (None if max_tier_bytes is None
                 else {str(k): int(v) for k, v in max_tier_bytes.items()})

    def deco(fn: Callable) -> Callable:
        contract = ShardedContract(
            name=name or fn.__name__,
            collectives=decl_collectives,
            max_exchange_bytes=int(max_exchange_bytes),
            max_tier_bytes=decl_tier,
        )
        SHARDED_CONTRACTS[contract.name] = contract
        fn.__sharded_contract__ = contract
        return fn

    return deco
