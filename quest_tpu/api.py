"""Public API: the TPU-native equivalent of the reference's dispatch layer
(QuEST/src/QuEST.c) exposing the full ~140-function surface of QuEST.h.

Every function follows the reference's dispatch shape (QuEST.c:177-186):
validate -> kernel on the ket qubits -> if density matrix, conjugated twin
kernel on the bra qubits (+numQubits shift; QuEST.c:8-10,181-183) -> QASM
record.  Kernels are jit-compiled pure functions over the register's on-HBM
amplitude array (quest_tpu.ops.*); the register object just re-binds its
``amps`` handle, so a chain of API calls is a chain of donated in-place XLA
updates.

Semantic trap preserved (SURVEY.md §2.3): the ``apply*`` family
(applyMatrix2/4/N, applyMultiControlledMatrixN, applyPauliSum/Hamil,
applyPhaseFunc*, applyDiagonalOp) performs NO unitarity validation and NO
density-matrix twin — on a density matrix it left-multiplies
(QuEST.c:1074-1105).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import env as _env
from . import fusion as _fusion
from . import rng as _rng
from . import telemetry as _telemetry
from . import validation as V
from .ops import calculations as C
from .ops import cplx as CX
from .parallel import dist as PAR
from .ops import density as D
from .ops import gatedefs as G
from .ops import kernels as K
from .ops import paulis as P
from .ops import phasefunc as PF
from .precision import complex_dtype, real_dtype, validation_eps
from .qureg import DiagonalOp, PauliHamil, Qureg

# pauliOpType (QuEST.h:96)
PAULI_I, PAULI_X, PAULI_Y, PAULI_Z = 0, 1, 2, 3

# per-kernel-family dispatch counters: keys prebuilt once so the per-gate
# hot-loop cost is one int test + one dict upsert (telemetry.inc_key)
_K_UNITARY = _telemetry.counter_key("dispatch_total", family="unitary")
_K_DIAG = _telemetry.counter_key("dispatch_total", family="diag")
_K_NOT = _telemetry.counter_key("dispatch_total", family="not")
_K_PARITY = _telemetry.counter_key("dispatch_total", family="parity_phase")
_K_SWAP = _telemetry.counter_key("dispatch_total", family="swap")
_K_PERM = _telemetry.counter_key("dispatch_total", family="permutation")
# bitEncoding (QuEST.h:269)
UNSIGNED, TWOS_COMPLEMENT = 0, 1


def _bw(qureg) -> int:
    """Telemetry weight of one dispatch on this register: a BatchedQureg
    applies every gate to all B bank elements, so dispatch_total counts
    B logical gate applications (batch.py; telemetry truthfulness under
    batching)."""
    return int(getattr(qureg, "batch_size", 0) or 0) or 1


def _guard_batched_eager(qureg, what: str) -> None:
    """A BatchedQureg's (B, 2, 2^n) bank only flows through the fused
    drain (vmapped) and the batch helpers — the eager scalar kernels
    would silently misread the leading batch axis, so falling out of the
    capture path is a structured error, never a wrong answer."""
    if getattr(qureg, "batch_size", 0):
        raise V.QuESTError(
            f"{what}: the operation fell out of the fused capture path, "
            "and a BatchedQureg bank has no eager scalar dispatch — keep "
            "gates within fusion limits (<= "
            f"{_fusion.FUSION_MAX_GATE_QUBITS} qubits, shard-local on a "
            "mesh) or use the quest_tpu.batch helpers")

# ---------------------------------------------------------------------------
# Environment (QuEST.h:1851-1939)
# ---------------------------------------------------------------------------

createQuESTEnv = _env.create_quest_env
initDistributed = _env.init_distributed  # multi-host MPI_Init analogue
destroyQuESTEnv = _env.destroy_quest_env
syncQuESTEnv = _env.sync_quest_env
syncQuESTSuccess = _env.sync_quest_success
reportQuESTEnv = _env.report_quest_env
getEnvironmentString = _env.get_environment_string
seedQuEST = _env.seed_quest
seedQuESTDefault = _env.seed_quest_default
QuESTError = V.QuESTError


def copyStateToGPU(qureg: Qureg) -> None:
    """No-op: amplitudes are always device-resident (the reference GPU
    backend keeps a host mirror it must sync, QuEST_gpu.cu:517-539)."""


def copyStateFromGPU(qureg: Qureg) -> None:
    """No-op: see copyStateToGPU."""


def invalidQuESTInputError(errMsg: str, errFunc: str):
    """Reference's overridable error hook (QuEST.h:5354); in Python the
    equivalent is catching QuESTError."""
    raise V.QuESTError(f"{errFunc}: {errMsg}")


# ---------------------------------------------------------------------------
# Register lifecycle (QuEST.c:36-76)
# ---------------------------------------------------------------------------


def createQureg(numQubits: int, env: _env.QuESTEnv) -> Qureg:
    """Create a state-vector register of numQubits qubits (QuEST.h:529).
    Admission-controlled: with an HBM budget active, a register whose
    modeled footprint does not fit raises a structured
    MemoryAdmissionError BEFORE any device allocation (governor.py) —
    the governed analogue of validateMemoryAllocationSize."""
    from . import governor as _gov

    V.validate_num_qubits(numQubits, "createQureg", num_ranks=env.num_ranks)
    q = Qureg(numQubits, env, is_density_matrix=False)
    _gov.admit_new(q, "createQureg")
    q.amps = q.device_put(K.init_zero_state(q.num_amps_total, q.dtype))
    return q


def createDensityQureg(numQubits: int, env: _env.QuESTEnv) -> Qureg:
    """Create a density-matrix register (state-vector of 2N qubits) (QuEST.h:623)."""
    from . import governor as _gov

    V.validate_num_qubits(numQubits, "createDensityQureg", num_ranks=env.num_ranks)
    q = Qureg(numQubits, env, is_density_matrix=True)
    _gov.admit_new(q, "createDensityQureg")
    q.amps = q.device_put(
        K.init_classical_density(numQubits, 0, q.dtype)
    )
    return q


def createCloneQureg(qureg: Qureg, env: _env.QuESTEnv) -> Qureg:
    """Create a new register cloning an existing one (QuEST.h:644)."""
    from . import governor as _gov

    q = Qureg(qureg.num_qubits_represented, env, qureg.is_density_matrix)
    _gov.admit_new(q, "createCloneQureg")
    q.amps = jnp.array(qureg.amps, copy=True)
    return q


def destroyQureg(qureg: Qureg, env: Optional[_env.QuESTEnv] = None) -> None:
    """Free a register's amplitude storage (QuEST.h:666)."""
    from . import governor as _gov

    _gov.release(qureg)
    qureg.amps = None


def reportState(qureg: Qureg) -> None:
    """Dump amplitudes to one state_rank_<r>.csv per amplitude chunk — the
    reference writes one file per MPI rank from that rank's chunk
    (QuEST_common.c:229-245, header on rank 0 only); here each mesh
    device's shard plays the chunk role, so no full-state gather to one
    host buffer ever happens."""
    from .parallel import dist as PAR

    amps = qureg.amps
    # chunk = amp-axis shard size (NOT total/num_devices: a multi-axis
    # (dp, amps) mesh has fewer amplitude shards than devices)
    env = qureg.env
    ndev_amp = PAR.amp_axis_size(env.mesh) if env.mesh is not None else 1
    chunk = (qureg.num_amps_total // ndev_amp
             if qureg.num_amps_total >= ndev_amp else qureg.num_amps_total)
    shards = sorted(
        amps.addressable_shards,
        key=lambda sh: (sh.index[1].start or 0) if len(sh.index) > 1 else 0,
    )
    seen = set()
    for sh in shards:
        start = (sh.index[1].start or 0) if len(sh.index) > 1 else 0
        rank = start // chunk if chunk else 0
        if rank in seen:   # replicated small registers: write once
            continue
        seen.add(rank)
        data = np.asarray(sh.data)
        with open(f"state_rank_{rank}.csv", "w") as f:
            if rank == 0:
                f.write("real, imag\n")
            for re, im in zip(data[0], data[1]):
                f.write(f"{re:.12f}, {im:.12f}\n")


def reportStateToScreen(qureg: Qureg, env=None, reportRank: int = 0) -> None:
    """Print all amplitudes to stdout (QuEST.h:1289)."""
    from .debug import _guard_host_gather

    _guard_host_gather(qureg, "reportStateToScreen")
    amps = np.asarray(qureg.amps)
    print("Reporting state from rank 0:")
    for re, im in zip(amps[0], amps[1]):
        print(f"{re} {im}")


def reportQuregParams(qureg: Qureg) -> None:
    """Print register metadata (QuEST.h:1297)."""
    print(f"QUBITS:\nNumber of qubits is {qureg.num_qubits_represented}.")
    print(f"Number of amps is {qureg.num_amps_total}.")
    print(f"Number of amps per rank is {qureg.num_amps_per_chunk}.")


def getNumQubits(qureg: Qureg) -> int:
    """Number of qubits represented (QuEST.h:1333)."""
    return qureg.num_qubits_represented


def getNumAmps(qureg: Qureg) -> int:
    """Number of amplitudes (2^numQubits) (QuEST.h:1351)."""
    V.validate_state_vector(qureg, "getNumAmps")
    return qureg.num_amps_total


# ---------------------------------------------------------------------------
# Matrix / operator structures (QuEST.c:1383-1602)
# ---------------------------------------------------------------------------


def createComplexMatrixN(numQubits: int) -> np.ndarray:
    """Allocate a 2^N x 2^N complex matrix (QuEST.h:721)."""
    V.validate_num_qubits(numQubits, "createComplexMatrixN")
    dim = 1 << numQubits
    return np.zeros((dim, dim), dtype=np.complex128)


def destroyComplexMatrixN(matrix) -> None:
    """Free a ComplexMatrixN (no-op placeholder for parity) (QuEST.h:739)."""
    pass


def initComplexMatrixN(m: np.ndarray, reals, imags) -> None:
    """Fill a ComplexMatrixN from real/imag nested lists (QuEST.h:764)."""
    m[...] = np.asarray(reals, dtype=np.float64) + 1j * np.asarray(imags, np.float64)


def getStaticComplexMatrixN(reals, imags) -> np.ndarray:
    return np.asarray(reals, dtype=np.float64) + 1j * np.asarray(imags, np.float64)


def createPauliHamil(numQubits: int, numSumTerms: int) -> PauliHamil:
    """Allocate a PauliHamil (flat pauli codes + term coefficients) (QuEST.h:802)."""
    V.validate_hamil_params(numQubits, numSumTerms, "createPauliHamil")
    return PauliHamil(numQubits, numSumTerms)


def destroyPauliHamil(hamil: PauliHamil) -> None:
    """Free a PauliHamil (QuEST.h:810)."""
    pass


def createPauliHamilFromFile(filename: str) -> PauliHamil:
    """Text format: per line 'coeff code_0 code_1 ... code_{n-1}'
    (reference parser, QuEST.c:1405-1488; file-specific error codes from
    QuEST_validation.c:539-545, 660-697)."""
    func = "createPauliHamilFromFile"
    try:
        with open(filename) as f:
            lines = [ln.split() for ln in f if ln.strip()]
    except OSError:
        V.validate_file_opened(False, filename, func)
    num_qubits = len(lines[0]) - 1 if lines else 0
    num_terms = len(lines)
    V.validate_hamil_file_params(num_qubits, num_terms, filename, func)
    h = PauliHamil(num_qubits, num_terms)
    for t, toks in enumerate(lines):
        V.validate_hamil_file_pauli_parsed(len(toks) == num_qubits + 1,
                                           filename, func)
        try:
            h.term_coeffs[t] = float(toks[0])
        except ValueError:
            V.validate_hamil_file_coeff_parsed(False, filename, func)
        codes = []
        for x in toks[1:]:
            try:
                codes.append(int(x))
            except ValueError:
                V.validate_hamil_file_pauli_parsed(False, filename, func)
        for c in codes:
            V.validate_hamil_file_pauli_code(c, filename, func)
        h.pauli_codes[t, :] = codes
    return h


def initPauliHamil(hamil: PauliHamil, coeffs, codes) -> None:
    """Fill a PauliHamil from coefficients and pauli codes (QuEST.h:897)."""
    V.validate_hamil_params(hamil.num_qubits, hamil.num_sum_terms, "initPauliHamil")
    codes = np.asarray(codes).reshape(hamil.num_sum_terms, hamil.num_qubits)
    V.validate_pauli_codes(codes.ravel(), "initPauliHamil")
    hamil.term_coeffs[:] = np.asarray(coeffs, dtype=np.float64)
    hamil.pauli_codes[...] = codes


def reportPauliHamil(hamil: PauliHamil) -> None:
    """Print a PauliHamil in the reference text format (QuEST.h:1321)."""
    for t in range(hamil.num_sum_terms):
        codes = " ".join(str(int(c)) for c in hamil.pauli_codes[t])
        print(f"{hamil.term_coeffs[t]:g}\t{codes}")


def createDiagonalOp(numQubits: int, env: _env.QuESTEnv) -> DiagonalOp:
    """Allocate a distributed diagonal operator (QuEST.h:977)."""
    V.validate_num_qubits_in_diag_op(numQubits, env.num_ranks, "createDiagonalOp")
    return DiagonalOp(numQubits, env)


def destroyDiagonalOp(op: DiagonalOp, env=None) -> None:
    """Free a DiagonalOp (QuEST.h:991)."""
    pass


def syncDiagonalOp(op: DiagonalOp) -> None:
    """No-op: the reference must mirror host arrays into
    op.deviceOperator (QuEST.h:297); ours are always device-resident."""


def initDiagonalOp(op: DiagonalOp, reals, imags) -> None:
    """Fill a DiagonalOp from real/imag arrays (QuEST.h:1039)."""
    rdt = real_dtype()
    dim = 1 << op.num_qubits
    sharding = op.env.sharding_for_dim(dim)
    V.validate_finite(np.asarray(reals), "initDiagonalOp")
    V.validate_finite(np.asarray(imags), "initDiagonalOp")
    op.real = jax.device_put(jnp.asarray(np.asarray(reals), rdt), sharding)
    op.imag = jax.device_put(jnp.asarray(np.asarray(imags), rdt), sharding)


def setDiagonalOpElems(op: DiagonalOp, startInd: int, reals, imags, numElems: int) -> None:
    """Overwrite a contiguous range of diagonal-operator elements (QuEST.h:1185)."""
    reals = np.asarray(reals, dtype=np.float64)[:numElems]
    imags = np.asarray(imags, dtype=np.float64)[:numElems]
    V.validate_num_elems(op, startInd, numElems, "setDiagonalOpElems")
    V.validate_finite(reals, "setDiagonalOpElems")
    V.validate_finite(imags, "setDiagonalOpElems")
    op.real = op.real.at[startInd:startInd + numElems].set(reals.astype(op.real.dtype))
    op.imag = op.imag.at[startInd:startInd + numElems].set(imags.astype(op.imag.dtype))


def initDiagonalOpFromPauliHamil(op: DiagonalOp, hamil: PauliHamil) -> None:
    """Requires an all-I/Z Hamiltonian; diagonal_d = sum_t c_t prod_q
    (-1)^{z_q(d)}, computed ON DEVICE over the sharded index space
    (reference agnostic_initDiagonalOpFromPauliHamil,
    QuEST_cpu.c:4188-4227; paulis.diag_from_z_hamil)."""
    V.validate_diag_pauli_hamil(op, hamil, "initDiagonalOpFromPauliHamil")
    codes = np.asarray(hamil.pauli_codes)
    zmasks = np.zeros(hamil.num_sum_terms, np.uint64)
    for q in range(hamil.num_qubits):
        zmasks |= ((codes[:, q] == PAULI_Z).astype(np.uint64) << np.uint64(q))
    split = P._PAR_LO_BITS
    lo = (zmasks & np.uint64((1 << split) - 1)).astype(np.uint32)
    hi = (zmasks >> np.uint64(split)).astype(np.uint32)
    rdt = real_dtype()
    dim = 1 << op.num_qubits
    sharding = op.env.sharding_for_dim(dim)
    diag = P.diag_from_z_hamil(
        jnp.asarray(lo), jnp.asarray(hi),
        jnp.asarray(hamil.term_coeffs, rdt),
        num_qubits=op.num_qubits, dtype=rdt, sharding=sharding,
    )
    op.real = jax.device_put(diag, sharding)
    op.imag = jax.device_put(jnp.zeros((dim,), rdt), sharding)


def createDiagonalOpFromPauliHamilFile(filename: str, env: _env.QuESTEnv) -> DiagonalOp:
    """Build a diagonal operator from an all-Z PauliHamil file (QuEST.h:1137)."""
    hamil = createPauliHamilFromFile(filename)
    op = DiagonalOp(hamil.num_qubits, env)
    initDiagonalOpFromPauliHamil(op, hamil)
    return op


# ---------------------------------------------------------------------------
# State initialisation (QuEST.h:1361-1559)
# ---------------------------------------------------------------------------


def initBlankState(qureg: Qureg) -> None:
    """Set all amplitudes to zero (QuEST.h:1361)."""
    qureg.amps = qureg.device_put(K.init_blank_state(qureg.num_amps_total, qureg.dtype))


def initZeroState(qureg: Qureg) -> None:
    """Set the register to |0...0> (QuEST.h:1375)."""
    if qureg.is_density_matrix:
        qureg.amps = qureg.device_put(
            K.init_classical_density(qureg.num_qubits_represented, 0, qureg.dtype)
        )
    else:
        qureg.amps = qureg.device_put(K.init_zero_state(qureg.num_amps_total, qureg.dtype))
    qureg.qasm_log.init_zero()


def initPlusState(qureg: Qureg) -> None:
    """Set the register to |+>^n (uniform superposition) (QuEST.h:1394)."""
    if qureg.is_density_matrix:
        qureg.amps = qureg.device_put(
            D.init_pure_state_density(
                K.init_plus_state(1 << qureg.num_qubits_represented, qureg.dtype),
                num_qubits=qureg.num_qubits_represented,
            )
        )
    else:
        qureg.amps = qureg.device_put(K.init_plus_state(qureg.num_amps_total, qureg.dtype))


def initClassicalState(qureg: Qureg, stateInd: int) -> None:
    """Set the register to a computational basis state (QuEST.h:1431)."""
    V.validate_state_index(qureg, stateInd, "initClassicalState")
    if qureg.is_density_matrix:
        qureg.amps = qureg.device_put(
            K.init_classical_density(qureg.num_qubits_represented, stateInd, qureg.dtype)
        )
    else:
        qureg.amps = qureg.device_put(
            K.init_classical_state(qureg.num_amps_total, stateInd, qureg.dtype)
        )


def initPureState(qureg: Qureg, pure: Qureg) -> None:
    """Initialise a register (or rho = |psi><psi|) from a pure state (QuEST.h:1451)."""
    V.validate_state_vector(pure, "initPureState")
    V.validate_matching_qureg_dims(qureg, pure, "initPureState")
    if qureg.is_density_matrix:
        qureg.amps = qureg.device_put(
            D.init_pure_state_density(pure.amps, num_qubits=qureg.num_qubits_represented)
        )
    else:
        qureg.amps = jnp.array(pure.amps, copy=True)


def initDebugState(qureg: Qureg) -> None:
    """Set amplitude k to (2k mod ..)/10 + i(2k+1 mod ..)/10 (test oracle state) (QuEST.h:1463)."""
    qureg.amps = qureg.device_put(K.init_debug_state(qureg.num_amps_total, qureg.dtype))


def initStateFromAmps(qureg: Qureg, reals, imags) -> None:
    """Set all amplitudes from real/imag arrays (QuEST.h:1490;
    state-vectors only, QuEST.c:157-158)."""
    V.validate_state_vector(qureg, "initStateFromAmps")
    re = np.asarray(reals, dtype=np.float64).ravel()
    im = np.asarray(imags, dtype=np.float64).ravel()
    if re.size != qureg.num_amps_total or im.size != qureg.num_amps_total:
        raise V.QuESTError("initStateFromAmps: Incorrect number of amplitudes.")
    V.validate_finite(re, "initStateFromAmps")
    V.validate_finite(im, "initStateFromAmps")
    qureg.amps = qureg.device_put(np.stack([re, im]))


def initSparseState(qureg: Qureg, indices, amps) -> None:
    """Initialise from a SPARSE amplitude list: ``state[indices[k]] =
    amps[k]``, every other amplitude zero (docs/design.md §28; sparse
    state preparation per arXiv:2504.08705).  State-vectors only.

    The register is admitted under the governor at SPARSE cost — the
    indices + values, not the dense 2^n footprint — and densifies
    lazily on the first touch under admission control
    (governor.admit_sparse_state), so a budget too tight for the dense
    state today still accepts the description and makes room when the
    first drain arrives.  On an ungoverned scalar register the dense
    state scatters directly on device (kernels.init_sparse_state) —
    either route produces bit-identical amplitudes."""
    from . import governor as _governor

    V.validate_state_vector(qureg, "initSparseState")
    _guard_batched_eager(qureg, "initSparseState")
    idx = np.asarray(indices, dtype=np.int64).ravel()
    vals = np.asarray(amps, dtype=np.complex128).ravel()
    if idx.size == 0 or idx.size != vals.size:
        raise V.QuESTError(
            "initSparseState: indices and amps must be non-empty and "
            "equal length.")
    if int(idx.min()) < 0 or int(idx.max()) >= qureg.num_amps_total:
        raise V.QuESTError("initSparseState: Invalid amplitude index.")
    if np.unique(idx).size != idx.size:
        raise V.QuESTError("initSparseState: duplicate amplitude indices.")
    V.validate_finite(vals.real, "initSparseState")
    V.validate_finite(vals.imag, "initSparseState")
    _telemetry.inc_key(_K_PERM, _bw(qureg))
    _telemetry.inc("sparse_inits_total")
    _telemetry.inc("sparse_init_amps_total", int(idx.size))
    # a wholesale init makes pending fused gates unobservable — drop
    # them like the amps setter does
    if qureg._fusion is not None and qureg._fusion.gates:
        qureg._fusion.gates.clear()
    if not _governor.enabled() and not _fusion._shard_bits(qureg):
        qureg.amps = qureg.device_put(K.init_sparse_state(
            qureg.num_amps_total, idx, vals.real, vals.imag, qureg.dtype))
    else:
        _governor.admit_sparse_state(qureg, idx, vals.real, vals.imag)


def initSparseClusteredState(qureg: Qureg, bases, blocks) -> None:
    """Initialise a sparse CLUSTERED state (arXiv:2504.08705): the
    nonzero amplitudes sit in contiguous blocks, ``state[bases[c] + k] =
    blocks[c][k]`` — the structured-sparsity workload class bench
    config 16 exercises.  Expands the blocks to a flat sparse list and
    delegates to :func:`initSparseState` (same admission semantics)."""
    bl = list(blocks)
    bs = np.asarray(bases, dtype=np.int64).ravel()
    if bs.size == 0 or bs.size != len(bl):
        raise V.QuESTError(
            "initSparseClusteredState: bases and blocks must be "
            "non-empty and equal length.")
    idx_parts = []
    val_parts = []
    for base, block in zip(bs, bl):
        v = np.asarray(block, dtype=np.complex128).ravel()
        if v.size == 0:
            raise V.QuESTError(
                "initSparseClusteredState: empty amplitude block.")
        idx_parts.append(int(base) + np.arange(v.size, dtype=np.int64))
        val_parts.append(v)
    initSparseState(qureg, np.concatenate(idx_parts),
                    np.concatenate(val_parts))


def setAmps(qureg: Qureg, startInd: int, reals, imags, numAmps: int) -> None:
    """Overwrite a contiguous range of amplitudes (QuEST.h:1537)."""
    V.validate_state_vector(qureg, "setAmps")
    V.validate_num_amps(qureg, startInd, numAmps, "setAmps")
    from .ops import element as E

    re = np.asarray(reals, dtype=np.float64).ravel()[:numAmps]
    im = np.asarray(imags, dtype=np.float64).ravel()[:numAmps]
    if re.size != numAmps or im.size != numAmps:
        raise V.QuESTError("setAmps: Incorrect number of amplitudes.")
    V.validate_finite(re, "setAmps")
    V.validate_finite(im, "setAmps")
    vals = np.stack([re, im]).astype(qureg.dtype)
    # layout-safe ranged write: tile-aligned block updates + edge tiles,
    # never the eager .at[].set() whose gather relayouts a canonically-
    # held big state (ops/element.py)
    qureg.amps = E.set_amp_range(qureg.amps, int(startInd), vals)


def setDensityAmps(qureg: Qureg, reals, imags) -> None:
    """Debug API (QuEST_debug.h): overwrite all rho amplitudes."""
    V.validate_density_matrix(qureg, "setDensityAmps")
    re = np.asarray(reals, dtype=np.float64).ravel()
    im = np.asarray(imags, dtype=np.float64).ravel()
    V.validate_finite(re, "setDensityAmps")
    V.validate_finite(im, "setDensityAmps")
    qureg.amps = qureg.device_put(np.stack([re, im]))


def cloneQureg(targetQureg: Qureg, copyQureg: Qureg) -> None:
    """Overwrite targetQureg with a copy of copyQureg (QuEST.h:1559)."""
    V.validate_matching_qureg_types(targetQureg, copyQureg, "cloneQureg")
    V.validate_matching_qureg_dims(targetQureg, copyQureg, "cloneQureg")
    targetQureg.amps = jnp.array(copyQureg.amps, copy=True)


# ---------------------------------------------------------------------------
# Unitary dispatch helpers (QuEST.c:177-346 twin-op pattern)
# ---------------------------------------------------------------------------


def _sv_n(qureg: Qureg) -> int:
    return qureg.num_qubits_in_state_vec


def _shift(qureg: Qureg) -> int:
    return qureg.num_qubits_represented


def _dispatch_matrix(qureg, stacked, targets, controls, control_states):
    """Route a dense-matrix gate, updating the register IN PLACE: explicit
    ppermute path for sharded target qubits (the reference's Distributed
    kernels), ordinary kernel (GSPMD propagation) otherwise — the locality
    predicate of QuEST_cpu_distributed.c:366-371 as a trace-time branch.

    On a sharded register targets are addressed through the live
    logical->physical permutation (Qureg._perm): a multi-target gate
    reaching mesh-coordinate bits relocalizes with half-shard swaps and
    does NOT swap back — the permutation persists (mpiQulacs-style
    communication avoidance, arXiv:2203.16044), later gates hitting the
    same qubits pay ZERO exchanges, and canonical order rematerializes
    lazily on the next state read.  dist.use_lazy_remap(False) restores
    the reference's eager swap-in/swap-out pairs
    (QuEST_cpu_distributed.c:1447-1545)."""
    env = qureg.env
    n = _sv_n(qureg)
    # size of the amplitude-sharding axis, NOT total devices: meshes may
    # carry extra axes (e.g. the (dp, amps) training mesh)
    _guard_batched_eager(qureg, "_dispatch_matrix")
    ndev = PAR.amp_axis_size(env.mesh) if env.mesh is not None else 1
    if ndev > 1 and (1 << n) > ndev and PAR.explicit_dist_enabled():
        nloc = n - PAR.num_shard_bits(env.mesh)
        lazy = PAR.lazy_remap_enabled()
        if not lazy:
            _ = qureg.amps  # materialize any perm left by a lazy phase
        amps = qureg._amps_raw()  # drains any pending fusion first
        perm = qureg._perm
        ptargets = qureg._phys_bits(targets)
        pcontrols = qureg._phys_bits(controls)
        # recency bookkeeping BEFORE computing the eviction order below:
        # the current gate's qubits are the hottest
        for b in (*targets, *controls):
            qureg._use_clock += 1
            qureg._last_use[b] = qureg._use_clock
        high = [t for t in ptargets if t >= nloc]
        if not high:
            _telemetry.inc("dispatch_route_total", route="perm_local")
            qureg._set_amps_permuted(
                K.apply_matrix(
                    amps, stacked, num_qubits=n,
                    targets=ptargets, controls=pcontrols,
                    control_states=control_states),
                perm)
            return
        if len(ptargets) == 1:
            _telemetry.inc("dispatch_route_total", route="exchange_1q")
            qureg._set_amps_permuted(
                PAR.apply_matrix_1q_sharded(
                    amps, stacked, mesh=env.mesh, num_qubits=n,
                    target=ptargets[0], controls=pcontrols,
                    control_states=control_states),
                perm)
            return
        # evict least-recently-used residents: order the free pool by the
        # occupying LOGICAL qubit's last use (never-used first)
        inv = {p: q for q, p in enumerate(perm)} if perm is not None else None
        last = qureg._last_use
        free_order = sorted(
            range(nloc),
            key=lambda p: last.get(inv[p] if inv is not None else p, -1))
        swaps, new_targets = PAR.plan_relocalization(
            n, nloc, ptargets, pcontrols, free_order=free_order)
        if swaps is not None:
            _telemetry.inc("dispatch_route_total", route="relocalize")
            for lo, hi in swaps:
                amps = PAR.swap_sharded(
                    amps, mesh=env.mesh, num_qubits=n, qb_low=lo, qb_high=hi
                )
            amps = K.apply_matrix(
                amps, stacked, num_qubits=n, targets=new_targets,
                controls=pcontrols, control_states=control_states,
            )
            if not lazy:
                for lo, hi in reversed(swaps):
                    amps = PAR.swap_sharded(
                        amps, mesh=env.mesh, num_qubits=n, qb_low=lo,
                        qb_high=hi
                    )
                qureg.amps = amps
                return
            # no swap-back: fold the relocation into the permutation
            newperm = list(perm) if perm is not None else list(range(n))
            inv = [0] * n
            for q, p in enumerate(newperm):
                inv[p] = q
            for lo, hi in swaps:
                ql, qh = inv[lo], inv[hi]
                newperm[ql], newperm[qh] = hi, lo
                inv[lo], inv[hi] = qh, ql
            qureg._set_amps_permuted(amps, tuple(newperm))
            return
        # not enough free local qubits to relocalize (the reference
        # REJECTS such ops, QuEST_validation.c:469-471): materialize
        # canonical order and fall through to GSPMD propagation
    _telemetry.inc("dispatch_route_total", route="default")
    qureg.amps = K.apply_matrix(
        qureg.amps, stacked, num_qubits=n, targets=targets,
        controls=controls, control_states=control_states,
    )


def _apply_unitary(qureg, matrix, targets, controls=(), control_states=()):
    """Kernel on ket qubits; conjugated twin on bra qubits for rho
    (QuEST.c:181-183).  ``matrix`` is host complex; stacked to SoA here.
    Inside a gateFusion context the gate is buffered instead (fusion.py)."""
    targets = tuple(int(t) for t in targets)
    controls = tuple(int(c) for c in controls)
    control_states = tuple(int(s) for s in control_states)
    _telemetry.inc_key(_K_UNITARY, _bw(qureg))
    stacked = CX.soa(matrix)
    if _fusion.capture_unitary(qureg, stacked, targets, controls, control_states):
        return
    _dispatch_matrix(qureg, stacked, targets, controls, control_states)
    if qureg.is_density_matrix:
        sh = _shift(qureg)
        conj_stacked = np.stack([stacked[0], -stacked[1]])
        _dispatch_matrix(
            qureg,
            conj_stacked,
            tuple(t + sh for t in targets),
            tuple(c + sh for c in controls),
            control_states,
        )


def _apply_diag(qureg, diag, targets, controls=(), control_states=()):
    """Diagonal gates are elementwise in the computational basis, so they
    run at the PHYSICAL bit positions of a live permutation without any
    rematerialization (cf. the reference's no-pairing phase kernels,
    QuEST_cpu.c:3146-3361 — no exchange at any position)."""
    targets = tuple(int(t) for t in targets)
    controls = tuple(int(c) for c in controls)
    control_states = tuple(int(s) for s in control_states)
    _telemetry.inc_key(_K_DIAG, _bw(qureg))
    stacked = CX.soa(diag)
    if _fusion.capture_diag(qureg, stacked, targets, controls, control_states):
        return
    _guard_batched_eager(qureg, "_apply_diag")
    amps = qureg._amps_raw()  # drains any pending fusion first
    perm = qureg._perm
    qureg._set_amps_permuted(
        K.apply_diagonal(
            amps, stacked, num_qubits=_sv_n(qureg),
            targets=qureg._phys_bits(targets),
            controls=qureg._phys_bits(controls),
            control_states=control_states,
        ), perm)
    if qureg.is_density_matrix:
        sh = _shift(qureg)
        conj_stacked = np.stack([stacked[0], -stacked[1]])
        qureg._set_amps_permuted(
            K.apply_diagonal(
                qureg._amps_raw(), conj_stacked, num_qubits=_sv_n(qureg),
                targets=qureg._phys_bits(tuple(t + sh for t in targets)),
                controls=qureg._phys_bits(tuple(c + sh for c in controls)),
                control_states=control_states,
            ), perm)


# ---------------------------------------------------------------------------
# Unitaries (QuEST.h:1595-4744)
# ---------------------------------------------------------------------------


def phaseShift(qureg: Qureg, targetQubit: int, angle: float) -> None:
    """Shift the phase of the |1> amplitude of one qubit (QuEST.h:1595)."""
    V.validate_target(qureg, targetQubit, "phaseShift")
    _apply_diag(qureg, G.phase_shift_diag(angle), (targetQubit,))
    qureg.qasm_log.phase_shift(float(angle), (), targetQubit)


def controlledPhaseShift(qureg: Qureg, idQubit1: int, idQubit2: int, angle: float) -> None:
    """Controlled phase shift by the given angle (QuEST.h:1640)."""
    V.validate_control_target(qureg, idQubit1, idQubit2, "controlledPhaseShift")
    _apply_diag(qureg, G.phase_shift_diag(angle), (idQubit2,), (idQubit1,))
    qureg.qasm_log.phase_shift(float(angle), (idQubit1,), idQubit2)


def multiControlledPhaseShift(qureg: Qureg, controlQubits: Sequence[int], angle: float) -> None:
    """Phase on the all-ones state of the listed qubits.  List lengths
    replace the C API's explicit count arguments throughout this binding."""
    qubits = [int(q) for q in controlQubits]
    V.validate_multi_qubits(qureg, qubits, "multiControlledPhaseShift")
    _apply_diag(qureg, G.phase_shift_diag(angle), (qubits[-1],), tuple(qubits[:-1]))
    qureg.qasm_log.phase_shift(float(angle), tuple(qubits[:-1]), qubits[-1])


def controlledPhaseFlip(qureg: Qureg, idQubit1: int, idQubit2: int) -> None:
    """Controlled phase flip (controlled-Z) (QuEST.h:1723)."""
    V.validate_control_target(qureg, idQubit1, idQubit2, "controlledPhaseFlip")
    _apply_diag(qureg, G.Z_DIAG, (idQubit2,), (idQubit1,))
    qureg.qasm_log.gate("z", (idQubit1,), idQubit2)


def multiControlledPhaseFlip(qureg: Qureg, controlQubits: Sequence[int]) -> None:
    """Phase flip conditioned on all given qubits being 1 (QuEST.h:1768)."""
    qubits = [int(q) for q in controlQubits]
    V.validate_multi_qubits(qureg, qubits, "multiControlledPhaseFlip")
    _apply_diag(qureg, G.Z_DIAG, (qubits[-1],), tuple(qubits[:-1]))
    qureg.qasm_log.gate("z", tuple(qubits[:-1]), qubits[-1])


def sGate(qureg: Qureg, targetQubit: int) -> None:
    """Apply the S (phase) gate (QuEST.h:1801)."""
    V.validate_target(qureg, targetQubit, "sGate")
    _apply_diag(qureg, G.S_GATE_DIAG, (targetQubit,))
    qureg.qasm_log.gate("s", (), targetQubit)


def tGate(qureg: Qureg, targetQubit: int) -> None:
    """Apply the T (pi/8) gate (QuEST.h:1834)."""
    V.validate_target(qureg, targetQubit, "tGate")
    _apply_diag(qureg, G.T_GATE_DIAG, (targetQubit,))
    qureg.qasm_log.gate("t", (), targetQubit)


def compactUnitary(qureg: Qureg, targetQubit: int, alpha, beta) -> None:
    """Apply the compact unitary [[alpha, -conj(beta)], [beta, conj(alpha)]] (QuEST.h:2141)."""
    V.validate_target(qureg, targetQubit, "compactUnitary")
    alpha, beta = complex(alpha), complex(beta)
    if abs(abs(alpha) ** 2 + abs(beta) ** 2 - 1) > 64 * validation_eps():
        raise V.QuESTError("compactUnitary: Compact matrix formed by given complex numbers is not unitary.")
    m = G.compact_unitary_matrix(alpha, beta)
    _apply_unitary(qureg, m, (targetQubit,))
    qureg.qasm_log.unitary_2x2(np.array([[alpha, -np.conj(beta)], [beta, np.conj(alpha)]]), (), targetQubit)


def unitary(qureg: Qureg, targetQubit: int, u) -> None:
    """Arbitrary single-qubit unitary (QuEST.h:2182)."""
    V.validate_target(qureg, targetQubit, "unitary")
    V.validate_unitary(u, 1, "unitary")
    _apply_unitary(qureg, u, (targetQubit,))
    qureg.qasm_log.unitary_2x2(np.asarray(u, complex), (), targetQubit)


def rotateX(qureg: Qureg, rotQubit: int, angle: float) -> None:
    V.validate_target(qureg, rotQubit, "rotateX")
    _apply_unitary(qureg, G.rotate_x_matrix(angle), (rotQubit,))
    qureg.qasm_log.gate("Rx", (), rotQubit, [float(angle)])


def rotateY(qureg: Qureg, rotQubit: int, angle: float) -> None:
    V.validate_target(qureg, rotQubit, "rotateY")
    _apply_unitary(qureg, G.rotate_y_matrix(angle), (rotQubit,))
    qureg.qasm_log.gate("Ry", (), rotQubit, [float(angle)])


def rotateZ(qureg: Qureg, rotQubit: int, angle: float) -> None:
    V.validate_target(qureg, rotQubit, "rotateZ")
    _apply_diag(qureg, G.rotate_z_diag(angle), (rotQubit,))
    qureg.qasm_log.gate("Rz", (), rotQubit, [float(angle)])


def rotateAroundAxis(qureg: Qureg, rotQubit: int, angle: float, axis) -> None:
    """Rotation around an arbitrary Bloch axis (QuEST.h:2327)."""
    V.validate_target(qureg, rotQubit, "rotateAroundAxis")
    ax = _axis_vec(axis)
    V.validate_unit_vector(ax[0], ax[1], ax[2], "rotateAroundAxis")
    m = G.rotate_around_axis_matrix(angle, ax)
    _apply_unitary(qureg, m, (rotQubit,))
    qureg.qasm_log.unitary_2x2(np.asarray(m), (), rotQubit)


def controlledRotateX(qureg, controlQubit, targetQubit, angle) -> None:
    V.validate_control_target(qureg, controlQubit, targetQubit, "controlledRotateX")
    _apply_unitary(qureg, G.rotate_x_matrix(angle), (targetQubit,), (controlQubit,))
    qureg.qasm_log.gate("Rx", (controlQubit,), targetQubit, [float(angle)])


def controlledRotateY(qureg, controlQubit, targetQubit, angle) -> None:
    V.validate_control_target(qureg, controlQubit, targetQubit, "controlledRotateY")
    _apply_unitary(qureg, G.rotate_y_matrix(angle), (targetQubit,), (controlQubit,))
    qureg.qasm_log.gate("Ry", (controlQubit,), targetQubit, [float(angle)])


def controlledRotateZ(qureg, controlQubit, targetQubit, angle) -> None:
    V.validate_control_target(qureg, controlQubit, targetQubit, "controlledRotateZ")
    _apply_diag(
        qureg,
        G.rotate_z_diag(angle),
        (targetQubit,),
        (controlQubit,),
    )
    qureg.qasm_log.gate("Rz", (controlQubit,), targetQubit, [float(angle)])


def controlledRotateAroundAxis(qureg, controlQubit, targetQubit, angle, axis) -> None:
    """Controlled rotation around an arbitrary Bloch axis (QuEST.h:2486)."""
    V.validate_control_target(qureg, controlQubit, targetQubit, "controlledRotateAroundAxis")
    ax = _axis_vec(axis)
    V.validate_unit_vector(ax[0], ax[1], ax[2], "controlledRotateAroundAxis")
    m = G.rotate_around_axis_matrix(angle, ax)
    _apply_unitary(qureg, m, (targetQubit,), (controlQubit,))
    qureg.qasm_log.unitary_2x2(np.asarray(m), (controlQubit,), targetQubit)


def controlledCompactUnitary(qureg, controlQubit, targetQubit, alpha, beta) -> None:
    """Controlled compact unitary (QuEST.h:2537)."""
    V.validate_control_target(qureg, controlQubit, targetQubit, "controlledCompactUnitary")
    alpha, beta = complex(alpha), complex(beta)
    if abs(abs(alpha) ** 2 + abs(beta) ** 2 - 1) > 64 * validation_eps():
        raise V.QuESTError("controlledCompactUnitary: Compact matrix formed by given complex numbers is not unitary.")
    _apply_unitary(qureg, G.compact_unitary_matrix(alpha, beta), (targetQubit,), (controlQubit,))
    qureg.qasm_log.unitary_2x2(
        np.array([[alpha, -np.conj(beta)], [beta, np.conj(alpha)]]),
        (controlQubit,), targetQubit,
    )


def controlledUnitary(qureg, controlQubit, targetQubit, u) -> None:
    """Controlled arbitrary single-qubit unitary (QuEST.h:2588)."""
    V.validate_control_target(qureg, controlQubit, targetQubit, "controlledUnitary")
    V.validate_unitary(u, 1, "controlledUnitary")
    _apply_unitary(qureg, u, (targetQubit,), (controlQubit,))
    qureg.qasm_log.unitary_2x2(np.asarray(u, complex), (controlQubit,), targetQubit)


def multiControlledUnitary(qureg, controlQubits, targetQubit, u) -> None:
    """Multi-controlled arbitrary single-qubit unitary (QuEST.h:2652)."""
    controls, target = [int(c) for c in controlQubits], int(targetQubit)
    V.validate_multi_controls_target(qureg, controls, target, "multiControlledUnitary")
    V.validate_unitary(u, 1, "multiControlledUnitary")
    _apply_unitary(qureg, u, (target,), tuple(controls))
    qureg.qasm_log.unitary_2x2(np.asarray(u, complex), tuple(controls), target)


def multiStateControlledUnitary(qureg, controlQubits, controlStates, targetQubit, u) -> None:
    """Controlled unitary with per-control 0/1 condition states (QuEST.h:3877)."""
    controls = list(controlQubits)
    states = list(controlStates)
    V.validate_multi_controls_target(qureg, controls, targetQubit, "multiStateControlledUnitary")
    V.validate_control_states(controls, states, "multiStateControlledUnitary")
    V.validate_unitary(u, 1, "multiStateControlledUnitary")
    _apply_unitary(qureg, u, (targetQubit,), tuple(controls), tuple(states))
    qureg.qasm_log.unitary_2x2(np.asarray(u, complex), tuple(controls), targetQubit, states)


def pauliX(qureg: Qureg, targetQubit: int) -> None:
    """Apply Pauli-X (QuEST.h:2689)."""
    V.validate_target(qureg, targetQubit, "pauliX")
    _apply_not(qureg, (targetQubit,), ())
    qureg.qasm_log.gate("x", (), targetQubit)


def pauliY(qureg: Qureg, targetQubit: int) -> None:
    """Apply Pauli-Y (QuEST.h:2724)."""
    V.validate_target(qureg, targetQubit, "pauliY")
    _apply_unitary(qureg, G.PAULI_Y, (targetQubit,))
    qureg.qasm_log.gate("y", (), targetQubit)


def pauliZ(qureg: Qureg, targetQubit: int) -> None:
    """Apply Pauli-Z (QuEST.h:2762)."""
    V.validate_target(qureg, targetQubit, "pauliZ")
    _apply_diag(qureg, G.Z_DIAG, (targetQubit,))
    qureg.qasm_log.gate("z", (), targetQubit)


def hadamard(qureg: Qureg, targetQubit: int) -> None:
    """Apply the Hadamard gate (QuEST.h:2794)."""
    V.validate_target(qureg, targetQubit, "hadamard")
    _apply_unitary(qureg, G.HADAMARD, (targetQubit,))
    qureg.qasm_log.gate("h", (), targetQubit)


def controlledNot(qureg: Qureg, controlQubit: int, targetQubit: int) -> None:
    """Controlled Pauli-X (CNOT) (QuEST.h:2838)."""
    V.validate_control_target(qureg, controlQubit, targetQubit, "controlledNot")
    _apply_not(qureg, (targetQubit,), (controlQubit,))
    qureg.qasm_log.gate("x", (controlQubit,), targetQubit)


def multiQubitNot(qureg: Qureg, targs: Sequence[int]) -> None:
    """Pauli-X on several target qubits at once (QuEST.h:2971)."""
    targets = [int(t) for t in targs]
    V.validate_multi_targets(qureg, targets, "multiQubitNot")
    _apply_not(qureg, tuple(targets), ())
    for t in targets:
        qureg.qasm_log.gate("x", (), t)


def multiControlledMultiQubitNot(qureg, ctrls, targs) -> None:
    """Multi-controlled multi-target Pauli-X (QuEST.h:2914)."""
    controls, targets = [int(c) for c in ctrls], [int(t) for t in targs]
    V.validate_multi_controls_targets(qureg, controls, targets, "multiControlledMultiQubitNot")
    _apply_not(qureg, tuple(targets), tuple(controls))
    for t in targets:
        qureg.qasm_log.gate("x", tuple(controls), t)


def _apply_not(qureg, targets, controls, control_states=()):
    """NOTs are pure index-bit flips, position-independent — like
    _apply_diag they run at the physical positions of a live
    permutation."""
    _telemetry.inc_key(_K_NOT, _bw(qureg))
    if _fusion.capture_not(qureg, targets, controls, control_states):
        return
    _guard_batched_eager(qureg, "_apply_not")
    amps = qureg._amps_raw()  # drains any pending fusion first
    perm = qureg._perm
    qureg._set_amps_permuted(
        K.apply_multi_qubit_not(
            amps, num_qubits=_sv_n(qureg),
            targets=qureg._phys_bits(targets),
            controls=qureg._phys_bits(controls),
            control_states=control_states,
        ), perm)
    if qureg.is_density_matrix:
        sh = _shift(qureg)
        qureg._set_amps_permuted(
            K.apply_multi_qubit_not(
                qureg._amps_raw(), num_qubits=_sv_n(qureg),
                targets=qureg._phys_bits(tuple(t + sh for t in targets)),
                controls=qureg._phys_bits(tuple(c + sh for c in controls)),
                control_states=control_states,
            ), perm)


def controlledPauliY(qureg: Qureg, controlQubit: int, targetQubit: int) -> None:
    """Controlled Pauli-Y (QuEST.h:3013)."""
    V.validate_control_target(qureg, controlQubit, targetQubit, "controlledPauliY")
    _apply_unitary(qureg, G.PAULI_Y, (targetQubit,), (controlQubit,))
    qureg.qasm_log.gate("y", (controlQubit,), targetQubit)


_SWAP_SOA = np.stack([
    np.array([[1.0, 0, 0, 0], [0, 0, 1, 0], [0, 1, 0, 0], [0, 0, 0, 1]]),
    np.zeros((4, 4)),
])


def swapGate(qureg: Qureg, qubit1: int, qubit2: int) -> None:
    """Swap two qubits' amplitudes (QuEST.h:3768).

    On a sharded register under the lazy-permutation scheduler a SWAP is
    pure relabeling: it folds into the live logical->physical permutation
    at ZERO data-movement cost (where the reference's distributed
    statevec_swapQubitAmps exchanges half the state,
    QuEST_cpu_distributed.c:1397-1436); canonical order rematerializes on
    the next state read."""
    V.validate_unique_targets(qureg, qubit1, qubit2, "swapGate")
    _telemetry.inc_key(_K_SWAP, _bw(qureg))
    if _fusion.capture_unitary(qureg, _SWAP_SOA, (qubit1, qubit2)):
        qureg.qasm_log.gate("swap", (qubit1,), qubit2)
        return
    env = qureg.env
    ndev = PAR.amp_axis_size(env.mesh) if env.mesh is not None else 1
    if (PAR.lazy_remap_enabled() and PAR.explicit_dist_enabled()
            and ndev > 1 and qureg.num_amps_total >= env.num_devices):
        amps = qureg._amps_raw()
        n = _sv_n(qureg)
        perm = list(qureg._perm or range(n))
        pairs = [(qubit1, qubit2)]
        if qureg.is_density_matrix:
            sh = _shift(qureg)
            pairs.append((qubit1 + sh, qubit2 + sh))
        for a, b in pairs:
            perm[a], perm[b] = perm[b], perm[a]
        qureg._set_amps_permuted(amps, tuple(perm))
        qureg.qasm_log.gate("swap", (qubit1,), qubit2)
        return
    _guard_batched_eager(qureg, "swapGate")
    from . import circuit as _circ

    if _circ.perm_fast_enabled():
        # §28 relabel route: ONE transpose-shaped index relabel
        # (kernels.permute_qubits) instead of swap_qubit_amps' matmul
        # pass — covers ket and bra bits in the same kernel
        n = _sv_n(qureg)
        perm = list(range(n))
        pairs = [(qubit1, qubit2)]
        if qureg.is_density_matrix:
            sh = _shift(qureg)
            pairs.append((qubit1 + sh, qubit2 + sh))
        for a, b in pairs:
            perm[a], perm[b] = perm[b], perm[a]
        _telemetry.inc_key(_K_PERM, _bw(qureg))
        _telemetry.inc("permutation_gates_total", route="relabel")
        qureg.amps = K.permute_qubits(
            qureg.amps, num_qubits=n, perm=tuple(perm))
    else:
        qureg.amps = K.swap_qubit_amps(
            qureg.amps, num_qubits=_sv_n(qureg), qb1=qubit1, qb2=qubit2)
        if qureg.is_density_matrix:
            sh = _shift(qureg)
            qureg.amps = K.swap_qubit_amps(
                qureg.amps, num_qubits=_sv_n(qureg), qb1=qubit1 + sh,
                qb2=qubit2 + sh
            )
    qureg.qasm_log.gate("swap", (qubit1,), qubit2)


def sqrtSwapGate(qureg: Qureg, qb1: int, qb2: int) -> None:
    """Apply the square-root-of-SWAP gate (QuEST.h:3816)."""
    V.validate_unique_targets(qureg, qb1, qb2, "sqrtSwapGate")
    _apply_unitary(qureg, G.SQRT_SWAP, (qb1, qb2))
    qureg.qasm_log.gate("sqrtswap", (qb1,), qb2)


def multiRotateZ(qureg: Qureg, qubits: Sequence[int], angle: float) -> None:
    """Rotation generated by a product of Z operators (parity phase) (QuEST.h:3912)."""
    qubits, angle = [int(q) for q in qubits], float(angle)
    V.validate_multi_targets(qureg, qubits, "multiRotateZ")
    _apply_parity_phase(qureg, angle, tuple(qubits), ())
    qureg.qasm_log.comment(f"multiRotateZ(angle={angle:g}) on qubits {qubits}")


def multiControlledMultiRotateZ(qureg, controlQubits, targetQubits, angle) -> None:
    """Multi-controlled Z-product rotation (QuEST.h:4037)."""
    controls, targets = list(controlQubits), list(targetQubits)
    V.validate_multi_controls_targets(qureg, controls, targets, "multiControlledMultiRotateZ")
    _apply_parity_phase(qureg, angle, tuple(targets), tuple(controls))
    qureg.qasm_log.comment(
        f"multiControlledMultiRotateZ(angle={angle:g}) ctrls {controls} targs {targets}"
    )


def _apply_parity_phase(qureg, angle, qubits, controls, conj=False):
    # parity phases are index-derived (elementwise): physical positions
    # of the live permutation, no rematerialization
    _telemetry.inc_key(_K_PARITY, _bw(qureg))
    _guard_batched_eager(qureg, "_apply_parity_phase")
    a = -angle if conj else angle
    amps = qureg._amps_raw()  # drains any pending fusion first
    perm = qureg._perm
    qureg._set_amps_permuted(
        K.apply_parity_phase(
            amps, a, num_qubits=_sv_n(qureg),
            qubits=qureg._phys_bits(qubits),
            controls=qureg._phys_bits(controls),
        ), perm)
    if qureg.is_density_matrix:
        sh = _shift(qureg)
        qureg._set_amps_permuted(
            K.apply_parity_phase(
                qureg._amps_raw(), -a, num_qubits=_sv_n(qureg),
                qubits=qureg._phys_bits(tuple(q + sh for q in qubits)),
                controls=qureg._phys_bits(tuple(c + sh for c in controls)),
            ), perm)


def multiRotatePauli(qureg: Qureg, targetQubits, targetPaulis, angle: float) -> None:
    """Rotation generated by a product of Pauli operators (QuEST.h:3967)."""
    targets = [int(t) for t in targetQubits]
    paulis = [int(p) for p in targetPaulis]
    V.validate_multi_targets(qureg, targets, "multiRotatePauli")
    V.validate_pauli_codes(paulis, "multiRotatePauli")
    _multi_rotate_pauli(qureg, targets, paulis, float(angle), controls=())
    qureg.qasm_log.comment(
        f"multiRotatePauli(angle={angle:g}) on qubits {targets} paulis {paulis}"
    )


def multiControlledMultiRotatePauli(qureg, controlQubits, targetQubits, targetPaulis, angle) -> None:
    """Multi-controlled Pauli-product rotation (QuEST.h:4138)."""
    controls = [int(c) for c in controlQubits]
    targets = [int(t) for t in targetQubits]
    paulis = [int(p) for p in targetPaulis]
    V.validate_multi_controls_targets(qureg, controls, targets, "multiControlledMultiRotatePauli")
    V.validate_pauli_codes(paulis, "multiControlledMultiRotatePauli")
    _multi_rotate_pauli(qureg, targets, paulis, float(angle), controls=tuple(controls))
    qureg.qasm_log.comment(
        f"multiControlledMultiRotatePauli(angle={angle:g}) ctrls {controls} targs {targets} paulis {paulis}"
    )


_RY_M90 = G.RY_M90  # Z->X
_RX_P90 = G.RX_P90  # Z->Y


def _multi_rotate_pauli(qureg, targets, paulis, angle, controls):
    """Basis-rotate X/Y targets onto Z, multiRotateZ, unrotate
    (statevec_multiRotatePauli, QuEST_common.c:424-462).  The basis gates are
    applied through the twin-aware helpers so the rho path is automatic."""
    z_qubits = []
    for t, p in zip(targets, paulis):
        if p == PAULI_I:
            continue
        z_qubits.append(t)
        if p == PAULI_X:
            _apply_unitary(qureg, _RY_M90, (t,), controls)
        elif p == PAULI_Y:
            _apply_unitary(qureg, _RX_P90, (t,), controls)
    if z_qubits:
        _apply_parity_phase(qureg, angle, tuple(z_qubits), controls)
    for t, p in zip(targets, paulis):
        if p == PAULI_X:
            _apply_unitary(qureg, _RY_M90.conj().T, (t,), controls)
        elif p == PAULI_Y:
            _apply_unitary(qureg, _RX_P90.conj().T, (t,), controls)


def twoQubitUnitary(qureg: Qureg, targetQubit1: int, targetQubit2: int, u) -> None:
    """Arbitrary two-qubit unitary (QuEST.h:4353)."""
    V.validate_unique_targets(qureg, targetQubit1, targetQubit2, "twoQubitUnitary")
    V.validate_unitary(u, 2, "twoQubitUnitary")
    V.validate_multi_qubit_matrix_fits_in_node(qureg, 2, "twoQubitUnitary")
    _apply_unitary(qureg, u, (targetQubit1, targetQubit2))
    qureg.qasm_log.comment("twoQubitUnitary applied")


def controlledTwoQubitUnitary(qureg, controlQubit, targetQubit1, targetQubit2, u) -> None:
    """Controlled arbitrary two-qubit unitary (QuEST.h:4420)."""
    V.validate_multi_controls_targets(
        qureg, [controlQubit], [targetQubit1, targetQubit2], "controlledTwoQubitUnitary"
    )
    V.validate_unitary(u, 2, "controlledTwoQubitUnitary")
    V.validate_multi_qubit_matrix_fits_in_node(qureg, 2, "controlledTwoQubitUnitary")
    _apply_unitary(qureg, u, (targetQubit1, targetQubit2), (controlQubit,))
    qureg.qasm_log.comment("controlledTwoQubitUnitary applied")


def multiControlledTwoQubitUnitary(qureg, controlQubits, targetQubit1, targetQubit2, u) -> None:
    """Multi-controlled arbitrary two-qubit unitary (QuEST.h:4499)."""
    controls = list(controlQubits)
    V.validate_multi_controls_targets(
        qureg, controls, [targetQubit1, targetQubit2], "multiControlledTwoQubitUnitary"
    )
    V.validate_unitary(u, 2, "multiControlledTwoQubitUnitary")
    V.validate_multi_qubit_matrix_fits_in_node(qureg, 2, "multiControlledTwoQubitUnitary")
    _apply_unitary(qureg, u, (targetQubit1, targetQubit2), tuple(controls))
    qureg.qasm_log.comment("multiControlledTwoQubitUnitary applied")


def multiQubitUnitary(qureg: Qureg, targs: Sequence[int], u) -> None:
    """Arbitrary unitary on N target qubits (QuEST.h:4582)."""
    targets = list(targs)
    V.validate_multi_targets(qureg, targets, "multiQubitUnitary")
    V.validate_unitary(u, len(targets), "multiQubitUnitary")
    V.validate_multi_qubit_matrix_fits_in_node(qureg, len(targets), "multiQubitUnitary")
    _apply_unitary(qureg, u, tuple(targets))
    qureg.qasm_log.comment("multiQubitUnitary applied")


def controlledMultiQubitUnitary(qureg, ctrl, targs, u) -> None:
    """Controlled arbitrary multi-qubit unitary (QuEST.h:4655)."""
    targets = list(targs)
    V.validate_multi_controls_targets(qureg, [ctrl], targets, "controlledMultiQubitUnitary")
    V.validate_unitary(u, len(targets), "controlledMultiQubitUnitary")
    V.validate_multi_qubit_matrix_fits_in_node(qureg, len(targets), "controlledMultiQubitUnitary")
    _apply_unitary(qureg, u, tuple(targets), (ctrl,))
    qureg.qasm_log.comment("controlledMultiQubitUnitary applied")


def multiControlledMultiQubitUnitary(qureg, ctrls, targs, u) -> None:
    """Multi-controlled arbitrary multi-qubit unitary (QuEST.h:4744)."""
    controls, targets = list(ctrls), list(targs)
    V.validate_multi_controls_targets(qureg, controls, targets, "multiControlledMultiQubitUnitary")
    V.validate_unitary(u, len(targets), "multiControlledMultiQubitUnitary")
    V.validate_multi_qubit_matrix_fits_in_node(qureg, len(targets), "multiControlledMultiQubitUnitary")
    _apply_unitary(qureg, u, tuple(targets), tuple(controls))
    qureg.qasm_log.comment("multiControlledMultiQubitUnitary applied")


def _axis_vec(axis):
    if hasattr(axis, "x"):
        return (float(axis.x), float(axis.y), float(axis.z))
    ax = np.asarray(axis, dtype=np.float64)
    return (float(ax[0]), float(ax[1]), float(ax[2]))


class Vector:
    """3-vector for rotateAroundAxis (QuEST.h:198)."""

    def __init__(self, x: float, y: float, z: float):
        self.x, self.y, self.z = float(x), float(y), float(z)
