"""Execution introspection: plan explainer, HLO audit, reconciliation.

The telemetry layer (telemetry.py) counts what *happened* — dispatches,
exchange programs, per-shard ICI bytes.  Nothing so far could tell a user
what a circuit *will* cost before it runs, nor prove that the measured
counters still agree with the scheduler's cost model as the planner
evolves.  mpiQulacs (arXiv:2203.16044 §V) and qHiPSTER (arXiv:1601.07195
§IV) both treat predictive communication accounting as the tuning
surface of a distributed simulator; this module closes that loop
(docs/design.md §21):

* **Plan explainer** — :func:`explain_circuit` dry-runs the fusion
  planner (circuit.plan_remap_windows + the channel-segmentation rules
  of fusion._split_items) with NO device execution and returns a
  per-window report: gates fused, remap sigma, predicted per-shard ICI
  bytes (circuit.remap_exchange_bytes), the pipeline chunk split the
  PIPELINE_MIN_BYTES policy resolves, the plan-cache key status /
  expected retrace behavior, and bucket occupancy for a BatchedQureg.
  The report is a JSON-serializable dict with a ``.table()`` text
  rendering; :func:`report_circuit_plan` prints it (the ``report*``
  family, like reportQuregParams / reportPerf).

* **HLO audit** — :func:`audit` compiles a function and histograms the
  ACTUAL collective instructions in the optimized HLO (exact opcodes,
  promoted from tests/test_distributed_hlo.py where the recipe was
  trapped), plus ``Compiled.cost_analysis()`` flops/bytes.
  :class:`CollectiveBudget` asserts per-op budgets — as a context
  manager every :func:`audit` inside is checked automatically, so user
  code, CI, and the tests share one budget surface.

* **Reconciliation** — after each sharded drain, fusion._run calls
  :func:`reconcile_drain`: the measured ``exchanges_total`` /
  ``exchange_bytes_total{op=window_remap}`` deltas are compared against
  an INDEPENDENT re-derivation from the window planner's cost model.
  Agreement is the contract (``model_drift_total == 0``); any deviation
  increments ``model_drift_total{kind}`` and emits one structured JSON
  log line on the ``quest_tpu.introspect`` logger.  reportPerf gains a
  predicted-vs-measured section.  :func:`perturb_prediction` (or the
  ``QT_INTROSPECT_PERTURB`` env var) injects a planner-policy
  perturbation — e.g. a forced chunk-count override — to prove the loop
  detects drift, the same fault-injection philosophy as
  resilience.FaultPlan.
"""

from __future__ import annotations

import contextlib
import functools
import json
import logging
import os
import re
from typing import Iterator, Optional, Sequence

import numpy as np

from . import circuit as C
from . import telemetry as _telemetry

_LOG = logging.getLogger("quest_tpu.introspect")

_PERTURB_ENV = "QT_INTROSPECT_PERTURB"

# ---------------------------------------------------------------------------
# Plan explainer
# ---------------------------------------------------------------------------


class ExplainReport(dict):
    """The explain_circuit result: a plain JSON-serializable dict (every
    value is a Python native) plus a ``table()`` text rendering."""

    def table(self) -> str:
        return format_explain(self)


def _as_items(gates) -> list:
    """Normalize a user gate sequence to drain items: circuit.Gate and
    fusion.ChannelItem pass through; ``(targets, mat)`` pairs become
    Gates (mat in the stacked (2, s, s) SoA form)."""
    from . import fusion as F

    items = []
    for g in gates:
        if isinstance(g, (C.Gate, F.ChannelItem)):
            items.append(g)
        else:
            targets, mat = g
            items.append(C.Gate(tuple(int(t) for t in targets),
                                np.asarray(mat)))
    return items


def _segment_stats(items, nloc=None, perm=None) -> tuple:
    """(plan_windows, gates, channels, perm_windows, mega_windows,
    mega_ops) for one item run under fusion._split_items's segmentation:
    each maximal consecutive gate run splits into permutation runs (§28
    — their own ("perm", ...) parts, which fusion_windows_total does NOT
    count) and dense runs that fold into ONE ("plan", ...) part each;
    channels emit chan/chansweep parts, also uncounted.  When ``nloc``
    is given and the §29 megakernel planner is active, each dense run is
    additionally planned through circuit.plan_circuit — the exact local
    planner the drain dispatches — to count megawin groups and the
    winfused ops they absorb; ``perm`` first rewrites logical targets to
    their physical shard-local bits, mirroring the sharded dispatcher's
    own rewrite."""
    from . import fusion as F
    from .ops import fused as _fused

    count_mega = (nloc is not None and nloc >= C.WINDOW
                  and _fused.megakernel_planning())
    plan_parts = 0
    perm_parts = 0
    gates = 0
    chans = 0
    mega_groups = 0
    mega_ops = 0
    seg: list = []

    def flush():
        nonlocal plan_parts, perm_parts, mega_groups, mega_ops
        if not seg:
            return
        for kind, sub in F._perm_runs(seg):
            if kind == "perm":
                perm_parts += 1
            else:
                plan_parts += 1
                if count_mega:
                    for op in C.plan_circuit(list(sub), nloc):
                        if op[0] == "megawin":
                            mega_groups += 1
                            mega_ops += len(op[1])
        seg.clear()

    for it in items:
        if isinstance(it, F.ChannelItem):
            chans += 1
            flush()
        else:
            gates += 1
            if perm is not None:
                it = C.Gate(tuple(perm[t] for t in it.targets), it.mat)
            seg.append(it)
    flush()
    return plan_parts, gates, chans, perm_parts, mega_groups, mega_ops


def _sigma_cost(sigma, n: int, nloc: int, nsh: int, itemsize: int,
                backend: Optional[str] = None) -> dict:
    """Exchange classes, per-shard ICI bytes, and the pipeline chunk
    split for ONE batched remap — straight from the scheduling layer's
    own cost model (dist.decompose_sigma / circuit.remap_exchange_bytes
    / the PIPELINE_MIN_BYTES policy via dist.remap_chunk_plan)."""
    from .parallel import dist as PAR

    mixed, _lp, mesh_tau = PAR.decompose_sigma(tuple(sigma), nloc, nsh)
    ch_half, ch_full = PAR.remap_chunk_plan(nloc, itemsize, backend=backend)
    # per-interconnect-tier refinement of the same model (QT_TOPOLOGY;
    # single-host arrangements put everything under "ici")
    tiers = PAR.remap_exchange_tiers(tuple(sigma), nloc, nsh, itemsize)
    return {
        "sigma": [int(p) for p in sigma],
        "mixed_swaps": len(mixed),
        "mesh_permute": mesh_tau is not None,
        "exchanges": PAR.remap_exchange_count(tuple(sigma), nloc, nsh),
        "exchange_bytes": int(C.remap_exchange_bytes(
            tuple(sigma), n, nloc, itemsize)),
        "tier_bytes": {t: int(b) for t, (_c, b) in tiers.items()},
        "tier_exchanges": {t: int(c) for t, (c, _b) in tiers.items()},
        "chunks": {"half_shard": int(ch_half), "full_shard": int(ch_full)},
    }


def _optimizer_section(orig_items, opt_items, ostats, *, n, nloc, nsh,
                       perm0, itemsize, bw) -> dict:
    """The explain report's ``optimizer`` entry: the rewrite's own stats
    plus projected exchange savings — the SAME per-tier cost model the
    window accounting uses, diffed between the original and the
    optimized stream (sharded registers; scalar registers diff the local
    planner's pass count instead)."""
    from . import fusion as F
    from .parallel import dist as PAR

    section = {
        "mode": ostats["mode"],
        "gates_in": int(ostats["gates_in"]),
        "gates_out": int(ostats["gates_out"]),
        "removed": {k: int(v) for k, v in ostats["removed"].items()},
        "reordered": bool(ostats["reordered"]),
        "windows_before": ostats["windows_before"],
        "windows_after": ostats["windows_after"],
        "tier_savings_bytes": None,
        "exchange_savings": None,
    }
    changed = (ostats["reordered"]
               or any(ostats["removed"].values())
               or len(opt_items) != len(orig_items))
    if nsh and orig_items:

        def _cost(seq):
            tiers = {"ici": 0, "dcn": 0}
            count = 0
            if not seq:
                return tiers, count
            segments, fperm = C.plan_remap_windows(
                [F._item_entry(it) for it in seq], n, nloc, perm0)
            sigmas = [s for _ij, s, _p in segments if s is not None]
            if fperm is not None and list(fperm) != list(range(n)):
                sigmas.append(PAR.canonical_sigma(tuple(fperm)))
            for sigma in sigmas:
                count += PAR.remap_exchange_count(tuple(sigma), nloc, nsh)
                for t, b in C.remap_exchange_bytes_tiers(
                        tuple(sigma), n, nloc, itemsize).items():
                    tiers[t] = tiers.get(t, 0) + b
            return tiers, count

        t0, c0 = _cost(orig_items)
        t1, c1 = (t0, c0) if not changed else _cost(opt_items)
        section["tier_savings_bytes"] = {
            t: int((t0.get(t, 0) - t1.get(t, 0)) * bw) for t in t0}
        section["exchange_savings"] = int((c0 - c1) * bw)
    elif not nsh:
        # scalar registers have no exchange cost; the comparable
        # quantity is the local planner's HBM pass count (bounded:
        # a dry re-plan of very long streams is not worth the host time)
        gates0 = [it for it in orig_items if isinstance(it, C.Gate)]
        if 0 < len(gates0) <= 512 and all(
                isinstance(g.mat, np.ndarray) and g.mat.ndim == 3
                for g in gates0):
            gates1 = [it for it in opt_items if isinstance(it, C.Gate)]
            wb = C.stats(C.plan_circuit(gates0, nloc))["total_passes"]
            wa = C.stats(C.plan_circuit(gates1, nloc))["total_passes"] \
                if gates1 else 0
            if not changed:
                wa = wb
            section["windows_before"] = int(wb)
            section["windows_after"] = int(wa)
    return section


def explain_circuit(qureg, gates=None) -> ExplainReport:
    """Dry-run the fusion planner over ``gates`` (or the register's
    pending fusion buffer when None) — NO device execution, no drain,
    no telemetry mutation — and return the per-window plan report.

    The predicted window-remap exchange count and per-shard bytes are
    the SAME quantities telemetry records at dispatch time
    (``exchanges_total``/``exchange_bytes_total{op=window_remap}``):
    running the explained stream and diffing the counters must agree
    exactly, and :func:`reconcile_drain` asserts exactly that after
    every sharded drain.  ``final_remap`` is the extra canonical-order
    rematerialization (``op=remap``) the next ``Qureg.amps`` read pays
    when the plan leaves a live permutation behind.

    The circuit optimizer (optimizer.py, docs/design.md §26) rewrites
    the stream before planning, so the whole report prices the
    OPTIMIZED stream — exactly what a drain would execute — and the
    ``optimizer`` section carries the rewrite's accounting: gates
    in/out, removals by kind, remap windows before/after, and the
    projected per-tier exchange savings from the same cost model."""
    from . import fusion as F
    from . import optimizer as _optimizer
    from .ops import fused as _fusedmod
    from .parallel import topology as _topology

    if gates is None:
        buf = getattr(qureg, "_fusion", None)
        items = list(buf.gates) if buf is not None else []
    else:
        items = _as_items(gates)
    n = qureg.num_qubits_in_state_vec
    nsh = F._shard_bits(qureg)
    nloc = n - nsh
    bsz = int(getattr(qureg, "batch_size", 0) or 0)
    bw = max(bsz, 1)
    itemsize = int(np.dtype(qureg.dtype).itemsize)
    sweep_ok = _fusedmod.channel_sweep_enabled(qureg.dtype)
    perm0 = qureg._perm if nsh else None

    # the optimizer rewrite a drain would apply (quiet: no telemetry,
    # no cache-status flips) — everything below prices opt_items; the
    # memory section re-derives the same rewrite through
    # plan_items_quiet, so both views describe one stream
    orig_items = items
    items, ostats = _optimizer.optimize_items(
        items, n=n, nloc=nloc, nsh=nsh, perm0=perm0, quiet=True)
    optimizer_section = _optimizer_section(
        orig_items, items, ostats, n=n, nloc=nloc, nsh=nsh, perm0=perm0,
        itemsize=itemsize, bw=bw)

    register = {
        "qubits": int(qureg.num_qubits_represented),
        "density": bool(qureg.is_density_matrix),
        "state_bits": int(n),
        "shards": int(1 << nsh),
        "shard_bits": int(nsh),
        "nloc": int(nloc),
        "perm0": None if perm0 is None else [int(p) for p in perm0],
        "itemsize": itemsize,
    }
    if bsz:
        from . import batch as _batch

        register["batch"] = _batch.bank_occupancy(qureg)

    windows: list = []
    final_remap = None
    tot_exch = 0
    tot_bytes = 0
    tot_tier = {"ici": 0, "dcn": 0}
    plan_windows = 0
    perm_windows = 0
    mega_windows = 0
    if nsh and items:
        entries = [F._item_entry(it) for it in items]
        segments, final_perm = C.plan_remap_windows(entries, n, nloc, perm0)
        for k, ((i, j), sigma, _perm) in enumerate(segments):
            if C._is_relabel_entry(entries[i]):
                # §28 permutation fold: nothing dispatches — the run is
                # composed into the live perm; any cross-shard component
                # surfaces in final_remap like every deferred hop
                windows.append({"window": k, "start": int(i), "end": int(j),
                                "gates": j - i, "channels": 0,
                                "plan_windows": 0, "perm_windows": 0,
                                "mega_windows": 0, "mega_ops": 0,
                                "kind": "relabel", "sigma": None,
                                "exchanges": 0, "exchange_bytes": 0,
                                "chunks": None})
                continue
            parts, ngates, nchans, pparts, mparts, mops = _segment_stats(
                items[i:j], nloc=nloc, perm=_perm)
            plan_windows += parts
            perm_windows += pparts
            mega_windows += mparts
            entry = {"window": k, "start": int(i), "end": int(j),
                     "gates": ngates, "channels": nchans,
                     "plan_windows": parts, "perm_windows": pparts,
                     "mega_windows": mparts, "mega_ops": mops,
                     "kind": ("mega" if mparts
                              else "perm" if parts == 0 and pparts
                              else "dense"),
                     "sigma": None,
                     "exchanges": 0, "exchange_bytes": 0, "chunks": None}
            if sigma is not None:
                entry.update(_sigma_cost(sigma, n, nloc, nsh, itemsize))
                entry["exchanges"] *= bw
                entry["exchange_bytes"] *= bw
                for t in entry["tier_bytes"]:
                    entry["tier_bytes"][t] *= bw
                    entry["tier_exchanges"][t] *= bw
                    tot_tier[t] += entry["tier_bytes"][t]
                tot_exch += entry["exchanges"]
                tot_bytes += entry["exchange_bytes"]
            windows.append(entry)
        if final_perm is not None and list(final_perm) != list(range(n)):
            from .parallel import dist as PAR

            final_remap = _sigma_cost(
                PAR.canonical_sigma(tuple(final_perm)), n, nloc, nsh,
                itemsize)
            final_remap["exchanges"] *= bw
            final_remap["exchange_bytes"] *= bw
            for t in final_remap["tier_bytes"]:
                final_remap["tier_bytes"][t] *= bw
                final_remap["tier_exchanges"][t] *= bw
            final_remap["final_perm"] = [int(p) for p in final_perm]
    else:
        parts, ngates, nchans, pparts, mparts, mops = _segment_stats(
            items, nloc=nloc)
        plan_windows = parts
        perm_windows = pparts
        mega_windows = mparts
        if items:
            windows.append({"window": 0, "start": 0, "end": len(items),
                            "gates": ngates, "channels": nchans,
                            "plan_windows": parts, "perm_windows": pparts,
                            "mega_windows": mparts, "mega_ops": mops,
                            "kind": ("mega" if mparts
                                     else "perm" if parts == 0 and pparts
                                     else "dense"),
                            "sigma": None,
                            "exchanges": 0, "exchange_bytes": 0,
                            "chunks": None})

    key = F._plan_key(items, nloc, sweep_ok, perm0, nsh) if items else None
    cacheable = key is not None
    hit = cacheable and key in F._plan_cache
    from .parallel import dist as PAR

    plan = {
        "cacheable": cacheable,
        "cache": "hit" if hit else ("miss" if cacheable else "uncacheable"),
        # a plan-cache hit replays a program the compiled-executor
        # lru_cache has already traced (same skeleton + exchange key);
        # a miss may still reuse an executor if the skeleton coincides
        "retrace_expected": (None if not cacheable else not hit),
        "exchange_chunks_key": str(PAR.exchange_config_key() or "auto"),
    }

    # §31 AOT-tier prediction, computed on the SAME live plan key the
    # drain will use (fusion.aot_probe replans quietly and hashes the
    # full semantic identity): "memory" = an in-process executor is
    # live (no disk consult, no counter moves), "hit"/"miss" = what the
    # persistent tier will answer, "disabled"/"uncacheable" otherwise.
    # Pinned drift-0 against the post-run aot_cache_* counters.
    aot = F.aot_probe(qureg, orig_items)
    compile_section = {
        "aot": aot["status"],
        "aot_enabled": aot["enabled"],
        "aot_key": aot["key"],
        "plan_cache": plan["cache"],
    }

    read_exch = final_remap["exchanges"] if final_remap else 0
    read_bytes = final_remap["exchange_bytes"] if final_remap else 0
    # predicted per-device footprint of draining this stream — the
    # governor's analytic model (state x live-copy multiplier + pass
    # arrays, docs/design.md §22) over the EXACT program the drain
    # would dispatch, planned quietly (no telemetry, no cache insert;
    # plan_items_quiet re-applies the same optimizer rewrite, so the
    # ORIGINAL stream goes in and is optimized exactly once)
    from . import governor as _gov

    memory = _gov.explain_memory(qureg, orig_items)
    return ExplainReport(
        register=register,
        items=len(items),
        windows=windows,
        final_remap=final_remap,
        plan=plan,
        compile=compile_section,
        optimizer=optimizer_section,
        memory=memory,
        totals={
            "windows": len(windows),
            "plan_windows": int(plan_windows),
            "perm_windows": int(perm_windows),
            "mega_windows": int(mega_windows),
            "exchanges": int(tot_exch),
            "exchange_bytes": int(tot_bytes),
            "exchanges_with_read": int(tot_exch + read_exch),
            "exchange_bytes_with_read": int(tot_bytes + read_bytes),
            "tier_bytes": {t: int(b) for t, b in tot_tier.items()},
            "weighted_exchange_cost": float(sum(
                _topology.tier_weights()[t] * b
                for t, b in tot_tier.items())),
            "topology": _topology.resolve(1 << nsh).describe()
            if nsh else None,
        },
    )


def format_explain(report: dict) -> str:
    """Fixed-width text table for an :func:`explain_circuit` report —
    the ``report*`` print family's rendering."""
    reg = report["register"]
    head = (f"circuit plan: {reg['qubits']} qubits"
            f"{' (density)' if reg['density'] else ''}, "
            f"{reg['shards']} shard(s)")
    if reg["shard_bits"]:
        head += f" (nloc={reg['nloc']})"
    if reg.get("batch"):
        b = reg["batch"]
        head += (f", batch={b['size']} (bucket={b['bucket']} "
                 f"occupancy={b['occupancy']:.2f})")
    plan = report["plan"]
    head += (f", {report['items']} item(s), plan-cache={plan['cache']}, "
             f"chunks={plan['exchange_chunks_key']}")
    comp = report.get("compile")
    if comp and comp.get("aot") != "disabled":
        head += f", aot={comp['aot']}"
    lines = [head]
    opt = report.get("optimizer")
    if opt:
        rm = opt["removed"]
        oline = (f"optimizer: mode={opt['mode']} "
                 f"gates {opt['gates_in']}->{opt['gates_out']} "
                 f"(cancel={rm['cancel']} merge={rm['merge']} "
                 f"diag={rm['diag_coalesce']} "
                 f"perm={rm.get('perm_coalesce', 0)}"
                 + (" reordered" if opt["reordered"] else "") + ")")
        if opt["windows_before"] is not None:
            oline += f" windows {opt['windows_before']}->{opt['windows_after']}"
        ts = opt.get("tier_savings_bytes")
        if ts is not None:
            oline += (f" saves exch={opt['exchange_savings']} "
                      f"bytes ici={ts['ici']} dcn={ts['dcn']}")
        lines.append(oline)
    cols = f"{'window':>7} {'kind':>8} {'items':>6} {'gates':>6} " \
           f"{'chans':>6} {'exch':>5} {'bytes/shard':>12} {'chunks':>7}" \
           f"  sigma"
    lines.append(cols)

    def row(label, kind, items, gates, chans, entry):
        ch = entry.get("chunks")
        ch_s = f"{ch['half_shard']}/{ch['full_shard']}" if ch else "-"
        sig = entry.get("sigma")
        sig_s = "(" + ",".join(str(p) for p in sig) + ")" if sig else "-"
        lines.append(
            f"{label:>7} {kind:>8} {items:>6} {gates:>6} {chans:>6} "
            f"{entry['exchanges']:>5} {entry['exchange_bytes']:>12} "
            f"{ch_s:>7}  {sig_s}")

    for w in report["windows"]:
        row(str(w["window"]), w.get("kind", "dense"),
            w["end"] - w["start"], w["gates"], w["channels"], w)
    if report["final_remap"]:
        row("read", "-", "-", "-", "-", report["final_remap"])
    t = report["totals"]
    lines.append(
        f"totals: plan_windows={t['plan_windows']}"
        + (f" perm_windows={t['perm_windows']}"
           if t.get("perm_windows") else "")
        + (f" mega_windows={t['mega_windows']}"
           if t.get("mega_windows") else "")
        + f" exchanges={t['exchanges']} bytes={t['exchange_bytes']}"
        + (f" (+{t['exchanges_with_read'] - t['exchanges']} exch / "
           f"+{t['exchange_bytes_with_read'] - t['exchange_bytes']} bytes "
           f"at read)" if report["final_remap"] else ""))
    if t.get("topology"):
        tb = t["tier_bytes"]
        lines.append(
            f"topology: {t['topology']} tier bytes: ici={tb['ici']} "
            f"dcn={tb['dcn']} weighted_cost={t['weighted_exchange_cost']:.0f}")
    mem = report.get("memory")
    if mem:
        line = (f"memory: peak/device={mem['predicted_peak_bytes']} "
                f"(state={mem['state_bytes_per_device']} "
                f"x{mem['live_multiplier']:.2f} + "
                f"arrays={mem['pass_array_bytes']}), "
                f"resident_other={mem['other_resident_bytes']}")
        if mem["budget_bytes"] is not None:
            line += (f", budget={mem['budget_bytes']} "
                     f"policy={mem['policy']} "
                     f"fits={'yes' if mem['fits'] else 'NO'}")
        lines.append(line)
    return "\n".join(lines)


def report_circuit_plan(qureg, gates=None) -> None:
    """Print the plan-explainer table — the introspection member of the
    reference's ``report*`` family (reportQuregParams, reportPerf...)."""
    print(explain_circuit(qureg, gates).table())


# ---------------------------------------------------------------------------
# HLO audit
# ---------------------------------------------------------------------------

# loose word-regex over the whole HLO text: also matches metadata/comment
# mentions, so counts are upper bounds — useful for "is there ANY
# communication" / "none at all" audits
COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|collective-permute|all-gather|all-to-all|"
    r"reduce-scatter)\b")

# exact HLO opcodes (an instruction is "%name = TYPE opcode(args)")
COLLECTIVE_OPS = (
    "all-reduce", "all-reduce-start", "collective-permute",
    "collective-permute-start", "all-gather", "all-gather-start",
    "all-to-all", "reduce-scatter",
)

# one collective-permute instruction's routing table in optimized HLO:
# source_target_pairs={{0,1},{1,0},...}
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:[^{}]|\{[^{}]*\})*)\}")
_PAIR_RE = re.compile(r"\{\s*(\d+)\s*,\s*(\d+)\s*\}")


class CollectiveBudgetError(AssertionError):
    """An audited program exceeded its collective budget."""


class AuditReport:
    """Result of :func:`audit`: ``collectives`` (exact opcode histogram),
    ``matches`` (loose word-regex histogram, an upper bound including
    metadata mentions), ``flops`` / ``bytes_accessed`` / ``cost`` from
    ``Compiled.cost_analysis()``, and the optimized HLO ``text``."""

    __slots__ = ("collectives", "matches", "flops", "bytes_accessed",
                 "cost", "text")

    def __init__(self, collectives, matches, cost, text):
        self.collectives = collectives
        self.matches = matches
        self.cost = cost
        self.flops = cost.get("flops")
        self.bytes_accessed = cost.get("bytes accessed")
        self.text = text

    def count(self, family: str) -> int:
        """Exact occurrences of ``family`` summed with its async
        ``-start`` variant (all-reduce may lower to all-reduce-start +
        -done on some backends)."""
        return (self.collectives.get(family, 0)
                + self.collectives.get(family + "-start", 0))

    @property
    def total(self) -> int:
        return sum(self.collectives.values())

    def tier_counts(self, chips: int) -> dict:
        """Per-interconnect-tier histogram of the compiled program's
        collective-permute instructions under an ``hosts x chips``
        arrangement (parallel/topology.py): an instruction whose routing
        table contains ANY pair crossing a host boundary
        (``src ^ dst >= chips``) counts as "dcn", else "ici" — the
        emulated-topology placement pin hlocheck's per-tier verification
        and tests/test_topology.py assert against real HLO."""
        from .parallel import topology as _topo

        out = {"ici": 0, "dcn": 0}
        for m in _PAIRS_RE.finditer(self.text):
            pairs = [(int(a), int(b))
                     for a, b in _PAIR_RE.findall(m.group(1))]
            split = _topo.split_pair_list(pairs, chips)
            if split["ici"] or split["dcn"]:
                out["dcn" if split["dcn"] else "ici"] += 1
        return out

    def as_dict(self) -> dict:
        return {"collectives": dict(self.collectives),
                "matches": dict(self.matches),
                "flops": self.flops, "bytes_accessed": self.bytes_accessed}

    def __repr__(self) -> str:
        return (f"AuditReport(collectives={self.collectives}, "
                f"flops={self.flops}, bytes_accessed={self.bytes_accessed})")


def _cost_analysis(compiled) -> dict:
    """Normalize Compiled.cost_analysis() across JAX versions (dict, or
    a one-element list of dicts, or unavailable on some backends)."""
    try:
        cost = compiled.cost_analysis()
    # qlint: allow(broad-except): cost_analysis availability and failure types vary per backend/JAX version; the audit degrades to an empty cost dict
    except Exception:  # pragma: no cover - backend-dependent API
        return {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost) if cost else {}


def audit(fn, *args, donate: bool = False) -> AuditReport:
    """Compile ``fn(*args)`` and audit the optimized HLO: the exact
    collective-opcode histogram, the loose word-match histogram, and
    cost_analysis flops/bytes.  Every ambient :class:`CollectiveBudget`
    (entered as a context manager) checks the report before it is
    returned.  Compilation only — the program never executes."""
    import jax

    jfn = jax.jit(fn, donate_argnums=(0,) if donate else ())
    compiled = jfn.lower(*args).compile()
    txt = compiled.as_text()
    collectives: dict = {}
    for op in COLLECTIVE_OPS:
        c = txt.count(f" {op}(")
        if c:
            collectives[op] = c
    matches: dict = {}
    for m in COLLECTIVE_RE.finditer(txt):
        matches[m.group(1)] = matches.get(m.group(1), 0) + 1
    report = AuditReport(collectives, matches, _cost_analysis(compiled), txt)
    for budget in _BUDGET_STACK:
        budget.check(report)
    return report


_BUDGET_STACK: list = []


class CollectiveBudget:
    """Collective-count budget for audited programs.

    ``CollectiveBudget(collective_permute=2)`` caps the exact
    collective-permute count (including the ``-start`` variant) at 2;
    ``exact={"collective-permute": 1}`` pins the whole exact histogram;
    ``total=N`` caps the sum of all collectives; ``allow=(...)`` rejects
    any opcode family outside the set.  ``check(report)`` raises
    :class:`CollectiveBudgetError` on violation.  As a context manager
    the budget becomes ambient: every :func:`audit` inside is checked
    automatically::

        with CollectiveBudget(collective_permute=1):
            introspect.audit(my_sharded_gate, amps, donate=True)
    """

    def __init__(self, exact: Optional[dict] = None,
                 total: Optional[int] = None,
                 allow: Optional[Sequence[str]] = None, **max_ops):
        self.exact = dict(exact) if exact is not None else None
        self.total = total
        self.allow = tuple(allow) if allow is not None else None
        # keyword budgets name op families with underscores
        self.max_ops = {k.replace("_", "-"): int(v)
                        for k, v in max_ops.items()}

    def check(self, report) -> "AuditReport":
        hist = (report.collectives if isinstance(report, AuditReport)
                else dict(report))
        if not isinstance(report, AuditReport):
            report = None

        def fam_count(family):
            return hist.get(family, 0) + hist.get(family + "-start", 0)

        if self.exact is not None and hist != self.exact:
            raise CollectiveBudgetError(
                f"collective budget: expected exactly {self.exact}, "
                f"compiled program has {hist}")
        for family, cap in self.max_ops.items():
            got = fam_count(family)
            if got > cap:
                raise CollectiveBudgetError(
                    f"collective budget: {family} x{got} exceeds the "
                    f"budget of {cap} ({hist})")
        if self.total is not None and sum(hist.values()) > self.total:
            raise CollectiveBudgetError(
                f"collective budget: {sum(hist.values())} collectives "
                f"exceed the total budget of {self.total} ({hist})")
        if self.allow is not None:
            allowed = set(self.allow) | {a + "-start" for a in self.allow}
            extra = set(hist) - allowed
            if extra:
                raise CollectiveBudgetError(
                    f"collective budget: {sorted(extra)} outside the "
                    f"allowed families {sorted(self.allow)} ({hist})")
        return report

    def __enter__(self) -> "CollectiveBudget":
        _BUDGET_STACK.append(self)
        return self

    def __exit__(self, *exc) -> None:
        _BUDGET_STACK.remove(self)


# ---------------------------------------------------------------------------
# Predicted-vs-measured reconciliation
# ---------------------------------------------------------------------------

# active prediction perturbations (perturb_prediction context manager);
# the QT_INTROSPECT_PERTURB env var ("chunks=4" / "scale=2") is folded in
# at reconcile time so operators can arm the drift alarm without code
_PERTURB_STACK: list = []


@contextlib.contextmanager
def perturb_prediction(count: Optional[int] = None,
                       nbytes: Optional[int] = None,
                       chunks: Optional[str] = None,
                       scale: Optional[float] = None) -> Iterator[None]:
    """Inject a planner-policy perturbation into the reconciliation
    prediction — the fault-injection hook proving the predict->measure->
    reconcile loop actually detects drift (resilience.FaultPlan's
    philosophy applied to the cost model).  ``chunks`` forces the
    predicted chunk-config key; ``scale`` multiplies the predicted
    exchange count and bytes; ``count``/``nbytes`` force them
    outright."""
    entry = {"count": count, "nbytes": nbytes, "chunks": chunks,
             "scale": scale}
    _PERTURB_STACK.append(entry)
    try:
        yield
    finally:
        _PERTURB_STACK.remove(entry)


def _env_perturbation() -> Optional[dict]:
    raw = os.environ.get(_PERTURB_ENV, "").strip()
    if not raw:
        return None
    out = {"count": None, "nbytes": None, "chunks": None, "scale": None}
    for part in raw.split(","):
        k, _, v = part.partition("=")
        k = k.strip()
        if k == "chunks":
            out["chunks"] = v.strip()
        elif k == "scale":
            out["scale"] = float(v)
        elif k in ("count", "nbytes"):
            out[k] = int(v)
    return out


def _apply_perturbations(pred: dict) -> dict:
    stack = list(_PERTURB_STACK)
    env = _env_perturbation()
    if env:
        stack.append(env)
    for p in stack:
        if p["scale"] is not None:
            pred["count"] = int(pred["count"] * p["scale"])
            pred["nbytes"] = int(pred["nbytes"] * p["scale"])
        if p["count"] is not None:
            pred["count"] = int(p["count"])
        if p["nbytes"] is not None:
            pred["nbytes"] = int(p["nbytes"])
        if p["chunks"] is not None:
            pred["chunks"] = str(p["chunks"])
    return pred


@functools.lru_cache(maxsize=256)
def _predict_cached(bit_key, n: int, nloc: int, nsh: int, perm_key,
                    itemsize: int, topo_sig):
    # Pure function of the plan inputs, memoized so the per-drain
    # reconciliation stays O(1) on repeated streams — the measured path
    # it is compared against hits the plan cache the same way.
    # ``topo_sig`` (topology.signature) keys the memo on the live
    # QT_TOPOLOGY / planner-mode arrangement: the tier-aware planner
    # emits different sigmas per arrangement, so a stale entry would
    # mispredict across an env flip.
    from .parallel import dist as PAR
    from .parallel import topology as _topo

    count = 0
    nbytes = 0
    tiers = {"ici": 0, "dcn": 0}
    topology = _topo.resolve(1 << nsh)
    segments, _final_perm = C.plan_remap_windows(
        [list(b) for b in bit_key], n, nloc,
        list(perm_key) if perm_key is not None else None)
    for _ij, sigma, _perm in segments:
        if sigma is None:
            continue
        count += PAR.remap_exchange_count(tuple(sigma), nloc, nsh)
        nbytes += C.remap_exchange_bytes(tuple(sigma), n, nloc, itemsize)
        for t, (_c, b) in PAR.remap_exchange_tiers(
                tuple(sigma), nloc, nsh, itemsize, topology).items():
            tiers[t] += b
    return count, nbytes, (tiers["ici"], tiers["dcn"])


def predict_window_exchanges(bit_sets: Sequence, n: int, nloc: int,
                             nsh: int, perm0, itemsize: int,
                             batch: int = 0) -> dict:
    """Independent re-derivation of what a sharded drain over
    ``bit_sets`` must exchange (``op=window_remap`` only — the
    canonical-read rematerialization is the separate ``op=remap``):
    re-plan the windows and fold every sigma through the cost model,
    including the per-interconnect-tier byte split under the live
    topology.  This is the prediction reconcile_drain holds the
    measured counters against."""
    from .parallel import dist as PAR
    from .parallel import topology as _topo

    bw = max(int(batch), 1)
    count, nbytes, (ici_b, dcn_b) = _predict_cached(
        tuple(tuple(b) for b in bit_sets), n, nloc, nsh,
        tuple(perm0) if perm0 is not None else None, itemsize,
        _topo.signature(1 << nsh))
    return {"count": count * bw, "nbytes": nbytes * bw,
            "tier_nbytes": {"ici": ici_b * bw, "dcn": dcn_b * bw},
            "chunks": str(PAR.exchange_config_key() or "auto")}


def reconcile_drain(*, bit_sets: Sequence, n: int, nloc: int, nsh: int,
                    perm0, itemsize: int, batch: int,
                    measured_count: float, measured_bytes: float,
                    measured_chunks: str,
                    measured_tier_bytes: Optional[dict] = None
                    ) -> Optional[dict]:
    """Compare a drain's measured window-remap telemetry deltas against
    the independent plan prediction.  Records the prediction into
    ``predicted_exchanges_total`` / ``predicted_exchange_bytes_total``
    (reportPerf's predicted-vs-measured section; bytes carry the
    per-interconnect ``tier`` label so the per-tier series reconcile
    too); any deviation increments ``model_drift_total{kind}`` per
    drifting dimension (count / bytes / chunks / tier_bytes) and emits
    ONE structured JSON log line.  Returns the drift dict (empty when
    the model holds)."""
    if not _telemetry.enabled():
        return None
    pred = predict_window_exchanges(bit_sets, n, nloc, nsh, perm0,
                                    itemsize, batch)
    pred = _apply_perturbations(pred)
    if pred["count"]:
        _telemetry.inc("predicted_exchanges_total", pred["count"],
                       op="window_remap")
    for tier, b in pred["tier_nbytes"].items():
        if b:
            _telemetry.inc("predicted_exchange_bytes_total", b,
                           op="window_remap", tier=tier)
    drift: dict = {}
    if int(measured_count) != int(pred["count"]):
        drift["count"] = {"predicted": int(pred["count"]),
                          "measured": int(measured_count)}
    if int(measured_bytes) != int(pred["nbytes"]):
        drift["bytes"] = {"predicted": int(pred["nbytes"]),
                          "measured": int(measured_bytes)}
    if measured_tier_bytes is not None:
        for tier, b in pred["tier_nbytes"].items():
            if int(measured_tier_bytes.get(tier, 0)) != int(b):
                drift.setdefault("tier_bytes", {})[tier] = {
                    "predicted": int(b),
                    "measured": int(measured_tier_bytes.get(tier, 0))}
    if (pred["count"] or measured_count) and \
            str(measured_chunks) != str(pred["chunks"]):
        drift["chunks"] = {"predicted": str(pred["chunks"]),
                           "measured": str(measured_chunks)}
    if drift:
        for kind in drift:
            _telemetry.inc("model_drift_total", kind=kind)
        _telemetry.flight_event("model_drift",
                                kinds=",".join(sorted(drift)),
                                shards=1 << nsh, items=len(bit_sets))
        _LOG.warning(json.dumps(
            {"event": "model_drift", "kinds": sorted(drift),
             "drift": drift, "shards": 1 << nsh, "items": len(bit_sets)},
            sort_keys=True))
    return drift


def measure_dispatch_floor(calls: int = 64) -> float:
    """Median host cost of dispatching ONE trivial jitted program — the
    live, in-process version of scripts/bench_dispatch.py's per-program
    overhead probe.  Publishes the ``per_program_dispatch_seconds``
    gauge; the §30 per-op attribution section of ``reportPerf`` labels a
    route ``dispatch_bound`` when its mean dispatched-group wall time
    sits within 10% of this floor (the r04->r05 measurement regime,
    flagged live instead of by forensic bisection)."""
    import time

    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1.0)
    x = jnp.zeros(())
    f(x).block_until_ready()  # compile outside the timed loop
    samples = []
    for _ in range(max(8, int(calls))):
        t0 = time.perf_counter()
        f(x).block_until_ready()
        samples.append(time.perf_counter() - t0)
    samples.sort()
    floor = samples[len(samples) // 2]
    _telemetry.set_gauge("per_program_dispatch_seconds", floor)
    return floor


# camelCase mirrors (the reference-style API surface)
explainCircuit = explain_circuit
reportCircuitPlan = report_circuit_plan
