"""Public API, part 2: measurements, decoherence channels, calculations,
composite operators (apply*), and QASM recording control.

Continues quest_tpu.api (same dispatch conventions; see that module's
docstring).  Reference parity: QuEST.c:985-1602 + QuEST_common.c composites.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from . import telemetry as _telemetry
from . import validation as V
from .ops import calculations as C
from .ops import density as D
from .ops import kernels as K
from .ops import paulis as P
from .ops import phasefunc as PF
from .precision import get_precision, real_eps
from .qureg import DiagonalOp, PauliHamil, Qureg
from .rng import GLOBAL_RNG
# qlint: allow(layer-violation): api_ops.py is api.py's size-split continuation (one API surface split across two files, see module docstring), not a second API composing the first; it shares api.py's private helpers by design
from .api import (
    PAULI_I,
    _apply_diag,
    _apply_unitary,
    _shift,
    _sv_n,
    hadamard,
    swapGate,
)



def _quad() -> bool:
    """prec-4: route reductions through double-double accumulation."""
    return get_precision() == 4

# ---------------------------------------------------------------------------
# Measurement (QuEST.c:985-995, QuEST_common.c:168-183,374-380)
# ---------------------------------------------------------------------------


def calcProbOfOutcome(qureg: Qureg, measureQubit: int, outcome: int) -> float:
    """Probability of measuring the given outcome of one qubit (QuEST.h:3047)."""
    V.validate_target(qureg, measureQubit, "calcProbOfOutcome")
    V.validate_outcome(outcome, "calcProbOfOutcome")
    quad = _quad()
    if qureg.is_density_matrix:
        p = C.calc_prob_of_outcome_density(
            qureg.amps, num_qubits=qureg.num_qubits_represented,
            target=measureQubit, outcome=outcome, quad=quad)
    else:
        p = C.calc_prob_of_outcome_statevec(
            qureg.amps, num_qubits=_sv_n(qureg), target=measureQubit,
            outcome=outcome, quad=quad)
    return float(p)


def calcProbOfAllOutcomes(qureg: Qureg, qubits: Sequence[int]) -> np.ndarray:
    """Probabilities of every outcome of a sub-register measurement (QuEST.h:3136)."""
    qubits = [int(q) for q in qubits]
    V.validate_multi_targets(qureg, qubits, "calcProbOfAllOutcomes")
    if qureg.is_density_matrix:
        p = C.calc_prob_of_all_outcomes_density(
            qureg.amps, num_qubits=qureg.num_qubits_represented, qubits=tuple(qubits)
        )
    else:
        p = C.calc_prob_of_all_outcomes_statevec(
            qureg.amps, num_qubits=_sv_n(qureg), qubits=tuple(qubits)
        )
    return np.asarray(p)


def _generate_measurement_outcome(zero_prob: float):
    """(generateMeasurementOutcome, QuEST_common.c:168-183): degenerate
    probabilities short-circuit; otherwise draw from the global MT RNG."""
    if zero_prob < real_eps():
        return 1
    if 1 - zero_prob < real_eps():
        return 0
    return 0 if GLOBAL_RNG.uniform() <= zero_prob else 1


def _collapse(qureg: Qureg, qubit: int, outcome: int, prob: float) -> None:
    if qureg.is_density_matrix:
        qureg.amps = K.collapse_density(
            qureg.amps, float(prob), num_qubits=qureg.num_qubits_represented,
            target=qubit, outcome=outcome,
        )
    else:
        qureg.amps = K.collapse_statevec(
            qureg.amps, float(prob), num_qubits=_sv_n(qureg),
            target=qubit, outcome=outcome,
        )


def collapseToOutcome(qureg: Qureg, measureQubit: int, outcome: int) -> float:
    """Project one qubit to a known outcome and renormalise (QuEST.h:3170)."""
    V.validate_target(qureg, measureQubit, "collapseToOutcome")
    V.validate_outcome(outcome, "collapseToOutcome")
    prob = calcProbOfOutcome(qureg, measureQubit, outcome)
    if prob < real_eps():
        raise V.QuESTError(
            "collapseToOutcome: Can't collapse to state with zero probability."
        )
    _collapse(qureg, measureQubit, outcome, prob)
    qureg.qasm_log.comment(f"collapseToOutcome({outcome}) on qubit {measureQubit}")
    return prob


def measure(qureg: Qureg, measureQubit: int) -> int:
    """Measure one qubit, collapsing the state (QuEST.h:3194)."""
    outcome, _ = measureWithStats(qureg, measureQubit)
    return outcome


def measureWithStats(qureg: Qureg, measureQubit: int):
    """Measure one qubit, also returning the outcome probability
    (QuEST.h:3219).  Default: ONE fused device program per shot — prob
    reduce, on-device threshold draw from the seeded key, conditional
    collapse (ops/measurement.py).  QT_HOST_MEASURE=1 (or strict parity
    mode) restores the reference's host-MT sampling stream
    (calcProb -> generateMeasurementOutcome -> collapse)."""
    if getattr(qureg, "batch_size", 0):
        raise V.QuESTError(
            "measureWithStats: the register is a BatchedQureg bank — "
            "use quest_tpu.batch.measureBatched, which draws from the "
            "per-element key streams")
    V.validate_target(qureg, measureQubit, "measureWithStats")
    _telemetry.inc("measurement_shots_total")
    from .ops import measurement as M
    if M.host_path_enabled():
        zero_prob = calcProbOfOutcome(qureg, measureQubit, 0)
        outcome = _generate_measurement_outcome(zero_prob)
        prob = zero_prob if outcome == 0 else 1 - zero_prob
        _collapse(qureg, measureQubit, outcome, prob)
        qureg.qasm_log.measure(measureQubit)
        return outcome, prob
    key, shot = M.KEYS.next_shots()
    amps, outcome, prob = M.measure_fused(
        qureg.amps, key, shot, num_qubits=qureg.num_qubits_represented,
        target=measureQubit, is_density=qureg.is_density_matrix,
        quad=_quad())
    qureg.amps = amps
    qureg.qasm_log.measure(measureQubit)
    return int(outcome), float(prob)


def measureSequence(qureg: Qureg, qubits: Sequence[int]):
    """EXTENSION (no reference analogue — its measure is irreducibly one
    host round-trip per qubit): measure a sequence of qubits in ONE
    compiled device program, each step collapsing before the next
    qubit's probability is computed, exactly as a loop of measure()
    calls — same seeded outcome stream, one dispatch total (on-chip at
    26q: 8 ms/shot vs the host loop's 510 ms/shot).  Returns
    (outcomes list, probabilities list).  Respects QT_HOST_MEASURE=1 by
    falling back to a loop of host-path measureWithStats."""
    from .ops import measurement as M

    if getattr(qureg, "batch_size", 0):
        raise V.QuESTError(
            "measureSequence: the register is a BatchedQureg bank — "
            "use quest_tpu.batch.measureBatched, which draws from the "
            "per-element key streams")
    qubits = [int(q) for q in qubits]
    for q in qubits:
        V.validate_target(qureg, q, "measureSequence")
    if not qubits:
        return [], []
    if M.host_path_enabled():
        outs, probs = [], []
        for q in qubits:
            o, p = measureWithStats(qureg, q)
            outs.append(o)
            probs.append(p)
        return outs, probs
    # (the host path above counts per measureWithStats call)
    _telemetry.inc("measurement_shots_total", len(qubits))
    key, shot = M.KEYS.next_shots(len(qubits))
    amps, outs, probs = M.measure_sequence(
        qureg.amps, key, shot, num_qubits=qureg.num_qubits_represented,
        targets=tuple(qubits), is_density=qureg.is_density_matrix,
        quad=_quad())
    qureg.amps = amps
    for q in qubits:
        qureg.qasm_log.measure(q)
    return [int(o) for o in np.asarray(outs)], [float(p)
                                                for p in np.asarray(probs)]


# ---------------------------------------------------------------------------
# Decoherence (QuEST.c:1259-1331; channels in ops.density)
# ---------------------------------------------------------------------------


def mixDephasing(qureg: Qureg, targetQubit: int, prob: float) -> None:
    """One-qubit dephasing channel (QuEST.h:3421)."""
    V.validate_density_matrix(qureg, "mixDephasing")
    V.validate_target(qureg, targetQubit, "mixDephasing")
    V.validate_one_qubit_dephase_prob(prob, "mixDephasing")
    from .ops import gatedefs as G
    if _capture_channel(
            qureg,
            [math.sqrt(1 - prob) * G.PAULI_I, math.sqrt(prob) * G.PAULI_Z],
            (targetQubit,)):
        return
    qureg.amps = D.mix_dephasing(
        qureg.amps, prob, num_qubits=qureg.num_qubits_represented, target=targetQubit
    )


def mixTwoQubitDephasing(qureg: Qureg, qubit1: int, qubit2: int, prob: float) -> None:
    """Two-qubit dephasing channel (QuEST.h:3453)."""
    V.validate_density_matrix(qureg, "mixTwoQubitDephasing")
    V.validate_unique_targets(qureg, qubit1, qubit2, "mixTwoQubitDephasing")
    V.validate_two_qubit_dephase_prob(prob, "mixTwoQubitDephasing")
    from .ops import gatedefs as G
    i2, z = np.asarray(G.PAULI_I), np.asarray(G.PAULI_Z)
    # Kraus order (q2 (x) q1): matrix bit 0 = qubit1
    ops = [math.sqrt(1 - prob) * np.kron(i2, i2),
           math.sqrt(prob / 3) * np.kron(i2, z),
           math.sqrt(prob / 3) * np.kron(z, i2),
           math.sqrt(prob / 3) * np.kron(z, z)]
    if _capture_channel(qureg, ops, (qubit1, qubit2)):
        return
    qureg.amps = D.mix_two_qubit_dephasing(
        qureg.amps, prob, num_qubits=qureg.num_qubits_represented,
        qubit1=qubit1, qubit2=qubit2,
    )


def _mix_kraus(qureg: Qureg, ops, targets) -> None:
    """Apply a Kraus channel: under gateFusion the superoperator is
    CAPTURED into the drain as a dense gate on (T, T+n) — noise channels
    then fold into the same window passes as gates (one compiled program
    for a whole noise layer); on a sharded register with sharded bra
    bits the superoperator routes through the dense-gate dispatcher
    (SWAP-relocalization, 2 ppermutes per sharded bit — the reference's
    distributed multiQubitUnitary strategy the Kraus fold rides,
    QuEST_common.c:630-652 + QuEST_cpu_distributed.c:1503-1545);
    otherwise the generic superoperator kernel runs eagerly."""
    if _capture_channel(qureg, ops, targets):
        return
    if _explicit_sharded(qureg):
        from .api import _dispatch_matrix
        from .ops import cplx as CX
        from .parallel import dist as PAR

        nq = qureg.num_qubits_represented
        nloc = 2 * nq - PAR.num_shard_bits(qureg.env.mesh)
        sv_targets = D.kraus_targets(tuple(targets), nq)
        # locality is judged at the PHYSICAL positions of the live
        # permutation — _dispatch_matrix relocalizes lazily from there
        if any(t >= nloc for t in qureg._phys_bits(sv_targets)):
            sup = D.superoperator_from_kraus(ops)
            dt = (np.float64 if np.dtype(qureg.dtype) == np.float64
                  else np.float32)
            _dispatch_matrix(
                qureg, CX.soa(sup).astype(dt), tuple(sv_targets), (), ())
            return
    qureg.amps = D.apply_kraus_map(
        qureg.amps, ops, num_qubits=qureg.num_qubits_represented, targets=tuple(targets)
    )


def _capture_channel(qureg: Qureg, ops, targets) -> bool:
    from . import fusion
    from .ops import cplx as CX

    if getattr(qureg, "_fusion", None) is None:
        return False
    sup = D.superoperator_from_kraus(ops)
    sv_targets = D.kraus_targets(tuple(targets), qureg.num_qubits_represented)
    dt = np.float64 if qureg.amps.dtype == jnp.float64 else np.float32
    return fusion.capture_raw(qureg, CX.soa(sup).astype(dt), sv_targets)


def _pair_channel_sharded(qureg: Qureg, prob: float, target: int,
                          kind: str) -> bool:
    """Explicit ppermute path for depolarise/damping when the bra target
    bit is a mesh-coordinate bit (dist.mix_pair_channel_sharded)."""
    from .parallel import dist as PAR

    env = qureg.env
    if not PAR.explicit_dist_enabled() or not _spans_mesh(qureg):
        return False
    nq = qureg.num_qubits_represented
    nloc = 2 * nq - PAR.num_shard_bits(env.mesh)
    if target + nq < nloc:
        return False
    qureg.amps = PAR.mix_pair_channel_sharded(
        qureg.amps, prob, mesh=env.mesh, num_qubits=nq, target=target,
        kind=kind)
    return True


def mixDepolarising(qureg: Qureg, targetQubit: int, prob: float) -> None:
    """One-qubit depolarising channel (QuEST.h:3496).  Routed, in order:
    fusion capture (superoperator folds into the drain's window passes) ->
    explicit ppermute pair-exchange for sharded bra bits -> the dedicated
    elementwise pair-average kernel (ref QuEST_cpu.c:125-246), never the
    16x generic superoperator."""
    V.validate_density_matrix(qureg, "mixDepolarising")
    V.validate_target(qureg, targetQubit, "mixDepolarising")
    V.validate_one_qubit_depol_prob(prob, "mixDepolarising")
    # Under gateFusion the channel is captured as a ChannelItem — the
    # SAME one-pass elementwise kernel, run inside the drain program in
    # call order (never the rank-4 superoperator fold, which measured
    # slower) — so a whole noise layer costs one dispatch.  Outside
    # fusion this drains (no-op) and runs eagerly.
    from . import fusion
    if fusion.capture_pair_channel(qureg, "depol", targetQubit, prob):
        return
    if _pair_channel_sharded(qureg, prob, targetQubit, "depol"):
        return
    qureg.amps = D.mix_depolarising(
        qureg.amps, prob, num_qubits=qureg.num_qubits_represented,
        target=targetQubit)


def mixDamping(qureg: Qureg, targetQubit: int, prob: float) -> None:
    """One-qubit amplitude damping channel (QuEST.h:3534).  Same routing
    as mixDepolarising (ref elementwise form QuEST_cpu.c:300-385)."""
    V.validate_density_matrix(qureg, "mixDamping")
    V.validate_target(qureg, targetQubit, "mixDamping")
    V.validate_one_qubit_damping_prob(prob, "mixDamping")
    # captured as a ChannelItem under gateFusion — see mixDepolarising
    from . import fusion
    if fusion.capture_pair_channel(qureg, "damping", targetQubit, prob):
        return
    if _pair_channel_sharded(qureg, prob, targetQubit, "damping"):
        return
    qureg.amps = D.mix_damping(
        qureg.amps, prob, num_qubits=qureg.num_qubits_represented,
        target=targetQubit)


def mixTwoQubitDepolarising(qureg: Qureg, qubit1: int, qubit2: int, prob: float) -> None:
    """Two-qubit depolarising channel (QuEST.h:3601).  Routed, in order:
    fusion capture (superoperator folds into the drain) -> explicit
    <=2-ppermute double-flip orbit kernel for sharded bra bits
    (dist.mix_two_qubit_depol_sharded, the reference's dedicated
    distributed algorithm QuEST_cpu_distributed.c:553-852) -> the
    dedicated elementwise orbit kernel (never the 256x generic
    superoperator, ref QuEST_cpu.c:387-733)."""
    V.validate_density_matrix(qureg, "mixTwoQubitDepolarising")
    V.validate_unique_targets(qureg, qubit1, qubit2, "mixTwoQubitDepolarising")
    V.validate_two_qubit_depol_prob(prob, "mixTwoQubitDepolarising")
    if _capture_channel(
            qureg, D.two_qubit_depolarising_kraus(prob, qureg.dtype),
            (qubit1, qubit2)):
        return
    if _explicit_sharded(qureg):
        from .parallel import dist as PAR

        nq = qureg.num_qubits_represented
        nloc = 2 * nq - PAR.num_shard_bits(qureg.env.mesh)
        if max(qubit1, qubit2) + nq >= nloc:
            qureg.amps = PAR.mix_two_qubit_depol_sharded(
                qureg.amps, prob, mesh=qureg.env.mesh, num_qubits=nq,
                qubit1=qubit1, qubit2=qubit2)
            return
    qureg.amps = D.mix_two_qubit_depolarising(
        qureg.amps, prob, num_qubits=qureg.num_qubits_represented,
        qubit1=qubit1, qubit2=qubit2)


def mixPauli(qureg: Qureg, targetQubit: int, probX: float, probY: float, probZ: float) -> None:
    """One-qubit Pauli channel with probabilities (pX, pY, pZ) (QuEST.h:3642)."""
    V.validate_density_matrix(qureg, "mixPauli")
    V.validate_target(qureg, targetQubit, "mixPauli")
    V.validate_one_qubit_pauli_probs(probX, probY, probZ, "mixPauli")
    _mix_kraus(qureg, D.pauli_kraus(probX, probY, probZ, qureg.dtype), (targetQubit,))


def mixDensityMatrix(combineQureg: Qureg, prob: float, otherQureg: Qureg) -> None:
    """Mix another density matrix in: rho = (1-p) rho + p other (QuEST.h:3664)."""
    V.validate_density_matrix(combineQureg, "mixDensityMatrix")
    V.validate_density_matrix(otherQureg, "mixDensityMatrix")
    V.validate_matching_qureg_dims(combineQureg, otherQureg, "mixDensityMatrix")
    V.validate_prob(prob, "mixDensityMatrix")
    combineQureg.amps = D.mix_density_matrix(combineQureg.amps, otherQureg.amps, prob)


def mixKrausMap(qureg: Qureg, target: int, ops, numOps: Optional[int] = None) -> None:
    """Apply a one-qubit CPTP Kraus map (QuEST.h:4789)."""
    ops = list(ops)[: int(numOps)] if numOps is not None else list(ops)
    V.validate_density_matrix(qureg, "mixKrausMap")
    V.validate_target(qureg, target, "mixKrausMap")
    V.validate_kraus_ops(ops, 1, "mixKrausMap")
    _mix_kraus(qureg, [np.asarray(o, complex) for o in ops], (target,))


def mixTwoQubitKrausMap(qureg: Qureg, target1: int, target2: int, ops, numOps: Optional[int] = None) -> None:
    """Apply a two-qubit CPTP Kraus map (QuEST.h:4828)."""
    ops = list(ops)[: int(numOps)] if numOps is not None else list(ops)
    V.validate_density_matrix(qureg, "mixTwoQubitKrausMap")
    V.validate_unique_targets(qureg, target1, target2, "mixTwoQubitKrausMap")
    V.validate_kraus_ops(ops, 2, "mixTwoQubitKrausMap")
    _mix_kraus(qureg, [np.asarray(o, complex) for o in ops], (target1, target2))


def mixMultiQubitKrausMap(qureg: Qureg, targets: Sequence[int], ops, numOps: Optional[int] = None) -> None:
    """Apply an N-qubit CPTP Kraus map (QuEST.h:4878)."""
    ops = list(ops)[: int(numOps)] if numOps is not None else list(ops)
    targets = [int(t) for t in targets]
    V.validate_density_matrix(qureg, "mixMultiQubitKrausMap")
    V.validate_multi_targets(qureg, targets, "mixMultiQubitKrausMap")
    V.validate_multi_qubit_matrix_fits_in_node(qureg, 2 * len(targets), "mixMultiQubitKrausMap")
    V.validate_kraus_ops(ops, len(targets), "mixMultiQubitKrausMap")
    _mix_kraus(qureg, [np.asarray(o, complex) for o in ops], tuple(targets))


# ---------------------------------------------------------------------------
# Calculations (QuEST.h:1987-2099, 3246-3724, 4189-4285, 4911)
# ---------------------------------------------------------------------------


def getAmp(qureg: Qureg, index: int) -> complex:
    """Fetch one complex amplitude (QuEST.h:1987).  Routed through the
    layout-safe dynamic-slice kernel (ops/element.py): O(1 tile) on a
    canonically-held big state, never a full-state relayout — matching
    the reference's O(1) chunk read (QuEST_cpu_local.c:225-233)."""
    from .ops import element as E

    V.validate_state_vector(qureg, "getAmp")
    V.validate_num_amps(qureg, index, 1, "getAmp")
    pair = np.asarray(E.get_amp_pair(qureg.amps, int(index)))
    return complex(pair[0], pair[1])


def getRealAmp(qureg: Qureg, index: int) -> float:
    """Fetch the real part of one amplitude (QuEST.h:2008)."""
    return getAmp(qureg, index).real


def getImagAmp(qureg: Qureg, index: int) -> float:
    """Fetch the imaginary part of one amplitude (QuEST.h:2029)."""
    return getAmp(qureg, index).imag


def getProbAmp(qureg: Qureg, index: int) -> float:
    """Fetch |amp|^2 of one amplitude (QuEST.h:2050)."""
    a = getAmp(qureg, index)
    return a.real * a.real + a.imag * a.imag


def getDensityAmp(qureg: Qureg, row: int, col: int) -> complex:
    """Fetch one density-matrix element rho[row, col] (QuEST.h:2072) —
    same layout-safe slice kernel as getAmp."""
    from .ops import element as E

    V.validate_density_matrix(qureg, "getDensityAmp")
    dim = 1 << qureg.num_qubits_represented
    if not (0 <= row < dim and 0 <= col < dim):
        raise V.QuESTError("getDensityAmp: Invalid amplitude index.")
    pair = np.asarray(E.get_amp_pair(qureg.amps, int(row + col * dim)))
    return complex(pair[0], pair[1])


def calcTotalProb(qureg: Qureg) -> float:
    """Total probability (trace / norm^2) of the register, Kahan-summed
    (QuEST.h:2099).  Quad precision (set_precision(4)) accumulates in
    double-double (C.quad_sum — the QuEST_PREC=4 scope decision,
    precision.set_precision docstring)."""
    if qureg.is_density_matrix:
        if _quad():
            return float(C.calc_total_prob_density_quad(
                qureg.amps, num_qubits=qureg.num_qubits_represented))
        return float(
            C.calc_total_prob_density(qureg.amps, num_qubits=qureg.num_qubits_represented)
        )
    if _quad():
        return float(C.calc_total_prob_statevec_quad(qureg.amps))
    return float(C.calc_total_prob_statevec(qureg.amps))


def calcInnerProduct(bra: Qureg, ket: Qureg) -> complex:
    """Complex inner product <bra|ket> of two state-vectors (QuEST.h:3246)."""
    V.validate_state_vector(bra, "calcInnerProduct")
    V.validate_state_vector(ket, "calcInnerProduct")
    V.validate_matching_qureg_dims(bra, ket, "calcInnerProduct")
    if _quad():
        r = np.asarray(C.calc_inner_product_quad(bra.amps, ket.amps))
    else:
        r = np.asarray(C.calc_inner_product(bra.amps, ket.amps))
    return complex(r[0], r[1])


def calcDensityInnerProduct(rho1: Qureg, rho2: Qureg) -> float:
    """Hilbert-Schmidt inner product Tr(rho1^dag rho2) of two density matrices (QuEST.h:3299)."""
    V.validate_density_matrix(rho1, "calcDensityInnerProduct")
    V.validate_density_matrix(rho2, "calcDensityInnerProduct")
    V.validate_matching_qureg_dims(rho1, rho2, "calcDensityInnerProduct")
    return float(C.calc_density_inner_product(
        rho1.amps, rho2.amps, quad=_quad()))


def calcPurity(qureg: Qureg) -> float:
    """Purity Tr(rho^2) of a density matrix (QuEST.h:3692)."""
    V.validate_density_matrix(qureg, "calcPurity")
    return float(C.calc_purity(qureg.amps, quad=_quad()))


def calcFidelity(qureg: Qureg, pureState: Qureg) -> float:
    """Fidelity of a register against a pure reference state (QuEST.h:3724)."""
    V.validate_second_qureg_state_vec(pureState, "calcFidelity")
    V.validate_matching_qureg_dims(qureg, pureState, "calcFidelity")
    quad = _quad()
    if qureg.is_density_matrix:
        return float(C.calc_fidelity_density(
            qureg.amps, pureState.amps,
            num_qubits=qureg.num_qubits_represented, quad=quad))
    ip_fn = C.calc_inner_product_quad if quad else C.calc_inner_product
    ip = np.asarray(ip_fn(qureg.amps, pureState.amps))
    return float(ip[0] ** 2 + ip[1] ** 2)


def calcHilbertSchmidtDistance(a: Qureg, b: Qureg) -> float:
    """Hilbert-Schmidt distance between two density matrices (QuEST.h:4911)."""
    V.validate_density_matrix(a, "calcHilbertSchmidtDistance")
    V.validate_density_matrix(b, "calcHilbertSchmidtDistance")
    V.validate_matching_qureg_dims(a, b, "calcHilbertSchmidtDistance")
    return float(C.calc_hilbert_schmidt_distance(
        a.amps, b.amps, quad=_quad()))


def _spans_mesh(qureg: Qureg) -> bool:
    """True when the register's amplitude axis actually spans a
    multi-device mesh (replicated-small registers do not)."""
    from .parallel import dist as PAR

    env = qureg.env
    return (env.mesh is not None and PAR.amp_axis_size(env.mesh) > 1
            and qureg.num_amps_total >= env.num_devices)


def _explicit_sharded(qureg: Qureg) -> bool:
    """Route to the explicit shard_map kernels: the register spans the
    mesh and the explicit-collective layer is enabled (the default).
    This is the ONE routing predicate for scan-based composites — the
    same kernels run on the virtual CPU mesh and on real multi-chip TPU
    meshes (one-kernel-set contract, QuEST_internal.h:63-292)."""
    from .parallel import dist as PAR

    return PAR.explicit_dist_enabled() and _spans_mesh(qureg)


def _gspmd_pallas_unsafe(qureg: Qureg) -> bool:
    """True when GSPMD propagation of raw Pallas kernels would fail: a
    real TPU backend with the register actually spanning the mesh (a raw
    pallas_call has no GSPMD partitioning rule there; the virtual CPU
    mesh partitions interpret-mode kernels as plain XLA ops).  Only
    consulted on the explicitly-opted-out GSPMD path
    (dist.use_explicit_dist(False)) — the default explicit path has no
    such fallback."""
    import jax as _jax

    return _jax.default_backend() == "tpu" and _spans_mesh(qureg)


def _full_codes(qureg, targets, codes) -> tuple:
    n = qureg.num_qubits_represented
    full = [PAULI_I] * n
    for t, c in zip(targets, codes):
        full[t] = int(c)
    return tuple(full)


def calcExpecPauliProd(qureg: Qureg, targetQubits, pauliCodes, workspace: Optional[Qureg] = None) -> float:
    """Expected value of a product of Pauli operators (uses workspace) (QuEST.h:4189)."""
    targets = [int(t) for t in targetQubits]
    codes = [int(c) for c in pauliCodes]
    V.validate_multi_targets(qureg, targets, "calcExpecPauliProd")
    V.validate_pauli_codes(codes, "calcExpecPauliProd")
    coeffs = np.ones(1)
    flat = _full_codes(qureg, targets, codes)
    quad = _quad()
    if qureg.is_density_matrix:
        val = P.calc_expec_pauli_sum_density(
            qureg.amps, coeffs, num_qubits=qureg.num_qubits_represented,
            codes_flat=flat, num_terms=1, quad=quad,
        )
    else:
        val = P.calc_expec_pauli_sum_statevec(
            qureg.amps, coeffs, num_qubits=qureg.num_qubits_represented,
            codes_flat=flat, num_terms=1, quad=quad,
        )
    return float(val)


def calcExpecPauliSum(qureg: Qureg, allPauliCodes, termCoeffs, workspace: Optional[Qureg] = None) -> float:
    """Expected value of a weighted sum of Pauli products (uses workspace) (QuEST.h:4244)."""
    n = qureg.num_qubits_represented
    codes = tuple(int(c) for c in np.asarray(allPauliCodes).ravel())
    coeffs = np.asarray(termCoeffs, dtype=np.float64)
    num_terms = coeffs.size
    V.validate_num_pauli_sum_terms(num_terms, "calcExpecPauliSum")
    if len(codes) != num_terms * n:
        raise V.QuESTError("calcExpecPauliSum: Number of Pauli codes doesn't match numSumTerms*numQubits.")
    V.validate_pauli_codes(codes, "calcExpecPauliSum")
    cj = coeffs
    quad = _quad()
    if qureg.is_density_matrix:
        val = P.calc_expec_pauli_sum_density(
            qureg.amps, cj, num_qubits=n, codes_flat=codes,
            num_terms=num_terms, quad=quad
        )
    elif _gspmd_pallas_unsafe(qureg) and not _explicit_sharded(qureg):
        # opted-out GSPMD mode on a real TPU mesh: the scan's Pallas
        # product layers cannot partition there — per-term kernels
        val = P.calc_expec_pauli_sum_statevec(
            qureg.amps, cj, num_qubits=n, codes_flat=codes,
            num_terms=num_terms, quad=quad,
        )
    else:
        # scan over the term table: one compiled body regardless of term
        # count (the unrolled variant took ~100 s to compile at 16x24q);
        # sharded registers run the SAME scan inside one shard_map with
        # explicit collectives (dist.expec_pauli_sum_scan_sharded)
        codes_seq = jnp.asarray(
            np.asarray(codes, np.int32).reshape(num_terms, n))
        if _explicit_sharded(qureg):
            from .parallel import dist as PAR
            val = PAR.expec_pauli_sum_scan_sharded(
                qureg.amps, codes_seq, jnp.asarray(cj),
                mesh=qureg.env.mesh, num_qubits=n, quad=quad)
        else:
            val = P.expec_pauli_sum_scan(
                qureg.amps, codes_seq, jnp.asarray(cj), num_qubits=n,
                quad=quad,
            )
    return float(val)


def calcExpecPauliHamil(qureg: Qureg, hamil: PauliHamil, workspace: Optional[Qureg] = None) -> float:
    """Expected value of a PauliHamil (uses workspace register) (QuEST.h:4285)."""
    V.validate_pauli_hamil(hamil, "calcExpecPauliHamil")
    V.validate_hamil_matches_qureg(hamil, qureg, "calcExpecPauliHamil")
    return calcExpecPauliSum(qureg, hamil.pauli_codes, hamil.term_coeffs, workspace)


def calcExpecDiagonalOp(qureg: Qureg, op: DiagonalOp) -> complex:
    """Expected value of a diagonal operator in the given state (QuEST.h:1255)."""
    V.validate_diag_op_matches_qureg(op, qureg, "calcExpecDiagonalOp")
    quad = _quad()
    if qureg.is_density_matrix:
        r = np.asarray(C.calc_expec_diagonal_density(
            qureg.amps, op.real, op.imag,
            num_qubits=qureg.num_qubits_represented, quad=quad))
    else:
        r = np.asarray(C.calc_expec_diagonal_statevec(
            qureg.amps, op.real, op.imag, quad=quad))
    return complex(r[0], r[1])


# ---------------------------------------------------------------------------
# Composite operators — apply* family: NO twin, NO unitarity checks
# (QuEST.c:1074-1105)
# ---------------------------------------------------------------------------


def setWeightedQureg(fac1, qureg1: Qureg, fac2, qureg2: Qureg, facOut, out: Qureg) -> None:
    """out = f1 q1 + f2 q2 + fOut out (weighted register sum) (QuEST.h:4936)."""
    V.validate_matching_qureg_types(qureg1, qureg2, "setWeightedQureg")
    V.validate_matching_qureg_types(qureg1, out, "setWeightedQureg")
    V.validate_matching_qureg_dims(qureg1, qureg2, "setWeightedQureg")
    V.validate_matching_qureg_dims(qureg1, out, "setWeightedQureg")
    facs = np.array(
        [
            [complex(facOut).real, complex(fac1).real, complex(fac2).real],
            [complex(facOut).imag, complex(fac1).imag, complex(fac2).imag],
        ]
    )
    if out is qureg1 or out is qureg2:
        # aliased call (out doubles as an input): donating out would hand
        # XLA a buffer that is also a live argument — keep the copy
        out.amps = K.set_weighted_qureg(
            out.amps, qureg1.amps, qureg2.amps, facs)
    else:
        out.amps = K.set_weighted_qureg_donated(
            out.amps, qureg1.amps, qureg2.amps, facs)


def _apply_matrix_raw(qureg: Qureg, m, targets, controls=()):
    from .ops import cplx as CX

    _telemetry.inc("dispatch_total", family="matrix_raw")
    qureg.amps = K.apply_matrix(
        qureg.amps, CX.soa(m), num_qubits=_sv_n(qureg),
        targets=tuple(int(t) for t in targets), controls=tuple(int(c) for c in controls),
    )
    qureg.qasm_log.comment("here a numeric matrix was applied (not recordable in QASM)")


def applyMatrix2(qureg: Qureg, targetQubit: int, u) -> None:
    """Left-multiply an arbitrary 2x2 matrix (no unitarity check, no density-matrix twin) (QuEST.h:5140)."""
    V.validate_target(qureg, targetQubit, "applyMatrix2")
    V.validate_matrix_size(u, 1, "applyMatrix2")
    _apply_matrix_raw(qureg, u, (targetQubit,))


def applyMatrix4(qureg: Qureg, targetQubit1: int, targetQubit2: int, u) -> None:
    """Left-multiply an arbitrary 4x4 matrix (no unitarity check, no density-matrix twin) (QuEST.h:5192)."""
    V.validate_unique_targets(qureg, targetQubit1, targetQubit2, "applyMatrix4")
    V.validate_matrix_size(u, 2, "applyMatrix4")
    _apply_matrix_raw(qureg, u, (targetQubit1, targetQubit2))


def applyMatrixN(qureg: Qureg, targs: Sequence[int], u) -> None:
    """Left-multiply an arbitrary 2^N x 2^N matrix (no unitarity check, no density-matrix twin) (QuEST.h:5260)."""
    targets = [int(t) for t in targs]
    V.validate_multi_targets(qureg, targets, "applyMatrixN")
    V.validate_multi_qubit_matrix_fits_in_node(qureg, len(targets), "applyMatrixN")
    V.validate_matrix_size(u, len(targets), "applyMatrixN")
    _apply_matrix_raw(qureg, u, tuple(targets))


def applyMultiControlledMatrixN(qureg: Qureg, ctrls: Sequence[int], targs: Sequence[int], u) -> None:
    """Left-multiply a controlled arbitrary matrix (no unitarity check, no twin) (QuEST.h:5313)."""
    controls = [int(c) for c in ctrls]
    targets = [int(t) for t in targs]
    V.validate_multi_controls_targets(qureg, controls, targets, "applyMultiControlledMatrixN")
    V.validate_matrix_size(u, len(targets), "applyMultiControlledMatrixN")
    _apply_matrix_raw(qureg, u, tuple(targets), tuple(controls))


def applyPauliSum(inQureg: Qureg, allPauliCodes, termCoeffs, outQureg: Qureg) -> None:
    """Left-multiply a weighted sum of Pauli products, writing outQureg (QuEST.h:4995)."""
    n = inQureg.num_qubits_represented
    codes = tuple(int(c) for c in np.asarray(allPauliCodes).ravel())
    coeffs = np.asarray(termCoeffs, dtype=np.float64)
    num_terms = coeffs.size
    V.validate_num_pauli_sum_terms(num_terms, "applyPauliSum")
    if len(codes) != num_terms * n:
        raise V.QuESTError("applyPauliSum: Number of Pauli codes doesn't match numSumTerms*numQubits.")
    V.validate_pauli_codes(codes, "applyPauliSum")
    V.validate_matching_qureg_types(inQureg, outQureg, "applyPauliSum")
    V.validate_matching_qureg_dims(inQureg, outQureg, "applyPauliSum")
    outQureg.amps = P.apply_pauli_sum(
        inQureg.amps, coeffs, outQureg.amps,
        num_qubits=n, num_state_qubits=_sv_n(inQureg),
        codes_flat=codes, num_terms=num_terms,
    )


def applyPauliHamil(inQureg: Qureg, hamil: PauliHamil, outQureg: Qureg) -> None:
    """Left-multiply a PauliHamil onto inQureg, writing outQureg (QuEST.h:5039)."""
    V.validate_pauli_hamil(hamil, "applyPauliHamil")
    V.validate_hamil_matches_qureg(hamil, inQureg, "applyPauliHamil")
    applyPauliSum(inQureg, hamil.pauli_codes, hamil.term_coeffs, outQureg)


def applyTrotterCircuit(qureg: Qureg, hamil: PauliHamil, time: float, order: int, reps: int) -> None:
    """Symmetrized Suzuki-Trotter e^{-iHt} (agnostic_applyTrotterCircuit,
    QuEST_common.c:752-834).

    The whole gate stream runs as ONE lax.scan over a (T, n) Pauli-code
    table (paulis.trotter_scan): compile cost is a single term body
    regardless of term count / order / reps, where the unrolled per-term
    multiRotatePauli stream took minutes to compile at config-5 scale.
    With QASM recording active the per-term path runs instead so each
    rotation is logged."""
    V.validate_pauli_hamil(hamil, "applyTrotterCircuit")
    V.validate_hamil_matches_qureg(hamil, qureg, "applyTrotterCircuit")
    V.validate_trotter_params(order, reps, "applyTrotterCircuit")
    if time == 0:
        return
    seq = _trotter_schedule(hamil.num_sum_terms, time, order, reps)
    if qureg.qasm_log.is_logging or (
            _gspmd_pallas_unsafe(qureg) and not _explicit_sharded(qureg)):
        # per-term path so every rotation is QASM-logged.  NOTE:
        # deliberately NOT wrapped in fusion.gate_fusion — the per-term
        # parity phase forces a drain every ~36 rotations, and the
        # drain's host-side plan materialization costs more than the
        # saved passes (measured 0.3 s unfused vs 2.9 s fused for a 20q
        # 8-term stream).
        from .api import multiRotatePauli

        targets = list(range(hamil.num_qubits))
        for t, fac in seq:
            multiRotatePauli(qureg, targets,
                             [int(c) for c in hamil.pauli_codes[t]],
                             2 * fac * float(hamil.term_coeffs[t]))
        return
    t_idx = np.asarray([t for t, _ in seq])
    facs = np.asarray([f for _, f in seq])
    codes_seq = np.asarray(hamil.pauli_codes)[t_idx].astype(np.int32)
    angles = 2.0 * facs * np.asarray(hamil.term_coeffs, np.float64)[t_idx]
    if _explicit_sharded(qureg):
        # same scan inside one shard_map: per-shard window layers +
        # ppermute exchange for sharded qubits (one-kernel-set contract
        # on real multi-chip meshes)
        from .parallel import dist as PAR
        qureg.amps = PAR.trotter_scan_sharded(
            qureg.amps, jnp.asarray(codes_seq), jnp.asarray(angles),
            mesh=qureg.env.mesh,
            num_qubits=qureg.num_qubits_in_state_vec,
            rep_qubits=qureg.num_qubits_represented,
        )
        return
    qureg.amps = P.trotter_scan(
        qureg.amps, jnp.asarray(codes_seq), jnp.asarray(angles),
        num_qubits=qureg.num_qubits_in_state_vec,
        rep_qubits=qureg.num_qubits_represented,
    )


def _trotter_schedule(num_terms: int, time: float, order: int, reps: int):
    """(term index, time factor) sequence of the symmetrized Suzuki
    recursion — the same expansion _symmetrized_trotter walks, flattened
    so the scan can consume it as data."""
    seq = []

    def exp_hamil(fac, reverse):
        rng = range(num_terms)
        for t in (reversed(rng) if reverse else rng):
            seq.append((t, fac))

    def symm(t, o):
        if o == 1:
            exp_hamil(t, False)
        elif o == 2:
            exp_hamil(t / 2, False)
            exp_hamil(t / 2, True)
        else:
            p = 1.0 / (4 - 4 ** (1.0 / (o - 1)))
            lower = o - 2
            symm(p * t, lower)
            symm(p * t, lower)
            symm((1 - 4 * p) * t, lower)
            symm(p * t, lower)
            symm(p * t, lower)

    for _ in range(reps):
        symm(time / reps, order)
    return seq


def applyDiagonalOp(qureg: Qureg, op: DiagonalOp) -> None:
    """Left-multiplies D onto the state — on rho this is D.rho, NOT D rho D^dag
    (QuEST.c apply-family semantics; densmatr path QuEST_cpu.c:4042-4082)."""
    V.validate_diag_op_matches_qureg(op, qureg, "applyDiagonalOp")
    if qureg.is_density_matrix:
        nq = qureg.num_qubits_represented
        routed = False
        if _explicit_sharded(qureg):
            from .parallel import dist as PAR

            r = PAR.num_shard_bits(qureg.env.mesh)
            # op must itself be sharded over the amp axis (tiny
            # replicated ops have nothing to gather) and rows shard-local
            if (1 << nq) >= PAR.amp_axis_size(qureg.env.mesh) and r <= nq:
                qureg.amps = PAR.apply_diag_op_density_sharded(
                    qureg.amps, op.real, op.imag, mesh=qureg.env.mesh,
                    num_qubits=nq)
                routed = True
        if not routed:
            qureg.amps = D.apply_diagonal_op_density(
                qureg.amps, op.real, op.imag, num_qubits=nq
            )
    else:
        qureg.amps = K.apply_full_diagonal(qureg.amps, op.real, op.imag)
    qureg.qasm_log.comment("here a diagonal operator was applied")


# ---------------------------------------------------------------------------
# Phase functions (QuEST.h:5571-6326)
# ---------------------------------------------------------------------------


def _empty_overrides():
    return np.zeros((0, 1), np.int64), np.zeros((0,), np.float64)


def _norm_overrides(overrideInds, overridePhases, num_regs):
    if overrideInds is None or len(np.asarray(overridePhases).ravel()) == 0:
        return np.zeros((0, num_regs), np.int64), np.zeros((0,), np.float64)
    inds = np.asarray(overrideInds, np.int64).reshape(-1, num_regs)
    phases = np.asarray(overridePhases, np.float64).ravel()
    return inds, phases


def _pad_params(params, func_name, num_regs):
    """Named-func divergence/shift params live at fixed slots
    (QuEST_cpu.c:4484-4543); pad so the kernel can index them statically."""
    p = np.asarray(params, np.float64).ravel() if params is not None else np.zeros(0)
    need = 2 + num_regs  # covers the largest (shifted-norm) layout
    if p.size < need:
        p = np.concatenate([p, np.zeros(need - p.size)])
    return p


def applyPhaseFunc(qureg: Qureg, qubits, encoding, coeffs, exponents) -> None:
    """Apply exp(i coeff * x^exp) phases from the index of one sub-register (QuEST.h:5571)."""
    applyPhaseFuncOverrides(qureg, qubits, encoding, coeffs, exponents, None, None)


def applyPhaseFuncOverrides(qureg: Qureg, qubits, encoding, coeffs, exponents, overrideInds, overridePhases) -> None:
    """Single-variable phase function with explicit per-index overrides (QuEST.h:5682)."""
    qubits = [int(q) for q in qubits]
    V.validate_qubit_subregs(qureg, [qubits], "applyPhaseFunc")
    V.validate_bit_encoding(int(encoding), "applyPhaseFunc",
                            num_qubits=len(qubits))
    inds, phases = _norm_overrides(overrideInds, overridePhases, 1)
    V.validate_phase_func_terms(len(qubits), int(encoding), coeffs, exponents,
                                [i[0] for i in inds], "applyPhaseFunc")
    V.validate_phase_func_overrides([len(qubits)], int(encoding), inds, "applyPhaseFunc")
    qureg.amps = PF.apply_phase_func(
        qureg.amps, np.asarray(coeffs, np.float64), np.asarray(exponents, np.float64),
        inds, phases,
        num_qubits=_sv_n(qureg), qubits=tuple(qubits), encoding=int(encoding),
    )
    qureg.qasm_log.phase_func(
        qubits, int(encoding), list(np.asarray(coeffs, np.float64).ravel()),
        list(np.asarray(exponents, np.float64).ravel()), inds, phases)


def applyMultiVarPhaseFunc(qureg: Qureg, qubits, numQubitsPerReg, encoding, coeffs, exponents, numTermsPerReg) -> None:
    """Apply exp(i sum_r coeff * x_r^exp) over multiple sub-register variables (QuEST.h:5843)."""
    applyMultiVarPhaseFuncOverrides(
        qureg, qubits, numQubitsPerReg, encoding, coeffs, exponents, numTermsPerReg, None, None
    )


def _split_regs(qubits, numQubitsPerReg):
    regs = []
    flat = [int(q) for q in np.asarray(qubits).ravel()]
    pos = 0
    for nq in numQubitsPerReg:
        regs.append(tuple(flat[pos:pos + int(nq)]))
        pos += int(nq)
    return tuple(regs)


def applyMultiVarPhaseFuncOverrides(qureg, qubits, numQubitsPerReg, encoding, coeffs, exponents, numTermsPerReg, overrideInds, overridePhases) -> None:
    """Multi-variable phase function with explicit per-index phase overrides (QuEST.h:5925)."""
    regs = _split_regs(qubits, numQubitsPerReg)
    V.validate_qubit_subregs(qureg, [list(r) for r in regs],
                             "applyMultiVarPhaseFunc")
    V.validate_multi_reg_bit_encoding([len(r) for r in regs], int(encoding),
                                      "applyMultiVarPhaseFunc")
    exps = np.asarray(exponents, np.float64)
    pos = 0
    exps_per_reg = []
    for t in numTermsPerReg:
        exps_per_reg.append(exps[pos:pos + int(t)])
        pos += int(t)
    V.validate_multi_var_phase_func_terms(
        [len(r) for r in regs], int(encoding), exps_per_reg,
        "applyMultiVarPhaseFunc")
    inds, phases = _norm_overrides(overrideInds, overridePhases, len(regs))
    V.validate_phase_func_overrides(
        [len(r) for r in regs], int(encoding), inds, "applyMultiVarPhaseFunc"
    )
    qureg.amps = PF.apply_multi_var_phase_func(
        qureg.amps, np.asarray(coeffs, np.float64), np.asarray(exponents, np.float64),
        inds, phases,
        num_qubits=_sv_n(qureg), reg_qubits=regs, encoding=int(encoding),
        terms_per_reg=tuple(int(t) for t in numTermsPerReg),
    )
    qureg.qasm_log.multi_var_phase_func(
        regs, int(encoding), list(np.asarray(coeffs, np.float64).ravel()),
        list(exps.ravel()), [int(t) for t in numTermsPerReg], inds, phases)


def applyNamedPhaseFunc(qureg, qubits, numQubitsPerReg, encoding, functionNameCode) -> None:
    """Apply one of the 14 named phase functions over sub-register variables (QuEST.h:6065)."""
    applyParamNamedPhaseFuncOverrides(
        qureg, qubits, numQubitsPerReg, encoding, functionNameCode, None, None, None
    )


def applyNamedPhaseFuncOverrides(qureg, qubits, numQubitsPerReg, encoding, functionNameCode, overrideInds, overridePhases) -> None:
    """Named phase function with explicit per-index phase overrides (QuEST.h:6138)."""
    applyParamNamedPhaseFuncOverrides(
        qureg, qubits, numQubitsPerReg, encoding, functionNameCode, None,
        overrideInds, overridePhases,
    )


def applyParamNamedPhaseFunc(qureg, qubits, numQubitsPerReg, encoding, functionNameCode, params) -> None:
    """Named phase function with extra scalar parameters (QuEST.h:6251)."""
    applyParamNamedPhaseFuncOverrides(
        qureg, qubits, numQubitsPerReg, encoding, functionNameCode, params, None, None
    )


def applyParamNamedPhaseFuncOverrides(qureg, qubits, numQubitsPerReg, encoding, functionNameCode, params, overrideInds, overridePhases, *, _conj=False) -> None:
    """Parameterised named phase function with per-index overrides (QuEST.h:6326)."""
    regs = _split_regs(qubits, numQubitsPerReg)
    shift = _shift(qureg) if _conj else 0
    V.validate_qubit_subregs(
        qureg, [[q - shift for q in r] for r in regs], "applyNamedPhaseFunc")
    V.validate_multi_reg_bit_encoding([len(r) for r in regs], int(encoding),
                                      "applyNamedPhaseFunc")
    num_params = 0 if params is None else int(np.asarray(params).size)
    V.validate_phase_func_name(int(functionNameCode), len(regs), num_params,
                               "applyNamedPhaseFunc")
    inds, phases = _norm_overrides(overrideInds, overridePhases, len(regs))
    V.validate_phase_func_overrides(
        [len(r) for r in regs], int(encoding), inds, "applyNamedPhaseFunc"
    )
    qureg.amps = PF.apply_named_phase_func(
        qureg.amps, _pad_params(params, int(functionNameCode), len(regs)),
        inds, phases,
        num_qubits=_sv_n(qureg), reg_qubits=regs, encoding=int(encoding),
        func_name=int(functionNameCode), conj=_conj,
    )
    qureg.qasm_log.named_phase_func(
        regs, int(encoding), int(functionNameCode),
        [] if params is None else list(np.asarray(params, np.float64).ravel()),
        inds, phases)


# ---------------------------------------------------------------------------
# QFT (agnostic_applyQFT, QuEST_common.c:836-898)
# ---------------------------------------------------------------------------


def applyQFT(qureg: Qureg, qubits: Sequence[int], numQubits: Optional[int] = None) -> None:
    """Apply the quantum Fourier transform to the given qubits (QuEST.h:6536)."""
    qubits = [int(q) for q in qubits]
    V.validate_multi_targets(qureg, qubits, "applyQFT")
    _apply_qft(qureg, qubits)


def applyFullQFT(qureg: Qureg) -> None:
    """Apply the quantum Fourier transform to every qubit (QuEST.h:6420)."""
    _apply_qft(qureg, list(range(qureg.num_qubits_represented)))


def _apply_qft(qureg: Qureg, qubits) -> None:
    if _qft_fused(qureg, qubits):
        return
    n = len(qubits)
    for q in range(n - 1, -1, -1):
        hadamard(qureg, qubits[q])
        if q == 0:
            break
        # fused controlled-phase ladder: theta = (pi/2^q) * x_low * x_q
        regs = (tuple(qubits[:q]), (qubits[q],))
        params = np.array([math.pi / (1 << q)])
        inds = np.zeros((0, 2), np.int64)
        phases = np.zeros((0,), np.float64)
        qureg.amps = PF.apply_named_phase_func(
            qureg.amps, _pad_params(params, PF.SCALED_PRODUCT, 2), inds, phases,
            num_qubits=_sv_n(qureg), reg_qubits=regs, encoding=PF.UNSIGNED,
            func_name=PF.SCALED_PRODUCT, conj=False,
        )
        if qureg.is_density_matrix:
            sh = _shift(qureg)
            sregs = (tuple(x + sh for x in regs[0]), tuple(x + sh for x in regs[1]))
            qureg.amps = PF.apply_named_phase_func(
                qureg.amps, _pad_params(params, PF.SCALED_PRODUCT, 2), inds, phases,
                num_qubits=_sv_n(qureg), reg_qubits=sregs, encoding=PF.UNSIGNED,
                func_name=PF.SCALED_PRODUCT, conj=True,
            )
        qureg.qasm_log.comment("here a controlled-phase ladder (QFT layer) was applied")
    for i in range(n // 2):
        swapGate(qureg, qubits[i], qubits[n - i - 1])


def _qft_fused(qureg: Qureg, qubits) -> bool:
    """Fused QFT (circuit.fused_qft): per-layer elementwise ladder passes +
    one scheduled low-qubit window pass + ONE bit-reversal permute for the
    whole swap network (both halves at once for a density matrix), instead
    of the reference's per-layer dispatch (agnostic_applyQFT,
    QuEST_common.c:836-898).  Applies when the targeted qubits are a
    contiguous ascending run starting at 0 or >= 7 and the state vector is
    window-sized; otherwise returns False and the layered path runs.

    Sharded registers: a FULL-register statevector QFT runs as ONE
    explicit shard_map program (dist.fused_qft_sharded — ppermute H
    exchanges for mesh-bit layers, the same Pallas ladder kernels
    per-shard for local layers, and an all_to_all bit-reversal); partial
    and density QFTs run the general-run shard_map kernel
    (dist.fused_qft_runs_sharded), so the fused kernel set runs on real
    TPU meshes for EVERY QFT shape (QuEST_internal.h:63-292
    one-kernel-set contract).  Only the explicitly-opted-out GSPMD mode
    (dist.use_explicit_dist(False)) retains a layered-path fallback on
    real multi-chip TPU meshes (a raw pallas_call has no GSPMD
    partitioning rule)."""
    import jax as _jax

    from quest_tpu import circuit as CIRC
    from quest_tpu.parallel import dist as PAR

    nsv = _sv_n(qureg)
    if nsv < CIRC.WINDOW:
        return False
    env = qureg.env
    nt = len(qubits)
    start = qubits[0]
    if list(qubits) != list(range(start, start + nt)):
        return False
    if not (start == 0 or start >= CIRC.LANE):
        return False

    sharded = _spans_mesh(qureg)
    if sharded:
        r = PAR.num_shard_bits(env.mesh)
        if (not qureg.is_density_matrix and start == 0 and nt == nsv
                and nsv - r >= r):
            qureg.amps = PAR.fused_qft_sharded(
                qureg.amps, mesh=env.mesh, num_qubits=nsv)
            _qft_qasm_trail(qureg, qubits, nt)
            return True
        if PAR.explicit_dist_enabled():
            # partial-register / density QFT on a sharded register: the
            # general-run shard_map kernel (fully-local runs execute the
            # unsharded fused kernels per shard; runs reaching mesh bits
            # use ppermute layers + the mixed bit reversal)
            runs = [(start, nt, False)]
            if qureg.is_density_matrix:
                runs.append((start + _shift(qureg), nt, True))
            qureg.amps = PAR.fused_qft_runs_sharded(
                qureg.amps, mesh=env.mesh, num_qubits=nsv,
                runs=tuple(runs))
            _qft_qasm_trail(qureg, qubits, nt)
            return True
        if _jax.default_backend() == "tpu":
            # opted-out GSPMD mode cannot partition the raw Pallas
            # kernels on a real mesh: layered path
            return False

    shifts = [0, _shift(qureg)] if qureg.is_density_matrix else [0]
    qureg.amps = CIRC.fused_qft(qureg.amps, nsv, start, nt, shifts=shifts)
    _qft_qasm_trail(qureg, qubits, nt)
    return True


def _qft_qasm_trail(qureg: Qureg, qubits, nt: int) -> None:
    """QASM record mirroring the layered path's trail."""
    for q in range(nt - 1, -1, -1):
        qureg.qasm_log.gate("h", (), qubits[q])
        if q:
            qureg.qasm_log.comment(
                "here a controlled-phase ladder (QFT layer) was applied")
    for i in range(nt // 2):
        qureg.qasm_log.gate("swap", (qubits[i],), qubits[nt - 1 - i])


# ---------------------------------------------------------------------------
# Circuit optimizer knob (optimizer.py, docs/design.md §26)
# ---------------------------------------------------------------------------


def setCircuitOptimizer(mode: Optional[str]) -> None:
    """Select the circuit-optimizer mode for subsequent fusion drains:
    ``"off"``, ``"on"`` (cancellation/merging, diagonal coalescing, and
    greedy cost-guided reordering), or ``"aggressive"`` (wider reorder
    search + near-identity drops).  ``None`` returns control to the
    ``QT_OPTIMIZER`` env var.  The mode is part of the fusion plan-cache
    key and the batch structure fingerprint, so flipping it retraces
    rather than replaying a stale plan."""
    from . import optimizer as _optimizer

    _optimizer.set_circuit_optimizer(mode)


def getCircuitOptimizer() -> str:
    """The active circuit-optimizer mode string."""
    from . import optimizer as _optimizer

    return _optimizer.get_circuit_optimizer()


# ---------------------------------------------------------------------------
# QASM recording (QuEST.h:3351-3390)
# ---------------------------------------------------------------------------


def startRecordingQASM(qureg: Qureg) -> None:
    """Begin recording API gates as OPENQASM 2.0 (QuEST.h:3351)."""
    qureg.qasm_log.start()


def stopRecordingQASM(qureg: Qureg) -> None:
    """Stop recording QASM (QuEST.h:3362)."""
    qureg.qasm_log.stop()


def clearRecordedQASM(qureg: Qureg) -> None:
    """Clear the register's recorded QASM buffer (QuEST.h:3370)."""
    qureg.qasm_log.clear()


def printRecordedQASM(qureg: Qureg) -> None:
    """Print the recorded QASM to stdout (QuEST.h:3379)."""
    print(str(qureg.qasm_log), end="")


def writeRecordedQASMToFile(qureg: Qureg, filename: str) -> None:
    """Write the recorded QASM to a file (QuEST.h:3390)."""
    try:
        with open(filename, "w") as f:
            f.write(str(qureg.qasm_log))
    except OSError:
        raise V.QuESTError(f"writeRecordedQASMToFile: Could not open file {filename}")
