"""Batched registers: ensembles of same-structure circuits on one mesh.

The reference simulates exactly one register per program; running N
small-circuit variants (a VQE parameter sweep, randomized compiling, shot
batches, quantum trajectories) costs N full dispatch pipelines even
though every variant executes the SAME gate structure.  On TPU that
leaves the chip idle: a 20-qubit state is 16 MB — a fraction of one
core's HBM and far below the VPU's saturation point, so amortizing one
compiled program over a leading batch axis is close to free (the same
observation driving qHiPSTER's circuit batching, arXiv:1601.07195 §III,
and mpiQulacs' batched trajectory mode, arXiv:2203.16044 §V).

:class:`BatchedQureg` carries a (B, 2, 2^n) SoA amplitude bank — batch
OUTER, amplitudes inner, so the amplitude axis shards over the mesh
exactly as a scalar register's and every sharded dispatch wrapper works
unchanged per element.  Gate dispatch rides the existing fusion drain
(fusion._run) vmapped over the bank: the circuit plan, the live
logical->physical permutation, and the window-remap schedule are SHARED
across the batch because every element runs the same gate stream; only
the matrices may differ per element ((B, 2, s, s) ``Gate.mat``).
Measurement draws from a PER-ELEMENT key bank, so batched sampling is
bit-identical to B independent seeded runs.

On top of the bank:

- :class:`EnsembleScheduler` — ``submit()`` circuits, ``drain()`` runs
  them grouped by structural fingerprint and padded to power-of-two
  batch buckets, so the jit retrace count is bounded by the bucket
  count, not the submission count.
- :func:`run_trajectories` — quantum-trajectory (Monte-Carlo wavefunction)
  unraveling of mixDephasing / mixDepolarising / mixDamping as
  stochastic gate insertion over a trajectory bank, reducing observables
  with error bars; the B-trajectory mean converges to the exact density
  channel (ops/density.py) it unravels.
"""

from __future__ import annotations

import time
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import circuit as C
from . import fusion as _fusion
from . import telemetry as _telemetry
from .env import AMP_AXIS, QuESTEnv
from .qureg import Qureg
from .validation import QuESTError

__all__ = [
    "BatchedQureg",
    "EnsembleScheduler",
    "bank_gate_items",
    "bank_occupancy",
    "createBatchedQureg",
    "applyBatchedUnitary",
    "measureBatched",
    "calcExpecPauliSumBatched",
    "run_trajectories",
]


# ---------------------------------------------------------------------------
# The register bank
# ---------------------------------------------------------------------------


class BatchedQureg(Qureg):
    """B same-width registers as ONE (B, 2, 2^n) amplitude bank.

    Subclasses :class:`Qureg` so the whole read/drain protocol (the
    ``amps`` property, fusion drain, lazy permutation rematerialization,
    checkpointing) applies to the bank unchanged — fusion and the
    distributed remap detect the leading batch axis and vmap over it.
    Gates issued through the ordinary imperative API (hadamard,
    controlledNot, ...) are always captured into the fusion buffer (it
    re-arms itself after a ``stop_gate_fusion``); operations that would
    fall back to eager scalar dispatch raise a structured error instead
    of silently misreading the bank.
    """

    def __init__(self, num_qubits: int, env: QuESTEnv, batch_size: int, *,
                 is_density_matrix: bool = False, seeds=None):
        if int(batch_size) < 1:
            raise QuESTError(
                f"BatchedQureg: batch_size must be >= 1, got {batch_size}")
        super().__init__(num_qubits, env, is_density_matrix)
        self.batch_size = int(batch_size)
        self.seed_elements(seeds)

    # -- always-capturing fusion: the buffer re-arms after a
    #    stop_gate_fusion (resilience windows stop/start around every
    #    checkpoint) so API gates never fall through to eager dispatch --
    @property
    def _fusion(self):
        buf = self.__dict__.get("_fusion_buf")
        if buf is None:
            buf = _fusion.FusionBuffer()
            self.__dict__["_fusion_buf"] = buf
        return buf

    @_fusion.setter
    def _fusion(self, value):
        self.__dict__["_fusion_buf"] = value

    # -- per-element measurement keys ------------------------------------

    def seed_elements(self, seeds=None) -> None:
        """(Re)seed the per-element measurement key bank.  ``seeds[i]``
        seeds element i exactly as ``seedQuEST(seeds[i])`` would seed a
        standalone register's device measurement stream
        (ops/measurement._KeyState.seed), so batched outcomes are
        bit-identical to B independent runs.  Default: the global RNG
        seed with the element index folded in."""
        from .ops import measurement as M

        B = self.batch_size
        if seeds is None:
            from .rng import GLOBAL_RNG

            base = [int(s) for s in (getattr(GLOBAL_RNG, "_keys", None)
                                     or [0])]
            seeds = [base + [i] for i in range(B)]
        if len(seeds) != B:
            raise QuESTError(
                f"BatchedQureg: got {len(seeds)} seeds for a batch of {B}")
        keys = []
        for s in seeds:
            if isinstance(s, (int, np.integer)):
                s = [int(s)]
            ks = M._KeyState()
            ks.seed([int(x) for x in s])
            raw = jax.random.key_data(ks.key) \
                if jnp.issubdtype(ks.key.dtype, jax.dtypes.prng_key) \
                else ks.key
            keys.append(np.asarray(raw, dtype=np.uint32))
        self._mkeys = np.stack(keys)            # (B, key_words) uint32
        self._mshots = [0] * B                  # per-element shot counters

    def key_state(self) -> dict:
        """JSON-serializable per-element (key, shot counter) bank — the
        batched analogue of measurement._KeyState.get_state, carried in
        checkpoint metadata so resumed banks draw the same streams."""
        return {
            "keys": [[int(x) for x in row] for row in self._mkeys],
            "counters": [int(c) for c in self._mshots],
        }

    def set_key_state(self, state: dict) -> None:
        keys = state.get("keys")
        if keys is None or len(keys) != self.batch_size:
            raise QuESTError(
                "BatchedQureg: checkpoint key bank holds "
                f"{0 if keys is None else len(keys)} elements but the "
                f"register batch is {self.batch_size}")
        self._mkeys = np.array(keys, dtype=np.uint32)
        self._mshots = [int(c) for c in state.get(
            "counters", [0] * self.batch_size)]

    # -- bank-aware array plumbing ---------------------------------------

    def sharding(self):
        """Batch-outer / amps-inner: the amplitude axis (last) shards
        over the mesh exactly as a scalar register's, every element on
        every device's shard — collectives see B independent rows."""
        from jax.sharding import NamedSharding, PartitionSpec

        if self.num_amps_total >= self.env.num_devices:
            return NamedSharding(
                self.env.mesh, PartitionSpec(None, None, AMP_AXIS))
        return self.env.replicated_sharding()

    def _as_bank(self, value):
        """Lift a scalar (2, 2^n) write to the full bank (the init family
        writes one state for all elements); a (B, 2, 2^n) write binds
        element-wise."""
        value = jnp.asarray(value, self.dtype)
        if value.ndim == 2:
            value = jnp.broadcast_to(
                value[None], (self.batch_size,) + value.shape)
        elif value.ndim != 3 or value.shape[0] != self.batch_size:
            raise QuESTError(
                "BatchedQureg: expected amplitudes of shape (2, "
                f"{self.num_amps_total}) or ({self.batch_size}, 2, "
                f"{self.num_amps_total}), got {tuple(value.shape)}")
        return value

    @property
    def amps(self):
        return Qureg.amps.fget(self)

    @amps.setter
    def amps(self, value):
        Qureg.amps.fset(self, jax.device_put(self._as_bank(value),
                                             self.sharding()))

    def device_put(self, amps):
        return jax.device_put(self._as_bank(amps), self.sharding())

    def element(self, i: int):
        """Canonical-order amplitudes of batch element ``i`` as a
        (2, 2^n) array (pending gates drain, permutation
        rematerializes)."""
        if not 0 <= int(i) < self.batch_size:
            raise QuESTError(
                f"BatchedQureg.element: index {i} out of range for batch "
                f"{self.batch_size}")
        return self.amps[int(i)]


def createBatchedQureg(numQubits: int, env: QuESTEnv, batchSize: int, *,
                       is_density_matrix: bool = False,
                       seeds=None) -> BatchedQureg:
    """Create a bank of ``batchSize`` registers in the zero state
    (|0...0> per element; |0...0><0...0| for a density bank).  ``seeds``
    optionally gives each element its own measurement stream seed
    (default: global seed + element index)."""
    from . import validation as V
    from .ops import kernels as K

    V.validate_num_qubits(numQubits, "createBatchedQureg",
                          num_ranks=env.num_ranks)
    q = BatchedQureg(numQubits, env, batchSize,
                     is_density_matrix=is_density_matrix, seeds=seeds)
    # admission is batch-aware: the modeled footprint carries the bank
    # dimension, so an oversized ensemble is refused before device_put
    from . import governor as _gov

    _gov.admit_new(q, "createBatchedQureg")
    if is_density_matrix:
        q.amps = K.init_classical_density(numQubits, 0, q.dtype)
    else:
        q.amps = K.init_zero_state(q.num_amps_total, q.dtype)
    return q


# ---------------------------------------------------------------------------
# Per-element gates
# ---------------------------------------------------------------------------


def _soa_per_element(mats, batch: int):
    """Stack per-element matrices to a concrete (B, 2, s, s) SoA array.
    Accepts (B, s, s) complex or (B, 2, s, s) already-stacked input."""
    from .ops import cplx as CX

    m = np.asarray(mats)
    if m.ndim == 3:
        m = np.stack([np.asarray(CX.soa(m[b])) for b in range(m.shape[0])])
    if m.ndim != 4 or m.shape[0] != batch or m.shape[1] != 2 \
            or m.shape[2] != m.shape[3]:
        raise QuESTError(
            "applyBatchedUnitary: expected matrices of shape (B, s, s) "
            f"complex or (B, 2, s, s) SoA with B={batch}, got "
            f"{tuple(np.asarray(mats).shape)}")
    return m


def applyBatchedUnitary(qureg: BatchedQureg, targets, mats,
                        controls=(), control_states=()) -> None:
    """Apply a DIFFERENT unitary to each batch element in one fused pass:
    ``mats[b]`` acts on element b's ``targets`` (density banks get the
    conjugated bra twin, as _apply_unitary does).  The per-element stack
    is planned against one shared program skeleton — a (B, 2, s, s)
    ``Gate.mat`` in the fusion buffer — so the bank still drains as one
    vmapped dispatch."""
    from . import api as _api

    if not getattr(qureg, "batch_size", 0):
        raise QuESTError(
            "applyBatchedUnitary: the register is not a BatchedQureg")
    targets = tuple(int(t) for t in targets)
    controls = tuple(int(c) for c in controls)
    B = qureg.batch_size
    stacked = _soa_per_element(mats, B)
    _telemetry.inc_key(_api._K_UNITARY, B)
    if controls:
        stacked = np.stack([
            C.controlled_dense(stacked[b], len(controls), control_states)
            for b in range(B)])
    bits = targets + controls
    if not _fusion._capturable(qureg, bits) or (
            qureg.is_density_matrix and not _fusion._capturable(
                qureg, tuple(b + qureg.num_qubits_represented
                             for b in bits))):
        raise QuESTError(
            "applyBatchedUnitary: the gate does not qualify for the fused "
            f"path (<= {_fusion.FUSION_MAX_GATE_QUBITS} qubits, and "
            "shard-local space for a distributed bank) — batched "
            "registers have no eager fallback")
    buf = qureg._fusion
    buf.gates.append(C.Gate(bits, stacked))
    if qureg.is_density_matrix:
        sh = qureg.num_qubits_represented
        cstacked = np.stack([stacked[:, 0], -stacked[:, 1]], axis=1)
        buf.gates.append(C.Gate(tuple(b + sh for b in bits), cstacked))


# ---------------------------------------------------------------------------
# Batched measurement
# ---------------------------------------------------------------------------


@partial(jax.jit,
         static_argnames=("num_qubits", "target", "is_density", "quad"),
         donate_argnums=0)
def _measure_bank(amps, keys, shots, *, num_qubits: int, target: int,
                  is_density: bool, quad: bool = False):
    from .ops import measurement as M

    def one(a, k, s):
        return M._measure_once(a, k, s, num_qubits, target, is_density,
                               quad)

    return jax.vmap(one)(amps, keys, shots)


def measureBatched(qureg: BatchedQureg, measureQubit: int):
    """Measure ``measureQubit`` on EVERY batch element in one vmapped
    program — each element draws from its OWN key/shot stream, so the
    (outcomes, probabilities) arrays are bit-identical to B independent
    seeded ``measure`` calls.  Collapses the bank in place; returns
    ((B,) int outcomes, (B,) float probabilities)."""
    from . import validation as V
    from .api_ops import _quad

    if not getattr(qureg, "batch_size", 0):
        raise QuESTError("measureBatched: the register is not a "
                         "BatchedQureg")
    V.validate_target(qureg, measureQubit, "measureBatched")
    B = qureg.batch_size
    _telemetry.inc("measurement_shots_total", B)
    amps, outs, probs = _measure_bank(
        qureg.amps, jnp.asarray(qureg._mkeys),
        jnp.asarray(qureg._mshots, jnp.int32),
        num_qubits=qureg.num_qubits_represented, target=int(measureQubit),
        is_density=qureg.is_density_matrix, quad=_quad())
    qureg.amps = amps
    qureg._mshots = [s + 1 for s in qureg._mshots]
    qureg.qasm_log.measure(int(measureQubit))
    return np.asarray(outs), np.asarray(probs)


# ---------------------------------------------------------------------------
# Batched expectation values
# ---------------------------------------------------------------------------


def calcExpecPauliSumBatched(qureg: BatchedQureg, codes, coeffs,
                             *, quad: Optional[bool] = None) -> np.ndarray:
    """Per-element <psi_b| sum_t c_t P_t |psi_b> over the bank as a (B,)
    array.  Elements evaluate through the SAME scan composite a scalar
    register would use (sharded direct body included), sliced from the
    bank — a (2, 2^n) slice of the (B, 2, 2^n) bank keeps the scalar
    sharding geometry, so per-element values are bit-identical to
    standalone runs."""
    from .api_ops import _quad as _qd
    from .ops import paulis as OPS_P

    if not getattr(qureg, "batch_size", 0):
        raise QuESTError("calcExpecPauliSumBatched: the register is not "
                         "a BatchedQureg")
    quad = _qd() if quad is None else bool(quad)
    codes = jnp.asarray(codes, jnp.int32)
    coeffs = jnp.asarray(coeffs)
    n = qureg.num_qubits_represented
    amps = qureg.amps
    nsh = _fusion._shard_bits(qureg)
    vals = []
    for b in range(qureg.batch_size):
        a = amps[b]
        if nsh:
            from .parallel import dist as PAR

            v = PAR.expec_pauli_sum_scan_sharded(
                a, codes, coeffs, mesh=qureg.env.mesh, num_qubits=n,
                quad=quad)
        else:
            v = OPS_P.expec_pauli_sum_scan(a, codes, coeffs, num_qubits=n,
                                           quad=quad)
        vals.append(v)
    return np.asarray([float(v) for v in vals])


# ---------------------------------------------------------------------------
# Ensemble scheduler
# ---------------------------------------------------------------------------


def _bucket_size(count: int, max_batch: int) -> int:
    """Next power of two >= count, capped at max_batch — padding to
    power-of-two buckets bounds the jit retrace count per circuit
    structure by the bucket count (log2(max_batch)+1), not the
    submission count."""
    b = 1
    while b < count:
        b <<= 1
    return min(b, max_batch)


def bank_occupancy(qureg, real: Optional[int] = None) -> dict:
    """Bucket occupancy of a batched register for the plan explainer
    (introspect.explain_circuit): the live batch size, the power-of-two
    bucket it pads to, and the real/padded fraction — the same quantity
    EnsembleScheduler publishes as the ``batch_occupancy`` gauge.

    ``real`` (serving layer): the bank was ALREADY padded to a
    power-of-two batch and only ``real`` of its elements carry live
    jobs — report true occupancy with the padding excluded."""
    bsz = int(getattr(qureg, "batch_size", 0) or 0)
    if not bsz:
        return {"size": 0, "bucket": 0, "occupancy": 1.0}
    if real is not None:
        return {"size": int(real), "bucket": bsz,
                "occupancy": int(real) / bsz}
    bucket = _bucket_size(bsz, 1 << 30)
    return {"size": bsz, "bucket": bucket, "occupancy": bsz / bucket}


def _structure_fingerprint(gates: Sequence, num_qubits: int,
                           is_density: bool) -> tuple:
    """Hashable circuit STRUCTURE (targets + matrix shapes, not values):
    submissions with equal fingerprints plan to the same program skeleton
    and may share a batch bucket.  The circuit-optimizer mode is part of
    the fingerprint — the optimizer rewrites the bank's shared item list
    before planning, so streams bucketed under different QT_OPTIMIZER
    modes must never share a batch."""
    from . import optimizer as _optimizer

    parts = [("q", int(num_qubits), bool(is_density), _optimizer.mode())]
    for g in gates:
        m = np.asarray(g.mat)
        parts.append((tuple(g.targets), m.shape[-1]))
    return tuple(parts)


def bank_gate_items(streams: Sequence[Sequence], num_qubits: int,
                    is_density: bool, *, qureg=None) -> List:
    """Fuse B same-STRUCTURE gate streams into ONE bank item list.

    ``streams[b]`` is submission b's gate sequence; all B must share a
    structural fingerprint (same targets and matrix shapes gate for
    gate).  Gate j collapses to one shared (2, s, s) item when every
    element's matrix is bitwise identical, else stacks to a per-element
    (B, 2, s, s) item (the applyBatchedUnitary representation); density
    banks get the conjugated bra twin after each item.  The result is
    appendable to a :class:`BatchedQureg`'s fusion buffer — the shared
    path of ``EnsembleScheduler._run_bucket`` and the window-stepped
    banks of :mod:`quest_tpu.serve` build their programs through here.

    ``qureg``: when given, each gate is validated against the fused
    path's capture limits (batched registers have no eager fallback).
    """
    B = len(streams)
    items: List = []
    for j in range(len(streams[0])):
        mats = [np.asarray(s[j].mat) for s in streams]
        targets = tuple(int(t) for t in streams[0][j].targets)
        if qureg is not None and (
                not _fusion._capturable(qureg, targets) or (
                    is_density and not _fusion._capturable(
                        qureg, tuple(t + num_qubits for t in targets)))):
            raise QuESTError(
                "bank_gate_items: gate does not qualify for the fused "
                f"path (<= {_fusion.FUSION_MAX_GATE_QUBITS} qubits, and "
                "shard-local space for a distributed bank) — batched "
                "registers have no eager fallback")
        if all(m.tobytes() == mats[0].tobytes() for m in mats[1:]):
            shared = mats[0]
            items.append(C.Gate(targets, shared))
            if is_density:
                items.append(C.Gate(
                    tuple(t + num_qubits for t in targets),
                    np.stack([shared[0], -shared[1]])))
        else:
            stacked = _soa_per_element(np.stack(mats), B)
            items.append(C.Gate(targets, stacked))
            if is_density:
                items.append(C.Gate(
                    tuple(t + num_qubits for t in targets),
                    np.stack([stacked[:, 0], -stacked[:, 1]], axis=1)))
    return items


class EnsembleScheduler:
    """Collect same-width circuit submissions and run them batched.

    ``submit(gates)`` queues a circuit (a sequence of
    :class:`quest_tpu.circuit.Gate` with concrete numpy SoA matrices —
    e.g. the same ansatz at different parameters); ``drain()`` groups the
    queue by structural fingerprint, pads each group to power-of-two
    batch buckets (<= ``max_batch``), runs every bucket as ONE
    BatchedQureg program, and returns each submission's final canonical
    (2, 2^n) amplitudes in submission order.  Identical matrices across
    a bucket collapse to one shared (2, s, s) gate; differing matrices
    ride the per-element (B, 2, s, s) path.  Records
    ``batch_occupancy`` (real/padded fraction), ``ensemble_circuits_total``
    and ``ensemble_circuits_per_sec`` telemetry."""

    def __init__(self, num_qubits: int, env: QuESTEnv, *,
                 is_density_matrix: bool = False, max_batch: int = 64):
        if max_batch < 1 or (max_batch & (max_batch - 1)):
            raise QuESTError(
                f"EnsembleScheduler: max_batch must be a power of two, "
                f"got {max_batch}")
        self.num_qubits = int(num_qubits)
        self.env = env
        self.is_density_matrix = bool(is_density_matrix)
        self.max_batch = int(max_batch)
        self._pending: List[Tuple[int, tuple, list, object]] = []
        self._next_id = 0

    def submit(self, gates: Sequence, *, seed=None) -> int:
        """Queue one circuit; returns its submission id (the index of its
        result in ``drain()``'s list)."""
        gates = list(gates)
        for g in gates:
            if not isinstance(g.mat, np.ndarray):
                raise QuESTError(
                    "EnsembleScheduler.submit: gate matrices must be "
                    "concrete numpy arrays (traced values cannot be "
                    "stacked across submissions)")
        fp = _structure_fingerprint(gates, self.num_qubits,
                                    self.is_density_matrix)
        sid = self._next_id
        self._next_id += 1
        self._pending.append((sid, fp, gates, seed))
        return sid

    def _run_bucket(self, group: list) -> Tuple[dict, int, int]:
        """Execute one fingerprint group bucket; returns
        ({sid: amps}, real, padded) so ``drain()`` can aggregate TRUE
        occupancy (padding excluded) across buckets."""
        real = len(group)
        B = _bucket_size(real, self.max_batch)
        # pad with copies of the last submission: padding elements run
        # (and are discarded), keeping the batch shape a power of two
        padded = group + [group[-1]] * (B - real)
        seeds = [s if s is not None else i
                 for i, (_, _, _, s) in enumerate(padded)]
        q = createBatchedQureg(
            self.num_qubits, self.env, B,
            is_density_matrix=self.is_density_matrix, seeds=seeds)
        from . import api as _api

        items = bank_gate_items([sub[2] for sub in padded],
                                self.num_qubits, self.is_density_matrix,
                                qureg=q)
        _telemetry.inc_key(_api._K_UNITARY, B * len(group[0][2]))
        q._fusion.gates.extend(items)
        bank = np.asarray(q.amps)
        _telemetry.observe("ensemble_bucket_occupancy", real / B)
        return {sub[0]: bank[i] for i, sub in enumerate(group)}, real, B

    def drain(self) -> List[np.ndarray]:
        """Run every pending submission; returns final canonical
        amplitudes in submission order and clears the queue.  The
        ``batch_occupancy`` gauge is set ONCE per drain to the
        aggregate real/padded fraction over every bucket run — a
        partially-filled trailing bucket no longer overwrites the gauge
        with its own (lower or higher) ratio."""
        if not self._pending:
            return []
        t0 = time.perf_counter()
        pending, self._pending = self._pending, []
        groups: dict = {}
        for sub in pending:
            groups.setdefault(sub[1], []).append(sub)
        results: dict = {}
        occ_real = occ_padded = 0
        with _telemetry.span("batch.ensemble_drain",
                             circuits=len(pending), groups=len(groups)):
            for group in groups.values():
                for i in range(0, len(group), self.max_batch):
                    res, real, padded = self._run_bucket(
                        group[i:i + self.max_batch])
                    results.update(res)
                    occ_real += real
                    occ_padded += padded
        if occ_padded:
            _telemetry.set_gauge("batch_occupancy", occ_real / occ_padded)
        dt = time.perf_counter() - t0
        _telemetry.inc("ensemble_circuits_total", len(pending))
        if dt > 0:
            _telemetry.set_gauge("ensemble_circuits_per_sec",
                                 len(pending) / dt)
        return [results[sub[0]] for sub in pending]


# ---------------------------------------------------------------------------
# Quantum trajectories (Monte-Carlo wavefunction unraveling)
# ---------------------------------------------------------------------------

_I2 = np.stack([np.eye(2), np.zeros((2, 2))])
_X2 = np.stack([np.array([[0., 1.], [1., 0.]]), np.zeros((2, 2))])
_Y2 = np.stack([np.zeros((2, 2)), np.array([[0., -1.], [1., 0.]])])
_Z2 = np.stack([np.diag([1., -1.]), np.zeros((2, 2))])


@partial(jax.jit, static_argnames=("num_qubits", "target"))
def _prob1_bank(amps, *, num_qubits: int, target: int):
    from .ops import calculations as CALC

    def one(a):
        return CALC.calc_prob_of_outcome_statevec(
            a, num_qubits=num_qubits, target=target, outcome=1)

    return jax.vmap(one)(amps)


def _sample_pauli_insertion(kind: str, prob: float, u: np.ndarray):
    """Per-trajectory Pauli choice for a unitary-proportional channel:
    dephasing flips Z with probability p; depolarising picks X/Y/Z with
    probability p/3 each (mixDephasing / mixDepolarising Kraus weights,
    which are STATE-INDEPENDENT — no norm feedback needed)."""
    B = u.shape[0]
    mats = np.broadcast_to(_I2, (B, 2, 2, 2)).copy()
    if kind == "dephasing":
        mats[u < prob] = _Z2
    else:  # depolarising
        third = prob / 3.0
        mats[u < third] = _X2
        mats[(u >= third) & (u < 2 * third)] = _Y2
        mats[(u >= 2 * third) & (u < prob)] = _Z2
    return mats


def _sample_damping(qureg: BatchedQureg, target: int, prob: float,
                    rng: np.random.Generator):
    """Amplitude damping is STATE-DEPENDENT: the jump probability is
    p * <1|rho_b|1>, so the bank drains, each element's excited-state
    population reads back, and the per-element renormalized Kraus branch
    (jump: sqrt(p)|0><1| / sqrt(p*p1); no-jump: diag(1, sqrt(1-p)) /
    sqrt(1-p*p1)) applies as one batched gate."""
    B = qureg.batch_size
    p1 = np.asarray(_prob1_bank(
        qureg.amps, num_qubits=qureg.num_qubits_represented,
        target=int(target)))
    pjump = np.clip(prob * p1, 0.0, 1.0)
    u = rng.random(B)
    jump = u < pjump
    mats = np.zeros((B, 2, 2, 2))
    for b in range(B):
        if jump[b]:
            mats[b, 0, 0, 1] = np.sqrt(prob) / np.sqrt(pjump[b])
        else:
            keep = max(1.0 - pjump[b], np.finfo(np.float64).tiny)
            mats[b, 0, 0, 0] = 1.0 / np.sqrt(keep)
            mats[b, 0, 1, 1] = np.sqrt(1.0 - prob) / np.sqrt(keep)
    return mats


_NOISE_KINDS = ("dephasing", "depolarising", "damping")


def run_trajectories(ops: Sequence, num_qubits: int, env: QuESTEnv,
                     n_traj: int, *, observable=None, seed: int = 0):
    """Unravel a noisy circuit as ``n_traj`` quantum trajectories run as
    ONE batched state-vector program.

    ``ops`` is a sequence of circuit entries in order:

    - a :class:`quest_tpu.circuit.Gate` (applied to every trajectory), or
    - ``(kind, target, prob)`` with kind in ``("dephasing",
      "depolarising", "damping")`` — the stochastic unraveling of the
      matching mix* density channel: each trajectory samples its own
      Kraus branch (host RNG, seeded by ``seed``) and the B choices
      apply as one per-element batched gate.

    Returns a dict: ``values`` — the (n_traj,) per-trajectory
    expectation of ``observable`` (a (codes, coeffs) Pauli-sum pair);
    ``mean`` and ``sem`` — its sample mean and standard error, which
    converge to the exact density-matrix channel expectation as 1/sqrt(B)
    (cross-validated against ops/density.py in tests).  With
    ``observable=None``, returns the final (n_traj, 2, 2^n) bank
    instead (key ``amps``)."""
    if n_traj < 1:
        raise QuESTError(f"run_trajectories: n_traj must be >= 1, got "
                         f"{n_traj}")
    rng = np.random.default_rng(seed)
    t0 = time.perf_counter()
    q = createBatchedQureg(num_qubits, env, n_traj,
                           seeds=[seed + i for i in range(n_traj)])
    nsites = 0
    with _telemetry.span("batch.trajectories", n_traj=n_traj,
                         ops=len(ops)):
        for op in ops:
            if isinstance(op, C.Gate):
                from . import api as _api

                _telemetry.inc_key(_api._K_UNITARY, n_traj)
                q._fusion.gates.append(op)
                continue
            kind, target, prob = op
            if kind not in _NOISE_KINDS:
                raise QuESTError(
                    f"run_trajectories: unknown noise kind {kind!r} "
                    f"(expected one of {_NOISE_KINDS})")
            nsites += 1
            prob = float(prob)
            if kind == "damping":
                mats = _sample_damping(q, int(target), prob, rng)
            else:
                mats = _sample_pauli_insertion(kind, prob,
                                               rng.random(n_traj))
            applyBatchedUnitary(q, (int(target),), mats)
        _telemetry.inc("trajectory_runs_total", n_traj)
        _telemetry.set_gauge("trajectory_noise_sites", nsites)
        if observable is None:
            out = {"amps": np.asarray(q.amps)}
        else:
            codes, coeffs = observable
            vals = calcExpecPauliSumBatched(q, codes, coeffs)
            sem = float(vals.std(ddof=1) / np.sqrt(n_traj)) \
                if n_traj > 1 else float("nan")
            out = {"values": vals, "mean": float(vals.mean()), "sem": sem}
    dt = time.perf_counter() - t0
    if dt > 0:
        _telemetry.set_gauge("trajectories_per_sec", n_traj / dt)
    return out
