// ASan smoke driver for the native circuit planner: plans a layered
// 1q + neighbour-2q circuit through both planners and frees the result
// buffers.  Built with -fsanitize=address in CI (.github/workflows/
// native-asan.yml) — the analogue of the reference's llvm-asan.yml run of
// its kernel suite under AddressSanitizer.
//
// Build: g++ -O1 -g -fsanitize=address scheduler.cc scheduler_smoke.cc
//        -o scheduler_smoke && ./scheduler_smoke

#include <cstdint>
#include <cstdio>
#include <vector>

extern "C" {
int qts_plan(int64_t n, int64_t num_gates, const int64_t* offsets,
             const int64_t* targets, int64_t** out_buf, int64_t* out_len);
int qts_plan_windowed(int64_t n, int64_t num_gates, const int64_t* offsets,
                      const int64_t* targets, const int64_t* xranks,
                      const int64_t* flags,
                      int64_t** out_buf, int64_t* out_len);
void qts_free(int64_t* buf);
}

static int run(int64_t n, int64_t depth) {
  std::vector<int64_t> offsets{0};
  std::vector<int64_t> targets;
  std::vector<int64_t> xranks;
  for (int64_t d = 0; d < depth; ++d) {
    for (int64_t q = 0; q < n; ++q) {
      targets.push_back(q);
      offsets.push_back((int64_t)targets.size());
      xranks.push_back(0);
    }
    for (int64_t q = d % 2; q + 1 < n; q += 2) {
      targets.push_back(q);
      targets.push_back(q + 1);
      offsets.push_back((int64_t)targets.size());
      xranks.push_back(2);
    }
  }
  int64_t num_gates = (int64_t)offsets.size() - 1;

  int64_t* buf = nullptr;
  int64_t len = 0;
  int rc = qts_plan(n, num_gates, offsets.data(), targets.data(), &buf, &len);
  if (rc != 0 || !buf || len <= 0) {
    std::printf("qts_plan failed rc=%d len=%lld\n", rc, (long long)len);
    return 1;
  }
  qts_free(buf);

  buf = nullptr;
  len = 0;
  std::vector<int64_t> flags(xranks.size(), 0);
  rc = qts_plan_windowed(n, num_gates, offsets.data(), targets.data(),
                         xranks.data(), flags.data(), &buf, &len);
  if (rc != 0 || !buf || len <= 0) {
    std::printf("qts_plan_windowed failed rc=%d len=%lld\n", rc,
                (long long)len);
    return 1;
  }
  qts_free(buf);
  return 0;
}

int main() {
  for (int64_t n : {14, 16, 20, 26}) {
    for (int64_t depth : {1, 4, 10}) {
      if (run(n, depth) != 0) return 1;
    }
  }
  // error paths must not leak or overrun either
  int64_t off_bad[2] = {0, 1};
  int64_t tgt_bad[1] = {99};
  int64_t* buf = nullptr;
  int64_t len = 0;
  if (qts_plan(14, 1, off_bad, tgt_bad, &buf, &len) == 0) return 1;
  std::puts("scheduler ASan smoke OK");
  return 0;
}
